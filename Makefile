# Developer entry points (reference analogue: Makefile:47-105 presubmit /
# test / battletest / benchmark / e2etests targets).

PY ?= python
# CPU-only targets bypass the axon TPU plugin entirely (-u PALLAS_AXON_POOL_IPS):
# when the deployment relay wedges, sitecustomize's register() blocks EVERY
# plain python start at interpreter boot — see the verify skill's "Wedged TPU
# tunnel" note and karpenter_tpu/utils/jaxenv.py.
CPU_ENV = env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8

.PHONY: presubmit lint noretry hotloops crashpoints cardinality phaseacct reasons test battletest deflake benchmark bench e2e foreigntest docs native run solver-serve verify-entry catalog chaos chaos-crash chaos-storm failover-drill spot-storm spot-storm-small fleet-bench fleet-drill fleet-drill-small churn-drill churn-drill-small telemetry-drill claims diagnose provenance multichip soak incremental-soak perf-regress ledger-backfill profile-drill explain-drill critical-drill critical-drill-small

presubmit: lint claims provenance noretry hotloops crashpoints cardinality phaseacct reasons perf-regress failover-drill fleet-drill-small churn-drill-small critical-drill-small spot-storm-small incremental-soak test verify-entry  ## what CI runs

perf-regress:  ## tier-1-sized micro-benches must stay inside the ledger's noise bands
	$(CPU_ENV) $(PY) hack/check_perf_regress.py

ledger-backfill:  ## seed/refresh the perf ledger from historical artifacts (idempotent)
	$(PY) -m benchmarks.ledger backfill

claims:  ## every benchmark number in docs must cite a recorded artifact
	$(PY) hack/check_round_claims.py

provenance:  ## BENCH_*.json headline claims must be on-chip or carry degraded provenance
	$(PY) hack/check_headline_provenance.py

multichip:  ## wire-served sharded parity at the 50k stress shape (records an artifact)
	$(CPU_ENV) $(PY) -m benchmarks.multichip_wire

noretry:  ## retries must flow through resilience.RetryPolicy (shared budget)
	$(PY) hack/check_no_adhoc_retry.py

hotloops:  ## no per-pod/per-node Python loops in HOT:BEGIN/END sections
	$(PY) hack/check_hot_loops.py

cardinality:  ## identity labels on metrics must route through the tenant guard
	$(PY) hack/check_label_cardinality.py

soak:  ## columnar-state soak: 100k nodes / 1M pods under churn, RECORDED
	$(CPU_ENV) $(PY) bench.py --soak

incremental-soak:  ## tier-1-sized incremental-plane soak (artifact + ledger land in /tmp)
	$(CPU_ENV) KARPENTER_TPU_SOAK_DIR=$(or $(SOAK_DIR),/tmp/karpenter-incremental-soak) \
		KARPENTER_TPU_LEDGER=$(or $(SOAK_DIR),/tmp/karpenter-incremental-soak)/ledger.jsonl \
		$(PY) bench.py --soak --soak-nodes 2000 --soak-pods 20000 --soak-cycles 12

crashpoints:  ## crashpoint catalog and call sites must stay in lockstep
	$(PY) hack/check_crashpoints.py

phaseacct:  ## gap-ledger phases and Tracer span registry must stay in lockstep
	$(PY) hack/check_phase_accounting.py

reasons:  ## explain reason vocabulary, mask dimensions and citing call sites must stay in lockstep
	$(PY) hack/check_decision_reasons.py

profile-drill:  ## 10k-pod attribution drill: >=95% of wall accounted, <5% overhead, RECORDED
	$(CPU_ENV) $(PY) -m benchmarks.profile_drill

explain-drill:  ## 10k-pod decision-provenance drill: 100% attribution, oracle parity, <1% overhead, RECORDED
	$(CPU_ENV) $(PY) -m benchmarks.explain_drill

critical-drill:  ## 10k-pod critical-path drill: >=95% attribution, serial overlap ~0, serialize share named, RECORDED
	$(CPU_ENV) $(PY) -m benchmarks.critical_drill

critical-drill-small:  ## presubmit-sized critical-path drill (400 pods, /tmp artifact + ledger)
	$(CPU_ENV) KARPENTER_TPU_CRITICAL_DIR=$(or $(CRITICAL_DIR),/tmp/karpenter-critical-drill) \
		KARPENTER_TPU_LEDGER=$(or $(CRITICAL_DIR),/tmp/karpenter-critical-drill)/ledger.jsonl \
		$(PY) -m benchmarks.critical_drill --small

diagnose:  ## introspection smoke: deadman, statusz, flight-recorder bundles
	$(CPU_ENV) $(PY) -m pytest tests/test_introspect.py -q

chaos:  ## seeded deterministic fault-injection sweep (docs/designs/chaos.md)
	$(CPU_ENV) $(PY) -m karpenter_tpu chaos --seed $(or $(SEED),0) --scenarios $(or $(SCENARIOS),3)

chaos-crash:  ## crash-restart recovery drill: every crashpoint + fenced failover
	$(CPU_ENV) $(PY) -m karpenter_tpu chaos --crash --seed $(or $(SEED),0)

chaos-storm:  ## multi-tenant storm drill: fairness bound + shed paths, replayable
	$(CPU_ENV) $(PY) -m karpenter_tpu chaos --storm --seed $(or $(SEED),42) --scenarios $(or $(SCENARIOS),2)

failover-drill:  ## fleet membership/failover drill: kill, partition, gray, poison, rejoin
	$(CPU_ENV) $(PY) -m karpenter_tpu chaos --partition --seed $(or $(SEED),0)

spot-storm:  ## spot reclaim-storm drill: 10k nodes, 2000 simultaneous reclaims, RECORDED
	$(CPU_ENV) $(PY) -m karpenter_tpu chaos --spot-storm --seed $(or $(SEED),0) --out-dir benchmarks/results/spot

spot-storm-small:  ## presubmit-sized spot storm (240 nodes / 60 reclaims, /tmp artifact + ledger)
	$(CPU_ENV) KARPENTER_TPU_LEDGER=$(or $(SPOT_DIR),/tmp/karpenter-spot-storm)/ledger.jsonl \
		$(PY) -m karpenter_tpu chaos --spot-storm --spot-nodes 240 --spot-reclaims 60 \
		--seed $(or $(SEED),0) --out-dir $(or $(SPOT_DIR),/tmp/karpenter-spot-storm)

fleet-bench:  ## multi-tenant fleet benchmark: sustained solves/sec + p99, RECORDED
	$(CPU_ENV) $(PY) bench.py --fleet

fleet-drill:  ## REAL-replica drill: 4 subprocesses, 1000 tenants, mid-run kill, RECORDED
	$(CPU_ENV) $(PY) -m benchmarks.fleet_drill

fleet-drill-small:  ## tier-1-sized real-replica drill (2 subprocesses, no throughput floor)
	$(CPU_ENV) KARPENTER_TPU_DRILL_DIR=$(or $(DRILL_DIR),/tmp/karpenter-fleet-drill) \
		KARPENTER_TPU_LEDGER=$(or $(DRILL_DIR),/tmp/karpenter-fleet-drill)/ledger.jsonl \
		$(PY) -m benchmarks.fleet_drill --small

churn-drill:  ## catalog-churn endurance drill: 1000 zipf tenants, HBM cap, A/B thrash audit, RECORDED
	$(CPU_ENV) $(PY) -m benchmarks.churn_drill

churn-drill-small:  ## tier-1-sized churn drill (2 replicas, 32 tenants, same audits)
	$(CPU_ENV) KARPENTER_TPU_DRILL_DIR=$(or $(DRILL_DIR),/tmp/karpenter-churn-drill) \
		KARPENTER_TPU_LEDGER=$(or $(DRILL_DIR),/tmp/karpenter-churn-drill)/ledger.jsonl \
		$(PY) -m benchmarks.churn_drill --small

telemetry-drill:  ## 2-replica/1000-tenant telemetry acceptance drill, RECORDED
	$(CPU_ENV) $(PY) -m benchmarks.telemetry_drill

lint:  ## static analysis: bytecode-compile everything; ruff when installed
	$(PY) -m compileall -q karpenter_tpu tests hack benchmarks bench.py __graft_entry__.py
	@if $(PY) -c "import ruff" 2>/dev/null; then \
		$(PY) -m ruff check karpenter_tpu tests hack benchmarks bench.py __graft_entry__.py; \
	else \
		echo "ruff not installed; compileall-only lint (CI runs ruff)"; \
	fi

test:  ## hermetic suite (8-device virtual CPU mesh)
	$(CPU_ENV) $(PY) -m pytest tests/ -q

battletest:  ## randomized/race tier: shuffled order (seed logged) + random per-test delay, 3x
	for i in 1 2 3; do \
		$(CPU_ENV) KARPENTER_TPU_RANDOMIZE=1 KARPENTER_TPU_TEST_DELAY_MS=10 \
			$(PY) -m pytest tests/test_battletest.py tests/test_packer_parity.py -q || exit 1; \
	done

deflake:  ## loop the randomized race tier until it fails (fresh seed each round)
	while $(CPU_ENV) KARPENTER_TPU_RANDOMIZE=1 KARPENTER_TPU_TEST_DELAY_MS=10 \
		$(PY) -m pytest tests/test_battletest.py -q; do :; done

benchmark:  ## interruption ladder + BASELINE configs, RECORDED + diffed
	$(CPU_ENV) $(PY) -m benchmarks.record

bench:  ## the headline one-line benchmark (real TPU when present)
	$(PY) bench.py

catalog:  ## regenerate the real-data fleet catalog (provenance in the output)
	$(PY) hack/gen_catalog.py

e2e:  ## E2E-analogue scenario suites only
	$(CPU_ENV) $(PY) -m pytest tests/test_e2e_scenarios.py tests/test_controllers.py -q

foreigntest:  ## wire-compat tier against a real kube-apiserver (fetches envtest)
	bash hack/fetch_envtest.sh || true  # offline: the tier skips on absent binaries
	$(CPU_ENV) $(PY) -m pytest tests/test_foreign_apiserver.py -q

docs:  ## regenerate generated docs (metrics/settings/instance-types)
	$(CPU_ENV) $(PY) hack/gen_docs.py all

native:  ## build the C++ fallback packer
	bash hack/build_native.sh

run:  ## run the controller plane against the simulated cloud
	$(PY) -m karpenter_tpu controller --simulate

solver-serve:  ## host the TPU solver gRPC service
	$(PY) -m karpenter_tpu solver-serve

verify-entry:  ## driver contract: graft entry compiles, multichip dryrun passes
	$(CPU_ENV) $(PY) -c "import __graft_entry__ as g; fn, args = g.entry(); \
import jax; jax.jit(fn).lower(*args).compile(); g.dryrun_multichip(8)"
