"""Controller-plane integration tests: the hermetic analogue of the
reference's E2E suites (utilization, emptiness, expiration, drift,
interruption, consolidation) run against KubeStore + FakeCloud with the REAL
provisioning/termination/deprovisioning controllers in the loop
(suite_test.go:63-66 'core-in-the-loop' pattern)."""

import json

import pytest

from karpenter_tpu.apis import wellknown as wk
from karpenter_tpu.apis.nodetemplate import NodeTemplate
from karpenter_tpu.apis.provisioner import Limits, Provisioner
from karpenter_tpu.apis.settings import Settings
from karpenter_tpu.controllers.interruption import FakeQueue
from karpenter_tpu.fake.cloud import FakeCloud
from karpenter_tpu.models.instancetype import Catalog, make_instance_type
from karpenter_tpu.models.pod import make_pod
from karpenter_tpu.models.requirements import Requirements, OP_IN
from karpenter_tpu.operator import Operator
from karpenter_tpu.utils.clock import FakeClock


def catalog():
    return Catalog(types=[
        make_instance_type("t.small", cpu=2, memory="2Gi", od_price=0.05, spot_price=0.02),
        make_instance_type("m.large", cpu=4, memory="16Gi", od_price=0.20, spot_price=0.07),
        make_instance_type("m.xlarge", cpu=16, memory="64Gi", od_price=0.80, spot_price=0.28),
    ])


@pytest.fixture
def op():
    clock = FakeClock()
    cloud = FakeCloud(catalog=catalog(), clock=clock)
    settings = Settings(cluster_name="itest",
                        cluster_endpoint="https://k.example",
                        interruption_queue_name="iq",
                        batch_idle_duration=0.0, batch_max_duration=0.0)
    operator = Operator(cloud, settings, catalog(), clock=clock)
    operator.kube.create("nodetemplates", "default", NodeTemplate(
        name="default",
        subnet_selector={"id": "subnet-zone-1a,subnet-zone-1b,subnet-zone-1c"},
        security_group_selector={"id": "sg-default"}))
    operator.cloudprovider.register_nodetemplate(
        operator.kube.get("nodetemplates", "default"))
    yield operator
    operator.stop()


def add_provisioner(op, name="default", **kw):
    p = Provisioner(name=name, provider_ref="default", **kw)
    p.set_defaults()
    p.validate()
    op.kube.create("provisioners", name, p)
    return p


class TestProvisioning:
    def test_utilization_100_pods_100_nodes(self, op):
        # E2E parity: utilization/suite_test.go:40-58 — 1.5-cpu pods on a
        # 2-cpu catalog type => one node per pod
        add_provisioner(op, requirements=Requirements.of(
            (wk.LABEL_INSTANCE_TYPE, OP_IN, ["t.small"])))
        for i in range(100):
            op.kube.create("pods", f"p{i}",
                           make_pod(f"p{i}", cpu="1.5", memory="128Mi"))
        op.provisioning.reconcile_once()
        assert len(op.cluster.nodes) == 100
        assert len(op.kube.pending_pods()) == 0
        assert all(len(n.pods) == 1 for n in op.cluster.nodes.values())
        # every pod bound to a distinct node; machines exist in store
        assert len(op.kube.machines()) == 100
        assert op.cloudprovider.cloud.create_fleet_api.called_with_count >= 1

    def test_bin_packing_one_node(self, op):
        add_provisioner(op)
        for i in range(10):
            op.kube.create("pods", f"p{i}",
                           make_pod(f"p{i}", cpu="1", memory="2Gi"))
        op.provisioning.reconcile_once()
        assert len(op.cluster.nodes) == 1
        (node,) = op.cluster.nodes.values()
        assert node.instance_type == "m.xlarge"
        assert len(node.pods) == 10

    def test_existing_capacity_reused(self, op):
        add_provisioner(op)
        op.kube.create("pods", "a", make_pod("a", cpu="1", memory="1Gi"))
        op.provisioning.reconcile_once()
        assert len(op.cluster.nodes) == 1
        op.kube.create("pods", "b", make_pod("b", cpu="1", memory="1Gi"))
        op.provisioning.reconcile_once()
        assert len(op.cluster.nodes) == 1  # pod b joined the in-flight node
        (node,) = op.cluster.nodes.values()
        assert sorted(p.name for p in node.pods) == ["a", "b"]

    def test_limits_respected(self, op):
        add_provisioner(op, limits=Limits(cpu_millis=4000))
        for i in range(40):
            op.kube.create("pods", f"p{i}", make_pod(f"p{i}", cpu="1.9", memory="1Gi"))
        op.provisioning.reconcile_once()
        total_cpu = sum(n.allocatable[wk.RESOURCE_INDEX[wk.RESOURCE_CPU]]
                       for n in op.cluster.nodes.values())
        assert total_cpu <= 4000 + 16000  # at most one node over (race-free check)
        assert op.recorder.by_reason("LimitExceeded")

    def test_unschedulable_event(self, op):
        add_provisioner(op)
        op.kube.create("pods", "huge", make_pod("huge", cpu="64", memory="1Gi"))
        op.provisioning.reconcile_once()
        assert op.recorder.by_reason("FailedScheduling")


class TestEmptinessExpiration:
    def test_emptiness_ttl(self, op):
        add_provisioner(op, ttl_seconds_after_empty=30)
        op.kube.create("pods", "a", make_pod("a", cpu="1", memory="1Gi"))
        op.provisioning.reconcile_once()
        (name,) = op.cluster.nodes
        # pod removed -> node becomes empty
        node = op.cluster.nodes[name]
        node.pods.clear()
        op.deprovisioning.reconcile_emptiness()
        assert not op.cluster.nodes[name].marked_for_deletion  # TTL not elapsed
        op.clock.step(31)
        op.deprovisioning.reconcile_emptiness()
        assert op.cluster.nodes[name].marked_for_deletion
        op.termination.reconcile_once()
        assert not op.cluster.nodes  # drained + cloud-deleted

    def test_expiration_ttl(self, op):
        add_provisioner(op, ttl_seconds_until_expired=3600)
        op.kube.create("pods", "a", make_pod("a", cpu="1", memory="1Gi"))
        op.provisioning.reconcile_once()
        op.deprovisioning.reconcile_expiration()
        (node,) = op.cluster.nodes.values()
        assert not node.marked_for_deletion
        op.clock.step(3601)
        op.deprovisioning.reconcile_expiration()
        assert node.marked_for_deletion


class TestDrift:
    def test_drift_replaces_node(self, op):
        op.settings.feature_gates.drift_enabled = True
        add_provisioner(op)
        op.kube.create("pods", "a", make_pod("a", cpu="1", memory="1Gi"))
        op.provisioning.reconcile_once()
        assert op.deprovisioning.reconcile_drift() == []
        op.cloudprovider.cloud.ssm_parameters[
            "/karpenter-tpu/images/default/amd64/latest"] = "img-new"
        op.cloudprovider.images.cache.flush()
        drifted = op.deprovisioning.reconcile_drift()
        assert len(drifted) == 1


class TestInterruption:
    def spot_message(self, iid):
        return json.dumps({
            "source": "cloud.spot",
            "detail-type": "Spot Instance Interruption Warning",
            "detail": {"instance-id": iid},
        })

    def test_spot_interruption_drains_and_marks_ice(self, op):
        add_provisioner(op, requirements=Requirements.of(
            (wk.LABEL_CAPACITY_TYPE, OP_IN, ["spot"])))
        op.kube.create("pods", "a", make_pod("a", cpu="1", memory="1Gi"))
        op.provisioning.reconcile_once()
        (node,) = op.cluster.nodes.values()
        assert node.capacity_type == "spot"
        from karpenter_tpu.models.machine import parse_provider_id

        _, iid = parse_provider_id(node.provider_id)
        # global REGISTRY: assert deltas, not absolutes (see consolidation note)
        recv_before = op.interruption.received.value(message_type="SpotInterruption")
        del_before = op.interruption.deleted.value()
        op.queue.send(self.spot_message(iid))
        handled = op.interruption.reconcile_once()
        assert handled == 1
        assert node.marked_for_deletion
        assert op.cloudprovider.ice.is_unavailable(
            "spot", node.instance_type, node.zone)
        assert op.interruption.received.value(
            message_type="SpotInterruption") == recv_before + 1
        assert op.interruption.deleted.value() == del_before + 1

    def test_unparseable_and_unknown_messages_are_noop(self, op):
        add_provisioner(op)
        noop_before = op.interruption.received.value(message_type="NoOp")
        op.queue.send("{malformed")
        op.queue.send(json.dumps({"source": "x", "detail-type": "y"}))
        assert op.interruption.reconcile_once() == 2
        assert op.interruption.received.value(
            message_type="NoOp") == noop_before + 2

    def test_rebalance_recommendation_event_without_action(self, op):
        """Advisory rebalance recommendations surface as node events but
        never cordon/drain (reference deprovisioning.md:113)."""
        add_provisioner(op)
        op.kube.create("pods", "a", make_pod("a", cpu="1", memory="1Gi"))
        op.provisioning.reconcile_once()
        (node,) = op.cluster.nodes.values()
        from karpenter_tpu.models.machine import parse_provider_id

        _, iid = parse_provider_id(node.provider_id)
        op.queue.send(json.dumps({
            "source": "cloud.spot",
            "detail-type": "Instance Rebalance Recommendation",
            "detail": {"instance-id": iid},
        }))
        assert op.interruption.reconcile_once() == 1
        assert not node.marked_for_deletion
        assert op.recorder.by_reason("RebalanceRecommendation")

    def test_state_change_only_on_stopping_states(self, op):
        add_provisioner(op)
        op.kube.create("pods", "a", make_pod("a", cpu="1", memory="1Gi"))
        op.provisioning.reconcile_once()
        (node,) = op.cluster.nodes.values()
        from karpenter_tpu.models.machine import parse_provider_id

        _, iid = parse_provider_id(node.provider_id)
        op.queue.send(json.dumps({
            "source": "cloud.compute",
            "detail-type": "Instance State-change Notification",
            "detail": {"instance-id": iid, "state": "running"},
        }))
        op.interruption.reconcile_once()
        assert not node.marked_for_deletion
        # benign state changes are SILENT: no advisory node event (the
        # reference's parser NoOps non-stopping states before events)
        assert not op.recorder.by_reason("StateChange")
        op.queue.send(json.dumps({
            "source": "cloud.compute",
            "detail-type": "Instance State-change Notification",
            "detail": {"instance-id": iid, "state": "stopping"},
        }))
        op.interruption.reconcile_once()
        assert node.marked_for_deletion


class TestConsolidationLoop:
    def test_consolidation_deletes_underutilized(self, op):
        from karpenter_tpu.models.cluster import StateNode

        add_provisioner(op, consolidation_enabled=True)
        # two half-empty m.large nodes; n-2's pod is do-not-evict so it can
        # only HOST (multi-node mechanism, which runs first, has <2
        # candidates) and the single delete of n-1 decides
        for name, pods, sticky in (("n-1", ["a"], False), ("n-2", ["b"], True)):
            node = StateNode(
                name=name,
                labels={wk.LABEL_ARCH: "amd64", wk.LABEL_OS: "linux",
                        wk.LABEL_ZONE: "zone-1a",
                        wk.LABEL_CAPACITY_TYPE: "on-demand",
                        wk.LABEL_INSTANCE_TYPE: "m.large"},
                allocatable=wk.capacity_vector({wk.RESOURCE_CPU: 4000,
                                                wk.RESOURCE_MEMORY: 16 * 2**30,
                                                wk.RESOURCE_PODS: 110}),
                price=0.20, provisioner_name="default",
                pods=[make_pod(p, cpu="1", memory="2Gi", node_name=name,
                               do_not_evict=sticky)
                      for p in pods],
            )
            op.cluster.add_node(node)
            op.kube.create("nodes", name, node)
        # the global REGISTRY is shared across the whole pytest process:
        # assert the DELTA, not an absolute count
        before = op.deprovisioning.actions.value(action="consolidation-delete")
        action = op.deprovisioning.reconcile_consolidation()
        assert action is not None
        assert action.kind == "delete"
        assert op.cluster.nodes[action.node].marked_for_deletion
        assert op.deprovisioning.actions.value(
            action="consolidation-delete") == before + 1
        # termination completes the action (pods evicted for rescheduling)
        done = op.termination.reconcile_once()
        assert done == [action.node]
        assert len(op.cluster.nodes) == 1


class TestMachineLifecycle:
    def test_launched_to_registered_to_initialized(self, op):
        from karpenter_tpu.models.machine import INITIALIZED, LAUNCHED
        from karpenter_tpu.models.pod import Taint

        add_provisioner(op, startup_taints=(
            Taint(key="node.example/not-ready", value="true", effect="NoSchedule"),))
        op.kube.create("pods", "a", make_pod("a", cpu="1", memory="1Gi"))
        op.provisioning.reconcile_once()
        (machine,) = op.kube.machines()
        (node,) = op.cluster.nodes.values()
        assert machine.status.state == LAUNCHED
        assert not node.initialized
        assert node.startup_taints  # registered with the startup taint
        # the launch template registered both taint sets
        inst = next(iter(op.cloudprovider.cloud.instances.values()))
        lt = op.cloudprovider.cloud.launch_templates[inst.launch_template]
        assert "node.example/not-ready" in lt.userdata
        # one pass: LAUNCHED->REGISTERED, second: REGISTERED->INITIALIZED
        # (instance already 'running' after the create-describe wait)
        init_before = op.machinelifecycle.initialized.value(provisioner="default")
        assert op.machinelifecycle.reconcile_once() >= 1
        op.machinelifecycle.reconcile_once()
        assert machine.status.state == INITIALIZED
        assert node.initialized and node.startup_taints == ()
        assert op.machinelifecycle.initialized.value(
            provisioner="default") == init_before + 1

    def test_initialization_gates_consolidation(self, op):
        add_provisioner(op, consolidation_enabled=True)
        # two one-pod nodes (hostname anti-affinity); freeing node 2 makes
        # node 1's pod movable
        op.kube.create("pods", "a", make_pod("a", cpu="1.9", memory="128Mi",
                                             anti_affinity_hostname=True))
        op.kube.create("pods", "b", make_pod("b", cpu="1.9", memory="128Mi",
                                             anti_affinity_hostname=True))
        op.provisioning.reconcile_once()
        assert len(op.cluster.nodes) == 2
        # pod->node assignment follows launch completion order (the two
        # launches race), so pick nodes by content: n1 holds pod a, n2 pod b
        (n1, n2) = sorted(op.cluster.nodes.values(),
                          key=lambda n: sorted(p.name for p in n.pods))
        n2.pods.clear()
        op.kube.delete("pods", "b")
        # NOT initialized yet: no candidate
        assert op.deprovisioning.reconcile_consolidation() is None
        op.machinelifecycle.reconcile_once()
        op.machinelifecycle.reconcile_once()
        assert n1.initialized and n2.initialized
        action = op.deprovisioning.reconcile_consolidation()
        assert action is not None and action.kind == "delete"


class TestSettingsWatch:
    def test_configmap_update_applies_live(self, op):
        assert op.settings.batch_idle_duration == 0.0
        op.kube.create("configmaps", "karpenter-global-settings", {"data": {
            "clusterName": "itest", "clusterEndpoint": "https://k.example",
            "batchIdleDuration": "2s", "batchMaxDuration": "20s",
            "featureGates.driftEnabled": "true",
            "interruptionQueueName": "iq",
        }})
        changed = op.settingswatch.reconcile_once()
        assert "batch_idle_duration" in changed
        assert op.settings.batch_idle_duration == 2.0
        assert op.settings.feature_gates.drift_enabled is True
        # the provisioning controller shares the object by reference
        assert op.provisioning.settings.batch_idle_duration == 2.0
        # unchanged data is a no-op
        assert op.settingswatch.reconcile_once() == []

    def test_invalid_update_keeps_last_good(self, op):
        before = op.settings.batch_max_duration
        op.kube.create("configmaps", "karpenter-global-settings", {"data": {
            "clusterName": "",  # required -> rejected
            "batchMaxDuration": "99s",
        }})
        assert op.settingswatch.reconcile_once() == []
        assert op.settings.batch_max_duration == before
        assert op.settings.cluster_name == "itest"


class TestNodeTemplateController:
    def test_status_resolution(self, op):
        op.nodetemplate.reconcile_once()
        t = op.kube.get("nodetemplates", "default")
        assert [s["id"] for s in t.status.subnets] == [
            "subnet-zone-1a", "subnet-zone-1b", "subnet-zone-1c"]  # free-ip order
        # generation-change predicate: second call is a no-op until requeue
        assert op.nodetemplate.reconcile_once() == 0
        t.generation += 1
        assert op.nodetemplate.reconcile_once() == 1


class TestTermination:
    def test_request_deletion_distinguishes_already_marked(self, op):
        # the multi-node consolidation rollback must only undo marks IT
        # created; the status contract here is what makes that possible
        add_provisioner(op)
        op.kube.create("pods", "a", make_pod("a", cpu="1", memory="1Gi"))
        op.provisioning.reconcile_once()
        (name,) = op.cluster.nodes
        t = op.termination
        assert t.request_deletion("no-such-node") == ""
        assert t.request_deletion(name) == t.MARKED_NEW
        ts = op.cluster.nodes[name].deletion_requested_ts
        assert t.request_deletion(name) == t.MARKED_ALREADY
        # re-request must not refresh the original request timestamp
        assert op.cluster.nodes[name].deletion_requested_ts == ts

    def test_do_not_evict_blocks_drain(self, op):
        add_provisioner(op)
        op.kube.create("pods", "a", make_pod("a", cpu="1", memory="1Gi",
                                             do_not_evict=True))
        op.provisioning.reconcile_once()
        (name,) = op.cluster.nodes
        op.termination.request_deletion(name)
        assert op.termination.reconcile_once() == []  # blocked
        assert op.recorder.by_reason("FailedDraining")
        # pod deleted -> drain proceeds
        op.cluster.nodes[name].pods.clear()
        assert op.termination.reconcile_once() == [name]


class TestReviewRegressions:
    def test_multiarch_override_lt_pairing(self, op):
        # each override must carry its arch's launch template
        cat = op.cloudprovider.instance_types.source
        cat.types.append(
            __import__("karpenter_tpu.models.instancetype",
                       fromlist=["make_instance_type"]).make_instance_type(
                "arm.large", cpu=4, memory="16Gi", arch="arm64", od_price=0.02))
        cat.bump()
        add_provisioner(op, name="multi", requirements=Requirements.of(
            (wk.LABEL_ARCH, OP_IN, ["amd64", "arm64"])))
        op.kube.create("pods", "m0", make_pod("m0", cpu="1", memory="1Gi"))
        op.provisioning.reconcile_once()
        (node,) = op.cluster.nodes.values()
        assert node.instance_type == "arm.large"  # cheapest
        iid = node.provider_id.rsplit("/", 1)[1]
        inst = op.cloudprovider.cloud.instances[iid]
        lt = op.cloudprovider.cloud.launch_templates[inst.launch_template]
        assert lt.image_id == "img-arm64-1"  # arm image, not amd64

    def test_missing_image_raises_clean_error(self, op):
        op.cloudprovider.cloud.ssm_parameters.clear()
        op.cloudprovider.images.cache.flush()
        add_provisioner(op)
        op.kube.create("pods", "x", make_pod("x", cpu="1", memory="1Gi"))
        op.provisioning.reconcile_once()
        assert op.recorder.by_reason("LaunchFailed")
        assert not op.cluster.nodes

    def test_queue_redelivery_after_visibility_timeout(self, op):
        op.queue.visibility_seconds = 5
        op.queue.send("{malformed")
        msgs = op.queue.receive()
        assert len(msgs) == 1  # received, NOT deleted
        assert op.queue.receive() == []
        op.clock.step(6)
        again = op.queue.receive()
        assert len(again) == 1 and again[0].body == "{malformed"


class TestSolverCacheAndRouting:
    def test_steady_state_zero_solver_rebuilds(self, op):
        # VERDICT r2 ask #4: the in-process solver (and its option grid) is
        # held across reconciles, invalidated by catalog CONTENT hash
        add_provisioner(op)
        pc = op.provisioning
        for i in range(3):
            p = make_pod(f"w{i}", cpu="1", memory="1Gi")
            op.kube.create("pods", p.name, p)
            pc.reconcile_once()
        # routing may satisfy every solve on the native path; force one
        # primary build to compare against, then reconcile again
        pc.route_threshold = 0  # always prefer the primary (device) solver
        p = make_pod("wx", cpu="1", memory="1Gi")
        op.kube.create("pods", p.name, p)
        pc.reconcile_once()
        builds = pc.solver_rebuilds
        assert builds == 1
        for i in range(3):
            q = make_pod(f"y{i}", cpu="1", memory="1Gi")
            op.kube.create("pods", q.name, q)
            pc.reconcile_once()
        assert pc.solver_rebuilds == builds  # zero rebuilds steady-state

    def test_catalog_content_change_rebuilds_once(self, op):
        add_provisioner(op)
        pc = op.provisioning
        pc.route_threshold = 0
        p = make_pod("a", cpu="1", memory="1Gi")
        op.kube.create("pods", p.name, p)
        pc.reconcile_once()
        assert pc.solver_rebuilds == 1
        # content mutation + seqnum bump -> exactly one rebuild
        cat = op.cloudprovider.catalog_for(None)
        from karpenter_tpu.models.instancetype import Offering, Offerings
        big = cat.by_name["m.xlarge"]
        object.__setattr__(big, "offerings", Offerings(
            Offering(o.zone, o.capacity_type, o.price, available=False)
            for o in big.offerings))
        cat.bump()
        for i in range(2):
            q = make_pod(f"b{i}", cpu="1", memory="1Gi")
            op.kube.create("pods", q.name, q)
            pc.reconcile_once()
        assert pc.solver_rebuilds == 2

    def test_small_batches_route_native(self, op):
        # measured crossover on the tunneled chip is null -> native first
        add_provisioner(op)
        pc = op.provisioning
        pc.route_threshold = None
        p = make_pod("r0", cpu="1", memory="1Gi")
        op.kube.create("pods", p.name, p)
        pc.reconcile_once()
        assert pc.last_solver_kind == "native"
        assert pc.solver_rebuilds == 0  # device path never engaged

    def test_large_batches_route_primary(self, op):
        add_provisioner(op)
        pc = op.provisioning
        pc.route_threshold = 2  # batches of >=2 pods go to the device path
        for i in range(3):
            p = make_pod(f"s{i}", cpu="1", memory="1Gi")
            op.kube.create("pods", p.name, p)
        pc.reconcile_once()
        assert pc.last_solver_kind == "tpu"
        assert pc.solver_rebuilds == 1

    def test_ladder_rungs_are_backend_stable_across_order_swap(
            self, op, monkeypatch):
        """Small batches attempt native first, but ladder rungs bind to
        FIXED backend identities (tpu=0, native=1): a native failure while
        tpu is healthy must not degrade the ladder past the healthy tpu
        rung (it would skip it in every later cycle)."""
        add_provisioner(op)
        pc = op.provisioning
        pc.route_threshold = None  # native attempted first on every batch

        class BrokenNative:
            def __init__(self, *a, **k):
                pass

            def adopt_static(self, other):
                pass

            def solve(self, *a, **k):
                raise RuntimeError("native packer down")

        monkeypatch.setattr(
            "karpenter_tpu.controllers.provisioning.NativeSolver",
            BrokenNative)
        p = make_pod("bs0", cpu="1", memory="1Gi")
        op.kube.create("pods", p.name, p)
        pc.reconcile_once()
        # native failed, the tpu rung answered...
        assert pc.last_solver_kind == "tpu"
        # ...and the ladder stays on its best rung: the worse rung's
        # failure says nothing the ladder routes on while tpu is healthy
        assert pc.solve_ladder.rung() == 0
        assert not pc.solve_ladder.evidence()["transitions"]

    def test_tpu_failure_degrades_to_the_native_rung(self, op):
        add_provisioner(op)
        pc = op.provisioning
        pc.route_threshold = 0  # every batch is "large": tpu first

        class Broken:
            def solve(self, *a, **k):
                raise RuntimeError("sidecar crashed")

        pc._solver_factory = lambda catalog, provs: Broken()
        pc._solver_cache.clear()
        p = make_pod("dg0", cpu="1", memory="1Gi")
        op.kube.create("pods", p.name, p)
        pc.reconcile_once()
        assert pc.last_solver_kind == "native"
        assert pc.solve_ladder.rung() == 1
        assert pc.solve_ladder.rung_name() == "native"
        # sticky: the next cycle starts at native, no tpu re-try
        q = make_pod("dg1", cpu="1", memory="1Gi")
        op.kube.create("pods", q.name, q)
        pc.reconcile_once()
        assert pc.last_solver_kind == "native"
        assert pc.solve_ladder.rung() == 1


class TestReplaceBeforeDrain:
    def _seed_replaceable(self, op):
        # lone expensive node with one small pod and nowhere else to go:
        # the search proposes "replace with a cheaper type"
        from karpenter_tpu.models.cluster import StateNode

        add_provisioner(op, consolidation_enabled=True)
        node = StateNode(
            name="n-big",
            labels={wk.LABEL_ARCH: "amd64", wk.LABEL_OS: "linux",
                    wk.LABEL_ZONE: "zone-1a",
                    wk.LABEL_CAPACITY_TYPE: "on-demand",
                    wk.LABEL_INSTANCE_TYPE: "m.xlarge"},
            allocatable=wk.capacity_vector({wk.RESOURCE_CPU: 16000,
                                            wk.RESOURCE_MEMORY: 64 * 2**30,
                                            wk.RESOURCE_PODS: 110}),
            price=0.80, provisioner_name="default", initialized=True,
            pods=[make_pod("lone", cpu="1", memory="1Gi", node_name="n-big")],
        )
        op.cluster.add_node(node)
        op.kube.create("nodes", "n-big", node)
        op.kube.create("pods", "lone",
                       make_pod("lone", cpu="1", memory="1Gi", node_name="n-big"))
        return node

    def test_replacement_launches_before_drain(self, op):
        # consolidation.md:15: launch the cheaper node; drain only when ready
        self._seed_replaceable(op)
        replace_count = op.deprovisioning.actions.value(
            action="consolidation-replace")
        action = op.deprovisioning.reconcile_consolidation()
        assert action is not None and action.kind == "replace"
        # phase 1: replacement launched, old node NOT yet marked
        assert not op.cluster.nodes["n-big"].marked_for_deletion
        new_names = [n for n in op.cluster.nodes if n != "n-big"]
        assert len(new_names) == 1
        replacement = op.cluster.nodes[new_names[0]]
        assert not replacement.initialized
        # zero pending-pod window so far
        assert len(op.kube.pending_pods()) == 0
        # not initialized yet -> still no drain on the next cycle
        assert op.deprovisioning.reconcile_consolidation() is None
        assert not op.cluster.nodes["n-big"].marked_for_deletion
        # machine lifecycle initializes the replacement -> drain proceeds
        op.machinelifecycle.reconcile_once()
        op.machinelifecycle.reconcile_once()
        assert op.cluster.nodes[new_names[0]].initialized
        done = op.deprovisioning.reconcile_consolidation()
        assert done is not None and done.kind == "replace"
        assert op.cluster.nodes["n-big"].marked_for_deletion
        assert op.deprovisioning.actions.value(
            action="consolidation-replace") == replace_count + 1
        # termination evicts (the ReplicaSet analogue recreates the pod);
        # the pod rebinds onto the ALREADY-READY node — no new launch
        op.termination.reconcile_once()
        assert set(op.cluster.nodes) == {new_names[0]}
        op.kube.create("pods", "lone", make_pod("lone", cpu="1", memory="1Gi"))
        op.provisioning.reconcile_once()
        assert len(op.kube.pending_pods()) == 0
        assert set(op.cluster.nodes) == {new_names[0]}  # zero extra nodes
        assert len(op.cluster.nodes[new_names[0]].pods) == 1

    def test_replacement_timeout_rolls_back(self, op):
        self._seed_replaceable(op)
        replace_count = op.deprovisioning.actions.value(
            action="consolidation-replace")
        action = op.deprovisioning.reconcile_consolidation()
        assert action is not None and action.kind == "replace"
        (rep_name,) = [n for n in op.cluster.nodes if n != "n-big"]
        # never initialized; past the timeout the replacement is rolled back
        op.clock.step(op.deprovisioning.REPLACE_INIT_TIMEOUT_S + 1)
        assert op.deprovisioning.reconcile_consolidation() is None
        assert op.cluster.nodes[rep_name].marked_for_deletion
        assert not op.cluster.nodes["n-big"].marked_for_deletion
        assert op.deprovisioning.actions.value(
            action="consolidation-replace") == replace_count

    def _seed_delete_pairs(self, op):
        # two independent delete-consolidatable pairs (each pair's pod fits
        # on the other member)
        from karpenter_tpu.models.cluster import StateNode

        add_provisioner(op, consolidation_enabled=True)
        for name, pods in (("n-1", ["a"]), ("n-2", ["b"]),
                           ("n-3", ["c"]), ("n-4", ["d"])):
            node = StateNode(
                name=name,
                labels={wk.LABEL_ARCH: "amd64", wk.LABEL_OS: "linux",
                        wk.LABEL_ZONE: "zone-1a",
                        wk.LABEL_CAPACITY_TYPE: "on-demand",
                        wk.LABEL_INSTANCE_TYPE: "m.large"},
                allocatable=wk.capacity_vector({wk.RESOURCE_CPU: 4000,
                                                wk.RESOURCE_MEMORY: 16 * 2**30,
                                                wk.RESOURCE_PODS: 110}),
                price=0.20, provisioner_name="default", initialized=True,
                pods=[make_pod(p, cpu="1", memory="2Gi", node_name=name)
                      for p in pods],
            )
            op.cluster.add_node(node)
            op.kube.create("nodes", name, node)

    def test_stabilization_window_defers_next_action(self, op):
        self._seed_delete_pairs(op)
        first = op.deprovisioning.reconcile_consolidation()
        assert first is not None and first.kind == "delete"
        # immediately after the action: deferred (cluster in flux)
        assert op.deprovisioning.reconcile_consolidation() is None
        # quiet cluster: settles after the short window
        op.clock.step(op.deprovisioning.STABILIZATION_S + 1)
        second = op.deprovisioning.reconcile_consolidation()
        assert second is not None

    def test_stabilization_uses_long_window_while_pods_pending(self, op):
        self._seed_delete_pairs(op)
        assert op.deprovisioning.reconcile_consolidation() is not None
        # a pod goes pending (e.g. evicted by the action's drain)
        op.kube.create("pods", "pend", make_pod("pend", cpu="1", memory="2Gi"))
        op.clock.step(op.deprovisioning.STABILIZATION_S + 1)
        # short window elapsed but pods are pending -> still deferred
        assert op.deprovisioning.reconcile_consolidation() is None
        op.clock.step(op.deprovisioning.STABILIZATION_PENDING_S)
        # long window elapsed -> next action may proceed (pod still pending
        # is fine; the window bounds flux, not cluster fullness)
        assert op.deprovisioning.reconcile_consolidation() is not None


class TestReplaceRevalidation:
    def test_terminating_replacement_abandons_drain(self, op):
        # the replacement gets interrupted/marked during the init window:
        # draining the old node into it would strand the pods
        tb = TestReplaceBeforeDrain()
        tb._seed_replaceable(op)
        action = op.deprovisioning.reconcile_consolidation()
        assert action is not None and action.kind == "replace"
        (rep_name,) = [n for n in op.cluster.nodes if n != "n-big"]
        op.machinelifecycle.reconcile_once()
        op.machinelifecycle.reconcile_once()
        assert op.cluster.nodes[rep_name].initialized
        op.termination.request_deletion(rep_name)  # e.g. spot interruption
        assert op.deprovisioning.reconcile_consolidation() is None
        assert not op.cluster.nodes["n-big"].marked_for_deletion
        assert op.deprovisioning._pending_replace is None

    def test_revalidation_aborts_when_old_node_gained_pods(self, op):
        # during the init wait, provisioning binds MORE pods onto the old
        # node (it was unmarked capacity); the original replacement can no
        # longer host them all -> abandon + roll the replacement back
        tb = TestReplaceBeforeDrain()
        tb._seed_replaceable(op)
        action = op.deprovisioning.reconcile_consolidation()
        assert action is not None and action.kind == "replace"
        (rep_name,) = [n for n in op.cluster.nodes if n != "n-big"]
        # 8 new 1-cpu pods land on n-big while the replacement initializes
        big = op.cluster.nodes["n-big"]
        for i in range(8):
            p = make_pod(f"late{i}", cpu="1", memory="1Gi", node_name="n-big")
            op.kube.create("pods", f"late{i}", p)
            big.pods.append(p)
        op.machinelifecycle.reconcile_once()
        op.machinelifecycle.reconcile_once()
        assert op.cluster.nodes[rep_name].initialized
        assert op.deprovisioning.reconcile_consolidation() is None
        assert not op.cluster.nodes["n-big"].marked_for_deletion
        assert op.cluster.nodes[rep_name].marked_for_deletion  # rolled back


class TestGarbageCollection:
    def test_orphan_instance_reaped_after_grace(self, op):
        # a machine launched, then its store object lost (crashed controller
        # between cloud create and machine write): the cloud instance leaks
        add_provisioner(op)
        op.kube.create("pods", "a", make_pod("a", cpu="1", memory="1Gi"))
        op.provisioning.reconcile_once()
        (name,) = [m.name for m in op.kube.machines()]
        op.kube.delete("machines", name)  # simulate the lost write
        # within the grace window: too early to judge (eventual consistency)
        assert op.garbagecollection.reconcile_once() == []
        op.clock.step(op.garbagecollection.grace_seconds + 1)
        reaped = op.garbagecollection.reconcile_once()
        assert len(reaped) == 1
        assert op.cloudprovider.list_machines() == []
        assert op.garbagecollection.collected.value() >= 1

    def test_owned_instances_never_reaped(self, op):
        add_provisioner(op)
        op.kube.create("pods", "a", make_pod("a", cpu="1", memory="1Gi"))
        op.provisioning.reconcile_once()
        op.clock.step(op.garbagecollection.grace_seconds + 1)
        assert op.garbagecollection.reconcile_once() == []
        assert len(op.cloudprovider.list_machines()) == 1

    def test_vanished_instance_retires_machine_and_node(self, op):
        # out-of-band termination (instance gone, no interruption message):
        # GC retires the machine through the normal drain path
        add_provisioner(op)
        op.kube.create("pods", "a", make_pod("a", cpu="1", memory="1Gi"))
        op.provisioning.reconcile_once()
        (node_name,) = list(op.cluster.nodes)
        node = op.cluster.nodes[node_name]
        from karpenter_tpu.models.machine import parse_provider_id

        _, iid = parse_provider_id(node.provider_id)
        op.cloudprovider.instances.delete(iid)  # vanishes out-of-band
        # first sweep only *observes* the absence — the listing is eventually
        # consistent, so retirement needs the missing-since window to elapse
        assert op.garbagecollection.reconcile_once() == []
        assert not op.cluster.nodes[node_name].marked_for_deletion
        op.clock.step(op.garbagecollection.grace_seconds + 1)
        assert op.garbagecollection.reconcile_once() == []
        assert op.cluster.nodes[node_name].marked_for_deletion
        op.termination.reconcile_once()
        assert node_name not in op.cluster.nodes
        assert op.kube.machines() == []

    def test_vanished_preregistration_machine_deleted(self, op):
        # machine launched, instance died before any node joined: the
        # machine object itself is GC'd (no node to drain) — but only after
        # absence is confirmed across the grace window
        from karpenter_tpu.models.machine import Machine, MachineSpec, MachineStatus

        add_provisioner(op)
        m = Machine(name="ghost", spec=MachineSpec(provisioner_name="default"),
                    status=MachineStatus(provider_id="tpu:///zone-1a/i-gone"))
        op.kube.create("machines", "ghost", m)
        op.garbagecollection.reconcile_once()
        assert op.kube.get("machines", "ghost") is not None  # window open
        op.clock.step(op.garbagecollection.grace_seconds + 1)
        op.garbagecollection.reconcile_once()
        assert op.kube.get("machines", "ghost") is None

    def test_just_launched_machine_survives_stale_listing(self, op):
        # ADVICE r3 (high): a machine whose instance launched AFTER the
        # sweep's instance listing must not be torn down. Simulated by a
        # listing race: the instance is absent at sweep N, present again by
        # sweep N+1 — the missing-since entry resets and nothing is retired.
        from karpenter_tpu.models.machine import Machine, MachineSpec, MachineStatus

        add_provisioner(op)
        m = Machine(name="young", spec=MachineSpec(provisioner_name="default"),
                    status=MachineStatus(provider_id="tpu:///zone-1a/i-late"))
        op.kube.create("machines", "young", m)
        op.garbagecollection.reconcile_once()  # observes absence, starts window
        # the launch write lands (eventual consistency catches up)
        from karpenter_tpu.fake.cloud import CloudInstance
        from karpenter_tpu.providers.instance import TAG_CLUSTER
        op.cloudprovider.cloud.instances["i-late"] = CloudInstance(
            id="i-late", instance_type="t.small", zone="zone-1a",
            capacity_type="on-demand", tags={TAG_CLUSTER: "itest"},
            launch_time=op.clock.now())
        op.clock.step(op.garbagecollection.grace_seconds + 1)
        op.garbagecollection.reconcile_once()
        assert op.kube.get("machines", "young") is not None
        # and the window restarts from scratch if it vanishes again later
        assert "young" not in op.garbagecollection._missing_since


class TestEventObjects:
    def test_events_persist_to_the_coordination_plane(self, op):
        # kubectl-get-events parity: recorded events become store objects
        add_provisioner(op)
        op.kube.create("pods", "a", make_pod("a", cpu="1", memory="1Gi"))
        op.provisioning.reconcile_once()
        stored = op.kube.list("events")
        assert stored, "no Event objects landed in the store"
        reasons = {e["reason"] for e in stored}
        assert "Launched" in reasons
        assert all({"ts", "kind", "reason", "object_ref", "message"}
                   <= set(e) for e in stored)

    def test_event_retention_is_bounded(self, op):
        op.MAX_STORED_EVENTS = 10
        for i in range(25):
            op.recorder.normal(f"node/n{i}", "Test", f"msg {i}")
        assert len(op.kube.list("events")) == 10

    def test_restart_prunes_orphaned_events(self, op):
        # a crashed replica's events have no process-local retention state;
        # start() caps the store-wide population oldest-first
        for i in range(30):
            op.kube.create("events", f"evt-dead-{i:07d}",
                           {"name": f"evt-dead-{i:07d}", "ts": float(i),
                            "kind": "Normal", "reason": "Old",
                            "object_ref": "node/x", "message": "stale"})
        op.MAX_STORED_EVENTS = 12
        op._prune_stored_events()
        left = op.kube.list("events")
        assert len(left) == 12
        assert min(e["ts"] for e in left) == 18.0  # oldest went first


def test_cleanup_cli_reaps_persisted_leaks(capsys, tmp_path):
    """Operational cleanup tooling (reference test-account sweeper analogue):
    a LEAKED instance persisted in a simulated-account state file is reaped
    by a separate cleanup process sharing the account through that file."""
    import json

    from karpenter_tpu.__main__ import main
    from karpenter_tpu.fake.cloud import (CloudInstance, FakeCloud,
                                          LaunchTemplate)

    state = tmp_path / "account.json"
    cloud = FakeCloud()
    cloud.instances["i-leak-1"] = CloudInstance(
        id="i-leak-1", instance_type="m.large", zone="zone-1a",
        capacity_type="on-demand", launch_time=0.0,
        tags={"karpenter.sh/provisioner-name": "default",
              "karpenter.sh/cluster": "simulated"})
    cloud.launch_templates["Karpenter-simulated-abc"] = LaunchTemplate(
        name="Karpenter-simulated-abc", image_id="img-amd64-2",
        tags={"karpenter.k8s.tpu/cluster": "simulated"})
    cloud.save_state(str(state))

    rc = main(["cleanup", "--state", str(state), "--all",
               "--launch-templates"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "reaped 1 leaked" in out, out
    doc = json.loads(state.read_text())
    states = {i["id"]: i["state"] for i in doc["instances"]}
    assert states["i-leak-1"] != "running"

    # without --state (or with a typo'd path) the tool refuses rather than
    # sweeping — and then persisting — a fresh empty account
    assert main(["cleanup"]) == 2
    assert main(["cleanup", "--state", str(tmp_path / "typo.json")]) == 2
    assert not (tmp_path / "typo.json").exists()


def test_counters_controller_maintains_provisioner_status_resources(op):
    """kubectl-visible consumption (core counters-controller parity): after
    provisioning, each provisioner's status.resources carries the same sums
    the limits gate reads; consumption changes update it."""
    from karpenter_tpu.coordination import serde

    add_provisioner(op)
    for i in range(4):
        op.kube.create("pods", f"cnt-{i}",
                       make_pod(f"cnt-{i}", cpu="1", memory="1Gi"))
    op.reconcile_all_once()
    prov = op.kube.get("provisioners", "default")
    res = prov.status_resources
    assert res and res["nodes"] != "0"
    cpu, mem = op.cluster.total_usage("default")
    assert res["cpu"] == f"{cpu}m"
    assert res["memory"] == f"{mem // 2**20}Mi"
    # kubectl sees it in real schema, not just the embedded model
    doc = serde.to_manifest("provisioners", "default", prov)
    assert doc["status"]["resources"] == res
    # consumption changes flow through on the next sweep
    for name in list(op.cluster.nodes):
        op.termination.request_deletion(name)
    op.reconcile_all_once()
    op.reconcile_all_once()
    prov2 = op.kube.get("provisioners", "default")
    assert prov2.status_resources["nodes"] == "0"


def test_pod_annotation_update_reaches_live_node_pods(op):
    """kubectl-annotating a BOUND pod (do-not-evict) must refresh the
    owning node's resident list — eligibility reads node.pods, and the
    bind-time object goes stale when the store copy is replaced."""
    import dataclasses

    add_provisioner(op, consolidation_enabled=True)
    op.kube.create("pods", "w-0", make_pod("w-0", cpu="1", memory="1Gi"))
    op.reconcile_all_once()
    (node_name,) = list(op.cluster.nodes)
    live = op.cluster.nodes[node_name]
    (pod,) = [p for p in live.pods if p.name == "w-0"]
    assert not pod.do_not_evict
    protected = dataclasses.replace(pod, do_not_evict=True)
    op.kube.update("pods", "w-0", protected)
    (pod2,) = [p for p in live.pods if p.name == "w-0"]
    assert pod2.do_not_evict, "live resident list not refreshed"
    from karpenter_tpu.oracle.consolidation import eligible
    assert not eligible(live, op.cluster)
    # deletion drops it from the resident list too
    op.kube.delete("pods", "w-0")
    assert not [p for p in live.pods if p.name == "w-0"]


class TestEmptyNodeConsolidation:
    """Mechanism 1 of consolidation (deprovisioning.md:74-77): entirely
    empty nodes delete in parallel BEFORE any search. With consolidation
    enabled, ttlSecondsAfterEmpty is API-excluded, so this is the only
    reclaim path for empty nodes of such provisioners."""

    def _empty_nodes(self, op, count):
        """Launch `count` initialized nodes (anti-affinity forces one per
        node), then remove their pods so all become empty."""
        for i in range(count):
            op.kube.create("pods", f"tmp-{i}", make_pod(
                f"tmp-{i}", cpu="3", memory="3Gi",
                anti_affinity_hostname=True))
        op.provisioning.reconcile_once()
        op.machinelifecycle.reconcile_once()
        op.machinelifecycle.reconcile_once()
        for i in range(count):
            op.kube.delete("pods", f"tmp-{i}")
        for n in op.cluster.nodes.values():
            n.pods = [p for p in n.pods if not p.name.startswith("tmp-")]

    def test_empty_nodes_deleted_in_parallel(self, op):
        add_provisioner(op, consolidation_enabled=True)
        self._empty_nodes(op, 2)
        emptied = {n for n, v in op.cluster.nodes.items() if v.is_empty()}
        assert len(emptied) >= 2
        op.clock.step(600)
        act = op.deprovisioning.reconcile_consolidation()
        assert act is not None and act.kind == "delete"
        assert set(act.nodes) == emptied, "ALL empties delete in one pass"
        for _ in range(3):
            op.termination.reconcile_once()
            op.clock.step(5)
        assert not (set(op.cluster.nodes) & emptied)

    def test_do_not_consolidate_spares_empty_node(self, op):
        add_provisioner(op, consolidation_enabled=True)
        self._empty_nodes(op, 1)
        (name,) = [n for n, v in op.cluster.nodes.items() if v.is_empty()]
        op.cluster.nodes[name].annotations[
            "karpenter.sh/do-not-consolidate"] = "true"
        op.clock.step(600)
        assert op.deprovisioning.reconcile_consolidation() is None
        assert name in op.cluster.nodes

    def test_young_empty_node_protected(self, op):
        """A just-initialized empty node (e.g. the replacement of a
        two-phase replace whose pods have not rebound yet) must survive
        mechanism 1 until the launch-protection window passes."""
        add_provisioner(op, consolidation_enabled=True)
        self._empty_nodes(op, 1)
        op.clock.step(60)  # < EMPTY_NODE_PROTECT_S
        assert op.deprovisioning.reconcile_consolidation() is None
        assert op.cluster.nodes
        op.clock.step(600)  # window passed -> reclaimed
        act = op.deprovisioning.reconcile_consolidation()
        assert act is not None and act.kind == "delete"

    def test_pending_pods_block_empty_delete(self, op):
        """In-flight (re)scheduling may be about to claim the empty
        capacity: mechanism 1 must not race it."""
        add_provisioner(op, consolidation_enabled=True)
        self._empty_nodes(op, 1)
        op.clock.step(600)
        op.kube.create("pods", "incoming", make_pod(
            "incoming", cpu="64", memory="1Gi"))  # pending (fits nowhere)
        assert op.deprovisioning.reconcile_consolidation() is None
        assert op.cluster.nodes
        op.kube.delete("pods", "incoming")
        act = op.deprovisioning.reconcile_consolidation()
        assert act is not None and act.kind == "delete"

    def test_uninitialized_empty_node_spared(self, op):
        add_provisioner(op, consolidation_enabled=True)
        op.kube.create("pods", "tmp", make_pod("tmp", cpu="3", memory="3Gi"))
        op.provisioning.reconcile_once()  # launched, NOT initialized
        op.kube.delete("pods", "tmp")
        for n in op.cluster.nodes.values():
            n.pods.clear()
        op.clock.step(600)
        assert op.deprovisioning.reconcile_consolidation() is None
        assert op.cluster.nodes
