"""Overload-control plane: admission-filter earn semantics under sketch
saturation, guard ladder hysteresis on FakeClock, pin refcounts vs
eviction, the strict-noop contract, Retry-After clamping, the bounded
per-tenant backlog's deterministic oldest-drop, and the churn drill's
pure replay/audit helpers (no subprocesses in this file — the real
4-replica run is `make churn-drill`)."""

from __future__ import annotations

import dataclasses
import random

import pytest

from karpenter_tpu import overload
from karpenter_tpu.overload import eviction as oev
from karpenter_tpu.overload import guard as og
from karpenter_tpu.overload import state as ostate
from karpenter_tpu.utils.clock import FakeClock


@pytest.fixture()
def plane_on():
    """Force the plane ON for the test body, restoring the prior state
    (the suite may run with KARPENTER_TPU_OVERLOAD=0)."""
    prev = ostate.set_enabled(True)
    try:
        yield
    finally:
        ostate.set_enabled(prev)


# -- admission filter ---------------------------------------------------------


class TestAdmissionFilter:
    def test_one_shot_flood_never_earns(self, plane_on):
        """The regression the lower-bound fix exists for: space-saving
        displacement hands a newcomer the evicted slot's floor as its
        raw count, so once one-shot traffic saturates the 16-slot sketch
        a brand-new hash would read count >= 2 and earn instantly. The
        earn test must use count - error, under which a first sighting
        is always exactly 1."""
        f = oev.AdmissionFilter(k=16)
        for i in range(400):
            assert f.offer(f"one-shot-{i}") is False, (
                f"one-shot key #{i} earned residency on first sight "
                f"(sketch-inheritance regression)")

    def test_repeated_key_earns_even_after_saturation(self, plane_on):
        f = oev.AdmissionFilter(k=16)
        for i in range(200):
            f.offer(f"flood-{i}")
        assert f.offer("hot") is False  # first sighting: probation
        assert f.offer("hot") is True   # provably seen twice: earned

    def test_seeded_churn_property(self, plane_on):
        """Property, across seeds: in any interleaving of a small hot set
        with a one-shot flood, a key offered exactly once never earns,
        and every hot key earns by its second consecutive offer."""
        for seed in (0, 7, 1234):
            rng = random.Random(seed)
            f = oev.AdmissionFilter(k=16)
            one_shots = iter(range(10 ** 6, 10 ** 7))
            hot = [f"hot-{i}" for i in range(4)]
            for _ in range(600):
                if rng.random() < 0.6:
                    assert f.offer(f"one-{next(one_shots)}") is False
                else:
                    k = rng.choice(hot)
                    f.offer(k)
                    # back-to-back re-offer: count - error moved by a full
                    # +1 regardless of sketch churn in between
                    assert f.offer(k) is True

    def test_disabled_filter_is_plain_lru(self):
        """Strict noop: disabled, offer() admits everything and moves no
        sketch state and no counter."""
        f = oev.AdmissionFilter(k=16)
        with ostate.disabled():
            before = oev.counters()
            snap_before = f.snapshot()
            for i in range(50):
                assert f.offer(f"k-{i}") is True
            assert oev.counters() == before
            after = f.snapshot()
            assert after["offers"] == snap_before["offers"]
            assert after["tracked"] == snap_before["tracked"]


class TestSketchLowerBound:
    def test_lower_bound_is_one_for_displacing_newcomer(self):
        from karpenter_tpu.metrics.cardinality import TenantTracker

        t = TenantTracker(k=4)
        for i in range(4):
            t.offer(f"warm-{i}", amount=5.0)
        key, evicted = t.offer("newcomer")
        assert evicted is not None
        # raw count inherited the victim's floor...
        assert t.tracked()["newcomer"] == 6.0
        # ...but the provable share of it is exactly the one offer
        assert t.lower_bound("newcomer") == 1.0
        assert t.lower_bound("absent") == 0.0


# -- the guard ladder ---------------------------------------------------------


class TestGuardLadder:
    def _guard(self):
        return og.OverloadGuard(clock=FakeClock(), rss_soft_cap=None)

    def test_spike_rises_straight_to_brownout(self, plane_on):
        g = self._guard()
        assert g.observe(backlog=0.95) == 3
        assert g.level_name() == "brownout"
        # one transition, 0 -> 3: a spike must not take three observes
        assert [(t["from"], t["to"]) for t in g.transitions] == [(0, 3)]

    def test_recovery_is_one_step_with_hysteresis(self, plane_on):
        g = self._guard()
        g.observe(backlog=0.95)                    # -> 3
        # above ENTER[3] - HYSTERESIS (0.75): stays browned out
        assert g.observe(backlog=0.80) == 3
        # exactly AT the boundary: < is strict, still no fall
        assert g.observe(backlog=0.75) == 3
        # below it: falls exactly one level per observe, never two,
        # even though 0.10 is far below every threshold
        assert g.observe(backlog=0.10) == 2
        assert g.observe(backlog=0.10) == 1
        assert g.observe(backlog=0.10) == 0
        downs = [t for t in g.transitions if t["to"] < t["from"]]
        assert all(t["from"] - t["to"] == 1 for t in downs)
        assert len(downs) == 3

    def test_fall_requires_clearing_own_threshold(self, plane_on):
        g = self._guard()
        g.observe(backlog=0.78)                    # -> 2 (shed)
        # 0.65 is above ENTER[2] - HYSTERESIS = 0.60: holds at shed
        assert g.observe(backlog=0.65) == 2
        assert g.observe(backlog=0.59) == 1

    def test_decide_fairness_contract(self, plane_on):
        g = self._guard()
        for pressure, verdict in ((0.55, "defer"), (0.78, "shed"),
                                  (0.95, "brownout")):
            g = self._guard()
            g.observe(backlog=pressure)
            # within-weight tenants are accepted at EVERY level
            assert g.decide(over_rate=False) == "accept"
            assert g.decide(over_rate=True) == verdict

    def test_strict_noop_when_disabled(self):
        g = og.OverloadGuard(clock=FakeClock(), rss_soft_cap=None)
        with ostate.disabled():
            before = og.counters()
            assert g.observe(backlog=1.0, deadline=1.0) == 0
            assert g.decide(over_rate=True) == "accept"
            assert g.level() == 0
            assert g.transitions == []
            assert og.counters() == before

    def test_simulated_rss_drives_pressure(self, plane_on):
        g = og.OverloadGuard(clock=FakeClock(), rss_soft_cap=1000)
        og.set_simulated_rss(960)
        try:
            assert g.observe() == 3
            assert g.snapshot()["inputs"]["rss"] == 0.96
        finally:
            og.set_simulated_rss(None)


# -- pin refcounts vs eviction ------------------------------------------------


class TestPinsBlockEviction:
    def _service(self):
        from karpenter_tpu.solver.service import SolverService

        svc = SolverService()
        # sentinel residents: eviction order and pin honoring are pure
        # OrderedDict/refcount mechanics, no real solver needed
        svc._cache[(1, 1)] = (object(), 0)
        svc._cache[(2, 2)] = (object(), 1)
        return svc

    def test_pinned_entry_survives_eviction_pass(self):
        svc = self._service()
        assert svc.checkout((1, 1)) is not None
        with svc._lock:
            evicted = svc._evict_one_locked((svc._probation, svc._cache))
        # LRU order would pick (1, 1) — the MRU bump from checkout puts it
        # last, but pin it back at the front to make the point sharper
        assert evicted == (2, 2)
        assert (1, 1) in svc._cache

    def test_all_pinned_yields_to_correctness(self):
        svc = self._service()
        svc.checkout((1, 1))
        svc.checkout((2, 2))
        with svc._lock:
            assert svc._evict_one_locked(
                (svc._probation, svc._cache)) is None
        assert len(svc._cache) == 2

    def test_checkin_releases_the_pin(self):
        svc = self._service()
        svc.checkout((1, 1))
        svc.checkout((1, 1))   # refcount 2
        svc.checkin((1, 1))
        with svc._lock:        # still pinned: one checkout outstanding
            assert svc._evict_one_locked(
                (svc._cache,), protect=(2, 2)) is None
        svc.checkin((1, 1))
        with svc._lock:
            assert svc._evict_one_locked(
                (svc._cache,), protect=(2, 2)) == (1, 1)
        assert svc.eviction_stats()["pinned"] == 0

    def test_checkout_unknown_key_is_none_and_unpinned(self):
        svc = self._service()
        assert svc.checkout((9, 9)) is None
        assert svc.eviction_stats()["pinned"] == 0


# -- Retry-After --------------------------------------------------------------


class TestRetryAfter:
    def _policy(self, slept):
        from karpenter_tpu.resilience.policy import RetryPolicy

        return RetryPolicy("kube", clock=FakeClock(), base=0.05, cap=5.0,
                           sleep=slept.append)

    def test_server_figure_honored_and_clamped(self):
        slept = []
        pol = self._policy(slept)
        assert pol.sleep_retry_after(2.0) == 2.0
        assert pol.sleep_retry_after(99.0) == 5.0    # clamped to cap
        assert pol.sleep_retry_after(-3.0) == 0.0    # never negative
        assert slept == [2.0, 5.0, 0.0]
        assert pol.sleeps_total == 7.0

    def test_resets_jitter_state(self):
        slept = []
        pol = self._policy(slept)
        for _ in range(6):
            pol.sleep_backoff()                      # walk _prev up
        pol.sleep_retry_after(1.0)
        # the next jittered delay must not compound on the server's
        # figure: decorrelated state is back at base, so the very next
        # backoff is bounded by base + U * (3*base - base) <= 3*base
        assert pol.next_backoff() <= 3 * pol.base + 1e-9


# -- bounded per-tenant backlog ----------------------------------------------


class TestBacklogBound:
    def _frontend(self, monkeypatch, bound=3):
        from karpenter_tpu.fleet.frontend import FleetFrontend

        monkeypatch.setenv(og.TENANT_BACKLOG_MAX_ENV, str(bound))
        fe = FleetFrontend(solve_batch=lambda *a, **k: [],
                           clock=FakeClock(), tick_interval_s=0.01)
        fe.register_key("t", (1, 1))
        return fe

    def test_oldest_drop_is_deterministic(self, plane_on, monkeypatch):
        from karpenter_tpu.fleet.frontend import FleetShed

        fe = self._frontend(monkeypatch, bound=3)
        tickets = [fe.submit("t", pods=[], deadline_ms=0) for _ in range(3)]
        assert not any(t.done() for t in tickets)
        overflow = fe.submit("t", pods=[], deadline_ms=0)
        # the OLDEST queued ticket is shed, not the newcomer
        assert tickets[0].done()
        with pytest.raises(FleetShed, match="backlog exceeded the bound"):
            tickets[0].wait(0)
        assert not overflow.done()
        assert not tickets[1].done() and not tickets[2].done()
        stats = fe.stats()["tenants"]["t"]
        assert stats["shed_reasons"]["queue"][
            "overload-queue-overflow"] == 1

    def test_bound_inert_when_disabled(self, monkeypatch):
        fe = self._frontend(monkeypatch, bound=2)
        with ostate.disabled():
            tickets = [fe.submit("t", pods=[], deadline_ms=0)
                       for _ in range(8)]
            assert not any(t.done() for t in tickets)


# -- churn drill pure helpers (no subprocesses) -------------------------------


class TestChurnDrillHelpers:
    def test_schedule_is_replay_identical(self):
        from benchmarks import churn_drill as cd

        a, b = cd.build_items(cd.SMALL), cd.build_items(cd.SMALL)
        assert a == b
        assert cd.schedule_digest(a) == cd.schedule_digest(b)
        reseeded = dataclasses.replace(cd.SMALL, seed=1)
        assert (cd.schedule_digest(cd.build_items(reseeded))
                != cd.schedule_digest(a))

    def test_replay_plan_within_weight_population(self):
        from benchmarks import churn_drill as cd

        plan = cd.build_replay_plan(cd.SMALL)
        items = cd.build_items(cd.SMALL)
        import collections

        counts = collections.Counter(t for t, _, _ in items)
        assert plan["within_weight_tenants"] == \
            sum(1 for c in counts.values() if c == 1)
        assert plan["requests"] == len(items)
        assert plan["schedule_digest"] == cd.schedule_digest(items)

    def test_one_shot_variants_are_globally_unique(self):
        from benchmarks import churn_drill as cd

        ones = [v for _, v, k in cd.build_items(cd.SMALL) if k == "one"]
        assert len(ones) == len(set(ones))
        assert all(v >= cd.ONE_SHOT_BASE for v in ones)

    def test_classify_outcome_covers_the_shed_vocabulary(self):
        from benchmarks import churn_drill as cd
        from karpenter_tpu.explain.reasons import SHED_REASONS

        cases = {
            "r0: replica browned out (pressure 0.93)": "overload-brownout",
            "r0: overload pressure 0.81 and tenant 'x' is over":
                "overload-pressure",
            "tenant backlog exceeded the bound 64; dropping":
                "overload-queue-overflow",
            "17ms of budget cannot survive; shedding at admission":
                "deadline",
        }
        for msg, want in cases.items():
            outcome, reason = cd.classify_outcome(Exception(msg))
            assert outcome == "shed" and reason == want
            assert reason in SHED_REASONS
        assert cd.classify_outcome(Exception("boom")) == ("error", None)

    def test_variant_catalogs_hash_distinct(self):
        from benchmarks import churn_drill as cd
        from karpenter_tpu.solver import wire

        hashes = {wire.catalog_hash(cd._variant_catalog(v))
                  for v in (0, 1, 2, cd.ONE_SHOT_BASE)}
        assert len(hashes) == 4
