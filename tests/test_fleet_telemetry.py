"""Fleet-scale telemetry plane: FleetView federation (`/debug/fleetz`,
stitched Perfetto traces, merged trace index), the per-solver HBM
residency ledger with pressure-based LRU eviction, the end-to-end
2-replica / 1000-tenant telemetry drill, and the slow 256-tenant fleet
bench exercising the cardinality guard at scale."""

import dataclasses
import glob
import json
import urllib.request

import pytest

from karpenter_tpu.apis import wellknown as wk
from karpenter_tpu.apis.provisioner import Provisioner
from karpenter_tpu.introspect.fleetview import (FleetView, HttpReplica,
                                                LocalReplica, ScrapeError)
from karpenter_tpu.fleet.router import FleetRouter
from karpenter_tpu.models.instancetype import Catalog, make_instance_type
from karpenter_tpu.models.requirements import OP_IN, Requirements
from karpenter_tpu.solver import buckets
from karpenter_tpu.tracing import SpanContext, Tracer


def small_catalog():
    return Catalog(types=[
        make_instance_type("m.large", cpu=4, memory="16Gi",
                           od_price=0.20, spot_price=0.07),
        make_instance_type("m.xlarge", cpu=16, memory="64Gi",
                           od_price=0.80, spot_price=0.28),
    ])


def default_provisioner():
    p = Provisioner(name="default", requirements=Requirements.of(
        (wk.LABEL_CAPACITY_TYPE, OP_IN, ["spot", "on-demand"])))
    p.set_defaults()
    return p


def _statusz_stub(name, healthy=True, tenants=None):
    def build():
        if not healthy:
            raise RuntimeError(f"{name} is down")
        telemetry = {"k": 4, "tracked": [
            {"tenant": t, "count": c, "error": 0.0}
            for t, c in (tenants or {}).items()]}
        return {
            "schema": 6, "version": "test", "ts": 1.0,
            "resilience": {"watchdog": {"healthy": True}},
            "hbm": {"solvers": {"aa/bb": {"total_bytes": 64.0}},
                    "resident_bytes_total": 64.0, "pressure": None},
            "fleet": {"frontends": [
                {"name": name, "queued": 2,
                 "tenant_telemetry": telemetry}]},
        }
    return build


class TestFleetView:
    def test_fleetz_joins_replicas_and_pins_tenants(self):
        router = FleetRouter()
        fv = FleetView(router=router, name="fleet-test")
        fv.add_replica(LocalReplica(
            "rep-a", statusz=_statusz_stub("rep-a", tenants={"t1": 5.0})))
        fv.add_replica(LocalReplica(
            "rep-b", statusz=_statusz_stub("rep-b", tenants={"t2": 3.0,
                                                             "t1": 1.0})))
        doc = fv.fleetz()
        assert doc["tool"] == "karpenter-tpu-fleetz"
        assert doc["schema"] == 2
        assert doc["membership_epoch"] == 2
        assert set(doc["replicas"]) == {"rep-a", "rep-b"}
        for name, row in doc["replicas"].items():
            assert row["healthy"] is True
            assert row["resident_solvers"] == ["aa/bb"]
            assert row["queued"] == 2
        assert doc["replicas"]["rep-a"]["joined_epoch"] == 1
        assert doc["replicas"]["rep-b"]["joined_epoch"] == 2
        # merged tenant table sums sketch counts fleet-wide, heaviest first
        assert doc["tenants"][0] == {"tenant": "t1", "count": 6.0,
                                     "error": 0.0}
        # pinning comes from the SAME router that routes traffic
        assert set(doc["pinning"]) == {"t1", "t2"}
        for t, rep in doc["pinning"].items():
            assert rep == router.route(t)

    def test_dead_replica_degrades_to_error_row(self):
        fv = FleetView(name="fleet-test")
        fv.add_replica(LocalReplica(
            "alive", statusz=_statusz_stub("alive")))
        fv.add_replica(LocalReplica(
            "dead", statusz=_statusz_stub("dead", healthy=False)))
        doc = fv.fleetz()
        assert doc["replicas"]["alive"]["healthy"] is True
        dead = doc["replicas"]["dead"]
        assert dead["healthy"] is False
        assert "dead is down" in dead["error"]

    def test_remove_replica_bumps_epoch_and_router(self):
        router = FleetRouter()
        fv = FleetView(router=router)
        fv.add_replica(LocalReplica("a", statusz=_statusz_stub("a")))
        fv.add_replica(LocalReplica("b", statusz=_statusz_stub("b")))
        assert router.replicas == ("a", "b")
        fv.remove_replica("a")
        assert router.replicas == ("b",)
        assert fv.fleetz()["membership_epoch"] == 3

    def test_federated_trace_stitches_lanes(self):
        client = Tracer(ring_size=64, registry=None)
        server = Tracer(ring_size=64, registry=None)
        fv = FleetView(name="fed", tracer=client)
        fv.add_replica(LocalReplica("rep-a", tracer=server))
        with client.start_span("fleet.solve", tenant="t1") as root:
            s = server.start_span(
                "solver.service.Solve",
                context=SpanContext(root.trace_id, root.span_id))
            s.end()
        doc = fv.federated_trace(root.trace_id)
        assert doc is not None
        lanes = {e["args"]["name"]: e["pid"]
                 for e in doc["traceEvents"] if e["ph"] == "M"}
        assert set(lanes) == {"client:fed", "rep-a"}
        spans = [e for e in doc["traceEvents"] if e["ph"] == "X"]
        assert {e["name"] for e in spans} == {"fleet.solve",
                                              "solver.service.Solve"}
        # each span rides its own process lane, annotated with it
        by_name = {e["name"]: e for e in spans}
        assert by_name["fleet.solve"]["pid"] == lanes["client:fed"]
        assert by_name["solver.service.Solve"]["pid"] == lanes["rep-a"]
        assert by_name["solver.service.Solve"]["args"]["replica"] == "rep-a"
        # one shared trace id joins the lanes
        assert {e["cat"] for e in spans} == {root.trace_id}

    def test_federated_trace_dedupes_shared_ring(self):
        # an in-process replica may share the client's ring: each span
        # must appear exactly once
        shared = Tracer(ring_size=64, registry=None)
        fv = FleetView(name="self", tracer=shared)
        fv.add_replica(LocalReplica("self", tracer=shared))
        with shared.start_span("cycle") as root:
            pass
        doc = fv.federated_trace(root.trace_id)
        assert len([e for e in doc["traceEvents"] if e["ph"] == "X"]) == 1

    def test_federated_trace_unknown_id_is_none(self):
        fv = FleetView(name="x", tracer=Tracer(ring_size=8, registry=None))
        fv.add_replica(LocalReplica(
            "r", tracer=Tracer(ring_size=8, registry=None)))
        assert fv.federated_trace("deadbeef") is None

    def test_trace_index_merges_and_annotates(self):
        client = Tracer(ring_size=64, registry=None)
        server = Tracer(ring_size=64, registry=None)
        fv = FleetView(name="fed", tracer=client)
        fv.add_replica(LocalReplica("rep-a", tracer=server))
        with client.start_span("fleet.solve", tenant="t9") as root:
            s = server.start_span(
                "Solve", context=SpanContext(root.trace_id, root.span_id))
            s.end()
        with server.start_span("replica.only"):
            pass
        rows = fv.trace_index(limit=10)
        by_id = {r["trace_id"]: r for r in rows}
        joined = by_id[root.trace_id]
        # the client row won the merge (it carries tenant annotations)
        assert joined["root"] == "fleet.solve"
        assert joined["tenants"] == ["t9"]
        assert joined["replicas"] == ["rep-a"]
        # a replica-only trace still appears, attributed to its replica
        others = [r for r in rows if r["root"] == "replica.only"]
        assert others and others[0]["replicas"] == ["rep-a"]

    def test_http_replica_404_means_no_spans(self, monkeypatch):
        # _get_json classifies every raw urllib failure into ScrapeError;
        # trace_spans treats the http-404 kind as "no spans for this id"
        # (an empty ring, not a scrape failure) and re-raises the rest
        rep = HttpReplica("r", "http://127.0.0.1:1")

        def raise_404(*a, **kw):
            raise ScrapeError("http-404", "u: nf")

        monkeypatch.setattr(rep, "_get_json", raise_404)
        assert rep.trace_spans("abc") == []

        def raise_500(*a, **kw):
            raise ScrapeError("http-500", "u: boom")

        monkeypatch.setattr(rep, "_get_json", raise_500)
        with pytest.raises(ScrapeError):
            rep.trace_spans("abc")


class TestHbmLedger:
    def test_untracked_outside_scope(self):
        led = buckets.HbmLedger()
        led.track(1024.0, "catalog")  # no scope: stays unledgered
        assert led.resident_bytes() == 0.0

    def test_static_accumulates_delta_replaces(self):
        led = buckets.HbmLedger()
        with buckets.hbm_scope("k1"):
            led.track(100.0, "catalog")
            led.track(50.0, "catalog")   # second Sync upload accumulates
            led.track(30.0, "pack_inputs")
        led.attribute_delta("k1", "g8s64")
        snap = led.snapshot()
        assert snap["solvers"]["k1"]["static_bytes"] == {"catalog": 150.0}
        assert snap["solvers"]["k1"]["delta_bytes"] == {"delta:g8s64": 30.0}
        # the next solve on the same rung REPLACES (donated buffers reuse
        # the device allocation; stacking would double-count)
        with buckets.hbm_scope("k1"):
            led.track(40.0, "pack_inputs")
        led.attribute_delta("k1", "g8s64")
        assert led.snapshot()["solvers"]["k1"]["delta_bytes"] == {
            "delta:g8s64": 40.0}
        assert led.resident_bytes("k1") == 190.0

    def test_scope_bucket_files_rung_directly(self):
        led = buckets.HbmLedger()
        with buckets.hbm_scope("k1", bucket="delta:g4s32"):
            led.track(8.0, "pack_inputs")
        assert led.snapshot()["solvers"]["k1"]["delta_bytes"] == {
            "delta:g4s32": 8.0}

    def test_release_frees_everything(self):
        led = buckets.HbmLedger()
        with buckets.hbm_scope("k1"):
            led.track(100.0, "catalog")
            led.track(30.0, "pack_inputs")
        led.attribute_delta("k1", "b")
        assert led.release("k1") == 130.0
        assert led.resident_bytes() == 0.0
        assert led.snapshot()["solvers"] == {}

    def test_pressure_disarmed_without_capacity(self, monkeypatch):
        monkeypatch.delenv(buckets.HBM_CAPACITY_ENV, raising=False)
        led = buckets.HbmLedger()
        with buckets.hbm_scope("k1"):
            led.track(100.0, "catalog")
        assert led.pressure() is None
        assert led.snapshot()["pressure"] is None
        monkeypatch.setenv(buckets.HBM_CAPACITY_ENV, "200")
        assert led.pressure() == pytest.approx(0.5)
        assert led.snapshot()["capacity_bytes"] == 200

    def test_capacity_env_validation(self, monkeypatch):
        monkeypatch.setenv(buckets.HBM_CAPACITY_ENV, "garbage")
        assert buckets.hbm_capacity_default() is None
        monkeypatch.setenv(buckets.HBM_CAPACITY_ENV, "-5")
        assert buckets.hbm_capacity_default() is None
        monkeypatch.setenv(buckets.HBM_CAPACITY_ENV, "1024")
        assert buckets.hbm_capacity_default() == 1024

    def test_scope_restores_previous(self):
        with buckets.hbm_scope("outer", bucket="a"):
            with buckets.hbm_scope("inner"):
                assert buckets._SCOPE.solver_key == "inner"
            assert buckets._SCOPE.solver_key == "outer"
            assert buckets._SCOPE.bucket == "a"
        assert buckets._SCOPE.solver_key == ""


class TestHbmServicePressure:
    @pytest.fixture(autouse=True)
    def _clean_ledger(self):
        """The HBM ledger is process-global and earlier tests may leak
        resident entries; with this class's 1-byte capacity any residue
        reads as crowding and flips the admission path. Start empty."""
        for key in list(buckets.HBM.snapshot()["solvers"]):
            buckets.HBM.release(key)
        yield

    @staticmethod
    def _two_syncs(svc):
        """First Sync installs one solver; second Sync ships a moved-price
        catalog (new content hash) under a 1-byte declared capacity, so
        every resident grid is over the 0.9 pressure line."""
        from karpenter_tpu.solver import wire
        from karpenter_tpu.solver.service import pb

        cat = small_catalog()
        provs = [default_provisioner()]
        req = pb.SyncRequest(catalog=wire.catalog_to_wire(cat),
                             provisioners=[wire.provisioner_to_wire(p)
                                           for p in provs])
        svc.Sync(req, None)
        (key1,) = list(svc._cache)
        moved = dataclasses.replace(cat, types=[
            dataclasses.replace(t, offerings=type(t.offerings)(tuple(
                dataclasses.replace(o, price=o.price * 2)
                for o in t.offerings)))
            for t in cat.types], seqnum=cat.seqnum + 1)
        req2 = pb.SyncRequest(catalog=wire.catalog_to_wire(moved),
                              provisioners=[wire.provisioner_to_wire(p)
                                            for p in provs])
        svc.Sync(req2, None)
        return key1

    def test_sync_under_pressure_evicts_down_to_one(self, monkeypatch):
        """Overload plane ON (the default): the unearned newcomer lands on
        probation and the low-water drain evicts the warm resident, so
        exactly ONE solver stays device-resident (count cap alone would
        have kept both) and the evicted ledger bytes are released."""
        from karpenter_tpu.solver.service import SolverService, hbm_key

        monkeypatch.setenv(buckets.HBM_CAPACITY_ENV, "1")
        svc = SolverService()
        key1 = self._two_syncs(svc)
        assert len(svc._cache) + len(svc._probation) == 1
        (key2,) = list(svc._probation)
        assert key2 != key1
        # the evicted solver's ledger entries were released (gauges step
        # to zero, entries drop)
        assert buckets.HBM.resident_bytes(hbm_key(key1)) == 0.0
        assert buckets.HBM.resident_bytes(hbm_key(key2)) > 0
        buckets.HBM.release(hbm_key(key2))  # leave no residue behind

    def test_sync_under_pressure_disabled_keeps_newcomer(self, monkeypatch):
        """Plane disabled is a strict no-op: the pre-plane eviction loop —
        newcomer straight into the LRU, pressure pass keeps the entry
        just installed, old resident evicted and released."""
        from karpenter_tpu.overload import state as overload
        from karpenter_tpu.solver.service import SolverService, hbm_key

        monkeypatch.setenv(buckets.HBM_CAPACITY_ENV, "1")
        svc = SolverService()
        with overload.disabled():
            key1 = self._two_syncs(svc)
        assert len(svc._cache) == 1
        assert not svc._probation
        (key2,) = list(svc._cache)
        assert key2 != key1
        assert buckets.HBM.resident_bytes(hbm_key(key1)) == 0.0
        assert buckets.HBM.resident_bytes(hbm_key(key2)) > 0
        buckets.HBM.release(hbm_key(key2))  # leave no residue behind


class TestTelemetryDrill:
    def test_drill_meets_all_acceptance_criteria(self, tmp_path):
        """The 2-replica / 1000-tenant drill (benchmarks/telemetry_drill)
        end to end: bounded series, fleetz naming both replicas with
        pinning, one stitched federated trace, and a per-tenant SloBurn
        edge with a flight-recorder bundle for the throttled tenant."""
        from benchmarks.telemetry_drill import HOT, REPLICAS, run_drill

        artifact = run_drill(str(tmp_path))
        assert artifact["criteria"] == {
            "series_bounded_k_plus_1": True,
            "fleetz_names_both_replicas": True,
            "federated_trace_stitches_client_and_replica": True,
            "per_tenant_slo_burn_fired": True,
        }
        assert artifact["passed"] is True
        guard = artifact["tenant_guard"]
        assert guard["offers"] >= 1000
        for family, n in guard["series_per_family"].items():
            assert n <= guard["k"] + 1, (family, n)
        fleetz = artifact["fleetz"]
        assert set(REPLICAS) <= set(fleetz["replicas"])
        assert fleetz["pinning"][HOT] in REPLICAS
        # the burn bundle is on disk next to the artifact
        bundles = glob.glob(str(tmp_path / "bundles" / "bundle_*.json"))
        assert any("fleet_tenant_p99" in b for b in bundles)
        with open(artifact["artifact_path"]) as f:
            on_disk = json.load(f)
        assert on_disk["passed"] is True


class TestLabelCardinalityLint:
    LINT = "hack/check_label_cardinality.py"

    def _run(self, *args):
        import subprocess
        import sys as _sys

        return subprocess.run(
            [_sys.executable, self.LINT, *map(str, args)],
            capture_output=True, text=True, cwd="/root/repo")

    def test_repo_passes(self):
        res = self._run()
        assert res.returncode == 0, res.stderr

    def test_raw_tenant_label_flagged(self, tmp_path):
        bad = tmp_path / "bad.py"
        bad.write_text(
            "def f(metric, tenant_id):\n"
            "    metric.inc(tenant=tenant_id)\n"
            "    metric.observe(1.0, tenant=str(tenant_id))\n"
            "    metric.set(1.0, pod_name=f'pod-{tenant_id}')\n")
        res = self._run(bad)
        assert res.returncode == 1
        assert res.stderr.count("unbounded runtime value") == 3

    def test_guarded_and_allowlisted_pass(self, tmp_path):
        ok = tmp_path / "ok.py"
        ok.write_text(
            "def f(metric, guard, tid, raw):\n"
            "    metric.inc(tenant=guard.label(tid))\n"
            "    metric.observe(1.0, tenant=tenant_peek(tid))\n"
            "    tlabel = guard.peek(tid)\n"
            "    metric.set(1.0, tenant=tlabel)\n"
            "    metric.inc(tenant='literal', where='queue')\n"
            "    # label-cardinality-ok: test fixture, bounded by caller\n"
            "    metric.inc(node_name=raw)\n")
        res = self._run(ok)
        assert res.returncode == 0, res.stderr


@pytest.mark.slow
class TestFleetBenchTenantScale:
    def test_fleet_bench_at_256_tenants_bounds_series(self, tmp_path,
                                                      monkeypatch):
        """bench.py --fleet --tenants 256: the artifact carries the top-K
        tenant table and a series count that stayed <= K+1 per family
        even with 8x more tenants than sketch slots."""
        import types

        import jax

        import bench

        monkeypatch.setenv("KARPENTER_TPU_FLEET_BENCH_DIR", str(tmp_path))
        monkeypatch.setenv("KARPENTER_TPU_LEDGER",
                           str(tmp_path / "ledger.jsonl"))
        args = types.SimpleNamespace(fleet_tenants=256, fleet_rate=0.5,
                                     fleet_seconds=2.0)
        rc = bench._fleet_bench(args, jax)
        assert rc == 0
        with open(tmp_path / "fleet_bench.json") as f:
            record = json.load(f)
        assert record["tenants"] == 256
        tel = record["tenant_telemetry"]
        assert tel["k"] >= 1
        assert 0 < tel["series_max"] <= tel["k"] + 1
        for family, n in tel["series_per_family"].items():
            assert n <= tel["k"] + 1, (family, n)
        assert tel["top"], "top-K tenant table missing from artifact"
        # the perf ledger got the series-bound metric
        ledger_lines = [json.loads(line) for line in
                        open(tmp_path / "ledger.jsonl")]
        assert any(e.get("metric") == "fleet_tenant_series_max"
                   for e in ledger_lines)
