"""The io-probe gate's judgment (hack/tpu_capture.judge_io_probe) decides
whether bench.py and the capture tool route production reads through the
callback transport — driver-critical, so the truth table is pinned here.
(The probe itself needs a device; the judgment is pure.)"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from hack.tpu_capture import judge_io_probe


def _probe(sync_p50=0.05, received=6, error=None):
    p = {"sync_after": {"p50_ms": sync_p50, "min_ms": sync_p50},
         "values_received": received, "p50_ms": 0.5, "first_ms": 50.0}
    if error is not None:
        p = {"error": error}
    return p


def test_healthy_probe_enables_transport():
    assert judge_io_probe(_probe(), reps=5) == (True, True)


def test_degraded_sentinel_disables_both():
    streaming, ok = judge_io_probe(_probe(sync_p50=66.0), reps=5)
    assert (streaming, ok) == (False, False)


def test_streaming_but_undelivered_is_the_false_positive():
    # sub-ms sentinel with missing deliveries: link fine, transport NOT
    streaming, ok = judge_io_probe(_probe(received=0), reps=5)
    assert (streaming, ok) == (True, False)
    streaming, ok = judge_io_probe(_probe(received=5), reps=5)  # warmup lost
    assert (streaming, ok) == (True, False)


def test_errored_probe_means_transition_still_ahead_but_no_transport():
    # probe never ran device work: attribution says streaming, gate says no
    streaming, ok = judge_io_probe(_probe(error="io_callback unavailable"),
                                   reps=5)
    assert (streaming, ok) == (True, False)


def test_missing_sentinel_defaults_to_degraded():
    p = {"values_received": 6}
    assert judge_io_probe(p, reps=5) == (False, False)


# -- partial-capture salvage (a relay wedge banks completed sections) --------

def test_salvage_banks_checkpointed_sections(tmp_path, monkeypatch):
    import json

    import hack.tpu_capture as tc

    monkeypatch.setattr(tc, "RESULTS_DIR", str(tmp_path))
    partial = tmp_path / "partial.json"
    partial.write_text(json.dumps({
        "backend": "tpu",
        "exec_sweep": [{"n_pods": 100, "p50_ms": 1.0}],
        "exec_only_10k": {"n_pods": 10000, "p50_ms": 2.3}}))
    rec = tc._salvage_partial(str(partial), wedged_after_s=2400)
    assert rec is not None and rec["partial"] is True
    assert rec["wedged_after_s"] == 2400
    assert rec["exec_only_10k"]["p50_ms"] == 2.3
    assert not partial.exists()  # consumed
    (saved,) = list(tmp_path.glob("tpu_*.json"))
    assert json.loads(saved.read_text())["partial"] is True


def test_salvage_ignores_empty_or_missing_partial(tmp_path, monkeypatch):
    import json

    import hack.tpu_capture as tc

    monkeypatch.setattr(tc, "RESULTS_DIR", str(tmp_path))
    assert tc._salvage_partial(str(tmp_path / "absent.json"),
                               crashed_rc=1) is None
    p = tmp_path / "backend_only.json"
    p.write_text(json.dumps({"backend": "tpu"}))
    assert tc._salvage_partial(str(p), crashed_rc=1) is None  # nothing measured
    assert not list(tmp_path.glob("tpu_*.json"))


def test_salvage_records_crash_mode_distinctly(tmp_path, monkeypatch):
    import json

    import hack.tpu_capture as tc

    monkeypatch.setattr(tc, "RESULTS_DIR", str(tmp_path))
    p = tmp_path / "p.json"
    p.write_text(json.dumps({"backend": "tpu", "exec_sweep": []}))
    rec = tc._salvage_partial(str(p), crashed_rc=1)
    assert rec["crashed_rc"] == 1 and "wedged_after_s" not in rec


def test_route_crossover_skips_partial_without_sweep(tmp_path, monkeypatch):
    """A newer partial capture missing crossover_pods must not shadow the
    older complete capture's measured crossover."""
    import json

    from karpenter_tpu.utils import capture as capmod

    old = tmp_path / "tpu_20260101T000000Z.json"
    old.write_text(json.dumps({"crossover_pods": 3000}))
    new = tmp_path / "tpu_20260102T000000Z.json"
    new.write_text(json.dumps({"partial": True, "exec_sweep": []}))
    monkeypatch.setattr(capmod, "RESULTS_DIR", str(tmp_path))
    monkeypatch.delenv("KARPENTER_TPU_ROUTE_CROSSOVER", raising=False)
    assert capmod.route_crossover() == 3000
    # the newest record overall is still the partial (bench reporting)
    assert capmod.latest_capture(str(tmp_path))["partial"] is True
