"""The io-probe gate's judgment (hack/tpu_capture.judge_io_probe) decides
whether bench.py and the capture tool route production reads through the
callback transport — driver-critical, so the truth table is pinned here.
(The probe itself needs a device; the judgment is pure.)"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from hack.tpu_capture import judge_io_probe


def _probe(sync_p50=0.05, received=6, error=None):
    p = {"sync_after": {"p50_ms": sync_p50, "min_ms": sync_p50},
         "values_received": received, "p50_ms": 0.5, "first_ms": 50.0}
    if error is not None:
        p = {"error": error}
    return p


def test_healthy_probe_enables_transport():
    assert judge_io_probe(_probe(), reps=5) == (True, True)


def test_degraded_sentinel_disables_both():
    streaming, ok = judge_io_probe(_probe(sync_p50=66.0), reps=5)
    assert (streaming, ok) == (False, False)


def test_streaming_but_undelivered_is_the_false_positive():
    # sub-ms sentinel with missing deliveries: link fine, transport NOT
    streaming, ok = judge_io_probe(_probe(received=0), reps=5)
    assert (streaming, ok) == (True, False)
    streaming, ok = judge_io_probe(_probe(received=5), reps=5)  # warmup lost
    assert (streaming, ok) == (True, False)


def test_errored_probe_means_transition_still_ahead_but_no_transport():
    # probe never ran device work: attribution says streaming, gate says no
    streaming, ok = judge_io_probe(_probe(error="io_callback unavailable"),
                                   reps=5)
    assert (streaming, ok) == (True, False)


def test_missing_sentinel_defaults_to_degraded():
    p = {"values_received": 6}
    assert judge_io_probe(p, reps=5) == (False, False)
