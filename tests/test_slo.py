"""Perf SLO plane tests (docs/designs/slo.md): perf-ledger roundtrip and
backfill idempotence, burn-rate window math under a stepped clock,
edge-triggered SloBurn/SloRecovered events with flight-recorder bundles,
the >=95% phase-attribution invariant over a real provisioning cycle,
histogram trace-id exemplars resolving through /debug/traces, and the
perf-regress gate's falsifiability (a seeded regression MUST trip it)."""

import json
import threading
import urllib.request

from karpenter_tpu.apis.nodetemplate import NodeTemplate
from karpenter_tpu.apis.provisioner import Provisioner
from karpenter_tpu.apis.settings import Settings
from karpenter_tpu.fake.cloud import FakeCloud
from karpenter_tpu.introspect.slo import PHASE_METRIC, Slo, SloEvaluator
from karpenter_tpu.metrics import Registry
from karpenter_tpu.models.instancetype import Catalog, make_instance_type
from karpenter_tpu.models.pod import make_pod
from karpenter_tpu.operator import Operator
from karpenter_tpu.tracing import TRACER
from karpenter_tpu.utils.clock import FakeClock

from benchmarks import ledger


# -- the perf ledger ----------------------------------------------------------


class TestLedger:
    def test_record_roundtrip_via_env_override(self, tmp_path, monkeypatch):
        path = tmp_path / "ledger.jsonl"
        monkeypatch.setenv("KARPENTER_TPU_LEDGER", str(path))
        entry = ledger.record("cycle_ms", 12.5, "ms", source="test",
                              backend="cpu", workload={"pods": 10},
                              detail={"k": "v"})
        got = ledger.entries()
        assert len(got) == 1
        assert got[0] == entry
        assert got[0]["schema"] == ledger.SCHEMA_VERSION
        assert got[0]["metric"] == "cycle_ms"
        assert got[0]["value"] == 12.5
        assert got[0]["workload"] == {"pods": 10}
        assert got[0]["degraded"] is False
        # provenance fields exist even when empty
        for field in ("git_sha", "recorded_at", "artifact", "backend"):
            assert field in got[0]

    def test_torn_tail_line_does_not_poison_the_trend(self, tmp_path):
        path = tmp_path / "ledger.jsonl"
        ledger.record("m", 1.0, "ms", source="test", path=str(path))
        with open(path, "a") as f:
            f.write('{"metric": "m", "value": 2.0, "uncl')  # torn write
        assert [e["value"] for e in ledger.entries(str(path))] == [1.0]
        # and appending after the torn line still lands on its own line
        ledger.record("m", 3.0, "ms", source="test", path=str(path))
        assert len(ledger.entries(str(path))) >= 1

    def test_backfill_is_idempotent(self, tmp_path):
        root = tmp_path / "repo"
        (root / "benchmarks" / "results").mkdir(parents=True)
        artifact = {
            "recorded_at": "20260801T000000Z", "backend": "cpu",
            "entries": [
                {"bench": "interruption", "messages": 1000,
                 "msgs_per_sec": 5000.0},
                {"bench": "baseline_config", "name": "inflate-100",
                 "ms": 1.25},
                {"bench": "wire_provisioning", "pods": 10000,
                 "ingest_seconds": 4.0, "cycle_seconds": 9.0},
            ]}
        (root / "benchmarks" / "results" / "bench_x.json").write_text(
            json.dumps(artifact))
        path = str(tmp_path / "ledger.jsonl")
        first = ledger.backfill(root=str(root), path=path)
        assert first == 4  # msgs/s + ms + ingest_s + cycle_s
        metrics = {e["metric"] for e in ledger.entries(path)}
        assert metrics == {"interruption_msgs_per_sec", "baseline_config_ms",
                           "wire_ingest_seconds", "wire_cycle_seconds"}
        # every backfilled entry cites its artifact
        assert all(e["artifact"] for e in ledger.entries(path))
        assert ledger.backfill(root=str(root), path=path) == 0  # idempotent

    def test_committed_ledger_backfill_is_a_noop(self):
        """The committed trend already contains its own history: re-running
        backfill against the real repo must add nothing."""
        assert ledger.backfill() == 0
        assert len(ledger.entries()) > 200

    def test_noise_band_excludes_degraded(self, tmp_path):
        path = str(tmp_path / "l.jsonl")
        for v in (10.0, 11.0, 12.0):
            ledger.record("m", v, "ms", source="t", backend="cpu", path=path)
        ledger.record("m", 500.0, "ms", source="t", backend="cpu",
                      degraded=True, path=path)
        band = ledger.noise_band("m", backend="cpu", path=path)
        assert band["n"] == 3
        assert band["median"] == 11.0
        assert band["mad"] == 1.0
        wide = ledger.noise_band("m", backend="cpu", path=path,
                                 include_degraded=True)
        assert wide["n"] == 4


# -- burn-rate window math ----------------------------------------------------


class FakeRecorder:
    def __init__(self):
        self.events = []

    def warning(self, ref, reason, message):
        self.events.append(("warning", ref, reason, message))
        return True

    def normal(self, ref, reason, message):
        self.events.append(("normal", ref, reason, message))
        return True


class FakeFlightRecorder:
    def __init__(self):
        self.triggers = []

    def trigger(self, reason, detail="", force=False):
        self.triggers.append((reason, detail))
        return "/tmp/bundle.json"


class TestBurnMath:
    def _evaluator(self, slos):
        reg = Registry()
        clock = FakeClock()
        rec, fr = FakeRecorder(), FakeFlightRecorder()
        ev = SloEvaluator(registry=reg, clock=clock, recorder=rec,
                          flightrecorder=fr, slos=slos)
        hist = reg.histogram(PHASE_METRIC, "", ("phase",))
        return ev, reg, clock, rec, fr, hist

    def test_latency_burn_edge_triggers_and_recovers(self):
        slo = Slo("cycle_p99", "latency", "cycles under 1s",
                  metric=PHASE_METRIC,
                  labels={"phase": "provisioning.cycle"},
                  threshold_s=1.0, objective=0.90)
        ev, reg, clock, rec, fr, hist = self._evaluator((slo,))

        res = ev.evaluate()  # cold start: single snapshot, zero deltas
        assert res["cycle_p99"]["burning"] is False

        for _ in range(10):
            hist.observe(0.1, phase="provisioning.cycle")
        clock.step(60)
        res = ev.evaluate()
        # all 10 events inside the 5m window were good
        assert res["cycle_p99"]["windows"]["5m"]["value"] == 0.0
        assert res["cycle_p99"]["windows"]["5m"]["events"] == 10
        assert ev.g_healthy.value(slo="cycle_p99") == 1.0

        clock.step(60)
        for _ in range(10):
            hist.observe(2.0, phase="provisioning.cycle")  # all bad
        res = ev.evaluate()
        w = res["cycle_p99"]["windows"]["5m"]
        # window delta vs t=0: 10 of 20 events exceeded the threshold
        assert abs(w["value"] - 0.5) < 1e-9
        # burn = bad_fraction / (1 - objective) = 0.5 / 0.1
        assert abs(w["burn_rate"] - 5.0) < 1e-9
        assert res["cycle_p99"]["burning"] is True
        assert ev.g_healthy.value(slo="cycle_p99") == 0.0
        assert abs(ev.g_burn.value(slo="cycle_p99", window="5m")
                   - w["burn_rate"]) < 1e-6
        # edge-triggered exactly once, with a flight-recorder bundle
        burns = [e for e in rec.events if e[2] == "SloBurn"]
        assert len(burns) == 1
        assert [r for r, _ in fr.triggers] == ["slo_burn_cycle_p99"]

        # still burning on the next tick: NO duplicate event
        clock.step(10)
        assert ev.evaluate()["cycle_p99"]["burning"] is True
        assert len([e for e in rec.events if e[2] == "SloBurn"]) == 1

        # the bad burst ages out of the 5m window -> recovery, once
        clock.step(400)
        res = ev.evaluate()
        assert res["cycle_p99"]["burning"] is False
        recs = [e for e in rec.events if e[2] == "SloRecovered"]
        assert len(recs) == 1
        assert len(fr.triggers) == 1

    def test_burn_bundle_may_reenter_snapshot(self):
        """The real flight recorder's bundle captures statusz, whose slo
        section calls SloEvaluator.snapshot() — from the SAME thread that
        is inside evaluate(). Edge events must fire outside the evaluator
        lock or the first genuine burn wedges the slo loop forever."""
        slo = Slo("cycle_p99", "latency", "", metric=PHASE_METRIC,
                  labels={"phase": "provisioning.cycle"},
                  threshold_s=1.0, objective=0.90)
        ev, reg, clock, rec, fr, hist = self._evaluator((slo,))
        snaps = []
        fr.trigger = lambda reason, detail="", force=False: snaps.append(
            ev.snapshot())  # what statusz does inside the bundle
        ev.evaluate()
        hist.observe(5.0, phase="provisioning.cycle")  # bad: will burn
        clock.step(30)

        worker = threading.Thread(target=ev.evaluate, daemon=True)
        worker.start()
        worker.join(timeout=10)
        assert not worker.is_alive(), "evaluate() deadlocked in _on_burn"
        assert len(snaps) == 1
        assert snaps[0]["slos"]["cycle_p99"]["burning"] is True

    def test_long_window_still_sees_what_short_forgot(self):
        slo = Slo("cycle_p99", "latency", "", metric=PHASE_METRIC,
                  labels={"phase": "provisioning.cycle"},
                  threshold_s=1.0, objective=0.90)
        ev, reg, clock, rec, fr, hist = self._evaluator((slo,))
        ev.evaluate()
        hist.observe(5.0, phase="provisioning.cycle")
        clock.step(30)
        ev.evaluate()
        clock.step(600)  # past the 5m horizon, inside 1h
        res = ev.evaluate()["cycle_p99"]["windows"]
        assert res["5m"]["value"] == 0.0
        assert res["1h"]["value"] == 1.0

    def test_share_slo_prefix_aggregation(self):
        slo = Slo("ingest_share", "share", "ingest under half the cycle",
                  num_metric=PHASE_METRIC, num_labels={"phase": "ingest."},
                  den_metric=PHASE_METRIC,
                  den_labels={"phase": "provisioning.cycle"},
                  threshold=0.5)
        ev, reg, clock, rec, fr, hist = self._evaluator((slo,))
        ev.evaluate()
        # ingest.* family aggregates across decode+apply via prefix match
        hist.observe(0.2, phase="ingest.decode")
        hist.observe(0.2, phase="ingest.apply")
        hist.observe(1.0, phase="provisioning.cycle")
        clock.step(10)
        res = ev.evaluate()["ingest_share"]["windows"]["5m"]
        assert abs(res["value"] - 0.4) < 1e-9
        assert abs(res["burn_rate"] - 0.8) < 1e-9  # 0.4 / 0.5 ceiling
        # push ingest past the ceiling -> burning
        hist.observe(0.5, phase="ingest.apply")
        clock.step(10)
        res = ev.evaluate()
        assert res["ingest_share"]["burning"] is True

    def test_snapshot_never_empty_and_statusz_shaped(self):
        slo = Slo("cycle_p99", "latency", "", metric=PHASE_METRIC,
                  labels={"phase": "provisioning.cycle"},
                  threshold_s=1.0, objective=0.99)
        ev, *_ = self._evaluator((slo,))
        snap = ev.snapshot()  # no tick has run: evaluates inline
        assert set(snap) == {"windows", "burn_threshold", "slos"}
        assert "cycle_p99" in snap["slos"]
        assert set(snap["slos"]["cycle_p99"]["windows"]) == {"5m", "1h"}


# -- phase attribution over a real cycle --------------------------------------


def _operator(**kw):
    cat = Catalog(types=[
        make_instance_type("t.small", cpu=2, memory="2Gi",
                           od_price=0.05, spot_price=0.02),
        make_instance_type("m.xlarge", cpu=16, memory="64Gi",
                           od_price=0.80, spot_price=0.28),
    ])
    clock = FakeClock()
    op = Operator(FakeCloud(catalog=cat, clock=clock),
                  Settings(cluster_name="slo",
                           cluster_endpoint="https://k.example",
                           batch_idle_duration=0.0, batch_max_duration=0.0),
                  cat, clock=clock, **kw)
    op.kube.create("nodetemplates", "default", NodeTemplate(
        name="default", subnet_selector={"id": "subnet-zone-1a"},
        security_group_selector={"id": "sg-default"}))
    op.cloudprovider.register_nodetemplate(
        op.kube.get("nodetemplates", "default"))
    p = Provisioner(name="default", provider_ref="default")
    p.set_defaults()
    op.kube.create("provisioners", "default", p)
    return op


class TestPhaseCoverage:
    def test_cycle_phases_cover_95_percent_of_wall_clock(self):
        """The attribution invariant: a cycle-latency burn must be
        explainable from the phase split alone. If this drops below 95%,
        someone added cycle work outside any phase span."""
        op = _operator()
        try:
            for i in range(60):
                op.kube.create("pods", f"p{i}",
                               make_pod(f"p{i}", cpu="500m", memory="1Gi"))
            TRACER.clear()
            op.provisioning.reconcile_once()
            assert len(op.kube.pending_pods()) == 0
            cov = TRACER.phase_coverage()
            assert cov is not None
            assert cov["root"] == "provisioning.cycle"
            assert cov["root_s"] > 0
            assert {"provisioning.mask", "provisioning.solve",
                    "provisioning.bind"} <= set(cov["phases"])
            assert cov["coverage"] >= 0.95, (
                f"phases cover only {cov['coverage']:.1%} of the cycle: "
                f"{cov['phases']}")
        finally:
            op.stop()

    def test_dark_phases_are_spanned(self, monkeypatch):
        """The formerly-dark phases record real spans: solver interior
        (encode/dispatch/transfer/decode) and the binding fan-out. Routing
        is pinned to the device solver — the native scan path these pod
        counts would otherwise take has no interior to attribute."""
        monkeypatch.setenv("KARPENTER_TPU_ROUTE_CROSSOVER", "0")
        op = _operator()
        try:
            for i in range(40):
                op.kube.create("pods", f"p{i}",
                               make_pod(f"p{i}", cpu="500m", memory="1Gi"))
            TRACER.clear()
            op.provisioning.reconcile_once()
            names = {s.name for s in TRACER.finished_spans()}
            assert {"solver.encode", "solver.transfer",
                    "solver.decode"} <= names
            assert ("solver.dispatch.compile" in names
                    or "solver.dispatch.execute" in names)
            assert "provisioning.create" in names
            assert "provisioning.bind.pods" in names
            # fan-out spans joined the cycle's trace, not new roots
            root = next(s for s in TRACER.finished_spans()
                        if s.name == "provisioning.cycle")
            create = next(s for s in TRACER.finished_spans()
                          if s.name == "provisioning.create")
            assert create.trace_id == root.trace_id
        finally:
            op.stop()


# -- exemplars ----------------------------------------------------------------


class TestExemplars:
    def test_histogram_stores_and_exposes_exemplar(self):
        reg = Registry()
        h = reg.histogram("x_seconds", "help", ("m",))
        h.observe(0.2, exemplar="tid123", m="a")
        h.observe(0.3, m="a")  # no exemplar: last one sticks
        ex = h.exemplar(m="a")
        assert ex["trace_id"] == "tid123"
        assert ex["value"] == 0.2
        text = reg.expose()
        assert '# {trace_id="tid123"}' in text
        # the exemplar rides the +Inf bucket line only
        assert text.count("tid123") == 1

    def test_phase_exemplar_resolves_via_debug_traces(self):
        op = _operator(serve_http=True, metrics_port=0, health_port=0,
                       webhook_port=0)
        try:
            ports = op.serving.start()
            for i in range(30):
                op.kube.create("pods", f"p{i}",
                               make_pod(f"p{i}", cpu="500m", memory="1Gi"))
            TRACER.clear()
            op.provisioning.reconcile_once()
            ex = TRACER._phase_hist.exemplar(phase="provisioning.cycle")
            assert ex is not None
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{ports['metrics']}"
                    f"/debug/traces?id={ex['trace_id']}") as r:
                assert r.status == 200
                doc = json.loads(r.read().decode())
            assert "provisioning.cycle" in {e["name"]
                                            for e in doc["traceEvents"]}
            # the /metrics text carries the same trace id as an exemplar
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{ports['metrics']}/metrics") as r:
                body = r.read().decode()
            assert f'trace_id="{ex["trace_id"]}"' in body
        finally:
            op.stop()


# -- the regression gate: falsifiability --------------------------------------


class TestRegressGate:
    HOST = "slo-test-host"

    def _seed(self, path, metric, workload, values, unit):
        for v in values:
            ledger.record(metric, v, unit, source="hack.check_perf_regress",
                          backend="cpu", workload=workload, path=path,
                          detail={"host": self.HOST})

    def _ledger(self, tmp_path):
        path = str(tmp_path / "ledger.jsonl")
        self._seed(path, "interruption_msgs_per_sec", {"messages": 1000},
                   (5000.0, 5100.0, 4900.0, 5050.0), "msgs/s")
        self._seed(path, "baseline_config_ms", {"name": "inflate-100"},
                   (1.2, 1.3, 1.25, 1.28), "ms")
        self._seed(path, "profile_unaccounted_share",
                   {"name": "profile_gate", "pods": 400},
                   (0.02, 0.025, 0.022, 0.018), "ratio")
        return path

    def _run(self, tmp_path, monkeypatch, *inject):
        import hack.check_perf_regress as gate

        monkeypatch.setenv("KARPENTER_TPU_PERF_HOST", self.HOST)
        argv = ["--ledger", self._ledger(tmp_path)]
        for spec in inject:
            argv += ["--inject", spec]
        return gate.main(argv)

    def test_seeded_regression_trips_the_gate(self, tmp_path, monkeypatch,
                                              capsys):
        rc = self._run(tmp_path, monkeypatch,
                       "interruption_msgs_per_sec=100",
                       "baseline_config_ms=1.3",
                       "profile_unaccounted_share=0.02")
        out = capsys.readouterr().out
        assert rc == 1, out
        assert "FAIL  interruption_msgs_per_sec" in out
        assert "ok    baseline_config_ms" in out
        assert "ok    profile_unaccounted_share" in out

    def test_latency_regression_trips_too(self, tmp_path, monkeypatch,
                                          capsys):
        rc = self._run(tmp_path, monkeypatch,
                       "interruption_msgs_per_sec=5000",
                       "baseline_config_ms=99",
                       "profile_unaccounted_share=0.9")
        out = capsys.readouterr().out
        assert rc == 1, out
        assert "FAIL  baseline_config_ms" in out
        # attribution rot judges in the same pass: 90% unaccounted is
        # way past the seeded ~2% band ("lower" is the good direction)
        assert "FAIL  profile_unaccounted_share" in out

    def test_in_band_passes_and_faster_is_never_a_regression(
            self, tmp_path, monkeypatch, capsys):
        # 10x the throughput, half the latency, tighter attribution:
        # all GOOD directions
        rc = self._run(tmp_path, monkeypatch,
                       "interruption_msgs_per_sec=50000",
                       "baseline_config_ms=0.6",
                       "profile_unaccounted_share=0.005")
        assert rc == 0, capsys.readouterr().out

    def test_passing_measured_run_joins_the_band(self, tmp_path,
                                                 monkeypatch):
        """The band is a moving window: an in-band MEASURED run records a
        gate_sample so the band tracks gradual host drift, while a
        regressing measurement records nothing — a real slowdown must
        fail the current band, never pull the median toward itself."""
        import hack.check_perf_regress as gate

        path = self._ledger(tmp_path)
        before = len(ledger.entries(path))
        status, _ = gate.check_gate(
            "baseline_config_ms", {"name": "inflate-100"}, "cpu", "ms",
            "lower", lambda: 1.3, {}, path, self.HOST)
        assert status == "ok"
        es = ledger.entries(path)
        assert len(es) == before + 1
        assert es[-1]["value"] == 1.3
        assert es[-1]["detail"] == {"host": self.HOST, "gate_sample": True}

        status, _ = gate.check_gate(
            "baseline_config_ms", {"name": "inflate-100"}, "cpu", "ms",
            "lower", lambda: 99.0, {}, path, self.HOST)
        assert status == "regress"
        assert len(ledger.entries(path)) == before + 1

    def test_unknown_host_seeds_instead_of_judging(self, tmp_path,
                                                   monkeypatch, capsys):
        """History from OTHER hardware must not judge this machine: with no
        same-host points the gate reports SEED and passes even on numbers
        that would fail the other host's band."""
        import hack.check_perf_regress as gate

        monkeypatch.setenv("KARPENTER_TPU_PERF_HOST", "brand-new-box")
        rc = gate.main(["--ledger", self._ledger(tmp_path),
                        "--inject", "interruption_msgs_per_sec=100",
                        "--inject", "baseline_config_ms=99",
                        "--inject", "profile_unaccounted_share=0.9",
                        "--inject", "incremental_steady_encode_share=0.99",
                        "--inject", "critical_serialize_share=0.99",
                        "--inject", "churn_eviction_thrash_ratio=0.9"])
        out = capsys.readouterr().out
        assert rc == 0, out
        assert out.count("SEED") == 6
