"""Sharded (multi-device) packer vs single-device packer: bit parity.

Runs on the 8-device virtual CPU platform (conftest.py)."""

import jax
import numpy as np
import pytest

from karpenter_tpu.apis import wellknown as wk
from karpenter_tpu.apis.provisioner import Provisioner
from karpenter_tpu.models.encode import encode_problem
from karpenter_tpu.models.instancetype import Catalog, make_instance_type
from karpenter_tpu.models.pod import make_pod
from karpenter_tpu.parallel.sharded import make_mesh, sharded_pack
from karpenter_tpu.solver.core import _bucket
from karpenter_tpu.ops.packer import PackInputs, pack


def build_inputs():
    catalog = Catalog(types=[
        make_instance_type(f"t.{i}x", cpu=2 * (i + 1), memory=f"{8 * (i + 1)}Gi",
                           od_price=0.1 * (i + 1), spot_price=0.03 * (i + 1))
        for i in range(8)
    ])
    prov = Provisioner(name="default")
    prov.set_defaults()
    pods = [make_pod(f"a{i}", cpu="1", memory="2Gi") for i in range(40)] + [
        make_pod(f"b{i}", cpu="500m", memory="1Gi") for i in range(30)]
    enc = encode_problem(catalog, [prov], pods)
    return enc


def pad_inputs(enc):
    Gb = _bucket(enc.group_vec.shape[0])

    def pad(a, n, axis=0, fill=0):
        if a.shape[axis] == n:
            return a
        w = [(0, 0)] * a.ndim
        w[axis] = (0, n - a.shape[axis])
        return np.pad(a, w, constant_values=fill)

    return PackInputs(
        alloc_t=enc.alloc_t, tiebreak=enc.tiebreak,
        group_vec=pad(enc.group_vec, Gb), group_count=pad(enc.group_count, Gb),
        group_cap=pad(enc.group_cap, Gb), group_feas=pad(enc.group_feas, Gb),
        group_newprov=pad(enc.group_newprov, Gb, fill=-1), overhead=enc.overhead,
        ex_alloc=enc.ex_alloc, ex_used=enc.ex_used, ex_feas=pad(enc.ex_feas, Gb),
    ), _bucket(enc.n_slots)


def test_mesh_uses_all_devices():
    assert len(jax.devices()) == 8, "conftest must force 8 virtual devices"
    mesh = make_mesh(8)
    assert mesh.devices.size == 8
    assert mesh.axis_names == ("nodes", "types")


@pytest.mark.parametrize("n_devices", [2, 4, 8])
def test_sharded_pack_parity(n_devices):
    enc = build_inputs()
    _assert_parity(enc, n_devices)


def test_sharded_pack_parity_odd_type_count():
    # 5 types on a (2, 2) mesh: the type axis is NOT divisible by the mesh
    # dim, exercising pad_types (never-selectable padded entries)
    catalog = Catalog(types=[
        make_instance_type(f"o.{i}x", cpu=2 * (i + 1), memory=f"{8 * (i + 1)}Gi",
                           od_price=0.1 * (i + 1), spot_price=0.03 * (i + 1))
        for i in range(5)
    ])
    prov = Provisioner(name="default")
    prov.set_defaults()
    pods = [make_pod(f"a{i}", cpu="1", memory="2Gi") for i in range(25)]
    enc = encode_problem(catalog, [prov], pods)
    _assert_parity(enc, 4)


def _assert_parity(enc, n_devices):
    inputs, n_slots = pad_inputs(enc)
    base = jax.device_get(pack(jax.device_put(inputs), n_slots=n_slots))
    mesh = make_mesh(n_devices)
    sh = sharded_pack(inputs, n_slots, mesh)
    for name in ("assign", "ex_assign", "unsched", "decided", "nprov"):
        np.testing.assert_array_equal(
            np.asarray(getattr(base, name)), np.asarray(getattr(sh, name)),
            err_msg=f"sharded mismatch on {name} @ {n_devices} devices")
    np.testing.assert_array_equal(np.asarray(base.active), np.asarray(sh.active))
    np.testing.assert_array_equal(np.asarray(base.used), np.asarray(sh.used))


class TestMultihost:
    """Single-process coverage of the multi-host module (true multi-process
    runs need a pod; the driver's dryrun + these keep the path compiling)."""

    def test_hybrid_mesh_falls_back_single_process(self):
        from karpenter_tpu.parallel.multihost import (initialize_distributed,
                                                      make_hybrid_mesh,
                                                      mesh_description)

        assert initialize_distributed() is False  # one process in tests
        mesh = make_hybrid_mesh()
        assert mesh.axis_names == ("nodes", "types")
        desc = mesh_description(mesh)
        assert desc["n_devices"] == 8
        assert desc["n_processes"] == 1
        assert desc["types_axis_crosses_hosts"] is False

    def test_sharded_pack_on_hybrid_mesh(self):
        from karpenter_tpu.parallel.multihost import make_hybrid_mesh

        enc = build_inputs()
        inputs, n_slots = pad_inputs(enc)
        base = jax.device_get(pack(jax.device_put(inputs), n_slots=n_slots))
        sh = sharded_pack(inputs, n_slots, make_hybrid_mesh())
        np.testing.assert_array_equal(np.asarray(base.assign),
                                      np.asarray(sh.assign))
        np.testing.assert_array_equal(np.asarray(base.decided),
                                      np.asarray(sh.decided))


class TestShardedConsolidation:
    """Candidate lanes sharded over the mesh (pure data parallelism) must be
    bit-identical to the single-device sweep."""

    def test_lane_sharded_verdicts_bit_identical(self):
        import numpy as np

        from karpenter_tpu.apis import wellknown as wk
        from karpenter_tpu.apis.provisioner import Provisioner
        from karpenter_tpu.models.cluster import ClusterState, StateNode
        from karpenter_tpu.models.pod import make_pod
        from karpenter_tpu.ops.consolidate import (N_SLOTS,
                                                   encode_consolidation,
                                                   run_consolidation)
        from karpenter_tpu.ops.consolidate import _batched_pack_verdicts
        from karpenter_tpu.parallel.sharded import (
            make_lane_mesh, sharded_consolidation_verdicts)
        import jax

        from karpenter_tpu.models.instancetype import Catalog, make_instance_type

        big = make_instance_type("m.2xl", cpu=8, memory="32Gi",
                                 od_price=0.40, spot_price=0.15)
        small = make_instance_type("m.s", cpu=2, memory="8Gi",
                                   od_price=0.09, spot_price=0.04)
        cat = Catalog(types=[big, small])
        cluster = ClusterState()
        for i in range(13):  # deliberately NOT a device multiple (pad path)
            cluster.add_node(StateNode(
                name=f"n-{i:02d}",
                labels={**big.labels_dict(), wk.LABEL_ZONE: f"zone-1{'ab'[i % 2]}",
                        wk.LABEL_CAPACITY_TYPE: "on-demand",
                        wk.LABEL_PROVISIONER: "default"},
                allocatable=big.allocatable_vector(),
                instance_type=big.name, zone=f"zone-1{'ab'[i % 2]}",
                capacity_type="on-demand", price=big.offerings[0].price,
                provisioner_name="default",
                pods=[make_pod(f"p-{i}-{j}", cpu="500m", memory="1Gi",
                               node_name=f"n-{i:02d}") for j in range(i % 3)]))
        prov = Provisioner(name="default", consolidation_enabled=True)
        prov.set_defaults()
        batch = encode_consolidation(cluster, cat, [prov])
        assert batch is not None
        assert batch.inputs.group_feas is None  # rides as table+idx
        assert batch.feas_table.shape[0] >= 2  # all-False row + real rows
        single = np.asarray(jax.device_get(_batched_pack_verdicts(
            jax.device_put(batch.inputs), N_SLOTS,
            feas_table=jax.device_put(batch.feas_table),
            feas_idx=jax.device_put(batch.feas_idx))))
        mesh = make_lane_mesh(8)
        sharded = sharded_consolidation_verdicts(
            batch.inputs, N_SLOTS, mesh,
            feas_table=batch.feas_table, feas_idx=batch.feas_idx)
        assert sharded.shape == single.shape
        assert (sharded == single).all()

        # end-to-end: the chosen action is identical through the mesh path
        a_mesh = run_consolidation(cluster, cat, [prov], mesh=mesh)
        a_single = run_consolidation(cluster, cat, [prov])
        assert (a_mesh is None) == (a_single is None)
        if a_mesh is not None:
            assert (a_mesh.kind, a_mesh.nodes, a_mesh.replacement) == \
                (a_single.kind, a_single.nodes, a_single.replacement)
