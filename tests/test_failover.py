"""Fleet membership & failover plane tests (karpenter_tpu/fleet/
membership.py + failover.py): router sorted-at-insert (zero sorts on the
route hot path, deterministic tie-break), blast-radius property over
1000 tenants, the K-missed-beats and gray-failure detectors with their
recovery gates, monotone epochs into fleetz, client failover through
breakers and the shared budget, bounded hedging, poison-pill quarantine
with its shed DecisionRecord, the fleetz probe backoff, falsifiability
of all four partition-drill invariants, and the drill itself (FakeClock
smoke in tier 1, a real subprocess under the slow marker).
"""

import builtins
import json
import os
import subprocess
import sys

import pytest

from karpenter_tpu.chaos import invariants
from karpenter_tpu.chaos.runner import ChaosRunner
from karpenter_tpu.fleet import (FailoverClient, FailoverExhausted,
                                 FleetRouter, MembershipManager,
                                 QuarantineRing, ReplicaCrashed,
                                 ReplicaTimeout, ReplicaUnavailable,
                                 RequestQuarantined, request_fingerprint)
from karpenter_tpu.fleet import membership
from karpenter_tpu.fleet import router as router_mod
from karpenter_tpu.resilience import RetryBudget
from karpenter_tpu.utils.clock import FakeClock

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# -- router: sorted-at-insert ----------------------------------------------


class TestRouterHotPath:
    def test_route_never_sorts(self, monkeypatch):
        """10k routes, zero sorted() calls: membership mutations sort (at
        insert, via bisect), the per-request path only scans with max."""
        router = FleetRouter([f"replica-{i}" for i in range(8)])
        calls = {"n": 0}
        real_sorted = builtins.sorted

        def counting(*args, **kwargs):
            calls["n"] += 1
            return real_sorted(*args, **kwargs)

        monkeypatch.setattr(builtins, "sorted", counting)
        for i in range(10_000):
            router.route(f"tenant-{i}")
        monkeypatch.undo()
        assert calls["n"] == 0

    def test_replicas_sorted_at_insert(self):
        router = FleetRouter()
        for name in ("r3", "r1", "r9", "r2"):
            router.add_replica(name)
        assert router.replicas == ("r1", "r2", "r3", "r9")
        router.add_replica("r0")
        assert router.replicas == ("r0", "r1", "r2", "r3", "r9")

    def test_duplicate_score_tie_break_is_deterministic(self, monkeypatch):
        """With every score forced equal, the name breaks the tie — the
        same way regardless of insertion order (cryptographic collisions
        are negligible but the contract must not depend on luck)."""
        monkeypatch.setattr(router_mod, "_score", lambda t, r: 7)
        a = FleetRouter(["r1", "r2", "r3"])
        b = FleetRouter(["r3", "r1", "r2"])
        assert a.route("t") == b.route("t") == "r3"
        assert a.ranked("t") == b.ranked("t") == ["r3", "r2", "r1"]

    def test_ranked_head_is_route(self):
        router = FleetRouter([f"replica-{i}" for i in range(5)])
        for i in range(50):
            tenant = f"tenant-{i}"
            ranked = router.ranked(tenant)
            assert ranked[0] == router.route(tenant)
            assert sorted(ranked) == list(router.replicas)


class TestBlastRadius:
    def test_remove_remaps_exactly_the_lost_replicas_tenants(self):
        """1000 tenants / 5 replicas: removing one remaps exactly its own
        tenants (each to its next ranked choice), and rejoin restores the
        assignment bit-identically."""
        replicas = [f"replica-{i}" for i in range(5)]
        tenants = [f"tenant-{i:04d}" for i in range(1000)]
        router = FleetRouter(replicas)
        before = router.assignment(tenants)
        next_choice = {t: router.ranked(t)[1] for t in tenants}

        lost = replicas[2]
        router.remove_replica(lost)
        after = router.assignment(tenants)
        moved = {t for t in tenants if before[t] != after[t]}
        assert moved == {t for t in tenants if before[t] == lost}
        # a sane spread: ~1/5 of tenants lived there
        assert 100 < len(moved) < 300
        for t in moved:
            assert after[t] == next_choice[t]
        assert not invariants.check_remap_blast_radius(
            before, after, {lost})

        router.add_replica(lost)
        assert router.assignment(tenants) == before


# -- membership: detectors, epochs, events ---------------------------------


class _Probe:
    """Scriptable health surface: latency-returning success or raise."""

    def __init__(self, latency=0.001):
        self.latency = latency
        self.fail = False

    def __call__(self):
        if self.fail:
            raise RuntimeError("probe: connection refused")
        return self.latency


def make_manager(n=3, **kw):
    clock = FakeClock()
    router = FleetRouter()
    manager = MembershipManager(router, clock=clock, **kw)
    probes = {}
    for i in range(n):
        name = f"replica-{i}"
        probes[name] = _Probe()
        manager.register(name, probes[name])
    return manager, router, probes, clock


class TestMembership:
    def test_join_is_evidence_gated(self):
        manager, router, _, _ = make_manager(3)
        assert router.replicas == ()  # registered, never probed: no member
        events = manager.tick()
        assert events == [] and router.replicas == ()
        events = manager.tick()  # RECOVERY_PROBES=2 consecutive successes
        assert sorted(e["event"] for e in events) == ["ReplicaJoined"] * 3
        assert len(router.replicas) == 3
        assert manager.members() == sorted(router.replicas)

    def test_k_missed_beats_ejects_then_recovery_readmits(self):
        manager, router, probes, _ = make_manager(3)
        for _ in range(2):
            manager.tick()
        probes["replica-1"].fail = True
        ejections = []
        for _ in range(MembershipManager.MISSED_BEATS_K):
            ejections += [e for e in manager.tick()
                          if e["event"] == "ReplicaEjected"]
        assert [e["replica"] for e in ejections] == ["replica-1"]
        assert ejections[0]["reason"] == "k-missed-beats"
        assert "replica-1" not in router.replicas
        # one beat short must NOT have ejected: exactly K, not K-1
        snap = manager.snapshot()
        assert snap["replicas"]["replica-1"]["member"] is False

        probes["replica-1"].fail = False
        recovered = []
        for _ in range(MembershipManager.RECOVERY_PROBES):
            recovered += [e for e in manager.tick()
                          if e["event"] == "ReplicaRecovered"]
        assert [e["replica"] for e in recovered] == ["replica-1"]
        assert "replica-1" in router.replicas

    def test_gray_failure_ejected_and_gated_on_recovery(self):
        manager, router, probes, _ = make_manager(3)
        for _ in range(MembershipManager.GRAY_MIN_SAMPLES + 2):
            manager.tick()  # fill every latency window with fast beats
        probes["replica-2"].latency = 0.05  # ~50x the peers
        ejections = []
        for _ in range(4):
            ejections += [e for e in manager.tick()
                          if e.get("reason") == "gray-failure"]
        assert [e["replica"] for e in ejections] == ["replica-2"]
        assert "replica-2" not in router.replicas

        # still slow: probe SUCCESSES must not re-admit it (no flapping)
        for _ in range(6):
            assert not [e for e in manager.tick()
                        if e["event"] == "ReplicaRecovered"]
        assert "replica-2" not in router.replicas

        # healed: back under the gray bar, recovery proceeds
        probes["replica-2"].latency = 0.001
        recovered = []
        for _ in range(MembershipManager.RECOVERY_PROBES + 1):
            recovered += [e for e in manager.tick()
                          if e["event"] == "ReplicaRecovered"]
        assert [e["replica"] for e in recovered] == ["replica-2"]

    def test_gray_needs_a_peer_baseline(self):
        """A fleet of one has no 'slow': the gray detector never fires
        without at least one peer carrying samples."""
        manager, router, probes, _ = make_manager(1)
        probes["replica-0"].latency = 10.0
        for _ in range(MembershipManager.GRAY_MIN_SAMPLES + 4):
            events = manager.tick()
            assert not [e for e in events if e.get("reason") ==
                        "gray-failure"]
        assert "replica-0" in router.replicas

    def test_epochs_are_monotone_and_observed_by_fleetz(self):
        from karpenter_tpu.introspect.fleetview import FleetView

        manager, router, probes, _ = make_manager(3)
        view = FleetView(name="t")
        view.set_epoch_source(manager.epoch)
        epochs = [manager.epoch()]
        for _ in range(2):
            manager.tick()
            epochs.append(manager.epoch())
        assert view.fleetz()["membership_epoch"] == manager.epoch() == 3
        probes["replica-0"].fail = True
        for _ in range(MembershipManager.MISSED_BEATS_K):
            manager.tick()
            epochs.append(manager.epoch())
        probes["replica-0"].fail = False
        for _ in range(MembershipManager.RECOVERY_PROBES):
            manager.tick()
            epochs.append(manager.epoch())
        assert not invariants.check_epoch_monotone(epochs)
        assert epochs[-1] == 5  # 3 joins + 1 eject + 1 recover
        assert view.fleetz()["membership_epoch"] == 5

    def test_flight_trigger_fires_at_the_ejection_edge(self):
        triggers = []
        clock = FakeClock()
        router = FleetRouter()
        manager = MembershipManager(
            router, clock=clock,
            flight_trigger=lambda reason, detail:
                triggers.append((reason, detail)))
        probe = _Probe()
        manager.register("replica-0", probe)
        for _ in range(2):
            manager.tick()
        assert triggers == []  # joins are not forensic events
        probe.fail = True
        for _ in range(MembershipManager.MISSED_BEATS_K):
            manager.tick()
        assert len(triggers) == 1
        assert triggers[0][0] == "fleet_replica_ejected"
        assert "k-missed-beats" in triggers[0][1]

    def test_disabled_plane_is_a_strict_noop(self):
        router = FleetRouter([f"replica-{i}" for i in range(3)])
        tenants = [f"tenant-{i}" for i in range(64)]
        before_assign = router.assignment(tenants)
        with membership.disabled():
            before = membership.activity()
            manager = MembershipManager(router, clock=FakeClock())
            probe = _Probe()
            probe.fail = True  # a dead probe that must never be consulted
            manager.register("replica-0", probe)
            events = []
            for _ in range(6):
                events.extend(manager.tick())
            after = membership.activity()
        assert events == []
        assert after == before
        assert router.assignment(tenants) == before_assign
        assert manager.epoch() == 0
        assert not invariants.check_membership_noop(
            {"enabled": False, "before": before, "after": after})


# -- client failover --------------------------------------------------------


class _Script:
    """Scriptable transport for one replica: raises the scripted failure
    class, else serves. Records (replica, timeout_s) per attempt."""

    def __init__(self, name, log):
        self.name = name
        self.log = log
        self.failure = None   # exception CLASS or None

    def __call__(self, tenant_id, request, timeout_s):
        self.log.append((self.name, timeout_s))
        if self.failure is not None:
            raise self.failure(self.name, "scripted")
        return {"replica": self.name}


def make_client(n=3, **kw):
    names = [f"replica-{i}" for i in range(n)]
    router = FleetRouter(names)
    log = []
    scripts = {name: _Script(name, log) for name in names}
    client = FailoverClient(router, dict(scripts), clock=FakeClock(), **kw)
    return client, router, scripts, log


class TestFailoverClient:
    def test_reroutes_to_next_ranked_on_unavailable(self):
        client, router, scripts, log = make_client()
        ranked = router.ranked("tenant-a")
        scripts[ranked[0]].failure = ReplicaUnavailable
        out = client.solve("tenant-a", {"pods": 1})
        assert out["replica"] == ranked[1]
        assert [r for r, _ in log] == ranked[:2]
        # a refused connection indicts the replica, never the request
        assert client.quarantine.victims(
            request_fingerprint({"pods": 1})) == []

    def test_hedge_horizon_bounds_the_home_attempt(self):
        client, router, scripts, log = make_client()
        ranked = router.ranked("tenant-a")
        scripts[ranked[0]].failure = ReplicaTimeout
        out = client.solve("tenant-a", {"pods": 2}, timeout_s=5.0)
        assert out["replica"] == ranked[1]
        # home ran under the hedge horizon, the hedge under the caller's
        # full deadline
        assert log[0] == (ranked[0], client.hedge_horizon_s)
        assert log[1] == (ranked[1], 5.0)

    def test_two_timeout_victims_quarantine_the_request(self):
        client, router, scripts, _ = make_client()
        for s in scripts.values():
            s.failure = ReplicaTimeout
        with pytest.raises(RequestQuarantined):
            client.solve("tenant-a", {"pods": 3}, timeout_s=5.0)
        fp = request_fingerprint({"pods": 3})
        assert client.quarantine.victims(fp) == sorted(
            router.ranked("tenant-a")[:2])

    def test_poison_quarantined_after_exactly_two_crashes(self):
        client, router, scripts, log = make_client()
        for s in scripts.values():
            s.failure = ReplicaCrashed
        request = {"poison": True}
        with pytest.raises(RequestQuarantined):
            client.solve("tenant-a", request)
        # exactly two victims, the third candidate never contacted
        assert len(log) == 2
        fp = request_fingerprint(request)
        assert client.quarantine.is_quarantined(fp)
        assert len(client.quarantine.victims(fp)) == 2
        # resubmission sheds at the door: zero transport calls
        with pytest.raises(RequestQuarantined):
            client.solve("tenant-b", request)
        assert len(log) == 2

    def test_quarantine_shed_lands_as_a_decision_record(self):
        from karpenter_tpu import explain

        client, _, scripts, _ = make_client()
        for s in scripts.values():
            s.failure = ReplicaCrashed
        prev = explain.set_enabled(True)
        try:
            before = explain.activity()["sheds_total"]
            with pytest.raises(RequestQuarantined):
                client.solve("tenant-a", {"poison": "yes"})
            assert explain.activity()["sheds_total"] == before + 1
            rec = explain.DECISIONS.records(kind="shed")[-1]
            assert rec["reason"] == "poison-quarantine"
            assert rec["where"] == "failover"
            assert rec["reason"] in explain.SHED_REASONS
        finally:
            explain.set_enabled(prev)

    def test_breaker_fails_known_dead_replica_fast(self):
        client, router, scripts, log = make_client()
        ranked = router.ranked("tenant-a")
        scripts[ranked[0]].failure = ReplicaUnavailable
        for _ in range(FailoverClient.BREAKER_THRESHOLD):
            client.solve("tenant-a", {"pods": 4})
        del log[:]
        out = client.solve("tenant-a", {"pods": 4})
        assert out["replica"] == ranked[1]
        assert [r for r, _ in log] == [ranked[1]]  # home skipped, not dialed

    def test_budget_exhaustion_gives_up_not_retries(self):
        client, router, scripts, log = make_client(
            budget=RetryBudget(capacity=1.0, refill_per_success=0.0))
        for s in scripts.values():
            s.failure = ReplicaUnavailable
        with pytest.raises(FailoverExhausted) as e:
            client.solve("tenant-a", {"pods": 5})
        assert "budget" in str(e.value)
        assert len(log) == 2  # home + the single budgeted reroute

    def test_cold_remap_counts_loss_and_resyncs(self):
        remaps = []
        client, router, scripts, _ = make_client(
            on_remap=lambda tenant, replica: remaps.append(
                (tenant, replica)))
        ranked = router.ranked("tenant-a")
        client.solve("tenant-a", {"pods": 6})
        assert client.warm_state_losses == 0  # first home is not a remap
        scripts[ranked[0]].failure = ReplicaUnavailable
        client.solve("tenant-a", {"pods": 6})
        assert client.warm_state_losses == 1
        assert remaps == [("tenant-a", ranked[1])]
        scripts[ranked[0]].failure = None
        client.solve("tenant-a", {"pods": 6})  # comes home: another remap
        assert client.warm_state_losses == 2
        assert remaps[-1] == ("tenant-a", ranked[0])

    def test_no_sleep_anywhere_in_the_failover_loop(self):
        """Failover re-routes, it never waits: the retry policies are
        built with a no-op sleep so FakeClock tests can't deadlock and
        the no-adhoc-retry discipline holds by construction."""
        client, _, scripts, _ = make_client()
        for s in scripts.values():
            s.failure = ReplicaUnavailable
        t0 = client.clock.now()
        with pytest.raises(FailoverExhausted):
            client.solve("tenant-a", {"pods": 7})
        assert client.clock.now() == t0

    def test_evidence_is_deterministic_shape(self):
        client, _, scripts, _ = make_client()
        client.solve("tenant-a", {"pods": 8})
        ev = client.evidence()
        assert set(ev) == {"budget", "breakers", "warm_state_losses",
                           "quarantine"}
        assert ev["quarantine"]["victim_limit"] == 2


class TestQuarantineRing:
    def test_trips_exactly_once_on_the_second_distinct_victim(self):
        ring = QuarantineRing()
        assert ring.note_victim("fp", "r1") is False
        assert ring.note_victim("fp", "r1") is False  # same replica: no-op
        assert ring.note_victim("fp", "r2") is True   # the trip, exactly once
        assert ring.note_victim("fp", "r3") is False  # already quarantined
        assert ring.is_quarantined("fp")

    def test_capacity_bounds_the_ring(self):
        ring = QuarantineRing(capacity=4)
        for i in range(10):
            ring.note_victim(f"fp{i}", "r1")
        assert len(ring.evidence()["victims"]) == 4


# -- fleetz probe backoff ----------------------------------------------------


class TestFleetviewBackoff:
    def test_dead_replica_probe_is_suppressed_then_retried(self):
        from karpenter_tpu.introspect.fleetview import (
            PROBE_BACKOFF_S, PROBE_FAILURE_THRESHOLD, FleetView,
            LocalReplica)

        clock = FakeClock()
        view = FleetView(name="t", clock=clock)
        state = {"up": False}

        def statusz():
            if not state["up"]:
                raise ConnectionError("refused")
            return {"schema": 1, "version": "t", "ts": clock.now()}

        view.add_replica(LocalReplica("replica-0", statusz=statusz))
        for i in range(PROBE_FAILURE_THRESHOLD):
            row = view.fleetz()["replicas"]["replica-0"]
            assert row["healthy"] is False
            assert row["consecutive_failures"] == i + 1
            assert "probe_suppressed" not in row
        # threshold reached: the fetch itself is now suppressed
        row = view.fleetz()["replicas"]["replica-0"]
        assert row["probe_suppressed"] is True
        assert row["consecutive_failures"] == PROBE_FAILURE_THRESHOLD
        # after the backoff window one probe goes through; the replica is
        # back, so the row heals and the failure streak resets
        state["up"] = True
        clock.step(PROBE_BACKOFF_S + 1.0)
        row = view.fleetz()["replicas"]["replica-0"]
        assert row["healthy"] is True
        assert row["consecutive_failures"] == 0

    def test_healthy_replica_rows_carry_zero_streak(self):
        from karpenter_tpu.introspect.fleetview import (FleetView,
                                                        LocalReplica)

        view = FleetView(name="t", clock=FakeClock())
        view.add_replica(LocalReplica(
            "replica-0", statusz=lambda: {"schema": 1}))
        row = view.fleetz()["replicas"]["replica-0"]
        assert row["consecutive_failures"] == 0


# -- invariant falsifiability ------------------------------------------------


class TestInvariantFalsifiability:
    """Each partition-drill invariant must actually reject the failure it
    exists for — an invariant that cannot fail proves nothing."""

    def test_remap_blast_radius(self):
        before = {"t1": "r1", "t2": "r2", "t3": "r1"}
        ok = {"t1": "r1", "t2": "r3", "t3": "r1"}
        assert not invariants.check_remap_blast_radius(before, ok, {"r2"})
        still_lost = {"t1": "r1", "t2": "r2", "t3": "r1"}
        assert invariants.check_remap_blast_radius(
            before, still_lost, {"r2"})
        over_radius = {"t1": "r3", "t2": "r3", "t3": "r1"}
        assert invariants.check_remap_blast_radius(
            before, over_radius, {"r2"})
        vanished = {"t1": "r1", "t3": "r1"}
        assert invariants.check_remap_blast_radius(
            before, vanished, {"r2"})
        # the rejoin check: with nothing lost, ANY movement violates
        assert invariants.check_remap_blast_radius(before, ok, set())

    def test_completes_or_sheds(self):
        good = [{"tenant": "a", "outcome": "served"},
                {"tenant": "b", "outcome": "shed", "reason": "deadline"},
                {"tenant": "c", "outcome": "shed",
                 "reason": "poison-quarantine"}]
        assert not invariants.check_completes_or_sheds(good)
        assert invariants.check_completes_or_sheds(
            [{"tenant": "a", "outcome": "shed", "reason": "cosmic-rays"}])
        assert invariants.check_completes_or_sheds(
            [{"tenant": "a", "outcome": "error", "detail": "boom"}])
        assert invariants.check_completes_or_sheds(
            [{"tenant": "a", "outcome": None}])

    def test_quarantine_cascade(self):
        assert not invariants.check_quarantine_cascade(
            {"fp1": ["r1", "r2"], "fp2": ["r3"]})
        bad = invariants.check_quarantine_cascade(
            {"fp1": ["r1", "r2", "r3"]})
        assert bad and "fp1" in bad[0].message

    def test_epoch_monotone(self):
        assert not invariants.check_epoch_monotone([0, 0, 1, 2, 2, 5])
        bad = invariants.check_epoch_monotone([0, 2, 1, 3])
        assert bad and "regressed" in bad[0].message

    def test_membership_noop(self):
        frozen = {"probes_total": 4, "transitions_total": 1}
        assert not invariants.check_membership_noop(
            {"enabled": False, "before": frozen, "after": dict(frozen)})
        moved = dict(frozen, probes_total=5)
        assert invariants.check_membership_noop(
            {"enabled": False, "before": frozen, "after": moved})
        # plane on: not this drill's concern
        assert not invariants.check_membership_noop(
            {"enabled": True, "before": frozen, "after": moved})


# -- the drill ---------------------------------------------------------------


class TestPartitionDrill:
    def test_fakeclock_drill_passes_at_seed_zero(self):
        artifact = ChaosRunner(seed=0, partition=True).run_partition_drill()
        assert artifact["passed"], json.dumps(
            [v for s in artifact["scenarios"] for v in s["violations"]],
            indent=2)
        drill = artifact["scenarios"][0]
        # the headline physics: ~1/R remap, recovery bounded by the
        # detectors, the poison stopped at two victims
        assert abs(drill["remap_fraction"] - 0.2) < 0.15
        assert max(drill["recovery_to_green_cycles"].values()) <= \
            MembershipManager.MISSED_BEATS_K + 1
        assert len(drill["quarantine"]["quarantined"]) == 1
        assert drill["totals"]["shed_quarantine"] > 0
        assert drill["ejection_flight_triggers"] >= 4
        noop = artifact["scenarios"][1]
        assert noop["passed"]
        assert all(v == 0 for v in noop["membership"]["deltas"].values())

    def test_drill_is_replay_identical(self):
        a = ChaosRunner(seed=3, partition=True).run_partition_drill()
        b = ChaosRunner(seed=3, partition=True).run_partition_drill()
        for art in (a, b):
            art.pop("duration_s")
            art.pop("bundles")
        assert a == b

    def test_gray_ejected_before_p99_stays_doubled(self):
        drill = ChaosRunner(
            seed=0, partition=True).run_partition_scenario(0)
        gray = [p for p in drill["phases"] if p["phase"] == "gray"][0]
        assert any(e.get("reason") == "gray-failure"
                   for e in gray["events"])
        # once ejected, per-cycle p99 returns to baseline and stays there
        assert gray["cycle_p99"][-1] < 2.0 * drill["baseline_p99_s"]
        assert drill["gray_elevated_cycles"] <= ChaosRunner.GRAY_EJECT_BOUND


_DRILL_WORKER = r'''
import json, os, sys
sys.path.insert(0, os.environ["KT_REPO"])
from karpenter_tpu.chaos.runner import ChaosRunner
artifact = ChaosRunner(seed=7, partition=True).run_partition_drill()
drill = artifact["scenarios"][0]
print("WORKER_OK " + json.dumps({
    "passed": artifact["passed"],
    "remap_fraction": drill["remap_fraction"],
    "epoch": drill["membership_epoch"],
    "quarantined": len(drill["quarantine"]["quarantined"]),
}), flush=True)
'''


@pytest.mark.slow
def test_partition_drill_in_real_subprocess():
    """The drill run as a genuinely separate OS process: proves the
    plane carries no hidden dependence on this process's global plane
    switches or metric state. Spawn hygiene comes from the SAME harness
    the real-replica fleet drill uses (fleet/replica.py
    subprocess_env), so this test and benchmarks/fleet_drill.py can
    never drift apart on backend/device-count/pool-pointer handling."""
    from karpenter_tpu.fleet.replica import subprocess_env

    env = subprocess_env()
    env["KT_REPO"] = REPO
    proc = subprocess.Popen([sys.executable, "-c", _DRILL_WORKER],
                            stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT,
                            env=env, cwd=REPO, text=True)
    out, _ = proc.communicate(timeout=240)
    assert proc.returncode == 0, out
    payload = [ln for ln in out.splitlines()
               if ln.startswith("WORKER_OK ")]
    assert payload, out
    result = json.loads(payload[0][len("WORKER_OK "):])
    assert result["passed"] is True
    assert abs(result["remap_fraction"] - 0.2) < 0.15
    assert result["quarantined"] == 1
