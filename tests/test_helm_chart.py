"""Chart parity: `helmless render` (the in-repo `helm template` subset —
the image has no helm binary) at DEFAULT values must reproduce the static
manifests in deploy/ byte-for-byte, and overrides must actually steer the
render (VERDICT r3 ask #7; reference analogue: charts/karpenter with
values.yaml:134-142, plus the split charts/karpenter-crd)."""

import os
import subprocess
import sys

import pytest
import yaml

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "hack"))

from helmless import Renderer, _parse_set  # noqa: E402

CHART = os.path.join(REPO, "charts", "karpenter-tpu")
CRD_CHART = os.path.join(REPO, "charts", "karpenter-tpu-crd")
DEPLOY = os.path.join(REPO, "deploy", "karpenter-tpu")


def test_default_render_matches_static_manifests_byte_for_byte():
    docs = Renderer(CHART).render()
    static = sorted(f for f in os.listdir(DEPLOY) if f.endswith(".yaml"))
    assert sorted(docs) == static
    for name in static:
        with open(os.path.join(DEPLOY, name)) as f:
            want = f.read()
        assert docs[name] == want, f"{name} render drifted from deploy/"


def test_crd_chart_matches_deploy_crds():
    docs = Renderer(CRD_CHART).render()
    crd_dir = os.path.join(REPO, "deploy", "crds")
    static = sorted(os.listdir(crd_dir))
    assert sorted(docs) == static
    for name in static:
        with open(os.path.join(crd_dir, name)) as f:
            assert docs[name] == f.read()


def test_every_render_is_valid_yaml_with_expected_kinds():
    docs = Renderer(CHART).render()
    kinds = set()
    for body in docs.values():
        for doc in yaml.safe_load_all(body):
            assert doc and doc.get("kind")
            kinds.add(doc["kind"])
    assert {"Deployment", "Service", "ConfigMap", "PodDisruptionBudget",
            "ServiceAccount", "ClusterRole", "ClusterRoleBinding",
            "ValidatingWebhookConfiguration",
            "MutatingWebhookConfiguration", "ServiceMonitor"} <= kinds


def test_overrides_steer_the_render():
    docs = Renderer(CHART, _parse_set([
        "replicas=3", "leaderElect=false", "controller.metricsPort=9090",
        "solver.port=6000", "serviceMonitor.enabled=false",
    ])).render()
    dep = yaml.safe_load(docs["deployment.yaml"])
    assert dep["spec"]["replicas"] == 3
    ctrl = dep["spec"]["template"]["spec"]["containers"][0]
    assert "--leader-elect" not in ctrl["args"]
    assert "127.0.0.1:6000" in ctrl["args"]
    svc = yaml.safe_load(docs["service.yaml"])
    assert svc["spec"]["ports"][0]["port"] == 9090
    assert "servicemonitor.yaml" not in docs  # empty renders are dropped
    cm = yaml.safe_load(docs["settings.yaml"])
    assert cm["data"]["solverEndpoint"] == "127.0.0.1:6000"


def test_solver_readback_value_renders_flag_only_when_non_default():
    # default ("get"): no --readback arg, keeping the deploy/ byte parity
    default = yaml.safe_load(Renderer(CHART).render()["deployment.yaml"])
    solver_args = default["spec"]["template"]["spec"]["containers"][1]["args"]
    assert "--readback" not in solver_args
    # callback transport (relay escape hatch, docs/designs/solver-boundary.md)
    docs = Renderer(CHART, _parse_set(["solver.readback=callback"])).render()
    dep = yaml.safe_load(docs["deployment.yaml"])
    solver_args = dep["spec"]["template"]["spec"]["containers"][1]["args"]
    assert solver_args[-2:] == ["--readback", "callback"]


def test_namespace_and_fullname_flow_through():
    docs = Renderer(CHART, {"fullnameOverride": "kp"},
                    namespace="kube-system").render()
    dep = yaml.safe_load(docs["deployment.yaml"])
    assert dep["metadata"]["name"] == "kp"
    assert dep["metadata"]["namespace"] == "kube-system"
    wh = list(yaml.safe_load_all(docs["webhooks.yaml"]))
    assert wh[0]["webhooks"][0]["clientConfig"]["service"]["namespace"] == \
        "kube-system"


def test_cli_render_runs():
    r = subprocess.run([sys.executable, os.path.join(REPO, "hack", "helmless.py"),
                        "render", CHART, "--set", "replicas=1"],
                       capture_output=True, text=True)
    assert r.returncode == 0, r.stderr
    assert "kind: Deployment" in r.stdout
