"""Incremental solving plane: decision identity, escapes, resident parity.

The plane's contract is absolute: enabled, every solve must produce the
SAME decisions a full solve would (the subproblem is a proof-carrying
optimization, not an approximation); disabled, it must be strictly
inert. Tests here pin both directions:

  * N-cycle property test: seeded add/bind/delete/mark churn streams,
    incremental solve fingerprint == full solve fingerprint every cycle,
    with real incremental (non-escape) cycles exercised
  * every escape-hatch reason trips exactly when its condition holds,
    and the escaped solve still equals the full solve (trivially)
  * the merge-back audit catches a corrupted subproblem solve and falls
    back to the full result
  * ResidentMasks / ResidentCandidates stay bit-identical to the fresh
    folds they cache, across churn, spec arrival, and PDB-set changes
  * empty/expired row sets match the deprovisioning sweeps' masks
  * KARPENTER_TPU_INCREMENTAL=0 means zero counter movement
  * the deletion log reports completeness honestly past its horizon
  * HbmLedger.set_resident REPLACE semantics + static-class guard

Property-style tests use seeded random.Random loops (hypothesis is not
in the image).
"""

import dataclasses
import random

import numpy as np
import pytest

from karpenter_tpu import incremental
from karpenter_tpu.apis import wellknown as wk
from karpenter_tpu.apis.provisioner import Provisioner
from karpenter_tpu.incremental import (DeltaTracker, IncrementalSolver,
                                       ResidentCandidates, ResidentMasks,
                                       empty_node_rows, expired_node_rows,
                                       extract_subproblem, solve_fingerprint)
from karpenter_tpu.incremental.extract import (ESCAPE_AUDIT_DIVERGENCE,
                                               ESCAPE_COLD_START,
                                               ESCAPE_DELETION_LOG_GAP,
                                               ESCAPE_DIRTY_THRESHOLD,
                                               ESCAPE_ENTANGLED_GROUP)
from karpenter_tpu.models.cluster import (ClusterState, PodDisruptionBudget,
                                          StateNode)
from karpenter_tpu.models.encode import existing_fit_vector
from karpenter_tpu.models.instancetype import Catalog, make_instance_type
from karpenter_tpu.models.pod import TopologySpreadConstraint, make_pod
from karpenter_tpu.models.requirements import OP_IN, Requirements
from karpenter_tpu.solver.core import TPUSolver


def _catalog():
    return Catalog(types=[
        make_instance_type("m.large", cpu=4, memory="16Gi",
                           od_price=0.20, spot_price=0.07),
        make_instance_type("m.xlarge", cpu=16, memory="64Gi",
                           od_price=0.80, spot_price=0.28),
    ])


def _prov(name="default"):
    p = Provisioner(name=name, requirements=Requirements.of(
        (wk.LABEL_CAPACITY_TYPE, OP_IN, ["spot", "on-demand"])))
    p.set_defaults()
    return p


def _alloc(cpu_m=4000, mem_mi=16384, pods=110):
    return wk.capacity_vector({wk.RESOURCE_CPU: cpu_m,
                               wk.RESOURCE_MEMORY: mem_mi * 2**20,
                               wk.RESOURCE_PODS: pods})


def _node(name, i=0, now=1_000_000.0):
    return StateNode(
        name=name,
        labels={wk.LABEL_ZONE: f"z-{'abc'[i % 3]}",
                wk.LABEL_CAPACITY_TYPE: "on-demand",
                wk.LABEL_INSTANCE_TYPE: "m.large",
                "team": f"t{i % 5}"},
        allocatable=_alloc(),
        provisioner_name="default",
        created_ts=now - (i % 1000),
        pods=[make_pod(f"{name}-p{j}", cpu="250m", memory="512Mi",
                       node_name=name, owner_kind="ReplicaSet")
              for j in range(i % 4)])


def _cluster(n=24):
    cluster = ClusterState()
    for k in range(n):
        cluster.add_node(_node(f"n-{k:03d}", k))
    return cluster


def _base(catalog, provisioners):
    solver = TPUSolver(catalog, provisioners)

    def run(pods, existing):
        return solver.solve(list(pods), existing=existing), "tpu"

    return run


def _pending(rng, cycle, count=3):
    return [make_pod(f"pend-{cycle}-{j}",
                     cpu=f"{rng.randint(1, 6) * 250}m",
                     memory=f"{rng.randint(1, 8) * 256}Mi",
                     owner_kind="ReplicaSet")
            for j in range(count)]


def _churn(rng, cluster, names, cycle, events=6):
    for j in range(events):
        op = rng.random()
        name = names[rng.randrange(len(names))]
        node = cluster.nodes[name]
        if op < 0.4:
            cluster.bind_pod(name, make_pod(
                f"churn-{cycle}-{j}", cpu="250m", memory="256Mi",
                node_name=name, owner_kind="ReplicaSet"))
        elif op < 0.65:
            if node.pods:
                node.pods.pop(rng.randrange(len(node.pods)))
        elif op < 0.8:
            node.labels["team"] = f"t{rng.randrange(5)}"
        elif op < 0.9:
            node.marked_for_deletion = not node.marked_for_deletion
        else:
            idx = names.index(name)
            cluster.delete_node(name)
            names[idx] = f"n-r{cycle}-{j}"
            cluster.add_node(_node(names[idx], rng.randrange(1000)))


# -- the tentpole property: decision identity under churn ----------------------


@pytest.mark.parametrize("seed", [0, 7, 20260806])
def test_incremental_solve_decision_identity(seed):
    """N cycles of seeded churn: the incremental solve's fingerprint must
    equal a from-scratch full solve's, every cycle, and the run must
    contain genuine incremental (non-escape) cycles for the claim to have
    teeth. The oracle merge-back audit runs live throughout."""
    rng = random.Random(seed)
    catalog, provisioners = _catalog(), [_prov()]
    cluster = _cluster(24)
    names = [f"n-{k:03d}" for k in range(24)]
    inc = IncrementalSolver(cluster)
    base = _base(catalog, provisioners)
    before = incremental.activity()

    incremental_cycles = 0
    for cycle in range(12):
        _churn(rng, cluster, names, cycle)
        pods = _pending(rng, cycle)
        full = cluster.existing_columns()
        want, _ = base(pods, full)
        got, _ = inc.solve(pods, full, base, catalog=catalog,
                           provisioners=provisioners)
        assert solve_fingerprint(got) == solve_fingerprint(want), (
            f"cycle {cycle}: incremental diverged from full solve "
            f"(mode={inc.last and inc.last.get('mode')})")
        if inc.last["mode"] == "incremental":
            incremental_cycles += 1
            assert len(full) >= inc.last["sub_nodes"]

    after = incremental.activity()
    assert incremental_cycles >= 3, "escape hatch swallowed the whole run"
    assert after["audit_divergences"] == before["audit_divergences"]


def test_incremental_subproblem_shrinks():
    """At steady state with small churn the subproblem must be strictly
    smaller than the fleet — otherwise the plane optimizes nothing."""
    catalog, provisioners = _catalog(), [_prov()]
    cluster = _cluster(40)
    inc = IncrementalSolver(cluster)
    base = _base(catalog, provisioners)
    pods = [make_pod("pend-0", cpu="250m", memory="256Mi",
                     owner_kind="ReplicaSet")]
    inc.solve(pods, cluster.existing_columns(), base)  # cold start
    cluster.bind_pod("n-000", make_pod("b0", cpu="100m", memory="128Mi",
                                       node_name="n-000",
                                       owner_kind="ReplicaSet"))
    result, _ = inc.solve(pods, cluster.existing_columns(), base,
                          catalog=catalog, provisioners=provisioners)
    assert inc.last["mode"] == "incremental"
    assert inc.last["sub_nodes"] < inc.last["full_nodes"]
    assert inc.last["resident_bytes"] > 0


# -- escape hatch reasons ------------------------------------------------------


def test_escape_cold_start_then_warm():
    catalog, provisioners = _catalog(), [_prov()]
    cluster = _cluster(8)
    inc = IncrementalSolver(cluster)
    base = _base(catalog, provisioners)
    pods = _pending(random.Random(1), 0)
    inc.solve(pods, cluster.existing_columns(), base)
    assert inc.last == {
        "mode": "full", "escape": ESCAPE_COLD_START, "dirty_nodes": 0,
        "full_nodes": 8, "kind": "tpu"}
    inc.solve(pods, cluster.existing_columns(), base)
    assert inc.last["mode"] == "incremental"


def test_escape_dirty_threshold():
    cluster = _cluster(8)
    tracker = DeltaTracker(cluster)
    tracker.advance()
    for k in range(6):  # dirty 6/8 = 0.75 > 0.25 default
        cluster.nodes[f"n-{k:03d}"].labels["team"] = "tX"
    from karpenter_tpu.models.pod import group_pods

    groups = group_pods(_pending(random.Random(2), 0))
    sub = extract_subproblem(cluster, groups, cluster.existing_columns(),
                             tracker)
    assert sub.escape == ESCAPE_DIRTY_THRESHOLD
    # an explicit generous threshold lets the same dirty set through
    sub2 = extract_subproblem(cluster, groups, cluster.existing_columns(),
                              tracker, threshold=0.9)
    assert sub2.escape is None


def test_escape_entangled_group():
    cluster = _cluster(8)
    tracker = DeltaTracker(cluster)
    tracker.advance()
    from karpenter_tpu.models.pod import group_pods

    spread = make_pod("spread-0", cpu="250m", memory="256Mi",
                      owner_kind="ReplicaSet",
                      topology=(TopologySpreadConstraint(
                          topology_key=wk.LABEL_ZONE, max_skew=1),))
    sub = extract_subproblem(cluster, group_pods([spread]),
                             cluster.existing_columns(), tracker)
    assert sub.escape == ESCAPE_ENTANGLED_GROUP


def test_escape_deletion_log_gap():
    cluster = _cluster(8)
    tracker = DeltaTracker(cluster)
    tracker.advance()
    # push the log horizon past the cursor: the tracker can no longer
    # prove which rows vanished, so the gate must refuse the delta path
    cluster._deletion_floor = cluster.seq + 10
    cluster.nodes["n-000"].labels["team"] = "tX"
    from karpenter_tpu.models.pod import group_pods

    groups = group_pods(_pending(random.Random(3), 0))
    sub = extract_subproblem(cluster, groups, cluster.existing_columns(),
                             tracker)
    assert sub.escape == ESCAPE_DELETION_LOG_GAP


def test_audit_divergence_falls_back_to_full():
    """A base solve that corrupts subproblem results (only) must be caught
    by the oracle audit; the returned result is the FULL solve's."""
    catalog, provisioners = _catalog(), [_prov()]
    cluster = _cluster(10)
    inc = IncrementalSolver(cluster)
    honest = _base(catalog, provisioners)
    pods = _pending(random.Random(4), 0)
    inc.solve(pods, cluster.existing_columns(), honest)  # warm the cursor
    cluster.bind_pod("n-001", make_pod("b1", cpu="100m", memory="128Mi",
                                       node_name="n-001",
                                       owner_kind="ReplicaSet"))
    full = cluster.existing_columns()

    class _Corrupt:
        def __init__(self, res):
            self._res = res

        def decisions(self):
            return ["bogus.node"]

        @property
        def existing_counts(self):
            return self._res.existing_counts

        def unschedulable_count(self):
            return self._res.unschedulable_count()

    def lying(ps, ex):
        res, kind = honest(ps, ex)
        if len(ex) < len(full):  # corrupt ONLY the subproblem solve
            return _Corrupt(res), kind
        return res, kind

    before = incremental.activity()
    got, _ = inc.solve(pods, full, lying, catalog=catalog,
                       provisioners=provisioners)
    after = incremental.activity()
    want, _ = honest(pods, full)
    assert inc.last["mode"] == "full"
    assert inc.last["escape"] == ESCAPE_AUDIT_DIVERGENCE
    assert after["audit_divergences"] == before["audit_divergences"] + 1
    assert solve_fingerprint(got) == solve_fingerprint(want)


# -- resident structures -------------------------------------------------------


def test_resident_masks_parity_under_churn():
    rng = random.Random(11)
    cluster = _cluster(20)
    names = [f"n-{k:03d}" for k in range(20)]
    specs = [
        make_pod("a", cpu="250m", memory="256Mi",
                 node_selector={"team": "t1"}),
        make_pod("b", cpu="500m", memory="512Mi",
                 node_selector={wk.LABEL_ZONE: "z-a"}),
        make_pod("c", cpu="1", memory="1Gi"),
    ]
    rmasks = ResidentMasks(cluster)
    for cycle in range(8):
        _churn(rng, cluster, names, cycle)
        rmasks.sync(specs)
        ex = cluster.existing_columns()
        for s in specs:
            assert np.array_equal(rmasks.mask_for(ex, s),
                                  existing_fit_vector(ex, s)), (
                f"cycle {cycle}: resident mask diverged for {s.name}")
    # the patch path must actually be incremental after the cold build
    assert rmasks.full_builds_total == len(specs)


def test_resident_masks_new_spec_arrival():
    cluster = _cluster(10)
    rmasks = ResidentMasks(cluster)
    first = [make_pod("a", cpu="250m", memory="256Mi")]
    rmasks.sync(first)
    late = make_pod("z", cpu="250m", memory="256Mi",
                    node_selector={"team": "t2"})
    rmasks.sync(first + [late])
    ex = cluster.existing_columns()
    assert np.array_equal(rmasks.mask_for(ex, late),
                          existing_fit_vector(ex, late))


def test_resident_candidates_parity_and_pdb_epoch():
    rng = random.Random(13)
    cluster = _cluster(20)
    names = [f"n-{k:03d}" for k in range(20)]
    rcands = ResidentCandidates(cluster)
    for cycle in range(6):
        _churn(rng, cluster, names, cycle)
        rcands.sync()
        assert rcands.candidate_names() == [
            n.name for n in cluster.consolidation_candidates()]
    # a PDB-set change shifts verdicts on CLEAN rows: the cache must drop
    builds = rcands.full_builds_total
    cluster.pdbs = [PodDisruptionBudget(
        name="block-all", selector={}, max_unavailable=0)]
    rcands.sync()
    assert rcands.full_builds_total == builds + 1
    assert rcands.candidate_names() == [
        n.name for n in cluster.consolidation_candidates()]


def test_empty_and_expired_rows_match_sweeps():
    from karpenter_tpu.controllers.deprovisioning import \
        DeprovisioningController
    from karpenter_tpu.utils.clock import FakeClock

    now = 1_000_000.0
    provs = [Provisioner(name="default", ttl_seconds_after_empty=30,
                         ttl_seconds_until_expired=500)]
    for p in provs:
        p.set_defaults()

    class _Kube:
        def provisioners(self):
            return provs

    class _Termination:
        def request_deletion(self, name):
            return False

    cluster = ClusterState()
    for k in range(12):
        node = _node(f"n-{k:03d}", k, now=now)
        node.created_ts = now - k * 100  # k>=5 ages past the 500s expiry
        if k % 3 == 0:
            node.pods = []  # empty
        cluster.add_node(node)
    ctrl = DeprovisioningController(
        kube=_Kube(), cloudprovider=None, cluster=cluster,
        termination=_Termination(), clock=FakeClock(now),
        use_tpu_solver=False)
    cols = cluster.columns
    _, ttl_e = ctrl._prov_ttl_columns("ttl_seconds_after_empty")
    _, ttl_x = ctrl._prov_ttl_columns("ttl_seconds_until_expired")

    e_rows = empty_node_rows(cluster, ttl_e)
    want_empty = sorted(
        name for name, n in cluster.nodes.items()
        if not n.pods and not n.marked_for_deletion)
    assert sorted(cols.name_of[r] for r in e_rows) == want_empty

    x_rows = expired_node_rows(cluster, ttl_x, now)
    want_expired = sorted(
        name for name, n in cluster.nodes.items()
        if not n.marked_for_deletion and now - n.created_ts >= 500)
    assert sorted(cols.name_of[r] for r in x_rows) == want_expired


# -- gate / noop / bookkeeping -------------------------------------------------


def test_disabled_is_strictly_noop():
    catalog, provisioners = _catalog(), [_prov()]
    cluster = _cluster(6)
    inc = IncrementalSolver(cluster)
    base = _base(catalog, provisioners)
    pods = _pending(random.Random(5), 0)
    prev = incremental.set_enabled(False)
    try:
        before = incremental.activity()
        got, kind = inc.solve(pods, cluster.existing_columns(), base,
                              catalog=catalog, provisioners=provisioners)
        after = incremental.activity()
        assert after == before, "disabled plane moved a counter"
        assert inc.last is None
        want, _ = base(pods, cluster.existing_columns())
        assert solve_fingerprint(got) == solve_fingerprint(want)
    finally:
        incremental.set_enabled(prev)


def test_deleted_since_honest_past_horizon():
    cluster = _cluster(4)
    cursor = cluster.seq
    cluster.delete_node("n-000")
    names, complete = cluster.deleted_since(cursor)
    assert names == ["n-000"] and complete
    # a cursor older than the log floor must report incomplete, not guess
    cluster._deletion_floor = cursor + 1
    _, complete = cluster.deleted_since(cursor)
    assert not complete


def test_set_resident_replace_semantics():
    from karpenter_tpu.solver.buckets import HbmLedger

    ledger = HbmLedger()
    ledger.set_resident("inc", "assignment", 1024.0)
    ledger.set_resident("inc", "assignment", 512.0)
    # replace, not accumulate: the second filing overwrites the first
    assert ledger._static["inc"]["assignment"] == 512.0
    with pytest.raises(ValueError):
        ledger.set_resident("inc", "not-a-static-class", 1.0)
    import json

    json.dumps(ledger.snapshot())  # snapshot stays serializable


def _consolidatable_cluster(n=36, now=1_000_000.0):
    """Heterogeneous consolidation fleet: under-utilized on-demand
    m.xlarge rows (repack/replace candidates), a couple of spot rows
    (delete-only), zone-spread pods on some rows (forces the encoder's
    survivors snapshot), and a marked row (never a candidate)."""
    catalog = _catalog()
    big = catalog.by_name["m.xlarge"]
    cluster = ClusterState()
    for i in range(n):
        spot = i % 9 == 4
        pods = [make_pod(f"c{i}-p0", cpu="250m", memory="512Mi",
                         node_name=f"c-{i:03d}", owner_kind="ReplicaSet")]
        if i % 7 == 2:  # zone-spread pods exercise prepare_groups(existing)
            pods.append(dataclasses.replace(
                make_pod(f"c{i}-tp", cpu="100m", memory="128Mi",
                         node_name=f"c-{i:03d}", owner_kind="ReplicaSet"),
                topology=(TopologySpreadConstraint(
                    topology_key=wk.LABEL_ZONE, max_skew=1,
                    when_unsatisfiable="DoNotSchedule"),)))
        node = StateNode(
            name=f"c-{i:03d}",
            labels={**big.labels_dict(),
                    wk.LABEL_ZONE: f"z-{'abc'[i % 3]}",
                    wk.LABEL_CAPACITY_TYPE: "spot" if spot else "on-demand",
                    wk.LABEL_PROVISIONER: "default"},
            allocatable=big.allocatable_vector(),
            instance_type=big.name, zone=f"z-{'abc'[i % 3]}",
            capacity_type="spot" if spot else "on-demand",
            price=0.28 if spot else 0.80, provisioner_name="default",
            created_ts=now - 3600.0, pods=pods)
        cluster.add_node(node)
    cluster.nodes["c-001"].marked_for_deletion = True
    return cluster, catalog


def test_stream_consolidation_matches_oneshot():
    """The streamed sweep (chunked encode + type-pruned dispatch + padded
    tail) must pick exactly the one-shot mega-batch's action at every
    stream width — including widths that force padding and a single
    undersized chunk."""
    from karpenter_tpu.ops.consolidate import (run_consolidation,
                                               stream_consolidation)

    cluster, catalog = _consolidatable_cluster()
    prov = Provisioner(name="default", consolidation_enabled=True)
    prov.set_defaults()
    want = run_consolidation(cluster, catalog, [prov])
    assert want is not None  # the fleet must actually consolidate
    for width in (5, 16, 1000):
        got = stream_consolidation(cluster, catalog, [prov],
                                   batch_lanes=width)
        assert got is not None, width
        assert (got.kind, got.nodes, got.replacement) == \
            (want.kind, want.nodes, want.replacement), width
        assert got.savings == pytest.approx(want.savings)
