"""Operational tooling: the log ring (/logz + `logs` CLI) and fixture sync
(`sync` CLI) — the analogues of the reference's test/cmd fleet tools
(logs/main.go log fetch; sync-cluster GitOps fixture sync)."""

import logging
import urllib.request

from karpenter_tpu.apis.yaml_compat import load_manifests
from karpenter_tpu.coordination.httpkube import HttpKubeStore
from karpenter_tpu.coordination.sync import sync_manifests
from karpenter_tpu.fake.kube import KubeStore
from karpenter_tpu.models.pod import make_pod
from karpenter_tpu.utils import logring

from tests.test_e2e_scenarios import make_operator  # noqa: F401

FIXTURE = """
apiVersion: karpenter.sh/v1alpha5
kind: Provisioner
metadata:
  name: default
spec:
  providerRef:
    name: default
---
apiVersion: karpenter.k8s.tpu/v1alpha1
kind: AWSNodeTemplate
metadata:
  name: default
spec:
  subnetSelector:
    id: subnet-zone-1a
  securityGroupSelector:
    id: sg-default
"""


class TestLogRing:
    def test_ring_captures_package_logs_bounded(self):
        h = logring.install(capacity=2000)
        log = logging.getLogger("karpenter.test.ring")
        marker = "ring-marker-xyz"
        log.info(marker)
        assert any(marker in ln for ln in logring.dump())
        # bounded: capacity caps retention
        for i in range(h.ring.maxlen + 50):
            log.info("flood %d", i)
        assert len(logring.dump()) == h.ring.maxlen
        # tail query
        assert len(logring.dump(10)) == 10

    def test_logz_endpoint_serves_ring(self):
        from karpenter_tpu.serving import ServingPlane

        op = make_operator()
        try:
            plane = ServingPlane(op, metrics_port=-1, health_port=0,
                                 webhook_port=-1)
            ports = plane.start()
            logging.getLogger("karpenter.test.logz").info("logz-marker-abc")
            try:
                body = urllib.request.urlopen(
                    f"http://127.0.0.1:{ports['health']}/logz?n=50",
                    timeout=5).read().decode()
                assert "logz-marker-abc" in body
            finally:
                plane.stop()
        finally:
            op.stop()


class TestTailDelta:
    """logs --follow cursor over /logz's sliding window: content-matched
    from the end, never an index (a full window makes an index cursor
    permanently silent)."""

    def test_saturated_window_keeps_advancing(self):
        from karpenter_tpu.__main__ import _tail_delta

        w1 = [f"l{i}" for i in range(500)]
        new, last = _tail_delta(w1, None)
        assert new == w1 and last == "l499"
        # window slides by 3: only the 3 new lines print
        w2 = w1[3:] + ["l500", "l501", "l502"]
        new, last = _tail_delta(w2, last)
        assert new == ["l500", "l501", "l502"] and last == "l502"

    def test_marker_rotated_out_prints_whole_window(self):
        from karpenter_tpu.__main__ import _tail_delta

        new, last = _tail_delta(["b1", "b2"], "gone")
        assert new == ["b1", "b2"] and last == "b2"

    def test_empty_poll_keeps_cursor(self):
        from karpenter_tpu.__main__ import _tail_delta

        new, last = _tail_delta([], "l9")
        assert new == [] and last == "l9"


class TestSyncManifests:
    def test_apply_then_idempotent(self):
        kube = KubeStore()
        loaded = load_manifests(FIXTURE)
        c1 = sync_manifests(kube, loaded)
        assert c1["created"] == 2 and c1["updated"] == 0
        assert kube.get("provisioners", "default") is not None
        assert kube.get("nodetemplates", "default") is not None
        c2 = sync_manifests(kube, loaded)
        assert c2["created"] == 0 and c2["pruned"] == 0

    def test_update_on_drifted_object(self):
        kube = KubeStore()
        loaded = load_manifests(FIXTURE)
        sync_manifests(kube, loaded)
        # drift the stored template, re-sync restores the fixture's version
        # (fresh load: the first sync stored the same objects `loaded` holds)
        t = kube.get("nodetemplates", "default")
        t.tags = {"drift": "yes"}
        kube.update("nodetemplates", "default", t)
        c = sync_manifests(kube, load_manifests(FIXTURE))
        assert c["updated"] >= 1
        assert kube.get("nodetemplates", "default").tags == {}

    def test_prune_removes_unmanaged_fixture_extras_only(self):
        kube = KubeStore()
        loaded = load_manifests(FIXTURE)
        sync_manifests(kube, loaded)
        from karpenter_tpu.apis.provisioner import Provisioner

        extra = Provisioner(name="stale")
        extra.set_defaults()
        kube.create("provisioners", "stale", extra)
        # a foreign kind must survive the prune
        kube.create("pods", "workload", make_pod("workload", cpu="1",
                                                 memory="1Gi"))
        c = sync_manifests(kube, loaded, prune=True)
        assert c["pruned"] == 1
        assert kube.get("provisioners", "stale") is None
        assert kube.get("pods", "workload") is not None

    def test_existing_pod_never_stomped(self):
        kube = KubeStore()
        bound = make_pod("w", cpu="1", memory="1Gi", node_name="node-1")
        kube.create("pods", "w", bound)
        fixture_pod = make_pod("w", cpu="1", memory="1Gi")
        loaded = load_manifests(FIXTURE)
        loaded.pods.append(fixture_pod)
        sync_manifests(kube, loaded)
        assert kube.get("pods", "w").node_name == "node-1"

    def test_create_denial_surfaces_not_swallowed(self):
        import pytest

        kube = KubeStore()
        kube.set_admission(lambda kind, obj, op_: (_ for _ in ()).throw(
            ValueError("denied by policy")))
        with pytest.raises(ValueError, match="denied"):
            sync_manifests(kube, load_manifests(FIXTURE))

    def test_sync_against_mini_apiserver(self):
        from karpenter_tpu.fake.apiserver import serve

        srv, port, _state = serve()
        try:
            kube = HttpKubeStore(f"http://127.0.0.1:{port}")
            kube.start()
            try:
                c = sync_manifests(kube, load_manifests(FIXTURE), prune=True)
                assert c["created"] == 2
                assert kube.get("provisioners", "default") is not None
                c2 = sync_manifests(kube, load_manifests(FIXTURE), prune=True)
                assert c2["created"] == 0 and c2["pruned"] == 0
            finally:
                kube.stop()
        finally:
            srv.shutdown()


class TestWalkthroughCLI:
    """The getting-started walkthrough's CLI surface (docs §4): the
    kubeconfig the `apiserver` subcommand writes is loadable, and `get`
    renders listings over it."""

    def test_get_lists_nodes_via_written_kubeconfig(self, tmp_path, capsys):
        import json

        from karpenter_tpu.__main__ import cmd_get
        from karpenter_tpu.fake.apiserver import serve
        from karpenter_tpu.models.cluster import StateNode

        srv, port, state = serve()
        try:
            kc = tmp_path / "kubeconfig"
            kc.write_text(json.dumps({
                "apiVersion": "v1", "kind": "Config",
                "clusters": [{"name": "mini", "cluster": {
                    "server": f"http://127.0.0.1:{port}"}}],
                "users": [{"name": "mini", "user": {}}],
                "contexts": [{"name": "mini", "context": {
                    "cluster": "mini", "user": "mini"}}],
                "current-context": "mini"}))
            # seed a node through the wire the way the controller would
            kube = HttpKubeStore(f"http://127.0.0.1:{port}")
            from karpenter_tpu.apis import wellknown as wk
            kube.create("nodes", "n-1", StateNode(
                name="n-1",
                labels={wk.LABEL_INSTANCE_TYPE: "t3a.small",
                        wk.LABEL_ZONE: "zone-1a",
                        wk.LABEL_CAPACITY_TYPE: "spot"},
                allocatable=[0] * wk.NUM_RESOURCES))

            class Args:
                kind = "nodes"
                kubeconfig = str(kc)

            assert cmd_get(Args()) == 0
            out = capsys.readouterr().out
            assert "n-1" in out and "t3a.small" in out and "zone-1a" in out
        finally:
            srv.shutdown()
            srv.server_close()
