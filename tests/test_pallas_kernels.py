"""Pallas quotient kernel vs the stock-XLA int32 reference: bit parity.

Runs in interpreter mode on the CPU test platform; the compiled path is
exercised on real TPU by bench.py --pallas.
"""

import numpy as np
import pytest

from karpenter_tpu.apis import wellknown as wk
from karpenter_tpu.ops import pallas_kernels as pk
from karpenter_tpu.ops.packer import _quotient

import jax.numpy as jnp


def reference_quotient_nt(alloc_t, used, vec):
    return np.asarray(_quotient(
        jnp.asarray(alloc_t)[None, :, :] - jnp.asarray(used)[:, None, :],
        jnp.asarray(vec)))


def rand_problem(rng, n, t, r=wk.NUM_RESOURCES):
    alloc_t = rng.integers(0, 2**20, size=(t, r), dtype=np.int32)
    used = rng.integers(0, 2**20, size=(n, r), dtype=np.int32)
    vec = rng.integers(0, 64, size=(r,), dtype=np.int32)
    vec[rng.random(r) < 0.4] = 0  # zero-demand resources are common
    return alloc_t, used, vec


@pytest.mark.parametrize("seed,n,t", [(0, 8, 16), (1, 64, 551), (2, 100, 37),
                                      (3, 1, 1), (4, 65, 129)])
def test_quotient_parity_random(seed, n, t):
    rng = np.random.default_rng(seed)
    alloc_t, used, vec = rand_problem(rng, n, t)
    got = np.asarray(pk.quotient_nt_auto(jnp.asarray(alloc_t),
                                         jnp.asarray(used), jnp.asarray(vec)))
    want = reference_quotient_nt(alloc_t, used, vec)
    np.testing.assert_array_equal(got, want)


def test_quotient_negative_availability():
    alloc_t = np.array([[4, 8]], dtype=np.int32)       # one type, R=2
    used = np.array([[6, 0], [0, 0], [4, 8]], dtype=np.int32)
    vec = np.array([2, 1], dtype=np.int32)
    got = np.asarray(pk.quotient_nt_auto(jnp.asarray(alloc_t),
                                         jnp.asarray(used), jnp.asarray(vec)))
    want = reference_quotient_nt(alloc_t, used, vec)
    np.testing.assert_array_equal(got, want)
    assert got[0, 0] == -1   # over-committed -> -1
    assert got[2, 0] == 0    # exactly full -> 0


def test_quotient_zero_vec_everywhere_is_big():
    alloc_t = np.zeros((3, 4), dtype=np.int32)
    used = np.zeros((2, 4), dtype=np.int32)
    vec = np.zeros(4, dtype=np.int32)
    got = np.asarray(pk.quotient_nt_auto(jnp.asarray(alloc_t),
                                         jnp.asarray(used), jnp.asarray(vec)))
    want = reference_quotient_nt(alloc_t, used, vec)
    np.testing.assert_array_equal(got, want)
    assert (got == int(pk.INT_BIG)).all()


def test_exact_boundary_divisions():
    # quotients at exact multiples and one-below, large magnitudes (< 2**24)
    vals = np.array([2**24 - 1, 2**24 - 2, 3 * 5461 * 1023], dtype=np.int32)
    alloc_t = np.stack([vals, vals], axis=0)           # [2, 3]
    used = np.zeros((4, 3), dtype=np.int32)
    used[1] = 1
    used[2] = [v % 7 for v in vals]
    vec = np.array([7, 5461, 1023], dtype=np.int32)
    got = np.asarray(pk.quotient_nt_auto(jnp.asarray(alloc_t),
                                         jnp.asarray(used), jnp.asarray(vec)))
    want = reference_quotient_nt(alloc_t, used, vec)
    np.testing.assert_array_equal(got, want)


def test_value_safety_gate_routes_oversized_to_xla():
    # f32 one-correction exactness holds only below 2**24; encode clamps at
    # INT_BIG (2**30), so build_pack_inputs must route huge extended
    # resource counts to the XLA path and keep the bit-parity contract
    from karpenter_tpu.ops.packer import F24, pallas_value_safe

    ok = np.array([[F24 - 1, 12]], dtype=np.int32)
    huge = np.array([[F24, 12]], dtype=np.int32)
    assert pallas_value_safe(ok, np.zeros((2, 2), np.int32))
    assert not pallas_value_safe(ok, huge)
    assert pallas_value_safe(None, ok)       # optional inputs skipped
    assert pallas_value_safe()               # vacuous


def test_pack_with_oversized_catalog_matches_oracle_convention():
    # end-to-end: a catalog entry with an extended-resource count above 2**24
    # must still solve exactly (XLA path) even with the pallas flag forced on
    from karpenter_tpu.apis.provisioner import Provisioner
    from karpenter_tpu.models.instancetype import Catalog, make_instance_type
    from karpenter_tpu.models.pod import make_pod
    from karpenter_tpu.solver.core import TPUSolver

    big = make_instance_type("huge.ex", cpu=64, memory="256Gi", od_price=1.0,
                             extended={"nvidia.com/gpu": 2**25})
    cat = Catalog(types=[big])
    prov = Provisioner(name="default")
    prov.set_defaults()
    pods = [make_pod(f"p{i}", cpu="1", memory="1Gi",
                     extended={"nvidia.com/gpu": 3}) for i in range(5)]
    pk.force_enable(True)
    try:
        res = TPUSolver(cat, [prov]).solve(pods)
    finally:
        pk.force_enable(False)
    assert sum(n.pod_count for n in res.nodes) == 5
    assert res.unschedulable_count() == 0
