"""Admission webhook tests (pkg/webhooks/webhooks.go analogue): defaulting
mutates on the way in, validation rejects bad specs, unregistered kinds pass
through untouched."""

import pytest

from karpenter_tpu.apis import wellknown as wk
from karpenter_tpu.apis.nodetemplate import NodeTemplate
from karpenter_tpu.apis.provisioner import Provisioner
from karpenter_tpu.apis.settings import Settings
from karpenter_tpu.fake.cloud import FakeCloud
from karpenter_tpu.models.instancetype import Catalog, make_instance_type
from karpenter_tpu.models.pod import make_pod
from karpenter_tpu.models.requirements import Requirements, OP_IN
from karpenter_tpu.operator import Operator
from karpenter_tpu.webhooks import AdmissionError, Webhooks


def make_operator():
    catalog = Catalog(types=[make_instance_type("m.l", cpu=2, memory="8Gi")])
    return Operator(FakeCloud(catalog),
                    Settings(cluster_name="t", cluster_endpoint="https://t"),
                    catalog)


class TestWebhooks:
    def test_provisioner_defaulted_on_create(self):
        op = make_operator()
        op.kube.create("provisioners", "p", Provisioner(name="p"))
        p = op.kube.get("provisioners", "p")
        # defaulting webhook applied linux/amd64/on-demand
        # (v1alpha5/provisioner.go:45-60)
        assert p.requirements.get(wk.LABEL_OS).has("linux")
        assert p.requirements.get(wk.LABEL_ARCH).has("amd64")
        assert p.requirements.get(wk.LABEL_CAPACITY_TYPE).has("on-demand")

    def test_invalid_provisioner_rejected(self):
        op = make_operator()
        bad = Provisioner(name="bad", requirements=Requirements.of(
            (wk.LABEL_PROVISIONER, OP_IN, ["nope"])))  # restricted label
        with pytest.raises(AdmissionError):
            op.kube.create("provisioners", "bad", bad)
        assert op.kube.get("provisioners", "bad") is None

    def test_mutually_exclusive_consolidation_ttl_rejected(self):
        op = make_operator()
        bad = Provisioner(name="bad", consolidation_enabled=True,
                          ttl_seconds_after_empty=30)
        with pytest.raises(AdmissionError):
            op.kube.create("provisioners", "bad", bad)

    def test_update_also_validated(self):
        op = make_operator()
        op.kube.create("provisioners", "p", Provisioner(name="p"))
        with pytest.raises(AdmissionError):
            op.kube.update("provisioners", "p", Provisioner(name="p", weight=101))

    def test_nodetemplate_validated(self):
        op = make_operator()
        t = NodeTemplate(name="tmpl", subnet_selector={"cluster": "t"},
                         security_group_selector={"cluster": "t"})
        op.kube.create("nodetemplates", "tmpl", t)
        assert op.kube.get("nodetemplates", "tmpl") is t

    def test_nodetemplate_missing_subnets_rejected(self):
        op = make_operator()
        with pytest.raises(AdmissionError):
            op.kube.create("nodetemplates", "bad", NodeTemplate(name="bad"))

    def test_nodetemplate_static_lt_exclusive(self):
        op = make_operator()
        bad = NodeTemplate(name="bad", subnet_selector={"c": "t"},
                           launch_template_name="lt-1", userdata="#!/bin/sh")
        with pytest.raises(AdmissionError):
            op.kube.create("nodetemplates", "bad", bad)

    def test_unregistered_kind_passthrough(self):
        op = make_operator()
        pod = make_pod("p", cpu="1", memory="1Gi")
        op.kube.create("pods", "p", pod)
        assert op.kube.get("pods", "p") is pod

    def test_admit_direct(self):
        w = Webhooks()
        p = Provisioner(name="x")
        w.admit("provisioners", p)
        assert p.requirements.get(wk.LABEL_OS) is not None


class TestNodeTemplateValidationDepth:
    """Round-3 v1alpha1 depth: the same invalid manifests the reference's
    validation rejects (provider_validation.go:46+, tags.go:29+,
    awsnodetemplate_validation.go)."""

    def _base(self, **kw):
        from karpenter_tpu.apis.nodetemplate import NodeTemplate

        kw.setdefault("subnet_selector", {"id": "subnet-zone-1a"})
        kw.setdefault("security_group_selector", {"id": "sg-default"})
        return NodeTemplate(name="t", **kw)

    def test_empty_selector_key_or_value_rejected(self):
        from karpenter_tpu.apis.provisioner import ValidationError

        with pytest.raises(ValidationError):
            self._base(subnet_selector={"": "x"}).validate()
        with pytest.raises(ValidationError):
            self._base(security_group_selector={"tag": ""}).validate()

    def test_malformed_resource_ids_rejected(self):
        from karpenter_tpu.apis.provisioner import ValidationError

        with pytest.raises(ValidationError):
            self._base(subnet_selector={"id": "not-a-subnet"}).validate()
        with pytest.raises(ValidationError):
            self._base(security_group_selector={"id": "subnet-1"}).validate()
        with pytest.raises(ValidationError):
            self._base(image_selector={"id": "vol-123"}).validate()
        # well-formed comma lists pass
        self._base(subnet_selector={
            "id": "subnet-zone-1a, subnet-zone-1b"}).validate()

    def test_security_group_selector_required_without_static_lt(self):
        from karpenter_tpu.apis.nodetemplate import NodeTemplate
        from karpenter_tpu.apis.provisioner import ValidationError

        with pytest.raises(ValidationError):
            NodeTemplate(name="t",
                         subnet_selector={"id": "subnet-zone-1a"}).validate()
        # a static LT carries its own SGs
        NodeTemplate(name="t", subnet_selector={"id": "subnet-zone-1a"},
                     launch_template_name="lt-1").validate()

    def test_static_lt_excludes_identity_and_network_fields(self):
        from karpenter_tpu.apis.nodetemplate import NodeTemplate
        from karpenter_tpu.apis.provisioner import ValidationError

        for kw in ({"security_group_selector": {"id": "sg-1"}},
                   {"instance_profile": "profile-x"}):
            with pytest.raises(ValidationError):
                NodeTemplate(name="t", launch_template_name="lt-1",
                             subnet_selector={"id": "subnet-zone-1a"},
                             **kw).validate()

    def test_per_cluster_ownership_tag_rejected(self):
        from karpenter_tpu.apis.provisioner import ValidationError

        t = self._base(tags={"kubernetes.io/cluster/prod-1": "owned"})
        with pytest.raises(ValidationError):
            t.validate(cluster_name="prod-1")
        # ANOTHER cluster's tag is legitimate shared-infra tagging when the
        # cluster context is known...
        self._base(tags={"kubernetes.io/cluster/other": "shared"}).validate(
            cluster_name="prod-1")
        # ...but without context every cluster-ownership tag is conservative
        with pytest.raises(ValidationError):
            self._base(tags={"kubernetes.io/cluster/other": "shared"}).validate()

    def test_empty_tag_key_rejected(self):
        from karpenter_tpu.apis.provisioner import ValidationError

        with pytest.raises(ValidationError):
            self._base(tags={"": "v"}).validate()

    def test_metadata_options_bounds(self):
        from karpenter_tpu.apis.nodetemplate import MetadataOptions
        from karpenter_tpu.apis.provisioner import ValidationError

        with pytest.raises(ValidationError):
            self._base(metadata_options=MetadataOptions(
                http_put_response_hop_limit=0)).validate()
        with pytest.raises(ValidationError):
            self._base(metadata_options=MetadataOptions(
                http_put_response_hop_limit=65)).validate()
        with pytest.raises(ValidationError):
            self._base(metadata_options=MetadataOptions(
                http_protocol_ipv6="on")).validate()
        self._base(metadata_options=MetadataOptions(
            http_protocol_ipv6="enabled")).validate()  # dual-stack ok

    def test_block_device_bounds_and_iops(self):
        from karpenter_tpu.apis.nodetemplate import BlockDeviceMapping
        from karpenter_tpu.apis.provisioner import ValidationError

        with pytest.raises(ValidationError):
            self._base(block_device_mappings=(
                BlockDeviceMapping(volume_size_gib=65 * 1024),)).validate()
        with pytest.raises(ValidationError):
            self._base(block_device_mappings=(
                BlockDeviceMapping(device_name=""),)).validate()
        with pytest.raises(ValidationError):
            self._base(block_device_mappings=(
                BlockDeviceMapping(volume_type="balanced", iops=3000),)).validate()

    def test_webhook_pipeline_carries_cluster_name(self):
        from karpenter_tpu.webhooks import AdmissionError, Webhooks

        hooks = Webhooks(cluster_name="prod-1")
        bad = self._base(tags={"kubernetes.io/cluster/prod-1": "owned"})
        with pytest.raises(AdmissionError):
            hooks.admit("nodetemplates", bad, "CREATE")
