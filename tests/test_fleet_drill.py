"""Tier-1 coverage for the real-replica fleet drill (ISSUE 16 tentpole).

Three layers, cheapest first:

* pure determinism: the replay plan is a function of (seed, config)
  only, and its schedule digest covers the EXACT tenant-id stream the
  live drill consumes — no subprocesses involved;
* scrape-plane hardening: every classified HttpReplica failure mode
  (connect, timeout, invalid JSON, oversized body, HTTP status)
  degrades to a NAMED fleetz error row while the probe breaker still
  counts and backs off;
* the real thing, small: `run_drill` against two genuine replica
  subprocesses with a mid-run SIGKILL, short window — the tier-1 proof
  that rendezvous, federation, membership, failover and the invariant
  audit work across live process boundaries. The full 4-replica /
  1000-tenant / throughput-floored run rides the slow marker
  (`make fleet-drill` is its recorded entrypoint).
"""

import http.server
import json
import os
import tempfile
import threading
import time

import pytest

from benchmarks.fleet_drill import (
    FULL, SMALL, DrillConfig, _Schedule, build_replay_plan, run_drill,
    schedule_digest)
from karpenter_tpu.introspect.fleetview import (
    PROBE_FAILURE_THRESHOLD, FleetView, HttpReplica, ScrapeError)
from karpenter_tpu.fleet import replica as replica_mod


class TestReplayPlan:
    def test_replay_identical_under_fixed_seed(self):
        assert build_replay_plan(FULL) == build_replay_plan(FULL)
        assert build_replay_plan(SMALL) == build_replay_plan(SMALL)

    def test_seed_and_config_change_the_digest(self):
        base = build_replay_plan(SMALL)
        reseeded = build_replay_plan(
            DrillConfig(**{**base_kwargs(SMALL), "seed": 1}))
        resized = build_replay_plan(
            DrillConfig(**{**base_kwargs(SMALL), "tenants": 49}))
        assert reseeded["schedule_digest"] != base["schedule_digest"]
        assert resized["schedule_digest"] != base["schedule_digest"]

    def test_digest_covers_the_live_schedule_stream(self):
        """The live _Schedule must emit exactly the stream the plan's
        digest commits to: the shuffled sweep, then the zipf tail."""
        cfg = SMALL
        plan = build_replay_plan(cfg)
        sched = _Schedule(cfg)
        sched.deadline = time.perf_counter() + 3600.0
        drawn = [sched.next() for _ in range(3 * cfg.tenants)]
        assert plan["schedule_digest"] == schedule_digest(
            drawn[:cfg.tenants], drawn[cfg.tenants:])
        assert drawn[:8] == plan["sweep_head"]
        assert drawn[cfg.tenants:cfg.tenants + 8] == plan["tail_head"]
        # the sweep names every tenant exactly once
        assert sorted(drawn[:cfg.tenants]) == [
            f"tenant-{i:04d}" for i in range(cfg.tenants)]

    def test_schedule_stops_at_deadline_after_sweep(self):
        cfg = SMALL
        sched = _Schedule(cfg)
        sched.deadline = time.perf_counter() - 1.0  # already past
        drawn = [sched.next() for _ in range(cfg.tenants)]
        assert all(t is not None for t in drawn)  # sweep always completes
        assert sched.next() is None               # tail is deadline-gated

    def test_victim_is_a_named_replica(self):
        for cfg in (FULL, SMALL):
            plan = build_replay_plan(cfg)
            assert plan["kill_victim"] in plan["replicas"]


def base_kwargs(cfg: DrillConfig) -> dict:
    from dataclasses import asdict

    d = asdict(cfg)
    d["warmup_rungs"] = tuple(d["warmup_rungs"])
    return d


# -- scrape-plane hardening (satellite 2's acceptance) ----------------------


class _StubHandler(http.server.BaseHTTPRequestHandler):
    """One behavior per server instance, set via class attribute."""

    behavior = "ok"

    def do_GET(self):  # noqa: N802 — BaseHTTPRequestHandler API
        b = self.behavior
        if b == "hang":
            time.sleep(5.0)
            return
        if b == "http-500":
            self.send_error(500, "boom")
            return
        if b == "invalid-json":
            body = b"<html>this is not json</html>"
        elif b == "oversized":
            body = b"[" + b"1," * 4096 + b"1]"
        else:
            body = json.dumps({"schema": 9, "pid": os.getpid(),
                               "ts": time.time(),
                               "resilience": {"watchdog": {"healthy":
                                                           True}}}).encode()
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *a):  # quiet
        pass


@pytest.fixture
def stub_server():
    servers = []

    def start(behavior):
        handler = type("H", (_StubHandler,), {"behavior": behavior})
        srv = http.server.HTTPServer(("127.0.0.1", 0), handler)
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        servers.append(srv)
        return f"http://127.0.0.1:{srv.server_address[1]}"

    yield start
    for srv in servers:
        srv.shutdown()
        srv.server_close()


class TestHttpReplicaHardening:
    def test_connect_refused_is_classified(self):
        # bind-then-close guarantees a dead port
        import socket

        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        rep = HttpReplica("corpse", f"http://127.0.0.1:{port}")
        with pytest.raises(ScrapeError) as ei:
            rep.statusz()
        assert ei.value.kind == "connect"

    def test_read_timeout_is_classified(self, stub_server):
        rep = HttpReplica("slug", stub_server("hang"), timeout_s=0.2)
        with pytest.raises(ScrapeError) as ei:
            rep.statusz()
        assert ei.value.kind == "timeout"

    def test_http_status_is_classified(self, stub_server):
        rep = HttpReplica("angry", stub_server("http-500"))
        with pytest.raises(ScrapeError) as ei:
            rep.statusz()
        assert ei.value.kind == "http-500"

    def test_invalid_json_is_classified(self, stub_server):
        rep = HttpReplica("garbled", stub_server("invalid-json"))
        with pytest.raises(ScrapeError) as ei:
            rep.statusz()
        assert ei.value.kind == "invalid-json"

    def test_oversized_body_is_clamped_and_classified(self, stub_server):
        rep = HttpReplica("bloated", stub_server("oversized"),
                          max_bytes=64)
        with pytest.raises(ScrapeError) as ei:
            rep.statusz()
        assert ei.value.kind == "oversized-response"

    def test_healthy_scrape_learns_pid_and_latency(self, stub_server):
        rep = HttpReplica("live", stub_server("ok"))
        snap = rep.statusz()
        assert snap["pid"] == os.getpid()
        assert rep.pid == os.getpid()
        assert rep.last_scrape_ms > 0

    def test_every_kind_degrades_to_named_error_row(self, stub_server):
        """The FleetView contract: a failing replica is a NAMED error
        row carrying the classified kind — never a raised exception,
        never an anonymous corpse."""
        view = FleetView(name="hardening")
        view.add_replica(HttpReplica("garbled", stub_server("invalid-json")))
        view.add_replica(HttpReplica("bloated", stub_server("oversized"),
                                     max_bytes=64))
        view.add_replica(HttpReplica("angry", stub_server("http-500")))
        rows = view.fleetz()["replicas"]
        assert rows["garbled"]["scrape_error"] == "invalid-json"
        assert rows["bloated"]["scrape_error"] == "oversized-response"
        assert rows["angry"]["scrape_error"] == "http-500"
        for row in rows.values():
            assert row["healthy"] is False
            assert row["error"]

    def test_probe_breaker_still_backs_off(self, stub_server):
        view = FleetView(name="backoff")
        view.add_replica(HttpReplica("angry", stub_server("http-500")))
        for i in range(PROBE_FAILURE_THRESHOLD):
            row = view.fleetz()["replicas"]["angry"]
            assert row["scrape_error"] == "http-500"
            assert row["consecutive_failures"] == i + 1
        row = view.fleetz()["replicas"]["angry"]
        assert row.get("probe_suppressed") is True


# -- rendezvous handshake ---------------------------------------------------


class TestRendezvous:
    def test_write_then_read_roundtrip(self, tmp_path):
        rec = {"schema": 1, "name": "r0", "pid": 1234,
               "grpc": "127.0.0.1:5", "debug": "http://127.0.0.1:6"}
        replica_mod.write_registration(str(tmp_path), rec)
        assert replica_mod.read_registrations(str(tmp_path)) == {"r0": rec}

    def test_torn_files_are_skipped(self, tmp_path):
        (tmp_path / "torn.json").write_text('{"name": "r1", ')
        replica_mod.write_registration(
            str(tmp_path), {"schema": 1, "name": "r0"})
        regs = replica_mod.read_registrations(str(tmp_path))
        assert list(regs) == ["r0"]

    def test_wait_names_the_stragglers(self, tmp_path):
        replica_mod.write_registration(
            str(tmp_path), {"schema": 1, "name": "r0"})
        with pytest.raises(TimeoutError) as ei:
            replica_mod.wait_for_registrations(
                str(tmp_path), ["r0", "r1", "r2"],
                timeout_s=0.3, poll_s=0.05)
        assert "r1" in str(ei.value) and "r2" in str(ei.value)
        assert "r0" not in str(ei.value).split(":")[-1]


# -- the real thing, small --------------------------------------------------

# tier-1-sized: two REAL subprocesses, a ~2.5s window, one SIGKILL. The
# boot dominates (two cold JAX imports timesharing the core), the physics
# is identical to the full drill.
TINY = DrillConfig(name="tiny", replicas=2, tenants=24, duration_s=3.0,
                   workers=6, max_wave=4, warmup_rungs=(2,),
                   starvation_bound=16)


class TestSmallDrill:
    @pytest.fixture(scope="class")
    def artifact(self, tmp_path_factory):
        out = str(tmp_path_factory.mktemp("fleet-drill"))
        return run_drill(TINY, out)

    def test_drill_passes(self, artifact):
        assert artifact["passed"], json.dumps(
            {"criteria": artifact["criteria"],
             "violations": artifact["violations"]}, indent=2)

    def test_replicas_were_real_processes(self, artifact):
        pids = {r["pid"] for r in artifact["registrations"].values()}
        assert len(pids) == TINY.replicas
        assert os.getpid() not in pids

    def test_every_tenant_reached_a_real_replica(self, artifact):
        assert artifact["traffic"]["distinct_tenants"] == TINY.tenants
        assert artifact["traffic"]["errors"] == 0

    def test_kill_was_absorbed_from_scrape_evidence(self, artifact):
        kill = artifact["kill"]
        assert kill["recovery_cycles"] is not None
        assert kill["recovery_cycles"] <= TINY.recovery_limit
        victim_row = artifact["scrape"]["rows"][kill["victim"]]
        assert victim_row["healthy"] is False
        assert victim_row["scrape_error"] == "connect"

    def test_survivor_rows_carry_scrape_provenance(self, artifact):
        victim = artifact["kill"]["victim"]
        for name, row in artifact["scrape"]["rows"].items():
            if name == victim:
                continue
            assert row["pid"] == artifact["registrations"][name]["pid"]
            assert row["scrape_ms"] > 0
            assert "staleness_s" in row

    def test_federated_trace_spanned_real_processes(self, artifact):
        fed = artifact["federation"]
        lanes = fed["lanes"]
        assert lanes["client:fleet-drill"] == os.getpid()
        for name, pid in fed["replica_pids"].items():
            assert lanes[name] == pid
        assert len(set(lanes.values())) >= 3

    def test_artifact_written_and_replayable(self, artifact):
        path = artifact["artifact_path"]
        on_disk = json.load(open(path))
        assert on_disk["replay"] == build_replay_plan(TINY)


@pytest.mark.slow
def test_full_scale_drill():
    """The recorded acceptance run: 4 real replicas, 1000 tenants, the
    2x-single-process throughput floor, one mid-run SIGKILL."""
    with tempfile.TemporaryDirectory() as out:
        artifact = run_drill(FULL, out)
    assert artifact["passed"], json.dumps(
        {"criteria": artifact["criteria"],
         "violations": artifact["violations"]}, indent=2)
    floor = artifact["baseline"]["floor_solves_per_sec"]
    assert artifact["traffic"]["aggregate_solves_per_sec"] >= floor
