"""Shared cloud-backend contract suite (VERDICT r4 ask #4).

One suite, two drivers: the in-memory FakeCloud and the HTTP driver
(cloudbackend.HttpCloud -> CloudAPIServer -> FakeCloud). Green against
both is the proof that the L7 boundary is transport-agnostic — every
provider/batcher behavior above it exercises identical semantics whether
the backend is in-process or across a socket.

Reference parity: session bootstrap contract context.go:53-99 (region
discovery, connectivity dry-run, retryer); error taxonomy round-trip
errors.go:52-79.
"""

import pytest

from karpenter_tpu.cloudbackend import (CloudSession, ConnectivityError,
                                        HttpCloud, connect)
from karpenter_tpu.cloudbackend.server import CloudAPIServer
from karpenter_tpu.fake.cloud import (CreateFleetRequest, FakeCloud,
                                      FleetOverride, LaunchTemplate)
from karpenter_tpu.providers.instancetypes import generate_fleet_catalog
from karpenter_tpu.utils import errors as cloud_errors


@pytest.fixture(scope="module")
def catalog():
    return generate_fleet_catalog(max_types=40)


@pytest.fixture(params=["fake", "http"])
def cloud(request, catalog):
    backing = FakeCloud(catalog=catalog)
    if request.param == "fake":
        backing.backing = backing  # uniform access to the simulator state
        yield backing
        return
    server = CloudAPIServer(backing, region="us-test-1").start()
    try:
        client = connect(server.endpoint)
        client.backing = backing  # state seeding stays out-of-band (tests
        # poke ICE pools the way the reference pokes its fake EC2 directly)
        yield client
    finally:
        server.stop()


def _fleet_request(lt="lt-1", pools=(("a1.large", "zone-1a", 0.05),),
                   capacity=2, capacity_type="on-demand"):
    return CreateFleetRequest(
        launch_template=lt,
        overrides=[FleetOverride(instance_type=t, zone=z, price=p,
                                 subnet_id=f"subnet-{z}")
                   for t, z, p in pools],
        capacity=capacity, capacity_type=capacity_type,
        tags={"karpenter.sh/provisioner-name": "default"})


class TestContract:
    def test_fleet_launch_describe_terminate(self, cloud):
        cloud.create_launch_template(LaunchTemplate(name="lt-1",
                                                    image_id="img-amd64-2"))
        resp = cloud.create_fleet(_fleet_request())
        assert len(resp.instance_ids) == 2 and not resp.errors
        got = cloud.describe_instances(resp.instance_ids)
        assert {i.id for i in got} == set(resp.instance_ids)
        # the fake flips pending->running on describe (eventual-consistency
        # analogue); both states are live
        assert all(i.instance_type == "a1.large" and i.zone == "zone-1a"
                   and i.state in ("pending", "running") for i in got)
        assert all(i.tags["karpenter.sh/provisioner-name"] == "default"
                   for i in got)
        states = cloud.terminate_instances(resp.instance_ids)
        assert all(s == "terminated" for _, s in states)

    def test_fleet_ice_pool_skips_to_next_cheapest(self, cloud):
        # ICE seeding pokes the simulator state directly (the way the
        # reference seeds its fake EC2); the fleet call runs THROUGH the
        # driver under test
        cloud.backing.insufficient_capacity_pools.add(
            ("on-demand", "a1.large", "zone-1a"))
        cloud.create_launch_template(LaunchTemplate(name="lt-1",
                                                    image_id="img-amd64-2"))
        resp = cloud.create_fleet(_fleet_request(
            pools=(("a1.large", "zone-1a", 0.05),
                   ("a1.xlarge", "zone-1b", 0.10))))
        assert [e.code for e in resp.errors] == ["InsufficientInstanceCapacity"]
        assert all(i.startswith("i-") for i in resp.instance_ids)

    def test_launch_template_lifecycle_and_not_found(self, cloud):
        cloud.create_launch_template(LaunchTemplate(
            name="lt-x", image_id="img-amd64-1", tags={"owner": "karpenter"}))
        lts = cloud.describe_launch_templates("owner", "karpenter")
        assert [lt.name for lt in lts] == ["lt-x"]
        cloud.delete_launch_template("lt-x")
        with pytest.raises(cloud_errors.CloudError) as ei:
            cloud.delete_launch_template("lt-x")
        assert cloud_errors.is_launch_template_not_found(ei.value)

    def test_fleet_missing_launch_template_maps_to_taxonomy(self, cloud):
        with pytest.raises(cloud_errors.CloudError) as ei:
            cloud.create_fleet(_fleet_request(lt="lt-missing"))
        assert cloud_errors.is_launch_template_not_found(ei.value)

    def test_describe_instances_not_found(self, cloud):
        with pytest.raises(cloud_errors.CloudError) as ei:
            cloud.terminate_instances(["i-doesnotexist"])
        assert cloud_errors.is_not_found(ei.value)

    def test_discovery_and_prices(self, cloud):
        subnets = cloud.describe_subnets({"id": "subnet-zone-1a"})
        assert [s.zone for s in subnets] == ["zone-1a"]
        sgs = cloud.describe_security_groups(
            {"kubernetes.io/cluster/test-cluster": "owned"})
        assert [g.id for g in sgs] == ["sg-default"]
        images = cloud.describe_images({"id": "img-arm64-1"})
        assert [i.arch for i in images] == ["arm64"]
        assert cloud.get_ssm_parameter(
            "/karpenter-tpu/images/default/amd64/latest") == "img-amd64-2"
        with pytest.raises(cloud_errors.CloudError) as ei:
            cloud.get_ssm_parameter("/missing")
        assert cloud_errors.is_not_found(ei.value)
        prices = cloud.get_prices()
        assert prices[("a1.large", "on-demand", "zone-1a")] == pytest.approx(
            0.051)

    def test_tagging_round_trip(self, cloud):
        cloud.create_launch_template(LaunchTemplate(name="lt-1",
                                                    image_id="img-amd64-2"))
        resp = cloud.create_fleet(_fleet_request(capacity=1))
        iid = resp.instance_ids[0]
        cloud.create_tags(iid, {"Name": "karpenter-node"})
        got = cloud.describe_instances_by_tag("Name", "karpenter-node")
        assert [i.id for i in got] == [iid]


class TestHttpDriverSpecifics:
    """Wire-only behaviors: bootstrap, retries, fault mapping."""

    def test_ice_errors_cross_the_wire(self, catalog):
        backing = FakeCloud(catalog=catalog)
        backing.insufficient_capacity_pools.add(
            ("spot", "a1.large", "zone-1a"))
        backing.create_launch_template(LaunchTemplate(name="lt-1",
                                                      image_id="img-amd64-2"))
        server = CloudAPIServer(backing).start()
        try:
            cloud = connect(server.endpoint)
            resp = cloud.create_fleet(_fleet_request(
                pools=(("a1.large", "zone-1a", 0.02),), capacity_type="spot"))
            assert not resp.instance_ids
            assert [(e.code, e.instance_type, e.zone) for e in resp.errors] \
                == [("InsufficientInstanceCapacity", "a1.large", "zone-1a")]
            assert cloud_errors.is_unfulfillable_capacity(
                cloud_errors.CloudError(resp.errors[0].code))
        finally:
            server.stop()

    def test_session_discovers_region_from_metadata(self, catalog):
        server = CloudAPIServer(FakeCloud(catalog=catalog),
                                region="eu-test-9").start()
        try:
            sess = CloudSession(server.endpoint)
            assert sess.region == "eu-test-9"
        finally:
            server.stop()

    def test_session_explicit_region_skips_discovery(self, catalog):
        server = CloudAPIServer(FakeCloud(catalog=catalog)).start()
        try:
            assert CloudSession(server.endpoint,
                                region="us-explicit-1").region == "us-explicit-1"
        finally:
            server.stop()

    def test_connectivity_dry_run_fails_fast_when_unreachable(self):
        with pytest.raises(ConnectivityError):
            CloudSession("http://127.0.0.1:1", retries=0, timeout_s=0.5)

    def test_transient_500_retries_then_succeeds(self, catalog):
        backing = FakeCloud(catalog=catalog)
        backing.create_launch_template(LaunchTemplate(name="lt-1",
                                                      image_id="img-amd64-2"))
        server = CloudAPIServer(backing).start()
        try:
            cloud = connect(server.endpoint)
            server.fail_next_with(500, times=2)
            resp = cloud.create_fleet(_fleet_request(capacity=1))
            assert len(resp.instance_ids) == 1  # 2 injected faults < 3 retries
        finally:
            server.stop()

    def test_create_fleet_client_token_dedupes_replay(self, catalog):
        """A retried CreateFleet whose first attempt launched but lost the
        response must replay the recorded result, not double-launch."""
        import dataclasses

        backing = FakeCloud(catalog=catalog)
        backing.create_launch_template(LaunchTemplate(name="lt-1",
                                                      image_id="img-amd64-2"))
        server = CloudAPIServer(backing).start()
        try:
            cloud = connect(server.endpoint)
            payload = dataclasses.asdict(_fleet_request(capacity=2))
            payload["client_token"] = "tok-1"
            first = cloud.session.call("CreateFleet", payload)
            replay = cloud.session.call("CreateFleet", payload)  # same token
            assert replay["instance_ids"] == first["instance_ids"]
            assert len(backing.instances) == 2  # no second launch
        finally:
            server.stop()

    def test_structured_4xx_resolves_half_open_probe(self, catalog):
        """A rehydrated business error IS a live server: it must judge the
        half-open probe as a success, not leave it in flight — an unjudged
        probe would reject every future call on the shared cloud edge
        forever (no timeout escape from HALF_OPEN)."""
        from karpenter_tpu.metrics import Registry
        from karpenter_tpu.resilience import CircuitBreaker, RetryPolicy
        from karpenter_tpu.utils.clock import FakeClock

        server = CloudAPIServer(FakeCloud(catalog=catalog)).start()
        try:
            clock = FakeClock()
            reg = Registry()
            br = CircuitBreaker("cloud", clock=clock, failure_threshold=1,
                                recovery_time=30.0, success_threshold=1,
                                registry=reg)
            pol = RetryPolicy("cloud", clock=clock, breaker=br,
                              registry=reg, sleep=lambda s: None)
            cloud = connect(server.endpoint, policy=pol)
            br.record_failure()  # cloud edge trips open
            clock.step(30.0)     # recovery window elapses
            with pytest.raises(cloud_errors.CloudError) as ei:
                cloud.terminate_instances(["i-missing"])  # the probe call
            assert cloud_errors.is_not_found(ei.value)
            assert br.state() == "closed"  # probe judged: server is live
            assert cloud.describe_instances([]) == []  # edge serves again
        finally:
            server.stop()

    def test_retries_exhausted_raises_connectivity(self, catalog):
        server = CloudAPIServer(FakeCloud(catalog=catalog)).start()
        try:
            cloud = connect(server.endpoint)
            server.fail_next_with(500, times=10)
            with pytest.raises(ConnectivityError):
                cloud.describe_instances(["i-1"])
        finally:
            server.stop()

    def test_full_operator_over_the_wire_cloud(self):
        """The strongest drop-in proof: the ENTIRE controller plane —
        provisioning batchers, machine lifecycle, GC, termination — runs
        with HttpCloud as its cloud object, so every CreateFleet /
        DescribeInstances / launch-template call the framework makes
        crosses a real socket and the error taxonomy round-trips."""
        from karpenter_tpu.apis.nodetemplate import NodeTemplate
        from karpenter_tpu.apis.provisioner import Provisioner
        from karpenter_tpu.apis.settings import Settings
        from karpenter_tpu.models.pod import make_pod
        from karpenter_tpu.operator import Operator

        full_catalog = generate_fleet_catalog(max_types=60)
        backing = FakeCloud(catalog=full_catalog)
        server = CloudAPIServer(backing).start()
        op = None
        try:
            cloud = connect(server.endpoint)
            settings = Settings(cluster_name="wirecloud",
                                cluster_endpoint="https://k.example",
                                batch_idle_duration=0.0,
                                batch_max_duration=0.0)
            op = Operator(cloud, settings, full_catalog)
            op.kube.create("nodetemplates", "default", NodeTemplate(
                name="default",
                subnet_selector={"id": "subnet-zone-1a"},
                security_group_selector={"id": "sg-default"}))
            op.cloudprovider.register_nodetemplate(
                op.kube.get("nodetemplates", "default"))
            prov = Provisioner(name="default", provider_ref="default")
            prov.set_defaults()
            op.kube.create("provisioners", "default", prov)
            for i in range(12):
                op.kube.create("pods", f"p{i}",
                               make_pod(f"p{i}", cpu="1", memory="2Gi"))
            op.provisioning.reconcile_once()
            # machines were launched THROUGH the wire into the backing sim
            assert backing.instances, "no instances created over the wire"
            assert len(op.kube.pending_pods()) == 0
            assert len(op.cluster.nodes) >= 1
            # termination crosses the wire too
            for node in list(op.cluster.nodes.values()):
                node.pods.clear()
                op.termination.request_deletion(node.name)
            op.termination.reconcile_once()
            assert all(i.state == "terminated"
                       for i in backing.instances.values())
        finally:
            if op is not None:
                op.stop()
            server.stop()

    def test_providers_run_over_the_wire(self, catalog):
        """Drop-in proof: the resource providers run unmodified against
        HttpCloud."""
        from karpenter_tpu.providers.securitygroup import SecurityGroupProvider
        from karpenter_tpu.providers.subnet import SubnetProvider

        server = CloudAPIServer(FakeCloud(catalog=catalog)).start()
        try:
            cloud = connect(server.endpoint)
            subnets = SubnetProvider(cloud).list({"id": "subnet-zone-1b"})
            assert [s.zone for s in subnets] == ["zone-1b"]
            sgs = SecurityGroupProvider(cloud).list(
                {"kubernetes.io/cluster/test-cluster": "owned"})
            assert [g.id for g in sgs] == ["sg-default"]
        finally:
            server.stop()
