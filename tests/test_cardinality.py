"""Cardinality guard edge cases: the top-K tenant sketch must bound every
guarded family at K+1 series (K exact tenants + the `_other` rollup) with
no observation lost or double-counted across evictions, no matter how
adversarial the tenant id stream — including a tenant literally named
"_other" and a sketch of width one."""

import random

import pytest

from karpenter_tpu.metrics import Counter, Gauge, Histogram
from karpenter_tpu.metrics.cardinality import (
    DEFAULT_K,
    K_ENV,
    OTHER,
    CardinalityGuard,
    TenantTracker,
    escape,
    top_k_default,
)


def _counter_sum(c):
    with c._lock:
        return sum(c._values.values())


class TestTracker:
    def test_space_saving_admission(self):
        t = TenantTracker(k=2)
        assert t.offer("a") == ("a", None)
        assert t.offer("b") == ("b", None)
        assert t.offer("a") == ("a", None)  # tracked: plain increment
        # full sketch: "c" displaces the min-count entry ("b")
        key, evicted = t.offer("c")
        assert (key, evicted) == ("c", "b")
        # space-saving: the newcomer inherits the victim's count as floor
        assert t.tracked()["c"] == 2.0
        assert t.table()[0]["tenant"] in ("a", "c")
        assert t.evictions == 1 and t.offers == 4

    def test_eviction_tie_breaks_deterministically(self):
        t = TenantTracker(k=2)
        t.offer("b")
        t.offer("a")  # both count 1: victim is the lexicographic min
        _, evicted = t.offer("z")
        assert evicted == "a"

    def test_k_one_tracks_exactly_the_last_offered(self):
        t = TenantTracker(k=1)
        t.offer("a")
        _, evicted = t.offer("b")
        assert evicted == "a"
        assert set(t.tracked()) == {"b"}
        # a heavy hitter stays resident once its count dominates
        for _ in range(10):
            t.offer("hot")
        assert "hot" in t and len(t.tracked()) == 1

    def test_table_error_bounds(self):
        t = TenantTracker(k=1)
        for _ in range(5):
            t.offer("a")
        t.offer("b")  # count = 5 (floor) + 1, error = 5
        (row,) = t.table()
        assert row == {"tenant": "b", "count": 6.0, "error": 5.0}
        # count is an upper bound, count - error a lower bound on truth
        assert row["count"] - row["error"] == 1.0


class TestEscape:
    def test_other_collision_is_impossible(self):
        # a tenant literally named "_other" can never alias the rollup
        assert escape("_other") == "__other"
        assert escape("__other") == "___other"
        assert escape("t1") == "t1"
        # injective on the underscore-prefixed namespace
        ids = ["_other", "__other", "_x", "x", "other"]
        assert len({escape(i) for i in ids}) == len(ids)

    def test_guard_keeps_impostor_distinct_from_rollup(self):
        g = CardinalityGuard(k=1)
        c = g.watch(Counter("imp_total", label_names=("tenant",)))
        assert g.label("_other") == "__other"
        c.inc(tenant="__other")
        # evicting the impostor folds it into the REAL rollup; the two
        # never shared a series
        g.label("real")
        c.inc(tenant="real")
        assert g.series_values(c) == {OTHER, "real"}
        assert c.value(tenant=OTHER) == 1.0

    def test_empty_id_goes_straight_to_rollup(self):
        g = CardinalityGuard(k=4)
        assert g.label("") == OTHER
        assert g.peek("") == OTHER
        assert g.tracker.offers == 0  # the rollup is not sketch traffic


class TestFolding:
    def _guard(self, k=2):
        g = CardinalityGuard(k=k)
        c = g.watch(Counter("fold_total", label_names=("tenant", "where")))
        h = g.watch(Histogram("fold_seconds", label_names=("tenant",),
                              buckets=(0.1, 1.0)))
        ga = g.watch(Gauge("fold_depth", label_names=("tenant",)))
        return g, c, h, ga

    def test_eviction_folds_counter_without_double_counting(self):
        g, c, h, ga = self._guard(k=2)
        for tid, n in (("a", 3), ("b", 2)):
            for _ in range(n):
                c.inc(tenant=g.label(tid), where="q")
        before = _counter_sum(c)
        # "z" evicts "b" (min count); b's series must fold into _other
        tl = g.label("z")
        c.inc(tenant=tl, where="q")
        assert _counter_sum(c) == before + 1  # nothing lost, nothing doubled
        assert c.value(tenant=OTHER, where="q") == 2.0
        assert g.series_values(c) == {"a", "z", OTHER}

    def test_eviction_merges_histogram_buckets_sums_totals(self):
        g, c, h, ga = self._guard(k=2)
        h.observe(0.05, tenant=g.label("a"))
        h.observe(0.5, tenant=g.label("b"))
        h.observe(2.0, tenant=g.label("b"))
        g.label("z")  # evicts the lighter of a/b -> folds its series
        with h._lock:
            total = sum(h._totals.values())
            ssum = sum(h._sums.values())
        assert total == 3  # observation count preserved across the fold
        assert ssum == pytest.approx(2.55)
        assert len(g.series_values(h)) <= g.k + 1
        # the rollup inherited cumulative bucket counts, not raw values
        with h._lock:
            assert (OTHER,) in h._totals

    def test_eviction_drops_gauge_series(self):
        g, c, h, ga = self._guard(k=1)
        ga.set(7.0, tenant=g.label("a"))
        g.label("b")  # evicts a: last-write gauges drop, never sum
        assert g.series_values(ga) == set()
        assert g.folded == 1

    def test_fold_preserves_other_labels(self):
        g, c, h, ga = self._guard(k=1)
        t = g.label("a")
        c.inc(tenant=t, where="admission")
        c.inc(tenant=t, where="queue")
        g.label("b")
        assert c.value(tenant=OTHER, where="admission") == 1.0
        assert c.value(tenant=OTHER, where="queue") == 1.0

    def test_peek_never_inflates_the_sketch(self):
        g, c, h, ga = self._guard(k=2)
        g.label("a")
        offers = g.tracker.offers
        assert g.peek("a") == "a"
        assert g.peek("stranger") == OTHER
        assert g.tracker.offers == offers

    def test_watch_rejects_unlabeled_family(self):
        g = CardinalityGuard(k=2)
        with pytest.raises(ValueError, match="no 'tenant' label"):
            g.watch(Counter("bare_total", label_names=("where",)))


class TestSeriesBoundProperty:
    def test_10k_random_tenants_stay_within_k_plus_one(self):
        """Property: after 10k observations over a heavy-tailed random id
        stream, every guarded family holds <= K+1 tenant values and no
        counter increment was lost."""
        rng = random.Random(0xC0FFEE)
        g = CardinalityGuard(k=8)
        c = g.watch(Counter("prop_total", label_names=("tenant",)))
        h = g.watch(Histogram("prop_seconds", label_names=("tenant",),
                              buckets=(0.1, 1.0)))
        ids = [f"tenant-{rng.randrange(10_000)}" for _ in range(5_000)]
        ids += [f"hot-{rng.randrange(4)}" for _ in range(5_000)]
        rng.shuffle(ids)
        for tid in ids:
            t = g.label(tid)
            c.inc(tenant=t)
            h.observe(0.01, tenant=t)
        snap = g.snapshot()
        assert snap["offers"] == 10_000
        for name, n in snap["series_per_family"].items():
            assert n <= g.k + 1, (name, n)
        assert _counter_sum(c) == 10_000  # folds never lose increments
        with h._lock:
            assert sum(h._totals.values()) == 10_000
        # the heavy hitters survive the churn (true freq ~1250 >> N/K)
        tracked = set(g.tracker.tracked())
        assert {f"hot-{i}" for i in range(4)} <= tracked


class TestEnvKnob:
    def test_default_and_validation(self, monkeypatch):
        monkeypatch.delenv(K_ENV, raising=False)
        assert top_k_default() == DEFAULT_K
        monkeypatch.setenv(K_ENV, "7")
        assert top_k_default() == 7
        monkeypatch.setenv(K_ENV, "banana")
        assert top_k_default() == DEFAULT_K  # warn + fall back
        monkeypatch.setenv(K_ENV, "0")
        assert top_k_default() == 1  # clamp: zero-width sketch impossible
        monkeypatch.setenv(K_ENV, "-3")
        assert top_k_default() == 1
