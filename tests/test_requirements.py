import pytest

from karpenter_tpu.models.requirements import (
    IncompatibleError, Requirement, Requirements,
    OP_DOES_NOT_EXIST, OP_EXISTS, OP_GT, OP_IN, OP_LT, OP_NOT_IN,
)


def req(key, op, *values):
    return Requirement.create(key, op, values)


class TestRequirement:
    def test_in(self):
        r = req("arch", OP_IN, "amd64", "arm64")
        assert r.has("amd64") and r.has("arm64") and not r.has("s390x")
        assert not r.allows_absent()

    def test_not_in(self):
        r = req("zone", OP_NOT_IN, "zone-1a")
        assert not r.has("zone-1a") and r.has("zone-1b")
        assert r.allows_absent()

    def test_exists(self):
        r = req("gpu", OP_EXISTS)
        assert r.has("anything")
        assert not r.allows_absent()

    def test_does_not_exist(self):
        r = req("gpu", OP_DOES_NOT_EXIST)
        assert not r.has("anything")
        assert r.allows_absent()

    def test_gt_lt(self):
        r = req("cpu", OP_GT, "4")
        assert r.has("8") and not r.has("4") and not r.has("2") and not r.has("x")
        r2 = req("cpu", OP_LT, "16")
        both = r.intersect(r2)
        assert both.has("8") and not both.has("16") and not both.has("4")

    def test_intersect_in_in(self):
        a = req("k", OP_IN, "a", "b")
        b = req("k", OP_IN, "b", "c")
        assert a.intersect(b).values == frozenset({"b"})

    def test_intersect_in_notin(self):
        a = req("k", OP_IN, "a", "b")
        b = req("k", OP_NOT_IN, "b")
        assert a.intersect(b).values == frozenset({"a"})

    def test_intersect_empty_raises(self):
        with pytest.raises(IncompatibleError):
            req("k", OP_IN, "a").intersect(req("k", OP_IN, "b"))

    def test_gt_lt_empty(self):
        with pytest.raises(IncompatibleError):
            req("k", OP_GT, "4").intersect(req("k", OP_LT, "5"))

    def test_doesnotexist_vs_in(self):
        with pytest.raises(IncompatibleError):
            req("k", OP_DOES_NOT_EXIST).intersect(req("k", OP_IN, "a"))
        # NotIn tolerates absence -> compatible, result stays forbid-key
        out = req("k", OP_DOES_NOT_EXIST).intersect(req("k", OP_NOT_IN, "a"))
        assert out.forbid_key


class TestRequirements:
    def test_matches_labels(self):
        r = Requirements.of(("arch", OP_IN, ["amd64"]), ("gpu", OP_DOES_NOT_EXIST))
        assert r.matches_labels({"arch": "amd64"})
        assert not r.matches_labels({"arch": "arm64"})
        assert not r.matches_labels({"arch": "amd64", "gpu": "1"})

    def test_missing_key_semantics(self):
        assert not Requirements.of(("k", OP_IN, ["v"])).matches_labels({})
        assert Requirements.of(("k", OP_NOT_IN, ["v"])).matches_labels({})
        assert not Requirements.of(("k", OP_EXISTS, [])).matches_labels({})

    def test_union_tightens(self):
        a = Requirements.of(("zone", OP_IN, ["z1", "z2"]))
        b = Requirements.of(("zone", OP_IN, ["z2", "z3"]))
        u = a.union(b)
        assert u.get("zone").values == frozenset({"z2"})

    def test_union_incompatible(self):
        a = Requirements.of(("zone", OP_IN, ["z1"]))
        b = Requirements.of(("zone", OP_IN, ["z2"]))
        with pytest.raises(IncompatibleError):
            a.union(b)

    def test_compatible(self):
        a = Requirements.of(("zone", OP_IN, ["z1", "z2"]))
        b = Requirements.of(("zone", OP_NOT_IN, ["z1"]))
        assert a.compatible(b)
        c = Requirements.of(("zone", OP_IN, ["z3"]))
        assert not a.compatible(c)
        assert a.compatible(Requirements())

    def test_from_node_selector(self):
        r = Requirements.from_node_selector({"a": "1", "b": "2"})
        assert r.matches_labels({"a": "1", "b": "2", "c": "3"})
        assert not r.matches_labels({"a": "1"})

    def test_to_specs_roundtrip(self):
        specs = [("a", OP_IN, ["x"]), ("b", OP_NOT_IN, ["y"]), ("c", OP_EXISTS, []),
                 ("d", OP_DOES_NOT_EXIST, []), ("e", OP_GT, ["3"])]
        r = Requirements()
        for k, op, vals in specs:
            r.add(Requirement.create(k, op, vals))
        assert sorted(r.to_specs()) == sorted(specs)


def test_to_specs_combined_bounds_canonical():
    # merged Gt+Lt must emit BOTH bounds (group-dedupe canonicality)
    a = Requirements()
    a.add(Requirement.create("cpu", OP_GT, ["1"]))
    a.add(Requirement.create("cpu", OP_LT, ["4"]))
    b = Requirements()
    b.add(Requirement.create("cpu", OP_GT, ["1"]))
    b.add(Requirement.create("cpu", OP_LT, ["100"]))
    assert a.to_specs() != b.to_specs()
    assert ("cpu", OP_GT, ["1"]) in a.to_specs() and ("cpu", OP_LT, ["4"]) in a.to_specs()


def test_to_specs_in_with_bounds_folds():
    r = Requirements()
    r.add(Requirement.create("cpu", OP_IN, ["2", "4", "8"]))
    r.add(Requirement.create("cpu", OP_GT, ["3"]))
    assert r.to_specs() == [("cpu", OP_IN, ["4", "8"])]


def test_exists_intersect_notin_keeps_presence():
    r = Requirements()
    r.add(Requirement.create("k", OP_EXISTS, []))
    r.add(Requirement.create("k", OP_NOT_IN, ["x"]))
    assert not r.matches_labels({})            # presence still required
    assert r.matches_labels({"k": "y"})
    assert not r.matches_labels({"k": "x"})
    specs = r.to_specs()
    assert ("k", OP_EXISTS, []) in specs and ("k", OP_NOT_IN, ["x"]) in specs


class TestCanonicalFreeze:
    """Copy-on-write contract: once a Requirements is published into a hash /
    group key, in-place mutation is refused (stale-memo guard)."""

    def test_eq_hash_spec_level(self):
        import karpenter_tpu.apis.wellknown as wk
        a = Requirements.of((wk.LABEL_ZONE, OP_IN, ["z2", "z1"]))
        b = Requirements.of((wk.LABEL_ZONE, OP_IN, ["z1", "z2"]))
        assert a == b and hash(a) == hash(b)

    def test_mutation_after_hash_raises(self):
        import pytest
        r = Requirements.of(("k", OP_IN, ["v"]))
        hash(r)
        with pytest.raises(RuntimeError):
            r.add(Requirement.create("k2", OP_IN, ["w"]))
        assert r.copy() is not r
        r.copy().add(Requirement.create("k2", OP_IN, ["w"]))  # copy unfrozen

    def test_group_key_freezes_pod_requirements(self):
        import pytest
        from karpenter_tpu.models.pod import make_pod
        p = make_pod("p", cpu="1", memory="1Gi", node_selector={"a": "b"})
        k1 = p.group_key()
        with pytest.raises(RuntimeError):
            p.requirements.add(Requirement.create("c", OP_IN, ["d"]))
        assert p.group_key() == k1

    def test_group_token_matches_group_key_equality(self):
        from karpenter_tpu.models import pod as pod_mod
        from karpenter_tpu.models.pod import group_pods, make_pod

        a1 = make_pod("a1", cpu="1", memory="1Gi")
        a2 = make_pod("a2", cpu="1", memory="1Gi")
        b = make_pod("b", cpu="2", memory="1Gi")
        assert a1.group_token() == a2.group_token()  # equal keys, one token
        assert a1.group_token() != b.group_token()
        groups = group_pods([a1, a2, b])
        assert sorted((g.count for g in groups)) == [1, 2]
        # a table clear bumps the epoch: stamped tokens are re-interned, so
        # equal-key specs from before and after the clear still land in ONE
        # group (group_pods stays a pure function of the pod list — the
        # solver wire protocol's client/server grouping must agree)
        with pod_mod._group_key_lock:
            pod_mod._group_key_tokens.clear()
            pod_mod._group_key_epoch += 1
        a3 = make_pod("a3", cpu="1", memory="1Gi")
        regrouped = group_pods([a1, a2, a3, b])
        assert sorted(g.count for g in regrouped) == [1, 3]
        assert a1.group_token() == a3.group_token()
        # and tokens are never numerically reused across epochs
        assert a1.group_token() != b.group_token()
