"""Wire-compat tier against a kube-apiserver THIS REPO DID NOT WRITE.

Self-authored client <-> self-authored server (fake/apiserver.py) can share
a bug invisibly — field casing, watch semantics, CAS on status. This tier
boots a real `kube-apiserver` + `etcd` (the envtest control plane,
fetched by hack/fetch_envtest.sh), applies the deploy/ CRDs and the
quickstart manifests through plain HTTP, and drives the full controller
plane through HttpKubeStore until a kubectl-authored pod is BOUND — the
same done-criterion as the mini-apiserver e2e (test_httpkube.py), now with
a foreign server on the other side of the socket.

Reference analogue: the envtest tier of
/root/reference/pkg/cloudprovider/suite_test.go:74-101 (a *real*
kube-apiserver binary under the unit suite).

Skips cleanly when the binaries are absent (zero-egress environments):
run `hack/fetch_envtest.sh` or point KUBEBUILDER_ASSETS at them.
"""

import json
import os
import shutil
import socket
import ssl
import subprocess
import time
import urllib.error
import urllib.request

import pytest
import yaml

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TOKEN = "envtest-token"


def _assets_dir():
    for cand in (os.environ.get("KUBEBUILDER_ASSETS"),
                 os.path.join(REPO, "hack", "bin", "envtest")):
        if cand and os.path.isfile(os.path.join(cand, "kube-apiserver")) \
                and os.path.isfile(os.path.join(cand, "etcd")):
            return cand
    return None


ASSETS = _assets_dir()
pytestmark = pytest.mark.skipif(
    ASSETS is None,
    reason="envtest binaries not present (hack/fetch_envtest.sh; offline "
           "environments skip the foreign-apiserver tier)")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _request(base, path, method="GET", doc=None, timeout=10):
    req = urllib.request.Request(
        base + path,
        None if doc is None else json.dumps(doc).encode(),
        {"Content-Type": "application/json",
         "Authorization": f"Bearer {TOKEN}"},
        method=method)
    ctx = ssl._create_unverified_context()
    with urllib.request.urlopen(req, timeout=timeout, context=ctx) as resp:
        return json.loads(resp.read() or b"{}")


@pytest.fixture(scope="module")
def apiserver(tmp_path_factory):
    """etcd + kube-apiserver on loopback, torn down at module end."""
    tmp = tmp_path_factory.mktemp("envtest")
    etcd_port, peer_port, api_port = _free_port(), _free_port(), _free_port()

    etcd = subprocess.Popen(
        [os.path.join(ASSETS, "etcd"),
         "--data-dir", str(tmp / "etcd"),
         "--listen-client-urls", f"http://127.0.0.1:{etcd_port}",
         "--advertise-client-urls", f"http://127.0.0.1:{etcd_port}",
         "--listen-peer-urls", f"http://127.0.0.1:{peer_port}"],
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)

    # service-account keypair + static token the test authenticates with
    sa_key, sa_pub = str(tmp / "sa.key"), str(tmp / "sa.pub")
    subprocess.run(["openssl", "genrsa", "-out", sa_key, "2048"],
                   check=True, capture_output=True)
    subprocess.run(["openssl", "rsa", "-in", sa_key, "-pubout", "-out",
                    sa_pub], check=True, capture_output=True)
    tokens = tmp / "tokens.csv"
    tokens.write_text(f"{TOKEN},envtest,envtest-uid,system:masters\n")

    apiserver = subprocess.Popen(
        [os.path.join(ASSETS, "kube-apiserver"),
         "--etcd-servers", f"http://127.0.0.1:{etcd_port}",
         "--secure-port", str(api_port),
         "--bind-address", "127.0.0.1",
         "--cert-dir", str(tmp / "certs"),
         "--service-account-issuer", "https://karpenter-tpu.envtest",
         "--service-account-key-file", sa_pub,
         "--service-account-signing-key-file", sa_key,
         "--token-auth-file", str(tokens),
         "--authorization-mode", "AlwaysAllow",
         "--disable-admission-plugins", "ServiceAccount",
         "--allow-privileged=true"],
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)

    base = f"https://127.0.0.1:{api_port}"
    try:
        deadline = time.time() + 120
        last = None
        while time.time() < deadline:
            if etcd.poll() is not None or apiserver.poll() is not None:
                raise RuntimeError("control plane process exited early")
            try:
                _request(base, "/readyz", timeout=3)
                break
            except (urllib.error.URLError, OSError) as e:
                last = e
                time.sleep(1)
        else:
            raise RuntimeError(f"kube-apiserver never became ready: {last}")
        yield base
    finally:
        apiserver.terminate()
        etcd.terminate()
        apiserver.wait(timeout=30)
        etcd.wait(timeout=30)
        shutil.rmtree(tmp, ignore_errors=True)


def _apply_crds(base):
    applied = set()
    for name in sorted(os.listdir(os.path.join(REPO, "deploy", "crds"))):
        doc = yaml.safe_load(open(os.path.join(REPO, "deploy", "crds", name)))
        applied.add(doc["metadata"]["name"])
        try:
            _request(base, "/apis/apiextensions.k8s.io/v1/"
                     "customresourcedefinitions", method="POST", doc=doc)
        except urllib.error.HTTPError as e:
            if e.code != 409:  # already applied by a previous test
                raise
    # wait until every CRD we applied reports Established — the real
    # apiserver takes a beat to serve new groups
    deadline = time.time() + 60
    while time.time() < deadline:
        ok = set()
        listing = _request(base, "/apis/apiextensions.k8s.io/v1/"
                           "customresourcedefinitions")
        for item in listing.get("items", []):
            conds = {c["type"]: c["status"]
                     for c in item.get("status", {}).get("conditions", [])}
            if conds.get("Established") == "True":
                ok.add(item["metadata"]["name"])
        if applied <= ok:
            return
        time.sleep(1)
    raise RuntimeError("CRDs never became Established")


def test_kubectl_authored_pod_schedules_against_foreign_apiserver(apiserver):
    from karpenter_tpu.apis.settings import Settings
    from karpenter_tpu.coordination.httpkube import HttpKubeStore
    from karpenter_tpu.fake.cloud import FakeCloud
    from karpenter_tpu.models.instancetype import Catalog, make_instance_type
    from karpenter_tpu.operator import Operator

    base = apiserver
    _apply_crds(base)

    bundle = open(os.path.join(REPO, "examples", "quickstart.yaml")).read() \
        .replace("${CLUSTER_NAME}", "foreign-test")
    for doc in yaml.safe_load_all(bundle):
        if not doc:
            continue
        if doc["kind"] == "Provisioner":
            _request(base, "/apis/karpenter.sh/v1alpha5/provisioners",
                     method="POST", doc=doc)
        elif doc["kind"] == "NodeTemplate":
            _request(base, "/apis/karpenter.k8s.tpu/v1alpha1/nodetemplates",
                     method="POST", doc=doc)
    _request(base, "/api/v1/namespaces/default/pods", method="POST", doc={
        "apiVersion": "v1", "kind": "Pod",
        "metadata": {"name": "web-0", "labels": {"app": "web"}},
        "spec": {"containers": [{
            "name": "c", "image": "registry.example/pause:3.2",
            "resources": {"requests": {"cpu": "1", "memory": "1Gi"}},
        }]},
    })

    cat = Catalog(types=[make_instance_type(
        "m.large", cpu=4, memory="16Gi", od_price=0.20, spot_price=0.07)])
    cloud = FakeCloud(cat)
    for s in cloud.subnets:
        s.tags.setdefault("karpenter.sh/discovery", "foreign-test")
    for g in cloud.security_groups:
        g.tags.setdefault("karpenter.sh/discovery", "foreign-test")

    kube = HttpKubeStore(base, token=TOKEN, verify_tls=False)
    kube.start()
    op = None
    try:
        assert [p.name for p in kube.provisioners()] == ["default"]
        assert [p.name for p in kube.pending_pods()] == ["web-0"]
        settings = Settings(cluster_name="foreign-test",
                            cluster_endpoint="https://foreign",
                            batch_idle_duration=0.0, batch_max_duration=0.0)
        op = Operator(cloud, settings, cat, kube=kube)
        op.reconcile_all_once()

        # server-side truth from the FOREIGN apiserver, not our cache
        pod_doc = _request(base, "/api/v1/namespaces/default/pods/web-0")
        assert pod_doc["spec"].get("nodeName"), "pod not bound server-side"
        machines = _request(base, "/apis/karpenter.sh/v1alpha5/machines")
        assert machines.get("items"), "no machine object on the server"
        # the exact-model embedding must survive real-apiserver pruning
        # (machines CRD preserves unknown fields at the root)
        assert any("x-karpenter-model" in m for m in machines["items"]), \
            "embedded model pruned — machine round-trip is lossy"
        nodes = _request(base, "/api/v1/nodes")
        node_names = {n["metadata"]["name"] for n in nodes.get("items", [])}
        assert pod_doc["spec"]["nodeName"] in node_names
        # counters + spec fidelity on the foreign server: status.resources
        # present AND the user-authored spec survived our status writes
        prov_doc = _request(base,
                            "/apis/karpenter.sh/v1alpha5/provisioners/default")
        res = (prov_doc.get("status") or {}).get("resources") or {}
        assert res.get("nodes") not in (None, "0"), prov_doc.get("status")
        assert prov_doc.get("spec", {}).get("requirements"), \
            "user spec blanked by a status write"
    finally:
        if op is not None:
            op.stop()
        kube.stop()
