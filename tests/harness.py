"""E2E scenario harness: the reference's polling Monitor + expectation
helpers (test/pkg/environment/common/monitor.go:36-145 and
expectations.go), adapted to the hermetic and threaded operators.

Scenario tests drive the operator, then assert through these helpers
instead of raw store reads — the same vocabulary the reference suites use
(ExpectCreatedNodeCount, EventuallyExpectHealthyPodCount, ...).
"""

from __future__ import annotations

import time


class Monitor:
    """Tracks node/pod population deltas from a reset point."""

    def __init__(self, op):
        self.op = op
        self.reset()

    def reset(self) -> None:
        self._nodes_at_reset = set(self.op.cluster.nodes)
        self._nodes_ever_seen = set(self.op.cluster.nodes)
        self._pods_at_reset = {p.name for p in self.op.kube.pods()}

    def _observe(self) -> "set[str]":
        current = set(self.op.cluster.nodes)
        self._nodes_ever_seen |= current
        return current

    # -- counts ----------------------------------------------------------------

    def created_node_count(self) -> int:
        return len(self._observe() - self._nodes_at_reset)

    def deleted_node_count(self) -> int:
        # every node observed since reset that is gone now (the reference
        # Monitor counts deletions off the watch stream; polling keeps a
        # running ever-seen set instead)
        return len(self._nodes_ever_seen - self._observe())

    def node_count(self) -> int:
        return len(self.op.cluster.nodes)

    def pending_pod_count(self) -> int:
        return len(self.op.kube.pending_pods())

    def bound_pod_count(self) -> int:
        return sum(1 for p in self.op.kube.pods()
                   if p.node_name and not p.is_daemon())

    def restarted_pod_count(self) -> int:
        """Pods recreated since reset (same name, delete+create churn)."""
        current = {p.name for p in self.op.kube.pods()}
        return len(current & self._pods_at_reset)

    # -- expectations ----------------------------------------------------------

    def expect_created_node_count(self, op: str, n: int) -> None:
        """ExpectCreatedNodeCount analogue: '==', '<=', '>=' against the
        nodes created since reset."""
        got = self.created_node_count()
        ok = {"==": got == n, "<=": got <= n, ">=": got >= n}[op]
        assert ok, f"created nodes: expected {op} {n}, got {got}"

    def expect_healthy_pod_count(self, n: int) -> None:
        got = self.bound_pod_count()
        assert got == n, f"bound pods: expected {n}, got {got}"

    def eventually(self, predicate, timeout_s: float = 15.0,
                   interval_s: float = 0.05, message: str = "") -> None:
        """EventuallyExpect* analogue for the threaded operator (real
        clock); hermetic tests drive reconciles directly and use the
        synchronous expectations instead."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if predicate():
                return
            time.sleep(interval_s)
        raise AssertionError(message or "condition never became true")

    def eventually_expect_healthy_pod_count(self, n: int,
                                            timeout_s: float = 15.0) -> None:
        self.eventually(lambda: self.bound_pod_count() == n,
                        timeout_s=timeout_s,
                        message=f"never reached {n} bound pods "
                                f"(at {self.bound_pod_count()})")
