"""Profiling plane (karpenter_tpu/profiling): gap-ledger accounting laws,
roofline monotonicity, the continuous profiler's lifecycle and CPU fallback
parity, the strict-noop contract, and the /debug/profilez endpoint."""

import importlib.util
import json
import time
import urllib.error
import urllib.request
from pathlib import Path

import pytest

from karpenter_tpu import profiling
from karpenter_tpu.apis.provisioner import Provisioner
from karpenter_tpu.apis.settings import Settings
from karpenter_tpu.fake.cloud import FakeCloud
from karpenter_tpu.models.instancetype import Catalog, make_instance_type
from karpenter_tpu.operator import Operator
from karpenter_tpu.profiling import (GAP_LEDGER, PHASE_NAMES, PROFILER,
                                     continuous, roofline)
from karpenter_tpu.solver.core import TPUSolver
from karpenter_tpu.utils.clock import FakeClock


@pytest.fixture(autouse=True)
def _clean_profiling():
    """Plane ON and an empty gap ring around every test; restore after."""
    prev = profiling.set_enabled(True)
    GAP_LEDGER.clear()
    yield
    GAP_LEDGER.clear()
    profiling.set_enabled(prev)


@pytest.fixture(scope="module")
def small_solver():
    """One compiled small solver shared across the module (compile once)."""
    cat = Catalog(types=[
        make_instance_type("small.2x", cpu=2, memory="8Gi", od_price=0.10),
        make_instance_type("large.8x", cpu=8, memory="32Gi", od_price=0.40),
    ])
    prov = Provisioner(name="default")
    prov.set_defaults()
    solver = TPUSolver(cat, [prov])
    from karpenter_tpu.models.pod import make_pod
    pods = [make_pod(f"p{i}", cpu="250m", memory="512Mi") for i in range(12)]
    solver.solve(pods)  # compile outside the measured tests
    return solver, pods


# -- gap ledger accounting laws ----------------------------------------------------


class TestGapLedger:
    def test_phases_sum_to_wall_within_tolerance(self):
        with GAP_LEDGER.solve_scope("test"):
            t0 = time.perf_counter()
            time.sleep(0.005)
            GAP_LEDGER.note("encode", time.perf_counter() - t0)
            t1 = time.perf_counter()
            time.sleep(0.003)
            GAP_LEDGER.note("device_exec", time.perf_counter() - t1)
        row = GAP_LEDGER.rows()[-1]
        assert row["source"] == "test"
        # attributed + residue is the wall by construction...
        assert row["attributed_ms"] + row["unaccounted_ms"] == pytest.approx(
            row["wall_ms"], abs=0.01)
        # ...and the back-to-back notes cover nearly all of it
        assert row["attributed_share"] > 0.9
        assert row["attributed_share"] + row["unaccounted_share"] == (
            pytest.approx(1.0, abs=1e-6))

    def test_residue_never_negative_under_clock_skew(self):
        # a phase note LARGER than the wall (cross-thread clock skew, or a
        # nested layer double-filing) must clamp the residue to zero, not
        # go negative — shares still sum to exactly 1
        with GAP_LEDGER.solve_scope("skew"):
            GAP_LEDGER.note("encode", 10.0)
        row = GAP_LEDGER.rows()[-1]
        assert row["unaccounted_ms"] == 0.0
        assert row["unaccounted_share"] == 0.0
        assert row["attributed_share"] == pytest.approx(1.0)

    def test_unknown_phase_raises(self):
        with GAP_LEDGER.solve_scope("bad"):
            with pytest.raises(ValueError, match="unknown gap phase"):
                GAP_LEDGER.note("warp_drive", 0.001)
            GAP_LEDGER.note("encode", 0.001)  # keep the row non-empty

    def test_note_outside_scope_is_noop(self):
        before = GAP_LEDGER.rows_total
        GAP_LEDGER.note("encode", 0.5)
        assert GAP_LEDGER.rows_total == before
        assert GAP_LEDGER.rows() == []

    def test_nested_scopes_accumulate_into_one_row(self):
        before = GAP_LEDGER.rows_total
        with GAP_LEDGER.solve_scope("outer"):
            GAP_LEDGER.note("serialize", 0.001)
            with GAP_LEDGER.solve_scope("inner") as rec:
                assert rec is not None  # transparent: the OUTER record
                GAP_LEDGER.note("encode", 0.002)
        assert GAP_LEDGER.rows_total == before + 1
        row = GAP_LEDGER.rows()[-1]
        assert row["source"] == "outer"
        assert set(row["phases_ms"]) == {"serialize", "encode"}

    def test_empty_scope_produces_no_row(self):
        before = GAP_LEDGER.rows_total
        with GAP_LEDGER.solve_scope("empty"):
            pass  # native solver / error path: nothing measured
        assert GAP_LEDGER.rows_total == before

    def test_solve_rows_full_accounting(self, small_solver):
        solver, pods = small_solver
        solver.solve(pods)
        row = GAP_LEDGER.rows()[-1]
        assert row["source"] == "solver"
        for phase in ("encode", "device_exec", "decode"):
            assert row["phases_ms"][phase] >= 0, phase
        assert set(row["phases_ms"]) <= set(PHASE_NAMES)
        assert row["unaccounted_ms"] >= 0
        assert row["route"] == "single"
        assert row["bucket"]
        rf = row["roofline"]
        assert rf["floor_ms"] > 0
        assert rf["bytes_moved"] > 0 and rf["flops"] > 0

    def test_snapshot_shape(self, small_solver):
        solver, pods = small_solver
        solver.solve(pods)
        snap = GAP_LEDGER.snapshot()
        assert snap["phases"] == list(PHASE_NAMES)
        assert snap["rows_total"] >= 1
        assert "unaccounted" in snap["phase_ms_total"]
        assert snap["last"]
        shares = snap["phase_share"]
        assert sum(shares.values()) == pytest.approx(1.0, abs=0.01)


# -- roofline ----------------------------------------------------------------------


class TestRoofline:
    RUNGS = ((8, 32, 8), (16, 64, 16), (64, 256, 64), (256, 1024, 256))

    def test_floor_monotone_in_rung_size(self):
        floors, bytes_, flops = [], [], []
        for g, n, e in self.RUNGS:
            rf = roofline.estimate(g, n, e, pv=2, t=16, s=4)
            floors.append(rf.floor_ms)
            bytes_.append(rf.bytes_moved)
            flops.append(rf.flops)
        assert floors == sorted(floors)
        assert bytes_ == sorted(bytes_) and len(set(bytes_)) == len(bytes_)
        assert flops == sorted(flops) and len(set(flops)) == len(flops)

    def test_observe_ratio(self):
        rf = roofline.estimate(16, 64, 16, bucket="g16n64e16")
        ratio = roofline.observe(rf, rf.floor_ms * 2)
        assert ratio == pytest.approx(2.0, rel=1e-6)

    def test_env_override_moves_floor(self, monkeypatch):
        base = roofline.estimate(64, 256, 64).floor_ms
        monkeypatch.setenv(roofline.BW_ENV, "0.0001")  # starve bandwidth
        slow = roofline.estimate(64, 256, 64).floor_ms
        assert slow > base

    def test_bad_env_falls_back(self, monkeypatch):
        base = roofline.estimate(16, 64, 16).floor_ms
        monkeypatch.setenv(roofline.BW_ENV, "not-a-number")
        assert roofline.estimate(16, 64, 16).floor_ms == base


# -- continuous profiler -----------------------------------------------------------


class TestContinuousProfiler:
    def test_host_sampler_start_stop(self):
        s = continuous.HostSampler(hz=200.0, ring=256)
        assert s.ensure_started()
        deadline = time.monotonic() + 2.0
        while s.samples_total == 0 and time.monotonic() < deadline:
            time.sleep(0.01)
        s.stop()
        assert not s.running()
        assert s.samples_total > 0
        folded = s.folded(10)
        assert folded and all(
            isinstance(st_, str) and cnt >= 1 for st_, cnt in folded)
        # stacks are root;...;leaf module.qualname chains
        assert any(";" in st_ for st_, _ in folded)
        snap = s.snapshot()
        assert snap["samples_total"] == s.samples_total
        # loose sanity bound: this runs at 10x the default Hz while the
        # rest of the suite loads every core, so the ratio is noisy here;
        # the <5% acceptance at default Hz is the drill artifact's job
        assert 0 <= snap["overhead_ratio"] < 0.5

    def test_sampler_refuses_while_disabled(self):
        s = continuous.HostSampler(hz=100.0, ring=64)
        with profiling.disabled():
            assert not s.ensure_started()
            assert not s.running()
        assert s.samples_total == 0

    def test_device_ladder_cpu_fallback_mode(self):
        # tier-1 runs under JAX_PLATFORMS=cpu: the ladder must land on the
        # synthetic-timer rung, honestly labelled, and trace capture (a
        # tpu-sync-only passthrough) must refuse
        assert PROFILER.device.mode() == "cpu-synthetic"
        assert PROFILER.device.start_trace("/tmp/nope") is False

    def test_fallback_timer_parity_with_gap_row(self, small_solver):
        # cpu-synthetic device events are the SAME perf_counter interval
        # the gap ledger files as device_exec — parity is exact
        solver, pods = small_solver
        solver.solve(pods)
        row = GAP_LEDGER.rows()[-1]
        ev = PROFILER.device.events()[-1]
        assert ev["mode"] == "cpu-synthetic"
        assert ev["ms"] == pytest.approx(row["phases_ms"]["device_exec"],
                                         abs=0.01)
        assert ev["route"] == "single"

    def test_merge_chrome_adds_profiling_lane(self):
        PROFILER.device.observe(0.0005, bucket="g8n32e1")
        now_us = time.time() * 1e6
        doc = {"traceEvents": [
            {"name": "provisioning.cycle", "ph": "X", "pid": 1, "tid": 1,
             "ts": now_us - 2e6, "dur": 4e6},
        ]}
        merged = profiling.merge_chrome(doc)
        lane = [e for e in merged["traceEvents"]
                if e.get("pid") == profiling.PROFILE_LANE_PID]
        assert any(e.get("ph") == "M" and
                   e["args"]["name"] == "profiling" for e in lane)
        assert any(e.get("ph") == "X" and
                   e["name"].startswith("device_exec[") for e in lane)
        # original doc untouched (merge copies)
        assert len(doc["traceEvents"]) == 1

    def test_merge_chrome_disabled_is_identity(self):
        doc = {"traceEvents": [{"name": "x", "ph": "X", "ts": 0, "dur": 1}]}
        with profiling.disabled():
            assert profiling.merge_chrome(doc) is doc


# -- strict-noop contract ----------------------------------------------------------


class TestStrictNoop:
    def test_disabled_plane_produces_nothing(self, small_solver):
        solver, pods = small_solver
        with profiling.disabled():
            before = profiling.activity()
            assert PROFILER.ensure_started() is False
            solver.solve(pods)
            with GAP_LEDGER.solve_scope("noop") as rec:
                assert rec is None
                GAP_LEDGER.note("encode", 0.5)
                GAP_LEDGER.annotate(bucket="nope")
            after = profiling.activity()
        assert after == before

    def test_chaos_invariant_flags_growth(self):
        from karpenter_tpu.chaos.invariants import check_profiling_noop

        before = {"host_samples": 3, "gap_rows": 1}
        grown = {"host_samples": 7, "gap_rows": 1}
        vs = check_profiling_noop(
            {"enabled": False, "before": before, "after": grown})
        assert len(vs) == 1
        assert vs[0].invariant == "profiling-strict-noop"
        assert "host_samples" in vs[0].message

    def test_chaos_invariant_quiet_when_clean_or_enabled(self):
        from karpenter_tpu.chaos.invariants import check_profiling_noop

        same = {"host_samples": 3, "gap_rows": 1}
        assert check_profiling_noop(
            {"enabled": False, "before": same, "after": dict(same)}) == []
        assert check_profiling_noop(
            {"enabled": True, "before": same,
             "after": {"host_samples": 99}}) == []
        assert check_profiling_noop(None) == []


# -- /debug/profilez ---------------------------------------------------------------


@pytest.fixture
def served_op():
    clock = FakeClock()
    cat = Catalog(types=[make_instance_type("m.large", cpu=4, memory="16Gi",
                                            od_price=0.2)])
    op = Operator(FakeCloud(catalog=cat, clock=clock),
                  Settings(cluster_name="prof", cluster_endpoint="https://k"),
                  cat, clock=clock, serve_http=True,
                  metrics_port=0, health_port=0, webhook_port=0)
    ports = op.serving.start()
    yield op, ports
    op.serving.stop()
    op.stop()


def _get(port, path):
    try:
        with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}",
                                    timeout=5) as r:
            return r.status, r.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


class TestProfilezEndpoint:
    def test_json_default(self, served_op):
        op, ports = served_op
        code, body = _get(ports["metrics"], "/debug/profilez")
        assert code == 200
        doc = json.loads(body)
        assert doc["tool"] == "karpenter_tpu.profilez"
        assert doc["enabled"] is True
        assert isinstance(doc["stacks"], list)
        assert doc["gap"]["phases"] == list(PHASE_NAMES)
        assert doc["device"]["mode"] == "cpu-synthetic"

    def test_folded_format(self, served_op):
        op, ports = served_op
        code, body = _get(ports["metrics"], "/debug/profilez?format=folded")
        assert code == 200
        for line in body.strip().splitlines():
            stack, _, count = line.rpartition(" ")
            assert stack and count.isdigit()

    def test_malformed_n_is_400(self, served_op):
        op, ports = served_op
        code, body = _get(ports["metrics"], "/debug/profilez?n=bogus")
        assert code == 400
        assert "integer" in body

    def test_unknown_format_is_400(self, served_op):
        op, ports = served_op
        code, body = _get(ports["metrics"], "/debug/profilez?format=xml")
        assert code == 400
        assert "xml" in body

    def test_oversized_and_negative_n_clamp(self, served_op):
        from karpenter_tpu.serving import MAX_PROFILE_STACKS

        op, ports = served_op
        code, body = _get(ports["metrics"], "/debug/profilez?n=999999")
        assert code == 200
        assert len(json.loads(body)["stacks"]) <= MAX_PROFILE_STACKS
        code, _ = _get(ports["metrics"], "/debug/profilez?n=-5")
        assert code == 200  # clamped up to 1, same as /debug/traces

    def test_statusz_carries_profiling_section(self, served_op):
        op, ports = served_op
        code, body = _get(ports["metrics"], "/debug/statusz")
        assert code == 200
        doc = json.loads(body)
        assert "profiling" in doc
        assert doc["profiling"]["enabled"] is True
        assert doc["profiling"]["gap"]["phases"] == list(PHASE_NAMES)


# -- presubmit lint ----------------------------------------------------------------


def test_phase_accounting_lint_passes():
    """The committed tree must satisfy its own phase-vocabulary lint."""
    path = Path(__file__).resolve().parent.parent / "hack" / \
        "check_phase_accounting.py"
    spec = importlib.util.spec_from_file_location("check_phase_accounting",
                                                  path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    assert mod.main() == 0
