"""Runtime infrastructure: caches, ICE cache, events, metrics, settings,
batcher engine, concrete batchers, fake cloud."""

import threading
import time

import pytest

from karpenter_tpu.apis.settings import Settings, SettingsError
from karpenter_tpu.batcher import Batcher, one_bucket_hasher
from karpenter_tpu.batcher.fleet import (
    CreateFleetBatcher, DescribeInstancesBatcher, TerminateInstancesBatcher,
)
from karpenter_tpu.cache import TTLCache, UnavailableOfferings
from karpenter_tpu.events import EventRecorder
from karpenter_tpu.fake.cloud import (
    CreateFleetRequest, FakeCloud, FleetOverride, LaunchTemplate,
)
from karpenter_tpu.metrics import Registry, decorate_cloudprovider
from karpenter_tpu.models.instancetype import Catalog, make_instance_type
from karpenter_tpu.utils import errors as cloud_errors
from karpenter_tpu.utils.clock import FakeClock


class TestTTLCache:
    def test_expiry_with_fake_clock(self):
        clock = FakeClock()
        c = TTLCache(ttl=60, clock=clock)
        c.set("k", "v")
        assert c.get("k") == "v"
        clock.step(61)
        assert c.get("k") is None

    def test_get_or_load(self):
        c = TTLCache(ttl=60, clock=FakeClock())
        calls = []
        loader = lambda: calls.append(1) or "x"
        assert c.get_or_load("k", loader) == "x"
        assert c.get_or_load("k", loader) == "x"
        assert len(calls) == 1


class TestUnavailableOfferings:
    def test_mark_and_expire(self):
        clock = FakeClock()
        ice = UnavailableOfferings(clock=clock)
        s0 = ice.seqnum
        ice.mark_unavailable("ICE", "m.large", "zone-1a", "spot")
        assert ice.is_unavailable("spot", "m.large", "zone-1a")
        assert not ice.is_unavailable("on-demand", "m.large", "zone-1a")
        assert ice.seqnum == s0 + 1
        clock.step(181)
        assert not ice.is_unavailable("spot", "m.large", "zone-1a")

    def test_fleet_err_marks_pools(self):
        ice = UnavailableOfferings(clock=FakeClock())
        err = cloud_errors.FleetError(
            "InsufficientInstanceCapacity",
            [("m.large", "zone-1a"), ("m.xlarge", "zone-1b")])
        ice.mark_unavailable_for_fleet_err(err, "spot")
        assert ice.is_unavailable("spot", "m.large", "zone-1a")
        assert ice.is_unavailable("spot", "m.xlarge", "zone-1b")

    def test_apply_flips_offerings(self):
        ice = UnavailableOfferings(clock=FakeClock())
        t = make_instance_type("m.large", cpu=2, memory="8Gi", spot_price=0.03)
        ice.mark_unavailable("ICE", "m.large", "zone-1a", "spot")
        (out,) = ice.apply([t])
        flipped = [o for o in out.offerings if not o.available]
        assert len(flipped) == 1
        assert (flipped[0].zone, flipped[0].capacity_type) == ("zone-1a", "spot")


class TestEvents:
    def test_dedupe(self):
        clock = FakeClock()
        rec = EventRecorder(clock=clock)
        assert rec.normal("node/n1", "Launched", "launched")
        assert not rec.normal("node/n1", "Launched", "launched")
        clock.step(121)
        assert rec.normal("node/n1", "Launched", "launched")
        assert len(rec.events) == 2


class TestMetrics:
    def test_counter_histogram_expose(self):
        reg = Registry()
        c = reg.counter("karpenter_test_total", "help", ("kind",))
        c.inc(kind="a")
        c.inc(2, kind="a")
        assert c.value(kind="a") == 3
        h = reg.histogram("karpenter_dur_seconds", "", ("m",))
        h.observe(0.003, m="x")
        with h.time(m="x"):
            pass
        assert h.count(m="x") == 2
        text = reg.expose()
        assert 'karpenter_test_total{kind="a"} 3' in text
        assert "karpenter_dur_seconds_count" in text

    def test_decorator(self):
        reg = Registry()

        class CP:
            def create(self):
                return "ok"

        cp = decorate_cloudprovider(CP(), reg)
        assert cp.create() == "ok"
        hist = reg.histogram("karpenter_cloudprovider_duration_seconds", "", ("controller", "method"))
        assert hist.count(controller="cloudprovider", method="create") == 1


class TestSettings:
    def test_defaults_and_parse(self):
        s = Settings.from_dict({"clusterName": "c1", "batchIdleDuration": "1s",
                                "batchMaxDuration": "10s", "tags.team": "ml"})
        assert s.cluster_name == "c1"
        assert s.batch_idle_duration == 1.0
        assert s.tags == {"team": "ml"}
        assert s.vm_memory_overhead_percent == 0.075

    def test_snapshot_is_consistent_copy(self):
        s = Settings.from_dict({"clusterName": "c1", "tags.team": "ml"})
        snap = s.snapshot()
        s.apply(Settings.from_dict({"clusterName": "c2",
                                    "batchIdleDuration": "2s",
                                    "batchMaxDuration": "20s"}))
        # the snapshot is immune to the later apply (incl. nested containers)
        assert snap.cluster_name == "c1"
        assert snap.batch_idle_duration == 1.0
        assert snap.tags == {"team": "ml"}
        assert s.cluster_name == "c2" and s.tags == {}

    def test_validation(self):
        with pytest.raises(SettingsError):
            Settings.from_dict({})  # no cluster name
        with pytest.raises(SettingsError):
            Settings.from_dict({"clusterName": "c", "clusterEndpoint": "http://x"})
        with pytest.raises(SettingsError):
            Settings.from_dict({"clusterName": "c", "tags.karpenter.sh/x": "y"})
        with pytest.raises(SettingsError):
            Settings.from_dict({"clusterName": "c", "batchIdleDuration": "bogus"})
        with pytest.raises(SettingsError):
            Settings.from_dict({"clusterName": "c",
                                "nodeNameConvention": "hostname"})

    def test_node_name_convention(self):
        # settings.go:29-47: ip-name (default) names nodes after the
        # instance's private DNS; resource-name after the instance id
        from karpenter_tpu.fake.cloud import FakeCloud
        from karpenter_tpu.models.instancetype import (Catalog,
                                                       make_instance_type)
        from karpenter_tpu.models.machine import Machine, MachineSpec
        from karpenter_tpu.cloudprovider import CloudProvider
        from karpenter_tpu.apis.nodetemplate import NodeTemplate

        catalog = Catalog(types=[make_instance_type(
            "t.small", cpu=2, memory="2Gi", od_price=0.05, spot_price=0.02)])

        def launch(convention):
            s = Settings.from_dict({"clusterName": "c",
                                    "nodeNameConvention": convention}
                                   if convention else {"clusterName": "c"})
            cp = CloudProvider(FakeCloud(catalog=catalog), s, catalog)
            cp.register_nodetemplate(NodeTemplate(
                name="default",
                subnet_selector={"id": "subnet-zone-1a"},
                security_group_selector={"id": "sg-default"}))
            m = Machine(name="m1", spec=MachineSpec(
                provisioner_name="default", machine_template_ref="default"))
            return cp.create(m).status

        st = launch(None)
        assert st.node_name.startswith("ip-10-") and st.node_name.endswith(".internal")
        st = launch("resource-name")
        assert st.node_name.startswith("i-")
        _, iid = st.provider_id[len("tpu:///"):].split("/")
        assert st.node_name == iid


class TestBatcherEngine:
    def test_coalesces_within_idle_window(self):
        batches = []

        def execf(reqs):
            batches.append(list(reqs))
            return [r * 10 for r in reqs]

        b = Batcher(execf, idle_seconds=0.05, max_seconds=1.0, max_items=100,
                    hasher=one_bucket_hasher)
        try:
            results = []
            threads = [threading.Thread(target=lambda i=i: results.append(b.add(i)))
                       for i in range(5)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=5)
            assert sorted(results) == [0, 10, 20, 30, 40]
            assert len(batches) == 1  # one merged call
        finally:
            b.stop()

    def test_max_items_flushes_immediately(self):
        batches = []

        def execf(reqs):
            batches.append(list(reqs))
            return list(reqs)

        b = Batcher(execf, idle_seconds=10, max_seconds=60, max_items=2,
                    hasher=one_bucket_hasher)
        try:
            results = []
            ts = [threading.Thread(target=lambda i=i: results.append(b.add(i)))
                  for i in range(2)]
            t0 = time.monotonic()
            for t in ts:
                t.start()
            for t in ts:
                t.join(timeout=5)
            assert time.monotonic() - t0 < 5  # didn't wait for the 10s idle
            assert len(results) == 2
        finally:
            b.stop()

    def test_error_fans_out(self):
        def execf(reqs):
            raise RuntimeError("boom")

        b = Batcher(execf, idle_seconds=0.01, max_seconds=0.1, max_items=10,
                    hasher=one_bucket_hasher)
        try:
            with pytest.raises(RuntimeError):
                b.add(1)
        finally:
            b.stop()


def fleet_request(capacity=1):
    return CreateFleetRequest(
        launch_template="lt-1",
        overrides=[FleetOverride("m.large", "zone-1a", "subnet-zone-1a", 0.1),
                   FleetOverride("m.large", "zone-1b", "subnet-zone-1b", 0.1)],
        capacity=capacity, capacity_type="on-demand",
        tags={"karpenter.sh/cluster": "test"})


class TestFleetBatchers:
    def setup_method(self):
        self.cloud = FakeCloud(catalog=Catalog(types=[
            make_instance_type("m.large", cpu=2, memory="8Gi")]))
        self.cloud.create_launch_template(LaunchTemplate(name="lt-1", image_id="img-amd64-2"))

    def test_create_fleet_merges_identical_requests(self):
        b = CreateFleetBatcher(self.cloud, idle=0.03, max_wait=0.5)
        try:
            results = []
            ts = [threading.Thread(
                target=lambda: results.append(b.create_fleet(fleet_request())))
                for _ in range(5)]
            for t in ts:
                t.start()
            for t in ts:
                t.join(timeout=5)
            assert self.cloud.create_fleet_api.called_with_count == 1
            assert self.cloud.create_fleet_api.calls[0].capacity == 5
            ids = [i for r in results for i in r.instance_ids]
            assert len(ids) == len(set(ids)) == 5
        finally:
            b.stop()

    def test_ice_pool_fans_error(self):
        self.cloud.insufficient_capacity_pools = {
            ("on-demand", "m.large", "zone-1a"), ("on-demand", "m.large", "zone-1b")}
        b = CreateFleetBatcher(self.cloud, idle=0.02, max_wait=0.2)
        try:
            with pytest.raises(cloud_errors.FleetError) as ei:
                b.create_fleet(fleet_request())
            assert cloud_errors.is_unfulfillable_capacity(ei.value)
            assert ("m.large", "zone-1a") in ei.value.failed_pools
        finally:
            b.stop()

    def test_describe_and_terminate_roundtrip(self):
        resp = self.cloud.create_fleet(fleet_request(capacity=2))
        d = DescribeInstancesBatcher(self.cloud, idle=0.02, max_wait=0.2)
        t = TerminateInstancesBatcher(self.cloud, idle=0.02, max_wait=0.2)
        try:
            inst = d.describe(resp.instance_ids[0])
            assert inst.instance_type == "m.large"
            iid, state = t.terminate(resp.instance_ids[0])
            assert state == "terminated"
            with pytest.raises(cloud_errors.CloudError):
                d.describe(resp.instance_ids[0])  # terminated -> not found
        finally:
            d.stop()
            t.stop()


class TestFakeCloud:
    def test_selector_matching(self):
        cloud = FakeCloud()
        subs = cloud.describe_subnets({"id": "subnet-zone-1a"})
        assert [s.zone for s in subs] == ["zone-1a"]
        assert cloud.describe_subnets({}) == []
        sgs = cloud.describe_security_groups({"kubernetes.io/cluster/test-cluster": "*"})
        assert [g.id for g in sgs] == ["sg-default"]

    def test_error_injection(self):
        cloud = FakeCloud()
        cloud.describe_instances_api.set_error(
            cloud_errors.CloudError("InternalError"), times=1)
        with pytest.raises(cloud_errors.CloudError):
            cloud.describe_instances(["i-1"])
        assert cloud.describe_instances(["i-1"]) == []  # error consumed


def test_batcher_stop_resolves_pending():
    import threading as th

    done = []

    def execf(reqs):
        return list(reqs)

    b = Batcher(execf, idle_seconds=30, max_seconds=60, max_items=100,
                hasher=one_bucket_hasher)
    t = th.Thread(target=lambda: done.append(b.add(1)))
    t.start()
    time.sleep(0.05)
    b.stop()  # must flush, not abandon
    t.join(timeout=2)
    assert done == [1]


def test_ttl_cache_caches_none():
    from karpenter_tpu.utils.clock import FakeClock as FC
    c = TTLCache(ttl=60, clock=FC())
    calls = []

    def loader():
        calls.append(1)
        return None

    assert c.get_or_load("k", loader) is None
    assert c.get_or_load("k", loader) is None
    assert len(calls) == 1


def test_histogram_exposes_inf_bucket():
    from karpenter_tpu.metrics import Registry as R
    reg = R()
    h = reg.histogram("karpenter_x_seconds", "", ("m",))
    h.observe(90.0, m="slow")  # above the largest bucket
    text = reg.expose()
    assert 'le="+Inf"' in text
    assert 'karpenter_x_seconds_count{m="slow"} 1' in text


def test_instancetype_provider_multi_template_memo():
    from karpenter_tpu.cache import UnavailableOfferings as UO
    from karpenter_tpu.providers.instancetypes import InstanceTypeProvider
    from karpenter_tpu.providers.subnet import SubnetProvider
    from karpenter_tpu.fake.cloud import FakeCloud
    from karpenter_tpu.apis.nodetemplate import NodeTemplate
    from karpenter_tpu.utils.clock import FakeClock as FC

    clock = FC()
    cloud = FakeCloud(clock=clock)
    cat = Catalog(types=[make_instance_type("m.2x", cpu=2, memory="8Gi")])
    p = InstanceTypeProvider(cat, UO(clock=clock), SubnetProvider(cloud, clock=clock))
    ta = NodeTemplate(name="a", subnet_selector={"id": "subnet-zone-1a"},
                      security_group_selector={"id": "sg-default"})
    tb = NodeTemplate(name="b", subnet_selector={"id": "subnet-zone-1b"},
                      security_group_selector={"id": "sg-default"})
    ca1, cb1 = p.list(ta), p.list(tb)
    ca2, cb2 = p.list(ta), p.list(tb)
    assert ca1 is ca2 and cb1 is cb2  # both variants stay memoized
    assert {o.zone for t in ca1.types for o in t.offerings} == {"zone-1a"}


class TestRestPricingSource:
    """The real pricing client stub: paged feeds, independent OD/spot
    updates (pricing.go:202-243, 283-316, 379-435)."""

    def _serve(self, handler):
        import threading
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
        import json as _json

        class H(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_GET(self):
                code, doc = handler(self.path)
                body = _json.dumps(doc).encode()
                self.send_response(code)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        srv = ThreadingHTTPServer(("127.0.0.1", 0), H)
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        return srv, srv.server_address[1]

    def test_paged_fetch_and_zone_fanout(self):
        from karpenter_tpu.providers.pricing import (PricingSource,
                                                     RestPricingSource)

        def handler(path):
            if path == "/on-demand?page=0":
                return 200, {"prices": [
                    {"instanceType": "m.large", "price": 0.2}], "next": True}
            if path == "/on-demand?page=1":
                return 200, {"prices": [
                    {"instanceType": "m.xl", "price": 0.4}], "next": False}
            if path.startswith("/spot"):
                return 200, {"prices": [
                    {"instanceType": "m.large", "zone": "z1", "price": 0.06}],
                    "next": False}
            return 404, {}

        srv, port = self._serve(handler)
        try:
            src = RestPricingSource(f"http://127.0.0.1:{port}",
                                    zones=["z1", "z2"])
            assert isinstance(src, PricingSource)
            prices = src.get_prices()
            assert prices[("m.large", "on-demand", "z1")] == 0.2
            assert prices[("m.large", "on-demand", "z2")] == 0.2
            assert prices[("m.xl", "on-demand", "z2")] == 0.4
            assert prices[("m.large", "spot", "z1")] == 0.06
            assert ("m.large", "spot", "z2") not in prices
        finally:
            srv.shutdown()

    def test_independent_updates_on_partial_outage(self):
        from karpenter_tpu.providers.pricing import (PricingProvider,
                                                     RestPricingSource)

        def handler(path):
            if path.startswith("/on-demand"):
                return 200, {"prices": [
                    {"instanceType": "m.large", "price": 0.25}], "next": False}
            return 500, {"error": "spot feed down"}

        srv, port = self._serve(handler)
        try:
            src = RestPricingSource(f"http://127.0.0.1:{port}", zones=["z1"])
            prov = PricingProvider(src, static_prices={
                ("m.large", "on-demand", "z1"): 0.2,
                ("m.large", "spot", "z1"): 0.05,
            })
            assert prov.update()  # OD side landed despite the spot outage
            assert prov.on_demand_price("m.large", "z1") == 0.25
            assert prov.spot_price("m.large", "z1") == 0.05  # static kept
        finally:
            srv.shutdown()

    def test_total_outage_keeps_previous_map(self):
        from karpenter_tpu.providers.pricing import (PricingProvider,
                                                     RestPricingSource)

        def handler(path):
            return 500, {}

        srv, port = self._serve(handler)
        try:
            src = RestPricingSource(f"http://127.0.0.1:{port}", zones=["z1"])
            prov = PricingProvider(src, static_prices={
                ("m.large", "on-demand", "z1"): 0.2})
            assert not prov.update()  # nothing fresh
            assert prov.on_demand_price("m.large", "z1") == 0.2
        finally:
            srv.shutdown()


def test_fleet_batcher_never_merges_distinct_contexts():
    """Requests differing only in fleet_context must not share a batch
    bucket — merged, the second template's reserved-capacity targeting
    would silently apply the first's context (reference createfleet.go
    hashes the full request shape)."""
    from karpenter_tpu.batcher.fleet import _fleet_hasher
    from karpenter_tpu.fake.cloud import CreateFleetRequest, FleetOverride

    base = dict(launch_template="lt-1",
                overrides=[FleetOverride("m.large", "zone-1a")],
                capacity=1, capacity_type="on-demand")
    a = CreateFleetRequest(**base, fleet_context="cr-a")
    b = CreateFleetRequest(**base, fleet_context="cr-b")
    c = CreateFleetRequest(**base, fleet_context="cr-a")
    assert _fleet_hasher(a) != _fleet_hasher(b)
    assert _fleet_hasher(a) == _fleet_hasher(c)


def test_vm_memory_overhead_percent_is_live():
    """settings.vmMemoryOverheadPercent re-derives every type's memory
    overhead (the source catalog bakes the default); the memo key carries
    the live value so a settings change invalidates derived catalogs."""
    from karpenter_tpu.apis import wellknown as wk
    from karpenter_tpu.apis.settings import Settings
    from karpenter_tpu.cache import UnavailableOfferings
    from karpenter_tpu.providers.instancetypes import (
        InstanceTypeProvider, generate_fleet_catalog)

    src = generate_fleet_catalog(max_types=5)
    settings = Settings(cluster_name="t", cluster_endpoint="https://k")
    provider = InstanceTypeProvider(src, UnavailableOfferings(),
                                    settings=settings)
    base_alloc = provider.list(None).types[0].allocatable_vector()
    settings.vm_memory_overhead_percent = 0.2
    fat_alloc = provider.list(None).types[0].allocatable_vector()
    mem_i = wk.RESOURCE_INDEX[wk.RESOURCE_MEMORY]
    assert fat_alloc[mem_i] < base_alloc[mem_i], (base_alloc, fat_alloc)
    # cpu overhead curve unchanged
    cpu_i = wk.RESOURCE_INDEX[wk.RESOURCE_CPU]
    assert fat_alloc[cpu_i] == base_alloc[cpu_i]
    # back to default: identical to the source-baked numbers
    settings.vm_memory_overhead_percent = 0.075
    assert provider.list(None).types[0].allocatable_vector() == base_alloc


def test_enable_pod_eni_advertises_branch_interfaces():
    """enablePodENI: trunking-compatible (nitro) types advertise pod-eni
    capacity; disabled (default) leaves it unadvertised so pod-eni pods are
    unschedulable (reference awsPodENI gating)."""
    from karpenter_tpu.apis import wellknown as wk
    from karpenter_tpu.apis.settings import Settings
    from karpenter_tpu.cache import UnavailableOfferings
    from karpenter_tpu.providers.instancetypes import (
        InstanceTypeProvider, generate_fleet_catalog)

    src = generate_fleet_catalog()
    settings = Settings(cluster_name="t", cluster_endpoint="https://k")
    provider = InstanceTypeProvider(src, UnavailableOfferings(),
                                    settings=settings)
    assert all(wk.RESOURCE_POD_ENI not in dict(t.capacity)
               for t in provider.list(None).types)
    settings.enable_pod_eni = True
    cat = provider.list(None)
    trunking = [t for t in cat.types
                if wk.RESOURCE_POD_ENI in dict(t.capacity)]
    xen = [t for t in cat.types
           if dict(t.labels).get(wk.LABEL_INSTANCE_HYPERVISOR) == "xen"]
    # real-data semantics: only trunking-compatible types advertise their
    # BAKED branch counts (limits table via hack/gen_catalog.py); a nitro
    # type without trunking support (t4g) must NOT have capacity fabricated
    assert trunking and all(
        dict(t.capacity)[wk.RESOURCE_POD_ENI] > 0 for t in trunking)
    assert dict(cat.by_name["m5.2xlarge"].capacity).get(
        wk.RESOURCE_POD_ENI, 0) == 38  # the limits-table value, not 3*cpu
    assert wk.RESOURCE_POD_ENI not in dict(
        cat.by_name["t4g.2xlarge"].capacity)  # nitro but non-trunking
    assert xen and all(
        wk.RESOURCE_POD_ENI not in dict(t.capacity) for t in xen)
    # a pod requesting pod-eni schedules end-to-end only when enabled
    from karpenter_tpu.apis.provisioner import Provisioner
    from karpenter_tpu.models.pod import make_pod
    from karpenter_tpu.solver.core import NativeSolver

    prov = Provisioner(name="default")
    prov.set_defaults()
    pod = make_pod("eni", cpu="1", memory="1Gi",
                   extended={wk.RESOURCE_POD_ENI: 2})
    res = NativeSolver(cat, [prov]).solve([pod])
    assert res.unschedulable_count() == 0
    settings.enable_pod_eni = False
    res2 = NativeSolver(provider.list(None), [prov]).solve([pod])
    assert res2.unschedulable_count() == 1


def test_pod_eni_disabled_strips_baked_capacity():
    """The gate is symmetric: disabled STRIPS pod-eni capacity baked into a
    source catalog (reference awsPodENI reports 0 when disabled)."""
    from karpenter_tpu.apis import wellknown as wk
    from karpenter_tpu.apis.settings import Settings
    from karpenter_tpu.cache import UnavailableOfferings
    from karpenter_tpu.models.instancetype import Catalog, make_instance_type
    from karpenter_tpu.providers.instancetypes import InstanceTypeProvider

    src = Catalog(types=[make_instance_type(
        "n.large", cpu=4, memory="16Gi", od_price=0.2,
        extended={wk.RESOURCE_POD_ENI: 5})])
    settings = Settings(cluster_name="t", cluster_endpoint="https://k")
    provider = InstanceTypeProvider(src, UnavailableOfferings(),
                                    settings=settings)
    assert wk.RESOURCE_POD_ENI not in dict(provider.list(None).types[0].capacity)
    settings.enable_pod_eni = True
    assert dict(provider.list(None).types[0].capacity).get(
        wk.RESOURCE_POD_ENI) == 5  # baked value preserved when enabled
