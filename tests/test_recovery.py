"""Crash-restart recovery plane: durable intent journal, crashpoint
rebirth drills, fenced leader failover, and boot-epoch monotonicity.

The unit tier exercises the journal and fencing primitives directly; the
drill tier drives ChaosRunner's crash mode — kill the process at a named
crashpoint, boot a fresh operator against the surviving stores, and
assert the recovery invariants (exactly-once launch, journal resolved
within the replay budget, fencing rejects zombie writes).
"""

import json
from types import SimpleNamespace

import pytest

from karpenter_tpu.chaos.runner import ChaosRunner
from karpenter_tpu.fake.apiserver import serve
from karpenter_tpu.fake.kube import Fenced, FencedKube, KubeStore
from karpenter_tpu.coordination.httpkube import HttpKubeStore
from karpenter_tpu.recovery import (BOOT_EPOCH_NAME, CRASHPOINTS,
                                    RecoveryManager, SimulatedCrash,
                                    crashpoint, install, uninstall)
from karpenter_tpu.recovery.journal import (LAUNCH, REPLACE, TERMINATION,
                                            IntentJournal)
from karpenter_tpu.utils.clock import FakeClock


# -- intent journal ----------------------------------------------------------


class TestIntentJournal:
    def _journal(self, epoch=0):
        holder = SimpleNamespace(epoch=epoch)
        j = IntentJournal(KubeStore(), clock=FakeClock(),
                          epoch_fn=lambda: holder.epoch)
        return j, holder

    def test_record_get_resolve_roundtrip(self):
        j, holder = self._journal(epoch=3)
        rec = j.record(LAUNCH, "m-1", {"machine": "m-1"})
        assert rec.name == "launch:m-1"
        assert rec.epoch == 3
        got = j.get(LAUNCH, "m-1")
        assert got is not None and got.payload == {"machine": "m-1"}
        assert [r.name for r in j.pending()] == ["launch:m-1"]
        assert j.resolve(LAUNCH, "m-1") is True
        assert j.pending() == []
        assert j.resolve(LAUNCH, "m-1") is False  # already terminal

    def test_pending_filters_by_kind_and_epoch(self):
        j, holder = self._journal(epoch=1)
        j.record(LAUNCH, "m-1", {})
        j.record(TERMINATION, "n-1", {})
        holder.epoch = 2
        j.record(REPLACE, "n-2", {})
        assert {r.kind for r in j.pending()} == {LAUNCH, TERMINATION, REPLACE}
        assert [r.kind for r in j.pending(kind=LAUNCH)] == [LAUNCH]
        # replay targets prior epochs only: the current epoch is in flight
        stale = j.pending(before_epoch=2)
        assert {r.name for r in stale} == {"launch:m-1", "termination:n-1"}

    def test_rerecord_refreshes_epoch(self):
        j, holder = self._journal(epoch=1)
        j.record(TERMINATION, "n-1", {"node": "n-1"})
        holder.epoch = 5
        j.record(TERMINATION, "n-1", {"node": "n-1"})
        assert j.get(TERMINATION, "n-1").epoch == 5
        assert j.pending(before_epoch=5) == []  # re-entered the normal flow

    def test_pending_is_oldest_first(self):
        j, _ = self._journal()
        j.clock.step(1.0)
        j.record(LAUNCH, "b", {})
        j.clock.step(1.0)
        j.record(LAUNCH, "a", {})
        assert [r.key for r in j.pending()] == ["b", "a"]

    def test_snapshot_counts_by_kind(self):
        j, _ = self._journal()
        j.record(LAUNCH, "m-1", {})
        j.record(LAUNCH, "m-2", {})
        j.record(REPLACE, "n-1", {})
        snap = j.snapshot()
        assert snap == {"pending": 3,
                        "pending_by_kind": {LAUNCH: 2, REPLACE: 1}}


# -- crashpoints -------------------------------------------------------------


class TestCrashpoints:
    def teardown_method(self):
        uninstall()

    def test_noop_without_hook(self):
        crashpoint("launch.pre_register")  # must not raise

    def test_hook_sees_site_and_uninstall_disarms(self):
        seen = []
        install(seen.append)
        crashpoint("launch.mid_bind")
        assert seen == ["launch.mid_bind"]
        uninstall()
        crashpoint("launch.mid_bind")
        assert seen == ["launch.mid_bind"]

    def test_simulated_crash_sails_past_except_exception(self):
        """The whole point of BaseException: in-band cleanup fences must
        not get a chance to tidy state a real SIGKILL would strand."""
        cleaned = []

        def action():
            try:
                raise SimulatedCrash("launch.pre_register")
            except Exception:  # noqa: BLE001 — the fence under test
                cleaned.append(True)

        with pytest.raises(SimulatedCrash) as e:
            action()
        assert cleaned == []
        assert e.value.site == "launch.pre_register"


# -- fencing -----------------------------------------------------------------


class TestFencing:
    def test_store_rejects_stale_epoch(self):
        store = KubeStore()
        new_leader = FencedKube(store, lambda: 2)
        old_leader = FencedKube(store, lambda: 1)
        new_leader.create("configmaps", "state", {"owner": "new"})
        assert store.fence_epoch() == 2
        with pytest.raises(Fenced):
            old_leader.update("configmaps", "state", {"owner": "old"})
        with pytest.raises(Fenced):
            old_leader.delete("configmaps", "state")
        assert store.fenced_writes_rejected == 2
        assert store.get("configmaps", "state") == {"owner": "new"}

    def test_lease_epoch_advances_fence_high_water(self):
        store = KubeStore()
        store.create("leases", "karpenter-leader", SimpleNamespace(epoch=7))
        assert store.fence_epoch() == 7
        with pytest.raises(Fenced):
            store.create("configmaps", "late", {}, epoch=6)

    def test_wire_fencing_rejects_zombie_writes(self):
        """End-to-end over the mini apiserver: X-Fencing-Epoch on mutating
        verbs, stale epoch -> 409 Fenced, high-water advertised back."""
        srv, port, state = serve()
        try:
            store = HttpKubeStore(f"http://127.0.0.1:{port}")
            store.create("configmaps", "state", {"owner": "new"}, epoch=2)
            assert store.fence_epoch() == 2
            with pytest.raises(Fenced):
                store.update("configmaps", "state", {"owner": "old"}, epoch=1)
            with pytest.raises(Fenced):
                store.delete("configmaps", "state", epoch=1)
            assert state.fenced_writes_rejected == 2
            assert state.fence_epoch == 2
        finally:
            srv.shutdown()


# -- epoch minting -----------------------------------------------------------


class TestBootEpoch:
    def _op(self, store):
        return SimpleNamespace(kube=store, leader=None, journal=None)

    def test_boot_counter_is_monotone_across_incarnations(self):
        store = KubeStore()
        epochs = [RecoveryManager(self._op(store)).begin_incarnation()
                  for _ in range(3)]
        assert epochs == [1, 2, 3]
        stored = store.get("configmaps", BOOT_EPOCH_NAME)
        assert stored["epoch"] == 3

    def test_boot_counter_respects_store_fence_high_water(self):
        """A standalone boot after a leader-elected history must not mint
        an epoch the fence has already seen — mixed-mode stays monotone."""
        store = KubeStore()
        store.create("leases", "leader", SimpleNamespace(epoch=9))
        assert RecoveryManager(self._op(store)).begin_incarnation() == 10


# -- journal replay ----------------------------------------------------------


class TestReplay:
    def _op(self):
        runner = ChaosRunner(seed=0, crash=True, out_dir=None)
        clock = FakeClock()
        op, cloud = runner._build(clock, name_suffix="rep")
        return op, cloud

    def test_stranded_launch_rolls_back(self):
        op, cloud = self._op()
        op.journal.record(LAUNCH, "ghost-00001", {"machine": "ghost-00001"})
        op.recovery.begin_incarnation()
        actions = op.recovery.replay()
        assert actions == [{"kind": LAUNCH, "key": "ghost-00001", "epoch": 0,
                            "outcome": "rolled_back"}]
        assert op.journal.pending() == []

    def test_stranded_termination_with_nothing_left_is_already_done(self):
        op, _ = self._op()
        op.journal.record(TERMINATION, "gone-node", {"node": "gone-node",
                                                     "machine": ""})
        op.recovery.begin_incarnation()
        actions = op.recovery.replay()
        assert [a["outcome"] for a in actions] == ["already_done"]

    def test_stranded_replace_without_replacement_aborts(self):
        op, _ = self._op()
        op.journal.record(REPLACE, "old-node", {"nodes": ["old-node"],
                                                "replacement": None})
        op.recovery.begin_incarnation()
        actions = op.recovery.replay()
        assert [a["outcome"] for a in actions] == ["aborted"]

    def test_current_epoch_records_are_left_in_flight(self):
        op, _ = self._op()
        op.recovery.begin_incarnation()
        op.journal.record(LAUNCH, "inflight-00001", {})
        assert op.recovery.replay() == []
        assert [r.key for r in op.journal.pending()] == ["inflight-00001"]


# -- the crash drill ---------------------------------------------------------


@pytest.fixture(scope="module")
def drill():
    return ChaosRunner(seed=0, crash=True, out_dir=None).run()


class TestCrashDrill:
    def test_drill_passes_at_seed_zero(self, drill):
        for s in drill["scenarios"]:
            assert s["passed"], (s["drill"], s["violations"])
        assert drill["passed"]

    def test_every_crashpoint_has_a_scenario(self, drill):
        sites = [s["site"] for s in drill["scenarios"]]
        for site in CRASHPOINTS:
            assert site in sites
        assert any(s["drill"] == "crash:leader-failover"
                   for s in drill["scenarios"])

    def test_every_scenario_actually_crashed(self, drill):
        assert all(s["crashed"] for s in drill["scenarios"])

    def test_write_ahead_record_survived_every_crash(self, drill):
        """At rebirth the journal must hold the dead incarnation's intent —
        the write-ahead ordering is what makes replay possible at all."""
        for s in drill["scenarios"]:
            if s["drill"] == "crash:leader-failover":
                assert s["replay"], s
            else:
                assert s["pending_at_rebirth"], s["drill"]

    def test_failover_fences_all_zombie_writes(self, drill):
        s = next(x for x in drill["scenarios"]
                 if x["drill"] == "crash:leader-failover")
        zw = s["zombie_writes"]
        assert zw["attempted"] >= 2
        assert zw["rejected"] == zw["attempted"]
        assert zw["store_rejections"] == zw["attempted"]
        assert s["epochs"]["reborn"] > s["epochs"]["crashed"]
        assert s["fence_epoch"] >= s["epochs"]["reborn"]

    def test_interruption_redelivery_deduped_across_rebirth(self, drill):
        s = next(x for x in drill["scenarios"]
                 if x["site"] == "interruption.pre_ack")
        assert s["interruption_deduped"] >= 1

    def test_scenarios_are_json_serializable(self, drill):
        json.dumps(drill["scenarios"])

    def test_single_site_drill_is_deterministic(self):
        """Replay contract: a crash scenario dict is a pure function of
        (seed, scenario) — two in-process runs must agree byte for byte."""
        a = ChaosRunner(seed=0, crash=True,
                        out_dir=None).run_crash_site("launch.pre_register", 1)
        b = ChaosRunner(seed=0, crash=True,
                        out_dir=None).run_crash_site("launch.pre_register", 1)
        assert a == b


@pytest.mark.slow
class TestCrashSweep:
    def test_full_drill_is_deterministic(self):
        volatile = ("duration_s", "bundles", "artifact_path")
        runs = [ChaosRunner(seed=0, crash=True, out_dir=None).run()
                for _ in range(2)]
        for artifact in runs:
            for key in volatile:
                artifact.pop(key, None)
        assert runs[0] == runs[1]

    @pytest.mark.parametrize("seed", range(20))
    def test_crash_sweep_twenty_seeds(self, seed):
        artifact = ChaosRunner(seed=seed, crash=True, out_dir=None).run()
        assert artifact["passed"], [
            (s["drill"], s["violations"])
            for s in artifact["scenarios"] if not s["passed"]]
