"""Introspection plane (ISSUE 3): watchdog deadman, statusz snapshot,
flight recorder triggers and bundles.

Tier-1 pieces: the FakeClock-driven deadman (stall -> unready -> recovery,
with the stalled controller named in /readyz and the healthy gauge reading
0 then 1), statusz schema stability (the snapshot is a wire format — the
ring and bundles persist it), the chaos invariant-breach trigger writing a
bundle next to the replay artifact, and the /debug/bundle round trip.
"""

import json
import urllib.error
import urllib.request

import pytest

from karpenter_tpu.apis.nodetemplate import NodeTemplate
from karpenter_tpu.apis.provisioner import Provisioner
from karpenter_tpu.apis.settings import Settings
from karpenter_tpu.chaos import ChaosRunner
from karpenter_tpu.chaos import invariants as chaos_invariants
from karpenter_tpu.fake.cloud import FakeCloud
from karpenter_tpu.introspect import FlightRecorder, Watchdog, snapshot
from karpenter_tpu.introspect.watchdog import cycle as wd_cycle
from karpenter_tpu.models.instancetype import Catalog, make_instance_type
from karpenter_tpu.operator import Operator
from karpenter_tpu.utils.clock import FakeClock


def _catalog():
    return Catalog(types=[make_instance_type(
        "m.large", cpu=4, memory="16Gi", od_price=0.2, spot_price=0.07)])


def _operator(clock, **kw):
    op = Operator(FakeCloud(catalog=_catalog(), clock=clock),
                  Settings(cluster_name="intro",
                           cluster_endpoint="https://intro"),
                  _catalog(), clock=clock, **kw)
    op.kube.create("nodetemplates", "default", NodeTemplate(
        name="default",
        subnet_selector={"id": "subnet-zone-1a"},
        security_group_selector={"id": "sg-default"}))
    op.cloudprovider.register_nodetemplate(
        op.kube.get("nodetemplates", "default"))
    prov = Provisioner(name="default", provider_ref="default")
    prov.set_defaults()
    op.kube.create("provisioners", "default", prov)
    return op


@pytest.fixture
def op():
    clock = FakeClock()
    op = _operator(clock)
    yield op, clock
    op.stop()


class TestWatchdog:
    def test_beat_and_status(self):
        clock = FakeClock()
        wd = Watchdog(clock=clock)
        wd.register("alpha", threshold=10.0)
        wd.beat("alpha", duration_s=0.25)
        st = wd.status()["alpha"]
        assert st["healthy"] and st["beats"] == 1
        assert st["last_cycle_ms"] == 250.0
        assert wd.check() == []

    def test_failure_records_without_refreshing_heartbeat(self):
        clock = FakeClock()
        wd = Watchdog(clock=clock)
        wd.register("alpha", threshold=10.0)
        wd.beat("alpha")
        clock.step(11.0)
        # a crash-looping controller fails every cycle: the failure is
        # recorded but the heartbeat must NOT refresh — it goes stale
        # exactly like a hung one
        with pytest.raises(RuntimeError):
            with wd.cycle("alpha"):
                raise RuntimeError("boom")
        assert wd.check() == ["alpha"]
        st = wd.status()["alpha"]
        assert st["failures"] == 1
        assert "RuntimeError: boom" in st["last_error"]

    def test_startup_grace_is_one_threshold(self):
        clock = FakeClock()
        wd = Watchdog(clock=clock)
        wd.register("quiet", threshold=5.0)
        clock.step(4.0)
        assert wd.check() == []  # never beat, still inside the grace
        clock.step(2.0)
        assert wd.check() == ["quiet"]

    def test_transition_events_are_edge_triggered(self):
        from karpenter_tpu.events import EventRecorder

        clock = FakeClock()
        rec = EventRecorder(clock=clock)
        wd = Watchdog(clock=clock, recorder=rec)
        wd.register("alpha", threshold=5.0)
        clock.step(6.0)
        wd.check()
        wd.check()  # still stalled: no second event
        wd.beat("alpha")
        wd.check()  # recovery
        wd.check()
        reasons = [e.reason for _, e in rec.recent()
                   if e.object_ref == "controller/alpha"]
        assert reasons == ["ControllerStalled", "ControllerRecovered"]

    def test_stall_listener_gets_newly_stalled_names(self):
        clock = FakeClock()
        wd = Watchdog(clock=clock)
        seen = []
        wd.add_stall_listener(seen.append)
        wd.register("a", threshold=5.0)
        wd.register("b", threshold=50.0)
        clock.step(6.0)
        wd.check()
        clock.step(60.0)
        wd.check()
        assert seen == [["a"], ["b"]]

    def test_module_cycle_tolerates_no_watchdog(self):
        with wd_cycle(None, "standalone"):
            pass  # strict no-op


class TestDeadmanReadyz:
    def test_stall_unready_recovery(self, op):
        op, clock = op
        op.reconcile_all_once()
        ok, detail = op.readyz()
        assert ok and detail == "ok"

        # 500s with no cycles: every 120s-threshold controller stalls;
        # garbagecollection (600s threshold) must NOT
        clock.step(500.0)
        ok, detail = op.readyz()
        assert not ok
        assert detail.startswith("unhealthy: stalled controllers: ")
        assert "provisioning" in detail
        assert "garbagecollection" not in detail

        def healthy(controller):
            for labels, v in op.watchdog.healthy_gauge.collect():
                if labels.get("controller") == controller:
                    return v
            raise AssertionError(f"no healthy series for {controller}")

        assert healthy("provisioning") == 0.0
        assert healthy("garbagecollection") == 1.0

        op.reconcile_all_once()
        ok, detail = op.readyz()
        assert ok and detail == "ok"
        assert healthy("provisioning") == 1.0

    def test_stall_emits_deduped_warning_event(self, op):
        op, clock = op
        op.reconcile_all_once()
        clock.step(500.0)
        op.watchdog.check()
        op.watchdog.check()
        stalls = [e for _, e in op.recorder.recent()
                  if e.reason == "ControllerStalled"
                  and e.object_ref == "controller/provisioning"]
        assert len(stalls) == 1
        assert stalls[0].kind == "Warning"


class TestStatusz:
    TOP_KEYS = {"tool", "schema", "version", "ts", "pid", "serving",
                "cluster", "controllers", "queues", "caches", "events",
                "resilience", "recovery", "fleet", "slo", "hbm",
                "profiling", "critical", "spot", "overload", "decisions",
                "incremental", "metrics"}
    CLUSTER_KEYS = {"nodes", "nodes_by_provisioner",
                    "nodes_marked_for_deletion", "machines", "pods",
                    "pending_pods", "provisioners", "nodetemplates", "pdbs"}

    def test_schema_stability(self, op):
        op, clock = op
        op.reconcile_all_once()
        snap = snapshot(op)
        # the snapshot is a wire format (ring + bundles persist it):
        # key-set changes are schema changes and must bump SCHEMA_VERSION
        assert set(snap) == self.TOP_KEYS
        assert snap["tool"] == "karpenter_tpu.statusz"
        assert snap["schema"] == 13
        assert set(snap["slo"]) == {"windows", "burn_threshold", "slos"}
        assert {"solvers", "resident_bytes_total", "capacity_bytes",
                "pressure"} <= set(snap["hbm"])
        assert set(snap["resilience"]) == {"breakers", "budgets", "ladders",
                                           "degraded", "open_breakers"}
        assert {"epoch", "replayed_total", "last_replay",
                "journal"} <= set(snap["recovery"])
        assert set(snap["cluster"]) == self.CLUSTER_KEYS
        assert set(snap["queues"]) == {"create_fleet", "describe_instances",
                                       "terminate_instances", "interruption"}
        assert set(snap["caches"]) == {"solver", "instance_types", "ice",
                                       "pricing", "launch_templates"}
        ctrl = snap["controllers"]["provisioning"]
        assert set(ctrl) == {"healthy", "last_cycle_age_s", "threshold_s",
                             "beats", "failures", "last_error",
                             "last_cycle_ms"}
        json.dumps(snap, default=str)  # must serialize

    def test_sections_degrade_independently(self, op):
        op, clock = op
        kube = op.kube
        op.kube = None  # wedge the cluster section
        try:
            snap = snapshot(op)
        finally:
            op.kube = kube
        assert "error" in snap["cluster"]
        assert isinstance(snap["controllers"], dict)  # others survive
        assert "error" not in snap["caches"]


class TestFlightRecorder:
    def test_ring_is_bounded(self, op):
        op, clock = op
        fr = FlightRecorder(op, ring_size=3)
        for _ in range(5):
            fr.record_snapshot()
            clock.step(1.0)
        ring = fr.ring()
        assert len(ring) == 3
        assert ring[0]["ts"] == 2.0  # oldest two evicted

    def test_auto_trigger_rate_limited_per_reason(self, op, tmp_path):
        op, clock = op
        fr = FlightRecorder(op, out_dir=str(tmp_path), clock=clock,
                            min_interval=60.0)
        first = fr.trigger("reconcile_exception", "boom 1")
        assert first is not None
        assert fr.trigger("reconcile_exception", "boom 2") is None
        clock.step(61.0)
        assert fr.trigger("reconcile_exception", "boom 3") is not None
        # force bypasses the limiter (chaos uses this)
        assert fr.trigger("reconcile_exception", "boom 4",
                          force=True) is not None

    def test_bundle_shape(self, op, tmp_path):
        op, clock = op
        op.reconcile_all_once()
        fr = FlightRecorder(op, out_dir=str(tmp_path), clock=clock)
        fr.record_snapshot()
        path = fr.trigger("watchdog_deadman", "provisioning")
        with open(path) as f:
            b = json.load(f)
        assert b["tool"] == "karpenter_tpu.diagnostics_bundle"
        assert b["trigger"] == {"reason": "watchdog_deadman",
                                "detail": "provisioning"}
        assert set(b) >= {"ts", "statusz", "statusz_ring", "logs", "traces",
                          "events", "metrics_text", "recent_triggers"}
        assert len(b["statusz_ring"]) == 1
        assert "karpenter_controller_healthy" in b["metrics_text"]

    def test_operator_wires_deadman_trigger(self, tmp_path, monkeypatch):
        monkeypatch.setenv("KARPENTER_TPU_BUNDLE_DIR", str(tmp_path))
        clock = FakeClock()
        op = _operator(clock)
        try:
            op.reconcile_all_once()
            clock.step(500.0)
            op.watchdog.check()  # deadman fires -> stall listener -> bundle
        finally:
            op.stop()
        bundles = list(tmp_path.glob("bundle_watchdog_deadman_*.json"))
        assert len(bundles) == 1
        b = json.loads(bundles[0].read_text())
        assert b["trigger"]["reason"] == "watchdog_deadman"
        assert "provisioning" in b["trigger"]["detail"]


class TestChaosBundle:
    def test_invariant_breach_dumps_bundle(self, tmp_path, monkeypatch):
        # force a breach: every scenario fails one synthetic invariant
        def always_breach(op, cloud, **kw):
            return [chaos_invariants.Violation(
                "synthetic", "injected breach for the trigger test")]

        monkeypatch.setattr(chaos_invariants, "check_all", always_breach)
        runner = ChaosRunner(seed=7, scenarios=1, out_dir=str(tmp_path))
        artifact = runner.run()
        assert artifact["passed"] is False
        # the bundle lands next to the replay artifact, deterministic name
        (bundle_path,) = artifact["bundles"]
        assert bundle_path.endswith("chaos_seed7_s0_bundle.json")
        with open(bundle_path) as f:
            b = json.load(f)
        assert b["trigger"]["reason"] == "chaos_invariant_breach"
        assert "[synthetic]" in b["trigger"]["detail"]
        # the ring carries per-cycle history from the exact failed run
        assert len(b["statusz_ring"]) > 1
        for section in ("logs", "traces", "events", "statusz"):
            assert section in b
        # scenario dicts stay a pure function of the seed: bundle paths
        # live only at the artifact top level
        assert "bundles" not in artifact["scenarios"][0]


class TestBundleEndpoint:
    def test_debug_bundle_round_trip(self, tmp_path):
        clock = FakeClock()
        op = _operator(clock, serve_http=True, metrics_port=0,
                       health_port=0, webhook_port=-1)
        ports = op.serving.start()
        try:
            op.reconcile_all_once()
            op.flightrecorder.record_snapshot()
            url = (f"http://127.0.0.1:{ports['metrics']}/debug/bundle")
            with urllib.request.urlopen(url, timeout=5) as r:
                assert r.status == 200
                b = json.loads(r.read())
        finally:
            op.serving.stop()
            op.stop()
        assert b["tool"] == "karpenter_tpu.diagnostics_bundle"
        assert b["trigger"]["reason"] == "manual"
        assert len(b["statusz_ring"]) == 1
        assert b["statusz"]["controllers"]["provisioning"]["beats"] >= 1
