"""Chaos plane: seeded fault injection + cross-layer invariants (ISSUE 2).

Tier-1 pieces: plan determinism, the fixed-seed smoke sweep (two full runs
of the same seed must produce byte-identical scenario dicts AND cover >=6
fault kinds across >=3 layers), the wire-mode CreateFleet regression
(5xx + same-token retry replays, never relaunches), and the self-test that
proves the token ledger can actually fail. The multi-seed sweep is the
`slow` tier.
"""

import dataclasses
import json

import pytest

from karpenter_tpu.chaos import (ChaosInjector, ChaosRunner, FaultPlan,
                                 FaultSpec, check_all)
from karpenter_tpu.chaos.invariants import check_token_ledger
from karpenter_tpu.chaos.plan import (KIND_CLOUD_5XX,
                                      KIND_WIRE_5XX_POST_DISPATCH,
                                      LAYER_OF_KIND, ChaosRng)
from karpenter_tpu.cloudbackend import CloudSession, connect
from karpenter_tpu.cloudbackend.server import CloudAPIServer
from karpenter_tpu.fake.cloud import (CreateFleetRequest, FakeCloud,
                                      FleetOverride, LaunchTemplate)
from karpenter_tpu.models.instancetype import Catalog, make_instance_type

SMOKE_SEED = 0


def small_catalog():
    return Catalog(types=[
        make_instance_type("a1.large", cpu=2, memory="4Gi",
                           od_price=0.05, spot_price=0.02)])


def _fleet_payload(token):
    req = CreateFleetRequest(
        launch_template="lt-1",
        overrides=[FleetOverride(instance_type="a1.large", zone="zone-1a",
                                 price=0.05, subnet_id="subnet-zone-1a")],
        capacity=2, capacity_type="on-demand",
        tags={"karpenter.sh/provisioner-name": "default"})
    payload = dataclasses.asdict(req)
    payload["client_token"] = token
    return payload


class TestPlan:
    def test_same_seed_same_schedule(self):
        a = FaultPlan.from_seed(42, scenario=3, wire=True)
        b = FaultPlan.from_seed(42, scenario=3, wire=True)
        assert a.describe() == b.describe()
        assert a.describe()  # non-empty

    def test_different_seeds_differ(self):
        schedules = {json.dumps(FaultPlan.from_seed(s).describe())
                     for s in range(8)}
        assert len(schedules) == 8

    def test_scenarios_fork_the_schedule(self):
        a = FaultPlan.from_seed(7, scenario=0)
        b = FaultPlan.from_seed(7, scenario=1)
        assert a.describe() != b.describe()

    def test_wire_sites_gated(self):
        assert "wire.create_fleet" not in FaultPlan.from_seed(5).faults
        assert "wire.create_fleet" in FaultPlan.from_seed(5, wire=True).faults

    def test_rng_fork_streams_are_independent(self):
        r = ChaosRng(99)
        a = [r.fork("alpha").next_u64() for _ in range(4)]
        b = [r.fork("beta").next_u64() for _ in range(4)]
        assert a != b
        assert a == [ChaosRng(99).fork("alpha").next_u64() for _ in range(4)]


class TestSmoke:
    """Fixed-seed tier-1 smoke: determinism + kind/layer coverage."""

    def test_smoke_sweep_deterministic_and_covers_kinds(self):
        first = ChaosRunner(seed=SMOKE_SEED, scenarios=3).run()
        second = ChaosRunner(seed=SMOKE_SEED, scenarios=3).run()
        # replay contract: scenario dicts are a pure function of the seed
        assert first["scenarios"] == second["scenarios"]
        assert first["passed"], [s["violations"]
                                 for s in first["scenarios"]]
        kinds = set(first["fault_kinds"])
        layers = {LAYER_OF_KIND[k] for k in kinds}
        assert len(kinds) >= 6, kinds
        assert len(layers) >= 3, layers

    def test_injector_disabled_is_noop(self):
        inj = ChaosInjector(FaultPlan.from_seed(1), enabled=False)
        assert inj.maybe("cloud.create_fleet") is None
        assert inj.site_counts() == {}
        assert inj.fired == []


class TestWireChaos:
    """Satellite: the PR-1 CreateFleet ClientToken fix, covered by the
    chaos plane (post-dispatch 5xx is the fault that makes it load-bearing)."""

    def _server(self):
        backing = FakeCloud(catalog=small_catalog())
        backing.create_launch_template(
            LaunchTemplate(name="lt-1", image_id="img-amd64-2"))
        return backing, CloudAPIServer(backing).start()

    def test_post_dispatch_5xx_retry_replays_not_relaunches(self):
        """Launch runs, the 500 eats the response, the session retries the
        same token: the recorded reply must come back, the inner
        CreateFleet must run exactly once, the ledger must stay clean."""
        backing, server = self._server()
        try:
            plan = FaultPlan(seed=1, scenario=0, faults={
                "wire.create_fleet": {0: FaultSpec(
                    "wire.create_fleet", 0, KIND_WIRE_5XX_POST_DISPATCH)}})
            injector = ChaosInjector(plan)
            injector.install_wire(server, backing)
            session = CloudSession(server.endpoint, region="us-test-1")
            out = session.call("CreateFleet", _fleet_payload("tok-chaos-1"))
            assert len(out["instance_ids"]) == 2
            assert backing.create_fleet_api.called_with_count == 1
            assert injector.token_launches == {"tok-chaos-1": 1}
            assert check_token_ledger(injector.token_launches) == []
        finally:
            server.stop()

    def test_inner_5xx_then_same_token_retry_replays_recorded_failure(self):
        """A CreateFleet that FAILED 5xx is also on record: the same-token
        retry replays the failure rather than re-launching (an exception
        proves nothing about whether capacity came up)."""
        backing, server = self._server()
        try:
            plan = FaultPlan(seed=2, scenario=0, faults={
                "cloud.create_fleet": {0: FaultSpec(
                    "cloud.create_fleet", 0, KIND_CLOUD_5XX)}})
            injector = ChaosInjector(plan)
            injector._wrap_cloud_api(backing.create_fleet_api,
                                     "cloud.create_fleet")
            injector.install_wire(server, backing)
            cloud = connect(server.endpoint)
            payload = _fleet_payload("tok-chaos-2")
            for _ in range(2):  # first attempt + same-token client retry
                with pytest.raises(Exception) as exc_info:
                    cloud.session.call("CreateFleet", payload)
                assert "InternalError" in str(exc_info.value)
            # the replay served the second attempt from the record:
            # exactly one inner launch attempt, zero instances
            assert backing.create_fleet_api.called_with_count == 1
            assert len(backing.instances) == 0
            assert check_token_ledger(injector.token_launches) == []
        finally:
            server.stop()

    def test_self_test_broken_dedupe_is_caught_by_ledger(self):
        """Acceptance self-test: with the token dedupe deliberately
        re-broken, the post-dispatch-5xx + retry sequence double-launches
        and the invariant checker MUST catch it — proof the ledger can
        actually fail."""

        class _AmnesiacDict(dict):
            """The PR-1 regression, reintroduced: outcomes are never
            remembered, so every retry looks like a fresh token."""

            def get(self, key, default=None):
                return None

            def __setitem__(self, key, value):
                pass

        backing, server = self._server()
        try:
            server._fleet_replies = _AmnesiacDict()
            plan = FaultPlan(seed=3, scenario=0, faults={
                "wire.create_fleet": {0: FaultSpec(
                    "wire.create_fleet", 0, KIND_WIRE_5XX_POST_DISPATCH)}})
            injector = ChaosInjector(plan)
            injector.install_wire(server, backing)
            session = CloudSession(server.endpoint, region="us-test-1")
            session.call("CreateFleet", _fleet_payload("tok-chaos-3"))
            assert backing.create_fleet_api.called_with_count == 2
            violations = check_token_ledger(injector.token_launches)
            assert [v.invariant for v in violations] == ["token-single-launch"]
            assert "tok-chaos-3" in violations[0].message
        finally:
            server.stop()


class TestInvariantsCatchBreakage:
    """The hermetic invariants must also be falsifiable."""

    def test_leaked_instance_and_unbound_pod_are_flagged(self):
        runner = ChaosRunner(seed=SMOKE_SEED, scenarios=1)
        from karpenter_tpu.utils.clock import FakeClock

        op, cloud = runner._build(FakeClock())
        try:
            from karpenter_tpu.models.pod import make_pod

            op.kube.create("pods", "stuck", make_pod("stuck", cpu="1"))
            # leak: capacity exists in the cloud with no machine/node
            cloud.create_fleet(CreateFleetRequest(
                launch_template="",
                overrides=[FleetOverride(instance_type="t.small",
                                         zone="zone-1a", price=0.05,
                                         subnet_id="subnet-zone-1a")],
                capacity=1, capacity_type="on-demand",
                tags={"cluster": "chaos"}, image_id="img-amd64-2"))
            names = {v.invariant for v in check_all(op, cloud)}
            assert "no-leaked-instances" in names
            assert "pod-binds-once" in names
        finally:
            op.stop()


@pytest.mark.slow
class TestSweep:
    """Full multi-seed sweep: every seed must converge with zero
    invariant violations (`make chaos` / CI slow tier)."""

    @pytest.mark.parametrize("seed", range(20))
    def test_seed_converges_clean(self, seed):
        scenario = ChaosRunner(seed=seed, scenarios=2).run()
        assert scenario["passed"], [s["violations"]
                                    for s in scenario["scenarios"]]
