"""Columnar cluster state: incremental-aggregate and parity properties.

The struct-of-arrays refactor (docs/designs/columnar-state.md) trades full
rescans for incremental column updates; every test here pins an incremental
value to the from-scratch computation it replaced:

  * StateNode.used_vector() == sum of pod resource vectors (satellite 1)
  * ClusterState.total_usage() == the full allocatable scan (satellite 2)
  * PDBIndex-accelerated pod_evictable == the every-PDB sweep (satellite 3)
  * existing_columns() == existing_views() as scheduler input, bit-identical
    encode arrays, across randomized add/bind/delete/mark sequences, and a
    dirtied node always reappears in dirty_since() (satellite 4)
  * fold_node_mask == Requirements.matches_labels row-by-row

Property-style tests use seeded random.Random loops (hypothesis is not in
the image).
"""

import random

import numpy as np

from karpenter_tpu.apis import wellknown as wk
from karpenter_tpu.apis.provisioner import Provisioner
from karpenter_tpu.chaos.invariants import check_columnar_coherence
from karpenter_tpu.models.cluster import (ClusterState, PDBIndex,
                                          PodDisruptionBudget, StateNode,
                                          pod_evictable)
from karpenter_tpu.models.encode import encode_problem, fold_node_mask
from karpenter_tpu.models.instancetype import Catalog, make_instance_type
from karpenter_tpu.models.pod import Taint, make_pod
from karpenter_tpu.models.requirements import (OP_DOES_NOT_EXIST, OP_EXISTS,
                                               OP_GT, OP_IN, OP_LT, OP_NOT_IN,
                                               IncompatibleError, Requirement,
                                               Requirements)
from karpenter_tpu.oracle.consolidation import eligible

_CPU = wk.RESOURCE_INDEX[wk.RESOURCE_CPU]
_MEM = wk.RESOURCE_INDEX[wk.RESOURCE_MEMORY]


class _FakeOp:
    def __init__(self, cluster):
        self.cluster = cluster


def _alloc(cpu_m=4000, mem_mi=16384, pods=110):
    return wk.capacity_vector({wk.RESOURCE_CPU: cpu_m,
                               wk.RESOURCE_MEMORY: mem_mi * 2**20,
                               wk.RESOURCE_PODS: pods})


def _node(name, zone="z-a", prov="default", taints=(), extra_labels=None,
          **kw):
    labels = {wk.LABEL_ZONE: zone, wk.LABEL_CAPACITY_TYPE: "on-demand",
              wk.LABEL_INSTANCE_TYPE: "m.large"}
    labels.update(extra_labels or {})
    return StateNode(name=name, labels=labels, allocatable=_alloc(),
                     provisioner_name=prov, taints=tuple(taints), **kw)


def _rand_pod(rng, name, node_name=None):
    return make_pod(
        name, cpu=f"{rng.randint(1, 8) * 100}m",
        memory=f"{rng.randint(1, 16) * 128}Mi",
        node_name=node_name,
        owner_kind=rng.choice(["ReplicaSet", "ReplicaSet", "DaemonSet", ""]),
        do_not_evict=rng.random() < 0.1,
        labels=tuple(sorted({f"k{rng.randint(0, 2)}": f"v{rng.randint(0, 2)}"
                             for _ in range(rng.randint(0, 3))}.items())),
    )


def _assert_coherent(cluster):
    violations = check_columnar_coherence(_FakeOp(cluster))
    assert not violations, [v.message for v in violations]


# -- satellite 1: incremental used vector --------------------------------------

def test_used_vector_incremental_matches_scan():
    rng = random.Random(7)
    cluster = ClusterState()
    node = _node("n0")
    cluster.add_node(node)
    k = 0
    for step in range(300):
        op = rng.random()
        if op < 0.5 or not node.pods:
            cluster.bind_pod("n0", _rand_pod(rng, f"p{k}"))
            k += 1
        elif op < 0.8:
            node.pods.pop(rng.randrange(len(node.pods)))
        elif op < 0.9:
            node.pods.remove(rng.choice(list(node.pods)))
        else:
            # wholesale reassignment (the watch-refresh path)
            node.pods = list(node.pods)[: rng.randrange(len(node.pods) + 1)]
        fresh = [0] * wk.NUM_RESOURCES
        for p in node.pods:
            for i, v in enumerate(p.resource_vector()):
                fresh[i] += v
        assert node.used_vector() == fresh, f"step {step}"
    _assert_coherent(cluster)


def test_used_vector_detached_node_still_works():
    node = _node("loose")
    node.pods.append(make_pod("a", cpu="500m", memory="1Gi"))
    assert node.used_vector()[_CPU] == 500
    node.pods.clear()
    assert node.used_vector() == [0] * wk.NUM_RESOURCES


# -- satellite 2: per-provisioner running totals -------------------------------

def test_total_usage_matches_full_scan():
    rng = random.Random(11)
    cluster = ClusterState()
    provs = ["p-a", "p-b", "p-c"]
    for step in range(200):
        op = rng.random()
        names = sorted(cluster.nodes)
        if op < 0.5 or not names:
            cluster.add_node(_node(f"n{step}", prov=rng.choice(provs)))
        elif op < 0.75:
            cluster.delete_node(rng.choice(names))
        else:  # reassignment moves the totals between provisioners
            cluster.nodes[rng.choice(names)].provisioner_name = \
                rng.choice(provs)
        for pname in provs:
            cpu = mem = 0
            for n in cluster.nodes.values():
                if n.provisioner_name == pname:
                    cpu += n.allocatable[_CPU]
                    mem += n.allocatable[_MEM] * 2**20
            assert cluster.total_usage(pname) == (cpu, mem), f"step {step}"
    _assert_coherent(cluster)


# -- satellite 3: PDB selector-key index ---------------------------------------

def _rand_pdbs(rng):
    pdbs = []
    for i in range(rng.randint(0, 8)):
        selector = {f"k{rng.randint(0, 2)}": f"v{rng.randint(0, 2)}"
                    for _ in range(rng.randint(0, 2))}
        if rng.random() < 0.5:
            pdbs.append(PodDisruptionBudget(
                f"pdb{i}", selector, min_available=rng.randint(0, 4)))
        else:
            pdbs.append(PodDisruptionBudget(
                f"pdb{i}", selector, max_unavailable=rng.randint(0, 3)))
    return pdbs


def test_pod_evictable_index_parity_random():
    rng = random.Random(13)
    for trial in range(40):
        pdbs = _rand_pdbs(rng)
        index = PDBIndex(pdbs)
        pods = [_rand_pod(rng, f"p{i}") for i in range(30)]
        healthy = {
            pdb.name: sum(1 for p in pods if pdb.matches(p)) for pdb in pdbs}
        for p in pods:
            fast = pod_evictable(p, pdbs, healthy, index=index)
            slow = pod_evictable(p, pdbs, healthy)
            assert fast == slow, (trial, p.name, p.labels)


def test_eligible_columnar_matches_scalar_sweep():
    """eligible()'s cached columnar verdict vs the same function forced down
    the scalar path (a detached twin node not owned by the cluster)."""
    rng = random.Random(17)
    for trial in range(25):
        cluster = ClusterState()
        cluster.pdbs.extend(_rand_pdbs(rng))
        twins = []
        for i in range(8):
            pods = [_rand_pod(rng, f"t{trial}-{i}-{j}", node_name=f"n{i}")
                    for j in range(rng.randint(0, 4))]
            marked = rng.random() < 0.15
            annotations = (
                {"karpenter.sh/do-not-consolidate": "true"}
                if rng.random() < 0.15 else {})
            cluster.add_node(_node(
                f"n{i}", pods=[*pods], marked_for_deletion=marked,
                initialized=rng.random() < 0.9, annotations=dict(annotations)))
            twins.append(_node(
                f"n{i}", pods=[*pods], marked_for_deletion=marked,
                initialized=cluster.nodes[f"n{i}"].initialized,
                annotations=dict(annotations)))
        for i in range(8):
            col = eligible(cluster.nodes[f"n{i}"], cluster)
            scalar = eligible(twins[i], cluster)
            assert col == scalar, (trial, f"n{i}")
        # the verdict cache must not survive a relevant delta
        names = [n for n in sorted(cluster.nodes)
                 if eligible(cluster.nodes[n], cluster)]
        if names:
            victim = cluster.nodes[names[0]]
            victim.pods.append(make_pod(
                f"bare{trial}", cpu="100m", node_name=victim.name,
                owner_kind=""))  # bare pod: never evictable
            assert not eligible(victim, cluster)


# -- satellite 4: columnar <-> dataclass parity + dirty set --------------------

def _random_mutation(rng, cluster, step):
    names = sorted(cluster.nodes)
    op = rng.random()
    if op < 0.30 or not names:
        zone = rng.choice(["z-a", "z-b"])
        taints = ((Taint("dedicated", "gpu", "NoSchedule"),)
                  if rng.random() < 0.2 else ())
        extra = {"team": f"t{rng.randint(0, 3)}"} if rng.random() < 0.5 else {}
        cluster.add_node(_node(f"n{step:03d}", zone=zone, taints=taints,
                               extra_labels=extra))
    elif op < 0.55:
        target = rng.choice(names)
        cluster.bind_pod(target, _rand_pod(rng, f"b{step}", node_name=target))
    elif op < 0.70:
        node = cluster.nodes[rng.choice(names)]
        if node.pods:
            node.pods.pop(rng.randrange(len(node.pods)))
    elif op < 0.80:
        cluster.delete_node(rng.choice(names))
    elif op < 0.90:
        node = cluster.nodes[rng.choice(names)]
        node.marked_for_deletion = not node.marked_for_deletion
    else:
        node = cluster.nodes[rng.choice(names)]
        node.labels["team"] = f"t{rng.randint(0, 3)}"


def test_columnar_views_parity_random_sequences():
    catalog = Catalog(types=[
        make_instance_type("m.large", cpu=4, memory="16Gi", od_price=0.20,
                           spot_price=0.07),
        make_instance_type("m.xlarge", cpu=16, memory="64Gi", od_price=0.80),
    ])
    prov = Provisioner(name="default", requirements=Requirements.of(
        (wk.LABEL_CAPACITY_TYPE, OP_IN, ["spot", "on-demand"])))
    prov.set_defaults()
    rng = random.Random(23)
    cluster = ClusterState()
    pending = [make_pod(f"p-{k}", cpu="500m", memory="1Gi") for k in range(12)]
    for step in range(120):
        _random_mutation(rng, cluster, step)
        if step % 20 != 19:
            continue
        views = cluster.existing_views()
        cols = cluster.existing_columns()
        assert [e.name for e in views] == list(cols.names)
        for v, name in zip(views, cols.names):
            c = cols[list(cols.names).index(name)]
            assert v.name == c.name
            assert list(v.allocatable) == list(c.allocatable)
            assert list(v.used) == list(c.used)
            assert dict(v.labels) == dict(c.labels)
            assert tuple(v.taints) == tuple(c.taints)
            assert v.resident_counts == c.resident_counts
        a = encode_problem(catalog, [prov], pending, existing=views)
        b = encode_problem(catalog, [prov], pending,
                           existing=cluster.existing_columns())
        for f in ("group_vec", "group_count", "group_cap", "group_feas",
                  "group_newprov", "ex_alloc", "ex_used", "ex_feas",
                  "daemon_overhead", "ex_cap", "group_origin"):
            x, y = getattr(a, f, None), getattr(b, f, None)
            if x is None and y is None:
                continue
            assert x is not None and y is not None, f
            assert np.array_equal(np.asarray(x), np.asarray(y)), f
        assert a.n_slots == b.n_slots
        _assert_coherent(cluster)


def test_dirty_set_never_skips_a_delta():
    """Every relevant delta to a node lands it in dirty_since(cursor): a
    consumer that re-evaluates only dirty nodes can never miss a change."""
    rng = random.Random(29)
    cluster = ClusterState()
    for i in range(10):
        cluster.add_node(_node(f"n{i}"))
    for step in range(150):
        cursor = cluster.seq
        names = sorted(cluster.nodes)
        target = rng.choice(names)
        node = cluster.nodes[target]
        op = rng.random()
        if op < 0.25:
            cluster.bind_pod(target, _rand_pod(rng, f"d{step}",
                                               node_name=target))
        elif op < 0.40 and node.pods:
            node.pods.pop()
        elif op < 0.55:
            node.marked_for_deletion = not node.marked_for_deletion
        elif op < 0.70:
            node.price = rng.random()
        elif op < 0.85:
            node.annotations["karpenter.sh/do-not-consolidate"] = \
                rng.choice(["true", "false"])
        else:
            node.initialized = not node.initialized
        assert target in cluster.dirty_since(cursor), f"step {step}"
        # unrelated nodes stay clean unless they actually changed
        assert set(cluster.dirty_since(cluster.seq)) == set()


def test_dirty_cursor_survives_node_churn():
    cluster = ClusterState()
    cluster.add_node(_node("a"))
    cursor = cluster.seq
    cluster.add_node(_node("b"))
    cluster.bind_pod("a", make_pod("x", cpu="100m", node_name="a"))
    assert set(cluster.dirty_since(cursor)) == {"a", "b"}
    cluster.delete_node("b")
    assert "a" in cluster.dirty_since(cursor)


# -- fold_node_mask vs matches_labels ------------------------------------------

def _rand_requirement(rng):
    key = rng.choice(["k0", "k1", "k2", "num"])
    op = rng.choice([OP_IN, OP_NOT_IN, OP_EXISTS, OP_DOES_NOT_EXIST,
                     OP_GT, OP_LT])
    if op in (OP_GT, OP_LT):
        return Requirement.create("num", op, [str(rng.randint(0, 9))])
    values = [f"v{rng.randint(0, 3)}" for _ in range(rng.randint(1, 3))]
    return Requirement.create(key, op, values)


def test_fold_node_mask_matches_scalar_matches_labels():
    rng = random.Random(31)
    for trial in range(60):
        label_sets = []
        for i in range(15):
            labels = {}
            for key in ("k0", "k1", "k2"):
                if rng.random() < 0.6:
                    labels[key] = f"v{rng.randint(0, 3)}"
            if rng.random() < 0.5:
                labels["num"] = str(rng.randint(0, 9))
            label_sets.append(labels)
        cluster = ClusterState()
        for i, labels in enumerate(label_sets):
            cluster.add_node(StateNode(
                name=f"n{i:02d}", labels=dict(labels), allocatable=_alloc()))
        cols = cluster.columns
        order = sorted(cluster.nodes)
        rows = np.fromiter((cols.row_of[n] for n in order), dtype=np.int64)

        def lookup(key):
            kc = cols.label_cols.get(key)
            if kc is None:
                return None
            return kc.codes[rows], kc.num[rows], kc.vocab

        try:
            reqs = Requirements.of()
            for _ in range(rng.randint(1, 4)):
                reqs.add(_rand_requirement(rng))
        except IncompatibleError:
            continue  # contradictory draw (e.g. num>5 ∩ num<3); redraw
        mask = fold_node_mask(reqs, lookup, len(order))
        for i, name in enumerate(order):
            want = reqs.matches_labels(cluster.nodes[name].labels)
            assert bool(mask[i]) == want, (trial, name, list(reqs),
                                           dict(cluster.nodes[name].labels))
