"""Adversarial tests for the inter-pod-affinity dependency HORIZON
(VERDICT r4 ask #5).

The two-round deferred solve (oracle/scheduler.py resolve_pod_affinity +
split_deferred_pods; solver/core.py TPUSolver.solve) resolves required
pod-(anti-)affinity between co-pending groups ONE dependency level per
solve: round 1 places the targets, round 2 places their dependents
against the claims. Chains DEEPER than that horizon are documented
best-effort — these tests pin down the bound and prove the failure mode:

  * the tail of a too-deep chain PENDS (unschedulable, retried next
    reconcile cycle) — it is NEVER placed in violation of its term;
  * retrying with each cycle's claims materialized as existing nodes
    converges one chain level per cycle (the pend-and-retry contract);
  * anti-affinity chains never co-locate a violating pair, at any depth;
  * oracle and device solver agree on all of it (decision parity).

Reference scenarios: /root/reference/test/suites/integration/
scheduling_test.go (inter-pod affinity/anti-affinity); the sequential
kube-scheduler shares the one-level horizon for co-pending pods.
"""

from karpenter_tpu.apis import wellknown as wk
from karpenter_tpu.apis.provisioner import Provisioner
from karpenter_tpu.models.instancetype import Catalog, make_instance_type
from karpenter_tpu.models.pod import PodAffinityTerm, make_pod
from karpenter_tpu.oracle.scheduler import ExistingNode, Scheduler
from karpenter_tpu.solver.core import TPUSolver


def catalog():
    return Catalog(types=[
        make_instance_type("small.2x", cpu=2, memory="8Gi", od_price=0.10),
        make_instance_type("large.8x", cpu=8, memory="32Gi", od_price=0.40),
    ])


def prov():
    p = Provisioner(name="default")
    p.set_defaults()
    return p


def chain_pod(i: int, depth_label: str, cpu="500m"):
    """Pod `app=lvl-{i}` requiring hostname co-location with lvl-{i-1}."""
    terms = ()
    if i > 0:
        terms = (PodAffinityTerm(match_labels=(("app", f"{depth_label}-{i-1}"),),
                                 topology_key=wk.LABEL_HOSTNAME),)
    return make_pod(f"{depth_label}-{i}-pod", cpu=cpu, memory="1Gi",
                    labels=(("app", f"{depth_label}-{i}"),),
                    pod_affinity=terms)


def pods_by_node(res):
    """node id -> set of app labels placed there (claims + existing)."""
    out = {}
    for ni, n in enumerate(res.nodes):
        apps = set()
        for g, cnt in n.pod_counts.items():
            if cnt > 0:
                apps.add(dict(res.groups[g].spec.labels).get("app"))
        out[f"claim-{ni}"] = apps
    for name, per_group in res.existing_by_group.items():
        apps = out.setdefault(name, set())
        for g, cnt in per_group.items():
            if cnt > 0:
                apps.add(dict(res.groups[g].spec.labels).get("app"))
    return out


def assert_no_affinity_violation(res, all_pods, resident_apps=None):
    """Every PLACED pod with a hostname-affinity term shares a node with a
    matching pod (or the node's pre-existing residents match). Pending is
    fine; violation is not."""
    resident_apps = resident_apps or {}
    by_app = {dict(p.labels).get("app"): p for p in all_pods}
    placements = pods_by_node(res)
    for node, apps in placements.items():
        full = apps | resident_apps.get(node, set())
        for app in apps:
            p = by_app.get(app)
            if p is None:
                continue
            for term in p.pod_affinity:
                want = dict(term.match_labels)["app"]
                assert want in full, (
                    f"{app} placed on {node} without its target {want}: "
                    f"placements={placements}")


def assert_no_anti_violation(res, all_pods, resident_apps=None):
    resident_apps = resident_apps or {}
    by_app = {dict(p.labels).get("app"): p for p in all_pods}
    placements = pods_by_node(res)
    for node, apps in placements.items():
        full = apps | resident_apps.get(node, set())
        for app in apps:
            p = by_app.get(app)
            if p is None:
                continue
            for term in p.pod_anti_affinity:
                avoid = dict(term.match_labels)["app"]
                assert avoid not in (full - {app}), (
                    f"{app} co-located with anti-target {avoid} on {node}")


def test_reference_utilization_invariant_on_real_catalog():
    """The reference's real-cluster utilization check, runnable verbatim
    now that t3a.small is a REAL catalog entry
    (test/suites/utilization/suite_test.go:55-74): provisioner pinned to
    instance-type t3a.small, 100 pods of 1.5 CPU — one pod per node
    enforced by instance size, exactly 100 nodes. Oracle and kernel agree
    on all 100 decisions."""
    from karpenter_tpu.models.requirements import OP_IN, Requirements
    from karpenter_tpu.providers.instancetypes import generate_fleet_catalog

    catalog = generate_fleet_catalog()
    p = Provisioner(name="default", requirements=Requirements.of(
        (wk.LABEL_INSTANCE_TYPE, OP_IN, ["t3a.small"])))
    p.set_defaults()
    pods = [make_pod(f"u-{i}", cpu="1.5") for i in range(100)]
    sched = Scheduler(catalog, [p])
    ores = sched.schedule(list(pods))
    kres = TPUSolver(catalog, [p]).solve(list(pods))
    assert kres.decisions() == ores.node_decisions(sched.options)
    assert kres.unschedulable_count() == 0
    assert len(kres.nodes) == 100
    assert all(n.option.itype.name == "t3a.small" and n.pod_count == 1
               for n in kres.nodes)


def test_gpu_pods_pick_cheapest_real_gpu_type():
    """Extended-resource decisions on the real catalog: 1-GPU pods land
    on the cheapest amd64 on-demand NVIDIA type (reference scenario
    shape: test/suites/integration extended resources), one node per GPU
    pod when the type carries a single device."""
    from karpenter_tpu.providers.instancetypes import generate_fleet_catalog

    catalog = generate_fleet_catalog()
    p = Provisioner(name="default")
    p.set_defaults()  # linux/amd64/on-demand
    pods = [make_pod(f"g-{i}", cpu="2", memory="8Gi",
                     extended={wk.RESOURCE_NVIDIA_GPU: 1}) for i in range(4)]
    sched = Scheduler(catalog, [p])
    ores = sched.schedule(list(pods))
    kres = TPUSolver(catalog, [p]).solve(list(pods))
    assert kres.decisions() == ores.node_decisions(sched.options)
    assert kres.unschedulable_count() == 0
    # FFD packs the whole group onto one node when a multi-GPU type can
    # host it (the reference's greedy pack does the same), and the final
    # decision must be the CHEAPEST amd64 OD type holding that many
    # GPUs (computed, not hard-coded, so a catalog regen that changes
    # the floor keeps the test honest)
    (node,) = kres.nodes
    assert node.pod_count == 4

    def fits(t):
        cap = dict(t.capacity)
        labels = dict(t.labels)
        return (cap.get(wk.RESOURCE_NVIDIA_GPU, 0) >= 4
                and labels[wk.LABEL_ARCH] == "amd64"
                and cap[wk.RESOURCE_CPU] >= 4 * 2000)
    cheapest = min((t for t in catalog.types if fits(t)),
                   key=lambda t: t.offerings[0].price)
    assert node.option.itype.name == cheapest.name


class TestAffinityChainHorizon:
    def test_depth2_resolves_in_one_solve(self):
        """A <- B: exactly the two-round horizon — fully placed."""
        pods = [chain_pod(0, "c2"), chain_pod(1, "c2")]
        res = TPUSolver(catalog(), [prov()]).solve(pods)
        assert res.unschedulable_count() == 0
        assert_no_affinity_violation(res, pods)
        # co-located on one node
        (apps,) = [a for a in pods_by_node(res).values() if a]
        assert apps == {"c2-0", "c2-1"}

    def test_depth4_chain_pends_beyond_horizon_never_violates(self):
        """A <- B <- C <- D: whatever the horizon leaves unplaced must
        pend; nothing may be placed away from its target."""
        pods = [chain_pod(i, "c4") for i in range(4)]
        res = TPUSolver(catalog(), [prov()]).solve(pods)
        assert_no_affinity_violation(res, pods)
        placed = sum(n.pod_count for n in res.nodes) + \
            sum(res.existing_counts.values())
        assert placed + res.unschedulable_count() == 4
        # the horizon guarantees at least the first two levels land
        assert placed >= 2
        assert res.unschedulable_count() > 0, (
            "a 4-level chain resolving in one solve would mean the horizon "
            "widened — update the documented bound and this suite")

    def test_chain_converges_one_level_per_retry_cycle(self):
        """Pend-and-retry: materializing each cycle's claims as existing
        nodes (what the controller's bind step does) resolves one more
        chain level per cycle; depth-6 converges within 5 cycles with zero
        violations at EVERY intermediate step."""
        depth = 6
        all_pods = [chain_pod(i, "c6") for i in range(depth)]
        solver = TPUSolver(catalog(), [prov()])
        pending = list(all_pods)
        existing: "list[ExistingNode]" = []
        resident_apps: "dict[str, set]" = {}
        for cycle in range(depth):
            res = solver.solve(pending, existing=existing)
            assert_no_affinity_violation(res, all_pods, resident_apps)
            # materialize this cycle's claims as bound nodes with residents
            new_existing = solver._nodes_as_existing(res, None)
            for ne, node in zip(new_existing, res.nodes):
                name = f"bound-{cycle}-{node.option.itype.name}-{len(existing)}"
                ne.name = name
                existing.append(ne)
                resident_apps[name] = {
                    dict(res.groups[g].spec.labels).get("app")
                    for g, c in node.pod_counts.items() if c > 0}
            # placements on existing nodes extend those nodes' residents
            for name, per_group in res.existing_by_group.items():
                resident_apps.setdefault(name, set()).update(
                    dict(res.groups[g].spec.labels).get("app")
                    for g, c in per_group.items() if c > 0)
                for e in existing:
                    if e.name == name:
                        e.resident = tuple(e.resident) + tuple(
                            res.groups[g].spec for g, c in per_group.items()
                            for _ in range(c))
            placed_apps = set().union(*pods_by_node(res).values(), set())
            pending = [p for p in pending
                       if dict(p.labels).get("app") not in placed_apps]
            if not pending:
                break
        assert not pending, (
            f"chain did not converge: {[p.name for p in pending]} still "
            f"pending after {depth} cycles")
        # final shape: each level co-located with its predecessor
        for i in range(1, depth):
            host = [n for n, apps in resident_apps.items()
                    if f"c6-{i}" in apps]
            assert host and any(f"c6-{i-1}" in resident_apps[h] for h in host)

    def test_anti_affinity_chain_never_colocates_any_depth(self):
        """B anti A, C anti B, D anti C: every prefix of the chain must be
        violation-free regardless of where the horizon lands."""
        pods = []
        for i in range(4):
            terms = ()
            if i > 0:
                terms = (PodAffinityTerm(
                    match_labels=(("app", f"anti-{i-1}"),),
                    topology_key=wk.LABEL_HOSTNAME),)
            pods.append(make_pod(f"anti-{i}-pod", cpu="500m", memory="1Gi",
                                 labels=(("app", f"anti-{i}"),),
                                 pod_anti_affinity=terms))
        res = TPUSolver(catalog(), [prov()]).solve(pods)
        assert_no_anti_violation(res, pods)
        # anti-affinity is always satisfiable by opening nodes: no pending
        assert res.unschedulable_count() == 0

    def test_mutual_cycle_first_wins_colocates(self):
        """A needs B, B needs A: first-wins keeps one primary; both land
        together (the k8s first-pod bootstrap rule, not a deadlock)."""
        a = make_pod("cyc-a", cpu="500m", memory="1Gi",
                     labels=(("app", "cyc-a"),),
                     pod_affinity=(PodAffinityTerm(
                         match_labels=(("app", "cyc-b"),),
                         topology_key=wk.LABEL_HOSTNAME),))
        b = make_pod("cyc-b", cpu="500m", memory="1Gi",
                     labels=(("app", "cyc-b"),),
                     pod_affinity=(PodAffinityTerm(
                         match_labels=(("app", "cyc-a"),),
                         topology_key=wk.LABEL_HOSTNAME),))
        res = TPUSolver(catalog(), [prov()]).solve([a, b])
        assert_no_affinity_violation(res, [a, b])
        placed = sum(n.pod_count for n in res.nodes)
        assert placed + res.unschedulable_count() == 2
        if placed == 2:  # co-located when both land
            (apps,) = [x for x in pods_by_node(res).values() if x]
            assert apps == {"cyc-a", "cyc-b"}

    def test_oracle_and_solver_agree_on_horizon_behavior(self):
        """The documented bound is a SHARED contract: oracle and kernel
        must pend the same pods on a depth-4 chain."""
        pods = [chain_pod(i, "par") for i in range(4)]
        sched = Scheduler(catalog(), [prov()])
        ores = sched.schedule(list(pods))
        kres = TPUSolver(catalog(), [prov()]).solve(list(pods))
        assert kres.unschedulable_count() == len(ores.unschedulable)
        assert kres.decisions() == ores.node_decisions(sched.options)

    def test_zone_affinity_chain_pends_not_misplaces(self):
        """Same horizon discipline for zone-scoped terms: the tail pends
        rather than landing in a zone without its target."""
        pods = []
        for i in range(3):
            terms = ()
            if i > 0:
                terms = (PodAffinityTerm(
                    match_labels=(("app", f"z-{i-1}"),),
                    topology_key=wk.LABEL_ZONE),)
            pods.append(make_pod(f"z-{i}-pod", cpu="500m", memory="1Gi",
                                 labels=(("app", f"z-{i}"),),
                                 node_selector=None, pod_affinity=terms))
        res = TPUSolver(catalog(), [prov()]).solve(pods)
        # zone check: every placed dependent shares a zone with its target
        zone_of_app = {}
        for ni, n in enumerate(res.nodes):
            for g, cnt in n.pod_counts.items():
                if cnt > 0:
                    app = dict(res.groups[g].spec.labels).get("app")
                    zone_of_app.setdefault(app, set()).add(n.option.zone)
        for i in range(1, 3):
            zones = zone_of_app.get(f"z-{i}")
            if zones is None:
                continue  # pended — the allowed failure mode
            assert zone_of_app.get(f"z-{i-1}") is not None
            assert zones <= zone_of_app[f"z-{i-1}"], (
                f"z-{i} landed outside its target's zone(s)")
