"""Consolidation: oracle semantics + kernel parity.

Encodes designs/consolidation.md behavior: delete when pods fit elsewhere,
replace with one strictly-cheaper node, min-disruption candidate selection,
do-not-evict/bare-pod/PDB blockers.
"""

import random

from karpenter_tpu.apis import wellknown as wk
from karpenter_tpu.apis.provisioner import Provisioner
from karpenter_tpu.models.cluster import ClusterState, PodDisruptionBudget, StateNode
from karpenter_tpu.models.instancetype import Catalog, make_instance_type
from karpenter_tpu.models.pod import make_pod
from karpenter_tpu.oracle.consolidation import (find_consolidation,
                                                find_multi_consolidation)
from karpenter_tpu.ops.consolidate import run_consolidation


def catalog():
    return Catalog(types=[
        make_instance_type("small.2x", cpu=2, memory="8Gi", od_price=0.10),
        make_instance_type("medium.4x", cpu=4, memory="16Gi", od_price=0.20),
        make_instance_type("large.8x", cpu=8, memory="32Gi", od_price=0.40),
    ])


def prov(**kw):
    p = Provisioner(name="default", **kw)
    p.set_defaults()
    return p


def node(name, cpu_alloc, price, pods, itype="large.8x", **kw):
    return StateNode(
        name=name,
        labels={wk.LABEL_ARCH: "amd64", wk.LABEL_OS: "linux",
                wk.LABEL_ZONE: "zone-1a", wk.LABEL_CAPACITY_TYPE: "on-demand",
                wk.LABEL_INSTANCE_TYPE: itype},
        allocatable=wk.capacity_vector({wk.RESOURCE_CPU: cpu_alloc * 1000,
                                        wk.RESOURCE_MEMORY: cpu_alloc * 4 * 2**30,
                                        wk.RESOURCE_PODS: 110}),
        price=price,
        provisioner_name="default",
        pods=list(pods),
        **kw,
    )


def _assert_parity(cluster, cat, provs, now=0.0):
    # oracle mirrors run_consolidation's policy: multi-node first, then
    # singles (reference mechanism order, deprovisioning.md:74-77)
    o = find_multi_consolidation(cluster, cat, provs, now=now)
    if o is None:
        o = find_consolidation(cluster, cat, provs, now=now)
    k = run_consolidation(cluster, cat, provs, now=now)
    if o is None:
        assert k is None, f"kernel found {k}, oracle none"
    else:
        assert k is not None, f"oracle found {o}, kernel none"
        assert (o.kind, o.nodes, o.replacement) == (k.kind, k.nodes, k.replacement), (o, k)
        assert abs(o.disruption_cost - k.disruption_cost) < 1e-9
    return o


def test_delete_when_pods_fit_elsewhere():
    cluster = ClusterState()
    cluster.add_node(node("n1", 8, 0.40, [make_pod("a", cpu="1", memory="1Gi", node_name="n1")]))
    # n2 hosts a do-not-evict pod: it can HOST rescheduled pods but is not
    # itself a candidate — so the multi-node mechanism (which runs FIRST,
    # reference order) has <2 candidates and the single delete decides
    cluster.add_node(node("n2", 8, 0.40, [make_pod("b", cpu="1", memory="1Gi",
                                                   node_name="n2",
                                                   do_not_evict=True)]))
    act = _assert_parity(cluster, catalog(), [prov()])
    assert act.kind == "delete"
    assert act.savings == 0.40


def test_pair_action_shadows_single_delete():
    """Reference mechanism order (deprovisioning.md:74-77): multi-node runs
    BEFORE single-node, so two half-empty nodes consolidate into one
    cheaper replacement even though a plain single delete also exists."""
    cluster = ClusterState()
    cluster.add_node(node("n1", 8, 0.40, [make_pod("a", cpu="1", memory="1Gi",
                                                   node_name="n1")]))
    cluster.add_node(node("n2", 8, 0.40, [make_pod("b", cpu="1", memory="1Gi",
                                                   node_name="n2")]))
    act = _assert_parity(cluster, catalog(), [prov()])
    assert act.kind == "replace" and set(act.nodes) == {"n1", "n2"}
    assert act.replacement[0] == "small.2x"
    assert abs(act.savings - 0.70) < 1e-9


def test_replace_with_cheaper_node():
    cluster = ClusterState()
    # lone big node with one small pod: nothing else to host it -> replace
    cluster.add_node(node("big", 8, 0.40, [make_pod("a", cpu="1", memory="1Gi")]))
    act = _assert_parity(cluster, catalog(), [prov()])
    assert act.kind == "replace"
    assert act.replacement[0] == "small.2x"
    assert abs(act.savings - 0.30) < 1e-9


def test_no_action_when_cluster_tight():
    cluster = ClusterState()
    # cheapest type already; no cheaper replacement exists, no room elsewhere
    cluster.add_node(node("n1", 2, 0.10,
                          [make_pod("a", cpu="1.5", memory="1Gi")], itype="small.2x"))
    assert _assert_parity(cluster, catalog(), [prov()]) is None


def test_min_disruption_candidate_wins():
    cluster = ClusterState()
    # both deletable; n-few has fewer pods -> lower disruption cost.
    # A PDB allowing 10 evictions blocks the PAIR (11 pods at once) so the
    # single-node mechanism decides — as in the reference, min-disruption
    # ordering applies within a mechanism.
    big_pods = [make_pod(f"b{i}", cpu="100m", memory="128Mi",
                         labels=(("app", "d"),)) for i in range(10)]
    few_pods = [make_pod("f0", cpu="100m", memory="128Mi",
                         labels=(("app", "d"),))]
    cluster.add_node(node("n-big", 8, 0.40, big_pods))
    cluster.add_node(node("n-few", 8, 0.40, few_pods))
    cluster.add_node(node("n-host", 8, 0.40, []))
    cluster.pdbs.append(PodDisruptionBudget("d-pdb", {"app": "d"},
                                            max_unavailable=10))
    # host node empty => skipped as candidate (emptiness path), but hosts pods
    act = _assert_parity(cluster, catalog(), [prov()])
    assert act.node == "n-few"


def test_do_not_evict_blocks():
    cluster = ClusterState()
    cluster.add_node(node("n1", 8, 0.40, [make_pod("a", cpu="1", memory="1Gi",
                                                   do_not_evict=True)]))
    cluster.add_node(node("n2", 8, 0.40, []))
    assert _assert_parity(cluster, catalog(), [prov()]) is None


def test_bare_pod_blocks():
    cluster = ClusterState()
    cluster.add_node(node("n1", 8, 0.40, [make_pod("a", cpu="1", memory="1Gi",
                                                   owner_kind="")]))
    cluster.add_node(node("n2", 8, 0.40, []))
    assert _assert_parity(cluster, catalog(), [prov()]) is None


def test_pdb_blocks():
    cluster = ClusterState()
    p = make_pod("a", cpu="1", memory="1Gi", labels=(("app", "web"),))
    cluster.add_node(node("n1", 8, 0.40, [p]))
    cluster.add_node(node("n2", 8, 0.40, []))
    cluster.pdbs.append(PodDisruptionBudget("web-pdb", {"app": "web"}, min_available=1))
    assert _assert_parity(cluster, catalog(), [prov()]) is None


def test_lifetime_weighting_prefers_expiring():
    p = prov(ttl_seconds_until_expired=3600)
    cluster = ClusterState()
    pods_a = [make_pod("a", cpu="100m", memory="128Mi")]
    pods_b = [make_pod("b", cpu="100m", memory="128Mi")]
    cluster.add_node(node("n-young", 8, 0.40, pods_a, created_ts=3500.0))
    cluster.add_node(node("n-old", 8, 0.40, pods_b, created_ts=0.0))
    cluster.add_node(node("n-host", 8, 0.40, []))
    act = _assert_parity(cluster, catalog(), [p], now=3600.0)
    # n-old has 0 lifetime remaining -> zero cost -> chosen
    assert act.node == "n-old"


def test_randomized_consolidation_parity():
    rng = random.Random(5)
    for _ in range(8):
        cat = Catalog(types=[
            make_instance_type(f"t.{i}", cpu=2 ** (i + 1), memory=f"{2 ** (i + 3)}Gi",
                               od_price=round(0.05 * 2 ** i, 3))
            for i in range(4)
        ])
        cluster = ClusterState()
        for n in range(rng.randint(2, 6)):
            cpu_alloc = rng.choice([2, 4, 8, 16])
            npods = rng.randint(0, 3)
            pods = [make_pod(f"n{n}p{i}", cpu=rng.choice(["100m", "500m", "1"]),
                             memory="512Mi") for i in range(npods)]
            cluster.add_node(node(f"node-{n}", cpu_alloc,
                                  round(0.05 * cpu_alloc / 2, 3), pods,
                                  itype=f"t.{cpu_alloc}"))
        _assert_parity(cluster, cat, [prov()])


def test_pdb_aggregate_blocks_multi_pod_eviction():
    # PDB minAvailable=4 over 5 replicas; candidate holds 2 -> allowed=1 < 2
    cluster = ClusterState()
    mk = lambda i, nn: make_pod(f"w{i}", cpu="100m", memory="128Mi",
                                labels=(("app", "web"),), node_name=nn)
    cluster.add_node(node("cand", 8, 0.40, [mk(0, "cand"), mk(1, "cand")]))
    cluster.add_node(node("rest", 8, 0.40, [mk(2, "rest"), mk(3, "rest"), mk(4, "rest")]))
    cluster.add_node(node("spare", 8, 0.40, []))
    cluster.pdbs.append(PodDisruptionBudget("web-pdb", {"app": "web"}, min_available=4))
    assert _assert_parity(cluster, catalog(), [prov()]) is None


def test_pdb_single_pod_candidate_allowed():
    # same PDB but candidate holds only 1 matching pod -> allowed=1 >= 1
    cluster = ClusterState()
    mk = lambda i, nn: make_pod(f"w{i}", cpu="100m", memory="128Mi",
                                labels=(("app", "web"),), node_name=nn)
    cluster.add_node(node("cand", 8, 0.40, [mk(0, "cand")]))
    cluster.add_node(node("rest", 8, 0.40, [mk(1, "rest"), mk(2, "rest"),
                                            mk(3, "rest"), mk(4, "rest")]))
    cluster.add_node(node("spare", 8, 0.40, []))
    cluster.pdbs.append(PodDisruptionBudget("web-pdb", {"app": "web"}, min_available=4))
    act = _assert_parity(cluster, catalog(), [prov()])
    assert act is not None and act.node == "cand"


# -- multi-node (pair) search: the TPU headroom feature the Go reference
# -- skips for cost (designs/consolidation.md 'Selecting Nodes') -------------

def pair_catalog():
    return Catalog(types=[
        make_instance_type("small.2x", cpu=2, memory="8Gi", od_price=0.10),
        make_instance_type("medium.4x", cpu=4, memory="16Gi", od_price=0.20),
        make_instance_type("large.8x", cpu=8, memory="32Gi", od_price=0.40),
        make_instance_type("xlarge.16x", cpu=16, memory="64Gi", od_price=0.70),
    ])


def test_pair_replace_when_singles_fail():
    # two FULL large.8x nodes (8x1cpu pods each): no single-node action — the
    # other node has no headroom and no cheaper-than-0.40 type holds 8 pods —
    # but BOTH consolidate onto one xlarge.16x (0.70 < 0.80 combined)
    cluster = ClusterState()
    for ni in range(2):
        pods = [make_pod(f"p{ni}-{i}", cpu="1", memory="1Gi",
                         node_name=f"n-{ni}") for i in range(8)]
        cluster.add_node(node(f"n-{ni}", 8, 0.40, pods))
    act = _assert_parity(cluster, pair_catalog(), [prov()])
    assert act is not None
    assert act.kind == "replace" and act.nodes == ("n-0", "n-1")
    assert act.replacement[0] == "xlarge.16x"
    assert abs(act.savings - 0.10) < 1e-6

def test_pair_search_skipped_when_single_action_exists():
    # candidate with movable pods: single delete wins, pair sweep never runs
    cluster = ClusterState()
    cluster.add_node(node("cand", 8, 0.40,
                          [make_pod("a", cpu="1", memory="1Gi", node_name="cand")]))
    cluster.add_node(node("host", 8, 0.40, []))
    # host is empty -> not an eligible candidate itself; cand's pod fits there
    act = _assert_parity(cluster, pair_catalog(), [prov()])
    assert act is not None and act.kind == "delete" and act.nodes == ("cand",)

def test_pair_none_when_combined_not_cheaper():
    # two FULL xlarge nodes: singles fail (no cheaper type holds 16 pods)
    # and the pair's 32 pods exceed every cheaper-than-combined type
    cluster = ClusterState()
    for ni in range(2):
        pods = [make_pod(f"p{ni}-{i}", cpu="1", memory="1Gi",
                         node_name=f"n-{ni}") for i in range(16)]
        cluster.add_node(node(f"n-{ni}", 16, 0.70, pods, itype="xlarge.16x"))
    assert _assert_parity(cluster, pair_catalog(), [prov()]) is None

def test_multi_node_flag_off_restores_reference_semantics():
    # the pair-consolidatable scenario from test_pair_replace_when_singles_fail:
    # with multi_node off the reference's single-node-only semantics hold
    cluster = ClusterState()
    for ni in range(2):
        pods = [make_pod(f"p{ni}-{i}", cpu="1", memory="1Gi",
                         node_name=f"n-{ni}") for i in range(8)]
        cluster.add_node(node(f"n-{ni}", 8, 0.40, pods))
    assert run_consolidation(cluster, pair_catalog(), [prov()],
                             multi_node=False) is None
    assert run_consolidation(cluster, pair_catalog(), [prov()]).kind == "replace"

def test_pair_blocked_by_combined_pdb_budget():
    # each node alone passes the PDB aggregate check (2 evictions allowed),
    # but evicting BOTH at once needs 4 -> the pair must be rejected
    cluster = ClusterState()
    mk = lambda i, nn: make_pod(f"w{i}", cpu="1", memory="1Gi",
                                labels=(("app", "web"),), node_name=nn)
    for ni in range(2):
        pods = [mk(ni * 8 + i, f"n-{ni}") for i in range(8)]
        cluster.add_node(node(f"n-{ni}", 8, 0.40, pods))
    # 16 replicas, minAvailable=14 -> disruptions_allowed = 2 < 8+8
    # (each node's own 8 > 2 too... use minAvailable=8: allowed=8 >= 8 per
    # node, but the pair's 16 > 8)
    cluster.pdbs.append(PodDisruptionBudget("web-pdb", {"app": "web"},
                                            min_available=8))
    act = _assert_parity(cluster, pair_catalog(), [prov()])
    assert act is None


def test_do_not_consolidate_annotation_vetoes_candidacy():
    """karpenter.sh/do-not-consolidate on a NODE (reference
    deprovisioning.md node-level veto): the annotated node is never a
    candidate even when it's the obvious win."""
    from karpenter_tpu.oracle.consolidation import (
        ANNOTATION_DO_NOT_CONSOLIDATE, eligible)

    cat = pair_catalog()
    cluster = ClusterState()
    big = cat.by_name["large.8x"]
    for i in range(4):
        cluster.add_node(StateNode(
            name=f"n-{i}", labels={**big.labels_dict(),
                                   wk.LABEL_ZONE: "zone-1a",
                                   wk.LABEL_CAPACITY_TYPE: "on-demand",
                                   wk.LABEL_PROVISIONER: "default"},
            allocatable=big.allocatable_vector(), instance_type=big.name,
            zone="zone-1a", capacity_type="on-demand",
            price=big.offerings[0].price, provisioner_name="default",
            pods=[make_pod(f"p-{i}", cpu="500m", memory="1Gi",
                           node_name=f"n-{i}")]))
    p = prov()
    baseline = run_consolidation(cluster, cat, [p])
    assert baseline is not None
    victim = baseline.nodes[0]
    cluster.nodes[victim].annotations[ANNOTATION_DO_NOT_CONSOLIDATE] = "true"
    assert not eligible(cluster.nodes[victim], cluster)
    after = run_consolidation(cluster, cat, [p])
    assert after is None or victim not in after.nodes
    # oracle spec agrees
    o = find_consolidation(cluster, cat, [p])
    assert o is None or victim not in o.nodes


def _spot_node(name, cpu_alloc, price, pods, itype="large.8x", **kw):
    n = node(name, cpu_alloc, price, pods, itype=itype, **kw)
    n.capacity_type = "spot"
    n.labels[wk.LABEL_CAPACITY_TYPE] = "spot"
    return n


def test_spot_node_never_replaced_only_deleted():
    """Reference deprovisioning.md:88: spot nodes consolidate by deletion
    only — a cheaper replacement must NOT be launched for them."""
    cluster = ClusterState()
    # lone big spot node: the on-demand twin of this shape yields `replace`
    # (test_replace_with_cheaper_node); spot must yield nothing
    cluster.add_node(_spot_node("big", 8, 0.40,
                                [make_pod("a", cpu="1", memory="1Gi")]))
    act = _assert_parity(cluster, catalog(), [prov()])
    assert act is None


def test_spot_node_delete_path_still_works():
    cluster = ClusterState()
    cluster.add_node(_spot_node("spot-a", 8, 0.40,
                                [make_pod("a", cpu="1", memory="1Gi")]))
    cluster.add_node(node("host", 8, 0.40, []))
    act = _assert_parity(cluster, catalog(), [prov()])
    assert act is not None and act.kind == "delete"
    assert act.nodes == ("spot-a",) or act.nodes == ("host",)


def test_pair_with_spot_member_cannot_replace():
    """The multi-node extension inherits the delete-only rule when ANY set
    member is spot (consistent extrapolation of the reference rule)."""
    cluster = ClusterState()
    # the on-demand version of this cluster produces a pair replace
    # (test_pair_replace_when_singles_fail idiom): two half-full nodes whose
    # combined pods fit one cheaper node
    def build(spot_first):
        # the test_pair_replace_when_singles_fail shape: two FULL large.8x
        # nodes whose combined pods fit one xlarge.16x
        c = ClusterState()
        for ni in range(2):
            pods = [make_pod(f"p{ni}-{i}", cpu="1", memory="1Gi",
                             node_name=f"n-{ni}") for i in range(8)]
            mk = _spot_node if (spot_first and ni == 0) else node
            c.add_node(mk(f"n-{ni}", 8, 0.40, pods))
        return c

    # the all-on-demand twin DOES pair-replace — proving the gate is what
    # suppresses the action below
    twin = find_multi_consolidation(build(False), pair_catalog(), [prov()])
    assert twin is not None and twin.kind == "replace"
    cluster = build(True)
    o = find_multi_consolidation(cluster, pair_catalog(), [prov()])
    k = run_consolidation(cluster, pair_catalog(), [prov()])
    assert o is None or o.kind != "replace"
    assert k is None or k.kind != "replace"


def test_fuzz_dense_vs_flat_dispatch_bit_parity():
    """The two-buffer flat dispatch (encode once, ship i32+u8, unpack on
    device) must be bit-identical to the dense per-leaf dispatch across
    random shapes — including lanes with per-node caps (anti-affinity
    pods trigger ex_cap) and heterogeneous prices (multiple feas-table
    rows). This locks the _flatten_batch/_verdicts_flat layout contract
    for every optional-array combination, not just the benchmark shape."""
    import jax
    import numpy as np

    from karpenter_tpu.oracle.consolidation import (MAX_PAIR_CANDIDATES,
                                                    candidate_pairs, eligible)
    from karpenter_tpu.ops import consolidate as cmod

    rng = random.Random(11)
    for trial in range(6):
        cat = Catalog(types=[
            make_instance_type(f"f.{i}", cpu=2 ** (i + 1),
                               memory=f"{2 ** (i + 3)}Gi",
                               od_price=round(0.04 * 2 ** i, 3))
            for i in range(4)
        ])
        cluster = ClusterState()
        for n in range(rng.randint(2, 7)):
            cpu_alloc = rng.choice([2, 4, 8])
            pods = []
            for i in range(rng.randint(0, 3)):
                pods.append(make_pod(
                    f"t{trial}n{n}p{i}", cpu=rng.choice(["100m", "500m"]),
                    memory="256Mi",
                    # some pods carry hostname anti-affinity: exercises the
                    # ex_cap optional array in the flat layout
                    anti_affinity_hostname=(rng.random() < 0.3)))
            cluster.add_node(node(
                f"t{trial}-node-{n}", cpu_alloc,
                round(0.04 * cpu_alloc / 2 * rng.choice([1.0, 1.5]), 3),
                pods, itype=f"f.{cpu_alloc}"))
        p = prov(consolidation_enabled=True)
        provs = [p]
        cand_nodes = [cluster.nodes[nm] for nm in sorted(cluster.nodes)
                      if eligible(cluster.nodes[nm], cluster)]
        if not cand_nodes:
            continue
        sets = candidate_pairs(cluster, provs, 0.0, MAX_PAIR_CANDIDATES,
                               nodes=cand_nodes) + [(n,) for n in cand_nodes]
        batch = cmod.encode_consolidation(cluster, cat, provs, cand_sets=sets)
        if batch is None:
            continue
        dense = np.asarray(cmod._batched_pack_verdicts(
            jax.device_put(batch.inputs), cmod.N_SLOTS,
            feas_table=jax.device_put(batch.feas_table),
            feas_idx=jax.device_put(batch.feas_idx)))
        i32, u8, dims = cmod._flatten_batch(batch)
        da, dt = cmod._dev_grid_arrays(batch.grid)
        flat = np.asarray(cmod._verdicts_flat(
            jax.device_put(i32), jax.device_put(u8), da, dt,
            dims, cmod.N_SLOTS))
        assert dense.shape == flat.shape and (dense == flat).all(), (
            f"trial {trial}: dense/flat divergence at "
            f"{np.argwhere(dense != flat)[:4]}")
