"""The fully deployed topology in one process tree: EVERY boundary a
production install has crosses a real socket simultaneously —

  coordination plane  ->  HttpKubeStore over the mini apiserver (HTTP)
  cloud backend       ->  HttpCloud over CloudAPIServer (HTTP)
  solver              ->  RemoteSolver over the gRPC sidecar

and the controller plane schedules, launches, binds, and terminates
through all three at once. This is the integration the deploy/ manifests
describe (controller pod + solver sidecar + apiserver + cloud API), run
hermetically.
"""

import pytest

from karpenter_tpu.apis.nodetemplate import NodeTemplate
from karpenter_tpu.apis.provisioner import Provisioner
from karpenter_tpu.apis.settings import Settings
from karpenter_tpu.cloudbackend import connect
from karpenter_tpu.cloudbackend.server import CloudAPIServer
from karpenter_tpu.coordination.httpkube import HttpKubeStore
from karpenter_tpu.fake.apiserver import serve as serve_apiserver
from karpenter_tpu.fake.cloud import FakeCloud
from karpenter_tpu.models.pod import make_pod
from karpenter_tpu.operator import Operator
from karpenter_tpu.providers.instancetypes import generate_fleet_catalog


@pytest.fixture
def deployed(monkeypatch):
    from karpenter_tpu.solver.client import RemoteSolver
    from karpenter_tpu.solver.service import serve as serve_solver

    # route every solve across the gRPC boundary: the deployed-topology
    # test exists to exercise the wire, not the in-process fallback the
    # measured routing policy would prefer at toy sizes
    monkeypatch.setenv("KARPENTER_TPU_ROUTE_CROSSOVER", "0")

    catalog = generate_fleet_catalog(max_types=80)
    backing = FakeCloud(catalog=catalog)
    cloud_srv = CloudAPIServer(backing).start()
    api_srv, api_port, _ = serve_apiserver()
    solver_srv, solver_port, _ = serve_solver()
    kube = HttpKubeStore(f"http://127.0.0.1:{api_port}")
    kube.start()
    cloud = connect(cloud_srv.endpoint)
    settings = Settings(cluster_name="deployed",
                        cluster_endpoint="https://k.example",
                        batch_idle_duration=0.0, batch_max_duration=0.0,
                        interruption_queue_name="deployed-queue")
    target = f"127.0.0.1:{solver_port}"
    op = Operator(
        cloud, settings, catalog, kube=kube,
        solver_factory=(lambda cat, provs:
                        RemoteSolver(cat, provs, target=target)),
        solver_target=target)
    op.kube.create("nodetemplates", "default", NodeTemplate(
        name="default", subnet_selector={"id": "subnet-zone-1a"},
        security_group_selector={"id": "sg-default"}))
    op.cloudprovider.register_nodetemplate(
        op.kube.get("nodetemplates", "default"))
    prov = Provisioner(name="default", provider_ref="default")
    prov.set_defaults()
    op.kube.create("provisioners", "default", prov)
    try:
        yield op, backing
    finally:
        op.stop()
        kube.stop()
        solver_srv.stop(0)
        cloud_srv.stop()
        api_srv.shutdown()
        api_srv.server_close()


def test_schedule_bind_terminate_across_all_three_wires(deployed):
    op, backing = deployed
    for i in range(15):
        op.kube.create("pods", f"p{i}",
                       make_pod(f"p{i}", cpu="1", memory="2Gi"))
    op.provisioning.reconcile_once()
    # the solve crossed the gRPC boundary (no in-process fallback)
    assert op.provisioning.last_solver_kind == "tpu"
    # machines launched through the HTTP cloud wire
    assert backing.instances
    # pods bound through the HTTP apiserver's binding subresource
    assert len(op.kube.pending_pods()) == 0
    assert all(p.node_name for p in op.kube.pods())
    # terminate through both wires: node deletes via kube, instance
    # terminations via the cloud API
    for node in list(op.cluster.nodes.values()):
        node.pods.clear()
        op.termination.request_deletion(node.name)
    op.termination.reconcile_once()
    assert all(i.state == "terminated" for i in backing.instances.values())


def test_interruption_drains_through_the_deployed_planes(deployed):
    op, backing = deployed
    for i in range(6):
        op.kube.create("pods", f"w{i}",
                       make_pod(f"w{i}", cpu="1", memory="2Gi"))
    op.provisioning.reconcile_once()
    nodes = list(op.cluster.nodes.values())
    assert nodes
    # a spot interruption for a live instance drains the node end-to-end
    iid = nodes[0].provider_id.rsplit("/", 1)[-1]
    import json as _json
    op.queue.send(_json.dumps({
        "source": "cloud.spot",
        "detail-type": "Spot Instance Interruption Warning",
        "detail": {"instance-id": iid}}))
    drained = op.interruption.reconcile_once()
    assert drained == 1
