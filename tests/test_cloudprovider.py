"""Provider layer + CloudProvider facade (reference cloudprovider suite
analogue, pkg/cloudprovider/suite_test.go pattern: fake backends, full
create path)."""

import pytest

from karpenter_tpu.apis import wellknown as wk
from karpenter_tpu.apis.nodetemplate import NodeTemplate
from karpenter_tpu.apis.provisioner import Provisioner, ValidationError
from karpenter_tpu.apis.settings import Settings
from karpenter_tpu.cloudprovider import CloudProvider
from karpenter_tpu.fake.cloud import FakeCloud
from karpenter_tpu.models.instancetype import Catalog, make_instance_type
from karpenter_tpu.models.machine import Machine, MachineSpec, parse_provider_id
from karpenter_tpu.models.requirements import Requirements, OP_IN
from karpenter_tpu.providers.images import BootstrapConfig, get_family
from karpenter_tpu.utils import errors as cloud_errors
from karpenter_tpu.utils.clock import FakeClock


def catalog():
    return Catalog(types=[
        make_instance_type("small.2x", cpu=2, memory="8Gi", od_price=0.10, spot_price=0.03),
        make_instance_type("medium.4x", cpu=4, memory="16Gi", od_price=0.20, spot_price=0.06),
        make_instance_type("gpu.8x", cpu=8, memory="64Gi", od_price=2.50,
                           extended={wk.RESOURCE_NVIDIA_GPU: 4}),
        make_instance_type("badspot.4x", cpu=4, memory="16Gi", od_price=0.50,
                           spot_price=0.45),  # spot above cheapest OD
    ])


@pytest.fixture
def cp():
    clock = FakeClock()
    cloud = FakeCloud(catalog=catalog(), clock=clock)
    settings = Settings(cluster_name="test-cluster",
                        cluster_endpoint="https://example.test")
    provider = CloudProvider(cloud, settings, catalog(), clock=clock)
    provider.register_nodetemplate(NodeTemplate(
        name="default",
        subnet_selector={"id": "subnet-zone-1a,subnet-zone-1b,subnet-zone-1c"},
        security_group_selector={"id": "sg-default"}))
    yield provider
    provider.stop()


def machine(name="m-1", cpu=1000, reqs=None, template="default", extended=None):
    r = Requirements.of((wk.LABEL_CAPACITY_TYPE, OP_IN, ["on-demand"]),
                        (wk.LABEL_ARCH, OP_IN, ["amd64"]))
    if reqs:
        r = r.union(reqs)
    requests = {wk.RESOURCE_CPU: cpu, wk.RESOURCE_PODS: 1}
    requests.update(extended or {})
    return Machine(name=name, spec=MachineSpec(
        requirements=r, resource_requests=requests,
        machine_template_ref=template, provisioner_name="default"))


class TestCreate:
    def test_launches_cheapest_compatible(self, cp):
        m = cp.create(machine())
        assert m.status.instance_type == "small.2x"
        assert m.status.state == "Launched"
        zone, iid = parse_provider_id(m.status.provider_id)
        assert zone.startswith("zone-1")
        assert cp.cloud.instances[iid].tags["karpenter.sh/machine"] == "m-1"
        assert m.status.price == pytest.approx(0.10)
        assert m.labels[wk.LABEL_INSTANCE_TYPE] == "small.2x"

    def test_gpu_requires_request(self, cp):
        # exotic filter: GPU type dropped without a GPU request
        m = cp.create(machine(cpu=3000))
        assert m.status.instance_type != "gpu.8x"
        g = cp.create(machine(name="m-g", cpu=1000,
                              extended={wk.RESOURCE_NVIDIA_GPU: 1}))
        assert g.status.instance_type == "gpu.8x"

    def test_ice_feedback_and_seqnum_retry(self, cp):
        cp.cloud.insufficient_capacity_pools = {
            ("on-demand", "small.2x", z) for z in ("zone-1a", "zone-1b", "zone-1c")}
        s0 = cp.ice.seqnum
        m = cp.create(machine())
        # fleet fell back to another pool, but small.2x pools are ICE-marked
        # only when the fleet reports them; lowest-price pick lands on a
        # usable pool without error here -> just assert it launched
        assert m.status.provider_id
        assert cp.ice.seqnum >= s0

    def test_unschedulable_when_nothing_fits(self, cp):
        with pytest.raises(cloud_errors.CloudError):
            cp.create(machine(cpu=64_000))

    def test_launch_creates_launch_template(self, cp):
        cp.create(machine())
        lts = cp.cloud.describe_launch_templates(
            "karpenter.k8s.tpu/cluster", "test-cluster")
        assert len(lts) == 1
        assert lts[0].name.startswith("Karpenter-test-cluster-")
        assert "bootstrap.sh" in lts[0].userdata

    def test_missing_template_raises(self, cp):
        with pytest.raises(cloud_errors.CloudError) as ei:
            cp.create(machine(template="nope"))
        assert cloud_errors.is_not_found(ei.value)


class TestGetDelete:
    def test_get_roundtrip(self, cp):
        m = cp.create(machine())
        got = cp.get(m.status.provider_id)
        assert got.status.instance_type == m.status.instance_type
        assert got.name == "m-1"

    def test_delete_idempotent(self, cp):
        m = cp.create(machine())
        cp.delete(m)
        _, iid = parse_provider_id(m.status.provider_id)
        assert cp.cloud.instances[iid].state == "terminated"
        cp.delete(m)  # second delete: not-found swallowed

    def test_list_cluster_machines(self, cp):
        cp.create(machine(name="m-a"))
        cp.create(machine(name="m-b"))
        names = sorted(m.name for m in cp.list_machines())
        assert names == ["m-a", "m-b"]


class TestDrift:
    def test_drift_detection(self, cp):
        cp.settings.feature_gates.drift_enabled = True
        m = cp.create(machine())
        assert not cp.is_machine_drifted(m)
        # new default image published -> old machines drift
        cp.cloud.ssm_parameters["/karpenter-tpu/images/default/amd64/latest"] = "img-amd64-3"
        cp.images.cache.flush()
        assert cp.is_machine_drifted(m)

    def test_drift_gated(self, cp):
        m = cp.create(machine())
        cp.cloud.ssm_parameters["/karpenter-tpu/images/default/amd64/latest"] = "img-x"
        cp.images.cache.flush()
        assert not cp.is_machine_drifted(m)  # feature gate off


class TestInstanceTypeProvider:
    def test_ice_invalidates_list(self, cp):
        before = cp.catalog_for()
        cp.ice.mark_unavailable("ICE", "small.2x", "zone-1a", "on-demand")
        after = cp.catalog_for()
        assert after.seqnum != before.seqnum
        t = after.by_name["small.2x"]
        dead = [o for o in t.offerings if not o.available]
        assert ("zone-1a", "on-demand") in [(o.zone, o.capacity_type) for o in dead]

    def test_memoized_until_seqnum_changes(self, cp):
        a = cp.catalog_for()
        b = cp.catalog_for()
        assert a is b


class TestSpotFilter:
    def test_spot_above_cheapest_od_dropped(self, cp):
        reqs = Requirements.of(
            (wk.LABEL_CAPACITY_TYPE, OP_IN, ["spot", "on-demand"]),
            (wk.LABEL_ARCH, OP_IN, ["amd64"]))
        types = cp.instance_types.list().types
        filtered = cp.instances.filter_instance_types(types, reqs)
        names = {t.name for t in filtered}
        # badspot.4x spot ($0.45) > cheapest OD ($0.10) but it still has its
        # own OD offering -> kept; a spot-only overpriced type would drop
        assert "badspot.4x" in names
        assert "gpu.8x" not in names  # exotic w/o request


class TestBootstrapFamilies:
    def test_shell_family(self):
        from karpenter_tpu.apis.provisioner import KubeletConfiguration

        cfg = BootstrapConfig(cluster_name="c", cluster_endpoint="https://e",
                              labels={"a": "1"},
                              kubelet=KubeletConfiguration(
                                  max_pods=58, pods_per_core=4,
                                  system_reserved_cpu_millis=250))
        out = get_family("ubuntu-k8s").userdata(cfg)
        assert "--max-pods=58" in out and "--node-labels=a=1" in out
        assert "--pods-per-core=4" in out
        assert "--system-reserved=cpu=250m" in out
        assert "--eviction-hard=memory.available<" in out

    def test_toml_family(self):
        cfg = BootstrapConfig(cluster_name="c", cluster_endpoint="https://e",
                              labels={"a": "1"})
        out = get_family("flatboat").userdata(cfg)
        assert '[settings.kubernetes]' in out and 'cluster-name = "c"' in out

    def test_mime_merge_with_custom(self):
        cfg = BootstrapConfig(cluster_name="c", cluster_endpoint="https://e",
                              custom_userdata="#!/bin/bash\necho hi")
        out = get_family("ubuntu-k8s").userdata(cfg)
        assert "multipart/mixed" in out and "echo hi" in out
        assert out.index("echo hi") < out.index("bootstrap.sh")

    def test_custom_family_passthrough(self):
        cfg = BootstrapConfig(cluster_name="c", cluster_endpoint="https://e",
                              custom_userdata="raw")
        assert get_family("custom").userdata(cfg) == "raw"

    def test_unknown_family_falls_back(self):
        assert get_family("whatever").name == "ubuntu-k8s"


class TestNodeTemplateValidation:
    def test_static_lt_exclusive(self):
        t = NodeTemplate(name="x", launch_template_name="my-lt", userdata="u")
        with pytest.raises(ValidationError):
            t.validate()

    def test_custom_requires_selector(self):
        t = NodeTemplate(name="x", image_family="custom",
                         subnet_selector={"id": "s"})
        with pytest.raises(ValidationError):
            t.validate()

    def test_restricted_tags(self):
        t = NodeTemplate(name="x", subnet_selector={"id": "s"},
                         tags={"karpenter.sh/foo": "bar"})
        with pytest.raises(ValidationError):
            t.validate()


def test_concurrent_creates_merge_into_one_fleet_call(cp):
    # regression: per-machine tags must not defeat the CreateFleet batcher
    import threading
    results = []
    ths = [threading.Thread(target=lambda i=i: results.append(cp.create(machine(name=f"mc-{i}"))))
           for i in range(8)]
    for t in ths:
        t.start()
    for t in ths:
        t.join(timeout=20)
    assert cp.cloud.create_fleet_api.called_with_count == 1
    ids = {m.status.provider_id for m in results}
    assert len(ids) == 8
    # machine tags applied post-launch
    names = {cp.cloud.instances[parse_provider_id(m.status.provider_id)[1]]
             .tags["karpenter.sh/machine"] for m in results}
    assert names == {f"mc-{i}" for i in range(8)}


class TestPodDensitySetting:
    def test_eni_limited_density_toggle(self):
        from karpenter_tpu.apis.settings import Settings
        from karpenter_tpu.cache import UnavailableOfferings
        from karpenter_tpu.providers.instancetypes import (
            InstanceTypeProvider, generate_fleet_catalog)

        catalog = generate_fleet_catalog(max_types=30)
        settings = Settings(cluster_name="t", cluster_endpoint="https://t")
        provider = InstanceTypeProvider(catalog, UnavailableOfferings(),
                                        settings=settings)
        small = next(t for t in provider.list().types
                     if dict(t.capacity)[wk.RESOURCE_CPU] <= 2000)
        assert dict(small.capacity)[wk.RESOURCE_PODS] < 110  # network-limited
        # live settings flip (the ConfigMap watch path) takes effect
        settings.enable_eni_limited_pod_density = False
        flat = provider.list()
        assert all(dict(t.capacity)[wk.RESOURCE_PODS] == 110
                   for t in flat.types)
        settings.enable_eni_limited_pod_density = True
        again = provider.list()
        assert dict(again.by_name[small.name].capacity)[wk.RESOURCE_PODS] == \
            dict(small.capacity)[wk.RESOURCE_PODS]
