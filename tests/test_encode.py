"""Vectorized mask folding (encode fast path) vs scalar oracle matching."""

import random

import numpy as np

from karpenter_tpu.apis import wellknown as wk
from karpenter_tpu.apis.provisioner import Provisioner
from karpenter_tpu.models.encode import build_grid, encode_problem, fold_option_mask
from karpenter_tpu.models.instancetype import Catalog, make_instance_type
from karpenter_tpu.models.pod import Toleration, make_pod
from karpenter_tpu.models.requirements import (
    IncompatibleError, Requirement, Requirements,
    OP_DOES_NOT_EXIST, OP_EXISTS, OP_GT, OP_IN, OP_LT, OP_NOT_IN,
)
from karpenter_tpu.oracle.scheduler import build_options, feasible_options, option_labels


def random_catalog(rng):
    types = []
    for i in range(rng.randint(3, 10)):
        cpu = rng.choice([1, 2, 4, 8, 16])
        types.append(make_instance_type(
            f"f{i % 3}.{i}x", cpu=cpu, memory=f"{cpu * 4}Gi",
            arch=rng.choice(["amd64", "arm64"]),
            zones=rng.sample(["zone-1a", "zone-1b", "zone-1c"], rng.randint(1, 3)),
            od_price=0.1 * cpu,
            spot_price=0.03 * cpu if rng.random() < 0.6 else None,
        ))
    return Catalog(types=types)


def random_requirements(rng):
    reqs = Requirements()
    pool = [
        (wk.LABEL_ARCH, OP_IN, [rng.choice(["amd64", "arm64"])]),
        (wk.LABEL_ZONE, OP_IN, rng.sample(["zone-1a", "zone-1b", "zone-1c"], rng.randint(1, 2))),
        (wk.LABEL_ZONE, OP_NOT_IN, [rng.choice(["zone-1a", "zone-1b"])]),
        (wk.LABEL_INSTANCE_CPU, OP_GT, [str(rng.choice([1, 2, 4]))]),
        (wk.LABEL_INSTANCE_CPU, OP_LT, [str(rng.choice([8, 16, 32]))]),
        (wk.LABEL_INSTANCE_FAMILY, OP_IN, [f"f{rng.randint(0, 3)}"]),
        (wk.LABEL_INSTANCE_GPU_NAME, OP_DOES_NOT_EXIST, []),
        (wk.LABEL_CAPACITY_TYPE, OP_IN, [rng.choice(["spot", "on-demand"])]),
        ("custom/team", OP_IN, ["ml"]),
        ("custom/team", OP_EXISTS, []),
    ]
    for spec in rng.sample(pool, rng.randint(0, 4)):
        try:
            reqs.add(Requirement.create(*spec[:2], spec[2]))
        except IncompatibleError:
            pass
    return reqs


def test_fold_matches_scalar_oracle_randomized():
    rng = random.Random(7)
    for _ in range(40):
        catalog = random_catalog(rng)
        grid = build_grid(catalog)
        cols = grid.get_cols()
        prov = Provisioner(name="p",
                           labels=(("custom/team", "ml"),) if rng.random() < 0.5 else ())
        if rng.random() < 0.7:
            prov.requirements = random_requirements(rng)
        prov.set_defaults()
        reqs = random_requirements(rng)
        try:
            combined = prov.scheduling_requirements().union(reqs)
        except IncompatibleError:
            continue
        fast = fold_option_mask(combined, cols, prov)
        # scalar: matches_labels per grid option
        slow = np.zeros_like(fast)
        for i, opt in enumerate(grid.options):
            if opt is None:
                continue
            slow[i] = combined.matches_labels(option_labels(opt, prov))
        assert (fast == slow).all(), (
            f"fold mismatch at {np.nonzero(fast != slow)};\nreqs={combined!r}")


def test_encode_feas_matches_oracle_feasible_options():
    rng = random.Random(11)
    for _ in range(10):
        catalog = random_catalog(rng)
        prov = Provisioner(name="default")
        prov.set_defaults()
        pod = make_pod("p", cpu=str(rng.choice([1, 2, 4])), memory="1Gi",
                       requirements=random_requirements(rng))
        enc = encode_problem(catalog, [prov], [pod])
        # oracle path over the SAME grid-ordered option list
        flat = [o for o in enc.grid.options if o is not None]
        want = feasible_options(pod, prov, flat, [0] * wk.NUM_RESOURCES)
        got = set(np.nonzero(enc.group_feas[0, 0].reshape(-1))[0].tolist())
        assert got == want


def test_group_pods_survives_intern_table_epoch_churn():
    """A mid-pass intern-table clear must not split equal-key pods into two
    groups (token==key only holds within one epoch), and pathological churn
    (table too small for the pass's keys) must terminate via the raw-key
    fallback with the identical partition."""
    import karpenter_tpu.models.pod as podmod
    from karpenter_tpu.models.pod import group_pods

    pods = [make_pod(f"q{i}", cpu="500m", memory="128Mi") for i in range(20)] \
        + [make_pod(f"r{i}", cpu="250m", memory="64Mi") for i in range(20)]
    want = sorted(g.count for g in group_pods(pods))
    assert want == [20, 20]

    saved = podmod._GROUP_KEY_TABLE_MAX
    try:
        podmod._GROUP_KEY_TABLE_MAX = 1  # every new intern clears + re-epochs
        with podmod._group_key_lock:
            podmod._group_key_tokens.clear()
            podmod._group_key_epoch += 1
        for p in pods:
            p.__dict__.pop("_group_token", None)
        got = group_pods(pods)
        assert sorted(g.count for g in got) == want
        assert len(got) == 2
    finally:
        podmod._GROUP_KEY_TABLE_MAX = saved


# -- static grid + dynamic availability (ICE-churn fast path) ----------------

def _ice_flip(catalog, type_name, zone, ct, available=False):
    """Clone-free availability flip + seqnum bump (what InstanceTypeProvider
    does on an ICE mark, minus the object rebuild)."""
    import dataclasses

    for ti, t in enumerate(catalog.types):
        if t.name != type_name:
            continue
        offs = tuple(
            dataclasses.replace(o, available=available)
            if (o.zone == zone and o.capacity_type == ct) else o
            for o in t.offerings)
        catalog.types[ti] = dataclasses.replace(t, offerings=type(t.offerings)(offs))
    catalog.seqnum += 1


def test_grid_reuse_shares_static_arrays_on_ice_flip():
    from karpenter_tpu.models.instancetype import Catalog

    cat = Catalog(types=[
        make_instance_type("a.large", cpu=4, memory="16Gi", od_price=0.2,
                           spot_price=0.07),
        make_instance_type("b.small", cpu=2, memory="4Gi", od_price=0.05,
                           spot_price=0.02)])
    g1 = build_grid(cat)
    g1.get_cols()
    _ice_flip(cat, "b.small", "zone-1a", "spot")
    g2 = build_grid(cat, reuse=g1)
    assert g2.layout_key == g1.layout_key
    assert g2.tiebreak is g1.tiebreak and g2.price is g1.price
    assert g2.alloc_t is g1.alloc_t and g2.cols is g1.cols
    assert g2.seqnum == cat.seqnum != g1.seqnum
    # exactly one option flipped off
    assert g1.valid.sum() - g2.valid.sum() == 1
    # a LAYOUT change (price move) must NOT reuse
    cat.types[0] = __import__("dataclasses").replace(
        cat.types[0], offerings=type(cat.types[0].offerings)(
            tuple(__import__("dataclasses").replace(o, price=o.price * 2)
                  for o in cat.types[0].offerings)))
    cat.seqnum += 1
    g3 = build_grid(cat, reuse=g2)
    assert g3.layout_key != g2.layout_key
    assert g3.tiebreak is not g2.tiebreak


def test_group_cache_static_level_survives_ice_churn():
    from karpenter_tpu.models.instancetype import Catalog

    cat = Catalog(types=[
        make_instance_type("a.large", cpu=4, memory="16Gi", od_price=0.2,
                           spot_price=0.07)])
    prov = Provisioner(name="default")
    prov.set_defaults()
    pods = [make_pod(f"p{i}", cpu="1", memory="1Gi") for i in range(8)]
    cache = {}
    grid = build_grid(cat)
    encode_problem(cat, [prov], pods, grid=grid, group_cache=cache)
    statics_before = dict(cache["static"])
    assert statics_before, "static level should be populated"
    _ice_flip(cat, "a.large", "zone-1a", "spot")
    grid2 = build_grid(cat, reuse=grid)
    enc = encode_problem(cat, [prov], pods, grid=grid2, group_cache=cache)
    # the static folds were reused object-identically; final level refreshed
    for k, v in statics_before.items():
        assert cache["static"][k] is v
    assert cache["seqnum"] == cat.seqnum
    # and the ICE'd option is truly infeasible in the fresh final encode
    zi = grid2.zones.index("zone-1a")
    ci = grid2.capacity_types.index("spot")
    si = zi * len(grid2.capacity_types) + ci
    assert not enc.group_feas[:, :, 0, si].any()


def test_fully_iced_zone_matches_oracle_zone_spread():
    """A zone losing ALL availability must shrink the zone-spread universe
    exactly like the oracle's (available-offering) universe — the static
    grid keeps the zone on its axis, so the spread pre-pass must consult
    active_zones, not the axis."""
    from karpenter_tpu.models.instancetype import Catalog
    from karpenter_tpu.models.pod import TopologySpreadConstraint
    from karpenter_tpu.oracle.scheduler import Scheduler
    from karpenter_tpu.solver.core import TPUSolver

    cat = Catalog(types=[
        make_instance_type("a.large", cpu=4, memory="16Gi", od_price=0.2,
                           spot_price=0.07),
        make_instance_type("b.large", cpu=8, memory="32Gi", od_price=0.4,
                           spot_price=0.14)])
    prov = Provisioner(name="default")
    prov.set_defaults()
    # ICE out zone-1c entirely (every type, both capacity types)
    for t in list(cat.types):
        for ct in ("spot", "on-demand"):
            _ice_flip(cat, t.name, "zone-1c", ct)
    grid = build_grid(cat)
    assert "zone-1c" in grid.zones  # static axis keeps it
    assert grid.active_zones() == ["zone-1a", "zone-1b"]
    pods = [make_pod(f"s{i}", cpu="1", memory="2Gi",
                     topology=(TopologySpreadConstraint(
                         max_skew=1, topology_key=wk.LABEL_ZONE),))
            for i in range(9)]
    sched = Scheduler(cat, [prov])
    oracle = sched.schedule(list(pods)).node_decisions(sched.options)
    kernel = TPUSolver(cat, [prov]).solve(pods).decisions()
    assert kernel == oracle
    zones_used = {d[1] for d in kernel}
    assert "zone-1c" not in zones_used


def test_donated_grid_never_bypasses_content_check():
    """Two distinct catalogs can share a seqnum (per-instance counters), so
    an adopted predecessor grid must only ever be a build_grid reuse donor
    — installing it as the live grid would serve the OLD catalog's prices
    (reviewer repro, round 4)."""
    from karpenter_tpu.models.instancetype import Catalog
    from karpenter_tpu.solver.core import NativeSolver

    cat_a = Catalog(types=[make_instance_type(
        "a.large", cpu=4, memory="16Gi", od_price=0.2, spot_price=0.07)])
    cat_b = Catalog(types=[make_instance_type(
        "a.large", cpu=4, memory="16Gi", od_price=9.9, spot_price=3.3)])
    assert cat_a.seqnum == cat_b.seqnum  # the hazard: equal counters
    s_a = NativeSolver(cat_a, [])
    g_a = s_a.grid()
    s_b = NativeSolver(cat_b, [])
    s_b.adopt_static(s_a)
    g_b = s_b.grid()
    assert g_b is not g_a
    assert abs(float(g_b.price.max()) - 9.9) < 1e-4  # B's prices, not A's
    # and an ICE-only successor still shares statics through donation
    import dataclasses
    cat_b2 = Catalog(types=[dataclasses.replace(
        cat_b.types[0],
        offerings=type(cat_b.types[0].offerings)(tuple(
            dataclasses.replace(o, available=(o.capacity_type != "spot"))
            for o in cat_b.types[0].offerings)))], seqnum=cat_b.seqnum + 1)
    s_b2 = NativeSolver(cat_b2, [])
    s_b2.adopt_static(s_b)
    g_b2 = s_b2.grid()
    assert g_b2.tiebreak is g_b.tiebreak  # layout match -> shared statics
    assert g_b2.valid.sum() < g_b.valid.sum()


def test_diagnose_unschedulable_stages():
    """The FailedScheduling diagnosis names the first admission stage no
    provisioner survives (toleration -> requirements -> fit -> availability)."""
    from karpenter_tpu.models.encode import diagnose_unschedulable
    from karpenter_tpu.models.instancetype import Catalog
    from karpenter_tpu.models.pod import Taint

    cat = Catalog(types=[make_instance_type(
        "m.large", cpu=4, memory="16Gi", od_price=0.2, spot_price=0.07)])
    tainted = Provisioner(name="t", taints=(
        Taint(key="team", value="x", effect="NoSchedule"),))
    tainted.set_defaults()
    plain = Provisioner(name="p")
    plain.set_defaults()

    # 1) toleration: only a tainted provisioner exists
    why = diagnose_unschedulable(
        make_pod("a", cpu="1", memory="1Gi"), [tainted], cat)
    assert "tolerate" in why

    # 2) requirements: zone nothing offers
    why = diagnose_unschedulable(
        make_pod("b", cpu="1", memory="1Gi",
                 node_selector={wk.LABEL_ZONE: "zone-9z"}), [plain], cat)
    assert "incompatible" in why

    # 3) fit: larger than every type
    why = diagnose_unschedulable(
        make_pod("c", cpu="64", memory="1Gi"), [plain], cat)
    assert "do not fit" in why

    # 4) availability: everything compatible is ICE'd
    _ice_flip(cat, "m.large", "zone-1a", "spot")
    _ice_flip(cat, "m.large", "zone-1a", "on-demand")
    _ice_flip(cat, "m.large", "zone-1b", "spot")
    _ice_flip(cat, "m.large", "zone-1b", "on-demand")
    _ice_flip(cat, "m.large", "zone-1c", "spot")
    _ice_flip(cat, "m.large", "zone-1c", "on-demand")
    why = diagnose_unschedulable(
        make_pod("d", cpu="1", memory="1Gi"), [plain], cat)
    assert "unavailable" in why
