"""Vectorized mask folding (encode fast path) vs scalar oracle matching."""

import random

import numpy as np

from karpenter_tpu.apis import wellknown as wk
from karpenter_tpu.apis.provisioner import Provisioner
from karpenter_tpu.models.encode import build_grid, encode_problem, fold_option_mask
from karpenter_tpu.models.instancetype import Catalog, make_instance_type
from karpenter_tpu.models.pod import Toleration, make_pod
from karpenter_tpu.models.requirements import (
    IncompatibleError, Requirement, Requirements,
    OP_DOES_NOT_EXIST, OP_EXISTS, OP_GT, OP_IN, OP_LT, OP_NOT_IN,
)
from karpenter_tpu.oracle.scheduler import build_options, feasible_options, option_labels


def random_catalog(rng):
    types = []
    for i in range(rng.randint(3, 10)):
        cpu = rng.choice([1, 2, 4, 8, 16])
        types.append(make_instance_type(
            f"f{i % 3}.{i}x", cpu=cpu, memory=f"{cpu * 4}Gi",
            arch=rng.choice(["amd64", "arm64"]),
            zones=rng.sample(["zone-1a", "zone-1b", "zone-1c"], rng.randint(1, 3)),
            od_price=0.1 * cpu,
            spot_price=0.03 * cpu if rng.random() < 0.6 else None,
        ))
    return Catalog(types=types)


def random_requirements(rng):
    reqs = Requirements()
    pool = [
        (wk.LABEL_ARCH, OP_IN, [rng.choice(["amd64", "arm64"])]),
        (wk.LABEL_ZONE, OP_IN, rng.sample(["zone-1a", "zone-1b", "zone-1c"], rng.randint(1, 2))),
        (wk.LABEL_ZONE, OP_NOT_IN, [rng.choice(["zone-1a", "zone-1b"])]),
        (wk.LABEL_INSTANCE_CPU, OP_GT, [str(rng.choice([1, 2, 4]))]),
        (wk.LABEL_INSTANCE_CPU, OP_LT, [str(rng.choice([8, 16, 32]))]),
        (wk.LABEL_INSTANCE_FAMILY, OP_IN, [f"f{rng.randint(0, 3)}"]),
        (wk.LABEL_INSTANCE_GPU_NAME, OP_DOES_NOT_EXIST, []),
        (wk.LABEL_CAPACITY_TYPE, OP_IN, [rng.choice(["spot", "on-demand"])]),
        ("custom/team", OP_IN, ["ml"]),
        ("custom/team", OP_EXISTS, []),
    ]
    for spec in rng.sample(pool, rng.randint(0, 4)):
        try:
            reqs.add(Requirement.create(*spec[:2], spec[2]))
        except IncompatibleError:
            pass
    return reqs


def test_fold_matches_scalar_oracle_randomized():
    rng = random.Random(7)
    for _ in range(40):
        catalog = random_catalog(rng)
        grid = build_grid(catalog)
        cols = grid.get_cols()
        prov = Provisioner(name="p",
                           labels=(("custom/team", "ml"),) if rng.random() < 0.5 else ())
        if rng.random() < 0.7:
            prov.requirements = random_requirements(rng)
        prov.set_defaults()
        reqs = random_requirements(rng)
        try:
            combined = prov.scheduling_requirements().union(reqs)
        except IncompatibleError:
            continue
        fast = fold_option_mask(combined, cols, prov)
        # scalar: matches_labels per grid option
        slow = np.zeros_like(fast)
        for i, opt in enumerate(grid.options):
            if opt is None:
                continue
            slow[i] = combined.matches_labels(option_labels(opt, prov))
        assert (fast == slow).all(), (
            f"fold mismatch at {np.nonzero(fast != slow)};\nreqs={combined!r}")


def test_encode_feas_matches_oracle_feasible_options():
    rng = random.Random(11)
    for _ in range(10):
        catalog = random_catalog(rng)
        prov = Provisioner(name="default")
        prov.set_defaults()
        pod = make_pod("p", cpu=str(rng.choice([1, 2, 4])), memory="1Gi",
                       requirements=random_requirements(rng))
        enc = encode_problem(catalog, [prov], [pod])
        # oracle path over the SAME grid-ordered option list
        flat = [o for o in enc.grid.options if o is not None]
        want = feasible_options(pod, prov, flat, [0] * wk.NUM_RESOURCES)
        got = set(np.nonzero(enc.group_feas[0, 0].reshape(-1))[0].tolist())
        assert got == want


def test_group_pods_survives_intern_table_epoch_churn():
    """A mid-pass intern-table clear must not split equal-key pods into two
    groups (token==key only holds within one epoch), and pathological churn
    (table too small for the pass's keys) must terminate via the raw-key
    fallback with the identical partition."""
    import karpenter_tpu.models.pod as podmod
    from karpenter_tpu.models.pod import group_pods

    pods = [make_pod(f"q{i}", cpu="500m", memory="128Mi") for i in range(20)] \
        + [make_pod(f"r{i}", cpu="250m", memory="64Mi") for i in range(20)]
    want = sorted(g.count for g in group_pods(pods))
    assert want == [20, 20]

    saved = podmod._GROUP_KEY_TABLE_MAX
    try:
        podmod._GROUP_KEY_TABLE_MAX = 1  # every new intern clears + re-epochs
        with podmod._group_key_lock:
            podmod._group_key_tokens.clear()
            podmod._group_key_epoch += 1
        for p in pods:
            p.__dict__.pop("_group_token", None)
        got = group_pods(pods)
        assert sorted(g.count for g in got) == want
        assert len(got) == 2
    finally:
        podmod._GROUP_KEY_TABLE_MAX = saved
