"""Device-resident serving path: bucket ladder, shape router, Sync-time
residency/warmup, and the wire-served sharded solve (PR 7 tentpole).

The residency contract is asserted via the host->device upload COUNTERS
(solver/buckets.py), never timing: `Sync`-then-repeat-`Solve` must perform
zero redundant uploads of unchanged catalog tensors, and that is a metric
delta of exactly zero, deterministic on any backend. The wire parity tests
force the shape router's crossover to 0 so even small problems take the
mesh kernel (conftest pins an 8-device virtual CPU mesh)."""

import numpy as np
import pytest

from karpenter_tpu.apis import wellknown as wk
from karpenter_tpu.apis.provisioner import Provisioner
from karpenter_tpu.models.instancetype import Catalog, make_instance_type
from karpenter_tpu.models.pod import TopologySpreadConstraint, make_pod
from karpenter_tpu.models.requirements import OP_IN, Requirements
from karpenter_tpu.solver import buckets
from karpenter_tpu.solver.buckets import BucketPlan, ShapeRouter, plan_for
from karpenter_tpu.solver.client import RemoteSolver
from karpenter_tpu.solver.core import NativeSolver, TPUSolver, _bucket
from karpenter_tpu.solver.service import SolverService, serve
from karpenter_tpu.solver import solver_pb2 as pb
from karpenter_tpu.solver import wire


def small_catalog():
    return Catalog(types=[
        make_instance_type("m.large", cpu=2, memory="8Gi",
                           od_price=0.10, spot_price=0.03),
        make_instance_type("m.xlarge", cpu=4, memory="16Gi",
                           od_price=0.20, spot_price=0.06),
        make_instance_type("c.xlarge", cpu=4, memory="8Gi",
                           od_price=0.17, spot_price=0.05),
    ])


def default_provisioner(**kw):
    p = Provisioner(name="default", requirements=Requirements.of(
        (wk.LABEL_CAPACITY_TYPE, OP_IN, ["spot", "on-demand"])), **kw)
    p.set_defaults()
    return p


def mixed_pods(n=40):
    pods = [make_pod(f"web-{i}", cpu="500m", memory="1Gi",
                     topology=(TopologySpreadConstraint(1, wk.LABEL_ZONE),))
            for i in range(n // 2)]
    pods += [make_pod(f"db-{i}", cpu="1", memory="4Gi",
                      node_selector={wk.LABEL_ZONE: "zone-1a"})
             for i in range(n - n // 2)]
    return pods


def uploads(tensor: str) -> float:
    return buckets.UPLOADS.value(tensor=tensor)


# -- the ladder ---------------------------------------------------------------

class TestBucketLadder:
    def test_fixed_rungs(self):
        assert buckets.bucket_up(1, "groups") == 8
        assert buckets.bucket_up(8, "groups") == 8
        assert buckets.bucket_up(9, "groups") == 32
        assert buckets.bucket_up(513, "groups") == 2048
        assert buckets.bucket_up(0, "existing") == 1
        assert buckets.bucket_up(5, "existing") == 16
        assert buckets.bucket_up(3, "wave") == 4

    def test_tail_growth_beyond_table(self):
        top = buckets.LADDERS["groups"][-1]
        assert buckets.bucket_up(top + 1, "groups") == top * 4
        wtop = buckets.LADDERS["wave"][-1]
        assert buckets.bucket_up(wtop + 1, "wave") == wtop * 2

    def test_ladder_not_doubling(self):
        # the point of the fix: 9 and 17 groups share ONE rung (32) where
        # the old doubling policy minted 16 and 32 (two compiles)
        assert buckets.bucket_up(9, "groups") == buckets.bucket_up(
            17, "groups")

    def test_core_bucket_shim_routes_to_ladders(self):
        # core._bucket keys the dimension on its legacy lo: 8 -> groups,
        # 1 -> existing, 2 -> wave
        assert _bucket(9) == buckets.bucket_up(9, "groups")
        assert _bucket(5, lo=1) == buckets.bucket_up(5, "existing")
        assert _bucket(3, lo=2) == buckets.bucket_up(3, "wave")

    def test_plan_label_and_cells(self):
        plan = plan_for(9, 100, 0)
        assert plan == BucketPlan(groups=32, slots=128, existing=1)
        assert plan.cells() == 32 * 128
        assert plan.label() == "g32n128e1"


# -- the router ---------------------------------------------------------------

class TestShapeRouter:
    def test_single_below_sharded_above(self):
        r = ShapeRouter(n_devices=8, crossover_cells=1000)
        assert r.route(BucketPlan(8, 8, 1)) == "single"
        assert r.route(BucketPlan(128, 128, 1)) == "sharded"

    def test_single_device_never_shards(self):
        r = ShapeRouter(n_devices=1, crossover_cells=1)
        assert r.route(BucketPlan(2048, 2048, 1)) == "single"

    def test_sticky_under_jitter_near_crossover(self):
        # hysteresis: above hi -> sharded; dipping into (lo, hi) keeps the
        # previous route in BOTH directions; only below lo flips back
        r = ShapeRouter(n_devices=8, crossover_cells=1024, hysteresis=4)
        between = BucketPlan(16, 32, 1)  # 512 cells: lo=256 <= 512 < hi
        assert r.route(between) == "single"  # initial route is single
        assert r.route(BucketPlan(32, 32, 1)) == "sharded"  # 1024 >= hi
        assert r.route(between) == "sharded"  # sticky: no flap
        assert r.route(BucketPlan(8, 8, 1)) == "single"  # 64 < lo=256
        assert r.route(between) == "single"  # sticky again

    def test_steady_route_is_stateless(self):
        r = ShapeRouter(n_devices=8, crossover_cells=1024)
        r.route(BucketPlan(32, 32, 1))  # live route now sharded
        assert r.steady_route(BucketPlan(8, 8, 1)) == "single"
        # the stateless query didn't disturb the sticky live route
        assert r._route == "sharded"

    def test_env_crossover_override(self, monkeypatch):
        monkeypatch.setenv("KARPENTER_TPU_SHARD_CROSSOVER_CELLS", "42")
        assert buckets.crossover_cells_default() == 42
        monkeypatch.setenv("KARPENTER_TPU_SHARD_CROSSOVER_CELLS", "junk")
        assert (buckets.crossover_cells_default()
                == buckets.DEFAULT_CROSSOVER_CELLS)


# -- device residency (metric-asserted, never timing) -------------------------

class TestDeviceResidency:
    def test_repeat_solve_uploads_no_catalog_tensors(self):
        solver = TPUSolver(small_catalog(), [default_provisioner()])
        pods = mixed_pods(24)
        solver.solve(pods)
        cat_before = uploads("catalog")
        delta_before = uploads("delta")
        solver.solve(pods)
        assert uploads("catalog") == cat_before, (
            "unchanged catalog tensors re-crossed the host->device "
            "boundary on a repeat solve")
        # the per-solve problem delta DOES ship (that's the contract: only
        # the delta crosses per cycle)
        assert uploads("delta") > delta_before

    def test_repeat_solve_hits_compile_cache(self):
        solver = TPUSolver(small_catalog(), [default_provisioner()])
        pods = mixed_pods(24)
        solver.solve(pods)
        solver.solve(pods)
        assert solver.last_solve_info["compile_cache"] == "hit"
        assert solver.last_solve_info["bucket"].startswith("g")

    def test_catalog_mutation_reuploads(self):
        cat = small_catalog()
        solver = TPUSolver(cat, [default_provisioner()])
        pods = mixed_pods(12)
        solver.solve(pods)
        before = uploads("catalog")
        # availability-only churn bumps the seqnum but shares the static
        # arrays (build_grid reuse) — still no re-upload
        from karpenter_tpu.models.instancetype import Offering, Offerings
        big = cat.by_name["m.large"]
        object.__setattr__(big, "offerings", Offerings(
            Offering(o.zone, o.capacity_type, o.price, available=False)
            for o in big.offerings))
        cat.bump()
        solver.solve(pods)
        assert uploads("catalog") == before

    def test_wire_sync_then_repeat_solve_zero_catalog_uploads(self):
        srv, port, svc = serve("127.0.0.1:0")
        try:
            client = RemoteSolver(small_catalog(), [default_provisioner()],
                                  target=f"127.0.0.1:{port}")
            pods = mixed_pods(24)
            client.solve(pods)  # sync-on-demand + first solve
            cat_before = uploads("catalog")
            client.solve(pods)
            client.solve(pods)
            assert uploads("catalog") == cat_before
        finally:
            srv.stop(grace=None)


# -- warmup -------------------------------------------------------------------

class TestWarmup:
    def test_warm_shapes_pre_jits_buckets(self):
        solver = TPUSolver(small_catalog(), [default_provisioner()])
        warm_before = buckets.COMPILE_WARMUPS.value()
        warmed = solver.warm_shapes([(9, 100, 0)])
        assert warmed == ["g32n128e1"]
        assert buckets.COMPILE_WARMUPS.value() == warm_before + 1
        # re-warming the same bucket compiles nothing new
        assert solver.warm_shapes([(9, 100, 0)]) == []

    def test_warmed_bucket_first_solve_is_a_cache_hit(self):
        solver = TPUSolver(small_catalog(), [default_provisioner()])
        pods = mixed_pods(24)
        probe = TPUSolver(small_catalog(), [default_provisioner()])
        probe.solve(pods)
        # warm THIS solver at the shape the probe just observed; the first
        # real solve then finds the bucket's program compiled
        solver.warm_shapes([probe.last_shape_key])
        solver.solve(pods)
        assert solver.last_solve_info["compile_cache"] == "hit"
        assert solver.last_shape_key == probe.last_shape_key

    def test_warm_shapes_respects_limit(self):
        solver = TPUSolver(small_catalog(), [default_provisioner()])
        shapes = [(g, 100, 0) for g in (1, 9, 33, 129, 513)]
        warmed = solver.warm_shapes(shapes, limit=2)
        assert len(warmed) <= 2

    def test_sync_warms_from_client_hints(self):
        svc = SolverService()
        srv, port, _ = serve(service=svc)
        try:
            cat = small_catalog()
            req = pb.SyncRequest(
                catalog=wire.catalog_to_wire(cat),
                provisioners=[wire.provisioner_to_wire(
                    default_provisioner())],
                warm_pod_counts=[4000],
            )
            client = RemoteSolver(cat, [default_provisioner()],
                                  target=f"127.0.0.1:{port}")
            resp = client._call("Sync", req)
            assert resp.device_count >= 2  # 8-device virtual CPU mesh
            assert "x" in resp.mesh
            assert resp.warmed_buckets >= 1
            # idempotent re-Sync with the same hints: nothing new compiles
            resp2 = client._call("Sync", req)
            assert resp2.warmed_buckets == 0
        finally:
            srv.stop(grace=None)

    def test_solve_records_shape_history(self):
        svc = SolverService()
        srv, port, _ = serve(service=svc)
        try:
            client = RemoteSolver(small_catalog(), [default_provisioner()],
                                  target=f"127.0.0.1:{port}")
            client.solve(mixed_pods(24))
            assert len(svc._shape_seen) == 1
            (key, count), = svc._shape_seen.items()
            assert count == 1 and len(key) == 8
        finally:
            srv.stop(grace=None)


# -- wire-served sharded parity ----------------------------------------------

def _wire_sharded_solve(pods, catalog, provisioners):
    """Solve over gRPC with the router's crossover forced to 0 (everything
    shards); returns (raw response, decoded result, service)."""
    svc = SolverService(crossover_cells=0)
    srv, port, svc = serve(service=svc)
    try:
        client = RemoteSolver(catalog, provisioners,
                              target=f"127.0.0.1:{port}", timeout=120.0)
        client.sync()
        req = pb.SolveRequest(
            catalog_seqnum=catalog.seqnum,
            catalog_hash=client.catalog_content_hash(),
            provisioner_hash=client._prov_hash,
            pods=[wire.pod_to_wire(p) for p in pods],
        )
        resp = client._call("Solve", req)
        return resp, client._decode(resp, pods), svc
    finally:
        srv.stop(grace=None)


class TestWireServedSharded:
    def test_sharded_solve_served_and_bit_identical(self):
        """Fixed-seed smoke of the `make multichip` contract: the gRPC-served
        sharded solve must report the mesh route and produce decisions
        bit-identical to the independent native scan."""
        catalog, provisioners, pods = small_catalog(), \
            [default_provisioner()], mixed_pods(40)
        resp, decoded, svc = _wire_sharded_solve(pods, catalog, provisioners)
        assert resp.routing == "tpu-sharded"
        assert resp.device_count >= 2
        assert resp.bucket.startswith("g")
        placed = sum(n.pod_count for n in decoded.nodes)
        assert placed + decoded.unschedulable_count() == len(pods)
        native = NativeSolver(catalog, provisioners).solve(pods)
        assert decoded.decisions() == native.decisions()

    def test_sharded_flat_bit_parity_with_single_device(self):
        """Core-level: the mesh dispatch and the single-device dispatch of
        the SAME padded problem return bit-identical flat buffers."""
        from karpenter_tpu.models.encode import encode_problem
        from karpenter_tpu.parallel.sharded import ShardedContext
        from karpenter_tpu.solver.core import (build_pack_inputs,
                                               dispatch_pack_inputs)

        solver = TPUSolver(small_catalog(), [default_provisioner()])
        pods = mixed_pods(32)
        enc = encode_problem(solver.catalog, solver.provisioners, pods, (),
                             None, None, grid=solver.grid(),
                             group_cache=solver._group_cache)
        inputs, dims, up = build_pack_inputs(
            enc, solver._dev_alloc_t, solver._dev_tiebreak)
        ctx = ShardedContext()
        flat_sharded = np.asarray(
            ctx.dispatch_flat(inputs, dims[1], up, enc.grid))
        flat_single = np.asarray(dispatch_pack_inputs(inputs, dims, up))
        assert flat_sharded.shape == flat_single.shape
        assert (flat_sharded == flat_single).all()

    @pytest.mark.slow
    def test_full_stress_parity_50k(self):
        """The full `make multichip` run (50k pods x 603 types over the
        8-device mesh) — slow tier; the smoke above carries tier-1."""
        from benchmarks.multichip_wire import run

        record = run(50_000, 8, out_dir=None)
        assert record["bit_parity"] and record["decision_parity"]
        assert record["routing"] == "tpu-sharded"
