"""gRPC solver boundary tests: wire round-trips, over-the-wire decision
parity with the in-process TPUSolver, seqnum re-sync, and the unreachable ->
oracle fallback contract inside the provisioning controller.

Reference analogues: the seqnum-memoized instance-type cache
(pkg/cloudprovider/instancetypes.go:104-120) and the fallback-on-failure
pattern (pricing.go:100-116)."""

import pytest

from karpenter_tpu.apis import wellknown as wk
from karpenter_tpu.apis.provisioner import Limits, Provisioner
from karpenter_tpu.models.instancetype import Catalog, make_instance_type
from karpenter_tpu.models.pod import (
    Taint, Toleration, TopologySpreadConstraint, make_pod,
)
from karpenter_tpu.models.requirements import OP_IN, Requirements
from karpenter_tpu.oracle.scheduler import ExistingNode
from karpenter_tpu.solver import wire
from karpenter_tpu.solver.client import RemoteSolver, SolverUnavailable
from karpenter_tpu.solver.core import TPUSolver
from karpenter_tpu.solver.service import serve


def small_catalog():
    return Catalog(types=[
        make_instance_type("m.large", cpu=2, memory="8Gi", od_price=0.10, spot_price=0.03),
        make_instance_type("m.xlarge", cpu=4, memory="16Gi", od_price=0.20, spot_price=0.06),
        make_instance_type("c.xlarge", cpu=4, memory="8Gi", od_price=0.17, spot_price=0.05),
    ])


def default_provisioner(**kw):
    p = Provisioner(name="default", requirements=Requirements.of(
        (wk.LABEL_CAPACITY_TYPE, OP_IN, ["spot", "on-demand"])), **kw)
    p.set_defaults()
    return p


def mixed_pods(n=40):
    pods = [make_pod(f"web-{i}", cpu="500m", memory="1Gi",
                     topology=(TopologySpreadConstraint(1, wk.LABEL_ZONE),))
            for i in range(n // 2)]
    pods += [make_pod(f"db-{i}", cpu="1", memory="4Gi",
                      node_selector={wk.LABEL_ZONE: "zone-1a"})
             for i in range(n - n // 2)]
    return pods


@pytest.fixture(scope="module")
def server():
    srv, port, svc = serve("127.0.0.1:0")
    yield port
    srv.stop(grace=None)


class TestWireRoundTrip:
    def test_pod_round_trip_preserves_group_key(self):
        p = make_pod(
            "p1", cpu="1500m", memory="3Gi",
            node_selector={wk.LABEL_ZONE: "zone-1b"},
            tolerations=(Toleration(key="gpu", operator="Exists", effect="NoSchedule"),),
            topology=(TopologySpreadConstraint(2, wk.LABEL_ZONE, "ScheduleAnyway"),),
            labels=(("app", "p"),), priority=7, deletion_cost=3,
            do_not_evict=True, anti_affinity_hostname=True,
        )
        q = wire.pod_from_wire(wire.pod_to_wire(p))
        assert q == p
        assert q.group_key() == p.group_key()

    def test_catalog_round_trip(self):
        c = small_catalog()
        c2 = wire.catalog_from_wire(wire.catalog_to_wire(c))
        assert [t.name for t in c2.types] == [t.name for t in c.types]
        assert c2.types[0] == c.types[0]
        assert c2.seqnum == c.seqnum

    def test_provisioner_round_trip(self):
        p = default_provisioner(
            taints=(Taint(key="dedicated", value="x", effect="NoSchedule"),),
            labels=(("team", "infra"),), weight=10,
            limits=Limits(cpu_millis=100_000),
            ttl_seconds_after_empty=30, provider_ref="tmpl")
        q = wire.provisioner_from_wire(wire.provisioner_to_wire(p))
        assert q.name == p.name
        assert q.requirements.to_specs() == p.requirements.to_specs()
        assert q.taints == p.taints
        assert q.limits == p.limits
        assert q.ttl_seconds_after_empty == 30
        assert q.ttl_seconds_until_expired is None
        assert q.provider_ref == "tmpl"
        assert wire.provisioners_hash([q]) == wire.provisioners_hash([p])

    def test_existing_node_round_trip(self):
        e = ExistingNode(name="n1", labels={wk.LABEL_ZONE: "zone-1a"},
                         allocatable=[4000, 8192, 110, 0, 0, 0, 0, 0][:wk.NUM_RESOURCES],
                         used=[0] * wk.NUM_RESOURCES,
                         taints=(Taint(key="k", effect="NoExecute"),))
        e2 = wire.existing_from_wire(wire.existing_to_wire(e))
        assert e2.name == e.name and e2.labels == e.labels
        assert e2.allocatable == e.allocatable and e2.taints == e.taints


class TestRemoteParity:
    def test_remote_matches_inprocess(self, server):
        catalog = small_catalog()
        provs = [default_provisioner()]
        pods = mixed_pods()
        local = TPUSolver(catalog, provs).solve(pods)
        remote = RemoteSolver(catalog, provs, target=f"127.0.0.1:{server}").solve(pods)
        assert remote.decisions() == local.decisions()
        assert remote.unschedulable_count() == local.unschedulable_count()

    def test_remote_with_existing_nodes(self, server):
        catalog = small_catalog()
        provs = [default_provisioner()]
        existing = [ExistingNode(
            name="existing-1",
            labels={wk.LABEL_ZONE: "zone-1a", wk.LABEL_ARCH: "amd64",
                    wk.LABEL_OS: "linux", wk.LABEL_INSTANCE_TYPE: "m.xlarge",
                    wk.LABEL_CAPACITY_TYPE: "on-demand"},
            allocatable=catalog.by_name["m.xlarge"].allocatable_vector(),
            used=[0] * wk.NUM_RESOURCES)]
        pods = [make_pod(f"p{i}", cpu="500m", memory="1Gi") for i in range(8)]
        solver = RemoteSolver(catalog, provs, target=f"127.0.0.1:{server}")
        local = TPUSolver(catalog, provs).solve(pods, existing=existing)
        remote = solver.solve(pods, existing=existing)
        assert remote.decisions() == local.decisions()
        assert remote.existing_counts == local.existing_counts

    def test_seqnum_bump_triggers_resync(self, server):
        catalog = small_catalog()
        provs = [default_provisioner()]
        solver = RemoteSolver(catalog, provs, target=f"127.0.0.1:{server}")
        r1 = solver.solve([make_pod("a", cpu="1", memory="1Gi")])
        assert len(r1.nodes) == 1
        # mutate the catalog: mark m.large unavailable everywhere, bump seqnum
        big = catalog.by_name["m.large"]
        from karpenter_tpu.models.instancetype import Offering, Offerings
        object.__setattr__(big, "offerings", Offerings(
            Offering(o.zone, o.capacity_type, o.price, available=False)
            for o in big.offerings))
        catalog.bump()
        r2 = solver.solve([make_pod("b", cpu="1", memory="1Gi")])
        assert r2.nodes[0].option.itype.name != "m.large"

    def test_restarted_controller_resyncs_cleanly(self):
        # restart scenario: the controller's process-local seqnum counter
        # resets while the long-lived sidecar keeps its higher one. Staleness
        # is content-keyed, so the fresh client with IDENTICAL content must
        # sync + solve (previously it got StaleSync forever and every
        # reconcile fell back to the oracle)
        from karpenter_tpu.solver.service import serve as serve_fresh

        srv, port, svc = serve_fresh("127.0.0.1:0")
        try:
            old = small_catalog()
            old.seqnum = 7  # long-running controller, several catalog bumps
            first = RemoteSolver(old, [default_provisioner()],
                                 target=f"127.0.0.1:{port}")
            assert first.solve([make_pod("a", cpu="1", memory="1Gi")]).nodes
            restarted_catalog = small_catalog()  # same content, seqnum 0
            restarted = RemoteSolver(restarted_catalog, [default_provisioner()],
                                     target=f"127.0.0.1:{port}")
            assert restarted.solve([make_pod("b", cpu="1", memory="1Gi")]).nodes
            # identical content: the device-resident grid was NOT rebuilt
            assert svc._cat_hash == restarted.catalog_content_hash()
        finally:
            srv.stop(grace=None)

    def test_divergent_replicas_both_keep_solving(self):
        # two replicas with DIFFERENT catalog content sharing one sidecar:
        # the service's solver LRU keeps BOTH grids resident so neither
        # replica pays rebuild thrash (nor FAILED_PRECONDITION loops)
        from karpenter_tpu.models.instancetype import Offering, Offerings
        from karpenter_tpu.solver.service import serve as serve_fresh

        srv, port, svc = serve_fresh("127.0.0.1:0")
        try:
            cat_a = small_catalog()
            cat_b = small_catalog()
            big = cat_b.by_name["m.large"]
            object.__setattr__(big, "offerings", Offerings(
                Offering(o.zone, o.capacity_type, o.price, available=False)
                for o in big.offerings))
            a = RemoteSolver(cat_a, [default_provisioner()],
                             target=f"127.0.0.1:{port}")
            b = RemoteSolver(cat_b, [default_provisioner()],
                             target=f"127.0.0.1:{port}")
            assert a.solve([make_pod("a", cpu="1", memory="1Gi")]).nodes
            rb = b.solve([make_pod("b", cpu="1", memory="1Gi")])
            assert rb.nodes[0].option.itype.name != "m.large"
            # both grids stay resident in the LRU; a keeps solving with no
            # rebuild and b's view is unaffected
            assert len(svc._cache) == 2
            ra = a.solve([make_pod("c", cpu="1", memory="1Gi")])
            assert ra.nodes
            assert len(svc._cache) == 2
        finally:
            srv.stop(grace=None)

    def test_health(self, server):
        solver = RemoteSolver(small_catalog(), [default_provisioner()],
                              target=f"127.0.0.1:{server}")
        h = solver.health()
        assert h.ok

    def test_unreachable_raises(self):
        solver = RemoteSolver(small_catalog(), [default_provisioner()],
                              target="127.0.0.1:1", timeout=0.5)
        with pytest.raises(SolverUnavailable):
            solver.solve([make_pod("a", cpu="1", memory="1Gi")])


class TestControllerFallback:
    def test_provisioning_falls_back_to_oracle_when_solver_unreachable(self):
        """ProvisioningController + RemoteSolver at a dead address still
        provisions (oracle fallback contract)."""
        from karpenter_tpu.apis.settings import Settings
        from karpenter_tpu.fake.cloud import FakeCloud
        from karpenter_tpu.operator import Operator

        catalog = small_catalog()
        cloud = FakeCloud(catalog)
        settings = Settings(cluster_name="t", cluster_endpoint="https://t")
        op = Operator(cloud, settings, catalog)
        op.provisioning._solver_factory = lambda cat, provs: RemoteSolver(
            cat, provs, target="127.0.0.1:1", timeout=0.2)
        op.kube.create("provisioners", "default", default_provisioner())
        for i in range(4):
            p = make_pod(f"p{i}", cpu="1", memory="1Gi")
            op.kube.create("pods", p.name, p)
        result = op.provisioning.reconcile_once()
        assert result is not None
        assert len(result.nodes) >= 1
        assert result.unschedulable_count() == 0


def test_version_skew_sync_without_content_hash_degrades_loudly():
    # ADVICE r2: an old server that predates content-hash Sync answers
    # catalog_hash=0. The client must accept via the legacy seqnum handshake
    # (not StaleSync every cycle) AND surface the skew via metric + warning.
    from karpenter_tpu.solver import solver_pb2 as pb
    from karpenter_tpu.solver.client import VERSION_SKEW
    from karpenter_tpu.solver.service import SolverService

    class LegacyService(SolverService):
        def Sync(self, request, context):
            resp = super().Sync(request, context)
            return pb.SyncResponse(seqnum=resp.seqnum, catalog_hash=0)

    srv, port, _svc = serve("127.0.0.1:0", service=LegacyService())
    try:
        before = VERSION_SKEW.value()
        solver = RemoteSolver(small_catalog(), [default_provisioner()],
                              target=f"127.0.0.1:{port}")
        res = solver.solve([make_pod("a", cpu="1", memory="1Gi")])
        assert res.nodes  # solve went through despite the skewed handshake
        assert VERSION_SKEW.value() == before + 1
        # synced state recorded: the next solve does NOT re-sync every cycle
        res2 = solver.solve([make_pod("b", cpu="1", memory="1Gi")])
        assert res2.nodes
        assert VERSION_SKEW.value() == before + 1
    finally:
        srv.stop(grace=None)


class TestSolveMany:
    """Wave-pipelined batch API: K solves, one concatenated device read
    (docs/designs/solver-boundary.md read-budget discipline)."""

    def test_wave_results_match_individual_solves(self):
        cat = small_catalog()
        solver = TPUSolver(cat, [default_provisioner()])
        problems = [
            {"pods": mixed_pods(16)},
            {"pods": [make_pod(f"big-{i}", cpu="2", memory="8Gi")
                      for i in range(10)]},
            {"pods": [make_pod(f"tiny-{i}", cpu="100m", memory="128Mi")
                      for i in range(30)]},
        ]
        wave = solver.solve_many(problems)
        solo = [solver.solve(**p) for p in problems]
        assert len(wave) == len(solo) == 3
        for w, s in zip(wave, solo):
            assert w.decisions() == s.decisions()
            assert w.unschedulable_count() == s.unschedulable_count()

    def test_same_shape_wave_folds_into_one_vmapped_dispatch(self, monkeypatch):
        """K same-shape problems must cost ONE device dispatch (the
        degraded tunnel link charges per operation, not per byte —
        docs/designs/solver-boundary.md cost model)."""
        import karpenter_tpu.solver.core as score

        calls = {"wave": 0, "single": 0}
        orig_wave, orig_flat = score._wave_pack_flat, score.pack_flat

        def count_wave(*a, **k):
            calls["wave"] += 1
            return orig_wave(*a, **k)

        def count_single(*a, **k):
            calls["single"] += 1
            return orig_flat(*a, **k)

        monkeypatch.setattr(score, "_wave_pack_flat", count_wave)
        monkeypatch.setattr(score, "pack_flat", count_single)
        solver = TPUSolver(small_catalog(), [default_provisioner()])
        problems = [{"pods": mixed_pods(16)} for _ in range(4)]
        wave = solver.solve_many(problems)
        assert calls == {"wave": 1, "single": 0}, calls
        solo = [solver.solve(**p) for p in problems]
        for w, s in zip(wave, solo):
            assert w.decisions() == s.decisions()
            assert w.unschedulable_count() == s.unschedulable_count() == 0

    def test_mixed_shape_wave_buckets_and_matches(self):
        """Problems of different padded shapes land in different vmap
        buckets (or the single-dispatch path) and still match solve()."""
        cat = small_catalog()
        solver = TPUSolver(cat, [default_provisioner()])
        problems = (
            [{"pods": mixed_pods(16)} for _ in range(2)]       # bucket A x2
            + [{"pods": [make_pod(f"w-{i}", cpu="250m", memory="512Mi")
                         for i in range(150)]}]                # bigger Gb/Nb
            + [{"pods": mixed_pods(5)}]                        # small
        )
        wave = solver.solve_many(problems)
        solo = [solver.solve(**p) for p in problems]
        for w, s in zip(wave, solo):
            assert w.decisions() == s.decisions()
            assert w.unschedulable_count() == s.unschedulable_count()

    def test_callback_readback_matches_device_get(self, monkeypatch):
        """KARPENTER_TPU_READBACK=callback routes results host-ward via
        io_callback (the relay escape hatch) — bit-identical decisions to
        the default device_get path."""
        import karpenter_tpu.solver.core as score

        cat = small_catalog()
        solver = TPUSolver(cat, [default_provisioner()])
        pods = mixed_pods(24)
        baseline = solver.solve(pods)
        monkeypatch.setattr(score, "_READBACK", "callback")
        cb_solver = TPUSolver(cat, [default_provisioner()])
        via_cb = cb_solver.solve(pods)
        assert via_cb.decisions() == baseline.decisions()
        assert via_cb.unschedulable_count() == baseline.unschedulable_count()
        # the wave's concatenated read routes through the same transport
        wave = cb_solver.solve_many([{"pods": pods}] * 2)
        assert all(w.decisions() == baseline.decisions() for w in wave)
        assert not score._CB_INBOX  # nothing leaked in the inbox

    def test_mid_wave_catalog_bump_stays_coherent(self, monkeypatch):
        """A catalog bump landing between two encodes of one wave must not
        pair a new-grid encode with stale device catalog arrays: problems
        encoded after the bump ship their own grid's arrays and bucket
        separately (grid identity is part of the bucket key)."""
        import karpenter_tpu.solver.core as score
        from karpenter_tpu.models.instancetype import make_instance_type

        cat = small_catalog()
        solver = TPUSolver(cat, [default_provisioner()])
        pods = mixed_pods(16)
        solo_old = solver.solve(pods)  # pre-bump decisions (old grid)

        real_encode = score.encode_problem
        calls = {"n": 0}

        def bumping_encode(*a, **k):
            calls["n"] += 1
            if calls["n"] == 2:  # between problem 1 and problem 2
                cat.types.append(make_instance_type(
                    "late.8xl", cpu=16, memory="64Gi", od_price=0.01))
                cat.bump()
            return real_encode(*a, **k)

        monkeypatch.setattr(score, "encode_problem", bumping_encode)
        wave = solver.solve_many([{"pods": pods} for _ in range(3)])
        monkeypatch.setattr(score, "encode_problem", real_encode)

        # problem 1 solved on the pre-bump snapshot
        assert wave[0].decisions() == solo_old.decisions()
        # problems 2-3 solved coherently on the bumped catalog (the dirt-
        # cheap late.8xl must win) and match a fresh post-bump solve
        solo_new = solver.solve(pods)
        assert wave[1].decisions() == wave[2].decisions() == solo_new.decisions()
        assert wave[1].decisions() != solo_old.decisions()
        assert {d[0] for d in wave[1].decisions()} == {"late.8xl"}
        for w in wave:
            assert w.unschedulable_count() == 0

    def test_deferred_affinity_problems_fall_back_to_two_round(self):
        from karpenter_tpu.models.pod import PodAffinityTerm

        cat = small_catalog()
        solver = TPUSolver(cat, [default_provisioner()])
        anchor = [make_pod(f"a-{i}", cpu="250m", memory="256Mi",
                           labels=(("app", "anchor"),)) for i in range(4)]
        follower = [make_pod(
            f"f-{i}", cpu="250m", memory="256Mi",
            pod_affinity=(PodAffinityTerm(
                match_labels=(("app", "anchor"),),
                topology_key=wk.LABEL_HOSTNAME),))
            for i in range(2)]
        problems = [{"pods": anchor + follower}, {"pods": mixed_pods(8)}]
        wave = solver.solve_many(problems)
        solo = [solver.solve(**p) for p in problems]
        for w, s in zip(wave, solo):
            assert w.decisions() == s.decisions()
        placed = sum(n.pod_count for n in wave[0].nodes)
        assert placed + wave[0].unschedulable_count() == 6

    def test_empty_wave(self):
        solver = TPUSolver(small_catalog(), [default_provisioner()])
        assert solver.solve_many([]) == []

    def test_native_solve_many_stays_on_host(self, monkeypatch):
        """NativeSolver is the device-unreachable fallback: its wave API
        must never touch the jax dispatch path."""
        import karpenter_tpu.solver.core as score
        from karpenter_tpu.solver.core import NativeSolver

        def boom(*a, **k):
            raise AssertionError("NativeSolver.solve_many dispatched to jax")

        monkeypatch.setattr(score, "dispatch_pack", boom)
        solver = NativeSolver(small_catalog(), [default_provisioner()])
        problems = [{"pods": mixed_pods(12)}, {"pods": mixed_pods(6)}]
        wave = solver.solve_many(problems)
        solo = [solver.solve(**p) for p in problems]
        for w, s in zip(wave, solo):
            assert w.decisions() == s.decisions()


def test_solver_service_profiling_hook(tmp_path):
    """--trace-dir captures a jax.profiler trace of the Nth solve
    (SURVEY §5.1 device-path profiling as a first-class service feature)."""
    import os

    from karpenter_tpu.solver.client import RemoteSolver
    from karpenter_tpu.solver.service import SolverService, serve

    svc = SolverService(trace_dir=str(tmp_path), trace_every=1)
    srv, port, _ = serve("127.0.0.1:0", service=svc)
    try:
        # generous deadline: the traced solve pays jax.profiler start/stop,
        # which grows with accumulated session state — late in a full-suite
        # run it can exceed the 10s production default (observed flake)
        solver = RemoteSolver(small_catalog(), [default_provisioner()],
                              target=f"127.0.0.1:{port}", timeout=120.0)
        res = solver.solve(mixed_pods(8))
        assert sum(n.pod_count for n in res.nodes) == 8
        produced = []
        for root, _dirs, files in os.walk(tmp_path):
            produced += [f for f in files if "trace" in f or f.endswith(".pb")]
        assert produced, "no profiler trace written"
    finally:
        srv.stop(grace=None)


class TestRemoteConsolidation:
    """The Consolidate RPC: the batched search runs on the SERVICE's device
    (the deployed split gives the chip to the sidecar) and must return the
    identical action the in-process kernel picks."""

    def _cluster(self, cat):
        from karpenter_tpu.models.cluster import ClusterState, StateNode

        big = cat.by_name["m.xlarge"]
        cluster = ClusterState()
        for i in range(8):
            cluster.add_node(StateNode(
                name=f"n-{i}",
                labels={**big.labels_dict(), wk.LABEL_ZONE: "zone-1a",
                        wk.LABEL_CAPACITY_TYPE: "on-demand",
                        wk.LABEL_PROVISIONER: "default"},
                allocatable=big.allocatable_vector(),
                instance_type=big.name, zone="zone-1a",
                capacity_type="on-demand", price=big.offerings[0].price,
                provisioner_name="default",
                pods=[make_pod(f"p-{i}", cpu="500m", memory="1Gi",
                               node_name=f"n-{i}")]))
        return cluster

    def test_remote_action_matches_in_process(self, server):
        from karpenter_tpu.oracle.consolidation import eligible
        from karpenter_tpu.ops.consolidate import run_consolidation
        from karpenter_tpu.solver.client import RemoteSolver

        cat = small_catalog()
        prov = default_provisioner(consolidation_enabled=True)
        cluster = self._cluster(cat)
        eligible_names = {n for n, node in cluster.nodes.items()
                          if eligible(node, cluster)}
        rs = RemoteSolver(cat, [prov], target=f"127.0.0.1:{server}")
        remote = rs.consolidate(cluster, eligible_names, now=0.0)
        local = run_consolidation(cluster, cat, [prov], now=0.0)
        assert (remote is None) == (local is None)
        assert remote.kind == local.kind
        assert remote.nodes == local.nodes
        assert abs(remote.savings - local.savings) < 1e-9
        assert abs(remote.disruption_cost - local.disruption_cost) < 1e-9
        assert remote.replacement == local.replacement

    def test_remote_respects_controller_eligibility_verdicts(self, server):
        from karpenter_tpu.solver.client import RemoteSolver

        cat = small_catalog()
        prov = default_provisioner(consolidation_enabled=True)
        cluster = self._cluster(cat)
        rs = RemoteSolver(cat, [prov], target=f"127.0.0.1:{server}")
        # the controller says NOTHING is eligible (e.g. every node's pods
        # are PDB-blocked): the service must find no action
        assert rs.consolidate(cluster, set(), now=0.0) is None

    def test_unsynced_consolidate_resyncs_transparently(self, server):
        from karpenter_tpu.oracle.consolidation import eligible
        from karpenter_tpu.solver.client import RemoteSolver

        cat = small_catalog()
        prov = default_provisioner(consolidation_enabled=True)
        cluster = self._cluster(cat)
        eligible_names = {n for n, node in cluster.nodes.items()
                          if eligible(node, cluster)}
        rs = RemoteSolver(cat, [prov], target=f"127.0.0.1:{server}")
        # no explicit sync() call: consolidate must sync on demand
        action = rs.consolidate(cluster, eligible_names, now=0.0)
        assert action is not None

    def test_operator_routes_consolidation_to_the_sidecar(self, server):
        """Operator(solver_target=...) wires the deprovisioner's remote
        chain: the action comes from the service (method=remote), and a
        dead sidecar degrades to the in-process kernel."""
        from karpenter_tpu.apis.settings import Settings
        from karpenter_tpu.fake.cloud import FakeCloud
        from karpenter_tpu.metrics import Registry
        from karpenter_tpu.models.cluster import StateNode
        from karpenter_tpu.operator import Operator

        catalog = small_catalog()
        cloud = FakeCloud(catalog)
        settings = Settings(cluster_name="t", cluster_endpoint="https://t")
        op = Operator(cloud, settings, catalog,
                      solver_target=f"127.0.0.1:{server}")
        assert op.deprovisioning.remote_consolidator is not None
        prov = default_provisioner(consolidation_enabled=True)
        op.kube.create("provisioners", "default", prov)
        big = catalog.by_name["m.xlarge"]
        for i in range(6):
            node = StateNode(
                name=f"n-{i}",
                labels={**big.labels_dict(), wk.LABEL_ZONE: "zone-1a",
                        wk.LABEL_CAPACITY_TYPE: "on-demand",
                        wk.LABEL_PROVISIONER: "default"},
                allocatable=big.allocatable_vector(),
                instance_type=big.name, zone="zone-1a",
                capacity_type="on-demand", price=big.offerings[0].price,
                provisioner_name="default",
                pods=[make_pod(f"p-{i}", cpu="250m", memory="512Mi",
                               node_name=f"n-{i}")])
            op.cluster.add_node(node)
            op.kube.create("nodes", node.name, node)
        action = op.deprovisioning.reconcile_consolidation()
        assert action is not None and action.kind in ("delete", "replace")

    def test_draining_nodes_never_absorb_evicted_pods_remotely(self, server):
        """A node concurrently marked for deletion (emptiness/interruption)
        must not be a landing spot in the remote simulation — the wire
        carries marked_for_deletion so the service's survivor mask matches
        the in-process kernel's."""
        from karpenter_tpu.ops.consolidate import run_consolidation
        from karpenter_tpu.solver.client import RemoteSolver

        cat = small_catalog()
        prov = default_provisioner(consolidation_enabled=True)
        cluster = self._cluster(cat)
        # every node except n-0 is draining: nothing may absorb n-0's pods.
        # The only legal action left is REPLACE onto a cheaper fresh node —
        # a service that ignored the draining mask would pick the
        # higher-savings DELETE (pods "fit" on a draining peer) instead.
        for name, node in cluster.nodes.items():
            if name != "n-0":
                node.marked_for_deletion = True
        rs = RemoteSolver(cat, [prov], target=f"127.0.0.1:{server}")
        remote = rs.consolidate(cluster, {"n-0"}, now=0.0)
        local = run_consolidation(cluster, cat, [prov], now=0.0)
        assert local is not None and local.kind == "replace"
        assert remote is not None and remote.kind == "replace"
        assert remote.nodes == local.nodes
        assert remote.replacement == local.replacement

    def test_dead_sidecar_degrades_to_in_process_kernel(self):
        """The remote-failure branch: a dead target must fall through to
        the in-process kernel and still produce the same action."""
        from karpenter_tpu.apis.settings import Settings
        from karpenter_tpu.fake.cloud import FakeCloud
        from karpenter_tpu.models.cluster import StateNode
        from karpenter_tpu.operator import Operator
        from karpenter_tpu.ops.consolidate import run_consolidation

        catalog = small_catalog()
        cloud = FakeCloud(catalog)
        settings = Settings(cluster_name="t", cluster_endpoint="https://t")
        op = Operator(cloud, settings, catalog,
                      solver_target="127.0.0.1:1")  # nothing listens here
        prov = default_provisioner(consolidation_enabled=True)
        op.kube.create("provisioners", "default", prov)
        big = catalog.by_name["m.xlarge"]
        for i in range(6):
            node = StateNode(
                name=f"n-{i}",
                labels={**big.labels_dict(), wk.LABEL_ZONE: "zone-1a",
                        wk.LABEL_CAPACITY_TYPE: "on-demand",
                        wk.LABEL_PROVISIONER: "default"},
                allocatable=big.allocatable_vector(),
                instance_type=big.name, zone="zone-1a",
                capacity_type="on-demand", price=big.offerings[0].price,
                provisioner_name="default",
                pods=[make_pod(f"p-{i}", cpu="250m", memory="512Mi",
                               node_name=f"n-{i}")])
            op.cluster.add_node(node)
            op.kube.create("nodes", node.name, node)
        # shrink the grpc timeout so the dead dial fails fast
        import karpenter_tpu.solver.client as client_mod
        orig = client_mod.RemoteSolver.__init__

        def fast_init(self, *a, **kw):
            kw.setdefault("timeout", 0.2)
            orig(self, *a, **kw)

        # the expectation comes from the UNMUTATED cluster (reconcile marks
        # the chosen nodes as it executes the action)
        want = run_consolidation(op.cluster, catalog, [prov], now=0.0)
        client_mod.RemoteSolver.__init__ = fast_init
        try:
            action = op.deprovisioning.reconcile_consolidation()
        finally:
            client_mod.RemoteSolver.__init__ = orig
        assert action is not None and want is not None
        assert action.kind == want.kind and action.nodes == want.nodes


def test_ice_resync_donates_static_grid():
    """An ICE-only catalog change re-synced to the service must reuse the
    resident solver's static grid arrays (the spot-storm fast path) while
    a layout change must not."""
    import dataclasses

    from karpenter_tpu.solver import wire
    from karpenter_tpu.solver.service import SolverService, pb

    svc = SolverService()
    cat = small_catalog()
    provs = [default_provisioner()]
    req = pb.SyncRequest(catalog=wire.catalog_to_wire(cat),
                         provisioners=[wire.provisioner_to_wire(p)
                                       for p in provs])
    svc.Sync(req, None)
    (s1, _), = list(svc._cache.values())
    g1 = s1.grid()

    iced = dataclasses.replace(cat, types=[
        dataclasses.replace(t, offerings=type(t.offerings)(tuple(
            dataclasses.replace(o, available=(o.capacity_type != "spot"))
            for o in t.offerings)))
        for t in cat.types], seqnum=cat.seqnum + 1)
    req2 = pb.SyncRequest(catalog=wire.catalog_to_wire(iced),
                          provisioners=[wire.provisioner_to_wire(p)
                                        for p in provs])
    svc.Sync(req2, None)
    s2 = [s for s, _ in svc._cache.values() if s is not s1][0]
    g2 = s2.grid()
    assert g2.tiebreak is g1.tiebreak and g2.alloc_t is g1.alloc_t
    assert g2.valid.sum() < g1.valid.sum()
    # the donor's cache dict is NOT shared (it keeps serving its clients)
    assert s2._group_cache is not s1._group_cache

    # layout change (price move): no static sharing
    moved = dataclasses.replace(cat, types=[
        dataclasses.replace(t, offerings=type(t.offerings)(tuple(
            dataclasses.replace(o, price=o.price * 2) for o in t.offerings)))
        for t in cat.types], seqnum=cat.seqnum + 2)
    req3 = pb.SyncRequest(catalog=wire.catalog_to_wire(moved),
                          provisioners=[wire.provisioner_to_wire(p)
                                        for p in provs])
    svc.Sync(req3, None)
    s3 = [s for s, _ in svc._cache.values()
          if s is not s1 and s is not s2][0]
    assert s3.grid().tiebreak is not g2.tiebreak
