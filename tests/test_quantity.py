from fractions import Fraction

import pytest

from karpenter_tpu.utils.quantity import (
    cpu_millis, format_cpu, format_mem, mem_bytes, parse_quantity,
)


def test_parse_plain():
    assert parse_quantity("2") == 2
    assert parse_quantity(3) == 3
    assert parse_quantity("1.5") == Fraction(3, 2)


def test_parse_milli_cpu():
    assert cpu_millis("100m") == 100
    assert cpu_millis("1") == 1000
    assert cpu_millis("1.5") == 1500
    assert cpu_millis(2) == 2000


def test_parse_memory_suffixes():
    assert mem_bytes("256M") == 256_000_000
    assert mem_bytes("1Gi") == 2**30
    assert mem_bytes("512Ki") == 512 * 1024
    assert mem_bytes("128974848") == 128974848
    assert mem_bytes("1e3") == 1000


def test_invalid():
    with pytest.raises(ValueError):
        parse_quantity("abc")
    with pytest.raises(ValueError):
        parse_quantity("1X")


def test_format_roundtrip():
    assert format_cpu(1500) == "1500m"
    assert format_cpu(2000) == "2"
    assert format_mem(2**31) == "2Gi"
