"""Test harness config: force an 8-device virtual CPU platform so multi-chip
sharding paths (mesh/pjit/shard_map) are exercised without TPU hardware —
the analogue of the reference's envtest-backed hermetic tier (SURVEY.md §4).

Must run before the first `import jax` anywhere in the test session.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
