"""Test harness config: force an 8-device virtual CPU platform so multi-chip
sharding paths (mesh/pjit/shard_map) are exercised without TPU hardware —
the analogue of the reference's envtest-backed hermetic tier (SURVEY.md §4).

The environment's sitecustomize pre-imports jax with the axon TPU platform,
so env vars alone are too late — the platform must also be pinned via
jax.config. The pin logic is single-sourced in karpenter_tpu/utils/jaxenv.py
(shared with bench.py and __graft_entry__.py).
"""

from karpenter_tpu.utils.jaxenv import pin_cpu

pin_cpu(8)
