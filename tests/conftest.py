"""Test harness config: force an 8-device virtual CPU platform so multi-chip
sharding paths (mesh/pjit/shard_map) are exercised without TPU hardware —
the analogue of the reference's envtest-backed hermetic tier (SURVEY.md §4).

The environment's sitecustomize pre-imports jax with the axon TPU platform,
so env vars alone are too late — the platform must also be pinned via
jax.config. The pin logic is single-sourced in karpenter_tpu/utils/jaxenv.py
(shared with bench.py and __graft_entry__.py).
"""

import os
import random
import time

import pytest

from karpenter_tpu.utils.jaxenv import pin_cpu

pin_cpu(8)

# Randomized tier (reference analogue: Makefile:65-72 battletest =
# --ginkgo.randomize-all + -tags random_test_delay). pytest-randomly is not
# in the image, so the shuffle lives here: KARPENTER_TPU_RANDOMIZE=1
# shuffles the collected test order with a logged, reproducible seed
# (KARPENTER_TPU_TEST_SEED pins it for replay), and
# KARPENTER_TPU_TEST_DELAY_MS=N sleeps a random 0..N ms before every test —
# the random_test_delay build-tag analogue that perturbs thread interleaving
# in the race tier.

def _randomize_enabled() -> bool:
    return os.environ.get("KARPENTER_TPU_RANDOMIZE") == "1"


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: long-running tier (multi-seed chaos sweeps etc.); the tier-1 "
        "suite runs -m 'not slow'")
    if _randomize_enabled():
        config._karpenter_seed = int(
            os.environ.get("KARPENTER_TPU_TEST_SEED", 0)) or \
            random.SystemRandom().randrange(1, 2**31)


def pytest_collection_modifyitems(config, items):
    seed = getattr(config, "_karpenter_seed", None)
    if seed is not None:
        random.Random(seed).shuffle(items)


def pytest_report_header(config):
    seed = getattr(config, "_karpenter_seed", None)
    if seed is not None:
        return (f"randomized order: seed={seed} "
                f"(replay: KARPENTER_TPU_TEST_SEED={seed})")
    return None


@pytest.fixture(autouse=True)
def random_test_delay():
    delay_ms = int(os.environ.get("KARPENTER_TPU_TEST_DELAY_MS", "0"))
    if delay_ms:
        time.sleep(random.random() * delay_ms / 1000.0)
    yield
