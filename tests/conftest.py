"""Test harness config: force an 8-device virtual CPU platform so multi-chip
sharding paths (mesh/pjit/shard_map) are exercised without TPU hardware —
the analogue of the reference's envtest-backed hermetic tier (SURVEY.md §4).

The environment's sitecustomize pre-imports jax with the axon TPU platform,
so env vars alone are too late — we must also flip jax_platforms via config.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
