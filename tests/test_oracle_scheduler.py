"""Oracle scheduler behavior tests.

These encode the reference semantics the whole framework is built to
(designs/bin-packing.md FFD; utilization E2E "100 pods => exactly 100 nodes",
test/suites/utilization/suite_test.go:40-58; price-ordered selection,
instance.go:445-462).
"""

import pytest

from karpenter_tpu.apis import wellknown as wk
from karpenter_tpu.apis.provisioner import Provisioner
from karpenter_tpu.models.instancetype import Catalog, make_instance_type
from karpenter_tpu.models.pod import Taint, Toleration, TopologySpreadConstraint, make_pod
from karpenter_tpu.models.requirements import Requirements, OP_IN, OP_NOT_IN
from karpenter_tpu.oracle.scheduler import ExistingNode, Scheduler


def small_catalog():
    return Catalog(types=[
        make_instance_type("small.2x", cpu=2, memory="8Gi", od_price=0.10, spot_price=0.03),
        make_instance_type("medium.4x", cpu=4, memory="16Gi", od_price=0.20, spot_price=0.06),
        make_instance_type("large.8x", cpu=8, memory="32Gi", od_price=0.40, spot_price=0.12),
        make_instance_type("arm.4x", cpu=4, memory="16Gi", arch="arm64", od_price=0.15),
        make_instance_type("gpu.8x", cpu=8, memory="64Gi", od_price=2.50,
                           extended={wk.RESOURCE_NVIDIA_GPU: 4},
                           extra_labels={wk.LABEL_INSTANCE_GPU_NAME: "a100"}),
    ])


def default_provisioner(**kw):
    p = Provisioner(name="default", **kw)
    p.set_defaults()
    return p


def test_single_pod_picks_cheapest_fitting_type():
    sched = Scheduler(small_catalog(), [default_provisioner()])
    res = sched.schedule([make_pod("p0", cpu="1", memory="1Gi")])
    assert len(res.new_nodes) == 1
    (name, zone, ct, npods), = res.node_decisions(sched.options)
    assert name == "small.2x"
    assert ct == "on-demand"  # default capacity-type requirement
    assert npods == 1


def test_bin_packs_multiple_pods_one_node():
    sched = Scheduler(small_catalog(), [default_provisioner()])
    res = sched.schedule([make_pod(f"p{i}", cpu="500m", memory="512Mi") for i in range(4)])
    assert len(res.new_nodes) == 1
    assert len(res.new_nodes[0].pods) == 4
    assert res.new_nodes[0].decided.itype.name == "small.2x"


def test_overflow_opens_second_node():
    # 5 x 1cpu pods: biggest type has 8 cpu -> one large + one small, FFD greedy
    sched = Scheduler(small_catalog(), [default_provisioner()])
    res = sched.schedule([make_pod(f"p{i}", cpu="1", memory="1Gi") for i in range(10)])
    total = sum(len(n.pods) for n in res.new_nodes)
    assert total == 10
    assert not res.unschedulable
    # capacity respected on every node under its decided type
    for n in res.new_nodes:
        alloc = n.decided.itype.allocatable_vector()
        assert all(u <= a for u, a in zip(n.used, alloc))


def test_utilization_parity_100_pods_100_nodes():
    # Reference E2E (utilization/suite_test.go:40-58): 1.5-cpu pods on a
    # 2-cpu type => exactly one pod per node.
    catalog = Catalog(types=[make_instance_type("t3a.small", cpu=2, memory="2Gi", od_price=0.05)])
    sched = Scheduler(catalog, [default_provisioner()])
    res = sched.schedule([make_pod(f"p{i}", cpu="1.5", memory="128Mi") for i in range(100)])
    assert len(res.new_nodes) == 100
    assert all(len(n.pods) == 1 for n in res.new_nodes)


def test_spot_preferred_when_allowed():
    p = Provisioner(name="spot", requirements=Requirements.of(
        (wk.LABEL_CAPACITY_TYPE, OP_IN, ["spot", "on-demand"])))
    p.set_defaults()
    sched = Scheduler(small_catalog(), [p])
    res = sched.schedule([make_pod("p0", cpu="1", memory="1Gi")])
    assert res.new_nodes[0].decided.capacity_type == "spot"  # spot is cheaper


def test_arch_requirement_filters_types():
    sched = Scheduler(small_catalog(), [default_provisioner()])
    res = sched.schedule([make_pod("p0", cpu="1", memory="1Gi",
                                   node_selector={wk.LABEL_ARCH: "arm64"})])
    # default provisioner restricts to amd64 -> unschedulable
    assert res.unschedulable

    p = Provisioner(name="any-arch", requirements=Requirements.of(
        (wk.LABEL_ARCH, OP_IN, ["amd64", "arm64"])))
    p.set_defaults()
    res2 = Scheduler(small_catalog(), [p]).schedule(
        [make_pod("p0", cpu="1", memory="1Gi", node_selector={wk.LABEL_ARCH: "arm64"})])
    assert len(res2.new_nodes) == 1
    assert res2.new_nodes[0].decided.itype.name == "arm.4x"


def test_gpu_pod_gets_gpu_node():
    sched = Scheduler(small_catalog(), [default_provisioner()])
    res = sched.schedule([make_pod("g0", cpu="1", memory="1Gi",
                                   extended={wk.RESOURCE_NVIDIA_GPU: 1})])
    assert res.new_nodes[0].decided.itype.name == "gpu.8x"


def test_taints_require_toleration():
    p = default_provisioner(taints=(Taint(key="dedicated", value="gpu", effect="NoSchedule"),))
    sched = Scheduler(small_catalog(), [p])
    res = sched.schedule([make_pod("p0", cpu="1", memory="1Gi")])
    assert res.unschedulable

    res2 = sched.schedule([make_pod(
        "p1", cpu="1", memory="1Gi",
        tolerations=(Toleration(key="dedicated", operator="Equal", value="gpu"),))])
    assert len(res2.new_nodes) == 1


def test_zone_selector_restricts_offering():
    sched = Scheduler(small_catalog(), [default_provisioner()])
    res = sched.schedule([make_pod("p0", cpu="1", memory="1Gi",
                                   node_selector={wk.LABEL_ZONE: "zone-1b"})])
    assert res.new_nodes[0].decided.zone == "zone-1b"


def test_incompatible_zone_pods_get_separate_nodes():
    # zone-1a pod and zone-1b pod cannot share a node even though both fit:
    # requirement tightening via option-set intersection.
    sched = Scheduler(small_catalog(), [default_provisioner()])
    res = sched.schedule([
        make_pod("a", cpu="100m", memory="128Mi", node_selector={wk.LABEL_ZONE: "zone-1a"}),
        make_pod("b", cpu="100m", memory="128Mi", node_selector={wk.LABEL_ZONE: "zone-1b"}),
    ])
    assert len(res.new_nodes) == 2
    zones = sorted(n.decided.zone for n in res.new_nodes)
    assert zones == ["zone-1a", "zone-1b"]


def test_zone_topology_spread_balances():
    sched = Scheduler(small_catalog(), [default_provisioner()])
    spread = (TopologySpreadConstraint(max_skew=1, topology_key=wk.LABEL_ZONE),)
    res = sched.schedule([make_pod(f"p{i}", cpu="1", memory="1Gi", topology=spread)
                          for i in range(9)])
    per_zone = {}
    for n in res.new_nodes:
        per_zone[n.decided.zone] = per_zone.get(n.decided.zone, 0) + len(n.pods)
    assert sorted(per_zone.values()) == [3, 3, 3]


def test_hostname_anti_affinity_one_per_node():
    sched = Scheduler(small_catalog(), [default_provisioner()])
    res = sched.schedule([make_pod(f"p{i}", cpu="100m", memory="128Mi",
                                   anti_affinity_hostname=True) for i in range(5)])
    assert len(res.new_nodes) == 5


def test_provisioner_weight_order():
    p_low = Provisioner(name="low", weight=1)
    p_high = Provisioner(name="high", weight=10,
                         labels=(("team", "ml"),))
    for p in (p_low, p_high):
        p.set_defaults()
    sched = Scheduler(small_catalog(), [p_low, p_high])
    res = sched.schedule([make_pod("p0", cpu="1", memory="1Gi")])
    assert res.new_nodes[0].provisioner.name == "high"


def test_existing_node_used_first():
    sched = Scheduler(small_catalog(), [default_provisioner()])
    existing = ExistingNode(
        name="node-1",
        labels={wk.LABEL_ARCH: "amd64", wk.LABEL_OS: "linux",
                wk.LABEL_ZONE: "zone-1a", wk.LABEL_CAPACITY_TYPE: "on-demand"},
        allocatable=wk.capacity_vector({wk.RESOURCE_CPU: 4000,
                                        wk.RESOURCE_MEMORY: 16 * 2**30,
                                        wk.RESOURCE_PODS: 110}),
        used=[0] * wk.NUM_RESOURCES,
    )
    res = sched.schedule([make_pod("p0", cpu="1", memory="1Gi")], existing=[existing])
    assert not res.new_nodes
    assert [p.name for p in res.existing_assignments["node-1"]] == ["p0"]


def test_daemonset_pods_excluded_but_overhead_counted():
    overhead = wk.resource_vector({wk.RESOURCE_CPU: 1500, wk.RESOURCE_PODS: 2})
    sched = Scheduler(small_catalog(), [default_provisioner()], daemon_overhead=overhead)
    res = sched.schedule([
        make_pod("d0", cpu="200m", memory="64Mi", owner_kind="DaemonSet"),
        make_pod("p0", cpu="1", memory="1Gi"),
    ])
    assert len(res.new_nodes) == 1
    # 1.5 cpu overhead + 1 cpu pod > 2 cpu small -> must use medium.4x
    assert res.new_nodes[0].decided.itype.name == "medium.4x"
    assert len(res.new_nodes[0].pods) == 1  # daemon pod not packed


def test_unschedulable_resource_too_big():
    sched = Scheduler(small_catalog(), [default_provisioner()])
    res = sched.schedule([make_pod("huge", cpu="64", memory="1Gi")])
    assert res.unschedulable


def test_zone_anti_affinity_one_per_zone():
    sched = Scheduler(small_catalog(), [default_provisioner()])
    res = sched.schedule([make_pod(f"p{i}", cpu="100m", memory="128Mi",
                                   anti_affinity_zone=True) for i in range(5)])
    # 3 zones -> 3 pods placed in distinct zones, 2 unschedulable
    assert len(res.new_nodes) == 3
    assert len({n.decided.zone for n in res.new_nodes}) == 3
    assert len(res.unschedulable) == 2


def test_unknown_extended_resource_unschedulable():
    sched = Scheduler(small_catalog(), [default_provisioner()])
    res = sched.schedule([make_pod("fpga", cpu="100m", memory="128Mi",
                                   extended={"intel.com/fpga": 1})])
    assert res.unschedulable


def test_unavailable_offerings_not_advertised():
    from karpenter_tpu.models.instancetype import Offering, Offerings
    t = make_instance_type("x.1", cpu=2, memory="4Gi")
    t = type(t)(name=t.name, labels=t.labels, capacity=t.capacity, overhead=t.overhead,
                offerings=Offerings([Offering("zone-1a", "on-demand", 1.0, available=False),
                                     Offering("zone-1b", "on-demand", 1.0, available=True)]))
    reqs = t.requirements()
    zone = reqs.get(wk.LABEL_ZONE)
    assert zone.has("zone-1b") and not zone.has("zone-1a")


def test_label_distinct_deployments_spread_independently():
    # two deployments with identical shapes but different labels must each
    # satisfy their own zone spread (group dedupe must not merge them)
    spread = (TopologySpreadConstraint(max_skew=1, topology_key=wk.LABEL_ZONE),)
    pods = [make_pod(f"web-{i}", cpu="1", memory="1Gi", topology=spread,
                     labels=(("app", "web"),)) for i in range(3)] + \
           [make_pod(f"api-{i}", cpu="1", memory="1Gi", topology=spread,
                     labels=(("app", "api"),)) for i in range(3)]
    sched = Scheduler(small_catalog(), [default_provisioner()])
    res = sched.schedule(pods)
    per = {}
    for n in res.new_nodes:
        for p in n.pods:
            app = dict(p.labels)["app"]
            per.setdefault(app, {}).setdefault(n.decided.zone, 0)
            per[app][n.decided.zone] += 1
    for app, zones in per.items():
        assert sorted(zones.values()) == [1, 1, 1], (app, zones)


def test_water_fill_closed_form_matches_sequential_loop():
    # the closed form must reproduce the sequential "lowest population,
    # name tie-break" loop bit-for-bit (it replaced an O(pods x zones) loop
    # on the encode hot path)
    import random

    from karpenter_tpu.oracle.scheduler import water_fill_shares

    rng = random.Random(7)
    for trial in range(300):
        n_zones = rng.randint(1, 6)
        allowed = sorted(f"z{i}" for i in range(n_zones))
        resident = {z: rng.randint(0, 12) for z in allowed}
        count = rng.randint(0, 40)
        # sequential reference
        counts = dict(resident)
        seq = {z: 0 for z in allowed}
        for _ in range(count):
            z = min(allowed, key=lambda zz: (counts[zz], zz))
            counts[z] += 1
            seq[z] += 1
        assert water_fill_shares(resident, allowed, count) == seq, (
            trial, resident, count)
