"""Differential tests: TPU packer kernel vs scalar oracle.

The reference's semantics live in the oracle (designs/bin-packing.md FFD +
instance.go:445-462 selection); the kernel must produce bit-identical node
decisions (SURVEY.md §7.3 "bit-parity with sequential greedy semantics").
"""

import random

import pytest

from karpenter_tpu.apis import wellknown as wk
from karpenter_tpu.apis.provisioner import Provisioner
from karpenter_tpu.models.instancetype import Catalog, make_instance_type
from karpenter_tpu.models.pod import Toleration, TopologySpreadConstraint, make_pod
from karpenter_tpu.models.requirements import Requirements, OP_IN
from karpenter_tpu.oracle.scheduler import ExistingNode, Scheduler
from karpenter_tpu.solver.core import TPUSolver


def assert_parity(catalog, provisioners, pods, existing=None, daemon_overhead=None):
    existing = existing or []
    # oracle mutates ExistingNode.used — give each side its own copies
    def mk_existing():
        return [ExistingNode(name=e.name, labels=dict(e.labels),
                             allocatable=list(e.allocatable), used=list(e.used),
                             taints=e.taints, resident=e.resident)
                for e in existing]

    sched = Scheduler(catalog, provisioners, daemon_overhead)
    oracle_res = sched.schedule(list(pods), existing=mk_existing())
    kernel_res = TPUSolver(catalog, provisioners).solve(
        list(pods), existing=mk_existing(), daemon_overhead=daemon_overhead)

    o_decisions = oracle_res.node_decisions(sched.options)
    k_decisions = kernel_res.decisions()
    assert k_decisions == o_decisions, (
        f"decision mismatch:\n oracle: {o_decisions}\n kernel: {k_decisions}")
    o_ex = {k: len(v) for k, v in oracle_res.existing_assignments.items() if v}
    assert kernel_res.existing_counts == o_ex
    assert kernel_res.unschedulable_count() == len(oracle_res.unschedulable)
    return kernel_res


def catalog5():
    return Catalog(types=[
        make_instance_type("small.2x", cpu=2, memory="8Gi", od_price=0.10, spot_price=0.03),
        make_instance_type("medium.4x", cpu=4, memory="16Gi", od_price=0.20, spot_price=0.06),
        make_instance_type("large.8x", cpu=8, memory="32Gi", od_price=0.40, spot_price=0.12),
        make_instance_type("arm.4x", cpu=4, memory="16Gi", arch="arm64", od_price=0.15),
        make_instance_type("gpu.8x", cpu=8, memory="64Gi", od_price=2.50,
                           extended={wk.RESOURCE_NVIDIA_GPU: 4}),
    ])


def prov(name="default", **kw):
    p = Provisioner(name=name, **kw)
    p.set_defaults()
    return p


def test_parity_single_pod():
    assert_parity(catalog5(), [prov()], [make_pod("p0", cpu="1", memory="1Gi")])


def test_parity_inflate_100():
    pods = [make_pod(f"inflate-{i}", cpu="1", memory="256M") for i in range(100)]
    res = assert_parity(catalog5(), [prov()], pods)
    assert sum(n.pod_count for n in res.nodes) == 100


def test_parity_mixed_sizes():
    pods = (
        [make_pod(f"big-{i}", cpu="3", memory="12Gi") for i in range(7)]
        + [make_pod(f"mid-{i}", cpu="1", memory="2Gi") for i in range(23)]
        + [make_pod(f"tiny-{i}", cpu="100m", memory="128Mi") for i in range(50)]
    )
    assert_parity(catalog5(), [prov()], pods)


def test_parity_zone_selectors():
    pods = (
        [make_pod(f"a-{i}", cpu="1", memory="1Gi",
                  node_selector={wk.LABEL_ZONE: "zone-1a"}) for i in range(5)]
        + [make_pod(f"b-{i}", cpu="1", memory="1Gi",
                    node_selector={wk.LABEL_ZONE: "zone-1b"}) for i in range(3)]
        + [make_pod(f"free-{i}", cpu="500m", memory="512Mi") for i in range(4)]
    )
    assert_parity(catalog5(), [prov()], pods)


def test_parity_topology_spread():
    spread = (TopologySpreadConstraint(max_skew=1, topology_key=wk.LABEL_ZONE),)
    pods = [make_pod(f"s-{i}", cpu="1", memory="1Gi", topology=spread) for i in range(10)]
    assert_parity(catalog5(), [prov()], pods)


def test_parity_hostname_anti_affinity():
    pods = [make_pod(f"h-{i}", cpu="100m", memory="128Mi", anti_affinity_hostname=True)
            for i in range(7)]
    assert_parity(catalog5(), [prov()], pods)


def test_parity_multi_provisioner_weights():
    p1 = prov("low")
    p2 = Provisioner(name="high", weight=10, labels=(("team", "ml"),))
    p2.set_defaults()
    pods = [make_pod(f"p{i}", cpu="1", memory="1Gi") for i in range(6)]
    assert_parity(catalog5(), [p1, p2], pods)


def test_parity_taints_and_gpu():
    p_gpu = Provisioner(
        name="gpu",
        taints=(__import__("karpenter_tpu.models.pod", fromlist=["Taint"]).Taint(
            key="nvidia.com/gpu", value="true", effect="NoSchedule"),),
        weight=5,
    )
    p_gpu.set_defaults()
    p_def = prov()
    pods = [make_pod(f"c{i}", cpu="1", memory="1Gi") for i in range(4)] + [
        make_pod(
            f"g{i}", cpu="1", memory="2Gi",
            extended={wk.RESOURCE_NVIDIA_GPU: 1},
            tolerations=(Toleration(key="nvidia.com/gpu", operator="Exists"),),
        )
        for i in range(3)
    ]
    assert_parity(catalog5(), [p_def, p_gpu], pods)


def test_parity_existing_nodes():
    existing = [
        ExistingNode(
            name=f"node-{i}",
            labels={wk.LABEL_ARCH: "amd64", wk.LABEL_OS: "linux",
                    wk.LABEL_ZONE: "zone-1a", wk.LABEL_CAPACITY_TYPE: "on-demand"},
            allocatable=wk.capacity_vector({wk.RESOURCE_CPU: 4000,
                                            wk.RESOURCE_MEMORY: 16 * 2**30,
                                            wk.RESOURCE_PODS: 110}),
            used=[0] * wk.NUM_RESOURCES,
        )
        for i in range(2)
    ]
    pods = [make_pod(f"p{i}", cpu="1", memory="1Gi") for i in range(12)]
    assert_parity(catalog5(), [prov()], pods, existing=existing)


def test_parity_daemon_overhead():
    overhead = wk.resource_vector({wk.RESOURCE_CPU: 1500, wk.RESOURCE_PODS: 2})
    pods = [make_pod(f"p{i}", cpu="1", memory="1Gi") for i in range(5)]
    assert_parity(catalog5(), [prov()], pods, daemon_overhead=overhead)


def test_parity_kubelet_max_pods():
    from karpenter_tpu.apis.provisioner import KubeletConfiguration

    p = prov(kubelet=KubeletConfiguration(max_pods=3))
    pods = [make_pod(f"p{i}", cpu="100m", memory="128Mi") for i in range(10)]
    res = assert_parity(catalog5(), [p], pods)
    # 10 tiny pods at <=3/node => at least 4 nodes
    assert len(res.nodes) >= 4
    assert all(n.pod_count <= 3 for n in res.nodes)


def test_parity_kubelet_pods_per_core():
    from karpenter_tpu.apis.provisioner import KubeletConfiguration

    p = prov(kubelet=KubeletConfiguration(pods_per_core=1))
    # small.2x (2 cores) caps at 2 pods; large.8x at 8
    pods = [make_pod(f"p{i}", cpu="100m", memory="128Mi") for i in range(12)]
    assert_parity(catalog5(), [p], pods)


def test_parity_kubelet_reserved_overhead():
    from karpenter_tpu.apis.provisioner import KubeletConfiguration

    p = prov(kubelet=KubeletConfiguration(
        system_reserved_cpu_millis=500,
        kube_reserved_memory_bytes=2 * 2**30,
        eviction_hard_memory_bytes=300 * 2**20))
    pods = [make_pod(f"p{i}", cpu="1.5", memory="6Gi") for i in range(6)]
    res = assert_parity(catalog5(), [p], pods)
    assert res.nodes  # still schedulable, just on bigger/more nodes


def test_parity_kubelet_mixed_provisioners():
    from karpenter_tpu.apis.provisioner import KubeletConfiguration

    capped = prov(name="capped", weight=10,
                  kubelet=KubeletConfiguration(max_pods=2))
    plain = prov(name="plain")
    pods = [make_pod(f"p{i}", cpu="200m", memory="256Mi") for i in range(9)]
    assert_parity(catalog5(), [capped, plain], pods)


def test_parity_unschedulable():
    pods = [make_pod("huge", cpu="64", memory="1Gi"),
            make_pod("ok", cpu="1", memory="1Gi")]
    res = assert_parity(catalog5(), [prov()], pods)
    assert res.unschedulable_count() == 1


def test_parity_randomized_sweep():
    rng = random.Random(42)
    zones = ("zone-1a", "zone-1b", "zone-1c")
    for trial in range(12):
        n_types = rng.randint(3, 12)
        types = []
        for i in range(n_types):
            cpu = rng.choice([1, 2, 4, 8, 16, 32])
            mem_gi = cpu * rng.choice([2, 4, 8])
            types.append(make_instance_type(
                f"t{trial}.{i}x", cpu=cpu, memory=f"{mem_gi}Gi",
                zones=rng.sample(zones, rng.randint(1, 3)),
                od_price=round(0.02 * cpu + rng.random() * 0.05, 4),
                spot_price=round(0.006 * cpu + rng.random() * 0.02, 4) if rng.random() < 0.7 else None,
                pods=rng.choice([16, 32, 110]),
            ))
        catalog = Catalog(types=types)
        provs = [prov("default")]
        if rng.random() < 0.5:
            p2 = Provisioner(name="spot", weight=rng.randint(1, 20),
                             requirements=Requirements.of(
                                 (wk.LABEL_CAPACITY_TYPE, OP_IN, ["spot", "on-demand"])))
            p2.set_defaults()
            provs.append(p2)
        pods = []
        for d in range(rng.randint(1, 6)):
            cnt = rng.randint(1, 40)
            cpu_m = rng.choice(["100m", "250m", "500m", "1", "2", "3"])
            mem = rng.choice(["128Mi", "512Mi", "1Gi", "2Gi", "4Gi"])
            sel = {}
            if rng.random() < 0.3:
                sel[wk.LABEL_ZONE] = rng.choice(zones)
            topo = ()
            if rng.random() < 0.25:
                topo = (TopologySpreadConstraint(max_skew=1, topology_key=wk.LABEL_ZONE),)
            for i in range(cnt):
                pods.append(make_pod(f"d{d}-p{i}", cpu=cpu_m, memory=mem,
                                     node_selector=dict(sel), topology=topo))
        assert_parity(catalog, provs, pods)


def test_parity_zero_request_pods_on_existing_nodes():
    # regression: INT_BIG per-slot fill must not overflow the waterfall cumsum
    existing = [
        ExistingNode(
            name=f"e{i}",
            labels={wk.LABEL_ARCH: "amd64", wk.LABEL_OS: "linux",
                    wk.LABEL_ZONE: "zone-1a", wk.LABEL_CAPACITY_TYPE: "on-demand"},
            allocatable=wk.capacity_vector({wk.RESOURCE_CPU: 4000,
                                            wk.RESOURCE_MEMORY: 16 * 2**30,
                                            wk.RESOURCE_PODS: 110}),
            used=[0] * wk.NUM_RESOURCES,
        )
        for i in range(5)
    ]
    pods = [make_pod(f"z{i}", cpu=0, memory=0) for i in range(7)]
    res = assert_parity(catalog5(), [prov()], pods, existing=existing)
    assert sum(res.existing_counts.values()) == 7


def test_parity_zone_only_unavailable_offerings():
    # regression: grid zone universe must exclude unavailable-only zones,
    # matching the oracle (zone-spread would otherwise pin pods to dead zones)
    from karpenter_tpu.models.instancetype import InstanceType, Offering, Offerings
    base = make_instance_type("m.4x", cpu=4, memory="16Gi", zones=("zone-1a", "zone-1b"),
                              od_price=0.2)
    dead = InstanceType(
        name="dead.4x", labels=base.labels, capacity=base.capacity, overhead=base.overhead,
        offerings=Offerings([Offering("zone-1c", "on-demand", 0.1, available=False)]))
    catalog = Catalog(types=[base, dead])
    spread = (TopologySpreadConstraint(max_skew=1, topology_key=wk.LABEL_ZONE),)
    pods = [make_pod(f"s{i}", cpu="1", memory="1Gi", topology=spread) for i in range(9)]
    res = assert_parity(catalog, [prov()], pods)
    assert res.unschedulable_count() == 0


def _existing_in_zone(name, zone, resident=(), cpu=8000, mem=32 * 2**30):
    return ExistingNode(
        name=name,
        labels={wk.LABEL_ARCH: "amd64", wk.LABEL_OS: "linux",
                wk.LABEL_ZONE: zone, wk.LABEL_CAPACITY_TYPE: "on-demand"},
        allocatable=wk.capacity_vector({wk.RESOURCE_CPU: cpu,
                                        wk.RESOURCE_MEMORY: mem,
                                        wk.RESOURCE_PODS: 110}),
        used=[0] * wk.NUM_RESOURCES,
        resident=tuple(resident),
    )


def test_parity_zone_spread_counts_existing_domains():
    # 4 pods of the spread group already live in zone-1a; the 2 new pods must
    # water-fill into 1b and 1c, NOT round-robin from scratch (VERDICT missing
    # #4: domain-population counting, designs/bin-packing.md:28-43)
    spread = (TopologySpreadConstraint(max_skew=1, topology_key=wk.LABEL_ZONE),)

    def pod(name):
        return make_pod(name, cpu="1", memory="1Gi", topology=spread)

    residents = [pod(f"old{i}") for i in range(4)]
    existing = [_existing_in_zone("node-a", "zone-1a", residents)]
    new = [pod("new0"), pod("new1")]
    res = assert_parity(catalog5(), [prov()], new, existing=existing)
    zones = sorted(n.option.zone for n in res.nodes)
    placed_new_on_existing = sum(res.existing_counts.values())
    # neither new pod lands in the saturated zone-1a
    assert placed_new_on_existing == 0
    assert zones == ["zone-1b", "zone-1c"], zones


def test_parity_zone_spread_fills_into_lagging_domain():
    # residents [2, 1, 0]: three new pods go [0->1a? no: min zone first]
    # water-fill: counts (2,1,0) -> picks 1c, 1b, 1c -> final (2,2,2)
    spread = (TopologySpreadConstraint(max_skew=1, topology_key=wk.LABEL_ZONE),)

    def pod(name):
        return make_pod(name, cpu="1", memory="1Gi", topology=spread)

    existing = [
        _existing_in_zone("node-a", "zone-1a", [pod("oa0"), pod("oa1")]),
        _existing_in_zone("node-b", "zone-1b", [pod("ob0")]),
    ]
    new = [pod(f"n{i}") for i in range(3)]
    res = assert_parity(catalog5(), [prov()], new, existing=existing)
    # one pod tops up zone-1b (fits on node-b), two go to fresh zone-1c nodes
    per_zone = {}
    for n in res.nodes:
        per_zone[n.option.zone] = per_zone.get(n.option.zone, 0) + n.pod_count
    assert per_zone.get("zone-1c", 0) == 2
    assert res.existing_counts.get("node-b", 0) == 1


def test_parity_schedule_anyway_relaxes_instead_of_failing():
    # ScheduleAnyway spread with a zone whose only capacity can't host the
    # pod: the soft zone pin is dropped and every pod still schedules
    spread = (TopologySpreadConstraint(max_skew=1, topology_key=wk.LABEL_ZONE,
                                       when_unsatisfiable="ScheduleAnyway"),)
    cat = Catalog(types=[
        make_instance_type("small.2x", cpu=2, memory="8Gi", od_price=0.10,
                           zones=("zone-1a", "zone-1b")),  # nothing in 1c
    ])
    pods = [make_pod(f"p{i}", cpu="1", memory="1Gi", topology=spread)
            for i in range(6)]
    res = assert_parity(cat, [prov()], pods)
    assert res.unschedulable_count() == 0
    placed = sum(n.pod_count for n in res.nodes)
    assert placed == 6
    # the 1a/1b shares stay pinned; only the 1c share relaxed
    per_zone = {}
    for n in res.nodes:
        per_zone[n.option.zone] = per_zone.get(n.option.zone, 0) + n.pod_count
    assert per_zone.get("zone-1a", 0) >= 2 and per_zone.get("zone-1b", 0) >= 2


def test_parity_hostname_anti_affinity_counts_residents():
    # a resident pod of the anti-affine group blocks its node for the new
    # pod even though capacity fits (per-(group, node) remaining cap)
    def pod(name):
        return make_pod(name, cpu="1", memory="1Gi", anti_affinity_hostname=True)

    existing = [_existing_in_zone("node-a", "zone-1a", [pod("old0")])]
    res = assert_parity(catalog5(), [prov()], [pod("new0")], existing=existing)
    assert sum(res.existing_counts.values()) == 0  # refused the resident node
    assert sum(n.pod_count for n in res.nodes) == 1


def test_parity_preference_relaxation_prefix():
    # ordered preference terms: [arm64 (top weight), spot] — catalog offers
    # no arm spot, so arm64 survives and the spot term is dropped
    p = make_pod("p0", cpu="1", memory="1Gi", preferences=(
        Requirements.of((wk.LABEL_ARCH, OP_IN, ["arm64"])),
        Requirements.of((wk.LABEL_CAPACITY_TYPE, OP_IN, ["spot"])),
    ))
    pr = prov(requirements=Requirements.of(
        (wk.LABEL_CAPACITY_TYPE, OP_IN, ["spot", "on-demand"]),
        (wk.LABEL_ARCH, OP_IN, ["amd64", "arm64"]),
    ))
    res = assert_parity(catalog5(), [pr], [p])  # arm.4x has no spot offering
    (node,) = res.nodes
    assert node.option.itype.name == "arm.4x"
    assert node.option.capacity_type == "on-demand"


def test_parity_zone_split_keeps_resident_hostname_caps():
    # the HA shape the origin-key plumbing exists for: zone spread AND
    # hostname anti-affinity together. Residents carry the PRE-split spec;
    # the zone-split subgroup must still count them on existing nodes.
    spread = (TopologySpreadConstraint(max_skew=1, topology_key=wk.LABEL_ZONE),)

    def pod(name):
        return make_pod(name, cpu="1", memory="1Gi", topology=spread,
                        anti_affinity_hostname=True)

    # one resident replica per zone, each on a roomy node
    existing = [
        _existing_in_zone("node-a", "zone-1a", [pod("oa")]),
        _existing_in_zone("node-b", "zone-1b", [pod("ob")]),
        _existing_in_zone("node-c", "zone-1c", [pod("oc")]),
    ]
    new = [pod(f"n{i}") for i in range(3)]
    res = assert_parity(catalog5(), [prov()], new, existing=existing)
    # every new replica must open a FRESH node: all existing nodes already
    # host one replica of the group (hostname anti-affinity cap = 1)
    assert sum(res.existing_counts.values()) == 0
    assert sum(n.pod_count for n in res.nodes) == 3
    assert all(n.pod_count == 1 for n in res.nodes)


def test_parity_pod_affinity_zone_follows_existing():
    from karpenter_tpu.models.pod import PodAffinityTerm

    backend = make_pod("db0", cpu="1", memory="1Gi",
                       labels=(("app", "db"),))
    existing = [_existing_in_zone("node-b", "zone-1b", [backend])]
    follower = make_pod("web0", cpu="1", memory="1Gi", pod_affinity=(
        PodAffinityTerm(match_labels=(("app", "db"),),
                        topology_key=wk.LABEL_ZONE),))
    res = assert_parity(catalog5(), [prov()], [follower], existing=existing)
    placed_existing = sum(res.existing_counts.values())
    zones = [n.option.zone for n in res.nodes]
    # lands in zone-1b: either on node-b itself or a fresh zone-1b node
    assert placed_existing == 1 or zones == ["zone-1b"]
    assert res.unschedulable_count() == 0


def test_parity_pod_affinity_unsatisfiable_is_unschedulable():
    from karpenter_tpu.models.pod import PodAffinityTerm

    lonely = make_pod("web0", cpu="1", memory="1Gi", pod_affinity=(
        PodAffinityTerm(match_labels=(("app", "nonexistent"),),
                        topology_key=wk.LABEL_ZONE),))
    res = assert_parity(catalog5(), [prov()], [lonely])
    assert res.unschedulable_count() == 1


def test_parity_pod_affinity_hostname_pins_to_node():
    from karpenter_tpu.models.pod import PodAffinityTerm

    backend = make_pod("db0", cpu="1", memory="1Gi", labels=(("app", "db"),))
    existing = [
        _existing_in_zone("node-a", "zone-1a"),
        _existing_in_zone("node-b", "zone-1b", [backend]),
    ]
    follower = make_pod("web0", cpu="1", memory="1Gi", pod_affinity=(
        PodAffinityTerm(match_labels=(("app", "db"),),
                        topology_key=wk.LABEL_HOSTNAME),))
    res = assert_parity(catalog5(), [prov()], [follower], existing=existing)
    assert res.existing_counts == {"node-b": 1}
    assert not res.nodes


def test_parity_pod_anti_affinity_zone_avoids_matching_domain():
    from karpenter_tpu.models.pod import PodAffinityTerm

    noisy = make_pod("noisy0", cpu="1", memory="1Gi", labels=(("app", "noisy"),))
    existing = [_existing_in_zone("node-a", "zone-1a", [noisy])]
    quiet = make_pod("quiet0", cpu="1", memory="1Gi", pod_anti_affinity=(
        PodAffinityTerm(match_labels=(("app", "noisy"),),
                        topology_key=wk.LABEL_ZONE),))
    res = assert_parity(catalog5(), [prov()], [quiet], existing=existing)
    assert sum(res.existing_counts.values()) == 0
    (node,) = res.nodes
    assert node.option.zone != "zone-1a"


def test_parity_pod_anti_affinity_hostname_avoids_node_not_zone():
    from karpenter_tpu.models.pod import PodAffinityTerm

    noisy = make_pod("noisy0", cpu="1", memory="1Gi", labels=(("app", "noisy"),))
    existing = [_existing_in_zone("node-a", "zone-1a", [noisy])]
    quiet = make_pod("quiet0", cpu="1", memory="1Gi", pod_anti_affinity=(
        PodAffinityTerm(match_labels=(("app", "noisy"),),
                        topology_key=wk.LABEL_HOSTNAME),))
    res = assert_parity(catalog5(), [prov()], [quiet], existing=existing)
    # refused node-a, but a fresh node (any zone, incl. 1a) is fine
    assert sum(res.existing_counts.values()) == 0
    assert sum(n.pod_count for n in res.nodes) == 1


def test_parity_soft_zone_split_shares_per_node_cap():
    # ADVICE r2 (medium): ScheduleAnyway zone-split subgroups have identical
    # hard requirements but distinct group keys; with hostname anti-affinity
    # (cap=1) each soft subgroup must NOT get its own per-node budget — the
    # cap budget is shared via the origin key on existing nodes and claims.
    spread = (TopologySpreadConstraint(max_skew=1, topology_key=wk.LABEL_ZONE,
                                       when_unsatisfiable="ScheduleAnyway"),)

    def pod(name):
        return make_pod(name, cpu="100m", memory="128Mi", topology=spread,
                        anti_affinity_hostname=True)

    # one roomy existing node: both soft subgroups could land here by
    # capacity, but required anti-affinity allows at most ONE pod total
    existing = [_existing_in_zone("node-a", "zone-1a")]
    pods = [pod(f"p{i}") for i in range(6)]
    res = assert_parity(catalog5(), [prov()], pods, existing=existing)
    assert sum(res.existing_counts.values()) <= 1
    # every node claim also carries at most one pod of the deployment
    assert all(n.pod_count == 1 for n in res.nodes)
    assert sum(n.pod_count for n in res.nodes) + sum(
        res.existing_counts.values()) == 6

    # native backend enforces the same shared budget
    from karpenter_tpu.solver.core import NativeSolver
    nres = NativeSolver(catalog5(), [prov()]).solve(
        pods, existing=[_existing_in_zone("node-a", "zone-1a")])
    assert sum(nres.existing_counts.values()) <= 1
    assert all(n.pod_count == 1 for n in nres.nodes)


def test_parity_copending_hostname_affinity_colocates():
    # VERDICT r2 ask #6: pod B requires hostname affinity to CO-PENDING pod
    # group A -> two-round solve places B on A's claims (hard co-location)
    from karpenter_tpu.models.pod import PodAffinityTerm

    targets = [make_pod(f"db-{i}", cpu="1", memory="2Gi",
                        labels=(("app", "db"),)) for i in range(3)]
    dependents = [make_pod(f"sidecar-{i}", cpu="250m", memory="256Mi",
                           labels=(("app", "sidecar"),),
                           pod_affinity=(PodAffinityTerm(
                               match_labels=(("app", "db"),),
                               topology_key=wk.LABEL_HOSTNAME),))
                  for i in range(3)]
    res = assert_parity(catalog5(), [prov()], targets + dependents)
    assert res.unschedulable_count() == 0
    # every node carrying a sidecar also carries a db pod
    for n in res.nodes:
        kinds = {res.groups[g].spec.labels for g in n.pod_counts}
        if (("app", "sidecar"),) in kinds:
            assert (("app", "db"),) in kinds, n.pod_counts


def test_parity_copending_hostname_anti_affinity_separates():
    from karpenter_tpu.models.pod import PodAffinityTerm

    noisy = [make_pod(f"noisy-{i}", cpu="100m", memory="128Mi",
                      labels=(("app", "noisy"),)) for i in range(2)]
    quiet = [make_pod(f"quiet-{i}", cpu="100m", memory="128Mi",
                      labels=(("app", "quiet"),),
                      pod_anti_affinity=(PodAffinityTerm(
                          match_labels=(("app", "noisy"),),
                          topology_key=wk.LABEL_HOSTNAME),))
             for i in range(2)]
    res = assert_parity(catalog5(), [prov()], noisy + quiet)
    assert res.unschedulable_count() == 0
    for n in res.nodes:
        kinds = {res.groups[g].spec.labels for g in n.pod_counts}
        assert not ((("app", "noisy"),) in kinds
                    and (("app", "quiet"),) in kinds), n.pod_counts


def test_parity_copending_zone_anti_affinity_separates_zones():
    from karpenter_tpu.models.pod import PodAffinityTerm

    a = [make_pod(f"a-{i}", cpu="1", memory="1Gi", labels=(("app", "a"),))
         for i in range(2)]
    b = [make_pod(f"b-{i}", cpu="1", memory="1Gi", labels=(("app", "b"),),
                  pod_anti_affinity=(PodAffinityTerm(
                      match_labels=(("app", "a"),),
                      topology_key=wk.LABEL_ZONE),))
         for i in range(2)]
    res = assert_parity(catalog5(), [prov()], a + b)
    assert res.unschedulable_count() == 0
    zones_a = {n.option.zone for n in res.nodes
               if any(res.groups[g].spec.labels == (("app", "a"),)
                      for g in n.pod_counts)}
    zones_b = {n.option.zone for n in res.nodes
               if any(res.groups[g].spec.labels == (("app", "b"),)
                      for g in n.pod_counts)}
    assert zones_a and zones_b and not (zones_a & zones_b)


def test_parity_copending_zone_affinity_coalesces_zone():
    from karpenter_tpu.models.pod import PodAffinityTerm

    a = [make_pod(f"w-{i}", cpu="1", memory="1Gi", labels=(("app", "w"),),
                  node_selector={wk.LABEL_ZONE: "zone-1b"})
         for i in range(2)]
    b = [make_pod(f"f-{i}", cpu="1", memory="1Gi", labels=(("app", "f"),),
                  pod_affinity=(PodAffinityTerm(
                      match_labels=(("app", "w"),),
                      topology_key=wk.LABEL_ZONE),))
         for i in range(2)]
    res = assert_parity(catalog5(), [prov()], a + b)
    assert res.unschedulable_count() == 0
    assert {n.option.zone for n in res.nodes} == {"zone-1b"}


def test_parity_copending_anti_affinity_forward_reference():
    # review r3: deferral must be input-order independent — the group WITH
    # the terms arrives BEFORE its target in the pod list and must still
    # defer (forward reference)
    from karpenter_tpu.models.pod import PodAffinityTerm

    quiet = [make_pod(f"quiet-{i}", cpu="100m", memory="128Mi",
                      labels=(("app", "quiet"),),
                      pod_anti_affinity=(PodAffinityTerm(
                          match_labels=(("app", "noisy"),),
                          topology_key=wk.LABEL_HOSTNAME),))
             for i in range(2)]
    noisy = [make_pod(f"noisy-{i}", cpu="100m", memory="128Mi",
                      labels=(("app", "noisy"),)) for i in range(2)]
    # terms-first ordering (the previously-broken direction)
    res = assert_parity(catalog5(), [prov()], quiet + noisy)
    assert res.unschedulable_count() == 0
    for n in res.nodes:
        kinds = {res.groups[g].spec.labels for g in n.pod_counts}
        assert not ((("app", "noisy"),) in kinds
                    and (("app", "quiet"),) in kinds), n.pod_counts


def test_parity_round2_sees_round1_existing_consumption():
    # fuzz-found (round 3): the two-round solve's second round re-encodes
    # existing nodes, so round-1 placements on REAL existing nodes must be
    # carried (used + origin-keyed counts) or round 2 overcommits them.
    # Here round 1 fills the only affinity-anchored node to the brim; the
    # deferred pod (hostname affinity to app=a) no longer fits and must be
    # unschedulable on BOTH paths - not placed into phantom capacity.
    from karpenter_tpu.models.pod import PodAffinityTerm

    filler = [make_pod(f"fill-{i}", cpu="1500m", memory="1Gi",
                       labels=(("app", "a"),)) for i in range(4)]
    dependent = make_pod("dep", cpu="2", memory="1Gi", labels=(("app", "b"),),
                         pod_affinity=(PodAffinityTerm(
                             match_labels=(("app", "a"),),
                             topology_key=wk.LABEL_HOSTNAME),))
    anchor = make_pod("res-a", cpu="500m", memory="512Mi",
                      labels=(("app", "a"),))
    # 8-cpu node: resident 0.5 + fillers 6.0 = 6.5 used; dep needs 2 > 1.5
    existing = [ExistingNode(
        name="node-a",
        labels={wk.LABEL_ARCH: "amd64", wk.LABEL_OS: "linux",
                wk.LABEL_ZONE: "zone-1a", wk.LABEL_CAPACITY_TYPE: "on-demand"},
        allocatable=wk.capacity_vector({wk.RESOURCE_CPU: 8000,
                                        wk.RESOURCE_MEMORY: 32 * 2**30,
                                        wk.RESOURCE_PODS: 110}),
        used=wk.resource_vector({wk.RESOURCE_CPU: 500,
                                 wk.RESOURCE_MEMORY: 512 * 2**20,
                                 wk.RESOURCE_PODS: 1}),
        resident=(anchor,),
    )]
    # the catalog's only zone-1a-capable small types can't host dep either
    # way; the point is parity on the existing-node accounting
    res = assert_parity(catalog5(), [prov()], filler + [dependent],
                        existing=existing)
    assert res.existing_counts.get("node-a", 0) == 4  # fillers only
    assert res.unschedulable_count() == 1  # dep: anchor node is full


def test_floor_div_fast_exact():
    """The f32-reciprocal floor-div (ops/packer._floor_div) must be
    bit-exact vs // over the encode domain (0 <= a <= INT_BIG, v >= 1):
    adversarial sweep of divisor regimes (v=1 maximizes the estimate's
    absolute error; v > 2^24 exercises the single-stage lane) plus exact
    multiples and off-by-one boundaries, where the +-1 fix must not
    over/under-shoot."""
    import jax.numpy as jnp
    import numpy as np

    from karpenter_tpu.ops.packer import INT_BIG, _floor_div

    rng = np.random.default_rng(1234)
    a = rng.integers(0, INT_BIG + 1, size=200_000, dtype=np.int64)
    v = np.concatenate([
        np.ones(20_000, dtype=np.int64),
        rng.integers(1, 8, size=40_000),
        rng.integers(8, 1 << 20, size=60_000),
        rng.integers(1 << 20, 1 << 24, size=40_000),
        rng.integers((1 << 24) + 1, 2**31 - 1, size=40_000),
    ])
    rng.shuffle(v)
    # boundary cases: a = q*v - 1, q*v, q*v + 1 for assorted (q, v)
    qs = np.array([0, 1, 2, 3, 127, 128, 129, 4095, 1 << 15, (1 << 26) - 1])
    vs = np.array([1, 2, 3, 7, 997, (1 << 20) - 1, (1 << 24) + 1, 2**31 - 1])
    for qq in qs:
        for vv in vs:
            prod = qq * vv
            for aa in (prod - 1, prod, prod + 1):
                if 0 <= aa <= INT_BIG:
                    a = np.append(a, aa)
                    v = np.append(v, vv)
    expect = a // v
    got = np.asarray(_floor_div(jnp.asarray(a, jnp.int32),
                                jnp.asarray(v, jnp.int32)))
    bad = np.nonzero(got != expect)[0]
    assert bad.size == 0, (
        f"{bad.size} mismatches, first: a={a[bad[0]]} v={v[bad[0]]} "
        f"got={got[bad[0]]} want={expect[bad[0]]}")


def test_resource_compression_bit_parity():
    """build_pack_inputs ships compressed resource columns (res_sel); the
    kernel must produce a bit-identical flat buffer to the same problem
    dispatched full-width with res_sel stripped."""
    import numpy as np

    from karpenter_tpu.models.encode import encode_problem
    from karpenter_tpu.ops.packer import pack_flat
    from karpenter_tpu.solver.core import build_pack_inputs

    catalog = catalog5()
    pods = [make_pod(f"p-{i}", cpu=100 * (1 + i % 7), memory=2**20 * (i % 5 + 1))
            for i in range(40)]
    enc = encode_problem(catalog, [prov()], pods)
    inputs, dims, use_pallas = build_pack_inputs(enc)
    assert inputs.res_sel is not None, "compression should engage (<=4 active)"
    assert int(inputs.res_sel[0]) == wk.RESOURCE_INDEX[wk.RESOURCE_PODS]

    compressed = np.asarray(
        pack_flat(inputs, dims[1], use_pallas=use_pallas))

    # full-width control: re-pad every compressed leaf back out by hand
    sel = np.asarray(inputs.res_sel)
    n_act = int(np.asarray(inputs.res_mask).sum())
    R = enc.alloc_t.shape[1]

    def widen(a):
        if a is None:
            return None
        out = np.zeros(a.shape[:-1] + (R,), a.dtype)
        out[..., sel[:n_act]] = a[..., :n_act]
        return out

    full = inputs._replace(
        group_vec=widen(np.asarray(inputs.group_vec)),
        overhead=widen(np.asarray(inputs.overhead)),
        ex_alloc=widen(np.asarray(inputs.ex_alloc)),
        ex_used=widen(np.asarray(inputs.ex_used)),
        prov_overhead=(None if inputs.prov_overhead is None
                       else widen(np.asarray(inputs.prov_overhead))),
        res_sel=None, res_mask=None)
    control = np.asarray(pack_flat(full, dims[1], use_pallas=use_pallas))
    assert np.array_equal(compressed, control)
