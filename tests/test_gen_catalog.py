"""Codegen guard for the real-data fleet catalog (hack/gen_catalog.py).

The checked-in data/fleet_catalog.json must be exactly what the generator
produces from the reference data artifacts — a hand-edit (or a generator
change without `make catalog`) breaks the provenance claim. Skipped
cleanly when the reference tree isn't present.
"""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DATA = os.path.join(REPO, "karpenter_tpu", "providers", "data",
                    "fleet_catalog.json")


@pytest.mark.skipif(not os.path.isdir("/root/reference/pkg"),
                    reason="reference data artifacts not present")
def test_checked_in_catalog_matches_generator(tmp_path):
    """Regenerating into a scratch path yields byte-identical JSON."""
    env = dict(os.environ)
    out = tmp_path / "fleet_catalog.json"
    code = (
        "import sys, runpy\n"
        f"sys.argv = ['gen_catalog.py']\n"
        f"import hack.gen_catalog as g\n"
        f"g.OUT = {str(out)!r}\n"
        "g.main()\n")
    r = subprocess.run([sys.executable, "-c", code], cwd=REPO, env=env,
                       capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stderr[-500:]
    assert "anchors validated: 10/10" in r.stdout
    with open(DATA) as f, open(out) as g:
        assert f.read() == g.read(), (
            "checked-in fleet_catalog.json differs from generator output — "
            "run `make catalog`")


def test_catalog_data_invariants():
    """Facts every consumer relies on, independent of the reference tree."""
    with open(DATA) as f:
        doc = json.load(f)
    types = doc["types"]
    assert len(types) >= 600
    names = [t["name"] for t in types]
    assert names == sorted(names) and len(set(names)) == len(names)
    for t in types:
        assert t["vcpu"] >= 1 and t["memory_mib"] >= 512, t["name"]
        assert 0 < t["od_price_usd"] < 200, t["name"]
        # the reference pod formula never exceeds the biggest published
        # eni-max-pods value
        assert 4 <= t["pods"] <= 737, t["name"]
        assert t["arch"] in ("amd64", "arm64")
        if t["pod_eni_branches"]:
            assert t["trunking"], t["name"]
    # provenance must be stamped
    assert doc["provenance"]["pricing"]["generated_at"]
    assert doc["provenance"]["eni_limits"]["generated_at"]
