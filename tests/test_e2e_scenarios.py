"""E2E-analogue scenario suites over the fake cloud + real controller plane.

Mirrors the reference's test/suites/ tier (SURVEY.md §4 tier 4) hermetically:
- chaos: runaway scale-up guards while consolidation/emptiness churn
  (/root/reference/test/suites/chaos/suite_test.go:65-112)
- integration/extended-resources: GPU pods w/ taints+tolerations
  (test/suites/integration/extended_resources_test.go)
- integration/scheduling: zone restriction, topology spread, anti-affinity
- integration/tags: tag propagation to instances + launch templates
- integration/block-device-mappings + metadata options
- the threaded operator plane end-to-end (async batching windows)
"""

import dataclasses
import json
import time

import pytest

from karpenter_tpu.apis import wellknown as wk
from karpenter_tpu.apis.nodetemplate import (BlockDeviceMapping, MetadataOptions,
                                             NodeTemplate)
from karpenter_tpu.apis.provisioner import Provisioner
from karpenter_tpu.apis.settings import Settings
from karpenter_tpu.fake.cloud import FakeCloud
from karpenter_tpu.models.instancetype import Catalog, make_instance_type
from karpenter_tpu.models.pod import (PodSpec, Taint, Toleration,
                                      TopologySpreadConstraint, make_pod)
from karpenter_tpu.models.requirements import OP_IN, Requirements
from karpenter_tpu.operator import Operator
from karpenter_tpu.utils.clock import FakeClock


def catalog():
    return Catalog(types=[
        make_instance_type("t.small", cpu=2, memory="2Gi", od_price=0.05, spot_price=0.02),
        make_instance_type("m.large", cpu=4, memory="16Gi", od_price=0.20, spot_price=0.07),
        make_instance_type("m.xlarge", cpu=16, memory="64Gi", od_price=0.80, spot_price=0.28),
        make_instance_type("gpu.large", cpu=8, memory="32Gi", od_price=2.50,
                           spot_price=0.90, extended={wk.RESOURCE_NVIDIA_GPU: 4},
                           extra_labels={wk.LABEL_INSTANCE_GPU_NAME: "a100",
                                         wk.LABEL_INSTANCE_GPU_COUNT: "4"}),
    ])


def make_operator(clock=None, **settings_kw):
    clock = clock or FakeClock()
    cloud = FakeCloud(catalog=catalog(), clock=clock)
    settings = Settings(cluster_name="e2e",
                        cluster_endpoint="https://k.example",
                        batch_idle_duration=0.0, batch_max_duration=0.0,
                        **settings_kw)
    op = Operator(cloud, settings, catalog(), clock=clock)
    op.kube.create("nodetemplates", "default", NodeTemplate(
        name="default",
        subnet_selector={"id": "subnet-zone-1a,subnet-zone-1b,subnet-zone-1c"},
        security_group_selector={"id": "sg-default"}))
    op.cloudprovider.register_nodetemplate(op.kube.get("nodetemplates", "default"))
    return op


def add_provisioner(op, name="default", **kw):
    p = Provisioner(name=name, provider_ref=kw.pop("provider_ref", "default"), **kw)
    p.set_defaults()
    p.validate()
    op.kube.create("provisioners", name, p)
    return p


@pytest.fixture
def op():
    operator = make_operator()
    yield operator
    operator.stop()


class TestChaos:
    """Runaway scale-up guards (chaos/suite_test.go:65-112): node count must
    stay bounded while deprovisioning churns against a steady workload."""

    def test_no_runaway_under_consolidation_churn(self, op):
        add_provisioner(op, consolidation_enabled=True)
        for i in range(20):
            op.kube.create("pods", f"p{i}", make_pod(f"p{i}", cpu="1", memory="2Gi"))
        op.provisioning.reconcile_once()
        peak = len(op.cluster.nodes)
        assert peak >= 1
        # churn: repeated consolidation + provisioning cycles with the same
        # workload must never create nodes beyond the initial peak + 1
        # (one in-flight replacement is legal during a replace action)
        for _ in range(10):
            op.deprovisioning.reconcile_once()
            op.termination.reconcile_once()
            op.provisioning.reconcile_once()
            op.clock.step(5)
            assert len(op.cluster.nodes) <= peak + 1, "runaway scale-up"
        # workload still fully scheduled at the end
        assert len(op.kube.pending_pods()) == 0

    def test_no_runaway_under_emptiness_churn(self, op):
        add_provisioner(op, ttl_seconds_after_empty=10)
        for i in range(10):
            op.kube.create("pods", f"p{i}", make_pod(f"p{i}", cpu="1", memory="2Gi"))
        op.provisioning.reconcile_once()
        peak = len(op.cluster.nodes)
        for cycle in range(6):
            # delete and recreate the workload: nodes empty, TTL elapses,
            # nodes are reclaimed, new pods must reuse/replace without runaway
            for pod in list(op.kube.pods()):
                op.kube.delete("pods", pod.name)
            for node in op.cluster.nodes.values():
                node.pods.clear()
            op.clock.step(11)
            op.deprovisioning.reconcile_emptiness()
            op.termination.reconcile_once()
            for i in range(10):
                op.kube.create("pods", f"c{cycle}-p{i}",
                               make_pod(f"c{cycle}-p{i}", cpu="1", memory="2Gi"))
            op.provisioning.reconcile_once()
            assert len(op.cluster.nodes) <= peak + 1, "runaway scale-up"


class TestExtendedResources:
    """GPU pods with taints/tolerations + extended-resource requests
    (BASELINE configs[2]; integration/extended_resources_test.go analogue)."""

    def gpu_provisioner(self, op):
        return add_provisioner(
            op, name="gpu",
            taints=(Taint(key="nvidia.com/gpu", value="true", effect="NoSchedule"),),
            requirements=Requirements.of(
                (wk.LABEL_INSTANCE_TYPE, OP_IN, ["gpu.large"])))

    def test_gpu_pods_land_on_gpu_nodes(self, op):
        self.gpu_provisioner(op)
        # cpu provisioner excludes the accelerator family, as in the reference
        # E2E setup (a dedicated tainted provisioner owns GPU capacity)
        add_provisioner(op, name="default", requirements=Requirements.of(
            (wk.LABEL_INSTANCE_TYPE, OP_IN, ["t.small", "m.large", "m.xlarge"])))
        for i in range(8):
            op.kube.create("pods", f"g{i}", make_pod(
                f"g{i}", cpu="1", memory="1Gi",
                extended={wk.RESOURCE_NVIDIA_GPU: 1},
                tolerations=(Toleration(key="nvidia.com/gpu", operator="Exists"),)))
        for i in range(4):
            op.kube.create("pods", f"c{i}", make_pod(f"c{i}", cpu="1", memory="1Gi"))
        op.provisioning.reconcile_once()
        assert len(op.kube.pending_pods()) == 0
        gpu_nodes = [n for n in op.cluster.nodes.values()
                     if n.instance_type == "gpu.large"]
        other = [n for n in op.cluster.nodes.values()
                 if n.instance_type != "gpu.large"]
        # 8 pods x 1 gpu on 4-gpu machines => exactly 2 gpu nodes
        assert len(gpu_nodes) == 2
        assert {p.name for n in gpu_nodes for p in n.pods} == {f"g{i}" for i in range(8)}
        # untolerated cpu pods never land on tainted gpu nodes
        assert all(not p.name.startswith("c") for n in gpu_nodes for p in n.pods)
        assert other and all(p.name.startswith("c") for n in other for p in n.pods)

    def test_gpu_node_carries_accelerator_labels(self, op):
        self.gpu_provisioner(op)
        op.kube.create("pods", "g0", make_pod(
            "g0", cpu="1", memory="1Gi", extended={wk.RESOURCE_NVIDIA_GPU: 1},
            tolerations=(Toleration(key="nvidia.com/gpu", operator="Exists"),)))
        op.provisioning.reconcile_once()
        (node,) = op.cluster.nodes.values()
        assert node.labels[wk.LABEL_INSTANCE_GPU_NAME] == "a100"
        assert node.allocatable[wk.RESOURCE_INDEX[wk.RESOURCE_NVIDIA_GPU]] == 4

    def test_unknown_extended_resource_unschedulable(self, op):
        add_provisioner(op)
        op.kube.create("pods", "x", make_pod(
            "x", cpu="1", memory="1Gi", extended={"vendor.example/fpga": 1}))
        op.provisioning.reconcile_once()
        assert not op.cluster.nodes
        assert op.recorder.by_reason("FailedScheduling")


class TestSchedulingConstraints:
    def test_zone_restriction(self, op):
        add_provisioner(op, requirements=Requirements.of(
            (wk.LABEL_ZONE, OP_IN, ["zone-1b"])))
        for i in range(5):
            op.kube.create("pods", f"p{i}", make_pod(f"p{i}", cpu="1.5", memory="1Gi"))
        op.provisioning.reconcile_once()
        assert op.cluster.nodes
        assert all(n.zone == "zone-1b" for n in op.cluster.nodes.values())

    def test_topology_spread_across_three_zones(self, op):
        add_provisioner(op, requirements=Requirements.of(
            (wk.LABEL_INSTANCE_TYPE, OP_IN, ["t.small"])))
        for i in range(9):
            op.kube.create("pods", f"p{i}", make_pod(
                f"p{i}", cpu="1.5", memory="1Gi",
                topology=(TopologySpreadConstraint(
                    max_skew=1, topology_key=wk.LABEL_ZONE),)))
        op.provisioning.reconcile_once()
        assert len(op.kube.pending_pods()) == 0
        per_zone = {}
        for n in op.cluster.nodes.values():
            per_zone[n.zone] = per_zone.get(n.zone, 0) + len(n.pods)
        assert len(per_zone) == 3
        assert max(per_zone.values()) - min(per_zone.values()) <= 1

    def test_hostname_anti_affinity_one_pod_per_node(self, op):
        add_provisioner(op)
        for i in range(6):
            op.kube.create("pods", f"p{i}", make_pod(
                f"p{i}", cpu="100m", memory="128Mi", anti_affinity_hostname=True))
        op.provisioning.reconcile_once()
        assert len(op.cluster.nodes) == 6
        assert all(len(n.pods) == 1 for n in op.cluster.nodes.values())

    def test_spot_preferred_when_allowed(self, op):
        # spot+OD allowed => cheapest (spot) offering chosen
        # (getCapacityType, instance.go:430-443)
        add_provisioner(op, requirements=Requirements.of(
            (wk.LABEL_CAPACITY_TYPE, OP_IN, ["spot", "on-demand"])))
        op.kube.create("pods", "a", make_pod("a", cpu="1", memory="1Gi"))
        op.provisioning.reconcile_once()
        (node,) = op.cluster.nodes.values()
        assert node.capacity_type == "spot"


class TestTagsAndLaunchTemplateOptions:
    def test_tags_propagate_to_instances(self, op):
        t = op.kube.get("nodetemplates", "default")
        t.tags = {"team": "ml", "env": "prod"}
        add_provisioner(op)
        op.kube.create("pods", "a", make_pod("a", cpu="1", memory="1Gi"))
        op.provisioning.reconcile_once()
        (inst,) = [i for i in op.cloudprovider.cloud.instances.values()]
        assert inst.tags["team"] == "ml" and inst.tags["env"] == "prod"
        # cluster ownership tags always present (launchInstance tag spec,
        # instance.go:223-239)
        assert any("cluster" in k for k in inst.tags)

    def test_block_devices_and_metadata_options_propagate(self, op):
        t = op.kube.get("nodetemplates", "default")
        t.metadata_options = MetadataOptions(http_tokens="optional",
                                             http_put_response_hop_limit=3)
        t.block_device_mappings = (
            BlockDeviceMapping(device_name="/dev/sda1", volume_size_gib=100,
                               volume_type="balanced"),)
        t.detailed_monitoring = True
        t.validate()
        add_provisioner(op)
        op.kube.create("pods", "a", make_pod("a", cpu="1", memory="1Gi"))
        op.provisioning.reconcile_once()
        (inst,) = op.cloudprovider.cloud.instances.values()
        lt = op.cloudprovider.cloud.launch_templates[inst.launch_template]
        assert lt.metadata_options["http_tokens"] == "optional"
        assert lt.metadata_options["http_put_response_hop_limit"] == 3
        assert lt.block_devices[0]["volume_size_gib"] == 100
        assert lt.block_devices[0]["volume_type"] == "balanced"
        assert lt.monitoring is True

    def test_distinct_options_yield_distinct_launch_templates(self, op):
        add_provisioner(op)
        op.kube.create("pods", "a", make_pod("a", cpu="1", memory="1Gi"))
        op.provisioning.reconcile_once()
        n_before = len(op.cloudprovider.cloud.launch_templates)
        t = op.kube.get("nodetemplates", "default")
        t.detailed_monitoring = True
        t.generation += 1
        # pod too large for the remaining capacity of the existing node
        op.kube.create("pods", "b", make_pod("b", cpu="15.5", memory="1Gi"))
        op.provisioning.reconcile_once()
        assert len(op.cloudprovider.cloud.launch_templates) == n_before + 1


class TestKubeletConfiguration:
    """Provisioner kubelet config shapes both the scheduling decision and the
    launched node's reported allocatable (integration/kubelet-config E2E
    analogue)."""

    def test_max_pods_bounds_packing_and_allocatable(self, op):
        from karpenter_tpu.apis.provisioner import KubeletConfiguration

        add_provisioner(op, kubelet=KubeletConfiguration(max_pods=2))
        for i in range(5):
            op.kube.create("pods", f"p{i}", make_pod(f"p{i}", cpu="100m",
                                                     memory="128Mi"))
        op.provisioning.reconcile_once()
        assert not op.kube.pending_pods()
        assert len(op.cluster.nodes) >= 3  # 5 pods at <=2/node
        pods_i = wk.RESOURCE_INDEX[wk.RESOURCE_PODS]
        for node in op.cluster.nodes.values():
            assert len(node.pods) <= 2
            assert node.allocatable[pods_i] == 2

    def test_reserved_memory_reduces_allocatable(self, op):
        from karpenter_tpu.apis.provisioner import KubeletConfiguration

        add_provisioner(op, kubelet=KubeletConfiguration(
            system_reserved_memory_bytes=4 * 2**30))
        op.kube.create("pods", "a", make_pod("a", cpu="1", memory="1Gi"))
        op.provisioning.reconcile_once()
        (node,) = op.cluster.nodes.values()
        mem_i = wk.RESOURCE_INDEX[wk.RESOURCE_MEMORY]
        base = dict(op.cloudprovider.catalog_for().by_name[
            node.instance_type].capacity)[wk.RESOURCE_MEMORY] // 2**20
        assert node.allocatable[mem_i] <= base - 4096


class TestNodeTemplateLifecycle:
    def test_deleted_template_stops_resolving(self, op):
        add_provisioner(op)
        op.kube.create("pods", "a", make_pod("a", cpu="1", memory="1Gi"))
        op.provisioning.reconcile_once()
        assert len(op.cluster.nodes) == 1
        # template deleted from the store -> machine creation must fail with
        # NodeTemplateNotFound, not keep launching from a stale registry
        op.kube.delete("nodetemplates", "default")
        op.kube.create("pods", "b", make_pod("b", cpu="15.5", memory="1Gi"))
        op.provisioning.reconcile_once()
        assert len(op.cluster.nodes) == 1  # no new capacity
        assert op.recorder.by_reason("LaunchFailed")

    def test_templates_differing_only_in_tags_get_distinct_lts(self, op):
        op.kube.create("nodetemplates", "tagged", NodeTemplate(
            name="tagged",
            subnet_selector={"id": "subnet-zone-1a"},
            security_group_selector={"id": "sg-default"},
            tags={"team": "web"}))
        add_provisioner(op, name="default")
        add_provisioner(op, name="tagged-prov", provider_ref="tagged")
        op.kube.create("pods", "a", make_pod(
            "a", cpu="1", memory="1Gi",
            node_selector={wk.LABEL_PROVISIONER: "default"}))
        op.kube.create("pods", "b", make_pod(
            "b", cpu="1", memory="1Gi",
            node_selector={wk.LABEL_PROVISIONER: "tagged-prov"}))
        op.provisioning.reconcile_once()
        lts = op.cloudprovider.cloud.launch_templates
        assert len(lts) == 2
        assert {lt.tags.get("team") for lt in lts.values()} == {None, "web"}


class TestThreadedOperator:
    """The async controller plane end-to-end with real threads + real clock
    (the reference's operator Start() path, cmd/controller/main.go:64)."""

    def test_pods_flow_to_nodes_through_background_loops(self):
        from karpenter_tpu.utils.clock import Clock

        clock = Clock()
        cloud = FakeCloud(catalog=catalog(), clock=clock)
        settings = Settings(cluster_name="e2e-threaded",
                            cluster_endpoint="https://k.example",
                            batch_idle_duration=0.02, batch_max_duration=0.1)
        op = Operator(cloud, settings, catalog(), clock=clock)
        op.kube.create("nodetemplates", "default", NodeTemplate(
            name="default",
            subnet_selector={"id": "subnet-zone-1a,subnet-zone-1b,subnet-zone-1c"},
            security_group_selector={"id": "sg-default"}))
        op.cloudprovider.register_nodetemplate(
            op.kube.get("nodetemplates", "default"))
        add_provisioner(op)
        try:
            op.start()
            for i in range(10):
                op.kube.create("pods", f"p{i}", make_pod(f"p{i}", cpu="1", memory="2Gi"))
            deadline = time.monotonic() + 15
            while time.monotonic() < deadline:
                if not op.kube.pending_pods() and op.cluster.nodes:
                    break
                time.sleep(0.05)
            assert not op.kube.pending_pods()
            # batching may split under scheduler jitter; bound, don't pin
            assert 1 <= len(op.cluster.nodes) <= 2
            assert op.livez() and op.healthz()
            assert "karpenter" in op.metrics_text()
        finally:
            op.stop()


class TestStorageAndDensity:
    """Storage + pod-density E2E analogues (reference
    test/suites/integration/storage_test.go and the enableENILimitedPodDensity
    flag, settings.md; VERDICT r2 ask #10)."""

    def test_ephemeral_storage_capacity_respected(self, op):
        # make_instance_type fixtures carry 20Gi ephemeral: a 15Gi request
        # monopolizes a node, so two such pods need two nodes
        add_provisioner(op)
        for i in range(2):
            p = make_pod(f"disk-{i}", cpu="100m", memory="128Mi")
            p = dataclasses.replace(p, requests=tuple(sorted(
                dict(p.requests, **{wk.RESOURCE_EPHEMERAL: 15 * 2**30}).items())))
            op.kube.create("pods", p.name, p)
        op.provisioning.reconcile_once()
        assert len(op.kube.pending_pods()) == 0
        assert len(op.cluster.nodes) == 2

    def test_oversized_ephemeral_request_unschedulable(self, op):
        add_provisioner(op)
        p = make_pod("bigdisk", cpu="100m", memory="128Mi")
        p = dataclasses.replace(p, requests=tuple(sorted(
            dict(p.requests, **{wk.RESOURCE_EPHEMERAL: 50 * 2**30}).items())))
        op.kube.create("pods", p.name, p)
        op.provisioning.reconcile_once()
        assert len(op.kube.pending_pods()) == 1
        assert op.recorder.by_reason("FailedScheduling")

    def _density_operator(self, enable_density: bool):
        clock = FakeClock()
        cat = Catalog(types=[
            make_instance_type("net.limited", cpu=16, memory="64Gi",
                               pods=4, od_price=0.10),  # network-limited
        ])
        cloud = FakeCloud(catalog=cat, clock=clock)
        settings = Settings(
            cluster_name="density", cluster_endpoint="https://k",
            batch_idle_duration=0.0, batch_max_duration=0.0,
            enable_eni_limited_pod_density=enable_density)
        o = Operator(cloud, settings, cat, clock=clock)
        o.kube.create("nodetemplates", "default", NodeTemplate(
            name="default",
            subnet_selector={"id": "subnet-zone-1a"},
            security_group_selector={"id": "sg-default"}))
        o.cloudprovider.register_nodetemplate(
            o.kube.get("nodetemplates", "default"))
        return o

    def test_network_limited_density_caps_pods_per_node(self):
        # flag ON (default): the type's network-limited 4-pod density holds
        o = self._density_operator(enable_density=True)
        try:
            add_provisioner(o)
            for i in range(8):
                o.kube.create("pods", f"p{i}",
                              make_pod(f"p{i}", cpu="100m", memory="128Mi"))
            o.provisioning.reconcile_once()
            assert len(o.kube.pending_pods()) == 0
            assert len(o.cluster.nodes) == 2  # 4 pods per node
        finally:
            o.stop()

    def test_density_flag_disabled_uses_default_max_pods(self):
        # flag OFF: every type reports the 110 default instead (settings.md
        # enableENILimitedPodDensity=false)
        o = self._density_operator(enable_density=False)
        try:
            add_provisioner(o)
            for i in range(8):
                o.kube.create("pods", f"p{i}",
                              make_pod(f"p{i}", cpu="100m", memory="128Mi"))
            o.provisioning.reconcile_once()
            assert len(o.kube.pending_pods()) == 0
            assert len(o.cluster.nodes) == 1  # all 8 share one node
        finally:
            o.stop()


class TestDualStack:
    """ipv6/dual-stack analogues (reference test/suites/ipv6)."""

    def test_ip_family_label_restricts_types(self):
        clock = FakeClock()
        cat = Catalog(types=[
            make_instance_type("v4.large", cpu=4, memory="16Gi", od_price=0.1),
            make_instance_type("ds.large", cpu=4, memory="16Gi", od_price=0.3,
                               extra_labels={"karpenter.k8s.tpu/ip-family":
                                             "dual-stack"}),
        ])
        cloud = FakeCloud(catalog=cat, clock=clock)
        o = Operator(cloud, Settings(cluster_name="ds",
                                     cluster_endpoint="https://k",
                                     batch_idle_duration=0.0,
                                     batch_max_duration=0.0), cat, clock=clock)
        o.kube.create("nodetemplates", "default", NodeTemplate(
            name="default",
            subnet_selector={"id": "subnet-zone-1a"},
            security_group_selector={"id": "sg-default"}))
        o.cloudprovider.register_nodetemplate(
            o.kube.get("nodetemplates", "default"))
        try:
            add_provisioner(o)
            o.kube.create("pods", "v6pod", make_pod(
                "v6pod", cpu="1", memory="1Gi",
                node_selector={"karpenter.k8s.tpu/ip-family": "dual-stack"}))
            o.kube.create("pods", "anypod",
                          make_pod("anypod", cpu="1", memory="1Gi"))
            o.provisioning.reconcile_once()
            assert len(o.kube.pending_pods()) == 0
            types = sorted(n.instance_type for n in o.cluster.nodes.values())
            # the pinned pod forced the dual-stack type; the free pod packs
            # wherever cheapest (may share the dual-stack node)
            assert "ds.large" in types
            v6_nodes = [n for n in o.cluster.nodes.values()
                        if n.instance_type == "ds.large"]
            assert any(p.name == "v6pod" for n in v6_nodes for p in n.pods)
        finally:
            o.stop()

    def test_ipv6_metadata_protocol_propagates_to_launch_template(self, op):
        t = op.kube.get("nodetemplates", "default")
        t.metadata_options = MetadataOptions(http_protocol_ipv6="enabled")
        t.validate()
        add_provisioner(op)
        op.kube.create("pods", "a", make_pod("a", cpu="1", memory="1Gi"))
        op.provisioning.reconcile_once()
        (inst,) = op.cloudprovider.cloud.instances.values()
        lt = op.cloudprovider.cloud.launch_templates[inst.launch_template]
        assert lt.metadata_options["http_protocol_ipv6"] == "enabled"


class TestChaosRound3:
    """Two more runaway guards (chaos/suite_test.go:65-112; VERDICT r2
    ask #10): scale-up during drift churn, and an interruption storm landing
    mid-consolidation."""

    def test_no_runaway_scaleup_during_drift_churn(self, op):
        op.settings.feature_gates.drift_enabled = True
        add_provisioner(op)
        for i in range(12):
            op.kube.create("pods", f"p{i}",
                           make_pod(f"p{i}", cpu="1", memory="2Gi"))
        op.provisioning.reconcile_once()
        peak = len(op.cluster.nodes)
        assert peak >= 1
        # the image moves: every node is drifted at once
        op.cloudprovider.cloud.ssm_parameters[
            "/karpenter-tpu/images/default/amd64/latest"] = "img-new"
        op.cloudprovider.images.cache.flush()
        for _ in range(8):
            op.deprovisioning.reconcile_once()
            op.termination.reconcile_once()
            # ReplicaSet analogue: re-create evicted pods
            alive = {p.name for p in op.kube.pods()}
            for i in range(12):
                if f"p{i}" not in alive:
                    op.kube.create("pods", f"p{i}",
                                   make_pod(f"p{i}", cpu="1", memory="2Gi"))
            op.provisioning.reconcile_once()
            op.machinelifecycle.reconcile_once()
            op.clock.step(5)
            assert len(op.cluster.nodes) <= peak + 1, "runaway during drift"
        assert len(op.kube.pending_pods()) == 0

    def test_interruption_storm_during_consolidation(self):
        clock = FakeClock()
        cloud = FakeCloud(catalog=catalog(), clock=clock)
        settings = Settings(cluster_name="storm",
                            cluster_endpoint="https://k.example",
                            interruption_queue_name="iq",
                            batch_idle_duration=0.0, batch_max_duration=0.0)
        op = Operator(cloud, settings, catalog(), clock=clock)
        op.kube.create("nodetemplates", "default", NodeTemplate(
            name="default",
            subnet_selector={"id": "subnet-zone-1a,subnet-zone-1b,subnet-zone-1c"},
            security_group_selector={"id": "sg-default"}))
        op.cloudprovider.register_nodetemplate(
            op.kube.get("nodetemplates", "default"))
        try:
            add_provisioner(op, consolidation_enabled=True,
                            requirements=Requirements.of(
                                (wk.LABEL_CAPACITY_TYPE, OP_IN, ["spot"])))
            for i in range(12):
                op.kube.create("pods", f"p{i}",
                               make_pod(f"p{i}", cpu="1", memory="2Gi"))
            op.provisioning.reconcile_once()
            op.machinelifecycle.reconcile_once()
            op.machinelifecycle.reconcile_once()
            peak = len(op.cluster.nodes)
            assert peak >= 1
            from karpenter_tpu.models.machine import parse_provider_id

            for cycle in range(6):
                # storm: interrupt half the live spot nodes mid-churn
                names = sorted(op.cluster.nodes)
                for name in names[: max(1, len(names) // 2)]:
                    node = op.cluster.nodes[name]
                    if node.provider_id:
                        _, iid = parse_provider_id(node.provider_id)
                        op.queue.send(json.dumps({
                            "source": "cloud.spot",
                            "detail-type": "Spot Instance Interruption Warning",
                            "detail": {"instance-id": iid}}))
                op.interruption.reconcile_once()
                op.deprovisioning.reconcile_once()
                op.termination.reconcile_once()
                alive = {p.name for p in op.kube.pods()}
                for i in range(12):
                    if f"p{i}" not in alive:
                        op.kube.create("pods", f"p{i}",
                                       make_pod(f"p{i}", cpu="1", memory="2Gi"))
                op.provisioning.reconcile_once()
                op.machinelifecycle.reconcile_once()
                op.clock.step(60)
                assert len(op.cluster.nodes) <= peak + 2, \
                    "runaway during interruption storm"
            assert len(op.kube.pending_pods()) == 0
        finally:
            op.stop()


class TestBackwardsCompat:
    """Reference-manifest backwards compatibility (the analogue of
    test/suites/integration/backwards_compat): manifests written for
    upstream AWS Karpenter — AWSNodeTemplate kind, karpenter.k8s.aws/*
    label keys, ${CLUSTER_NAME} discovery tags — drive this controller
    unchanged through the full provision path."""

    AWS_BUNDLE = """
apiVersion: karpenter.sh/v1alpha5
kind: Provisioner
metadata:
  name: legacy
spec:
  requirements:
    - key: karpenter.sh/capacity-type
      operator: In
      values: [spot, on-demand]
    - key: karpenter.k8s.aws/instance-generation
      operator: Exists
  providerRef:
    name: legacy
---
apiVersion: karpenter.k8s.aws/v1alpha1
kind: AWSNodeTemplate
metadata:
  name: legacy
spec:
  amiFamily: AL2
  subnetSelector:
    karpenter.sh/discovery: "${CLUSTER_NAME}"
  securityGroupSelector:
    karpenter.sh/discovery: "${CLUSTER_NAME}"
---
apiVersion: apps/v1
kind: Deployment
metadata:
  name: legacy-inflate
spec:
  replicas: 5
  selector:
    matchLabels: {app: legacy}
  template:
    metadata:
      labels: {app: legacy}
    spec:
      containers:
        - name: c
          resources:
            requests: {cpu: "1", memory: 1Gi}
"""

    def test_aws_flavored_bundle_schedules(self):
        from karpenter_tpu.apis.yaml_compat import load_manifests

        clock = FakeClock()
        cat = Catalog(types=[
            make_instance_type(
                "m.large", cpu=4, memory="16Gi", od_price=0.2,
                spot_price=0.07,
                extra_labels={"karpenter.k8s.tpu/instance-generation": "5"}),
        ])
        cloud = FakeCloud(catalog=cat, clock=clock)
        for s in cloud.subnets:
            s.tags.setdefault("karpenter.sh/discovery", "legacy-cluster")
        for g in cloud.security_groups:
            g.tags.setdefault("karpenter.sh/discovery", "legacy-cluster")
        op = Operator(cloud, Settings(cluster_name="legacy-cluster",
                                      cluster_endpoint="https://k",
                                      batch_idle_duration=0.0,
                                      batch_max_duration=0.0), cat, clock=clock)
        try:
            loaded = load_manifests(self.AWS_BUNDLE,
                                    env={"CLUSTER_NAME": "legacy-cluster"})
            (tmpl,) = loaded.templates
            (prov,) = loaded.provisioners
            assert len(loaded.pods) == 5
            # the aws label key mapped onto this provider's namespace
            assert prov.requirements.get(
                "karpenter.k8s.tpu/instance-generation") is not None
            op.kube.create("nodetemplates", tmpl.name, tmpl)
            op.kube.create("provisioners", prov.name, prov)
            for pod in loaded.pods:
                op.kube.create("pods", pod.name, pod)
            op.provisioning.reconcile_once()
            assert len(op.kube.pending_pods()) == 0
            assert all(n.provisioner_name == "legacy"
                       for n in op.cluster.nodes.values())
        finally:
            op.stop()


class TestMonitorHarness:
    """The reference's Monitor/expectations vocabulary
    (common/monitor.go:36-145, expectations.go) over both operator modes."""

    def test_monitor_tracks_utilization_run(self, op):
        from harness import Monitor

        add_provisioner(op, requirements=Requirements.of(
            (wk.LABEL_INSTANCE_TYPE, OP_IN, ["t.small"])))
        mon = Monitor(op)
        for i in range(10):
            op.kube.create("pods", f"p{i}",
                           make_pod(f"p{i}", cpu="1.5", memory="128Mi"))
        op.provisioning.reconcile_once()
        mon.expect_created_node_count("==", 10)  # utilization parity shape
        mon.expect_healthy_pod_count(10)
        assert mon.pending_pod_count() == 0
        # consolidation-free teardown shows deletions too
        for node in list(op.cluster.nodes.values()):
            node.pods.clear()
            op.termination.request_deletion(node.name)
        op.termination.reconcile_once()
        assert mon.deleted_node_count() == 10

    def test_monitor_eventually_with_threaded_operator(self):
        from harness import Monitor
        from karpenter_tpu.utils.clock import Clock

        clock = Clock()
        cloud = FakeCloud(catalog=catalog(), clock=clock)
        settings = Settings(cluster_name="mon", cluster_endpoint="https://k",
                            batch_idle_duration=0.02, batch_max_duration=0.1)
        o = Operator(cloud, settings, catalog(), clock=clock)
        o.kube.create("nodetemplates", "default", NodeTemplate(
            name="default", subnet_selector={"id": "subnet-zone-1a"},
            security_group_selector={"id": "sg-default"}))
        o.cloudprovider.register_nodetemplate(
            o.kube.get("nodetemplates", "default"))
        add_provisioner(o)
        try:
            o.start()
            mon = Monitor(o)
            for i in range(6):
                o.kube.create("pods", f"w{i}",
                              make_pod(f"w{i}", cpu="1", memory="2Gi"))
            mon.eventually_expect_healthy_pod_count(6, timeout_s=20)
            mon.expect_created_node_count(">=", 1)
            mon.expect_created_node_count("<=", 2)
        finally:
            o.stop()
