"""E2E-analogue lifecycle suites: node TTLs, termination, and
template-driven launch selection over the fake cloud + real controller plane.

Mirrors the reference's remaining integration suites
(SURVEY.md §4 tier 4; /root/reference/test/suites/integration/):
- emptiness_test.go — ttlSecondsAfterEmpty reclaims empty nodes, not busy ones
- expiration_test.go — ttlSecondsUntilExpired rotates nodes; workload survives
- termination_test.go — node deletion drains pods and terminates the instance
- ami_test.go — image selector picks the newest match; SSM default otherwise
- security_group_test.go — SG selector resolves into the launch path
- subnet_test.go — subnet selector constrains launch zone/subnet
"""

from karpenter_tpu.apis import wellknown as wk
from karpenter_tpu.apis.nodetemplate import NodeTemplate
from karpenter_tpu.models.pod import make_pod

from tests.test_e2e_scenarios import add_provisioner, make_operator, op  # noqa: F401


class TestEmptiness:
    """integration/emptiness_test.go: an empty node is reclaimed only after
    ttlSecondsAfterEmpty elapses; a node that regains pods is spared."""

    def test_empty_node_reclaimed_after_ttl(self, op):
        add_provisioner(op, ttl_seconds_after_empty=30)
        op.kube.create("pods", "a", make_pod("a", cpu="1", memory="1Gi"))
        op.provisioning.reconcile_once()
        (name,) = op.cluster.nodes
        op.kube.delete("pods", "a")
        op.cluster.nodes[name].pods.clear()
        # before the TTL: node must survive
        op.deprovisioning.reconcile_emptiness()
        op.termination.reconcile_once()
        assert name in op.cluster.nodes
        # after the TTL: node drained and its instance terminated
        op.clock.step(31)
        op.deprovisioning.reconcile_emptiness()
        op.termination.reconcile_once()
        assert name not in op.cluster.nodes
        assert all(i.state == "terminated"
                   for i in op.cloudprovider.cloud.instances.values())
        assert op.recorder.by_reason("EmptinessTTLExpired")

    def test_repopulated_node_resets_ttl(self, op):
        add_provisioner(op, ttl_seconds_after_empty=30)
        op.kube.create("pods", "a", make_pod("a", cpu="1", memory="1Gi"))
        op.provisioning.reconcile_once()
        (name,) = op.cluster.nodes
        node = op.cluster.nodes[name]
        op.kube.delete("pods", "a")
        node.pods.clear()
        op.deprovisioning.reconcile_emptiness()  # starts the empty clock
        op.clock.step(20)
        # pod lands on the node again: the emptiness clock must reset
        op.kube.create("pods", "b", make_pod("b", cpu="1", memory="1Gi"))
        op.kube.bind_pod("b", name)
        assert node.pods, "bound pod should be resident on the node"
        op.deprovisioning.reconcile_emptiness()
        op.clock.step(15)  # 35s since first empty, but only 15s since reset
        op.deprovisioning.reconcile_emptiness()
        op.termination.reconcile_once()
        assert name in op.cluster.nodes


class TestExpiration:
    """integration/expiration_test.go: nodes older than
    ttlSecondsUntilExpired are rotated; the workload reschedules."""

    def test_expired_node_rotates_and_workload_survives(self, op):
        add_provisioner(op, ttl_seconds_until_expired=300)
        for i in range(4):
            op.kube.create("pods", f"p{i}", make_pod(f"p{i}", cpu="1",
                                                     memory="2Gi"))
        op.provisioning.reconcile_once()
        first_gen = set(op.cluster.nodes)
        assert first_gen and not op.kube.pending_pods()
        # young nodes: expiration must not act
        op.deprovisioning.reconcile_expiration()
        op.termination.reconcile_once()
        assert set(op.cluster.nodes) == first_gen
        # age past the TTL: nodes drain, pods pend, provisioning replaces
        op.clock.step(301)
        for _ in range(6):  # drain is gradual: eviction then delete
            op.deprovisioning.reconcile_expiration()
            op.termination.reconcile_once()
            op.provisioning.reconcile_once()
            op.clock.step(5)
        assert not (set(op.cluster.nodes) & first_gen), "old nodes must rotate"
        assert not op.kube.pending_pods(), "workload must reschedule"
        assert op.recorder.by_reason("Expired")

    def test_no_ttl_means_no_expiration(self, op):
        add_provisioner(op)  # ttl_seconds_until_expired unset
        op.kube.create("pods", "a", make_pod("a", cpu="1", memory="1Gi"))
        op.provisioning.reconcile_once()
        op.clock.step(10 ** 6)
        assert op.deprovisioning.reconcile_expiration() == []


class TestTermination:
    """integration/termination_test.go: deleting a node drains its pods and
    terminates the backing instance; machine + node objects are removed."""

    def test_delete_drains_and_terminates_instance(self, op):
        add_provisioner(op)
        op.kube.create("pods", "a", make_pod("a", cpu="1", memory="1Gi"))
        op.provisioning.reconcile_once()
        (name,) = op.cluster.nodes
        node = op.cluster.nodes[name]
        assert node.pods
        op.termination.request_deletion(name)
        for _ in range(4):
            op.termination.reconcile_once()
            op.clock.step(5)
        assert name not in op.cluster.nodes
        assert op.kube.get("machines", node.machine_name) is None
        inst_id = node.provider_id.rsplit("/", 1)[-1]
        assert op.cloudprovider.cloud.instances[inst_id].state == "terminated"
        # the drain evicted (deleted) the bare pod — a controller-managed
        # pod would be recreated by its owner; bare pods are gone for good
        assert op.kube.get("pods", "a") is None

    def test_do_not_evict_pod_blocks_drain_until_removed(self, op):
        add_provisioner(op)
        op.kube.create("pods", "a", make_pod(
            "a", cpu="1", memory="1Gi", do_not_evict=True))
        op.provisioning.reconcile_once()
        (name,) = op.cluster.nodes
        op.termination.request_deletion(name)
        for _ in range(3):
            op.termination.reconcile_once()
            op.clock.step(5)
        assert name in op.cluster.nodes, "do-not-evict must block the drain"
        # pod removed -> drain completes
        op.kube.delete("pods", "a")
        op.cluster.nodes[name].pods.clear()
        for _ in range(3):
            op.termination.reconcile_once()
            op.clock.step(5)
        assert name not in op.cluster.nodes


class TestProvisionerDeletion:
    """deprovisioning.md:22: nodes are owned by their provisioner — deleting
    it gracefully terminates them (ownership cascade)."""

    def test_deleting_provisioner_terminates_owned_nodes(self, op):
        add_provisioner(op, name="blue")
        add_provisioner(op, name="green")
        op.kube.create("pods", "a", make_pod(
            "a", cpu="1", memory="1Gi",
            node_selector={wk.LABEL_PROVISIONER: "blue"}))
        op.kube.create("pods", "b", make_pod(
            "b", cpu="1", memory="1Gi",
            node_selector={wk.LABEL_PROVISIONER: "green"}))
        op.provisioning.reconcile_once()
        owned = {n: v.provisioner_name for n, v in op.cluster.nodes.items()}
        assert set(owned.values()) == {"blue", "green"}
        op.kube.delete("provisioners", "blue")
        blue_nodes = {n for n, p in owned.items() if p == "blue"}
        for n in blue_nodes:
            assert op.cluster.nodes[n].marked_for_deletion
        green_nodes = {n for n, p in owned.items() if p == "green"}
        for n in green_nodes:
            assert not op.cluster.nodes[n].marked_for_deletion
        assert op.recorder.by_reason("OwnerDeleted")
        # drain completes through termination (pods evicted)
        for _ in range(4):
            op.termination.reconcile_once()
            op.clock.step(5)
        assert not (set(op.cluster.nodes) & blue_nodes)
        assert green_nodes <= set(op.cluster.nodes)


    def test_gc_backstop_reaps_orphaned_node(self, op):
        """A node that registers AFTER the deletion event (or while the
        controller was down) is caught by the GC sweep's level-triggered
        orphan check once the launch grace passes."""
        add_provisioner(op, name="blue")
        op.kube.create("pods", "a", make_pod(
            "a", cpu="1", memory="1Gi",
            node_selector={wk.LABEL_PROVISIONER: "blue"}))
        op.provisioning.reconcile_once()
        (name,) = op.cluster.nodes
        # simulate the missed edge: clear the mark the watch cascade set
        op.kube.delete("provisioners", "blue")
        node = op.cluster.nodes[name]
        node.marked_for_deletion = False
        node.deletion_requested_ts = 0.0
        # young node: grace spares it
        op.garbagecollection.reconcile_once()
        assert not node.marked_for_deletion
        op.clock.step(op.garbagecollection.grace_seconds + 1)
        op.garbagecollection.reconcile_once()
        assert node.marked_for_deletion


class TestImageSelection:
    """integration/ami_test.go: selector-matched newest image wins; without a
    selector the family's SSM default alias resolves."""

    def test_selector_picks_newest_matching_image(self, op):
        t = op.kube.get("nodetemplates", "default")
        t.image_selector = {"id": "img-amd64-1,img-amd64-2"}
        add_provisioner(op)
        op.kube.create("pods", "a", make_pod("a", cpu="1", memory="1Gi"))
        op.provisioning.reconcile_once()
        (inst,) = op.cloudprovider.cloud.instances.values()
        assert inst.image_id == "img-amd64-2"  # created=2.0 beats created=1.0

    def test_pinned_selector_overrides_newer_image(self, op):
        t = op.kube.get("nodetemplates", "default")
        t.image_selector = {"id": "img-amd64-1"}
        add_provisioner(op)
        op.kube.create("pods", "a", make_pod("a", cpu="1", memory="1Gi"))
        op.provisioning.reconcile_once()
        (inst,) = op.cloudprovider.cloud.instances.values()
        assert inst.image_id == "img-amd64-1"

    def test_default_ssm_alias_without_selector(self, op):
        add_provisioner(op)  # default template has no image selector
        op.kube.create("pods", "a", make_pod("a", cpu="1", memory="1Gi"))
        op.provisioning.reconcile_once()
        (inst,) = op.cloudprovider.cloud.instances.values()
        # /karpenter-tpu/images/default/amd64/latest -> img-amd64-2
        assert inst.image_id == "img-amd64-2"


class TestSecurityGroupSelection:
    """integration/security_group_test.go: the SG selector resolves into
    NodeTemplate status (ordered) and the launch path uses it."""

    def test_selector_resolves_into_status(self, op):
        cloud = op.cloudprovider.cloud
        from karpenter_tpu.fake.cloud import SecurityGroup

        cloud.security_groups.append(SecurityGroup(
            id="sg-extra", name="extra", tags={"team": "ml"}))
        op.kube.create("nodetemplates", "sgt", NodeTemplate(
            name="sgt",
            subnet_selector={"id": "subnet-zone-1a"},
            security_group_selector={"team": "ml"}))
        op.nodetemplate.reconcile_once()
        t = op.kube.get("nodetemplates", "sgt")
        assert t.status.security_groups == ["sg-extra"]

    def test_security_groups_ride_the_launch_template(self, op):
        add_provisioner(op)
        op.kube.create("pods", "a", make_pod("a", cpu="1", memory="1Gi"))
        op.provisioning.reconcile_once()
        (inst,) = op.cloudprovider.cloud.instances.values()
        lt = op.cloudprovider.cloud.launch_templates[inst.launch_template]
        assert lt.security_group_ids == ["sg-default"]

    def test_unmatched_selector_fails_launch(self, op):
        t = op.kube.get("nodetemplates", "default")
        t.security_group_selector = {"id": "sg-nonexistent"}
        add_provisioner(op)
        op.kube.create("pods", "a", make_pod("a", cpu="1", memory="1Gi"))
        op.provisioning.reconcile_once()
        assert len(op.cluster.nodes) == 0
        assert op.recorder.by_reason("LaunchFailed")


class TestZoneFoldReachesDeprovisioning:
    """A consolidation replacement must respect the template's subnet zones
    (the same fold provisioning applies) — otherwise the search decides a
    zone the launch path cannot satisfy and the action fail-loops."""

    def test_replacement_stays_in_template_zone(self, op):
        t = op.kube.get("nodetemplates", "default")
        t.subnet_selector = {"id": "subnet-zone-1b"}
        add_provisioner(op, consolidation_enabled=True)
        op.kube.create("pods", "a", make_pod("a", cpu="3", memory="3Gi"))
        op.provisioning.reconcile_once()
        op.machinelifecycle.reconcile_once()  # LAUNCHED -> REGISTERED
        op.machinelifecycle.reconcile_once()  # REGISTERED -> INITIALIZED
        (name,) = op.cluster.nodes
        node = op.cluster.nodes[name]
        assert node.zone == "zone-1b" and node.initialized
        # shrink the workload so a cheaper type could host it: replace-eligible
        op.kube.delete("pods", "a")
        node.pods.clear()
        op.kube.create("pods", "small", make_pod("small", cpu="1", memory="1Gi"))
        op.kube.bind_pod("small", name)
        op.clock.step(600)  # clear stabilization windows
        action = op.deprovisioning.reconcile_consolidation()
        assert action is not None and action.kind == "replace"
        zone = action.replacement[1]
        assert zone == "zone-1b", (
            f"replacement decided for {zone}, template can only "
            f"launch into zone-1b")


class TestSubnetSelection:
    """integration/subnet_test.go: the subnet selector constrains which
    zone/subnet instances launch into."""

    def test_restricted_selector_pins_zone(self, op):
        t = op.kube.get("nodetemplates", "default")
        t.subnet_selector = {"id": "subnet-zone-1b"}
        add_provisioner(op)
        for i in range(3):
            op.kube.create("pods", f"p{i}", make_pod(
                f"p{i}", cpu="1", memory="1Gi",
                anti_affinity_hostname=True))
        op.provisioning.reconcile_once()
        assert len(op.cluster.nodes) >= 1
        for inst in op.cloudprovider.cloud.instances.values():
            assert inst.subnet_id == "subnet-zone-1b"
            assert inst.zone == "zone-1b"

    def test_most_free_ips_subnet_preferred(self, op):
        # default template selects all three subnets; zone-1a has the most
        # free IPs in the fake fixture (subnet provider picks most-free)
        add_provisioner(op)
        op.kube.create("pods", "a", make_pod("a", cpu="1", memory="1Gi"))
        op.provisioning.reconcile_once()
        (inst,) = op.cloudprovider.cloud.instances.values()
        assert inst.subnet_id == "subnet-zone-1a"


class TestKubeletPassthrough:
    """Reference CRD kubeletConfiguration keys with no scheduling impact
    still load from manifests, survive the store round trip, and reach the
    node's kubelet flags via the generated user data."""

    FULL = """
apiVersion: karpenter.sh/v1alpha5
kind: Provisioner
metadata: {name: kc}
spec:
  providerRef: {name: default}
  kubeletConfiguration:
    clusterDNS: ["10.0.0.10", "10.0.0.11"]
    containerRuntime: containerd
    cpuCFSQuota: false
    maxPods: 60
    evictionSoft:
      memory.available: "500Mi"
    evictionSoftGracePeriod:
      memory.available: "1m"
    evictionMaxPodGracePeriod: 120
    imageGCHighThresholdPercent: 85
    imageGCLowThresholdPercent: 70
"""

    def test_manifest_to_userdata_flags(self, op):
        from karpenter_tpu.apis.yaml_compat import load_manifests

        loaded = load_manifests(self.FULL)
        (p,) = loaded.provisioners
        k = p.kubelet
        assert k.cluster_dns == ("10.0.0.10", "10.0.0.11")
        assert k.container_runtime == "containerd"
        assert k.cpu_cfs_quota is False
        assert k.eviction_soft == (("memory.available", "500Mi"),)
        op.kube.create("provisioners", "kc", p)
        op.kube.create("pods", "a", make_pod(
            "a", cpu="1", memory="1Gi",
            node_selector={wk.LABEL_PROVISIONER: "kc"}))
        op.provisioning.reconcile_once()
        (inst,) = op.cloudprovider.cloud.instances.values()
        ud = op.cloudprovider.cloud.launch_templates[inst.launch_template].userdata
        for needle in ("--cluster-dns=10.0.0.10,10.0.0.11",
                       "--container-runtime=containerd",
                       "--cpu-cfs-quota=false",
                       "--eviction-soft=memory.available<500Mi",
                       "--eviction-soft-grace-period=memory.available=1m",
                       "--eviction-max-pod-grace-period=120",
                       "--image-gc-high-threshold=85",
                       "--image-gc-low-threshold=70"):
            assert needle in ud, f"{needle} missing from userdata"

    def test_flatboat_family_renders_passthrough_toml(self):
        from karpenter_tpu.apis.yaml_compat import load_manifests
        from karpenter_tpu.providers.images import BootstrapConfig, get_family

        (p,) = load_manifests(self.FULL).provisioners
        toml = get_family("flatboat").userdata(BootstrapConfig(
            cluster_name="c", cluster_endpoint="https://k",
            labels={}, taints=(), kubelet=p.kubelet))
        for needle in ('cluster-dns-ip = "10.0.0.10"',
                       "cpu-cfs-quota-enforced = false",
                       "eviction-max-pod-grace-period = 120",
                       "[settings.kubernetes.eviction-soft]",
                       '"memory.available" = "500Mi"',
                       "[settings.kubernetes.eviction-soft-grace-period]"):
            assert needle in toml, f"{needle} missing from TOML userdata"

    def test_store_round_trip_preserves_passthrough(self):
        from karpenter_tpu.apis.yaml_compat import load_manifests
        from karpenter_tpu.coordination import serde

        (p,) = load_manifests(self.FULL).provisioners
        doc = serde.to_manifest("provisioners", "kc", p)
        kube = doc["spec"]["kubeletConfiguration"]
        assert kube["clusterDNS"] == ["10.0.0.10", "10.0.0.11"]
        assert kube["cpuCFSQuota"] is False
        assert kube["evictionSoft"] == {"memory.available": "500Mi"}
        # the real-schema spec reloads to an EQUAL model (pruning apiserver)
        import yaml

        (p2,) = load_manifests(yaml.safe_dump(doc)).provisioners
        assert p2.kubelet == p.kubelet


def test_fleet_context_reaches_launch_api(op):
    """spec.context (reserved-capacity targeting) passes verbatim to the
    launch API (reference instance.go:228)."""
    import yaml as _yaml

    from karpenter_tpu.apis.yaml_compat import load_manifests
    from karpenter_tpu.coordination import serde

    t = op.kube.get("nodetemplates", "default")
    t.fleet_context = "cr-0123456789abcdef"
    add_provisioner(op)
    op.kube.create("pods", "a", make_pod("a", cpu="1", memory="1Gi"))
    op.provisioning.reconcile_once()
    (req,) = op.cloudprovider.cloud.create_fleet_api.calls
    assert req.fleet_context == "cr-0123456789abcdef"
    # manifest + store round trips carry the key
    doc = serde.to_manifest("nodetemplates", "default", t)
    assert doc["spec"]["context"] == "cr-0123456789abcdef"
    loaded = load_manifests(_yaml.safe_dump(doc))
    assert loaded.templates[0].fleet_context == "cr-0123456789abcdef"


def test_provisioner_annotations_applied_to_nodes(op):
    """CRD spec.annotations: applied to every node the provisioner launches
    — including veto knobs like do-not-consolidate, which must then reach
    the deprovisioner's eligibility checks."""
    import yaml as _yaml

    from karpenter_tpu.apis.yaml_compat import load_manifests
    from karpenter_tpu.coordination import serde

    M = """
apiVersion: karpenter.sh/v1alpha5
kind: Provisioner
metadata: {name: anno}
spec:
  providerRef: {name: default}
  consolidation: {enabled: true}
  annotations:
    team.example/cost-center: "42"
    karpenter.sh/do-not-consolidate: "true"
"""
    (p,) = load_manifests(M).provisioners
    op.kube.create("provisioners", "anno", p)
    op.kube.create("pods", "a", make_pod(
        "a", cpu="1", memory="1Gi",
        node_selector={wk.LABEL_PROVISIONER: "anno"}))
    op.provisioning.reconcile_once()
    (node,) = op.cluster.nodes.values()
    assert node.annotations["team.example/cost-center"] == "42"
    # the annotation-driven veto is live: empty node, yet never consolidated
    op.machinelifecycle.reconcile_once()
    op.machinelifecycle.reconcile_once()
    op.kube.delete("pods", "a")
    node.pods.clear()
    op.clock.step(600)
    assert op.deprovisioning.reconcile_consolidation() is None
    # store round trip keeps the annotations
    doc = serde.to_manifest("provisioners", "anno", p)
    (p2,) = load_manifests(_yaml.safe_dump(doc)).provisioners
    assert p2.annotations == p.annotations
