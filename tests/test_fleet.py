"""Multi-tenant solver fleet tests (karpenter_tpu/fleet/): mega-solve
bit-parity with sequential single-tenant solves, batching determinism
under FakeClock, fairness bounds, shed-at-admission vs shed-in-queue
(never after compute), rendezvous-router stability under replica churn,
two in-process wire replicas end-to-end, and the statusz/metrics surface.
"""

import logging

import pytest

from karpenter_tpu.apis import wellknown as wk
from karpenter_tpu.apis.provisioner import Provisioner
from karpenter_tpu.chaos.invariants import check_fairness_never_starves
from karpenter_tpu.fleet import (DEFAULT_TENANT, FleetFrontend, FleetRouter,
                                 FleetService, FleetShed, TenantNotSynced)
from karpenter_tpu.models.instancetype import Catalog, make_instance_type
from karpenter_tpu.models.pod import make_pod
from karpenter_tpu.models.requirements import OP_IN, Requirements
from karpenter_tpu.solver import solver_pb2 as pb
from karpenter_tpu.solver.service import SolverService, serve
from karpenter_tpu.utils.clock import FakeClock


def small_catalog():
    return Catalog(types=[
        make_instance_type("m.large", cpu=4, memory="16Gi",
                           od_price=0.20, spot_price=0.07),
        make_instance_type("m.xlarge", cpu=16, memory="64Gi",
                           od_price=0.80, spot_price=0.28),
    ])


def default_provisioner():
    p = Provisioner(name="default", requirements=Requirements.of(
        (wk.LABEL_CAPACITY_TYPE, OP_IN, ["spot", "on-demand"])))
    p.set_defaults()
    return p


def pods_for(tag, n=4, cpu="1", memory="2Gi"):
    return [make_pod(f"{tag}-p{i}", cpu=cpu, memory=memory)
            for i in range(n)]


def stub_frontend(record=None, **kw):
    """FleetFrontend over a deterministic stub backend (no JAX): the demux
    echoes each problem's pod count so callers can verify ordering."""
    def backend(key, problems):
        if record is not None:
            record.append([p["_tag"] for p in problems]
                          if "_tag" in (problems[0] if problems else {})
                          else len(problems))
        return [{"pods": len(p["pods"])} for p in problems]

    kw.setdefault("clock", FakeClock())
    kw.setdefault("tick_interval_s", 0.02)
    return FleetFrontend(solve_batch=backend, **kw)


class TestMegaSolveParity:
    def test_mega_solve_matches_sequential_single_tenant_solves(self):
        """The acceptance bar: K tenants coalesced into one mega-solve get
        bit-identical decisions to K sequential solver.solve calls."""
        catalog, prov = small_catalog(), default_provisioner()
        svc = SolverService()
        f = FleetFrontend(svc, clock=FakeClock(), tick_interval_s=0.05,
                          max_wave=8, name="parity")
        for t in range(4):
            f.register(f"tenant-{t}", catalog, [prov])
        submissions = []
        for t in range(4):
            # different pod counts per tenant: the demux must route each
            # tenant ITS result, not just any result of the right shape
            pods = pods_for(f"t{t}", n=4 + t)
            submissions.append((pods, f.submit(f"tenant-{t}", pods)))
        served = f.tick()
        assert served == 4
        assert f.mega_solves == 1  # one vmapped dispatch covered all four
        with svc._lock:
            solver = next(iter(svc._cache.values()))[0]
        for pods, ticket in submissions:
            res = ticket.wait(1)
            seq = solver.solve(pods)
            assert res.decisions() == seq.decisions()
            assert sum(n.pod_count for n in res.nodes) == len(pods)

    def test_content_identical_tenants_share_one_resident_solver(self):
        svc = SolverService()
        f = FleetFrontend(svc, clock=FakeClock(), name="dedupe")
        keys = {f.register(f"t{i}", small_catalog(), [default_provisioner()])
                for i in range(5)}
        assert len(keys) == 1
        with svc._lock:
            assert len(svc._cache) == 1


class TestBatchingDeterminism:
    def drive(self):
        """Fixed submission schedule against a fresh frontend; returns the
        exact batch compositions the backend saw plus who was served on
        which tick — the whole observable batching behavior."""
        batches = []

        def backend(key, problems):
            batches.append(tuple(p["pods"][0].name.rsplit("-", 1)[0]
                                 for p in problems))
            return [None] * len(problems)

        f = FleetFrontend(solve_batch=backend, clock=FakeClock(),
                          tick_interval_s=0.02, max_wave=6,
                          starvation_bound=3, name="det")
        for tid in ("a", "b", "c"):
            f.register_key(tid, (1, 1))
        schedule = [("a", 4), ("b", 2), ("c", 1), ("a", 3), ("b", 1),
                    ("c", 2), ("a", 2)]
        tickets = []
        for tick, (tid, n) in enumerate(schedule):
            for i in range(n):
                tk = f.submit(tid, pods_for(f"{tid}{tick}{i}"))
                tickets.append((tid, tk))
            f.clock.step(0.02)
            f.tick()
        guard = 0
        while f.queued() and guard < 50:
            guard += 1
            f.clock.step(0.02)
            f.tick()
        assert f.queued() == 0
        return batches, [(tid, tk.served_tick) for tid, tk in tickets]

    def test_same_schedule_same_batches(self):
        first, second = self.drive(), self.drive()
        assert first == second
        batches, served = first
        assert len(batches) >= 7  # every tick with work dispatched
        assert all(tick is not None for _, tick in served)


class TestFairness:
    def test_hot_tenant_cannot_starve_light_tenants(self):
        f = stub_frontend(max_wave=8, starvation_bound=4, name="fair")
        for tid in ("hot", "l1", "l2", "l3"):
            f.register_key(tid, (1, 1))
        for tick in range(30):
            for i in range(12):  # hot floods every tick, over capacity
                f.submit("hot", pods_for(f"h{tick}-{i}"))
            for tid in ("l1", "l2", "l3"):
                f.submit(tid, pods_for(f"{tid}-{tick}"))
            f.clock.step(0.02)
            f.tick()
        stats = f.stats()["tenants"]
        for tid in ("l1", "l2", "l3"):
            # light tenants ride the WRR pass every tick: bounded wait even
            # while the hot tenant's own backlog grows without bound
            assert stats[tid]["served"] >= 28
            assert stats[tid]["max_wait_ticks"] <= f.starvation_bound
        assert stats["hot"]["served"] > 0  # capped, not blocked

    def test_weight_shifts_share_without_starving_anyone(self):
        f = stub_frontend(max_wave=6, starvation_bound=4, name="weights")
        f.register_key("gold", (1, 1), weight=3)
        f.register_key("bronze", (1, 1), weight=1)
        for tick in range(20):
            for i in range(6):  # gold floods past even its 3x share
                f.submit("gold", pods_for(f"g{tick}-{i}"))
            # bronze stays WITHIN its weight — the bound protects exactly
            # the within-weight tenant, an over-rate one queues behind
            # its own excess by construction
            f.submit("bronze", pods_for(f"b{tick}"))
            f.clock.step(0.02)
            f.tick()
        stats = f.stats()["tenants"]
        assert stats["gold"]["served"] > stats["bronze"]["served"]
        assert stats["bronze"]["max_wait_ticks"] <= f.starvation_bound

    def test_unregistered_tenant_is_refused(self):
        f = stub_frontend(name="refuse")
        with pytest.raises(TenantNotSynced):
            f.submit("nobody", pods_for("x"))

    def test_fairness_invariant_flags_bound_breach(self):
        good = {"starvation_bound": 4, "queued": 0,
                "tenants": {"a": {"weight": 1, "submitted": 5, "served": 5,
                                  "shed_admission": 0, "shed_queue": 0,
                                  "errors": 0, "max_wait_ticks": 4}}}
        assert check_fairness_never_starves(good) == []
        bad = {"starvation_bound": 4, "queued": 2,
               "tenants": {"a": {"weight": 1, "submitted": 5, "served": 5,
                                 "shed_admission": 0, "shed_queue": 0,
                                 "errors": 0, "max_wait_ticks": 9}}}
        found = {v.invariant for v in check_fairness_never_starves(bad)}
        assert found == {"fairness-never-starves"}
        assert len(check_fairness_never_starves(bad)) == 2  # wait + queued


class TestShedding:
    def test_shed_at_admission_never_reaches_backend(self):
        calls = []

        def backend(key, problems):
            calls.append(len(problems))
            return [None] * len(problems)

        f = FleetFrontend(solve_batch=backend, clock=FakeClock(),
                          tick_interval_s=0.02, name="shed-adm")
        f.register_key("t", (1, 1))
        # 5ms of budget cannot survive the ~20ms tick + 10ms floor
        ticket = f.submit("t", pods_for("x"), deadline_ms=5)
        assert ticket.done()  # resolved synchronously, never queued
        with pytest.raises(FleetShed) as e:
            ticket.wait(0)
        assert e.value.where == "admission"
        f.clock.step(0.02)
        f.tick()
        assert calls == []  # the backend never saw it
        assert f.stats()["tenants"]["t"]["shed_admission"] == 1

    def test_shed_in_queue_before_compute(self):
        calls = []

        def backend(key, problems):
            calls.append(len(problems))
            return [None] * len(problems)

        f = FleetFrontend(solve_batch=backend, clock=FakeClock(),
                          tick_interval_s=0.02, name="shed-q")
        f.register_key("t", (1, 1))
        ticket = f.submit("t", pods_for("x"), deadline_ms=100)
        assert not ticket.done()  # admitted: 100ms survives one tick
        f.clock.step(0.2)  # ...but the budget drains while queued
        f.tick()
        with pytest.raises(FleetShed) as e:
            ticket.wait(0)
        assert e.value.where == "queue"
        assert calls == []  # shed BEFORE compute, not after
        st = f.stats()["tenants"]["t"]
        assert (st["shed_queue"], st["served"]) == (1, 0)

    def test_healthy_budget_is_served(self):
        f = stub_frontend(name="shed-ok")
        f.register_key("t", (1, 1))
        ticket = f.submit("t", pods_for("x"), deadline_ms=5000)
        f.clock.step(0.02)
        f.tick()
        assert ticket.wait(0) == {"pods": 4}


class TestRouter:
    def test_empty_fleet_raises(self):
        r = FleetRouter()
        with pytest.raises(LookupError):
            r.route("acme")
        assert r.route_or_none("acme") is None

    def test_route_is_deterministic_and_order_independent(self):
        a = FleetRouter(["r1", "r2", "r3"])
        b = FleetRouter(["r3", "r1", "r2"])
        for i in range(50):
            assert a.route(f"t{i}") == b.route(f"t{i}")

    def test_remove_remaps_only_the_lost_replicas_tenants(self):
        tenants = [f"cluster-{i}" for i in range(200)]
        r = FleetRouter(["r1", "r2", "r3"])
        before = r.assignment(tenants)
        assert set(before.values()) == {"r1", "r2", "r3"}
        r.remove_replica("r2")
        after = r.assignment(tenants)
        for t in tenants:
            if before[t] != "r2":
                assert after[t] == before[t]  # survivors keep their home
            else:
                assert after[t] in ("r1", "r3")
        # rejoin restores the exact original assignment (pure function)
        r.add_replica("r2")
        assert r.assignment(tenants) == before

    def test_add_steals_only_for_the_newcomer(self):
        tenants = [f"cluster-{i}" for i in range(200)]
        r = FleetRouter(["r1", "r2", "r3"])
        before = r.assignment(tenants)
        r.add_replica("r4")
        after = r.assignment(tenants)
        moved = [t for t in tenants if after[t] != before[t]]
        assert moved  # the newcomer takes a share...
        assert all(after[t] == "r4" for t in moved)  # ...and ONLY it gains
        # ~1/4 of tenants move, not ~all (the modulo-hash failure mode)
        assert len(moved) < 200 * 0.45

    def test_rejects_empty_replica_name(self):
        with pytest.raises(ValueError):
            FleetRouter().add_replica("")


class TestWireFleet:
    @pytest.fixture()
    def replicas(self):
        servers, frontends, targets = [], [], []
        for _ in range(2):
            svc = SolverService()
            fe = FleetFrontend(svc, tick_interval_s=0.005, name="wire")
            fe.start()
            srv, port, _ = serve("127.0.0.1:0", service=FleetService(fe))
            servers.append(srv)
            frontends.append(fe)
            targets.append(f"127.0.0.1:{port}")
        yield frontends, targets
        for fe in frontends:
            fe.stop()
        for srv in servers:
            srv.stop(grace=None)

    def test_two_replicas_route_sync_and_solve(self, replicas):
        from karpenter_tpu.solver.client import RemoteSolver
        from karpenter_tpu.solver.core import TPUSolver

        frontends, targets = replicas
        router = FleetRouter(targets)
        catalog, prov = small_catalog(), default_provisioner()
        local = TPUSolver(catalog, [prov])
        tenants = [f"cluster-{i}" for i in range(6)]
        homes = router.assignment(tenants)
        assert set(homes.values()) == set(targets)  # both replicas used
        for tid in tenants:
            remote = RemoteSolver(catalog, [prov], target=homes[tid],
                                  tenant_id=tid)
            pods = pods_for(tid, n=5)
            res = remote.solve(pods)
            assert res.decisions() == local.solve(pods).decisions()
        served_by = {t: fe.stats()["tenants"]
                     for t, fe in zip(targets, frontends)}
        for tid in tenants:
            # each tenant was admitted and served on ITS home replica only
            assert served_by[homes[tid]][tid]["served"] == 1
            other = next(t for t in targets if t != homes[tid])
            assert tid not in served_by[other]

    def test_wire_solve_without_tenant_runs_as_default(self, replicas):
        from karpenter_tpu.solver.client import RemoteSolver

        frontends, targets = replicas
        catalog, prov = small_catalog(), default_provisioner()
        remote = RemoteSolver(catalog, [prov], target=targets[0])
        res = remote.solve(pods_for("legacy", n=3))
        assert sum(n.pod_count for n in res.nodes) == 3
        assert DEFAULT_TENANT in frontends[0].stats()["tenants"]


class TestTenantWire:
    def test_solve_request_carries_tenant_id(self):
        req = pb.SolveRequest(tenant_id="acme", catalog_seqnum=3)
        blob = req.SerializeToString()
        back = pb.SolveRequest()
        back.ParseFromString(blob)
        assert back.tenant_id == "acme"
        assert pb.SolveRequest().tenant_id == ""  # proto3 default: legacy


class TestIntrospection:
    def test_statusz_schema_bumped_with_fleet_section(self):
        from karpenter_tpu.introspect import statusz

        assert statusz.SCHEMA_VERSION >= 4  # fleet section landed in 4
        f = stub_frontend(name="statusz-probe")
        f.register_key("t", (1, 1))
        f.submit("t", pods_for("x"))
        f.clock.step(0.02)
        f.tick()
        section = statusz._fleet_section()
        mine = [s for s in section["frontends"]
                if s["name"] == "statusz-probe"]
        assert len(mine) == 1
        assert mine[0]["tenants"]["t"]["served"] == 1
        assert mine[0]["mega_solves"] == 1

    def test_fleet_metrics_registered(self):
        from karpenter_tpu.metrics import REGISTRY

        with REGISTRY._lock:
            names = set(REGISTRY._metrics)
        for name in ("karpenter_fleet_queue_depth",
                     "karpenter_fleet_requests_total",
                     "karpenter_fleet_shed_total",
                     "karpenter_fleet_mega_solves_total",
                     "karpenter_fleet_batch_occupancy_ratio",
                     "karpenter_fleet_tenant_solve_seconds",
                     "karpenter_fleet_wait_ticks"):
            assert name in names


class TestTenantStorm:
    def test_storm_scenario_passes_and_replays(self):
        from karpenter_tpu.chaos import ChaosRunner

        runner = ChaosRunner(seed=7, storm=True)
        s1 = runner.run_storm_scenario(0)
        assert s1["passed"], s1["violations"]
        t = s1["totals"]
        assert t["shed_admission"] > 0 and t["shed_queue"] > 0
        assert t["served"] > 0
        for tid, st in s1["evidence"]["tenants"].items():
            assert st["max_wait_ticks"] <= s1["starvation_bound"], tid
        # replay contract: the scenario dict is a pure function of the seed
        assert ChaosRunner(seed=7, storm=True).run_storm_scenario(0) == s1


class TestCrossoverKnob:
    def test_default_when_unset(self, monkeypatch):
        from karpenter_tpu.solver import buckets

        for var in buckets._CROSSOVER_ENV_VARS:
            monkeypatch.delenv(var, raising=False)
        assert buckets.crossover_cells_default() == \
            buckets.DEFAULT_CROSSOVER_CELLS

    def test_valid_value_both_names(self, monkeypatch):
        from karpenter_tpu.solver import buckets

        for var in buckets._CROSSOVER_ENV_VARS:
            monkeypatch.delenv(var, raising=False)
        monkeypatch.setenv("KARPENTER_TPU_CROSSOVER_CELLS", "4096")
        assert buckets.crossover_cells_default() == 4096
        # the canonical SHARD_ name wins when both are set
        monkeypatch.setenv("KARPENTER_TPU_SHARD_CROSSOVER_CELLS", "65536")
        assert buckets.crossover_cells_default() == 65536

    def test_garbage_warns_and_falls_back(self, monkeypatch, caplog):
        from karpenter_tpu.solver import buckets

        monkeypatch.setenv("KARPENTER_TPU_SHARD_CROSSOVER_CELLS", "lots")
        with caplog.at_level(logging.WARNING,
                             logger="karpenter.solver.buckets"):
            assert buckets.crossover_cells_default() == \
                buckets.DEFAULT_CROSSOVER_CELLS
        assert "not an integer" in caplog.text

    def test_negative_clamps_to_zero_with_warning(self, monkeypatch, caplog):
        from karpenter_tpu.solver import buckets

        monkeypatch.setenv("KARPENTER_TPU_SHARD_CROSSOVER_CELLS", "-5")
        with caplog.at_level(logging.WARNING,
                             logger="karpenter.solver.buckets"):
            assert buckets.crossover_cells_default() == 0
        assert "clamping to 0" in caplog.text
