"""Operator serving plane: metrics, health probes, AdmissionReview webhook
(reference values.yaml:134-142 port wiring + pkg/webhooks AdmissionReview).
"""

import json
import urllib.request
import urllib.error

import pytest

from karpenter_tpu.apis.nodetemplate import NodeTemplate
from karpenter_tpu.apis.settings import Settings
from karpenter_tpu.fake.cloud import FakeCloud
from karpenter_tpu.models.instancetype import Catalog, make_instance_type
from karpenter_tpu.operator import Operator
from karpenter_tpu.utils.clock import FakeClock


@pytest.fixture
def served_op():
    clock = FakeClock()
    cat = Catalog(types=[make_instance_type("m.large", cpu=4, memory="16Gi",
                                            od_price=0.2)])
    op = Operator(FakeCloud(catalog=cat, clock=clock),
                  Settings(cluster_name="srv", cluster_endpoint="https://k"),
                  cat, clock=clock, serve_http=True,
                  metrics_port=0, health_port=0, webhook_port=0)
    ports = op.serving.start()
    yield op, ports
    op.serving.stop()
    op.stop()


def _get(port, path):
    try:
        with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}",
                                    timeout=5) as r:
            return r.status, r.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


def _review(port, plural, obj, operation="CREATE"):
    body = json.dumps({
        "apiVersion": "admission.k8s.io/v1", "kind": "AdmissionReview",
        "request": {"uid": "u-1", "operation": operation,
                    "resource": {"resource": plural}, "object": obj},
    }).encode()
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/validate", body,
        {"Content-Type": "application/json"}, method="POST")
    with urllib.request.urlopen(req, timeout=5) as r:
        return json.loads(r.read())


class TestServingPlane:
    def test_metrics_endpoint(self, served_op):
        op, ports = served_op
        code, body = _get(ports["metrics"], "/metrics")
        assert code == 200
        assert "karpenter" in body

    def test_health_endpoints(self, served_op):
        op, ports = served_op
        for path in ("/healthz", "/livez", "/readyz"):
            code, body = _get(ports["health"], path)
            assert code == 200, path
            assert body == "ok"

    def test_webhook_allows_valid_nodetemplate(self, served_op):
        op, ports = served_op
        resp = _review(ports["webhook"], "nodetemplates", {
            "apiVersion": "karpenter.k8s.tpu/v1alpha1", "kind": "NodeTemplate",
            "metadata": {"name": "ok"},
            "spec": {"subnetSelector": {"id": "subnet-zone-1a"},
                     "securityGroupSelector": {"id": "sg-default"}},
        })
        assert resp["response"]["allowed"] is True
        assert resp["response"]["uid"] == "u-1"

    def test_webhook_denies_invalid_nodetemplate(self, served_op):
        op, ports = served_op
        resp = _review(ports["webhook"], "nodetemplates", {
            "apiVersion": "karpenter.k8s.tpu/v1alpha1", "kind": "NodeTemplate",
            "metadata": {"name": "bad"},
            "spec": {"subnetSelector": {"id": "not-a-subnet-id!"},
                     "securityGroupSelector": {"id": "sg-default"}},
        })
        assert resp["response"]["allowed"] is False
        assert "subnet" in resp["response"]["status"]["message"]

    def test_webhook_denies_restricted_cluster_tag(self, served_op):
        op, ports = served_op
        resp = _review(ports["webhook"], "awsnodetemplates", {
            "apiVersion": "karpenter.k8s.aws/v1alpha1",
            "kind": "AWSNodeTemplate",
            "metadata": {"name": "bad"},
            "spec": {"subnetSelector": {"id": "subnet-zone-1a"},
                     "securityGroupSelector": {"id": "sg-default"},
                     "tags": {"kubernetes.io/cluster/srv": "owned"}},
        })
        assert resp["response"]["allowed"] is False

    def test_webhook_admits_unguarded_kinds(self, served_op):
        op, ports = served_op
        resp = _review(ports["webhook"], "pods", {"metadata": {"name": "p"}})
        assert resp["response"]["allowed"] is True

    def test_webhook_denies_garbage(self, served_op):
        op, ports = served_op
        req = urllib.request.Request(
            f"http://127.0.0.1:{ports['webhook']}/validate", b"not json",
            {"Content-Type": "application/json"}, method="POST")
        with urllib.request.urlopen(req, timeout=5) as r:
            resp = json.loads(r.read())
        assert resp["response"]["allowed"] is False


class TestDebugEndpoints:
    def test_statusz_serves_snapshot(self, served_op):
        op, ports = served_op
        op.reconcile_all_once()
        code, body = _get(ports["metrics"], "/debug/statusz")
        assert code == 200
        snap = json.loads(body)
        assert snap["tool"] == "karpenter_tpu.statusz"
        assert snap["controllers"]["provisioning"]["beats"] >= 1

    def test_bundle_serves_live_bundle(self, served_op):
        op, ports = served_op
        code, body = _get(ports["metrics"], "/debug/bundle")
        assert code == 200
        bundle = json.loads(body)
        assert bundle["tool"] == "karpenter_tpu.diagnostics_bundle"
        assert bundle["trigger"]["reason"] == "manual"

    def test_bundle_404_without_flight_recorder(self):
        from karpenter_tpu.serving import ServingPlane

        class NullOp:
            def metrics_text(self):
                return "x"

        plane = ServingPlane(NullOp(), metrics_port=0, health_port=-1,
                             webhook_port=-1)
        ports = plane.start()
        try:
            code, body = _get(ports["metrics"], "/debug/bundle")
        finally:
            plane.stop()
        assert code == 404
        assert "flight recorder" in body

    def test_traces_rejects_non_integer_limit(self, served_op):
        op, ports = served_op
        code, body = _get(ports["metrics"], "/debug/traces?limit=bogus")
        assert code == 400
        assert "integer" in body

    def test_traces_clamps_huge_limit(self, served_op):
        op, ports = served_op
        # a limit far past the ring must clamp, not error or balloon
        code, body = _get(ports["metrics"], "/debug/traces?limit=999999")
        assert code == 200
        traces = json.loads(body)["traces"]
        from karpenter_tpu.serving import MAX_TRACE_LIMIT
        assert len(traces) <= MAX_TRACE_LIMIT

    def test_eventz_lists_recent_events(self, served_op):
        op, ports = served_op
        op.recorder.warning("node/n-1", "TestReason", "hello eventz")
        code, body = _get(ports["health"], "/eventz?n=10")
        assert code == 200
        events = json.loads(body)["events"]
        assert any(e["reason"] == "TestReason"
                   and e["object"] == "node/n-1" for e in events)

    def test_eventz_rejects_non_integer_n(self, served_op):
        op, ports = served_op
        code, body = _get(ports["health"], "/eventz?n=many")
        assert code == 400

    def test_logz_rejects_unknown_level(self, served_op):
        op, ports = served_op
        code, body = _get(ports["health"], "/logz?level=LOUD")
        assert code == 400
        assert "unknown log level" in body

    def test_logz_json_mode_returns_records(self, served_op):
        import logging

        op, ports = served_op
        logging.getLogger("karpenter.test_serving").warning("logz json probe")
        code, body = _get(ports["health"], "/logz?format=json&n=50")
        assert code == 200
        records = [json.loads(line) for line in body.splitlines() if line]
        assert any(r["line"].endswith("logz json probe") and
                   r["level"] == "WARNING" for r in records)

    def test_readyz_names_stalled_controller(self, served_op):
        op, ports = served_op
        op.reconcile_all_once()
        code, body = _get(ports["health"], "/readyz")
        assert (code, body) == (200, "ok")
        op.clock.step(500.0)
        code, body = _get(ports["health"], "/readyz")
        assert code == 503
        assert "stalled controllers" in body and "provisioning" in body
        op.reconcile_all_once()
        code, body = _get(ports["health"], "/readyz")
        assert (code, body) == (200, "ok")


class TestServingHardening:
    def test_webhook_fails_closed_without_content_length(self, served_op):
        import http.client

        op, ports = served_op
        conn = http.client.HTTPConnection("127.0.0.1", ports["webhook"],
                                          timeout=5)
        # POST with no body and no Content-Length: must be denied, not
        # admitted as an empty review
        conn.putrequest("POST", "/validate")
        conn.endheaders()
        resp = conn.getresponse()
        body = json.loads(resp.read())
        assert body["response"]["allowed"] is False
        conn.close()

    def test_stop_releases_listening_sockets(self):
        import socket

        from karpenter_tpu.serving import ServingPlane

        class NullOp:
            def metrics_text(self):
                return "x"

            def healthz(self):
                return True

            def livez(self):
                return True

        plane = ServingPlane(NullOp(), metrics_port=0, health_port=0,
                             webhook_port=0)
        ports = plane.start()
        plane.stop()
        # the port must be immediately rebindable (server_close ran)
        s = socket.socket()
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind(("127.0.0.1", ports["metrics"]))
        s.close()

    def test_webhook_serves_tls_when_cert_provided(self, tmp_path):
        import ssl as _ssl
        import subprocess

        from karpenter_tpu.serving import ServingPlane

        cert, key = tmp_path / "tls.crt", tmp_path / "tls.key"
        gen = subprocess.run(
            ["openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
             "-keyout", str(key), "-out", str(cert), "-days", "1",
             "-subj", "/CN=karpenter-tpu.karpenter-tpu.svc"],
            capture_output=True)
        if gen.returncode != 0:
            pytest.skip("openssl unavailable")

        class NullOp:
            def metrics_text(self):
                return "x"

            def healthz(self):
                return True

            def livez(self):
                return True

            class webhooks:  # noqa: N801 - minimal admit surface
                @staticmethod
                def admit(kind, obj, op):
                    return obj

        plane = ServingPlane(NullOp(), metrics_port=-1, health_port=-1,
                             webhook_port=0, tls_cert=str(cert),
                             tls_key=str(key))
        ports = plane.start()
        try:
            ctx = _ssl.create_default_context()
            ctx.check_hostname = False
            ctx.verify_mode = _ssl.CERT_NONE
            req = urllib.request.Request(
                f"https://127.0.0.1:{ports['webhook']}/validate",
                json.dumps({"request": {"uid": "u", "resource":
                            {"resource": "pods"}, "object": {}}}).encode(),
                {"Content-Type": "application/json"}, method="POST")
            with urllib.request.urlopen(req, timeout=5, context=ctx) as r:
                resp = json.loads(r.read())
            assert resp["response"]["allowed"] is True  # unguarded kind
        finally:
            plane.stop()


class TestMutatingWebhook:
    def _mutate(self, port, plural, obj):
        body = json.dumps({
            "apiVersion": "admission.k8s.io/v1", "kind": "AdmissionReview",
            "request": {"uid": "m-1", "operation": "CREATE",
                        "resource": {"resource": plural}, "object": obj},
        }).encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/mutate", body,
            {"Content-Type": "application/json"}, method="POST")
        with urllib.request.urlopen(req, timeout=5) as r:
            return json.loads(r.read())

    def test_mutate_returns_defaulting_patch(self, served_op):
        import base64

        op, ports = served_op
        # a provisioner with no requirements: defaulting adds linux/amd64/
        # on-demand (v1alpha5/provisioner.go:45-60 analogue)
        resp = self._mutate(ports["webhook"], "provisioners", {
            "apiVersion": "karpenter.sh/v1alpha5", "kind": "Provisioner",
            "metadata": {"name": "min", "labels": {"team": "a"}},
            "spec": {},
        })
        assert resp["response"]["allowed"] is True
        assert resp["response"]["patchType"] == "JSONPatch"
        patch = json.loads(base64.b64decode(resp["response"]["patch"]))
        (op_item,) = patch
        assert op_item["op"] == "replace" and op_item["path"] == ""
        defaulted = op_item["value"]
        assert defaulted["metadata"]["name"] == "min"
        assert defaulted["metadata"]["labels"] == {"team": "a"}  # preserved
        from karpenter_tpu.coordination import serde

        prov = serde.from_manifest("provisioners", defaulted)
        assert prov.requirements.get("kubernetes.io/os") is not None

    def test_mutate_still_denies_invalid(self, served_op):
        op, ports = served_op
        resp = self._mutate(ports["webhook"], "nodetemplates", {
            "apiVersion": "karpenter.k8s.tpu/v1alpha1", "kind": "NodeTemplate",
            "metadata": {"name": "bad"},
            "spec": {"subnetSelector": {"id": "bogus!"}},
        })
        assert resp["response"]["allowed"] is False
        assert "patch" not in resp["response"]
