"""Tracing-plane tests: span nesting/parenting, cross-wire trace-context
propagation (client solve and service span share one trace), Chrome
trace_event export validity, ring-buffer bounding under concurrent
writers, and the controller-integrated end-to-end trace surfaced through
/debug/traces and the phase-duration histogram."""

import json
import threading
import urllib.request

import pytest

from karpenter_tpu.apis import wellknown as wk
from karpenter_tpu.apis.nodetemplate import NodeTemplate
from karpenter_tpu.apis.provisioner import Provisioner
from karpenter_tpu.apis.settings import Settings
from karpenter_tpu.fake.cloud import FakeCloud
from karpenter_tpu.models.instancetype import Catalog, make_instance_type
from karpenter_tpu.models.pod import make_pod
from karpenter_tpu.models.requirements import OP_IN, Requirements
from karpenter_tpu.operator import Operator
from karpenter_tpu.tracing import PHASE_METRIC, TRACER, SpanContext, Tracer
from karpenter_tpu.utils.clock import FakeClock


class TestSpanNesting:
    def test_thread_local_parenting(self):
        t = Tracer(ring_size=64, registry=None)
        with t.start_span("root") as root:
            with t.start_span("child") as child:
                with t.start_span("grandchild") as grand:
                    assert t.current_span() is grand
                assert t.current_span() is child
        assert t.current_span() is None
        assert child.trace_id == root.trace_id == grand.trace_id
        assert child.parent_id == root.span_id
        assert grand.parent_id == child.span_id
        assert root.parent_id == ""

    def test_explicit_parent_beats_current(self):
        t = Tracer(ring_size=64, registry=None)
        other = t.start_span("other-root")
        other.end()
        with t.start_span("cur"):
            s = t.start_span("adopted", parent=other)
            assert s.trace_id == other.trace_id
            assert s.parent_id == other.span_id
            s.end()

    def test_remote_context_joins_trace(self):
        t = Tracer(ring_size=64, registry=None)
        ctx = SpanContext(trace_id="aaaa", span_id="bbbb")
        with t.start_span("joined", context=ctx) as s:
            assert s.trace_id == "aaaa"
            assert s.parent_id == "bbbb"
        # an empty wire context (not tracing) falls through to a new root
        with t.start_span("fresh", context=SpanContext("", "")) as s:
            assert s.trace_id not in ("", "aaaa")
            assert s.parent_id == ""

    def test_exception_recorded_and_end_idempotent(self):
        t = Tracer(ring_size=64, registry=None)
        with pytest.raises(ValueError):
            with t.start_span("boom") as s:
                raise ValueError("x")
        assert s.attributes["error"] is True
        assert s.attributes["error.type"] == "ValueError"
        first = s.duration_s
        s.end()  # double-end is a no-op
        assert s.duration_s == first
        assert len(t.finished_spans()) == 1

    def test_annotate_hits_current_span_only(self):
        t = Tracer(ring_size=64, registry=None)
        t.annotate(ignored=True)  # no current span: silently dropped
        with t.start_span("s") as s:
            t.annotate(transfer_ms=1.5, compile_cache="hit")
        assert s.attributes == {"transfer_ms": 1.5, "compile_cache": "hit"}


class TestChromeExport:
    def test_chrome_trace_event_validity(self):
        t = Tracer(ring_size=64, registry=None)
        with t.start_span("cycle", pods=3) as root:
            with t.start_span("solve"):
                pass
        doc = json.loads(t.chrome_trace_json(root.trace_id))
        assert set(doc) == {"traceEvents", "displayTimeUnit"}
        assert doc["displayTimeUnit"] == "ms"
        assert len(doc["traceEvents"]) == 2
        for ev in doc["traceEvents"]:
            assert ev["ph"] == "X"
            assert isinstance(ev["ts"], float) and ev["ts"] > 0
            assert isinstance(ev["dur"], float) and ev["dur"] >= 0
            assert isinstance(ev["pid"], int) and isinstance(ev["tid"], int)
            assert ev["cat"] == root.trace_id
        # events are time-sorted; the root opened first
        assert [e["name"] for e in doc["traceEvents"]] == ["cycle", "solve"]
        assert doc["traceEvents"][0]["args"] == {"pods": 3}

    def test_trace_id_filter(self):
        t = Tracer(ring_size=64, registry=None)
        with t.start_span("a") as a:
            pass
        with t.start_span("b"):
            pass
        doc = t.chrome_trace(a.trace_id)
        assert [e["name"] for e in doc["traceEvents"]] == ["a"]
        assert len(t.chrome_trace()["traceEvents"]) == 2

    def test_traces_listing_groups_and_bounds(self):
        t = Tracer(ring_size=64, registry=None)
        for i in range(5):
            with t.start_span(f"root-{i}"):
                with t.start_span("child"):
                    pass
        out = t.traces(limit=3)
        assert [tr["root"] for tr in out] == ["root-4", "root-3", "root-2"]
        assert all(tr["n_spans"] == 2 for tr in out)


class TestRingBounding:
    def test_concurrent_writers_stay_bounded(self):
        t = Tracer(ring_size=50, registry=None)
        errors = []

        def writer(k):
            try:
                for i in range(200):
                    with t.start_span(f"w{k}-{i}"):
                        pass
            except Exception as e:  # pragma: no cover - diagnostic
                errors.append(e)

        threads = [threading.Thread(target=writer, args=(k,))
                   for k in range(8)]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        assert not errors
        assert len(t.finished_spans()) == 50
        # every thread kept an isolated stack: none left a dangling current
        assert t.current_span() is None


class TestCrossWirePropagation:
    """Client solve -> service spans must share ONE trace id, with the
    service span parented under the client's rpc span, and the device-path
    observability (routing / compile_cache / transfer_ms) recorded on BOTH
    sides of the wire."""

    def _catalog(self):
        return Catalog(types=[
            make_instance_type("m.large", cpu=2, memory="8Gi",
                               od_price=0.10, spot_price=0.03),
            make_instance_type("m.xlarge", cpu=4, memory="16Gi",
                               od_price=0.20, spot_price=0.06),
        ])

    def test_solve_joins_one_trace_with_attrs_on_both_sides(self):
        import grpc

        from karpenter_tpu.solver.client import RemoteSolver
        from karpenter_tpu.solver.service import serve

        cat = self._catalog()
        prov = Provisioner(name="default", requirements=Requirements.of(
            (wk.LABEL_CAPACITY_TYPE, OP_IN, ["spot", "on-demand"])))
        prov.set_defaults()
        srv, port, _svc = serve("127.0.0.1:0")
        try:
            rs = RemoteSolver(
                cat, [prov],
                channel=grpc.insecure_channel(f"127.0.0.1:{port}"))
            pods = [make_pod(f"p{i}", cpu="500m", memory="1Gi")
                    for i in range(8)]
            TRACER.clear()
            with TRACER.start_span("provisioning.solve") as outer:
                result = rs.solve(pods)
            assert result.nodes
            spans = {s.name: s for s in TRACER.finished_spans()}
            need = {"provisioning.solve", "solver.rpc.Sync",
                    "solver.service.Sync", "solver.rpc.Solve",
                    "solver.service.Solve"}
            assert need <= set(spans)
            # one connected trace across the wire
            assert {s.trace_id for s in spans.values()} == {outer.trace_id}
            rpc, svc = spans["solver.rpc.Solve"], spans["solver.service.Solve"]
            assert svc.parent_id == rpc.span_id
            assert rpc.parent_id == outer.span_id
            # both wire sides carry the device-path observability
            for side in (rpc, svc):
                assert side.attributes["routing"] == "tpu"
                assert side.attributes["compile_cache"] in ("hit", "miss")
                assert side.attributes["transfer_ms"] >= 0.0
                assert side.attributes["solve_ms"] > 0.0
            # the service side additionally breaks the pipeline down
            for key in ("encode_ms", "dispatch_ms", "decode_ms"):
                assert key in svc.attributes
            # the echo bubbled up to the enclosing controller-phase span
            assert outer.attributes["routing"] == "tpu"
        finally:
            srv.stop(grace=None)


class TestOperatorTrace:
    """One provisioning cycle under the fake cloud yields one connected
    trace with mask/solve/bind children, exported through /debug/traces
    and observed into the phase-duration histogram."""

    def _operator(self):
        cat = Catalog(types=[
            make_instance_type("t.small", cpu=2, memory="2Gi",
                               od_price=0.05, spot_price=0.02),
            make_instance_type("m.xlarge", cpu=16, memory="64Gi",
                               od_price=0.80, spot_price=0.28),
        ])
        clock = FakeClock()
        cloud = FakeCloud(catalog=cat, clock=clock)
        op = Operator(cloud,
                      Settings(cluster_name="trace",
                               cluster_endpoint="https://k.example",
                               batch_idle_duration=0.0,
                               batch_max_duration=0.0),
                      cat, clock=clock, serve_http=True,
                      metrics_port=0, health_port=0, webhook_port=0)
        op.kube.create("nodetemplates", "default", NodeTemplate(
            name="default",
            subnet_selector={"id": "subnet-zone-1a"},
            security_group_selector={"id": "sg-default"}))
        op.cloudprovider.register_nodetemplate(
            op.kube.get("nodetemplates", "default"))
        p = Provisioner(name="default", provider_ref="default")
        p.set_defaults()
        op.kube.create("provisioners", "default", p)
        return op

    def _get(self, port, path):
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}") as r:
            return r.status, r.read().decode()

    def test_cycle_trace_debug_surface_and_histogram(self):
        op = self._operator()
        try:
            ports = op.serving.start()
            for i in range(50):
                op.kube.create("pods", f"p{i}",
                               make_pod(f"p{i}", cpu="500m", memory="1Gi"))
            TRACER.clear()
            op.provisioning.reconcile_once()
            assert len(op.kube.pending_pods()) == 0

            spans = [s for s in TRACER.finished_spans()
                     if s.name.startswith("provisioning.")]
            by_name = {s.name: s for s in spans}
            root = by_name["provisioning.cycle"]
            for phase in ("mask", "solve", "bind"):
                child = by_name[f"provisioning.{phase}"]
                assert child.trace_id == root.trace_id
                assert child.parent_id == root.span_id
            assert root.attributes["pods"] == 50
            assert by_name["provisioning.solve"].attributes["routing"]
            assert "compile_cache" in by_name["provisioning.solve"].attributes
            assert "transfer_ms" in by_name["provisioning.solve"].attributes

            # /debug/traces listing contains the cycle trace
            status, body = self._get(ports["metrics"], "/debug/traces")
            assert status == 200
            listing = json.loads(body)["traces"]
            ids = {tr["trace_id"] for tr in listing}
            assert root.trace_id in ids
            # ?id= exports valid Chrome JSON for exactly that trace
            status, body = self._get(
                ports["metrics"], f"/debug/traces?id={root.trace_id}")
            assert status == 200
            doc = json.loads(body)
            names = {e["name"] for e in doc["traceEvents"]}
            assert {"provisioning.cycle", "provisioning.mask",
                    "provisioning.solve", "provisioning.bind"} <= names
            # span events are complete ("X"); federation may add "M"
            # process_name metadata rows, and the profiling lane adds "i"
            # instant events per host sample (standard chrome trace format)
            assert all(e["ph"] in ("X", "M", "i") for e in doc["traceEvents"])
            assert any(e["ph"] == "X" for e in doc["traceEvents"])
            # unknown id is a 404, not an empty export
            try:
                status, _ = self._get(ports["metrics"],
                                      "/debug/traces?id=deadbeef")
            except urllib.error.HTTPError as e:
                status = e.code
            assert status == 404
            # spans fed the phase-duration histogram
            status, body = self._get(ports["metrics"], "/metrics")
            assert status == 200
            for phase in ("provisioning.cycle", "provisioning.mask",
                          "provisioning.solve", "provisioning.bind"):
                assert f'{PHASE_METRIC}_count{{phase="{phase}"}}' in body
        finally:
            op.stop()
