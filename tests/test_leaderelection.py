"""Leader election: lease CAS semantics, standby takeover, and the
kill-the-leader HA scenario through the threaded operator.

Parity target: /root/reference/cmd/controller/main.go:34,42 (operator-managed
lease election, LEADER_ELECT) and the charts' 2-replica + PDB deployment.
"""

import threading
import time

from karpenter_tpu.apis.provisioner import Provisioner
from karpenter_tpu.apis.settings import Settings
from karpenter_tpu.apis.nodetemplate import NodeTemplate
from karpenter_tpu.fake.cloud import FakeCloud
from karpenter_tpu.fake.kube import KubeStore
from karpenter_tpu.leaderelection import LeaderElector
from karpenter_tpu.models.instancetype import Catalog, make_instance_type
from karpenter_tpu.models.pod import make_pod
from karpenter_tpu.operator import Operator
from karpenter_tpu.utils.clock import Clock, FakeClock


def catalog():
    return Catalog(types=[
        make_instance_type("m.large", cpu=4, memory="16Gi", od_price=0.20,
                           spot_price=0.07),
    ])


class TestLeaderElector:
    def test_acquire_then_renew(self):
        kube, clock = KubeStore(), FakeClock()
        a = LeaderElector(kube, "a", clock=clock)
        assert a.try_acquire_or_renew()
        assert a.is_leader()
        lease1 = kube.get("leases", a.name)
        clock.step(3)
        assert a.try_acquire_or_renew()
        lease2 = kube.get("leases", a.name)
        assert lease2.renew_ts > lease1.renew_ts
        assert lease2.acquired_ts == lease1.acquired_ts

    def test_standby_waits_then_takes_over_on_expiry(self):
        kube, clock = KubeStore(), FakeClock()
        a = LeaderElector(kube, "a", clock=clock, lease_duration_s=15)
        b = LeaderElector(kube, "b", clock=clock, lease_duration_s=15)
        assert a.try_acquire_or_renew()
        assert not b.try_acquire_or_renew()  # lease held and fresh
        assert not b.is_leader()
        # leader dies (stops renewing); standby must take over once the TTL
        # elapses, not before
        clock.step(14)
        assert not b.try_acquire_or_renew()
        clock.step(2)  # now expired
        assert b.try_acquire_or_renew()
        assert b.is_leader()
        # the late old leader notices the steal and demotes
        assert not a.try_acquire_or_renew()
        assert not a.is_leader()

    def test_graceful_release_hands_over_immediately(self):
        kube, clock = KubeStore(), FakeClock()
        a = LeaderElector(kube, "a", clock=clock)
        b = LeaderElector(kube, "b", clock=clock)
        assert a.try_acquire_or_renew()
        a.release()
        assert not a.is_leader()
        assert b.try_acquire_or_renew()  # no TTL wait
        assert b.is_leader()

    def test_concurrent_candidates_elect_exactly_one(self):
        kube, clock = KubeStore(), FakeClock()
        electors = [LeaderElector(kube, f"c{i}", clock=clock) for i in range(8)]
        barrier = threading.Barrier(len(electors))
        results = [None] * len(electors)

        def tick(i):
            barrier.wait()
            results[i] = electors[i].try_acquire_or_renew()

        threads = [threading.Thread(target=tick, args=(i,))
                   for i in range(len(electors))]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert sum(results) == 1
        assert sum(e.is_leader() for e in electors) == 1

    def test_release_does_not_clobber_successor(self):
        kube, clock = KubeStore(), FakeClock()
        a = LeaderElector(kube, "a", clock=clock, lease_duration_s=5)
        b = LeaderElector(kube, "b", clock=clock, lease_duration_s=5)
        assert a.try_acquire_or_renew()
        clock.step(6)  # a expired; b steals
        assert b.try_acquire_or_renew()
        a.release()  # late release must not delete b's lease
        lease = kube.get("leases", a.name)
        assert lease is not None and lease.holder == "b"

    def test_release_after_error_path_demotion_still_deletes_lease(self):
        """Regression: a store hiccup mid-renewal demotes the elector and
        clears `_held` while OUR lease object survives in the store. A
        release() gated on `_held` would early-return and strand that lease,
        forcing the standby to wait out the full TTL on what should be a
        graceful handoff."""
        kube, clock = KubeStore(), FakeClock()
        a = LeaderElector(kube, "a", clock=clock)
        b = LeaderElector(kube, "b", clock=clock)
        assert a.try_acquire_or_renew()
        a._demote_if_leading("simulated election error")
        assert a._held is None
        assert kube.get("leases", a.name).holder == "a"  # still ours in store
        a.release()
        assert kube.get("leases", a.name) is None  # deleted, not stranded
        assert b.try_acquire_or_renew()  # standby flips with no TTL wait
        assert b.is_leader()

    def test_epochs_strictly_increase_across_leadership_changes(self):
        kube, clock = KubeStore(), FakeClock()
        a = LeaderElector(kube, "a", clock=clock, lease_duration_s=5)
        b = LeaderElector(kube, "b", clock=clock, lease_duration_s=5)
        assert a.try_acquire_or_renew()
        e1 = a.fencing_token()
        assert e1 == 1
        clock.step(1)
        assert a.try_acquire_or_renew()  # renewal keeps the epoch
        assert a.fencing_token() == e1
        clock.step(6)  # a expired; takeover mints a fresh epoch
        assert b.try_acquire_or_renew()
        e2 = b.fencing_token()
        assert e2 > e1
        # graceful release DELETES the lease, so the next epoch must come
        # from the store's fence high-water mark, not the (gone) lease
        b.release()
        assert b.fencing_token() is None
        assert a.try_acquire_or_renew()
        assert a.fencing_token() > e2


class TestOperatorHA:
    def _mk_op(self, kube, identity):
        clock = Clock()
        cloud = FakeCloud(catalog=catalog(), clock=clock)
        settings = Settings(cluster_name="ha", cluster_endpoint="https://k",
                            batch_idle_duration=0.02, batch_max_duration=0.1)
        op = Operator(cloud, settings, catalog(), kube=kube, clock=clock,
                      leader_elect=True, identity=identity)
        # fast lease for the test
        op.leader.lease_duration_s = 1.2
        op.leader.renew_period_s = 0.15
        op.leader.retry_period_s = 0.1
        prov = Provisioner(name="default", provider_ref="default")
        prov.set_defaults()
        return op

    def test_kill_the_leader_standby_takes_over(self):
        kube = KubeStore()
        kube.create("nodetemplates", "default", NodeTemplate(
            name="default", subnet_selector={"id": "subnet-zone-1a"},
            security_group_selector={"id": "sg-default"}))
        a = self._mk_op(kube, "op-a")
        b = self._mk_op(kube, "op-b")
        for op in (a, b):
            op.cloudprovider.register_nodetemplate(
                kube.get("nodetemplates", "default"))
        prov = Provisioner(name="default", provider_ref="default")
        prov.set_defaults()
        kube.create("provisioners", "default", prov)
        try:
            a.start()
            b.start()
            deadline = time.monotonic() + 5
            while time.monotonic() < deadline and not (
                    a.elected.is_set() or b.elected.is_set()):
                time.sleep(0.02)
            leaders = [op for op in (a, b) if op.elected.is_set()]
            assert len(leaders) == 1, "exactly one replica must lead"
            leader, standby = leaders[0], (b if leaders[0] is a else a)

            # the leader (and only the leader) schedules the first pod
            kube.create("pods", "p1", make_pod("p1", cpu="1", memory="1Gi"))
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline and kube.pending_pods():
                time.sleep(0.05)
            assert not kube.pending_pods()
            machines_after_p1 = len(kube.machines())
            assert machines_after_p1 == 1  # exactly one actor provisioned
            assert len(standby.cluster.nodes) == 0  # standby stayed idle

            # HARD-kill the leader: no graceful release, lease left dangling
            leader.leader.release = lambda: None
            leader.stop()

            # standby must take over within the lease TTL (+renew slack)
            deadline = time.monotonic() + leader.leader.lease_duration_s + 3
            while time.monotonic() < deadline and not standby.elected.is_set():
                time.sleep(0.02)
            assert standby.elected.is_set(), "standby failed to take over"

            # the new leader schedules the next pod; still exactly one actor
            kube.create("pods", "p2", make_pod("p2", cpu="1", memory="1Gi"))
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline and kube.pending_pods():
                time.sleep(0.05)
            assert not kube.pending_pods()
            # the new leader ADOPTED the dead leader's capacity on takeover
            # (machine hydration + recovery replay run before its first
            # cycle): p2 lands in the surviving node's spare room instead of
            # double-launching a second machine
            assert len(kube.machines()) == machines_after_p1
            p2 = kube.get("pods", "p2")
            assert p2 is not None and p2.node_name
        finally:
            a.stop()
            b.stop()
