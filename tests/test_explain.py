"""Decision-provenance plane (ISSUE 14): explain ring, mask attribution,
oracle parity, strict-noop, and the /debug/decisions + CLI surfaces.

Tier-1 pieces: the attribution/oracle parity audit on a seeded workload
mix (the clause strings must match with ``==`` — reasons.CLAUSES is
lint-locked to diagnose_unschedulable's literals), ranked-summary
determinism, the strict-noop contract while the plane is disabled (the
chaos ``explain-strict-noop`` invariant diffs the same counters), the
HTTP listing-param discipline shared with /debug/traces (200/400/404 +
clamp), the statusz schema-8 ``decisions`` section, and the
``explain <pod>`` CLI verdict.
"""

import json
import types
import urllib.error
import urllib.request

import pytest

from karpenter_tpu import explain
from karpenter_tpu.apis.provisioner import Provisioner
from karpenter_tpu.apis import wellknown as wk
from karpenter_tpu.explain.records import DecisionRing
from karpenter_tpu.introspect import statusz
from karpenter_tpu.models.encode import (build_grid, diagnose_unschedulable,
                                         kubelet_arrays)
from karpenter_tpu.models.instancetype import Catalog, make_instance_type
from karpenter_tpu.models.pod import Taint, Toleration, make_pod


def _catalog():
    return Catalog(types=[
        make_instance_type("m.large", cpu=4, memory="16Gi",
                           od_price=0.2, spot_price=0.07),
        make_instance_type("m.xlarge", cpu=8, memory="32Gi",
                           od_price=0.4, spot_price=0.11)])


def _provisioners():
    """Two provisioners, BOTH tainted — untolerating pods are genuinely
    taint-blocked (the explain-drill problem shape at test size)."""
    taint = (Taint(key="team", value="infra"),)
    provs = []
    for name in ("tainted-a", "tainted-b"):
        p = Provisioner(name=name, taints=taint)
        p.set_defaults()
        provs.append(p)
    return provs


TOL = (Toleration(key="team", operator="Exists"),)


def _category_pod(cat: str, i: int, rng):
    if cat == "taints":  # schedulable but for the taint
        return make_pod(f"t-{i}", cpu=f"{rng.choice((100, 250, 500))}m",
                        memory="256Mi")
    if cat == "requirements":  # selector names a type nobody sells
        return make_pod(f"r-{i}", cpu="250m", memory="256Mi",
                        tolerations=TOL,
                        node_selector={wk.LABEL_INSTANCE_TYPE:
                                       f"absent.{rng.randint(0, 9)}"})
    if cat == "resources":  # bigger than the largest type
        return make_pod(f"b-{i}", cpu=str(rng.choice((16, 64, 128))),
                        memory="1Gi", tolerations=TOL)
    # "constraints": admissible — the oracle's residual clause
    return make_pod(f"c-{i}", cpu="250m", memory="256Mi", tolerations=TOL)


class TestOracleParity:
    def test_parity_on_seeded_workload(self):
        import random

        rng = random.Random(1307)
        catalog, provs = _catalog(), _provisioners()
        grid = build_grid(catalog)
        kub = kubelet_arrays(provs, catalog)
        cats = ("taints", "requirements", "resources", "constraints")
        for i in range(40):
            cat = cats[i % len(cats)]
            pod = _category_pod(cat, i, rng)
            oracle = diagnose_unschedulable(pod, provs, catalog,
                                            grid=grid, kubelet=kub)
            verdict = explain.attribute_pod(pod, provs, catalog,
                                            grid=grid, kubelet=kub)
            assert verdict["reason"] == oracle, (cat, pod.name)
            assert verdict["dimension"] == cat
            assert verdict["reason"] == explain.clause_for(cat)

    def test_ranked_summary_deterministic(self):
        import random

        catalog, provs = _catalog(), _provisioners()
        pod = _category_pod("resources", 0, random.Random(7))
        a = explain.attribute_pod(pod, provs, catalog)
        b = explain.attribute_pod(pod, provs, catalog)
        assert a == b
        # the dominant dimension comes from the oracle's stage walk, not
        # the raw counts (the default capacity-type fold rejects more)
        assert a["dimension"] == "resources"
        assert a["counts"]["resources"] > 0
        assert "nearest fit short by" in a["summary"]
        assert a["nearest"]["resource"] == wk.RESOURCE_CPU

    def test_counts_cover_the_candidate_lattice(self):
        catalog, provs = _catalog(), _provisioners()
        pod = make_pod("lone", cpu="250m", memory="256Mi", tolerations=TOL)
        v = explain.attribute_pod(pod, provs, catalog)
        assert sum(v["counts"].values()) == v["candidates"]
        assert set(v["counts"]) == set(explain.DIMENSIONS)


class TestDecisionRing:
    def test_strict_noop_when_disabled(self):
        ring = DecisionRing(maxlen=8)
        with explain.disabled():
            before = ring.activity()
            assert ring.emit("provisioning", {"nodes": 1}) is None
            ring.note_attribution(0.001, "resources")
            assert explain.note_shed("tenant-a", "queue",
                                     "deadline") is None
            assert ring.activity() == before
        assert before["records_total"] == 0 and before["ring"] == 0

    def test_ring_bounded_with_monotonic_ids(self):
        ring = DecisionRing(maxlen=3)
        ids = [ring.emit("provisioning", {"n": i}, ts=float(i))
               for i in range(5)]
        assert ids == [f"d-{i}" for i in range(5)]
        assert ring.ring_len() == 3
        assert [r["n"] for r in ring.records()] == [2, 3, 4]  # oldest out
        assert ring.activity()["records_total"] == 5
        assert ring.get("d-0") is None and ring.get("d-4")["n"] == 4

    def test_kind_filter_and_limit(self):
        ring = DecisionRing(maxlen=16)
        for i in range(4):
            ring.emit("provisioning", {"n": i}, ts=float(i))
        ring.emit("consolidation", {"n": 99}, ts=9.0)
        assert len(ring.records(kind="consolidation")) == 1
        assert [r["n"] for r in ring.records(limit=2)] == [3, 99]
        act = ring.activity()
        assert act["consolidations_total"] == 1
        assert act["sheds_total"] == 0

    def test_find_pod_prefers_newest(self):
        ring = DecisionRing(maxlen=8)
        ring.emit("provisioning",
                  {"assignments": [{"pods": ["web-1", "web-2"]}],
                   "unassigned": []}, ts=1.0)
        ring.emit("provisioning",
                  {"assignments": [],
                   "unassigned": [{"pod": "web-2", "reason": "x"}]},
                  ts=2.0)
        assert ring.find_pod("web-1")["ts"] == 1.0
        assert ring.find_pod("web-2")["ts"] == 2.0  # newest wins
        assert ring.find_pod("nope") is None

    def test_note_shed_cites_vocabulary(self):
        ring_before = explain.DECISIONS.activity()["sheds_total"]
        rid = explain.note_shed("tenant-a", "admission", "deadline", ts=1.0)
        try:
            assert rid is not None
            rec = explain.DECISIONS.get(rid)
            assert rec["kind"] == "shed"
            assert rec["reason"] in explain.SHED_REASONS
            assert rec["where"] == "admission"
            act = explain.DECISIONS.activity()
            assert act["sheds_total"] == ring_before + 1
        finally:
            explain.DECISIONS.clear()

    def test_snapshot_shape(self):
        doc = explain.snapshot()
        assert doc["schema"] == explain.SCHEMA_VERSION
        assert doc["enabled"] is True
        assert doc["dimensions"] == list(explain.DIMENSIONS)
        assert {"records_total", "attributions_total", "sheds_total",
                "consolidations_total", "ring_depth",
                "recent"} <= set(doc)
        json.dumps(doc, default=str)  # statusz embeds it: must serialize


class TestConsolidationVerdicts:
    def test_note_verdict_shape_and_vocabulary(self):
        from karpenter_tpu.ops import consolidate

        node = types.SimpleNamespace(name="node-a", price=0.25)
        capture = []
        consolidate._note_verdict(capture, [node], "delete", savings=0.25)
        consolidate._note_verdict(capture, [node], "no-cheaper-option")
        (evict, keep) = capture
        assert evict["verdict"] in explain.CONSOLIDATION_VERDICTS
        assert evict["evict"] is True and keep["evict"] is False
        assert evict["savings_per_hour"] == 0.25
        assert evict["cost_delta_per_hour"] == -0.25
        assert keep["nodes"] == ["node-a"]


@pytest.fixture
def server():
    from karpenter_tpu.apis.nodetemplate import NodeTemplate
    from karpenter_tpu.apis.settings import Settings
    from karpenter_tpu.fake.cloud import FakeCloud
    from karpenter_tpu.operator import Operator
    from karpenter_tpu.utils.clock import FakeClock

    clock = FakeClock()
    op = Operator(FakeCloud(catalog=_catalog(), clock=clock),
                  Settings(cluster_name="explain",
                           cluster_endpoint="https://explain"),
                  _catalog(), clock=clock, serve_http=True,
                  metrics_port=0, health_port=0, webhook_port=-1)
    op.kube.create("nodetemplates", "default", NodeTemplate(
        name="default",
        subnet_selector={"id": "subnet-zone-1a"},
        security_group_selector={"id": "sg-default"}))
    op.cloudprovider.register_nodetemplate(
        op.kube.get("nodetemplates", "default"))
    prov = Provisioner(name="default", provider_ref="default")
    prov.set_defaults()
    op.kube.create("provisioners", "default", prov)
    ports = op.serving.start()
    try:
        yield op, ports
    finally:
        op.serving.stop()
        op.stop()
        explain.DECISIONS.clear()


def _get(url):
    with urllib.request.urlopen(url, timeout=5) as r:
        return r.status, json.loads(r.read())


class TestDebugDecisionsEndpoint:
    def test_index_detail_pod_lookup(self, server):
        op, ports = server
        rid = explain.DECISIONS.emit(
            "provisioning",
            {"trace_id": "t-1",
             "assignments": [{"pods": ["web-1"], "itype": "m.large",
                              "zone": "zone-1a", "capacity_type":
                              "on-demand", "provisioner": "default",
                              "price": 0.2}],
             "unassigned": [{"pod": "web-9",
                             "reason": explain.clause_for("resources"),
                             "summary": "s", "ranked": ["resources"]}]},
            ts=1.0)
        base = f"http://127.0.0.1:{ports['metrics']}/debug/decisions"
        status, doc = _get(base)
        assert status == 200
        assert doc["schema"] == explain.SCHEMA_VERSION
        assert doc["enabled"] is True
        assert any(d["id"] == rid for d in doc["decisions"])
        status, rec = _get(f"{base}?id={rid}")
        assert status == 200 and rec["trace_id"] == "t-1"
        status, rec = _get(f"{base}?pod=web-9")
        assert status == 200 and rec["id"] == rid
        status, doc = _get(f"{base}?kind=shed")
        assert status == 200 and doc["decisions"] == []

    def test_unknown_id_and_pod_404(self, server):
        op, ports = server
        base = f"http://127.0.0.1:{ports['metrics']}/debug/decisions"
        for q in ("?id=d-99999", "?pod=absent-pod"):
            with pytest.raises(urllib.error.HTTPError) as e:
                _get(base + q)
            assert e.value.code == 404

    def test_malformed_limit_400_and_clamp(self, server):
        op, ports = server
        base = f"http://127.0.0.1:{ports['metrics']}/debug/decisions"
        with pytest.raises(urllib.error.HTTPError) as e:
            _get(f"{base}?limit=abc")
        assert e.value.code == 400
        for i in range(3):
            explain.DECISIONS.emit("provisioning", {"n": i}, ts=float(i))
        status, doc = _get(f"{base}?limit=999999")  # clamped, not rejected
        assert status == 200 and len(doc["decisions"]) <= 256
        status, doc = _get(f"{base}?limit=1")
        assert status == 200 and len(doc["decisions"]) == 1

    def test_eventz_param_discipline(self, server):
        op, ports = server
        base = f"http://127.0.0.1:{ports['health']}/eventz"
        with pytest.raises(urllib.error.HTTPError) as e:
            _get(f"{base}?n=abc")
        assert e.value.code == 400
        status, doc = _get(f"{base}?n=999999")  # clamps to the ring bound
        assert status == 200 and "events" in doc

    def test_bundle_decisions_param(self, server):
        op, ports = server
        op.reconcile_all_once()
        for i in range(5):
            explain.DECISIONS.emit("provisioning", {"n": i}, ts=float(i))
        base = f"http://127.0.0.1:{ports['metrics']}/debug/bundle"
        with pytest.raises(urllib.error.HTTPError) as e:
            _get(f"{base}?decisions=abc")
        assert e.value.code == 400
        status, b = _get(f"{base}?decisions=2")
        assert status == 200
        assert len(b["decisions"]["records"]) == 2
        assert b["decisions"]["schema"] == explain.SCHEMA_VERSION

    def test_statusz_carries_decisions_section(self, server):
        op, ports = server
        status, snap = _get(
            f"http://127.0.0.1:{ports['metrics']}/debug/statusz")
        assert status == 200
        assert snap["schema"] == statusz.SCHEMA_VERSION
        assert snap["decisions"]["dimensions"] == list(explain.DIMENSIONS)


class TestExplainCLI:
    def _args(self, ports, **kw):
        base = dict(pod=None, id=None, limit=20, json=False,
                    endpoint=f"http://127.0.0.1:{ports['metrics']}")
        base.update(kw)
        return types.SimpleNamespace(**base)

    def test_unschedulable_verdict(self, server, capsys):
        from karpenter_tpu.__main__ import cmd_explain

        op, ports = server
        explain.DECISIONS.emit(
            "provisioning",
            {"assignments": [],
             "unassigned": [{"pod": "web-9",
                             "reason": explain.clause_for("resources"),
                             "summary": "3 of 4 candidates rejected",
                             "ranked": list(explain.DIMENSIONS),
                             "nearest": {"display": "1.2 cores (cpu)"},
                             "parity": True}]}, ts=1.0)
        assert cmd_explain(self._args(ports, pod="web-9")) == 0
        out = capsys.readouterr().out
        assert "UNSCHEDULABLE" in out
        assert explain.clause_for("resources") in out
        assert "short by 1.2 cores" in out

    def test_assigned_verdict_and_index(self, server, capsys):
        from karpenter_tpu.__main__ import cmd_explain

        op, ports = server
        explain.DECISIONS.emit(
            "provisioning",
            {"assignments": [{"pods": ["web-1"], "itype": "m.large",
                              "zone": "zone-1a",
                              "capacity_type": "on-demand",
                              "provisioner": "default", "price": 0.2}],
             "unassigned": []}, ts=1.0)
        assert cmd_explain(self._args(ports, pod="web-1")) == 0
        assert "ASSIGNED" in capsys.readouterr().out
        assert cmd_explain(self._args(ports)) == 0  # index mode
        doc = json.loads(capsys.readouterr().out)
        assert doc["schema"] == explain.SCHEMA_VERSION

    def test_unknown_pod_is_an_error(self, server, capsys):
        from karpenter_tpu.__main__ import cmd_explain

        op, ports = server
        assert cmd_explain(self._args(ports, pod="ghost")) == 1
        assert "ghost" in capsys.readouterr().err


class TestProvisioningDecisions:
    def test_solve_emits_record_with_attribution(self):
        """End-to-end through the controller: an unschedulable pod's
        FailedScheduling diagnosis lands in a DecisionRecord with the
        parity bit set."""
        from karpenter_tpu.apis.nodetemplate import NodeTemplate
        from karpenter_tpu.apis.settings import Settings
        from karpenter_tpu.fake.cloud import FakeCloud
        from karpenter_tpu.operator import Operator
        from karpenter_tpu.utils.clock import FakeClock

        clock = FakeClock()
        op = Operator(FakeCloud(catalog=_catalog(), clock=clock),
                      Settings(cluster_name="explain",
                               cluster_endpoint="https://explain"),
                      _catalog(), clock=clock)
        try:
            op.kube.create("nodetemplates", "default", NodeTemplate(
                name="default",
                subnet_selector={"id": "subnet-zone-1a"},
                security_group_selector={"id": "sg-default"}))
            op.cloudprovider.register_nodetemplate(
                op.kube.get("nodetemplates", "default"))
            prov = Provisioner(name="default", provider_ref="default")
            prov.set_defaults()
            op.kube.create("provisioners", "default", prov)
            op.kube.create("pods", "ok-1",
                           make_pod("ok-1", cpu="1", memory="1Gi"))
            op.kube.create("pods", "huge-1",
                           make_pod("huge-1", cpu="64", memory="1Gi"))
            op.reconcile_all_once()
        finally:
            op.stop()
        recs = explain.DECISIONS.records(kind="provisioning")
        try:
            assert recs, "no provisioning DecisionRecord emitted"
            rec = recs[-1]
            assert rec["dimensions"] == list(explain.DIMENSIONS)
            (u,) = [u for u in rec["unassigned"] if u["pod"] == "huge-1"]
            assert u["parity"] is True
            assert u["reason"] == explain.clause_for("resources")
            assert explain.DECISIONS.find_pod("huge-1")["id"] == rec["id"]
            assert explain.DECISIONS.find_pod("ok-1")["id"] == rec["id"]
        finally:
            explain.DECISIONS.clear()
