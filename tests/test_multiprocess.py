"""TRUE multi-process distributed solve: two OS processes join a
jax.distributed mesh (Gloo collectives over the DCN analogue) and run the
sharded packer with the nodes axis crossing hosts.

Parity target: SURVEY §2.3/§5.8 — the reference's multi-host story is
NCCL/MPI-backed scale-out; here it is jax.distributed + GSPMD collectives
with the hybrid (nodes x types) mesh (parallel/multihost.py). The
single-process tier (tests/test_sharded.py) covers bit-parity; this tier
proves the actual cross-process path boots, shards, and agrees.
"""

import json
import os
import socket
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_WORKER = r'''
import os, sys, json
sys.path.insert(0, os.environ["KT_REPO"])
from karpenter_tpu.utils.jaxenv import pin_cpu
jax = pin_cpu(4)
from karpenter_tpu.parallel.multihost import (initialize_distributed,
                                              make_hybrid_mesh,
                                              mesh_description)
ok = initialize_distributed(os.environ["KT_COORD"], 2,
                            int(os.environ["KT_PID"]))
mesh = make_hybrid_mesh()
desc = mesh_description(mesh)
import numpy as np
from jax.experimental import multihost_utils
from karpenter_tpu.parallel.sharded import sharded_pack
import importlib.util
spec = importlib.util.spec_from_file_location(
    "ge", os.path.join(os.environ["KT_REPO"], "__graft_entry__.py"))
ge = importlib.util.module_from_spec(spec)
spec.loader.exec_module(ge)
enc = ge._example_problem(n_pods=32, n_types=8)
inputs, n_slots = ge._pad_inputs(enc)
result = sharded_pack(inputs, n_slots, mesh)
assign = np.asarray(multihost_utils.process_allgather(result.assign, tiled=True))
ex = np.asarray(multihost_utils.process_allgather(result.ex_assign, tiled=True))
unsched = np.asarray(multihost_utils.process_allgather(result.unsched, tiled=True))
decided = np.asarray(multihost_utils.process_allgather(result.decided, tiled=True))
print("WORKER_OK " + json.dumps({
    "pid": int(os.environ["KT_PID"]), "multi": bool(ok), "desc": desc,
    "placed": int(assign.sum()) + int(ex.sum()),
    "unsched": int(unsched.sum()),
    "decided": decided.tolist(),
}), flush=True)
'''


def test_two_process_distributed_sharded_pack():
    # bounded by the workers' communicate(timeout=240); no plugin needed
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()

    procs = []
    for pid in range(2):
        env = dict(os.environ, JAX_PLATFORMS="cpu", KT_REPO=REPO,
                   KT_COORD=f"127.0.0.1:{port}", KT_PID=str(pid))
        env.pop("PALLAS_AXON_POOL_IPS", None)  # never grab the real chip
        env.pop("XLA_FLAGS", None)  # worker pins its own 4-device count
        procs.append(subprocess.Popen(
            [sys.executable, "-c", _WORKER], env=env, cwd=REPO,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True))
    outs = [p.communicate(timeout=240)[0] for p in procs]
    results = []
    for o in outs:
        lines = [l for l in o.splitlines() if l.startswith("WORKER_OK ")]
        assert lines, f"worker died:\n{o[-1500:]}"
        results.append(json.loads(lines[-1][len("WORKER_OK "):]))

    for r in results:
        assert r["multi"] is True
        assert r["desc"]["n_processes"] == 2
        assert r["desc"]["n_devices"] == 8
        assert r["desc"]["axes"] == {"nodes": 4, "types": 2}
        # inter-host hops ride the nodes (DCN) axis, types stays intra-host
        assert r["desc"]["nodes_axis_spans_processes"] is True
        assert r["desc"]["types_axis_crosses_hosts"] is False
        assert r["placed"] == 32 and r["unsched"] == 0
    # both processes computed the identical global decision
    assert results[0]["decided"] == results[1]["decided"]
