"""Queue client boundary: RemoteQueueProvider URL lifecycle + the
interruption controller running against the interface.

Parity target: /root/reference/pkg/controllers/interruption/sqs.go:33-148
(lazy queue-URL discovery, name-change invalidation, stale-URL recovery).
"""

import json

from karpenter_tpu.controllers.interruption import InterruptionController
from karpenter_tpu.controllers.interruption.queues import (
    FakeQueue, QueueMessage, QueueNotFound, QueueProvider, RemoteQueueProvider)


class BrokerFake:
    """Low-level QueueAPI fake: named queues with URLs, counting discovery
    calls; deleting a queue makes its old URL raise QueueNotFound."""

    def __init__(self):
        self.queues: "dict[str, str]" = {}       # name -> url
        self.messages: "dict[str, list[QueueMessage]]" = {}  # url -> msgs
        self.url_lookups = 0
        self._gen = 0

    def create_queue(self, name: str) -> str:
        self._gen += 1
        url = f"https://broker.example/{name}-{self._gen}"
        self.queues[name] = url
        self.messages[url] = []
        return url

    def drop_queue(self, name: str) -> None:
        url = self.queues.pop(name, None)
        if url:
            self.messages.pop(url, None)

    # -- QueueAPI ------------------------------------------------------------

    def get_queue_url(self, name: str) -> str:
        self.url_lookups += 1
        if name not in self.queues:
            raise QueueNotFound(name)
        return self.queues[name]

    def send_message(self, queue_url: str, body: str) -> None:
        if queue_url not in self.messages:
            raise QueueNotFound(queue_url)
        r = f"r-{len(self.messages[queue_url])}"
        self.messages[queue_url].append(QueueMessage(body=body, receipt=r))

    def receive_message(self, queue_url, max_messages, wait_seconds):
        if queue_url not in self.messages:
            raise QueueNotFound(queue_url)
        out = self.messages[queue_url][:max_messages]
        return list(out)

    def delete_message(self, queue_url: str, receipt: str) -> None:
        if queue_url not in self.messages:
            raise QueueNotFound(queue_url)
        self.messages[queue_url] = [
            m for m in self.messages[queue_url] if m.receipt != receipt]


def test_url_discovered_lazily_and_cached():
    broker = BrokerFake()
    broker.create_queue("iq")
    q = RemoteQueueProvider(broker, "iq")
    assert broker.url_lookups == 0  # nothing resolved at construction
    q.send("hello")
    assert broker.url_lookups == 1
    q.send("again")
    (m1, m2) = q.receive(max_messages=10)
    assert broker.url_lookups == 1  # cached across calls
    assert (m1.body, m2.body) == ("hello", "again")
    q.delete(m1.receipt)
    assert [m.body for m in q.receive()] == ["again"]


def test_name_change_invalidates_url():
    broker = BrokerFake()
    broker.create_queue("old")
    broker.create_queue("new")
    name = {"v": "old"}
    q = RemoteQueueProvider(broker, lambda: name["v"])
    q.send("to-old")
    assert broker.url_lookups == 1
    name["v"] = "new"  # live settings change
    q.send("to-new")
    assert broker.url_lookups == 2  # re-discovered for the new name
    assert [m.body for m in broker.messages[broker.queues["new"]]] == ["to-new"]
    assert [m.body for m in broker.messages[broker.queues["old"]]] == ["to-old"]


def test_stale_url_recovers_once():
    broker = BrokerFake()
    broker.create_queue("iq")
    q = RemoteQueueProvider(broker, "iq")
    q.send("a")
    # queue deleted + recreated under us: the cached URL is now dead
    broker.drop_queue("iq")
    broker.create_queue("iq")
    q.send("b")  # QueueNotFound -> invalidate -> re-discover -> retry
    assert [m.body for m in q.receive()] == ["b"]


def test_missing_queue_raises_after_rediscovery():
    broker = BrokerFake()
    broker.create_queue("iq")
    q = RemoteQueueProvider(broker, "iq")
    q.send("a")
    broker.drop_queue("iq")  # gone for good
    try:
        q.send("b")
        raise AssertionError("expected QueueNotFound")
    except QueueNotFound:
        pass


def test_both_impls_satisfy_the_protocol():
    broker = BrokerFake()
    broker.create_queue("iq")
    assert isinstance(FakeQueue("iq"), QueueProvider)
    assert isinstance(RemoteQueueProvider(broker, "iq"), QueueProvider)


def test_controller_runs_against_remote_provider():
    # the controller only sees the QueueProvider interface: a parse->noop
    # cycle against the remote stub must receive, count, and delete
    from karpenter_tpu.models.cluster import ClusterState
    from karpenter_tpu.fake.kube import KubeStore

    broker = BrokerFake()
    broker.create_queue("iq")
    q = RemoteQueueProvider(broker, "iq")

    class NoIce:
        def mark_unavailable(self, *a, **kw): pass

    ctrl = InterruptionController(KubeStore(), ClusterState(), q, NoIce())
    q.send(json.dumps({"source": "cloud.spot",
                       "detail-type": "Spot Instance Interruption Warning",
                       "detail": {"instance-id": "i-404"}}))
    handled = ctrl.reconcile_once()
    assert handled == 1
    assert q.receive() == []  # deleted after handling
    ctrl.stop()


def test_drain_throughput_recorded_per_batch():
    # the per-batch msgs/s histogram is the attribution signal for queue
    # throughput regressions: one observation per non-empty receive batch
    from karpenter_tpu.fake.kube import KubeStore
    from karpenter_tpu.metrics import Registry
    from karpenter_tpu.models.cluster import ClusterState

    class NoIce:
        def mark_unavailable(self, *a, **kw): pass

    reg = Registry()
    q = FakeQueue("iq")
    ctrl = InterruptionController(KubeStore(), ClusterState(), q, NoIce(),
                                  registry=reg)
    assert ctrl.reconcile_once() == 0      # empty poll: no observation
    assert ctrl.drain_throughput.count(reason="reactive-reclaim") == 0
    for i in range(7):
        q.send(json.dumps({"source": "cloud.spot",
                           "detail-type": "Spot Instance Interruption Warning",
                           "detail": {"instance-id": f"i-{i}"}}))
    assert ctrl.reconcile_once() == 7
    # one batch, one observation, attributed to the platform-forced reason
    assert ctrl.drain_throughput.count(reason="reactive-reclaim") == 1
    assert ctrl.drain_throughput.sum(reason="reactive-reclaim") > 0
    ctrl.stop()
