"""Resilience plane: retry budgets, breakers, deadlines, ladders (ISSUE 4).

Everything here runs on FakeClock with injected sleep — no wall-clock
waits, no `random` module: backoff jitter must replay byte-identically
from its seed (the chaos determinism contract), and the breaker/ladder
FSMs are stepped through virtual time. The last classes close the loop:
the chaos invariants must PASS on honest evidence and FAIL on corrupted
evidence (a safety net that can't catch anything is worse than none), and
the fixed burst schedule must actually exercise the plane end to end.
"""

import pytest

from karpenter_tpu.chaos import invariants
from karpenter_tpu.chaos.plan import (KIND_CLOUD_5XX, KIND_SOLVER_CRASH,
                                      FaultPlan)
from karpenter_tpu.metrics import Registry
from karpenter_tpu.resilience import (BreakerOpen, CircuitBreaker,
                                      DegradeLadder, ResilienceHub,
                                      RetryBudget, RetryPolicy, deadline)
from karpenter_tpu.utils.clock import FakeClock


class Recorder:
    """EventRecorder stand-in capturing (kind, ref, reason) tuples."""

    def __init__(self):
        self.events = []

    def warning(self, ref, reason, msg):
        self.events.append(("Warning", ref, reason, msg))

    def normal(self, ref, reason, msg):
        self.events.append(("Normal", ref, reason, msg))

    def reasons(self):
        return [e[2] for e in self.events]


def make_policy(dep="cloud", sleeps=None, **kw):
    kw.setdefault("registry", Registry())
    kw.setdefault("clock", FakeClock())
    return RetryPolicy(
        dep, sleep=(sleeps.append if sleeps is not None else lambda s: None),
        **kw)


class TestJitterDeterminism:
    def test_same_seed_same_sequence(self):
        a = make_policy(seed=7)
        b = make_policy(seed=7)
        assert [a.next_backoff() for _ in range(16)] \
            == [b.next_backoff() for _ in range(16)]

    def test_different_seeds_differ(self):
        a = make_policy(seed=1)
        b = make_policy(seed=2)
        assert [a.next_backoff() for _ in range(8)] \
            != [b.next_backoff() for _ in range(8)]

    def test_different_deps_get_independent_streams(self):
        a = make_policy(dep="cloud", seed=0)
        b = make_policy(dep="kube", seed=0)
        assert [a.next_backoff() for _ in range(8)] \
            != [b.next_backoff() for _ in range(8)]

    def test_backoff_bounded_by_base_and_cap(self):
        pol = make_policy(seed=3, base=0.05, cap=5.0)
        delays = [pol.next_backoff() for _ in range(200)]
        assert all(0.05 <= d <= 5.0 for d in delays)
        # decorrelated jitter must actually spread, not degenerate
        assert len({round(d, 9) for d in delays}) > 100

    def test_success_resets_backoff_growth(self):
        pol = make_policy(seed=5)
        for _ in range(6):
            pol.next_backoff()
        pol.note_success()
        assert pol._prev == pol.base


class TestRetryBudget:
    def test_budget_exhaustion_turns_retries_into_give_up(self):
        reg = Registry()
        budget = RetryBudget(capacity=2.0, refill_per_success=0.2)
        sleeps = []
        pol = make_policy(budget=budget, max_attempts=10, registry=reg,
                          sleeps=sleeps)
        calls = []

        def boom():
            calls.append(1)
            raise ValueError("down")

        with pytest.raises(ValueError):
            pol.call(boom)
        # 1 initial + 2 budgeted retries, then an immediate give-up
        assert len(calls) == 3
        assert len(sleeps) == 2
        assert pol.retries_total.value(dep="cloud", outcome="retry") == 2
        assert pol.retries_total.value(dep="cloud",
                                       outcome="budget_exhausted") == 1
        assert pol.retries_total.value(dep="cloud", outcome="give_up") == 1
        ev = budget.evidence()
        assert ev["min_tokens"] >= 0
        assert ev["denied_total"] == 1

    def test_refill_never_exceeds_capacity(self):
        budget = RetryBudget(capacity=3.0, refill_per_success=1.0)
        for _ in range(10):
            budget.refill()
        assert budget.tokens() == 3.0

    def test_successes_slowly_earn_retries_back(self):
        budget = RetryBudget(capacity=1.0, refill_per_success=0.25)
        assert budget.try_spend()
        assert not budget.try_spend()
        for _ in range(4):
            budget.refill()
        assert budget.try_spend()

    def test_non_retriable_exceptions_pass_through_unspent(self):
        budget = RetryBudget(capacity=5.0)
        pol = make_policy(budget=budget)
        with pytest.raises(KeyError):
            pol.call(lambda: (_ for _ in ()).throw(KeyError("x")),
                     retriable=(ValueError,))
        assert budget.tokens() == 5.0

    def test_predicate_retriable_matches_by_code(self):
        class Err(RuntimeError):
            def __init__(self, code):
                self.code = code

        pol = make_policy(max_attempts=3)
        attempts = []

        def flaky():
            attempts.append(1)
            raise Err("Throttling" if len(attempts) < 2 else "Terminal")

        with pytest.raises(Err) as ei:
            pol.call(flaky, retriable=lambda e: e.code == "Throttling")
        assert ei.value.code == "Terminal"
        assert len(attempts) == 2


class TestBreakerFSM:
    def make(self, **kw):
        clock = FakeClock()
        rec = Recorder()
        br = CircuitBreaker("cloud", clock=clock, failure_threshold=3,
                            recovery_time=30.0, success_threshold=2,
                            recorder=rec, registry=Registry(), **kw)
        return br, clock, rec

    def test_trips_open_at_threshold(self):
        br, clock, rec = self.make()
        for _ in range(2):
            br.record_failure()
        assert br.state() == "closed"
        br.record_failure()
        assert br.state() == "open"
        assert rec.reasons() == ["BreakerOpened"]
        assert br.evidence()["max_closed_streak"] == 3

    def test_open_rejects_until_recovery_window(self):
        br, clock, rec = self.make()
        for _ in range(3):
            br.record_failure()
        assert not br.allow()
        assert not br.allow()
        assert br.snapshot()["rejected_total"] == 2
        clock.step(30.0)
        assert br.allow()  # the single half-open probe
        assert br.state() == "half-open"
        assert not br.allow()  # one probe at a time

    def test_failed_probe_reopens_and_rearms(self):
        br, clock, rec = self.make()
        for _ in range(3):
            br.record_failure()
        clock.step(30.0)
        assert br.allow()
        br.record_failure()
        assert br.state() == "open"
        assert not br.allow()  # full window re-armed
        clock.step(29.0)
        assert not br.allow()
        clock.step(1.0)
        assert br.allow()

    def test_probe_successes_close_at_threshold(self):
        br, clock, rec = self.make()
        for _ in range(3):
            br.record_failure()
        clock.step(30.0)
        assert br.allow()
        br.record_success()
        assert br.state() == "half-open"  # success_threshold=2
        assert br.allow()
        br.record_success()
        assert br.state() == "closed"
        assert rec.reasons() == ["BreakerOpened", "BreakerClosed"]

    def test_success_resets_closed_streak(self):
        br, clock, rec = self.make()
        br.record_failure()
        br.record_failure()
        br.record_success()
        br.record_failure()
        br.record_failure()
        assert br.state() == "closed"

    def test_transition_ledger_is_a_valid_fsm_walk(self):
        br, clock, rec = self.make()
        for _ in range(3):
            br.record_failure()
        clock.step(30.0)
        br.allow()
        br.record_failure()
        clock.step(30.0)
        br.allow()
        br.record_success()
        br.record_success()
        ev = br.evidence()
        assert not invariants.check_breaker_discipline({"breakers": {"cloud": ev}})
        assert ev["opened_total"] == 2
        assert ev["closed_total"] == 1

    def test_release_probe_unwedges_half_open(self):
        br, clock, rec = self.make()
        for _ in range(3):
            br.record_failure()
        clock.step(30.0)
        assert br.allow()  # probe admitted...
        br.release_probe()  # ...but the call said nothing about health
        assert br.state() == "half-open"
        assert br.allow()  # the NEXT call becomes the probe — not wedged

    def test_release_probe_noop_after_verdict(self):
        br, clock, rec = self.make()
        for _ in range(3):
            br.record_failure()
        clock.step(30.0)
        assert br.allow()
        br.record_failure()  # judged: probe failed, re-opened
        br.release_probe()   # late release must not disturb the verdict
        assert br.state() == "open"
        assert not br.allow()  # full recovery window still re-armed

    def test_non_retriable_error_resolves_half_open_probe(self):
        """A non-retriable exception racing the half-open window must not
        leave the probe in flight: every future allow() would then reject
        forever (no timeout escape from HALF_OPEN)."""
        reg = Registry()
        clock = FakeClock()
        br = CircuitBreaker("cloud", clock=clock, failure_threshold=1,
                            recovery_time=30.0, success_threshold=1,
                            registry=reg)
        pol = RetryPolicy("cloud", clock=clock, breaker=br, registry=reg,
                          sleep=lambda s: None)
        br.record_failure()  # open
        clock.step(30.0)     # recovery window elapses
        with pytest.raises(KeyError):  # business error admitted as probe
            pol.call(lambda: (_ for _ in ()).throw(KeyError("x")),
                     retriable=(ValueError,))
        calls = []
        pol.call(lambda: calls.append(1))  # would raise BreakerOpen if wedged
        assert calls
        assert br.state() == "closed"

    def test_policy_fails_fast_when_breaker_open(self):
        reg = Registry()
        clock = FakeClock()
        br = CircuitBreaker("cloud", clock=clock, failure_threshold=1,
                            registry=reg)
        pol = RetryPolicy("cloud", clock=clock, breaker=br, registry=reg,
                          sleep=lambda s: None)
        br.record_failure()
        calls = []
        with pytest.raises(BreakerOpen):
            pol.call(lambda: calls.append(1))
        assert not calls  # fail fast: the dependency was never touched
        assert pol.retries_total.value(dep="cloud",
                                       outcome="breaker_open") == 1


class TestDegradeLadder:
    def make(self, rungs=("primary", "fallback", "oracle")):
        clock = FakeClock()
        rec = Recorder()
        ld = DegradeLadder("solve", rungs, clock=clock, recorder=rec,
                           registry=Registry(), probe_interval_s=120.0)
        return ld, clock, rec

    def test_failure_degrades_one_rung_and_sticks(self):
        ld, clock, rec = self.make()
        assert ld.start_rung() == 0
        ld.record_failure(0)
        assert ld.rung() == 1
        assert ld.rung_name() == "fallback"
        # sticky: the broken best rung is NOT retried next cycle
        assert ld.start_rung() == 1
        assert rec.reasons() == ["DegradedTo"]

    def test_probe_after_interval_single_step_recovery(self):
        ld, clock, rec = self.make()
        ld.record_failure(0)
        ld.record_failure(1)
        assert ld.rung() == 2
        clock.step(120.0)
        assert ld.start_rung() == 1  # one rung up, not all the way
        ld.record_success(1)
        assert ld.rung() == 1
        clock.step(120.0)
        assert ld.start_rung() == 0
        ld.record_success(0)
        assert ld.rung() == 0
        assert rec.reasons() == ["DegradedTo", "DegradedTo",
                                 "RecoveredTo", "RecoveredTo"]

    def test_failed_probe_stays_put_and_rearms(self):
        ld, clock, rec = self.make()
        ld.record_failure(0)
        clock.step(120.0)
        assert ld.start_rung() == 0
        ld.record_failure(0)
        assert ld.rung() == 1
        assert ld.start_rung() == 1  # timer re-armed, no immediate re-probe
        clock.step(119.0)
        assert ld.start_rung() == 1
        clock.step(1.0)
        assert ld.start_rung() == 0

    def test_abort_probe_judges_nothing(self):
        ld, clock, rec = self.make()
        ld.record_failure(0)
        clock.step(120.0)
        assert ld.start_rung() == 0  # probe admitted...
        ld.abort_probe()             # ...but never ran (deadline expired)
        assert ld.rung() == 1
        assert ld.start_rung() == 1
        clock.step(120.0)
        assert ld.start_rung() == 0  # probing resumes later

    def test_success_above_current_rung_never_promotes(self):
        ld, clock, rec = self.make()
        ld.record_failure(0)
        ld.record_success(0)  # no probe admitted -> no promotion
        assert ld.rung() == 1

    def test_ledger_reasons_feed_the_monotone_invariant(self):
        ld, clock, rec = self.make()
        ld.record_failure(0)
        ld.record_failure(1)
        clock.step(120.0)
        ld.start_rung()
        ld.record_success(1)
        ev = ld.evidence()
        assert [t["reason"] for t in ev["transitions"]] \
            == ["failure", "failure", "probe-success"]
        assert not invariants.check_degrade_monotone({"ladders": {"solve": ev}})


class TestDeadline:
    def test_cycle_installs_and_clears_budget(self):
        clock = FakeClock()
        assert deadline.current() is None
        with deadline.cycle(clock, budget_s=60.0) as dl:
            assert deadline.current() is dl
            assert dl.remaining() == 60.0
        assert deadline.current() is None

    def test_expiry_after_clock_step(self):
        clock = FakeClock()
        with deadline.cycle(clock, budget_s=10.0) as dl:
            clock.step(9.0)
            assert not dl.expired()
            assert dl.remaining_ms() == 1000
            clock.step(2.0)
            assert dl.expired()
            assert dl.remaining_ms() == 0  # clamped for the wire
            with pytest.raises(deadline.DeadlineExceeded):
                dl.check("solve")

    def test_nested_cycles_keep_the_outer_budget(self):
        clock = FakeClock()
        with deadline.cycle(clock, budget_s=10.0) as outer:
            clock.step(4.0)
            with deadline.cycle(clock, budget_s=60.0) as inner:
                assert inner is outer
                assert deadline.current().remaining() == 6.0
            assert deadline.current() is outer


class _Aborted(Exception):
    def __init__(self, code, details):
        super().__init__(details)
        self.code = code
        self.details = details


class _Ctx:
    """grpc.ServicerContext stand-in: abort raises like the real one."""

    def abort(self, code, details):
        raise _Aborted(code, details)


class _FakeChannel:
    """Records every RPC; answers Sync with the matching content hash so
    the client's sync handshake passes without a server."""

    def __init__(self):
        self.calls = []

    def unary_unary(self, path, request_serializer=None,
                    response_deserializer=None):
        name = path.rsplit("/", 1)[-1]

        def call(request, timeout=None):
            from karpenter_tpu.solver import solver_pb2 as pb
            from karpenter_tpu.solver import wire

            self.calls.append((name, request, timeout))
            if name == "Sync":
                return pb.SyncResponse(
                    seqnum=request.catalog.seqnum,
                    catalog_hash=wire.catalog_hash(request.catalog))
            return pb.SolveResponse()

        return call


def _solver_fixture():
    from karpenter_tpu.apis import wellknown as wk
    from karpenter_tpu.apis.provisioner import Provisioner
    from karpenter_tpu.models.instancetype import Catalog, make_instance_type
    from karpenter_tpu.models.requirements import OP_IN, Requirements

    catalog = Catalog(types=[
        make_instance_type("m.large", cpu=2, memory="8Gi",
                           od_price=0.10, spot_price=0.03)])
    prov = Provisioner(name="default", requirements=Requirements.of(
        (wk.LABEL_CAPACITY_TYPE, OP_IN, ["spot", "on-demand"])))
    prov.set_defaults()
    return catalog, [prov]


class TestSolverDeadlineWire:
    def test_deadline_ms_ships_remaining_budget(self):
        from karpenter_tpu.solver.client import RemoteSolver

        catalog, provs = _solver_fixture()
        chan = _FakeChannel()
        client = RemoteSolver(catalog, provs, channel=chan)
        clock = FakeClock()
        with deadline.cycle(clock, budget_s=30.0):
            clock.step(12.0)
            client.solve([])
        solves = [(req, t) for name, req, t in chan.calls if name == "Solve"]
        assert len(solves) == 1
        req, timeout = solves[0]
        assert req.deadline_ms == 18000
        # the rpc timeout is clamped to the remaining budget's min with
        # the configured timeout (10s default < 18s remaining)
        assert timeout == pytest.approx(10.0)

    def test_no_cycle_means_no_deadline_on_the_wire(self):
        from karpenter_tpu.solver.client import RemoteSolver

        catalog, provs = _solver_fixture()
        chan = _FakeChannel()
        RemoteSolver(catalog, provs, channel=chan).solve([])
        req = [r for name, r, _ in chan.calls if name == "Solve"][0]
        assert req.deadline_ms == 0  # proto3 sentinel: no deadline

    def test_client_fails_fast_on_expired_deadline(self):
        from karpenter_tpu.solver.client import (RemoteSolver,
                                                 SolverUnavailable)

        catalog, provs = _solver_fixture()
        chan = _FakeChannel()
        client = RemoteSolver(catalog, provs, channel=chan)
        clock = FakeClock()
        with deadline.cycle(clock, budget_s=5.0):
            clock.step(6.0)
            with pytest.raises(SolverUnavailable, match="deadline exhausted"):
                client.solve([])
        assert not chan.calls  # nothing hit the wire

    def test_client_fails_fast_on_open_breaker(self):
        from karpenter_tpu.solver.client import (RemoteSolver,
                                                 SolverUnavailable)

        catalog, provs = _solver_fixture()
        hub = ResilienceHub(clock=FakeClock(), registry=Registry())
        for _ in range(3):
            hub.breaker("solver").record_failure()
        chan = _FakeChannel()
        client = RemoteSolver(catalog, provs, channel=chan, resilience=hub)
        with pytest.raises(SolverUnavailable, match="breaker open"):
            client.solve([])
        assert not chan.calls


class _FailingChannel(_FakeChannel):
    """_FakeChannel whose named RPC raises the given RpcError (Sync etc.
    still succeed, so the client's sync handshake passes)."""

    def __init__(self, fail_name, exc):
        super().__init__()
        self._fail_name = fail_name
        self._exc = exc

    def unary_unary(self, path, request_serializer=None,
                    response_deserializer=None):
        inner = super().unary_unary(
            path, request_serializer=request_serializer,
            response_deserializer=response_deserializer)
        name = path.rsplit("/", 1)[-1]

        def call(request, timeout=None):
            if name == self._fail_name:
                raise self._exc
            return inner(request, timeout)

        return call


def _rpc_error(code, details="injected"):
    import grpc

    class _Err(grpc.RpcError):
        def code(self):
            return code

        def details(self):
            return details

    return _Err(details)


class TestSolverBreakerFeedback:
    def test_self_inflicted_deadline_is_not_breaker_food(self):
        """DEADLINE_EXCEEDED while the caller's own cycle budget was
        propagated means the RPC ran out of OUR time (the timeout was
        capped to the remaining budget, the service sheds past-deadline
        work) — a few slow cycles must not trip the solver breaker on a
        healthy sidecar."""
        import grpc

        from karpenter_tpu.solver.client import (RemoteSolver,
                                                 SolverUnavailable)

        catalog, provs = _solver_fixture()
        clock = FakeClock()
        hub = ResilienceHub(clock=clock, registry=Registry())
        chan = _FailingChannel(
            "Solve", _rpc_error(grpc.StatusCode.DEADLINE_EXCEEDED))
        client = RemoteSolver(catalog, provs, channel=chan, resilience=hub)
        for _ in range(5):
            with deadline.cycle(clock, budget_s=30.0):
                with pytest.raises(SolverUnavailable, match="cycle budget"):
                    client.solve([])
        snap = hub.breaker("solver").snapshot()
        assert snap["state"] == "closed"
        assert snap["consecutive_failures"] == 0

    def test_deadline_exceeded_without_cycle_budget_is_breaker_food(self):
        """No propagated budget: DEADLINE_EXCEEDED is the sidecar being
        slow on its own terms — normal failure accounting applies."""
        import grpc

        from karpenter_tpu.solver.client import (RemoteSolver,
                                                 SolverUnavailable)

        catalog, provs = _solver_fixture()
        hub = ResilienceHub(clock=FakeClock(), registry=Registry())
        chan = _FailingChannel(
            "Solve", _rpc_error(grpc.StatusCode.DEADLINE_EXCEEDED))
        client = RemoteSolver(catalog, provs, channel=chan, resilience=hub)
        with pytest.raises(SolverUnavailable):
            client.solve([])
        assert hub.breaker("solver").snapshot()["consecutive_failures"] == 1

    def test_deadline_mid_rpc_releases_half_open_probe(self):
        """A self-inflicted deadline racing the half-open window must
        release the probe slot unjudged, not wedge the solver edge."""
        import grpc

        from karpenter_tpu.solver.client import (RemoteSolver,
                                                 SolverUnavailable)

        catalog, provs = _solver_fixture()
        clock = FakeClock()
        hub = ResilienceHub(clock=clock, registry=Registry())
        br = hub.breaker("solver")
        for _ in range(3):
            br.record_failure()  # solver edge trips open
        clock.step(30.0)         # recovery window elapses
        chan = _FailingChannel(
            "Solve", _rpc_error(grpc.StatusCode.DEADLINE_EXCEEDED))
        client = RemoteSolver(catalog, provs, channel=chan, resilience=hub)
        with deadline.cycle(clock, budget_s=30.0):
            with pytest.raises(SolverUnavailable):
                client.solve([])
        assert br.state() == "half-open"
        assert br.allow()  # probe slot is free again — not wedged


class TestServiceSheds:
    @pytest.fixture(scope="class")
    def service(self):
        from karpenter_tpu.solver import solver_pb2 as pb
        from karpenter_tpu.solver import wire
        from karpenter_tpu.solver.service import SolverService

        catalog, provs = _solver_fixture()
        svc = SolverService()
        resp = svc.Sync(pb.SyncRequest(
            catalog=wire.catalog_to_wire(catalog),
            provisioners=[wire.provisioner_to_wire(p) for p in provs]),
            _Ctx())
        return svc, resp.catalog_hash, wire.provisioners_hash(provs)

    def test_solve_sheds_below_min_budget(self, service):
        import grpc

        from karpenter_tpu.solver import solver_pb2 as pb

        svc, cat_hash, prov_hash = service
        with pytest.raises(_Aborted) as ei:
            svc.Solve(pb.SolveRequest(catalog_hash=cat_hash,
                                      provisioner_hash=prov_hash,
                                      deadline_ms=5), _Ctx())
        assert ei.value.code == grpc.StatusCode.DEADLINE_EXCEEDED
        assert "shedding" in ei.value.details

    def test_consolidate_sheds_below_min_budget(self, service):
        import grpc

        from karpenter_tpu.solver import solver_pb2 as pb

        svc, cat_hash, prov_hash = service
        with pytest.raises(_Aborted) as ei:
            svc.Consolidate(pb.ConsolidateRequest(
                catalog_hash=cat_hash, provisioner_hash=prov_hash,
                deadline_ms=3), _Ctx())
        assert ei.value.code == grpc.StatusCode.DEADLINE_EXCEEDED

    def test_solve_proceeds_with_enough_budget(self, service):
        from karpenter_tpu.solver import solver_pb2 as pb

        svc, cat_hash, prov_hash = service
        resp = svc.Solve(pb.SolveRequest(catalog_hash=cat_hash,
                                         provisioner_hash=prov_hash,
                                         deadline_ms=50_000), _Ctx())
        assert resp.catalog_seqnum >= 0  # a real response, not an abort


class TestPricingRetry:
    def test_transient_5xx_retried_per_page(self):
        import urllib.error

        from karpenter_tpu.providers.pricing import RestPricingSource

        src = RestPricingSource("http://prices.test", zones=["zone-1a"],
                                policy=make_policy(dep="pricing", seed=1))
        pages = []

        def fetch(path, page):
            pages.append((path, page))
            if len(pages) == 1:
                raise urllib.error.HTTPError(
                    "http://prices.test", 503, "unavailable", {}, None)
            return {"prices": [{"instanceType": "m.large", "price": 0.1,
                                "zone": "zone-1a"}],
                    "next": False}

        src._fetch_page = fetch
        prices = src.get_prices()
        assert ("m.large", "on-demand", "zone-1a") in prices
        # the 503 retried the PAGE, it did not abort the refresh
        assert len(pages) >= 3  # od page twice (retry) + spot page

    def test_non_transient_4xx_not_retried(self):
        import urllib.error

        from karpenter_tpu.providers.pricing import RestPricingSource

        src = RestPricingSource("http://prices.test", zones=["zone-1a"],
                                policy=make_policy(dep="pricing"))
        attempts = []

        def fetch(path, page):
            attempts.append(path)
            raise urllib.error.HTTPError(
                "http://prices.test", 404, "nope", {}, None)

        src._fetch_page = fetch
        assert src.get_prices() == {}
        assert len(attempts) == 2  # one per feed, zero retries


class TestHub:
    def test_shared_state_across_borrowers(self):
        hub = ResilienceHub(clock=FakeClock(), registry=Registry())
        assert hub.policy("cloud").breaker is hub.breaker("cloud")
        assert hub.policy("cloud").budget is hub.budgets["cloud"]
        assert set(hub.policies) == {"cloud", "kube", "solver", "pricing"}
        assert set(hub.ladders) == {"solve", "consolidate", "pricing"}

    def test_open_breakers_listed(self):
        hub = ResilienceHub(clock=FakeClock(), registry=Registry())
        assert hub.open_breakers() == []
        for _ in range(5):
            hub.breaker("cloud").record_failure()
        assert hub.open_breakers() == ["cloud"]
        assert "cloud" in hub.snapshot()["open_breakers"]

    def test_virtual_sleep_steps_the_fake_clock(self):
        clock = FakeClock()
        hub = ResilienceHub(clock=clock, registry=Registry())
        hub.use_virtual_sleep()
        delay = hub.policy("cloud").sleep_backoff()
        assert clock.now() == pytest.approx(delay)

    def test_clean_evidence_passes_all_invariants(self):
        hub = ResilienceHub(clock=FakeClock(), registry=Registry())
        for _ in range(7):
            hub.breaker("cloud").record_failure()  # trips at 5, then open
        hub.ladders["solve"].record_failure(0)
        ev = hub.evidence()
        assert not invariants.check_breaker_discipline(ev)
        assert not invariants.check_retry_budget(ev)
        assert not invariants.check_degrade_monotone(ev)


class TestInvariantFalsifiability:
    """Corrupted evidence must produce violations — proof the chaos checks
    can actually fail (mirrors the token-ledger self-test in test_chaos)."""

    def test_streak_past_threshold_is_flagged(self):
        ev = {"breakers": {"cloud": {
            "failure_threshold": 5, "max_closed_streak": 7,
            "opened_total": 0, "closed_total": 0, "rejected_total": 0,
            "final_state": "closed", "transitions": []}}}
        out = invariants.check_breaker_discipline(ev)
        assert [v.invariant for v in out] == ["breaker-opens-within-k"]

    def test_ledger_discontinuity_is_flagged(self):
        ev = {"breakers": {"cloud": {
            "failure_threshold": 5, "max_closed_streak": 5,
            "opened_total": 1, "closed_total": 0, "rejected_total": 0,
            "final_state": "open",
            "transitions": [{"ts": 1.0, "from": "half-open", "to": "open",
                             "why": "x"}]}}}
        assert invariants.check_breaker_discipline(ev)

    def test_negative_budget_watermark_is_flagged(self):
        ev = {"policies": {"cloud": {"budget": {
            "capacity": 10.0, "tokens": 0.0, "min_tokens": -1.0,
            "spent_total": 11, "denied_total": 0},
            "backoff_seconds_total": 0.0}}}
        out = invariants.check_retry_budget(ev)
        assert [v.invariant for v in out] == ["retry-budget-never-exceeded"]

    def test_overfull_bucket_is_flagged(self):
        ev = {"policies": {"cloud": {"budget": {
            "capacity": 10.0, "tokens": 12.0, "min_tokens": 0.0,
            "spent_total": 0, "denied_total": 0},
            "backoff_seconds_total": 0.0}}}
        assert invariants.check_retry_budget(ev)

    def test_spontaneous_recovery_is_flagged(self):
        ev = {"ladders": {"solve": {
            "rungs": ["primary", "fallback", "oracle"], "final_rung": 0,
            "probes_total": 0,
            "transitions": [
                {"ts": 1.0, "from": 0, "to": 2, "reason": "failure"},
                {"ts": 2.0, "from": 2, "to": 0, "reason": "probe-success"},
            ]}}}
        out = invariants.check_degrade_monotone(ev)
        assert any("skipped rungs" in v.message for v in out)

    def test_unexplained_degrade_is_flagged(self):
        ev = {"ladders": {"solve": {
            "rungs": ["primary", "fallback", "oracle"], "final_rung": 1,
            "probes_total": 0,
            "transitions": [
                {"ts": 1.0, "from": 0, "to": 1, "reason": "probe-success"},
            ]}}}
        out = invariants.check_degrade_monotone(ev)
        assert any("only failures" in v.message for v in out)


class TestBurstScenario:
    """The resilience acceptance run: a dense cloud-5xx + solver-crash
    window driven through the full operator must pass every invariant
    (including the three resilience checks) and must actually have
    exercised the plane — a burst that trips nothing proves nothing."""

    def test_burst_plan_is_fixed_and_dense(self):
        plan = FaultPlan.burst(0)
        assert plan.describe() == FaultPlan.burst(0).describe()
        kinds = plan.scheduled_kinds()
        assert kinds == {KIND_CLOUD_5XX, KIND_SOLVER_CRASH}
        assert len(plan.faults["cloud.create_fleet"]) == 8

    def test_burst_scenario_passes_resilience_invariants(self):
        from karpenter_tpu.chaos.runner import ChaosRunner

        result = ChaosRunner(seed=0, burst=True).run_scenario(0)
        assert result["passed"], result["violations"]
        ev = result["resilience"]
        # teeth: the cloud edge really was driven through the breaker
        cloud = ev["breakers"]["cloud"]
        assert cloud["opened_total"] >= 1
        assert cloud["max_closed_streak"] <= cloud["failure_threshold"]
        assert ev["policies"]["cloud"]["budget"]["spent_total"] >= 1
        # the solve chain degraded off its crashed primary and the ladder
        # ledger is monotone (already asserted by check_all, but the
        # transitions must exist for that assertion to mean anything)
        assert ev["ladders"]["solve"]["transitions"]
