"""Critical-path ledger (karpenter_tpu/profiling/critical): longest-chain
analysis on synthetic DAGs (serial / overlapped / diamond), the exact-0
serial guarantee, flat-projection bit-equality, wait attribution (lane
geometry + explicit notes), never-negative intervals under clock skew,
the strict-noop contract, /debug/criticalz, the statusz schema pin,
and measured-roofline drift falsifiability."""

import json
import logging
import urllib.error
import urllib.request

import pytest

from karpenter_tpu import profiling
from karpenter_tpu.apis.settings import Settings
from karpenter_tpu.fake.cloud import FakeCloud
from karpenter_tpu.models.instancetype import Catalog, make_instance_type
from karpenter_tpu.operator import Operator
from karpenter_tpu.profiling import GAP_LEDGER, critical, roofline
from karpenter_tpu.utils.clock import FakeClock


@pytest.fixture(autouse=True)
def _clean_critical():
    """Both planes ON, empty rings and no measured rungs around every test."""
    prev_prof = profiling.set_enabled(True)
    prev_crit = critical.set_enabled(True)
    GAP_LEDGER.clear()
    critical.CRITICAL.clear()
    roofline.clear_measured()
    yield
    GAP_LEDGER.clear()
    critical.CRITICAL.clear()
    roofline.clear_measured()
    critical.set_enabled(prev_crit)
    profiling.set_enabled(prev_prof)


def iv(lane, phase, start, dur):
    """Synthetic-DAG helper: an interval by (start, dur)."""
    return critical.make_interval(lane, phase, start + dur, dur)


# -- critical_path / analyze on synthetic DAGs --------------------------------------


class TestCriticalPath:
    def test_serial_chain_is_exactly_zero_overlap(self):
        # three back-to-back intervals: the chain IS the trace, and the
        # ratio is exactly 0.0 — not approximately — because analyze folds
        # total_work over the same end-sorted order the DP accumulates
        ivs = [iv("encode", "encode", 0.0, 0.125),
               iv("device", "device_exec", 0.125, 0.25),
               iv("encode", "decode", 0.375, 0.0625)]
        crit, members = critical.critical_path(ivs)
        assert sorted(members) == [0, 1, 2]
        row = critical.analyze(ivs)
        assert row["overlap_ratio"] == 0.0
        assert row["critical_path_ms"] == row["total_work_ms"]

    def test_serial_exact_zero_on_awkward_float_durations(self):
        # durations chosen to NOT be exactly representable — the bit-equal
        # fold guarantee is what keeps the ratio at literal 0.0 anyway
        durs = [0.1, 0.2, 0.3, 0.7, 0.011, 0.0043]
        ivs, t = [], 0.0
        for d in durs:
            ivs.append(iv("solver", "link", t, d))
            t += d + 0.001  # the real trace's between-phase gap
        assert critical.analyze(ivs)["overlap_ratio"] == 0.0

    def test_fully_overlapped_pair_is_half(self):
        ivs = [iv("encode", "encode", 0.0, 1.0),
               iv("device", "device_exec", 0.0, 1.0)]
        row = critical.analyze(ivs)
        assert row["overlap_ratio"] == pytest.approx(0.5)
        assert row["critical_path_ms"] == pytest.approx(1000.0)

    def test_diamond_puts_short_branch_off_critical(self):
        # encode -> (device ∥ serialize) -> decode; the device branch is
        # longer, so serialize is the off-critical branch
        ivs = [iv("encode", "encode", 0.0, 1.0),
               iv("device", "device_exec", 1.0, 1.0),
               iv("wire", "serialize", 1.0, 0.5),
               iv("encode", "decode", 2.0, 1.0)]
        row = critical.analyze(ivs)
        assert row["critical_path_ms"] == pytest.approx(3000.0)
        assert row["total_work_ms"] == pytest.approx(3500.0)
        assert row["overlap_ratio"] == pytest.approx(1 - 3 / 3.5, abs=1e-6)
        assert set(row["on_critical_path_ms"]) == {
            "encode", "device_exec", "decode"}
        assert set(row["off_critical_path_ms"]) == {"serialize"}
        # critical_share is share OF THE CHAIN, so it sums to 1 (each
        # share is rounded to 6 places before summing)
        assert sum(row["critical_share"].values()) == pytest.approx(
            1.0, abs=1e-5)

    def test_ratio_bounds_half_open(self):
        # heavy overlap cannot reach 1.0: the chain always contains at
        # least the longest single interval
        ivs = [iv("encode", "encode", 0.0, 1.0) for _ in range(16)]
        row = critical.analyze(ivs)
        assert 0.0 <= row["overlap_ratio"] < 1.0
        assert row["critical_path_ms"] >= 1000.0 - 1e-6

    def test_empty_trace(self):
        assert critical.critical_path([]) == (0.0, [])
        row = critical.analyze([])
        assert row["overlap_ratio"] == 0.0
        assert row["critical_path_ms"] == 0.0
        assert row["critical_share"] == {}

    def test_chain_respects_precedence_not_lane(self):
        # two lanes, interleaved serially — precedence is end<=start, not
        # same-lane adjacency, so the chain spans both lanes
        ivs = [iv("encode", "encode", 0.0, 1.0),
               iv("device", "device_exec", 1.0, 1.0),
               iv("encode", "decode", 2.0, 1.0),
               iv("device", "device_exec", 3.0, 1.0)]
        crit, members = critical.critical_path(ivs)
        assert crit == pytest.approx(4.0)
        assert sorted(members) == [0, 1, 2, 3]


class TestIntervalSkew:
    def test_make_interval_never_negative(self):
        # end earlier than the duration implies (cross-thread clock skew):
        # start clamps to 0, never negative
        a = critical.make_interval("encode", "encode", 0.001, 0.5)
        assert a.start == 0.0 and a.end == 0.001 and a.dur == 0.5
        # negative relative end (note filed before the scope anchor)
        b = critical.make_interval("device", "device_exec", -0.5, 0.25)
        assert b.start == 0.0 and b.end == 0.0 and b.dur == 0.25
        # negative measured duration clamps like the flat accumulation
        c = critical.make_interval("wire", "serialize", 1.0, -3.0)
        assert c.dur == 0.0 and c.start == c.end == 1.0

    def test_analyze_skewed_trace_stays_in_bounds(self):
        ivs = [critical.make_interval("encode", "encode", -1.0, 2.0),
               critical.make_interval("device", "device_exec", 0.001, 5.0)]
        row = critical.analyze(ivs)
        assert 0.0 <= row["overlap_ratio"] < 1.0
        assert row["critical_path_ms"] > 0.0


# -- flat projection bit-equality ---------------------------------------------------


class TestFlatProjection:
    def test_project_flat_folds_in_append_order(self):
        ivs = [iv("encode", "encode", 0.0, 0.1),
               iv("device", "device_exec", 0.1, 0.2),
               iv("encode", "encode", 0.3, 0.3)]
        flat = critical.project_flat(ivs)
        assert flat == {"encode": 0.1 + 0.3, "device_exec": 0.2}

    def test_real_trace_projection_is_bit_identical(self):
        # the flat row and the interval records are fed by the SAME note()
        # calls; the projection must equal rec.phases EXACTLY (==), not
        # approximately — awkward durations on purpose
        with GAP_LEDGER.solve_scope("proj") as rec:
            GAP_LEDGER.note("encode", 0.1)
            GAP_LEDGER.note("device_exec", 0.033)
            GAP_LEDGER.note("encode", 0.2)
            GAP_LEDGER.note("decode", 0.0077)
            assert critical.project_flat(rec.intervals) == rec.phases

    def test_real_serial_trace_reports_exact_zero(self):
        # end_pc pins phase boundaries so the intervals are strictly
        # serial; the embedded critical row must say 0.0 exactly
        with GAP_LEDGER.solve_scope("serial") as rec:
            t0 = rec.perf0
            GAP_LEDGER.note("encode", 0.01, end_pc=t0 + 0.011)
            GAP_LEDGER.note("device_exec", 0.02, end_pc=t0 + 0.035)
            GAP_LEDGER.note("decode", 0.005, end_pc=t0 + 0.045)
        row = GAP_LEDGER.rows()[-1]
        assert row["critical"]["overlap_ratio"] == 0.0
        assert (row["critical"]["critical_path_ms"]
                == row["critical"]["total_work_ms"])
        for key in ("critical_share", "waits_ms", "on_critical_path_ms",
                    "off_critical_path_ms"):
            assert key in row["critical"]

    def test_flat_row_keys_unchanged_by_critical_plane(self):
        # pre-existing consumers: attributed/unaccounted computed as before
        with GAP_LEDGER.solve_scope("compat"):
            GAP_LEDGER.note("encode", 10.0)
        row = GAP_LEDGER.rows()[-1]
        assert row["unaccounted_ms"] == 0.0
        assert row["attributed_share"] == pytest.approx(1.0)


# -- wait attribution ---------------------------------------------------------------


class TestWaitAttribution:
    def test_device_busy_gap_is_device_wait(self):
        ivs = [iv("solver", "link", 0.0, 1.0),
               iv("device", "device_exec", 1.0, 1.0),
               iv("solver", "link", 2.0, 1.0)]
        waits = critical.classify_waits(ivs)
        assert waits["device_wait"] == pytest.approx(1.0)
        assert waits["lock_wait"] == 0.0

    def test_encode_busy_gap_is_encode_wait(self):
        ivs = [iv("solver", "link", 0.0, 1.0),
               iv("encode", "encode", 1.0, 1.0),
               iv("solver", "link", 2.0, 1.0)]
        waits = critical.classify_waits(ivs)
        assert waits["encode_wait"] == pytest.approx(1.0)
        assert waits["device_wait"] == 0.0

    def test_idle_tick_gap_is_queue_wait(self):
        ivs = [iv("tick", "link", 0.0, 0.5),
               iv("tick", "link", 1.5, 0.5)]
        waits = critical.classify_waits(ivs)
        assert waits["queue_wait"] == pytest.approx(1.0)

    def test_unexplained_gap_is_lock_wait(self):
        ivs = [iv("solver", "link", 0.0, 0.5),
               iv("solver", "link", 1.5, 0.5)]
        waits = critical.classify_waits(ivs)
        assert waits["lock_wait"] == pytest.approx(1.0)
        assert waits["queue_wait"] == 0.0

    def test_jitter_gaps_are_not_waits(self):
        ivs = [iv("solver", "link", 0.0, 0.5),
               iv("solver", "link", 0.5 + 5e-6, 0.5)]  # < MIN_WAIT_S
        assert all(v == 0.0
                   for v in critical.classify_waits(ivs).values())

    def test_explicit_waits_fold_into_analyze(self):
        ivs = [iv("encode", "encode", 0.0, 1.0)]
        row = critical.analyze(
            ivs, explicit_waits=[("queue_wait", "tick", 0.25),
                                 ("not_a_wait", "tick", 9.0),
                                 ("lock_wait", "solver", -1.0)])
        assert row["waits_ms"]["queue_wait"] == pytest.approx(250.0)
        assert row["waits_ms"]["lock_wait"] == 0.0  # negative clamps
        assert "not_a_wait" not in row["waits_ms"]

    def test_note_wait_files_against_open_record(self):
        before = critical.activity()["wait_notes_total"]
        with GAP_LEDGER.solve_scope("w") as rec:
            GAP_LEDGER.note("encode", 0.001)
            GAP_LEDGER.note_wait("queue_wait", 0.5, lane="tick")
            assert rec.waits == [("queue_wait", "tick", 0.5)]
        assert critical.activity()["wait_notes_total"] == before + 1
        row = GAP_LEDGER.rows()[-1]
        assert row["critical"]["waits_ms"]["queue_wait"] >= 500.0

    def test_note_wait_unknown_kind_raises(self):
        with GAP_LEDGER.solve_scope("bad"):
            GAP_LEDGER.note("encode", 0.001)
            with pytest.raises(ValueError, match="unknown wait"):
                GAP_LEDGER.note_wait("coffee_wait", 0.1)
            with pytest.raises(ValueError, match="unknown lane"):
                GAP_LEDGER.note_wait("queue_wait", 0.1, lane="conveyor")

    def test_note_unknown_lane_raises(self):
        with GAP_LEDGER.solve_scope("bad"):
            with pytest.raises(ValueError, match="unknown lane"):
                GAP_LEDGER.note("encode", 0.001, lane="conveyor")
            GAP_LEDGER.note("encode", 0.001)  # keep the row non-empty


# -- strict-noop contract -----------------------------------------------------------


class TestStrictNoop:
    def test_disabled_plane_records_nothing(self):
        with critical.disabled():
            before = critical.activity()
            with GAP_LEDGER.solve_scope("noop") as rec:
                GAP_LEDGER.note("encode", 0.01)
                GAP_LEDGER.note("device_exec", 0.02)
                GAP_LEDGER.note_wait("queue_wait", 0.5)
                assert rec.intervals == []
                assert rec.waits == []
            assert critical.activity() == before
            assert critical.CRITICAL.ring_len() == 0
        # ...while the FLAT ledger kept accounting the whole time
        row = GAP_LEDGER.rows()[-1]
        assert row["phases_ms"]["encode"] == pytest.approx(10.0)
        assert "critical" not in row

    def test_observe_refuses_disabled_and_empty(self):
        with critical.disabled():
            assert critical.CRITICAL.observe(
                "x", [iv("encode", "encode", 0.0, 1.0)], [], 1.0, 0.0) is None
        assert critical.CRITICAL.observe("x", [], [], 1.0, 0.0) is None

    def test_set_enabled_returns_restore_token(self):
        assert critical.set_enabled(False) is True
        assert critical.enabled() is False
        assert critical.set_enabled(True) is False
        assert critical.enabled() is True

    def test_chaos_invariant_flags_noop_violation(self):
        from karpenter_tpu.chaos.invariants import check_critical_noop

        same = {"records_total": 3, "intervals_total": 9,
                "wait_notes_total": 1, "ring": 3}
        moved = dict(same, intervals_total=12)
        assert check_critical_noop(
            {"enabled": False, "before": same, "after": same}) == []
        out = check_critical_noop(
            {"enabled": False, "before": same, "after": moved})
        assert [v.invariant for v in out] == ["critical-strict-noop"]
        # enabled windows and absent evidence are out of scope
        assert check_critical_noop(
            {"enabled": True, "before": same, "after": moved}) == []
        assert check_critical_noop(None) == []


# -- ring / read surfaces -----------------------------------------------------------


class TestLedgerSurfaces:
    def _observe_one(self, source="t"):
        ivs = [iv("encode", "encode", 0.0, 0.01),
               iv("device", "device_exec", 0.01, 0.02)]
        return critical.CRITICAL.observe(source, ivs, [], 30.0, 1e9)

    def test_observe_row_shape(self):
        row = self._observe_one()
        assert row["source"] == "t"
        assert row["wall_ms"] == 30.0
        assert row["anchor_ts"] == 1e9
        assert len(row["records"]) == 2
        rec = row["records"][0]
        assert set(rec) == {"lane", "phase", "start_ms", "end_ms", "dur_ms"}

    def test_snapshot_and_criticalz(self):
        self._observe_one()
        snap = critical.snapshot()
        assert snap["enabled"] is True
        assert snap["lanes"] == list(critical.LANES)
        assert snap["records_total"] >= 1
        assert snap["last"] and "records" not in snap["last"][-1]
        assert "roofline_measured" in snap
        doc = critical.criticalz(limit=5)
        assert doc["tool"] == "karpenter_tpu.criticalz"
        assert doc["schema"] == 1
        assert doc["phase_lanes"] == dict(critical.PHASE_LANES)
        assert len(doc["rows"]) <= 5

    def test_merge_chrome_appends_critical_lane(self):
        self._observe_one()
        base = {"traceEvents": [
            {"name": "solve", "ph": "X", "ts": 1e15, "dur": 1e6, "pid": 1,
             "tid": 1}]}
        merged = critical.merge_chrome(base)
        crit_events = [e for e in merged["traceEvents"]
                       if e.get("pid") == critical.CriticalLedger.LANE_PID]
        assert any(e.get("ph") == "X" for e in crit_events)
        names = {e["args"]["name"] for e in crit_events
                 if e.get("ph") == "M" and e["name"] == "process_name"}
        assert names == {"critical"}

    def test_merge_chrome_skips_out_of_window_rows(self):
        self._observe_one()  # anchored at ts=1e9 s, far from the doc below
        base = {"traceEvents": [
            {"name": "solve", "ph": "X", "ts": 0.0, "dur": 10.0, "pid": 1,
             "tid": 1}]}
        assert critical.merge_chrome(base) == base


# -- /debug/criticalz + statusz -----------------------------------------------------


@pytest.fixture
def served_op():
    clock = FakeClock()
    cat = Catalog(types=[make_instance_type("m.large", cpu=4, memory="16Gi",
                                            od_price=0.2)])
    op = Operator(FakeCloud(catalog=cat, clock=clock),
                  Settings(cluster_name="crit", cluster_endpoint="https://k"),
                  cat, clock=clock, serve_http=True,
                  metrics_port=0, health_port=0, webhook_port=0)
    ports = op.serving.start()
    yield op, ports
    op.serving.stop()
    op.stop()


def _get(port, path):
    try:
        with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}",
                                    timeout=5) as r:
            return r.status, r.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


class TestCriticalzEndpoint:
    def test_json_default(self, served_op):
        op, ports = served_op
        code, body = _get(ports["metrics"], "/debug/criticalz")
        assert code == 200
        doc = json.loads(body)
        assert doc["tool"] == "karpenter_tpu.criticalz"
        assert doc["enabled"] is True
        assert doc["lanes"] == list(critical.LANES)
        assert doc["waits"] == list(critical.WAITS)
        assert isinstance(doc["rows"], list)

    def test_malformed_n_is_400(self, served_op):
        op, ports = served_op
        code, body = _get(ports["metrics"], "/debug/criticalz?n=bogus")
        assert code == 400
        assert "integer" in body

    def test_oversized_and_negative_n_clamp(self, served_op):
        from karpenter_tpu.serving import MAX_CRITICAL_ROWS

        op, ports = served_op
        code, body = _get(ports["metrics"], "/debug/criticalz?n=999999")
        assert code == 200
        assert len(json.loads(body)["rows"]) <= MAX_CRITICAL_ROWS
        code, _ = _get(ports["metrics"], "/debug/criticalz?n=-5")
        assert code == 200  # clamped up, same as /debug/profilez

    def test_statusz_schema_carries_critical_section(self, served_op):
        op, ports = served_op
        code, body = _get(ports["metrics"], "/debug/statusz")
        assert code == 200
        doc = json.loads(body)
        assert doc["schema"] == 13
        sect = doc["critical"]
        assert sect["enabled"] is True
        assert sect["lanes"] == list(critical.LANES)
        assert set(sect["wait_ms_total"]) == set(critical.WAITS)
        assert "roofline_measured" in sect


# -- measured roofline drift --------------------------------------------------------


class TestMeasuredRoofline:
    def _modelled(self, flops):
        return roofline.Roofline(
            bucket="b1", bytes_moved=1_000_000, flops=flops, floor_ms=0.1,
            bw_gbps=50.0, peak_gflops=100.0, backend="cpu", device_count=1)

    def test_drift_beyond_threshold_flags_and_warns(self, caplog):
        with caplog.at_level(logging.WARNING,
                             logger="karpenter_tpu.profiling.roofline"):
            entry = roofline.record_measured(
                "b1", flops=1e10, bytes_accessed=2e6,
                modelled=self._modelled(1e9))  # 10x > DRIFT_THRESHOLD
        assert entry["flagged"] is True
        assert entry["flops_drift"] == pytest.approx(10.0)
        assert any("roofline drift" in r.message for r in caplog.records)
        snap = roofline.measured_snapshot()
        assert snap["drift_flagged"] == ["b1"]
        assert snap["drift_threshold"] == roofline.DRIFT_THRESHOLD

    def test_drift_is_symmetric(self):
        # measured 10x BELOW the model flags just the same
        entry = roofline.record_measured(
            "b2", flops=1e8, bytes_accessed=2e6,
            modelled=self._modelled(1e9))
        assert entry["flagged"] is True
        assert entry["flops_drift"] == pytest.approx(10.0)

    def test_within_threshold_not_flagged(self):
        entry = roofline.record_measured(
            "b3", flops=1.5e9, bytes_accessed=2e6,
            modelled=self._modelled(1e9))
        assert entry["flagged"] is False
        assert roofline.measured_snapshot()["drift_flagged"] == []

    def test_no_model_no_drift_keys(self):
        entry = roofline.record_measured("b4", flops=1e9, bytes_accessed=2e6)
        assert entry["flagged"] is False
        assert "flops_drift" not in entry
        assert "modelled_flops" not in entry

    def test_measured_floor_uses_backend_peaks(self):
        entry = roofline.record_measured("b5", flops=0.0, bytes_accessed=0.0)
        assert entry["floor_ms"] == 0.0
        bigger = roofline.record_measured("b6", flops=1e12,
                                          bytes_accessed=1e9)
        assert bigger["floor_ms"] > 0.0

    def test_clear_measured_drops_rungs(self):
        roofline.record_measured("b7", flops=1.0, bytes_accessed=1.0)
        assert roofline.measured_snapshot()["rungs"]
        roofline.clear_measured()
        assert roofline.measured_snapshot()["rungs"] == {}
