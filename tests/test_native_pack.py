"""Differential tests: native C++ packer vs JAX kernel vs oracle.

The native scan (karpenter_tpu/native/ktpack.cc) is the controller's
in-process fallback; it consumes the same encoded problem as the device
kernel, so parity here means all three backends share one semantics spec
(SURVEY.md §7.3 "fallback equivalence")."""

import random

import numpy as np
import pytest

from karpenter_tpu.apis import wellknown as wk
from karpenter_tpu.apis.provisioner import Provisioner
from karpenter_tpu.models.encode import encode_problem
from karpenter_tpu.models.instancetype import Catalog, make_instance_type
from karpenter_tpu.models.pod import TopologySpreadConstraint, make_pod
from karpenter_tpu.models.requirements import Requirements, OP_IN
from karpenter_tpu.native import native_pack
from karpenter_tpu.ops.packer import PackInputs, pack
from karpenter_tpu.oracle.scheduler import ExistingNode
from karpenter_tpu.solver.core import NativeSolver, TPUSolver


def catalog5():
    return Catalog(types=[
        make_instance_type("small.2x", cpu=2, memory="8Gi", od_price=0.10, spot_price=0.03),
        make_instance_type("medium.4x", cpu=4, memory="16Gi", od_price=0.20, spot_price=0.06),
        make_instance_type("large.8x", cpu=8, memory="32Gi", od_price=0.40, spot_price=0.12),
        make_instance_type("arm.4x", cpu=4, memory="16Gi", arch="arm64", od_price=0.15),
        make_instance_type("gpu.8x", cpu=8, memory="64Gi", od_price=2.50,
                           extended={wk.RESOURCE_NVIDIA_GPU: 4}),
    ])


def prov(name="default", **kw):
    p = Provisioner(name=name, **kw)
    p.set_defaults()
    return p


def kernel_inputs(catalog, provisioners, pods, existing=(), overhead=None):
    enc = encode_problem(catalog, provisioners, pods, existing, overhead)
    inputs = PackInputs(
        alloc_t=enc.alloc_t, tiebreak=enc.tiebreak, group_vec=enc.group_vec,
        group_count=enc.group_count, group_cap=enc.group_cap,
        group_feas=enc.group_feas, group_newprov=enc.group_newprov,
        overhead=enc.overhead, ex_alloc=enc.ex_alloc, ex_used=enc.ex_used,
        ex_feas=enc.ex_feas,
        prov_overhead=enc.prov_overhead, prov_pods_cap=enc.prov_pods_cap,
    )
    return inputs, enc.n_slots


def assert_bit_parity(catalog, provisioners, pods, existing=(), overhead=None):
    inputs, n_slots = kernel_inputs(catalog, provisioners, pods, existing, overhead)
    kr = pack(inputs, n_slots=n_slots)
    nr = native_pack(inputs, n_slots)
    np.testing.assert_array_equal(np.asarray(kr.assign), nr.assign)
    np.testing.assert_array_equal(np.asarray(kr.ex_assign), nr.ex_assign)
    np.testing.assert_array_equal(np.asarray(kr.unsched), nr.unsched)
    np.testing.assert_array_equal(np.asarray(kr.active), nr.active)
    np.testing.assert_array_equal(np.asarray(kr.nprov), nr.nprov)
    np.testing.assert_array_equal(np.asarray(kr.decided), nr.decided)
    assert int(kr.n_open) == int(nr.n_open)


class TestNativeBitParity:
    def test_inflate(self):
        pods = [make_pod(f"p{i}", cpu="1", memory="256M") for i in range(100)]
        assert_bit_parity(catalog5(), [prov()], pods)

    def test_kubelet_caps_and_reserved(self):
        from karpenter_tpu.apis.provisioner import KubeletConfiguration

        p = prov(kubelet=KubeletConfiguration(
            max_pods=4, system_reserved_cpu_millis=250,
            kube_reserved_memory_bytes=2**30))
        pods = [make_pod(f"p{i}", cpu="200m", memory="512Mi") for i in range(15)]
        assert_bit_parity(catalog5(), [p], pods)

    def test_mixed_sizes_and_zones(self):
        pods = (
            [make_pod(f"big-{i}", cpu="3", memory="12Gi") for i in range(7)]
            + [make_pod(f"z-{i}", cpu="1", memory="1Gi",
                        node_selector={wk.LABEL_ZONE: "zone-1a"}) for i in range(5)]
            + [make_pod(f"tiny-{i}", cpu="100m", memory="128Mi") for i in range(50)]
        )
        assert_bit_parity(catalog5(), [prov()], pods)

    def test_topology_spread(self):
        spread = (TopologySpreadConstraint(max_skew=1, topology_key=wk.LABEL_ZONE),)
        pods = [make_pod(f"s-{i}", cpu="1", memory="1Gi", topology=spread)
                for i in range(10)]
        assert_bit_parity(catalog5(), [prov()], pods)

    def test_existing_nodes(self):
        catalog = catalog5()
        existing = [ExistingNode(
            name="ex-1",
            labels={wk.LABEL_ZONE: "zone-1a", wk.LABEL_ARCH: "amd64",
                    wk.LABEL_OS: "linux", wk.LABEL_INSTANCE_TYPE: "medium.4x",
                    wk.LABEL_CAPACITY_TYPE: "on-demand"},
            allocatable=catalog.by_name["medium.4x"].allocatable_vector(),
            used=[0] * wk.NUM_RESOURCES)]
        pods = [make_pod(f"p{i}", cpu="500m", memory="1Gi") for i in range(12)]
        assert_bit_parity(catalog, [prov()], pods, existing=existing)

    def test_unschedulable_overflow(self):
        # gpu pods with no gpu-admitting provisioner requirement mismatch:
        # arm-only provisioner vs amd64-only pods
        p = Provisioner(name="arm", requirements=Requirements.of(
            (wk.LABEL_ARCH, OP_IN, ["arm64"])))
        p.set_defaults()
        pods = [make_pod(f"p{i}", cpu="1", memory="1Gi",
                         node_selector={wk.LABEL_ARCH: "amd64"}) for i in range(3)]
        assert_bit_parity(catalog5(), [p], pods)

    def test_randomized_sweep(self):
        rng = random.Random(7)
        for trial in range(15):
            n = rng.randint(1, 60)
            pods = []
            for i in range(n):
                kw = {}
                if rng.random() < 0.3:
                    kw["node_selector"] = {wk.LABEL_ZONE: rng.choice(
                        ["zone-1a", "zone-1b", "zone-1c"])}
                if rng.random() < 0.2:
                    kw["topology"] = (TopologySpreadConstraint(
                        1, wk.LABEL_ZONE),)
                pods.append(make_pod(
                    f"t{trial}-p{i}",
                    cpu=rng.choice(["100m", "250m", "500m", "1", "2", "3"]),
                    memory=rng.choice(["128Mi", "512Mi", "1Gi", "4Gi", "12Gi"]),
                    **kw))
            assert_bit_parity(catalog5(), [prov()], pods)


class TestNativeSolverEndToEnd:
    def test_decisions_match_tpu_solver(self):
        catalog = catalog5()
        provs = [prov()]
        pods = ([make_pod(f"a{i}", cpu="1", memory="2Gi") for i in range(20)]
                + [make_pod(f"b{i}", cpu="250m", memory="512Mi") for i in range(30)])
        tpu = TPUSolver(catalog, provs).solve(pods)
        native = NativeSolver(catalog, provs).solve(pods)
        assert native.decisions() == tpu.decisions()
        assert native.unschedulable_count() == tpu.unschedulable_count()

    def test_provisioning_fallback_chain_uses_native(self):
        """Solver factory raising -> controller falls back to native, not
        straight to the Python oracle."""
        from karpenter_tpu.apis.settings import Settings
        from karpenter_tpu.fake.cloud import FakeCloud
        from karpenter_tpu.operator import Operator

        catalog = catalog5()
        op = Operator(FakeCloud(catalog),
                      Settings(cluster_name="t", cluster_endpoint="https://t"),
                      catalog)

        def boom(cat, provs):
            raise RuntimeError("sidecar down")

        op.provisioning._solver_factory = boom
        op.kube.create("provisioners", "default", prov())
        for i in range(4):
            p = make_pod(f"p{i}", cpu="1", memory="1Gi")
            op.kube.create("pods", p.name, p)
        result = op.provisioning.reconcile_once()
        assert result is not None and len(result.nodes) >= 1
        assert result.unschedulable_count() == 0
