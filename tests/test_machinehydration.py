"""MachineHydration controller tests (pkg/controllers/machinehydration
analogue): Machine backfill from pre-existing provisioner-owned nodes, with
instance tagging via CloudProvider.hydrate."""

from karpenter_tpu.apis import wellknown as wk
from karpenter_tpu.apis.provisioner import Provisioner
from karpenter_tpu.apis.settings import Settings
from karpenter_tpu.fake.cloud import CloudInstance, FakeCloud
from karpenter_tpu.models.cluster import StateNode
from karpenter_tpu.models.instancetype import Catalog, make_instance_type
from karpenter_tpu.models.machine import make_provider_id
from karpenter_tpu.operator import Operator


def make_operator():
    catalog = Catalog(types=[make_instance_type("m.l", cpu=4, memory="16Gi")])
    cloud = FakeCloud(catalog)
    op = Operator(cloud, Settings(cluster_name="test-cluster",
                                  cluster_endpoint="https://t"), catalog)
    return op, cloud


def preexisting_node(cloud, name="legacy-1", provisioner="default"):
    """A node + instance that predate the controller (no Machine)."""
    inst = CloudInstance(id=f"i-{name}", instance_type="m.l", zone="zone-1a",
                        capacity_type="on-demand",
                        tags={"kubernetes.io/cluster/test-cluster": "owned"})
    cloud.instances[inst.id] = inst
    node = StateNode(
        name=name,
        labels={wk.LABEL_PROVISIONER: provisioner,
                wk.LABEL_INSTANCE_TYPE: "m.l",
                wk.LABEL_ZONE: "zone-1a"},
        allocatable=[4000, 16384, 110] + [0] * (wk.NUM_RESOURCES - 3),
        provider_id=make_provider_id("zone-1a", inst.id),
        provisioner_name=provisioner,
        machine_name="",  # the gap hydration fills
    )
    return node, inst


class TestMachineHydration:
    def test_hydrates_machine_for_orphan_node(self):
        op, cloud = make_operator()
        op.kube.create("provisioners", "default", Provisioner(name="default"))
        node, inst = preexisting_node(cloud)
        op.kube.create("nodes", node.name, node)

        assert op.machinehydration.reconcile_once() == 1
        machine = op.kube.get("machines", "legacy-1-hydrated")
        assert machine is not None
        assert node.machine_name == "legacy-1-hydrated"
        assert machine.spec.provisioner_name == "default"
        # node labels became machine requirements
        assert machine.spec.requirements.get(wk.LABEL_INSTANCE_TYPE).has("m.l")
        # instance got the managed-by tag (hydrate -> create_tags)
        assert cloud.instances[inst.id].tags.get(
            "karpenter.sh/managed-by") == "test-cluster"

    def test_idempotent(self):
        op, cloud = make_operator()
        op.kube.create("provisioners", "default", Provisioner(name="default"))
        node, _ = preexisting_node(cloud)
        op.kube.create("nodes", node.name, node)
        assert op.machinehydration.reconcile_once() == 1
        assert op.machinehydration.reconcile_once() == 0
        assert len(op.kube.list("machines")) == 1

    def test_skips_unowned_node(self):
        op, cloud = make_operator()
        node, _ = preexisting_node(cloud)
        node.labels.pop(wk.LABEL_PROVISIONER)
        op.kube.create("nodes", node.name, node)
        assert op.machinehydration.reconcile_once() == 0
        assert not op.kube.list("machines")

    def test_relinks_when_machine_exists_by_provider_id(self):
        op, cloud = make_operator()
        op.kube.create("provisioners", "default", Provisioner(name="default"))
        node, _ = preexisting_node(cloud)
        op.kube.create("nodes", node.name, node)
        op.machinehydration.reconcile_once()
        node.machine_name = ""  # lose the back-reference
        assert op.machinehydration.reconcile_once() == 0  # relink, no new machine
        assert node.machine_name == "legacy-1-hydrated"
        assert len(op.kube.list("machines")) == 1

    def test_skips_node_without_provider_id(self):
        op, cloud = make_operator()
        op.kube.create("provisioners", "default", Provisioner(name="default"))
        node, _ = preexisting_node(cloud)
        node.provider_id = ""
        op.kube.create("nodes", node.name, node)
        assert op.machinehydration.reconcile_once() == 0

    def test_hydrated_node_joins_cluster_state(self):
        """Hydration brings the node under management: existing-capacity
        scheduling and termination must see it."""
        op, cloud = make_operator()
        op.kube.create("provisioners", "default", Provisioner(name="default"))
        node, _ = preexisting_node(cloud)
        op.kube.create("nodes", node.name, node)
        op.machinehydration.reconcile_once()
        assert "legacy-1" in op.cluster.nodes
        assert any(e.name == "legacy-1" for e in op.cluster.existing_views())
