"""Coordination plane over the wire: mini apiserver + HttpKubeStore +
the full controller plane scheduling a kubectl-authored pod.

Parity target: the reference boots against a real apiserver
(/root/reference/cmd/controller/main.go:33-65) and its unit tier runs
envtest; here the in-repo mini apiserver (fake/apiserver.py) plays the
kwok/envtest role and HttpKubeStore is the client-go analogue.
"""

import json
import time
import urllib.request

import pytest
import yaml

from karpenter_tpu.apis.settings import Settings
from karpenter_tpu.coordination.httpkube import HttpKubeStore
from karpenter_tpu.coordination.protocol import CoordinationPlane
from karpenter_tpu.coordination import serde
from karpenter_tpu.fake.apiserver import serve
from karpenter_tpu.fake.cloud import FakeCloud
from karpenter_tpu.fake.kube import Conflict, KubeStore
from karpenter_tpu.models.instancetype import Catalog, make_instance_type
from karpenter_tpu.models.pod import make_pod
from karpenter_tpu.operator import Operator
from karpenter_tpu.utils.clock import FakeClock


@pytest.fixture
def api():
    srv, port, state = serve()
    yield f"http://127.0.0.1:{port}", state
    srv.shutdown()


def _post_raw(base: str, path: str, doc: dict) -> None:
    req = urllib.request.Request(base + path, json.dumps(doc).encode(),
                                 {"Content-Type": "application/json"},
                                 method="POST")
    urllib.request.urlopen(req).read()


def catalog():
    return Catalog(types=[
        make_instance_type("m.large", cpu=4, memory="16Gi", od_price=0.20,
                           spot_price=0.07),
    ])


class TestProtocolConformance:
    def test_both_stores_implement_the_protocol(self, api):
        base, _ = api
        http_store = HttpKubeStore(base)
        assert isinstance(http_store, CoordinationPlane)
        assert isinstance(KubeStore(), CoordinationPlane)


class TestHttpStore:
    def test_crud_watch_and_read_your_writes(self, api):
        base, _ = api
        a = HttpKubeStore(base)
        a.start()
        b = HttpKubeStore(base)
        b.start()
        a.create("pods", "p1", make_pod("p1", cpu="1", memory="1Gi"))
        assert [p.name for p in a.pending_pods()] == ["p1"]  # no wait
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and not b.pending_pods():
            time.sleep(0.02)
        assert [p.name for p in b.pending_pods()] == ["p1"]
        # duplicate create conflicts through the wire
        with pytest.raises(Conflict):
            b.create("pods", "p1", make_pod("p1", cpu="1", memory="1Gi"))
        a.stop(), b.stop()

    def test_binding_subresource(self, api):
        base, _ = api
        a = HttpKubeStore(base)
        a.start()
        a.create("pods", "p1", make_pod("p1", cpu="1", memory="1Gi"))
        a.bind_pod("p1", "node-1")
        assert a.get("pods", "p1").node_name == "node-1"
        assert a.pending_pods() == []
        with pytest.raises(Conflict):
            a.bind_pod("p1", "node-2")
        a.stop()

    def test_cas_leases_over_the_wire(self, api):
        base, _ = api
        a = HttpKubeStore(base)
        a.start()
        from karpenter_tpu.leaderelection import Lease

        a.create("leases", "karpenter-leader", Lease("x", 1, 1, 15))
        cached = a.get("leases", "karpenter-leader")
        a.compare_and_swap("leases", "karpenter-leader", cached,
                           Lease("x", 1, 2, 15))
        with pytest.raises(Conflict):  # stale expectation loses
            a.compare_and_swap("leases", "karpenter-leader", cached,
                               Lease("y", 9, 9, 15))
        a.stop()

    def test_leader_election_over_http(self, api):
        base, _ = api
        from karpenter_tpu.leaderelection import LeaderElector
        from karpenter_tpu.utils.clock import FakeClock

        clock = FakeClock()
        a_store, b_store = HttpKubeStore(base), HttpKubeStore(base)
        a_store.start(), b_store.start()
        a = LeaderElector(a_store, "a", clock=clock, lease_duration_s=15)
        b = LeaderElector(b_store, "b", clock=clock, lease_duration_s=15)
        assert a.try_acquire_or_renew()
        deadline = time.monotonic() + 5  # b's cache must see a's lease
        while time.monotonic() < deadline and \
                b_store.get("leases", a.name) is None:
            time.sleep(0.02)
        assert not b.try_acquire_or_renew()
        clock.step(16)  # a stops renewing; TTL expires
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and not b.try_acquire_or_renew():
            time.sleep(0.05)
        assert b.is_leader()
        a_store.stop(), b_store.stop()


class TestControllerOverTheWire:
    def test_kubectl_authored_pod_schedules_end_to_end(self, api, tmp_path):
        """The done-criterion for VERDICT r2 ask #3: the controller, against
        a real (HTTP) apiserver, schedules a pending pod created in plain
        Kubernetes schema using the deploy/ + examples/ manifests."""
        base, state = api

        # 1) kubectl-style applies: CRDs (stored as-is), then the quickstart
        # provisioner + nodetemplate (k8s schema, parsed by yaml_compat)
        for crd_path in ("deploy/crds/karpenter.sh_provisioners.yaml",
                         "deploy/crds/karpenter.k8s.tpu_nodetemplates.yaml"):
            doc = yaml.safe_load(open(crd_path))
            _post_raw(base, "/apis/apiextensions.k8s.io/v1/"
                      "customresourcedefinitions", doc)
        bundle = open("examples/quickstart.yaml").read().replace(
            "${CLUSTER_NAME}", "wire-test")
        for doc in yaml.safe_load_all(bundle):
            if not doc:
                continue
            kind = doc["kind"]
            if kind == "Provisioner":
                _post_raw(base, "/apis/karpenter.sh/v1alpha5/provisioners", doc)
            elif kind == "NodeTemplate":
                _post_raw(base, "/apis/karpenter.k8s.tpu/v1alpha1/"
                          "nodetemplates", doc)
        # one plain-schema pending pod (what kube-scheduler would leave)
        _post_raw(base, "/api/v1/namespaces/default/pods", {
            "apiVersion": "v1", "kind": "Pod",
            "metadata": {"name": "web-0", "labels": {"app": "web"}},
            "spec": {"containers": [{
                "name": "c",
                "resources": {"requests": {"cpu": "1", "memory": "1Gi"}},
            }]},
        })

        # 2) the controller plane against the wire store
        kube = HttpKubeStore(base)
        kube.start()
        assert [p.name for p in kube.provisioners()] == ["default"]
        assert [t.name for t in kube.nodetemplates()] == ["default"]
        assert [p.name for p in kube.pending_pods()] == ["web-0"]

        cat = catalog()
        cloud = FakeCloud(cat)
        for s in cloud.subnets:
            s.tags.setdefault("karpenter.sh/discovery", "wire-test")
        for g in cloud.security_groups:
            g.tags.setdefault("karpenter.sh/discovery", "wire-test")
        settings = Settings(cluster_name="wire-test",
                            cluster_endpoint="https://wire",
                            batch_idle_duration=0.0, batch_max_duration=0.0)
        op = Operator(cloud, settings, cat, kube=kube)
        try:
            op.reconcile_all_once()
            # 3) server-side truth: the pod is BOUND and capacity objects
            # exist on the apiserver, not just in process memory
            pod_doc = state.bucket("pods")["web-0"]
            assert pod_doc["spec"].get("nodeName"), "pod not bound server-side"
            assert state.bucket("machines"), "no machine object on the server"
            assert state.bucket("nodes"), "no node object on the server"
            assert kube.pending_pods() == []
            # the bound node is the machine's node (names line up)
            node_name = pod_doc["spec"]["nodeName"]
            assert node_name in state.bucket("nodes")
            # counters controller: consumption is SERVER-side visible in
            # real schema (kubectl get provisioner shows it)
            prov_doc = state.bucket("provisioners")["default"]
            res = (prov_doc.get("status") or {}).get("resources") or {}
            assert res.get("nodes") not in (None, "0"), prov_doc.get("status")
            assert res.get("cpu", "").endswith("m")
        finally:
            op.stop()
            kube.stop()


class TestSerde:
    def test_k8s_pod_without_embedded_model_parses(self):
        doc = {"apiVersion": "v1", "kind": "Pod",
               "metadata": {"name": "kp", "labels": {"app": "y"}},
               "spec": {"nodeName": "n9", "containers": [{
                   "name": "c", "resources": {
                       "requests": {"cpu": "500m", "memory": "1Gi"}}}]}}
        pod = serde.from_manifest("pods", doc)
        assert pod.node_name == "n9"
        assert dict(pod.labels) == {"app": "y"}

    def test_machine_round_trip_is_lossless(self):
        from karpenter_tpu.models.machine import (Machine, MachineSpec,
                                                  MachineStatus)
        from karpenter_tpu.models.requirements import (OP_IN, Requirements)
        from karpenter_tpu.apis import wellknown as wk

        m = Machine(name="m1", spec=MachineSpec(
            requirements=Requirements.of((wk.LABEL_ARCH, OP_IN, ["amd64"])),
            resource_requests={"cpu": 1500}),
            status=MachineStatus(provider_id="tpu:///z-1a/i-123",
                                 state="Launched"))
        doc = serde.to_manifest("machines", "m1", m)
        json.dumps(doc)  # JSON-able
        m2 = serde.from_manifest("machines", doc)
        assert m2 == m

    def test_lease_survives_model_field_pruning(self):
        # ADVICE r3 (medium): a real apiserver prunes unknown fields on
        # built-in types, stripping x-karpenter-model from Leases. The
        # manifest must carry the real coordination.k8s.io/v1 spec so the
        # round-trip doesn't read back holder=""/renew_ts=0 (= always
        # expired => two concurrent leaders).
        from karpenter_tpu.leaderelection import Lease

        doc = serde.to_manifest("leases", "karpenter-leader",
                                Lease("replica-a", 100.0, 250.0, 15))
        json.dumps(doc)
        doc.pop(serde.MODEL_KEY)  # what a pruning apiserver does
        back = serde.from_manifest("leases", doc)
        assert back.holder == "replica-a"
        assert back.duration_s == 15.0
        assert abs(back.acquired_ts - 100.0) < 1e-3
        assert abs(back.renew_ts - 250.0) < 1e-3
        assert not back.expired(now=260.0)  # held, not falsely expired

    def test_statenode_pods_are_runtime_only(self):
        from karpenter_tpu.models.cluster import StateNode
        from karpenter_tpu.apis import wellknown as wk

        sn = StateNode(name="n", labels={}, allocatable=[0] * wk.NUM_RESOURCES,
                       pods=[make_pod("x", cpu="1", memory="1Gi")])
        back = serde.from_manifest(
            "nodes", serde.to_manifest("nodes", "n", sn))
        assert back.pods == []


class TestKeepAliveIdleDrop:
    """A pooled keep-alive socket idle past the threshold is proactively
    dropped before reuse (ISSUE 2 satellite; the complementary fix to the
    response-phase retry — never race the server's idle reaper)."""

    def test_idle_connection_dropped_and_redialed(self, api):
        base, _ = api
        clock = FakeClock()
        store = HttpKubeStore(base, clock=clock, keepalive_idle_seconds=30.0)
        c1, fresh = store._pooled_conn()
        assert fresh
        c2, fresh = store._pooled_conn()
        assert c2 is c1 and not fresh      # warm reuse inside the window
        clock.step(29.0)
        c3, fresh = store._pooled_conn()
        assert c3 is c1 and not fresh      # 29s idle: still inside
        clock.step(31.0)
        c4, fresh = store._pooled_conn()
        assert fresh and c4 is not c1      # 31s idle: dropped + redialed

    def test_each_use_restarts_the_idle_window(self, api):
        base, _ = api
        clock = FakeClock()
        store = HttpKubeStore(base, clock=clock, keepalive_idle_seconds=30.0)
        c1, _ = store._pooled_conn()
        for _ in range(4):                 # steady traffic never trips it
            clock.step(20.0)
            c, fresh = store._pooled_conn()
            assert c is c1 and not fresh

    def test_requests_still_work_across_the_idle_horizon(self, api):
        base, _ = api
        clock = FakeClock()
        store = HttpKubeStore(base, clock=clock, keepalive_idle_seconds=30.0)
        store.create("pods", "idle-p1", make_pod("idle-p1", cpu="1"))
        clock.step(3600.0)                 # a long quiet period
        store.create("pods", "idle-p2", make_pod("idle-p2", cpu="1"))
        names = {p["name"] if isinstance(p, dict) else p.name
                 for p in store.list("pods")}
        assert {"idle-p1", "idle-p2"} <= names

    def test_negative_threshold_disables_the_drop(self, api):
        base, _ = api
        clock = FakeClock()
        store = HttpKubeStore(base, clock=clock, keepalive_idle_seconds=-1)
        c1, _ = store._pooled_conn()
        clock.step(10_000.0)
        c2, fresh = store._pooled_conn()
        assert c2 is c1 and not fresh


class TestReviewHardening:
    def test_foreign_node_manifests_parse(self):
        # a real cluster has kubelet-authored Nodes with no embedded model
        doc = {"apiVersion": "v1", "kind": "Node",
               "metadata": {"name": "ip-10-0-0-1",
                            "labels": {"topology.kubernetes.io/zone": "z1"}},
               "spec": {"providerID": "tpu:///z1/i-9",
                        "taints": [{"key": "k", "value": "v",
                                    "effect": "NoSchedule"}]},
               "status": {"allocatable": {"cpu": "4", "memory": "16Gi",
                                          "pods": "110"}}}
        node = serde.from_manifest("nodes", doc)
        from karpenter_tpu.apis import wellknown as wk

        assert node.name == "ip-10-0-0-1"
        assert node.provider_id == "tpu:///z1/i-9"
        assert node.allocatable[wk.RESOURCE_INDEX[wk.RESOURCE_CPU]] == 4000
        assert node.taints[0].key == "k"

    def test_foreign_lease_manifests_parse(self):
        doc = {"apiVersion": "coordination.k8s.io/v1", "kind": "Lease",
               "metadata": {"name": "other-leader"},
               "spec": {"holderIdentity": "someone",
                        "renewTime": "2026-07-29T00:00:00Z",
                        "leaseDurationSeconds": 30}}
        lease = serde.from_manifest("leases", doc)
        assert lease.holder == "someone" and lease.duration_s == 30.0
        assert lease.renew_ts > 0

    def test_foreign_machine_is_skipped_not_fatal(self, api):
        base, _ = api
        _post_raw(base, "/apis/karpenter.sh/v1alpha5/machines", {
            "apiVersion": "karpenter.sh/v1alpha5", "kind": "Machine",
            "metadata": {"name": "foreign-1"}, "spec": {}})
        store = HttpKubeStore(base)
        store.start()  # must not raise on the uninterpretable machine
        assert store.machines() == []  # visible server-side, not cached
        store.stop()

    def test_events_list_goes_direct_not_cache(self, api):
        # ADVICE r3 (medium): events are unwatched, so list("events") must
        # LIST the server directly — otherwise orphaned evt-* objects from
        # crashed replicas are invisible to Operator._prune_stored_events
        # and accumulate forever.
        base, _ = api
        a = HttpKubeStore(base)
        a.start()
        a.create("events", "evt-dead-0000001", {
            "name": "evt-dead-0000001", "ts": 1.0, "kind": "Normal",
            "reason": "Launched", "object_ref": "machine/m1",
            "message": "from a replica that crashed"})
        a.stop()
        b = HttpKubeStore(base)  # fresh replica, no watch needed
        b.start()
        try:
            listed = b.list("events")
            assert [e["name"] for e in listed if isinstance(e, dict)
                    and e.get("name")] == ["evt-dead-0000001"]
        finally:
            b.stop()

    def test_delete_if_respects_server_side_precondition(self, api):
        base, state = api
        from karpenter_tpu.leaderelection import Lease

        a = HttpKubeStore(base)
        a.start()
        a.create("leases", "l", Lease("a", 1, 1, 15))
        ours = a.get("leases", "l")
        # a successor CAS-writes behind our back (raw PUT bumps the rv)
        doc = dict(state.bucket("leases")["l"])
        doc.pop("x-karpenter-model", None)
        doc["spec"] = {"holderIdentity": "b", "renewTime": "2026-07-29T00:00:00Z",
                       "leaseDurationSeconds": 15}
        del doc["metadata"]["resourceVersion"]
        req = urllib.request.Request(
            base + "/apis/coordination.k8s.io/v1/namespaces/default/leases/l",
            json.dumps(doc).encode(), {"Content-Type": "application/json"},
            method="PUT")
        urllib.request.urlopen(req).read()
        # our stale-precondition delete must NOT remove the successor's lease
        assert a.delete_if("leases", "l", ours) is False
        assert "l" in state.bucket("leases")
        a.stop()


class TestProvisionerWireFidelity:
    def test_spec_survives_a_pruning_apiserver_round_trip(self):
        """A provisioner written by the counters controller must read back
        with the user's spec intact even when the server PRUNES the
        embedded model (the foreign-apiserver failure mode: a spec-less
        PUT would destroy the user's configuration)."""
        from karpenter_tpu.apis import wellknown as wk
        from karpenter_tpu.apis.provisioner import Limits, Provisioner
        from karpenter_tpu.models.pod import Taint
        from karpenter_tpu.models.requirements import (OP_GT, OP_IN,
                                                       OP_NOT_IN,
                                                       Requirements)

        p = Provisioner(
            name="full", weight=30,
            requirements=Requirements.of(
                (wk.LABEL_CAPACITY_TYPE, OP_IN, ["spot"]),
                (wk.LABEL_ZONE, OP_NOT_IN, ["zone-1c"]),
                ("karpenter.k8s.tpu/instance-cpu", OP_GT, ["15"]),
            ),
            taints=(Taint(key="team", value="ml", effect="NoSchedule"),),
            labels=(("tier", "batch"),),
            limits=Limits(cpu_millis=100_000, memory_bytes=400 * 2**30),
            ttl_seconds_until_expired=2_592_000,
            consolidation_enabled=True,
            provider_ref="default",
        )
        p.set_defaults()
        p.status_resources = {"cpu": "4000m", "memory": "8192Mi",
                              "nodes": "2"}
        doc = serde.to_manifest("provisioners", "full", p)
        doc.pop(serde.MODEL_KEY)  # the pruning apiserver drops it
        back = serde.from_manifest("provisioners", doc)
        assert back.weight == 30
        assert back.limits.cpu_millis == 100_000
        assert back.limits.memory_bytes == 400 * 2**30
        assert back.ttl_seconds_after_empty is None
        assert back.ttl_seconds_until_expired == 2_592_000
        assert back.consolidation_enabled
        assert back.provider_ref == "default"
        assert back.taints == p.taints
        assert dict(back.labels)["tier"] == "batch"
        assert back.status_resources == p.status_resources
        # requirement semantics identical (set-form comparison)
        for key in (wk.LABEL_CAPACITY_TYPE, wk.LABEL_ZONE,
                    "karpenter.k8s.tpu/instance-cpu"):
            assert back.requirements.get(key) == p.requirements.get(key), key

    def test_merged_and_exact_quantities_survive_pruning(self):
        """The adversarial corners: a merged Exists∩NotIn requirement must
        keep its presence demand, and non-Mi-multiple memory quantities
        must not shrink, across a model-pruning round trip."""
        from karpenter_tpu.apis.provisioner import Limits, Provisioner
        from karpenter_tpu.models.requirements import (OP_EXISTS, OP_NOT_IN,
                                                       Requirement,
                                                       Requirements)

        reqs = Requirements()
        reqs.add(Requirement.create("team", OP_EXISTS, []))
        reqs.add(Requirement.create("team", OP_NOT_IN, ["a"]))
        p = Provisioner(name="corner", requirements=reqs,
                        limits=Limits(memory_bytes=100_000_000))
        doc = serde.to_manifest("provisioners", "corner", p)
        doc.pop(serde.MODEL_KEY)
        back = serde.from_manifest("provisioners", doc)
        got = back.requirements.get("team")
        assert got == p.requirements.get("team")
        assert got.requires_presence
        assert back.limits.memory_bytes == 100_000_000

    def test_nodetemplate_spec_survives_a_pruning_apiserver_round_trip(self):
        """The nodetemplate controller PUTs whole objects for status; the
        user's spec must survive model pruning, including native
        family/volume names."""
        from karpenter_tpu.apis.nodetemplate import (BlockDeviceMapping,
                                                     MetadataOptions,
                                                     NodeTemplate,
                                                     NodeTemplateStatus)

        t = NodeTemplate(
            name="rt", image_family="flatboat",
            subnet_selector={"karpenter.sh/discovery": "demo"},
            security_group_selector={"karpenter.sh/discovery": "demo"},
            image_selector={"name": "node-image-*"},
            userdata="[settings.kubernetes]\ncluster-name = 'demo'\n",
            instance_profile="KarpenterNodeRole",
            tags={"team": "ml"},
            metadata_options=MetadataOptions(http_protocol_ipv6="enabled"),
            block_device_mappings=(BlockDeviceMapping(
                device_name="/dev/xvdb", volume_size_gib=500,
                volume_type="throughput", encrypted=True),),
            detailed_monitoring=True,
        )
        t.status = NodeTemplateStatus(
            subnets=[{"id": "subnet-zone-1a", "zone": "zone-1a"}],
            security_groups=["sg-default"],
        )
        doc = serde.to_manifest("nodetemplates", "rt", t)
        doc.pop(serde.MODEL_KEY)
        back = serde.from_manifest("nodetemplates", doc)
        assert back.image_family == "flatboat"
        assert back.subnet_selector == t.subnet_selector
        assert back.security_group_selector == t.security_group_selector
        assert back.image_selector == t.image_selector
        assert back.tags == t.tags
        assert back.detailed_monitoring
        b = back.block_device_mappings[0]
        assert (b.device_name, b.volume_size_gib, b.volume_type) == \
            ("/dev/xvdb", 500, "throughput")
        assert back.status.subnets == t.status.subnets
        assert back.status.security_groups == t.status.security_groups
        assert back.metadata_options == t.metadata_options  # incl. ipv6
        assert back.userdata == t.userdata
        assert back.instance_profile == t.instance_profile

    def test_machine_status_printer_columns_in_real_schema(self):
        """kubectl get machines reads .status.providerID/.status.phase via
        the CRD printer columns — the wire manifest must carry them in
        real schema, not only inside the embedded model."""
        from karpenter_tpu.models.machine import (LAUNCHED, Machine,
                                                  MachineSpec, MachineStatus)

        m = Machine(name="m-1", spec=MachineSpec(provisioner_name="default"),
                    status=MachineStatus(provider_id="tpu://i-001",
                                         state=LAUNCHED,
                                         instance_type="m.large",
                                         zone="zone-1a",
                                         capacity_type="spot",
                                         node_name="ip-10-0-0-1.internal"))
        doc = serde.to_manifest("machines", "m-1", m)
        assert doc["status"]["providerID"] == "tpu://i-001"
        assert doc["status"]["phase"] == LAUNCHED
        assert doc["status"]["nodeName"] == "ip-10-0-0-1.internal"
        assert doc["spec"]["provisionerName"] == "default"
        # embedded model still round-trips exactly
        back = serde.from_manifest("machines", doc)
        assert back.status == m.status and back.spec == m.spec

    def test_cordon_reaches_the_apiserver_as_spec_unschedulable(self, api):
        """Marking a node for deletion must cordon it SERVER-SIDE
        (spec.unschedulable merge-PATCH): on a real cluster kube-scheduler
        keeps scheduling onto a node our solver merely stopped using."""
        base, state = api
        kube = HttpKubeStore(base)
        kube.start()
        try:
            from karpenter_tpu.models.cluster import StateNode

            node = StateNode(name="n-cordon", labels={}, allocatable=[0] * 8,
                             provider_id="tpu://i-1")
            kube.create("nodes", "n-cordon", node)
            kube.cordon_node("n-cordon")
            doc = state.bucket("nodes")["n-cordon"]
            assert doc["spec"].get("unschedulable") is True, doc["spec"]
            # the informer cache reflects it without waiting for the echo
            cached = kube.get("nodes", "n-cordon")
            assert cached.marked_for_deletion
            # the embedded model in the PATCHed doc is STALE (it predates
            # the cordon); the spec override must survive a full relist
            # (the self-undoing-echo regression)
            kube._relist("nodes")
            assert kube.get("nodes", "n-cordon").marked_for_deletion
            # rollback: uncordon clears server spec AND cache
            kube.uncordon_node("n-cordon")
            doc = state.bucket("nodes")["n-cordon"]
            assert "unschedulable" not in doc["spec"], doc["spec"]
            kube._relist("nodes")
            assert not kube.get("nodes", "n-cordon").marked_for_deletion
        finally:
            kube.stop()

    def test_kubectl_annotation_reaches_live_cluster_state(self, api):
        """kubectl annotate node ... karpenter.sh/do-not-consolidate=true
        must flow: apiserver PATCH -> watch echo -> serde metadata override
        -> operator sync hook -> the LIVE cluster-state node the
        deprovisioner's eligibility check reads."""
        import json as _json
        import time as _time
        import urllib.request

        from karpenter_tpu.apis.settings import Settings
        from karpenter_tpu.fake.cloud import FakeCloud
        from karpenter_tpu.models.instancetype import (Catalog,
                                                       make_instance_type)
        from karpenter_tpu.operator import Operator
        from karpenter_tpu.oracle.consolidation import (
            ANNOTATION_DO_NOT_CONSOLIDATE, eligible)

        base, state = api
        cat = Catalog(types=[make_instance_type(
            "m.large", cpu=4, memory="16Gi", od_price=0.20, spot_price=0.07)])
        cloud = FakeCloud(cat)
        for s in cloud.subnets:
            s.tags.setdefault("karpenter.sh/discovery", "anno-test")
        for g in cloud.security_groups:
            g.tags.setdefault("karpenter.sh/discovery", "anno-test")
        kube = HttpKubeStore(base)
        kube.start()
        settings = Settings(cluster_name="anno-test",
                            cluster_endpoint="https://anno",
                            batch_idle_duration=0.0, batch_max_duration=0.0)
        op = Operator(cloud, settings, cat, kube=kube)
        try:
            from karpenter_tpu.apis.nodetemplate import NodeTemplate
            from karpenter_tpu.apis.provisioner import Provisioner
            from karpenter_tpu.models.pod import make_pod

            op.kube.create("nodetemplates", "default", NodeTemplate(
                name="default",
                subnet_selector={"karpenter.sh/discovery": "anno-test"},
                security_group_selector={"karpenter.sh/discovery": "anno-test"}))
            prov = Provisioner(name="default", provider_ref="default",
                               consolidation_enabled=True)
            op.kube.create("provisioners", "default", prov)
            op.kube.create("pods", "w-0", make_pod("w-0", cpu="1",
                                                   memory="1Gi"))
            op.reconcile_all_once()
            op.reconcile_all_once()  # second pass: machine lifecycle flips
            (node_name,) = list(op.cluster.nodes)  # Initialized on pass 2
            assert eligible(op.cluster.nodes[node_name], op.cluster)

            # kubectl annotate: a raw merge-PATCH on metadata.annotations
            req = urllib.request.Request(
                f"{base}/api/v1/nodes/{node_name}",
                _json.dumps({"metadata": {"annotations": {
                    ANNOTATION_DO_NOT_CONSOLIDATE: "true"}}}).encode(),
                {"Content-Type": "application/merge-patch+json"},
                method="PATCH")
            urllib.request.urlopen(req).read()
            # the watch echo carries it into the informer cache and the
            # operator's sync hook copies it onto the LIVE node
            deadline = _time.time() + 5
            live = op.cluster.nodes[node_name]
            while _time.time() < deadline and \
                    live.annotations.get(ANNOTATION_DO_NOT_CONSOLIDATE) != "true":
                _time.sleep(0.05)
            assert live.annotations.get(ANNOTATION_DO_NOT_CONSOLIDATE) == \
                "true", live.annotations
            assert not eligible(live, op.cluster)
        finally:
            op.stop()
            kube.stop()
