"""Randomized / race tier — the `make battletest` analogue (SURVEY.md §4
tier 2: -race -cover --ginkgo.randomize-all -tags random_test_delay).

Three layers:
- hypothesis property tests: kernel/oracle decision parity over a generated
  pod space, quantity parsing laws
- threaded race stress with random delays: batcher fan-out, queue
  at-least-once delivery, TTL/ICE cache coherence under concurrency
- seeded random controller-op churn with global invariants
"""

import random
import threading
import time

import pytest

pytest.importorskip("hypothesis")  # not in every image; skip, don't error
from hypothesis import given, settings, strategies as st  # noqa: E402

from karpenter_tpu.apis import wellknown as wk
from karpenter_tpu.apis.provisioner import Provisioner
from karpenter_tpu.batcher import Batcher
from karpenter_tpu.cache import TTLCache, UnavailableOfferings
from karpenter_tpu.controllers.interruption import FakeQueue
from karpenter_tpu.models.instancetype import Catalog, make_instance_type
from karpenter_tpu.models.pod import TopologySpreadConstraint, make_pod
from karpenter_tpu.models.requirements import OP_IN, Requirements
from karpenter_tpu.oracle.scheduler import Scheduler
from karpenter_tpu.solver.core import TPUSolver
from karpenter_tpu.utils.quantity import cpu_millis, mem_bytes


def battletest_catalog():
    return Catalog(types=[
        make_instance_type("small.2x", cpu=2, memory="8Gi", od_price=0.10, spot_price=0.03),
        make_instance_type("medium.4x", cpu=4, memory="16Gi", od_price=0.20, spot_price=0.06),
        make_instance_type("large.8x", cpu=8, memory="32Gi", od_price=0.40, spot_price=0.12),
        make_instance_type("mem.4x", cpu=4, memory="64Gi", od_price=0.55, spot_price=0.17),
    ])


# -- hypothesis: parity over a generated pod space ---------------------------------

pod_strategy = st.builds(
    dict,
    cpu=st.sampled_from(["100m", "250m", "500m", "1", "1500m", "2", "3", "7"]),
    memory=st.sampled_from(["128Mi", "512Mi", "1Gi", "2Gi", "4Gi", "30Gi"]),
    zone=st.sampled_from(["", "zone-1a", "zone-1b"]),
    spread=st.booleans(),
    capacity=st.sampled_from(["", "spot", "on-demand"]),
    count=st.integers(min_value=1, max_value=12),
)


def pods_from_specs(specs, prefix=""):
    """Expand pod_strategy spec dicts into pods (shared by the parity and
    ICE-churn fuzz tests so their generators cannot diverge)."""
    pods = []
    for si, spec in enumerate(specs):
        sel = {wk.LABEL_ZONE: spec["zone"]} if spec["zone"] else {}
        if spec["capacity"]:
            sel[wk.LABEL_CAPACITY_TYPE] = spec["capacity"]
        topo = (TopologySpreadConstraint(max_skew=1, topology_key=wk.LABEL_ZONE),) \
            if spec["spread"] else ()
        for i in range(spec["count"]):
            pods.append(make_pod(f"{prefix}g{si}-p{i}", cpu=spec["cpu"],
                                 memory=spec["memory"], node_selector=dict(sel),
                                 topology=topo))
    return pods


@settings(max_examples=25, deadline=None)
@given(st.lists(pod_strategy, min_size=1, max_size=6))
def test_fuzz_parity_kernel_vs_oracle(specs):
    """Kernel decisions must be bit-identical to the scalar oracle on any
    workload the generator produces (FIXED catalog so compiled shapes are
    reused across examples)."""
    catalog = battletest_catalog()
    prov = Provisioner(name="default", requirements=Requirements.of(
        (wk.LABEL_CAPACITY_TYPE, OP_IN, ["spot", "on-demand"])))
    prov.set_defaults()
    pods = pods_from_specs(specs)
    sched = Scheduler(catalog, [prov])
    oracle = sched.schedule(list(pods))
    kernel = TPUSolver(catalog, [prov]).solve(list(pods))
    assert kernel.decisions() == oracle.node_decisions(sched.options)
    assert kernel.unschedulable_count() == len(oracle.unschedulable)


# -- hypothesis: wave batching parity over generated problem mixes -----------------

wave_problem_strategy = st.builds(
    dict,
    cpu=st.sampled_from(["250m", "500m", "1", "2"]),
    memory=st.sampled_from(["512Mi", "1Gi", "4Gi"]),
    count=st.integers(min_value=1, max_value=40),
)


@settings(max_examples=15, deadline=None)
@given(st.lists(wave_problem_strategy, min_size=1, max_size=6))
def test_fuzz_wave_solve_many_matches_solo(mixes):
    """solve_many's shape-bucketed vmapped dispatch (+ K padding, offset
    math into the concatenated read) must match per-problem solve() for
    any mix of problem sizes — same-bucket, cross-bucket, and padded-lane
    cases all arise from the generator."""
    catalog = battletest_catalog()
    prov = Provisioner(name="default")
    prov.set_defaults()
    solver = TPUSolver(catalog, [prov])
    problems = [{"pods": [make_pod(f"m{mi}-p{i}", cpu=m["cpu"],
                                   memory=m["memory"])
                          for i in range(m["count"])]}
                for mi, m in enumerate(mixes)]
    wave = solver.solve_many(problems)
    for w, pr in zip(wave, problems):
        s = solver.solve(**pr)
        assert w.decisions() == s.decisions()
        placed = sum(n.pod_count for n in w.nodes)
        assert placed + w.unschedulable_count() == len(pr["pods"])


# -- hypothesis: consolidation parity over generated clusters ----------------------

cnode_strategy = st.builds(
    dict,
    type_idx=st.integers(min_value=0, max_value=3),
    zone=st.sampled_from(["zone-1a", "zone-1b"]),
    # spot nodes take the delete-only consolidation path (reference
    # deprovisioning.md:88) — the fuzz must cover both gates
    capacity=st.sampled_from(["on-demand", "spot"]),
    pods=st.lists(
        st.builds(dict,
                  cpu=st.sampled_from(["100m", "500m", "1", "2", "3"]),
                  memory=st.sampled_from(["128Mi", "1Gi", "4Gi", "16Gi"]),
                  pinned=st.booleans()),
        min_size=0, max_size=3),
    marked=st.booleans(),
)


def build_consolidation_cluster(catalog, nodespecs):
    """Shared cluster builder for the consolidation fuzz tests."""
    from karpenter_tpu.models.cluster import ClusterState, StateNode

    cluster = ClusterState()
    for ni, nspec in enumerate(nodespecs):
        itype = catalog.types[nspec["type_idx"]]
        ct = nspec.get("capacity", "on-demand")
        price = next((o.price for o in itype.offerings
                      if o.capacity_type == ct and o.zone == nspec["zone"]),
                     itype.offerings[0].price)
        pods = [make_pod(f"c{ni}-p{pi}", cpu=p["cpu"], memory=p["memory"],
                         node_name=f"cn-{ni:02d}", do_not_evict=p["pinned"])
                for pi, p in enumerate(nspec["pods"])]
        cluster.add_node(StateNode(
            name=f"cn-{ni:02d}",
            labels={**itype.labels_dict(), wk.LABEL_ZONE: nspec["zone"],
                    wk.LABEL_CAPACITY_TYPE: ct,
                    wk.LABEL_PROVISIONER: "default"},
            allocatable=itype.allocatable_vector(),
            instance_type=itype.name, zone=nspec["zone"],
            capacity_type=ct, price=price,
            provisioner_name="default", pods=pods,
            marked_for_deletion=nspec["marked"]))
    return cluster


@settings(max_examples=10, deadline=None)
@given(st.lists(cnode_strategy, min_size=2, max_size=6))
def test_fuzz_multi_node_consolidation_parity(nodespecs):
    """Full-chain parity incl. the PAIR sweep: the batched pair grid runs
    FIRST (reference mechanism order) and must pick the same action as the
    oracle's sequential find_multi_consolidation, falling back to the
    single sweep identically (or the same no-action)."""
    from karpenter_tpu.ops.consolidate import run_consolidation
    from karpenter_tpu.oracle.consolidation import (find_consolidation,
                                                    find_multi_consolidation)

    catalog = battletest_catalog()
    cluster = build_consolidation_cluster(catalog, nodespecs)
    prov = Provisioner(name="default", consolidation_enabled=True)
    prov.set_defaults()
    kernel = run_consolidation(cluster, catalog, [prov], multi_node=True)
    oracle = find_multi_consolidation(cluster, catalog, [prov])
    if oracle is None:
        oracle = find_consolidation(cluster, catalog, [prov])
    assert (kernel is None) == (oracle is None), (kernel, oracle)
    if kernel is not None:
        assert (kernel.kind, kernel.nodes, kernel.replacement) == \
            (oracle.kind, oracle.nodes, oracle.replacement), (kernel, oracle)


@settings(max_examples=25, deadline=None)
@given(st.lists(cnode_strategy, min_size=1, max_size=7))
def test_fuzz_consolidation_parity_kernel_vs_oracle(nodespecs):
    """The batched consolidation sweep (unique-row feas table, shared
    ex_used, price-memoized cheaper-option mask) must pick the same
    single-node action as the scalar oracle on any generated cluster —
    including no-action, do-not-evict pods, and draining nodes."""
    from karpenter_tpu.ops.consolidate import run_consolidation
    from karpenter_tpu.oracle.consolidation import find_consolidation

    catalog = battletest_catalog()
    cluster = build_consolidation_cluster(catalog, nodespecs)
    prov = Provisioner(name="default", consolidation_enabled=True)
    prov.set_defaults()
    kernel = run_consolidation(cluster, catalog, [prov], multi_node=False)
    oracle = find_consolidation(cluster, catalog, [prov])
    assert (kernel is None) == (oracle is None), (kernel, oracle)
    if kernel is not None:
        assert (kernel.kind, kernel.nodes, kernel.replacement) == \
            (oracle.kind, oracle.nodes, oracle.replacement), (kernel, oracle)


@settings(max_examples=50, deadline=None)
@given(st.integers(min_value=0, max_value=10**15))
def test_fuzz_quantity_cpu_millis_roundtrip(n):
    assert cpu_millis(f"{n}m") == n
    assert cpu_millis(str(n)) == n * 1000


@settings(max_examples=50, deadline=None)
@given(st.integers(min_value=0, max_value=2**50),
       st.sampled_from(["", "Ki", "Mi", "Gi", "k", "M", "G"]))
def test_fuzz_quantity_mem_bytes_monotone(n, suffix):
    a = mem_bytes(f"{n}{suffix}")
    b = mem_bytes(f"{n + 1}{suffix}")
    assert 0 <= a < b


# -- threaded race stress ----------------------------------------------------------

class TestBatcherRaces:
    def test_concurrent_adds_each_caller_gets_own_result(self):
        delays = random.Random(7)

        def exec_fn(requests):
            time.sleep(delays.random() * 0.01)  # random_test_delay analogue
            return [r * 10 for r in requests]

        b = Batcher(exec_fn, idle_seconds=0.005, max_seconds=0.05, max_items=64)
        results = {}
        errors = []

        def worker(i):
            try:
                results[i] = b.add(i)
            except Exception as e:  # pragma: no cover
                errors.append(e)

        threads = [threading.Thread(target=worker, args=(i,)) for i in range(100)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=10)
        b.stop()
        assert not errors
        assert results == {i: i * 10 for i in range(100)}

    def test_stop_resolves_inflight_callers(self):
        release = threading.Event()

        def exec_fn(requests):
            release.wait(2)
            return list(requests)

        b = Batcher(exec_fn, idle_seconds=5.0, max_seconds=10.0, max_items=1000)
        out = {}
        t = threading.Thread(target=lambda: out.setdefault("r", b.add(1)))
        t.start()
        time.sleep(0.05)
        release.set()
        b.stop()
        t.join(timeout=5)
        assert out.get("r") == 1


class TestQueueRaces:
    def test_concurrent_producers_consumers_at_least_once(self):
        q = FakeQueue(visibility_seconds=60)
        N = 500
        seen = set()
        seen_lock = threading.Lock()

        def produce(base):
            for i in range(N // 5):
                q.send(f"msg-{base + i}")

        def consume():
            idle = 0
            while idle < 20:
                msgs = q.receive(max_messages=10)
                if not msgs:
                    idle += 1
                    time.sleep(0.002)
                    continue
                idle = 0
                for m in msgs:
                    with seen_lock:
                        seen.add(m.body)
                    q.delete(m.receipt)

        producers = [threading.Thread(target=produce, args=(i * (N // 5),))
                     for i in range(5)]
        consumers = [threading.Thread(target=consume) for _ in range(4)]
        for t in producers + consumers:
            t.start()
        for t in producers:
            t.join(timeout=10)
        for t in consumers:
            t.join(timeout=10)
        assert seen == {f"msg-{i}" for i in range(N)}
        assert q.approximate_depth() == 0


class TestCacheRaces:
    def test_ttl_cache_concurrent_mixed_ops(self):
        cache = TTLCache(ttl=0.05)
        stop = threading.Event()
        errors = []

        def hammer(seed):
            rng = random.Random(seed)
            while not stop.is_set():
                try:
                    k = rng.randrange(20)
                    op = rng.random()
                    if op < 0.4:
                        cache.set(k, k * 2)
                    elif op < 0.8:
                        v = cache.get(k)
                        assert v is None or v == k * 2
                    elif op < 0.9:
                        cache.delete(k)
                    else:
                        cache.get_or_load(k, lambda k=k: k * 2)
                except Exception as e:  # pragma: no cover
                    errors.append(e)
                    return

        threads = [threading.Thread(target=hammer, args=(i,)) for i in range(8)]
        for t in threads:
            t.start()
        time.sleep(0.4)
        stop.set()
        for t in threads:
            t.join(timeout=5)
        assert not errors

    def test_ice_cache_seqnum_monotone_under_concurrency(self):
        ice = UnavailableOfferings()
        seqs = []

        def mark(i):
            ice.mark_unavailable("test", f"t{i % 5}.x", "zone-1a", "spot")
            seqs.append(ice.seqnum)

        threads = [threading.Thread(target=mark, args=(i,)) for i in range(50)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=5)
        assert ice.seqnum >= max(seqs)
        assert ice.is_unavailable("spot", "t0.x", "zone-1a")


# -- seeded random controller churn ------------------------------------------------

@pytest.mark.parametrize("seed", [1, 2, 3])
def test_random_controller_op_churn_invariants(seed):
    """Random op sequence over the full controller plane; after every step the
    global invariants must hold (the randomize-all battletest analogue)."""
    from karpenter_tpu.apis.nodetemplate import NodeTemplate
    from karpenter_tpu.apis.settings import Settings
    from karpenter_tpu.fake.cloud import FakeCloud
    from karpenter_tpu.operator import Operator
    from karpenter_tpu.utils.clock import FakeClock

    rng = random.Random(seed)
    clock = FakeClock()
    catalog = battletest_catalog()
    cloud = FakeCloud(catalog=catalog, clock=clock)
    settings = Settings(cluster_name="battle", cluster_endpoint="https://k",
                        interruption_queue_name="bq",
                        batch_idle_duration=0.0, batch_max_duration=0.0)
    op = Operator(cloud, settings, catalog, clock=clock)
    op.kube.create("nodetemplates", "default", NodeTemplate(
        name="default",
        subnet_selector={"id": "subnet-zone-1a,subnet-zone-1b,subnet-zone-1c"},
        security_group_selector={"id": "sg-default"}))
    p = Provisioner(name="default", provider_ref="default",
                    ttl_seconds_after_empty=30)
    op.kube.create("provisioners", "default", p)

    pod_i = 0
    controllers = [
        op.provisioning.reconcile_once,
        op.termination.reconcile_once,
        op.deprovisioning.reconcile_once,
        op.nodetemplate.reconcile_once,
        op.machinehydration.reconcile_once,
        lambda: op.interruption.reconcile_once(),
    ]
    try:
        for step in range(60):
            roll = rng.random()
            if roll < 0.35:
                for _ in range(rng.randrange(1, 6)):
                    op.kube.create("pods", f"p{pod_i}", make_pod(
                        f"p{pod_i}", cpu=rng.choice(["250m", "1", "2"]),
                        memory=rng.choice(["256Mi", "1Gi", "4Gi"])))
                    pod_i += 1
            elif roll < 0.5 and op.kube.pods():
                victim = rng.choice(op.kube.pods())
                op.kube.delete("pods", victim.name)
                if victim.node_name and victim.node_name in op.cluster.nodes:
                    node = op.cluster.nodes[victim.node_name]
                    node.pods = [q for q in node.pods if q.name != victim.name]
            elif roll < 0.6:
                clock.step(rng.randrange(1, 60))
            # run a random subset of controllers in random order
            order = rng.sample(controllers, k=rng.randrange(1, len(controllers)))
            for fn in order:
                fn()

            # -- invariants -----------------------------------------------------
            for node in op.cluster.nodes.values():
                used = node.used_vector()
                assert all(u <= a for u, a in zip(used, node.allocatable)), \
                    f"seed={seed} step={step}: node {node.name} overpacked"
            for pod in op.kube.pods():
                if pod.node_name:
                    assert pod.node_name in op.cluster.nodes, \
                        f"seed={seed} step={step}: pod {pod.name} bound to ghost"
        # drain: everything pending must eventually schedule
        op.provisioning.reconcile_once()
        assert not op.kube.pending_pods()
    finally:
        op.stop()


class TestCoordinationRaces:
    """Race tier for the round-3 surfaces: the HTTP store under concurrent
    writers + watchers, and leader election under tick storms."""

    def test_http_store_concurrent_writers_and_watchers(self):
        import threading
        import time as _time

        from karpenter_tpu.coordination.httpkube import HttpKubeStore
        from karpenter_tpu.fake.apiserver import serve
        from karpenter_tpu.fake.kube import Conflict
        from karpenter_tpu.models.pod import make_pod

        srv, port, state = serve()
        stores = [HttpKubeStore(f"http://127.0.0.1:{port}") for _ in range(3)]
        try:
            for s in stores:
                s.start()
            seen = []
            stores[2].watch(lambda k, a, o: seen.append((k, a)))
            errors = []

            def writer(i):
                try:
                    for j in range(20):
                        stores[i].create(
                            "pods", f"w{i}-p{j}",
                            make_pod(f"w{i}-p{j}", cpu="1", memory="1Gi"))
                except Exception as e:
                    errors.append(e)

            def conflict_writer():
                # every writer races the same name: exactly one must win
                wins = 0
                for s in stores[:2]:
                    try:
                        s.create("pods", "contested",
                                 make_pod("contested", cpu="1", memory="1Gi"))
                        wins += 1
                    except Conflict:
                        pass
                if wins != 1:
                    errors.append(AssertionError(f"wins={wins}"))

            threads = [threading.Thread(target=writer, args=(i,))
                       for i in range(2)]
            threads.append(threading.Thread(target=conflict_writer))
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            assert not errors, errors
            # server-side truth: every pod landed exactly once
            assert len(state.bucket("pods")) == 41
            # all caches converge; the watcher saw the churn
            deadline = _time.monotonic() + 10
            while _time.monotonic() < deadline and any(
                    len(s.pods()) < 41 for s in stores):
                _time.sleep(0.05)
            assert all(len(s.pods()) == 41 for s in stores)
            assert sum(1 for k, a in seen if k == "pods" and a == "added") >= 40
        finally:
            for s in stores:
                s.stop()
            srv.shutdown()

    def test_election_tick_storm_exactly_one_leader(self):
        import threading

        from karpenter_tpu.fake.kube import KubeStore
        from karpenter_tpu.leaderelection import LeaderElector
        from karpenter_tpu.utils.clock import FakeClock

        kube, clock = KubeStore(), FakeClock()
        electors = [LeaderElector(kube, f"e{i}", clock=clock,
                                  lease_duration_s=10)
                    for i in range(6)]
        stop = threading.Event()
        errors = []

        def storm(e):
            try:
                for _ in range(50):
                    e.try_acquire_or_renew()
                    if stop.is_set():
                        return
            except Exception as ex:
                errors.append(ex)

        threads = [threading.Thread(target=storm, args=(e,)) for e in electors]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        stop.set()
        assert not errors, errors
        leaders = [e for e in electors if e.is_leader()]
        assert len(leaders) == 1
        lease = kube.get("leases", electors[0].name)
        assert lease is not None and lease.holder == leaders[0].identity


# -- extended parity fuzz: the round-3 semantics space -----------------------------
# (affinity terms x residents x soft/hard spread x existing nodes; the deep
# offline session that found the round-2 overcommit ran this generator at
# 3900 cases — this keeps the space covered in-tree)

rich_group_strategy = st.builds(
    dict,
    app=st.sampled_from(["a", "b", "c"]),
    cpu=st.sampled_from(["100m", "500m", "1", "2"]),
    memory=st.sampled_from(["128Mi", "1Gi", "4Gi"]),
    count=st.integers(min_value=1, max_value=5),
    aa_host=st.booleans(),
    spread=st.sampled_from(["", "DoNotSchedule", "ScheduleAnyway"]),
    zone=st.sampled_from(["", "zone-1a", "zone-1b"]),
    term=st.sampled_from(["", "aff-zone", "aff-host", "anti-zone", "anti-host"]),
    term_app=st.sampled_from(["a", "b", "c"]),
)

resident_strategy = st.builds(
    dict,
    zone=st.sampled_from(["zone-1a", "zone-1b", "zone-1c"]),
    apps=st.lists(st.sampled_from(["a", "b", "c"]), max_size=3),
)


@settings(max_examples=15, deadline=None)
@given(st.lists(rich_group_strategy, min_size=1, max_size=3),
       st.lists(resident_strategy, max_size=2))
def test_fuzz_parity_affinity_residents_space(groups, nodes):
    from karpenter_tpu.models.pod import PodAffinityTerm
    from karpenter_tpu.oracle.scheduler import ExistingNode
    from karpenter_tpu.solver.core import NativeSolver

    pods = []
    for gi, g in enumerate(groups):
        kw = {}
        if g["aa_host"]:
            kw["anti_affinity_hostname"] = True
        if g["spread"]:
            kw["topology"] = (TopologySpreadConstraint(
                max_skew=1, topology_key=wk.LABEL_ZONE,
                when_unsatisfiable=g["spread"]),)
        if g["term"]:
            mode, key = g["term"].split("-")
            term = PodAffinityTerm(
                match_labels=(("app", g["term_app"]),),
                topology_key=wk.LABEL_ZONE if key == "zone" else wk.LABEL_HOSTNAME)
            kw["pod_affinity" if mode == "aff" else "pod_anti_affinity"] = (term,)
        sel = {wk.LABEL_ZONE: g["zone"]} if g["zone"] else {}
        for i in range(g["count"]):
            pods.append(make_pod(f"g{gi}-{i}", cpu=g["cpu"], memory=g["memory"],
                                 labels=(("app", g["app"]),),
                                 node_selector=dict(sel), **kw))

    def mk_existing():
        out = []
        for ei, n in enumerate(nodes):
            res = tuple(make_pod(f"res{ei}-{ri}", cpu="500m", memory="1Gi",
                                 labels=(("app", app),), node_name=f"ex-{ei}")
                        for ri, app in enumerate(n["apps"]))
            used = [0] * wk.NUM_RESOURCES
            for p in res:
                for i, v in enumerate(p.resource_vector()):
                    used[i] += v
            out.append(ExistingNode(
                name=f"ex-{ei}",
                labels={wk.LABEL_ARCH: "amd64", wk.LABEL_OS: "linux",
                        wk.LABEL_ZONE: n["zone"],
                        wk.LABEL_CAPACITY_TYPE: "on-demand"},
                allocatable=wk.capacity_vector({wk.RESOURCE_CPU: 8000,
                                                wk.RESOURCE_MEMORY: 32 * 2**30,
                                                wk.RESOURCE_PODS: 110}),
                used=list(used), resident=res))
        return out

    cat = battletest_catalog()
    prov = Provisioner(name="default", requirements=Requirements.of(
        (wk.LABEL_CAPACITY_TYPE, OP_IN, ["spot", "on-demand"])))
    prov.set_defaults()
    sched = Scheduler(cat, [prov])
    o = sched.schedule(list(pods), existing=mk_existing())
    k = TPUSolver(cat, [prov]).solve(list(pods), existing=mk_existing())
    n = NativeSolver(cat, [prov]).solve(list(pods), existing=mk_existing())
    o_ex = {kk: len(v) for kk, v in o.existing_assignments.items() if v}
    assert o.node_decisions(sched.options) == k.decisions() == n.decisions()
    assert o_ex == k.existing_counts == n.existing_counts
    assert len(o.unschedulable) == k.unschedulable_count() == n.unschedulable_count()


class TestRound4Races:
    """Race tier for the round-4 surfaces: wave solves sharing one solver,
    and concurrent account-file persistence."""

    def test_concurrent_waves_and_solos_on_one_solver(self):
        cat = battletest_catalog()
        prov = Provisioner(name="default", requirements=Requirements.of(
            (wk.LABEL_CAPACITY_TYPE, OP_IN, ["spot", "on-demand"])))
        prov.set_defaults()
        solver = TPUSolver(cat, [prov])
        pods = [make_pod(f"w-{i}", cpu="500m", memory="1Gi")
                for i in range(24)]
        want = solver.solve(list(pods)).decisions()
        errors: "list[BaseException]" = []

        def wave():
            try:
                for r in solver.solve_many([{"pods": list(pods)}] * 3):
                    assert r.decisions() == want
            except BaseException as e:  # noqa: BLE001
                errors.append(e)

        def solo():
            try:
                for _ in range(3):
                    assert solver.solve(list(pods)).decisions() == want
            except BaseException as e:  # noqa: BLE001
                errors.append(e)

        threads = [threading.Thread(target=f)
                   for f in (wave, solo, wave, solo)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors, errors

    def test_concurrent_account_saves_never_corrupt_the_file(self, tmp_path):
        import json

        from karpenter_tpu.fake.cloud import (CloudInstance, FakeCloud)

        path = str(tmp_path / "account.json")
        clouds = []
        for k in range(3):
            c = FakeCloud()
            for i in range(20):
                iid = f"i-{k}-{i}"
                c.instances[iid] = CloudInstance(
                    id=iid, instance_type="m.large", zone="zone-1a",
                    capacity_type="on-demand")
            clouds.append(c)

        stop = threading.Event()
        errors: "list[BaseException]" = []

        def saver(c):
            while not stop.is_set():
                try:
                    c.save_state(path)
                except BaseException as e:  # noqa: BLE001
                    errors.append(e)
                    return

        threads = [threading.Thread(target=saver, args=(c,)) for c in clouds]
        for t in threads:
            t.start()
        try:
            deadline = time.time() + 1.5
            reads = 0
            while time.time() < deadline:
                try:
                    doc = json.loads(open(path).read())
                except FileNotFoundError:
                    continue
                # every observable state is a COMPLETE snapshot of one writer
                assert len(doc["instances"]) == 20
                reads += 1
        finally:
            stop.set()
            for t in threads:
                t.join(timeout=5)
        assert not errors, errors
        assert reads > 10
        fresh = FakeCloud()
        fresh.load_state(path)
        assert len(fresh.instances) == 20


class TestSerdeFuzz:
    """Differential fuzz for the wire-fidelity layer: ANY valid provisioner
    must survive to_manifest -> model-pruning -> from_manifest with
    identical scheduling semantics (the real-apiserver path the counters
    controller writes through)."""

    @settings(max_examples=120, deadline=None)
    @given(st.data())
    def test_provisioner_pruning_round_trip(self, data):
        from karpenter_tpu.apis.provisioner import Limits, Provisioner
        from karpenter_tpu.coordination import serde
        from karpenter_tpu.models.pod import Taint
        from karpenter_tpu.models.requirements import (
            IncompatibleError, Requirement)

        keys = ["team", "tier", wk.LABEL_ZONE, wk.LABEL_CAPACITY_TYPE,
                "karpenter.k8s.tpu/instance-cpu"]
        reqs = Requirements()
        for key in data.draw(st.lists(st.sampled_from(keys), unique=True,
                                      max_size=4)):
            numeric = key.endswith("instance-cpu")
            op = data.draw(st.sampled_from(
                ["In", "NotIn", "Exists", "Gt", "Lt"] if numeric
                else ["In", "NotIn", "Exists", "DoesNotExist"]))
            values: "list[str]" = []
            if op in ("In", "NotIn"):
                values = [str(v) for v in data.draw(st.lists(
                    st.integers(0, 99) if numeric
                    else st.sampled_from(["a", "b", "zone-1a", "spot",
                                          "on-demand"]),
                    min_size=0 if op == "In" else 1, max_size=3,
                    unique=True))]
            elif op in ("Gt", "Lt"):
                values = [str(data.draw(st.integers(1, 500)))]
            try:
                reqs.add(Requirement.create(key, op, values))
            except IncompatibleError:
                return  # self-conflicting draw; nothing to round-trip
        p = Provisioner(
            name="fuzz",
            requirements=reqs,
            taints=tuple(Taint(key=f"t{i}", value=data.draw(
                st.sampled_from(["", "v"])), effect="NoSchedule")
                for i in range(data.draw(st.integers(0, 2)))),
            weight=data.draw(st.integers(0, 100)),
            limits=Limits(
                cpu_millis=data.draw(st.one_of(
                    st.none(), st.integers(1, 10**7))),
                memory_bytes=data.draw(st.one_of(
                    st.none(), st.integers(1, 2**40)))),
            consolidation_enabled=data.draw(st.booleans()),
            provider_ref="default",
        )
        doc = serde.to_manifest("provisioners", "fuzz", p)
        doc.pop(serde.MODEL_KEY)
        back = serde.from_manifest("provisioners", doc)
        # set_defaults runs on parse; compare against the defaulted original
        p.set_defaults()
        assert back.requirements.to_specs() == p.requirements.to_specs()
        assert back.taints == p.taints
        assert back.weight == p.weight
        assert back.limits == p.limits
        assert back.consolidation_enabled == p.consolidation_enabled


# -- hypothesis: ICE-churn parity with a persistent solver + cache -----------------

ice_step_strategy = st.builds(
    dict,
    # which pool flips this step (type x zone x ct), and to which state —
    # expiry (re-available) is as load-bearing as marking: the static-grid
    # fast path must track BOTH directions through the two-level cache
    flip_type=st.integers(min_value=0, max_value=3),
    flip_zone=st.sampled_from(["zone-1a", "zone-1b", "zone-1c"]),
    flip_ct=st.sampled_from(["spot", "on-demand"]),
    available=st.booleans(),
    pods=st.lists(pod_strategy, min_size=1, max_size=3),
)


@settings(max_examples=15, deadline=None)
@given(st.lists(ice_step_strategy, min_size=2, max_size=5))
def test_fuzz_ice_churn_persistent_solver_matches_fresh_oracle(steps):
    """A LONG-LIVED solver chain (each step's solver adopts the last, the
    group cache's static level persisting across availability flips) must
    decide identically to a FRESH oracle built from scratch every step —
    the staleness trap the static-grid/dynamic-availability split could
    introduce if any availability-dependent state leaked into the reused
    layer."""
    import dataclasses

    catalog = battletest_catalog()
    prov = Provisioner(name="default", requirements=Requirements.of(
        (wk.LABEL_CAPACITY_TYPE, OP_IN, ["spot", "on-demand"])))
    prov.set_defaults()
    solver = TPUSolver(catalog, [prov])
    for si, step in enumerate(steps):
        # flip one pool's availability on a FRESH catalog object (the
        # provider rebuilds per seqnum the same way)
        tname = catalog.types[step["flip_type"] % len(catalog.types)].name
        new_types = []
        for t in catalog.types:
            if t.name != tname:
                new_types.append(t)
                continue
            new_types.append(dataclasses.replace(t, offerings=type(t.offerings)(
                tuple(dataclasses.replace(o, available=step["available"])
                      if (o.zone == step["flip_zone"]
                          and o.capacity_type == step["flip_ct"]) else o
                      for o in t.offerings))))
        catalog = Catalog(types=new_types, seqnum=catalog.seqnum + 1)
        nxt = TPUSolver(catalog, [prov])
        nxt.adopt_static(solver)
        solver = nxt

        pods = pods_from_specs(step["pods"], prefix=f"s{si}-")
        sched = Scheduler(catalog, [prov])  # fresh spec, no reused state
        oracle = sched.schedule(list(pods))
        kernel = solver.solve(list(pods))
        assert kernel.decisions() == oracle.node_decisions(sched.options), \
            f"divergence at step {si} after flipping {tname}/" \
            f"{step['flip_zone']}/{step['flip_ct']}->{step['available']}"
        assert kernel.unschedulable_count() == len(oracle.unschedulable)
