"""Replay harness: the REFERENCE's own example manifests, loaded unchanged,
scheduled by this framework with kernel/oracle decision parity
(SURVEY.md §7.2 step 1; BASELINE.json configs[0] names inflate.yaml).

Files under /root/reference/examples/ are read directly; nothing is copied
or edited — the switch-over contract is that a reference user's manifests
work as-is.
"""

import os

import pytest

from karpenter_tpu.apis import wellknown as wk
from karpenter_tpu.apis.yaml_compat import load_files, load_manifests
from karpenter_tpu.oracle.scheduler import Scheduler
from karpenter_tpu.providers.instancetypes import generate_fleet_catalog
from karpenter_tpu.solver.core import TPUSolver

REF = "/root/reference/examples"
ENV = {"CLUSTER_NAME": "replay"}

pytestmark = pytest.mark.skipif(
    not os.path.isdir(REF), reason="reference examples not mounted")


def schedule_with_parity(loaded, catalog=None):
    catalog = catalog or generate_fleet_catalog()
    provs = loaded.provisioners
    sched = Scheduler(catalog, provs)
    oracle = sched.schedule(list(loaded.pods))
    kernel = TPUSolver(catalog, provs).solve(list(loaded.pods))
    assert kernel.decisions() == oracle.node_decisions(sched.options)
    assert kernel.unschedulable_count() == len(oracle.unschedulable)
    return kernel


class TestProvisionerManifests:
    def test_every_provisioner_example_parses(self):
        files = [f for f in os.listdir(f"{REF}/provisioner") if f.endswith(".yaml")]
        assert len(files) >= 7
        for f in files:
            loaded = load_files(f"{REF}/provisioner/{f}", env=ENV)
            assert loaded.provisioners, f
            assert loaded.templates, f
            # providerRef wiring intact
            assert loaded.provisioners[0].provider_ref == loaded.templates[0].name

    def test_cpu_limit(self):
        loaded = load_files(f"{REF}/provisioner/100-cpu-limit.yaml", env=ENV)
        assert loaded.provisioners[0].limits.cpu_millis == 100_000

    def test_spot(self):
        loaded = load_files(f"{REF}/provisioner/spot.yaml", env=ENV)
        req = loaded.provisioners[0].requirements.get(wk.LABEL_CAPACITY_TYPE)
        assert req is not None and req.has("spot") and not req.has("on-demand")

    def test_node_ttls(self):
        loaded = load_files(f"{REF}/provisioner/node-ttls.yaml", env=ENV)
        p = loaded.provisioners[0]
        assert p.ttl_seconds_until_expired == 604800
        assert p.ttl_seconds_after_empty == 60

    def test_bottlerocket_family_and_block_devices(self):
        loaded = load_files(f"{REF}/provisioner/bottlerocket.yaml", env=ENV)
        t = loaded.templates[0]
        assert t.image_family == "flatboat"  # Bottlerocket analogue
        assert len(t.block_device_mappings) == 2
        assert t.block_device_mappings[1].volume_size_gib == 20

    def test_large_instances_notin(self):
        loaded = load_files(f"{REF}/provisioner/large-instances.yaml", env=ENV)
        req = loaded.provisioners[0].requirements.get(wk.LABEL_INSTANCE_TYPE)
        assert req is not None and not req.has("t3.small")
        assert req.has("m5.4xlarge")  # NotIn: anything not listed passes

    def test_inline_provider_becomes_anonymous_nodetemplate(self):
        # the v1alpha4 inline vendor block (designs/v1alpha4-api.md;
        # provisioner.go:38 DeserializeProvider) still loads
        loaded = load_manifests("""
apiVersion: karpenter.sh/v1alpha5
kind: Provisioner
metadata:
  name: legacy
spec:
  provider:
    amiFamily: Bottlerocket
    instanceProfile: legacyProfile
    subnetSelector:
      karpenter.sh/discovery: demo
    securityGroupSelector:
      karpenter.sh/discovery: demo
""")
        p = loaded.provisioners[0]
        assert p.provider_ref == "legacy"
        t = loaded.templates[0]
        assert (t.name, t.image_family, t.instance_profile) == \
            ("legacy", "flatboat", "legacyProfile")

    def test_inline_provider_and_providerref_are_exclusive(self):
        import pytest

        from karpenter_tpu.apis.provisioner import ValidationError

        with pytest.raises(ValidationError, match="mutually exclusive"):
            load_manifests("""
apiVersion: karpenter.sh/v1alpha5
kind: Provisioner
metadata:
  name: both
spec:
  providerRef:
    name: other
  provider:
    subnetSelector:
      karpenter.sh/discovery: demo
""")

    def test_inline_provider_collision_with_explicit_template_rejected(self):
        import pytest

        from karpenter_tpu.apis.provisioner import ValidationError

        with pytest.raises(ValidationError, match="collides"):
            load_manifests("""
apiVersion: karpenter.sh/v1alpha5
kind: Provisioner
metadata:
  name: foo
spec:
  provider:
    subnetSelector:
      karpenter.sh/discovery: demo
---
apiVersion: karpenter.k8s.tpu/v1alpha1
kind: NodeTemplate
metadata:
  name: foo
spec:
  subnetSelector:
    karpenter.sh/discovery: demo
""")

    def test_explicit_null_spec_parses(self):
        loaded = load_manifests("""
apiVersion: karpenter.sh/v1alpha5
kind: Provisioner
metadata:
  name: empty
spec:
---
apiVersion: karpenter.k8s.tpu/v1alpha1
kind: NodeTemplate
metadata:
  name: empty
spec:
""")
        assert loaded.provisioners[0].name == "empty"
        assert loaded.templates[0].name == "empty"

    def test_removed_v1alpha3_scalars_fail_loudly(self):
        import pytest

        from karpenter_tpu.apis.provisioner import ValidationError

        for field in ("architecture", "operatingSystem", "cluster"):
            with pytest.raises(ValidationError, match="removed in v1alpha4"):
                load_manifests(f"""
apiVersion: karpenter.sh/v1alpha5
kind: Provisioner
metadata:
  name: old
spec:
  {field}: whatever
""")


class TestWorkloadReplay:
    def load_workload(self, name, replicas=None):
        return load_files(
            f"{REF}/provisioner/general-purpose.yaml",
            f"{REF}/workloads/{name}", env=ENV, replicas_override=replicas)

    def test_inflate_100(self):
        # BASELINE configs[0]: 100 x (1 cpu, 256M), single provisioner
        loaded = self.load_workload("inflate.yaml", replicas=100)
        assert len(loaded.pods) == 100
        vec = dict(loaded.pods[0].requests)
        assert vec["cpu"] == 1000 and vec["memory"] == 256 * 10**6
        result = schedule_with_parity(loaded)
        assert result.unschedulable_count() == 0
        placed = sum(n.pod_count for n in result.nodes)
        assert placed == 100

    def test_spread_zone_balanced(self):
        loaded = self.load_workload("spread-zone.yaml", replicas=9)
        result = schedule_with_parity(loaded)
        per_zone = {}
        for n in result.nodes:
            per_zone[n.option.zone] = per_zone.get(n.option.zone, 0) + n.pod_count
        assert result.unschedulable_count() == 0
        assert len(per_zone) == 3
        assert max(per_zone.values()) - min(per_zone.values()) <= 1

    def test_spread_hostname_zone_caps_per_node(self):
        loaded = self.load_workload("spread-hostname-zone.yaml", replicas=12)
        assert loaded.pods[0].topology[0].max_skew == 2
        result = schedule_with_parity(loaded)
        assert result.unschedulable_count() == 0
        assert all(n.pod_count <= 2 for n in result.nodes)  # hostname maxSkew=2

    GPU_PROVISIONER = """
apiVersion: karpenter.sh/v1alpha5
kind: Provisioner
metadata:
  name: gpu
spec:
  requirements:
    - key: karpenter.k8s.aws/instance-gpu-name
      operator: Exists
  providerRef:
    name: default
"""

    def test_gpu_nvidia_lands_on_gpu_type(self):
        loaded = self.load_workload("gpu-nvidia.yaml", replicas=4)
        loaded.provisioners = load_manifests(
            self.GPU_PROVISIONER, env=ENV).provisioners
        vec = dict(loaded.pods[0].requests)
        assert vec[wk.RESOURCE_NVIDIA_GPU] == 1  # limits imply requests
        result = schedule_with_parity(loaded)
        assert result.unschedulable_count() == 0
        for n in result.nodes:
            caps = dict(n.option.itype.capacity)
            assert caps.get(wk.RESOURCE_NVIDIA_GPU, 0) >= 1

    ARCH_OPEN_PROVISIONER = """
apiVersion: karpenter.sh/v1alpha5
kind: Provisioner
metadata:
  name: default
spec:
  requirements:
    - key: kubernetes.io/arch
      operator: In
      values: [amd64, arm64]
  providerRef:
    name: default
"""

    def test_arm64_node_selector(self):
        # arm64 pods need an arch-open provisioner, exactly as in the
        # reference (v1alpha5 defaulting pins amd64 otherwise)
        loaded = load_files(
            f"{REF}/workloads/arm64.yaml", env=ENV, replicas_override=3)
        loaded.provisioners = load_manifests(
            self.ARCH_OPEN_PROVISIONER, env=ENV).provisioners
        result = schedule_with_parity(loaded)
        assert result.unschedulable_count() == 0
        assert all(dict(n.option.itype.labels)[wk.LABEL_ARCH] == "arm64"
                   for n in result.nodes)

    def test_spot_workload_tolerates_spot_provisioner(self):
        loaded = load_files(
            f"{REF}/provisioner/spot.yaml",
            f"{REF}/workloads/spot.yaml", env=ENV, replicas_override=5)
        result = schedule_with_parity(loaded)
        assert result.unschedulable_count() == 0
        assert all(n.option.capacity_type == "spot" for n in result.nodes)

    def test_disruption_budget_pdb_resolves_percentage(self):
        loaded = load_files(f"{REF}/workloads/disruption-budget.yaml", env=ENV)
        (pdb,) = loaded.pdbs
        # minAvailable 80% of 10 replicas -> 8
        assert pdb.min_available == 8
        assert len(loaded.pods) == 10

    def test_prefer_arm_lands_on_arm(self):
        loaded = self.load_workload("prefer-arm.yaml", replicas=2)
        # preferred affinities parse as ordered soft terms (weight desc:
        # arm64 at weight 50 before amd64 at weight 1), not hard reqs
        pod = loaded.pods[0]
        assert pod.requirements.get(wk.LABEL_ARCH) is None
        assert len(pod.preferences) == 2
        assert pod.preferences[0].get(wk.LABEL_ARCH).has("arm64")
        assert pod.preferences[1].get(wk.LABEL_ARCH).has("amd64")
        # general-purpose provisioner pins amd64 families: the arm64 term is
        # infeasible, relaxation drops to the amd64 term, pods still schedule
        result = schedule_with_parity(loaded)
        assert result.unschedulable_count() == 0
        # under a permissive provisioner the top-weight arm64 term is honored
        # (reference semantics: prefer-arm lands on arm when arm is offered)
        from karpenter_tpu.apis.provisioner import Provisioner
        from karpenter_tpu.models.requirements import OP_IN, Requirements

        prov = Provisioner(name="default", requirements=Requirements.of(
            (wk.LABEL_ARCH, OP_IN, ["amd64", "arm64"])))
        prov.set_defaults()
        import dataclasses

        loaded2 = dataclasses.replace(loaded, provisioners=[prov])
        result2 = schedule_with_parity(loaded2)
        assert result2.unschedulable_count() == 0
        for n in result2.nodes:
            assert dict(n.option.itype.labels)[wk.LABEL_ARCH] == "arm64"


class TestEndToEndManifestApply:
    def test_manifests_drive_the_controller_plane(self):
        """The loaded objects run through the real operator (apply -f flow)."""
        from karpenter_tpu.apis.settings import Settings
        from karpenter_tpu.fake.cloud import FakeCloud
        from karpenter_tpu.operator import Operator
        from karpenter_tpu.utils.clock import FakeClock

        loaded = load_files(
            f"{REF}/provisioner/general-purpose.yaml",
            f"{REF}/workloads/inflate.yaml", env=ENV, replicas_override=20)
        catalog = generate_fleet_catalog()
        clock = FakeClock()
        cloud = FakeCloud(catalog=catalog, clock=clock)
        # the reference discovers subnets by cluster tag; tag the fakes
        for s in cloud.subnets:
            s.tags["karpenter.sh/discovery"] = "replay"
        for g in cloud.security_groups:
            g.tags["karpenter.sh/discovery"] = "replay"
        settings = Settings(cluster_name="replay",
                            cluster_endpoint="https://replay",
                            batch_idle_duration=0.0, batch_max_duration=0.0)
        op = Operator(cloud, settings, catalog, clock=clock)
        try:
            for t in loaded.templates:
                op.kube.create("nodetemplates", t.name, t)
            for p in loaded.provisioners:
                op.kube.create("provisioners", p.name, p)
            for pod in loaded.pods:
                op.kube.create("pods", pod.name, pod)
            op.provisioning.reconcile_once()
            assert not op.kube.pending_pods()
            assert op.cluster.nodes
        finally:
            op.stop()


class TestPodRequests:
    def test_init_containers_fold_in_as_max(self):
        # k8s effective requests: max(sum(containers), max(initContainers))
        from karpenter_tpu.apis.yaml_compat import _pod_requests

        containers = [
            {"resources": {"requests": {"cpu": "500m", "memory": "1Gi"}}},
            {"resources": {"requests": {"cpu": "500m", "memory": "1Gi"}}},
        ]
        init = [
            {"resources": {"requests": {"cpu": "4", "memory": "512Mi"}}},
            {"resources": {"requests": {"cpu": "2", "memory": "4Gi"}}},
        ]
        r = _pod_requests(containers, init)
        assert r["cpu"] == 4000          # init phase dominates cpu
        assert r["memory"] == 4 * 1024 ** 3  # heaviest single init container
        # without init containers the sums stand
        r2 = _pod_requests(containers)
        assert r2["cpu"] == 1000 and r2["memory"] == 2 * 1024 ** 3

    def test_limits_imply_requests(self):
        from karpenter_tpu.apis.yaml_compat import _pod_requests

        r = _pod_requests([{"resources": {"limits": {"nvidia.com/gpu": 2}}}])
        assert r["nvidia.com/gpu"] == 2


class TestPodAffinityParsing:
    def test_required_pod_affinity_and_cross_group_anti(self):
        from karpenter_tpu.apis.yaml_compat import load_manifests

        loaded = load_manifests("""
apiVersion: v1
kind: Pod
metadata:
  name: web
  labels: {app: web}
spec:
  affinity:
    podAffinity:
      requiredDuringSchedulingIgnoredDuringExecution:
      - labelSelector:
          matchLabels: {app: db}
        topologyKey: topology.kubernetes.io/zone
    podAntiAffinity:
      requiredDuringSchedulingIgnoredDuringExecution:
      - labelSelector:
          matchLabels: {app: web}
        topologyKey: kubernetes.io/hostname
      - labelSelector:
          matchExpressions:
          - {key: app, operator: In, values: [noisy]}
        topologyKey: topology.kubernetes.io/zone
  containers:
  - name: c
    resources: {requests: {cpu: "1"}}
""")
        (pod,) = loaded.pods
        # app=db affinity -> cross-group term
        (aff,) = pod.pod_affinity
        assert aff.match_labels == (("app", "db"),)
        assert aff.topology_key == wk.LABEL_ZONE
        # app=web (self) hostname anti-affinity -> boolean AND a cross-group
        # term (the same selector can match other deployments' app=web pods;
        # ADVICE r2: self-spread and cross-group exclusion are not exclusive)
        assert pod.anti_affinity_hostname
        by_sel = {t.match_labels: t for t in pod.pod_anti_affinity}
        assert set(by_sel) == {(("app", "web"),), (("app", "noisy"),)}
        assert by_sel[(("app", "web"),)].topology_key == wk.LABEL_HOSTNAME
        # app=noisy (cross-group) zone anti-affinity -> term
        assert by_sel[(("app", "noisy"),)].topology_key == wk.LABEL_ZONE

    def test_self_selector_still_excludes_foreign_residents(self):
        # selector {app: x} matches the pod itself AND a resident pod of a
        # DIFFERENT deployment carrying app=x: the domain exclusion must
        # survive the self-fold (previously silently dropped)
        from karpenter_tpu.apis.yaml_compat import load_manifests
        from karpenter_tpu.models.instancetype import Catalog, make_instance_type
        from karpenter_tpu.models.pod import make_pod
        from karpenter_tpu.oracle.scheduler import ExistingNode, Scheduler
        from karpenter_tpu.apis.provisioner import Provisioner

        loaded = load_manifests("""
apiVersion: v1
kind: Pod
metadata:
  name: x-new
  labels: {app: x}
spec:
  affinity:
    podAntiAffinity:
      requiredDuringSchedulingIgnoredDuringExecution:
      - labelSelector:
          matchLabels: {app: x}
        topologyKey: topology.kubernetes.io/zone
  containers:
  - name: c
    resources: {requests: {cpu: "1", memory: 1Gi}}
""")
        (pod,) = loaded.pods
        assert pod.anti_affinity_zone
        assert any(t.match_labels == (("app", "x"),)
                   for t in pod.pod_anti_affinity)
        # a FOREIGN resident (different deployment, same app=x label) in
        # zone-1a forbids that zone for the new pod
        foreign = make_pod("other-deploy-0", cpu="100m", memory="128Mi",
                           labels=(("app", "x"), ("tier", "other")))
        catalog = Catalog(types=[make_instance_type(
            "m.xl", cpu=8, memory="32Gi", od_price=0.2)])
        prov = Provisioner(name="default")
        prov.set_defaults()
        existing = [ExistingNode(
            name="node-a",
            labels={wk.LABEL_ARCH: "amd64", wk.LABEL_OS: "linux",
                    wk.LABEL_ZONE: "zone-1a",
                    wk.LABEL_CAPACITY_TYPE: "on-demand"},
            allocatable=catalog.types[0].allocatable_vector(),
            used=[0] * wk.NUM_RESOURCES, resident=(foreign,))]
        sched = Scheduler(catalog, [prov])
        res = sched.schedule([pod], existing=existing)
        zones = {z for _, z, _, _ in res.node_decisions(sched.options)}
        assert zones and "zone-1a" not in zones
        assert not any(res.existing_assignments.values())


class TestExamplesDirectory:
    """The in-repo examples/ set (reference analogue: examples/provisioner +
    examples/workloads) must parse, validate, and — combined — schedule
    against the fleet catalog."""

    EXAMPLES = os.path.join(os.path.dirname(__file__), "..", "examples")

    def _load(self, *rel):
        import glob

        paths = []
        for r in rel:
            paths.extend(sorted(glob.glob(os.path.join(self.EXAMPLES, r))))
        assert paths
        return paths

    def test_every_example_parses_and_validates(self):
        for path in self._load("*.yaml", "provisioner/*.yaml",
                               "provisioner/launchtemplates/*.yaml",
                               "workloads/*.yaml"):
            loaded = load_manifests(open(path).read(),
                                    env={"CLUSTER_NAME": "demo"})
            for prov in loaded.provisioners:
                prov.validate()
            assert (loaded.provisioners or loaded.templates or loaded.pods
                    or loaded.pdbs), f"{path} loaded nothing"

    def test_example_breadth_matches_reference_shape(self):
        # reference: 7 provisioner + 4 launchtemplates + 11 workloads
        assert len(self._load("provisioner/*.yaml")) >= 8
        assert len(self._load("provisioner/launchtemplates/*.yaml")) >= 4
        assert len(self._load("workloads/*.yaml")) >= 11

    def test_combined_examples_schedule_end_to_end(self):
        provisioners, pods = [], []
        for path in self._load("provisioner/*.yaml"):
            provisioners.extend(load_manifests(
                open(path).read(), env={"CLUSTER_NAME": "demo"}).provisioners)
        for path in self._load("workloads/*.yaml"):
            pods.extend(load_manifests(
                open(path).read(), env={"CLUSTER_NAME": "demo"}).pods)
        for p in provisioners:
            p.set_defaults()
        catalog = generate_fleet_catalog()
        sched = Scheduler(catalog, provisioners)
        res = sched.schedule(pods)
        placed = sum(len(n.pods) for n in res.new_nodes)
        assert placed + len(res.unschedulable) == len(pods)
        # the accelerator workload is the only one the generated fleet may
        # not satisfy; everything else must schedule
        unsched_apps = {p.name.split("-")[0] for p in res.unschedulable}
        assert unsched_apps <= {"accel"}, unsched_apps
