"""Spot resilience plane: seeded property tests (ISSUE 19 satellites).

Three falsifiable properties, each driven by a fixed-seed RNG so a failure
reproduces bit-identically:

* forecaster determinism — same seed + same ledger bytes => identical rate
  tables (the ledger rung hashes the corpus, never wall clock or PID);
* diversity floor x 1000 random fleets — after RiskObjective.solve every
  over-concentrated spot pool is either fixed or explicitly accepted in
  the DecisionRecord, and the guard precedence held (never-strands >
  cost-never-raised > diversity: sticker cost and unschedulable count
  never exceed the un-floored baseline);
* rate-limit falsifiability — adversarial accrual/spend schedules can
  never push lifetime drains above lifetime predicted-interruption mass,
  and a cleared forecast zeroes the bank within one cycle.

Plus the mask-dimension parity check (kernel option_mask vs oracle barred
must produce bit-identical decisions) and the pricing-staleness gauge
satellite.
"""

import random

import pytest

from karpenter_tpu.apis import wellknown as wk
from karpenter_tpu.apis.provisioner import Provisioner
from karpenter_tpu.controllers.provisioning import _oracle_to_solve_result
from karpenter_tpu.metrics import Registry
from karpenter_tpu.models.instancetype import Catalog, make_instance_type
from karpenter_tpu.models.pod import make_pod
from karpenter_tpu.models.requirements import OP_IN, Requirements
from karpenter_tpu.oracle.scheduler import Scheduler
from karpenter_tpu.solver.core import TPUSolver
from karpenter_tpu.spot import state as spot_state
from karpenter_tpu.spot import forecaster as fc_mod
from karpenter_tpu.spot import objective as obj_mod
from karpenter_tpu.spot.forecaster import (FORECAST_RUNGS, RATE_CAP,
                                           REBALANCE_RATE_THRESHOLD,
                                           RISK_WEIGHT, STATIC_RATES,
                                           SpotForecaster)
from karpenter_tpu.spot.objective import (RiskObjective, diversity_report,
                                          pool_mask, risk_adjusted_catalog,
                                          _sticker_cost, _sticker_prices)
from karpenter_tpu.spot.rebalance import RebalanceRateLimiter
from karpenter_tpu.utils.clock import FakeClock

SEED = 0x5EED


def small_catalog():
    return Catalog(types=[
        make_instance_type("t.small", cpu=2, memory="2Gi",
                           od_price=0.05, spot_price=0.02),
        make_instance_type("m.large", cpu=4, memory="16Gi",
                           od_price=0.20, spot_price=0.07),
    ])


def prov():
    p = Provisioner(name="default", requirements=Requirements.of(
        (wk.LABEL_CAPACITY_TYPE, OP_IN, ["spot", "on-demand"])))
    p.set_defaults()
    return p


def make_forecaster(tmp_path, seed=0, live_source=None, ledger_text=None):
    path = tmp_path / "ledger.jsonl"
    if ledger_text is not None:
        path.write_text(ledger_text)
    return SpotForecaster(clock=FakeClock(), registry=Registry(), seed=seed,
                          ledger_path=str(path), live_source=live_source)


# -- forecaster determinism ----------------------------------------------------


class TestForecasterDeterminism:
    LEDGER = '{"metric": "m", "value": 1.0}\n{"metric": "m", "value": 2.0}\n'

    def test_same_seed_same_ledger_identical_rates(self, tmp_path):
        a = make_forecaster(tmp_path, seed=7, ledger_text=self.LEDGER)
        b = make_forecaster(tmp_path, seed=7)
        # no live source: the ladder falls live -> ledger
        assert a.refresh() == FORECAST_RUNGS.index("ledger")
        assert b.refresh() == FORECAST_RUNGS.index("ledger")
        assert a._rates == b._rates
        for pool in (("t.small", "zone-1a", "spot"),
                     ("m.large", "zone-1c", "spot"),
                     ("t.small", "zone-1b", "on-demand")):
            assert a.rate(*pool) == b.rate(*pool)
            assert a.penalty(*pool) == b.penalty(*pool)
        # refreshing again changes nothing: same bytes, same seed
        before = dict(a._rates)
        a.refresh()
        assert a._rates == before

    def test_seed_and_ledger_bytes_move_the_forecast(self, tmp_path):
        base = make_forecaster(tmp_path, seed=7, ledger_text=self.LEDGER)
        other_seed = make_forecaster(tmp_path, seed=8)
        base.refresh(), other_seed.refresh()
        assert base._rates != other_seed._rates
        edited = make_forecaster(tmp_path, seed=7,
                                 ledger_text=self.LEDGER + '{"metric":"x"}\n')
        edited.refresh()
        assert base._rates != edited._rates

    def test_ladder_degrades_to_static_and_warns_once(self, tmp_path):
        def broken_live():
            raise RuntimeError("feed down")

        fc = SpotForecaster(clock=FakeClock(), registry=Registry(), seed=0,
                            ledger_path=str(tmp_path / "missing.jsonl"),
                            live_source=broken_live)
        warns_before = fc_mod.counters()["spot_forecast_rung_warnings"]
        assert fc.refresh() == FORECAST_RUNGS.index("static")
        assert fc.rate("t.small", "zone-1a", "spot") == STATIC_RATES["spot"]
        assert fc.rate("t.small", "zone-1a", "on-demand") == 0.0
        assert fc.penalty("t.small", "zone-1a", "on-demand") == 1.0
        # the degraded-rung warning fires on the TRANSITION, not per refresh
        assert fc_mod.counters()["spot_forecast_rung_warnings"] \
            == warns_before + 1
        fc.refresh()
        assert fc_mod.counters()["spot_forecast_rung_warnings"] \
            == warns_before + 1

    def test_penalty_is_capped_and_on_demand_exact(self, tmp_path):
        hot = {("t.small", "zone-1a", "spot"): 0.9}
        fc = make_forecaster(tmp_path, live_source=lambda: hot)
        fc.refresh()
        assert fc.penalty("t.small", "zone-1a", "spot") == \
            pytest.approx(1.0 + RISK_WEIGHT * RATE_CAP)
        # live rung named only one pool: others fall to the static baseline
        assert fc.rate("m.large", "zone-1b", "spot") == STATIC_RATES["spot"]
        assert fc.penalty("t.small", "zone-1a", "on-demand") == 1.0

    def test_strict_noop_while_disabled(self, tmp_path):
        fc = make_forecaster(tmp_path, live_source=lambda: {
            ("t.small", "zone-1a", "spot"): 0.9})
        with spot_state.disabled():
            counters_before = fc_mod.counters()
            assert fc.refresh() is None
            assert fc.rate("t.small", "zone-1a", "spot") == 0.0
            assert fc.penalty("t.small", "zone-1a", "spot") == 1.0
            assert fc_mod.counters() == counters_before
        assert fc.refresh() is not None  # re-enabled: the feed works again


# -- diversity floor x 1000 random fleets --------------------------------------


def oracle_solve_fn(pods, provisioners):
    """The RiskObjective solve_fn contract over the scalar oracle: the
    barred pool set carries the mask dimension on this path (option_mask
    is the kernel backends' encoding of the same bar)."""
    def solve_fn(catalog, option_mask, barred, pod_transform):
        ps = list(pods)
        if pod_transform is not None:
            ps = pod_transform(ps)
        sched = Scheduler(catalog, provisioners, None, barred=barred)
        return _oracle_to_solve_result(sched.schedule(ps), sched)
    return solve_fn


def random_fleet(rng, i):
    """A few identical-pod workloads (workload = origin-key group, the
    identity the floor budgets on) with randomized shapes and counts."""
    shapes = [("250m", "256Mi"), ("500m", "1Gi"), ("1", "2Gi"), ("2", "4Gi")]
    pods = []
    for w in range(rng.randint(1, 3)):
        cpu, mem = rng.choice(shapes)
        for j in range(rng.randint(2, 6)):
            pods.append(make_pod(f"f{i}-w{w}-p{j}", cpu=cpu, memory=mem))
    return pods


def random_hot_schedule(rng, catalog):
    """Random live forecast with at least one pool above the rebalance
    threshold (so the objective activates) and randomized spread."""
    pools = [(t.name, o.zone, o.capacity_type)
             for t in catalog.types for o in t.offerings
             if o.capacity_type == wk.CAPACITY_TYPE_SPOT]
    hot = {pool: round(rng.uniform(0.2, 0.9), 3)
           for pool in rng.sample(pools, rng.randint(1, len(pools)))}
    for pool in pools:
        if pool not in hot and rng.random() < 0.5:
            hot[pool] = round(rng.uniform(0.0, 0.1), 3)
    return hot


def test_diversity_floor_1000_random_fleets(tmp_path):
    rng = random.Random(SEED)
    catalog = small_catalog()
    provisioners = [prov()]
    prices = _sticker_prices(catalog)
    checked_violations = 0
    for i in range(1000):
        hot = random_hot_schedule(rng, catalog)
        fc = make_forecaster(tmp_path, seed=i, live_source=lambda h=hot: h)
        fc.refresh()
        obj = RiskObjective(fc, floor=rng.choice((0.34, 0.5, 0.67)))
        assert obj.active()
        pods = random_fleet(rng, i)
        solve_fn = oracle_solve_fn(pods, provisioners)
        # the un-floored risk-adjusted baseline the guards compare against
        base = solve_fn(risk_adjusted_catalog(catalog, fc), None, None, None)
        base_cost = _sticker_cost(base, prices)
        base_unsched = base.unschedulable_count()
        result, info = obj.solve(catalog, solve_fn)
        # guard precedence: the floor never strands a pod and never raises
        # real (sticker) cost relative to the un-floored placement
        assert result.unschedulable_count() <= base_unsched, f"fleet {i}"
        assert _sticker_cost(result, prices) <= base_cost + 1e-9, f"fleet {i}"
        # every residual over-concentration is explicitly accepted in the
        # DecisionRecord -- no silent floor violations
        accepted = {tuple(p) for p in info["accepted_concentrations"]}
        residual = set()
        for pools in diversity_report(result, obj.floor).values():
            residual |= pools
        assert residual <= accepted, \
            f"fleet {i}: silent violations {residual - accepted}"
        checked_violations += len(residual)
        # restore_real_prices contract: recorded node prices are sticker
        for n in result.nodes:
            pool = (n.option.itype.name, n.option.zone,
                    n.option.capacity_type)
            assert n.option.price == pytest.approx(prices[pool])
    # the sweep must actually exercise the accept/rollback path sometimes,
    # or the property above is vacuous
    assert checked_violations > 0


def test_objective_inactive_at_static_baseline(tmp_path):
    """At the static 5% baseline the objective must NOT activate -- the
    advisory plane stays out of the steady-state hot path."""
    fc = make_forecaster(tmp_path, ledger_text='{"metric": "m"}\n')
    fc.refresh()
    assert fc.snapshot()["max_rate"] is not None
    assert fc.snapshot()["max_rate"] < REBALANCE_RATE_THRESHOLD
    assert not RiskObjective(fc).active()


# -- mask-dimension parity (kernel option_mask vs oracle barred) ---------------


def test_mask_dimension_oracle_parity():
    rng = random.Random(SEED)
    catalog = small_catalog()
    provisioners = [prov()]
    pools = [(t.name, o.zone, o.capacity_type)
             for t in catalog.types for o in t.offerings
             if o.capacity_type == wk.CAPACITY_TYPE_SPOT]
    for trial in range(25):
        barred = set(rng.sample(pools, rng.randint(0, len(pools) - 1)))
        pods = random_fleet(rng, trial)
        sched = Scheduler(catalog, provisioners, None, barred=barred)
        oracle_res = sched.schedule(list(pods))
        kernel_res = TPUSolver(catalog, provisioners).solve(
            list(pods), option_mask=pool_mask(catalog, barred))
        assert kernel_res.decisions() == \
            oracle_res.node_decisions(sched.options), \
            f"trial {trial}, barred={sorted(barred)}"
        assert kernel_res.unschedulable_count() == len(oracle_res.unschedulable)
        # the bar actually bars: nothing lands on a barred pool
        for name, zone, ct, _ in kernel_res.decisions():
            assert (name, zone, ct) not in barred


# -- rate-limit falsifiability -------------------------------------------------


class TestRebalanceRateLimiter:
    def test_adversarial_schedules_never_exceed_accrued(self):
        rng = random.Random(SEED)
        for _ in range(200):
            lim = RebalanceRateLimiter()
            for _ in range(rng.randint(1, 50)):
                mass = rng.choice((0.0, rng.uniform(0.0, 3.0)))
                budget = lim.accrue(mass)
                assert budget == int(lim.tokens)
                if mass <= 0.0:
                    assert lim.tokens == 0.0
                # spend as aggressively as the bank allows -- the
                # falsifying schedule, if one existed, is in here
                if budget and rng.random() < 0.8:
                    lim.spend(rng.randint(1, budget))
                assert lim.tokens >= 0.0
                assert lim.spent <= lim.accrued + 1e-9, lim.snapshot()
            assert lim.spent <= lim.accrued + 1e-9, lim.snapshot()

    def test_cleared_forecast_zeroes_the_bank(self):
        lim = RebalanceRateLimiter()
        assert lim.accrue(5.0) >= 1
        assert lim.accrue(0.0) == 0
        assert lim.tokens == 0.0
        # history is retained for the lifetime audit, only tokens clear
        assert lim.accrued > 0.0

    def test_burst_caps_the_bank(self):
        lim = RebalanceRateLimiter()
        for _ in range(100):
            lim.accrue(1.0)
        assert lim.tokens <= RebalanceRateLimiter.BURST * 1.0 + 1e-9


# -- pricing staleness satellite -----------------------------------------------


def test_pricing_staleness_gauge_by_rung():
    from karpenter_tpu.fake.cloud import FakeCloud
    from karpenter_tpu.providers.pricing import PricingProvider

    clock = FakeClock()
    reg = Registry()
    cloud = FakeCloud(catalog=small_catalog(), clock=clock)
    pricing = PricingProvider(cloud, clock=clock, registry=reg)
    clock.step(120.0)
    snap = pricing.observe_staleness()
    # never updated: the static rung ages from provider start
    assert snap["rung"] == "static"
    assert snap["staleness_seconds"] == pytest.approx(120.0)
    gauge = reg.gauge("karpenter_pricing_price_staleness_seconds",
                      label_names=("rung",))
    assert gauge.value(rung="static") == pytest.approx(120.0)
    assert pricing.update()
    snap = pricing.observe_staleness()
    assert snap["rung"] == "live"
    assert snap["staleness_seconds"] == pytest.approx(0.0)
    assert gauge.value(rung="live") == pytest.approx(0.0)
