"""The fleet telemetry drill: 2 replicas, 1000 tenants, one artifact.

Deterministic end-to-end proof (FakeClock, fixed tenant ids, stub solve
backends — no device, no wall clock) that the fleet-scale telemetry plane
holds its four contracts at a tenant cardinality far past the top-K:

1. **Series bound** — after 1000 distinct tenants submit through two
   FleetFrontends, every guarded metric family holds at most K+1 tenant
   label values (the top-K exact series plus the `_other` rollup).
2. **fleetz** — `FleetView.fleetz()` names BOTH replicas (healthy rows
   with their HBM residency) and the router's tenant pinning for the
   tenants in the merged top-K table.
3. **Federated trace** — one solve traced across the wire yields ONE
   Perfetto document with a client lane and a replica lane joined by the
   shared trace id.
4. **Per-tenant SLO burn** — one deliberately-throttled tenant (every
   solve held 2 s against a 1 s p99 objective) fires the templated
   `fleet_tenant_p99{tenant=...}` burn edge: an SloBurn warning event
   AND a flight-recorder bundle on disk, while the other tenants'
   instances stay healthy.

Run as `make telemetry-drill` (or `python -m benchmarks.telemetry_drill`)
for the JSON artifact under benchmarks/results/telemetry/, or in-process
from the tier-1 test (tests/test_telemetry_drill.py)."""

from __future__ import annotations

import glob
import json
import os
import sys
from types import SimpleNamespace

from karpenter_tpu.events import EventRecorder
from karpenter_tpu.fleet import metrics as fm
from karpenter_tpu.fleet.frontend import FleetFrontend
from karpenter_tpu.fleet.router import FleetRouter
from karpenter_tpu.introspect.flightrecorder import FlightRecorder
from karpenter_tpu.introspect.fleetview import FleetView, LocalReplica
from karpenter_tpu.introspect.slo import SloEvaluator
from karpenter_tpu.metrics import REGISTRY
from karpenter_tpu.models.pod import make_pod
from karpenter_tpu.solver import buckets
from karpenter_tpu.tracing import TRACER, SpanContext, Tracer
from karpenter_tpu.utils.clock import FakeClock

N_TENANTS = 1000
HOT = "tenant-hot"
REPLICAS = ("replica-a", "replica-b")
SOLVER_KEY = (0xD1A11, 0xBEEF)


def _backend(key, problems):
    # deterministic stub demux: the drill measures telemetry, not packing
    return [{"pods": len(p["pods"])} for p in problems]


def _one_pod(tid):
    return [make_pod(f"{tid}-p0", cpu="1", memory="2Gi")]


def run_drill(out_dir: "str | None" = None) -> dict:
    """Run the drill; returns the artifact dict (also written to
    `out_dir` along with the burn bundle when a directory is given)."""
    clock = FakeClock()
    recorder = EventRecorder(clock=clock)
    router = FleetRouter()
    fronts = {name: FleetFrontend(solve_batch=_backend, clock=clock,
                                  tick_interval_s=0.01, max_wave=1024,
                                  name=name)
              for name in REPLICAS}

    # per-replica HBM ledgers (instance-scoped so the drill leaves the
    # process-global ledger alone when run inside the test suite)
    ledgers = {name: buckets.HbmLedger() for name in REPLICAS}
    key_str = f"{SOLVER_KEY[0]:x}/{SOLVER_KEY[1]:x}"
    for name, ledger in ledgers.items():
        with buckets.hbm_scope(key_str):
            ledger.track(4 << 20, "catalog")       # Sync-resident static
            ledger.track(1 << 20, "pack_inputs")   # per-solve delta
        ledger.attribute_delta(key_str, "g8s64")

    def statusz_for(name):
        def build():
            return {
                "schema": 6,
                "version": "drill",
                "ts": clock.now(),
                "resilience": {"watchdog": {"healthy": True}},
                "hbm": ledgers[name].snapshot(),
                "fleet": {"frontends": [fronts[name].stats()]},
            }
        return build

    # the replica-side trace ring (its serving plane's tracer); the
    # client half lives in the process-global TRACER
    replica_tracers = {name: Tracer(ring_size=256, registry=None)
                       for name in REPLICAS}
    fleetview = FleetView(router=router, name="drill")
    for name in REPLICAS:
        fleetview.add_replica(LocalReplica(
            name, statusz=statusz_for(name), tracer=replica_tracers[name]))

    # -- traffic: 999 light tenants + 1 hot, routed by rendezvous pinning --
    tenants = [f"tenant-{i:04d}" for i in range(N_TENANTS - 1)] + [HOT]
    homes = router.assignment(tenants)
    for tid in tenants:
        fronts[homes[tid]].register_key(tid, SOLVER_KEY)

    # phase 1: one fast (good) solve per light tenant, then a good
    # baseline for the hot tenant LAST so it is still inside the top-K
    # sketch when the SLO evaluator first discovers its series
    for tid in tenants[:-1]:
        fronts[homes[tid]].submit(tid, _one_pod(tid))
    clock.step(0.01)
    for fe in fronts.values():
        fe.tick()
    for _ in range(2):
        fronts[homes[HOT]].submit(HOT, _one_pod(HOT))
    clock.step(0.01)
    fronts[homes[HOT]].tick()

    # -- per-tenant SLO machinery (stub op: the bundle's statusz sections
    # it cannot build degrade to fenced errors, by design) --
    bundle_dir = os.path.join(out_dir, "bundles") if out_dir else None
    stub_op = SimpleNamespace(clock=clock, recorder=recorder,
                              metrics_text=REGISTRY.expose)
    flightrec = FlightRecorder(stub_op, out_dir=bundle_dir, clock=clock)
    stub_op.flightrecorder = flightrec
    evaluator = SloEvaluator(clock=clock, recorder=recorder,
                             flightrecorder=flightrec)
    stub_op.slo = evaluator
    evaluator.evaluate()  # seed the rings: every instance's baseline

    # phase 2: throttle the hot tenant — 48 solves each held 2 s against
    # the 1 s p99 line (the only traffic between the two evaluations, so
    # the windowed delta is unambiguous)
    for i in range(48):
        fronts[homes[HOT]].submit(HOT, _one_pod(HOT))
    clock.step(2.0)
    fronts[homes[HOT]].tick()
    clock.step(1.0)
    results = evaluator.evaluate()

    hot_iname = f"fleet_tenant_p99{{tenant={HOT}}}"
    hot_res = results.get(hot_iname, {})
    burn_events = [(ts, e.object_ref, e.message)
                   for ts, e in recorder.recent()
                   if e.reason == "SloBurn" and HOT in e.object_ref]
    bundles = (sorted(glob.glob(os.path.join(bundle_dir, "bundle_*.json")))
               if bundle_dir else [])
    hot_bundles = [b for b in bundles if "fleet_tenant_p99" in b]
    healthy_peers = [iname for iname, res in results.items()
                     if iname.startswith("fleet_tenant_p99{")
                     and iname != hot_iname and not res["burning"]]

    # -- one federated trace for a single solve --
    with TRACER.start_span("fleet.solve", tenant=HOT) as client_span:
        server = replica_tracers[homes[HOT]].start_span(
            "solver.service.Solve",
            context=SpanContext(client_span.trace_id, client_span.span_id),
            tenant=HOT)
        server.end()
    fed = fleetview.federated_trace(client_span.trace_id)
    fed_lanes = sorted(e["args"]["name"] for e in (fed or {})["traceEvents"]
                       if e["ph"] == "M")
    fed_spans = [e for e in (fed or {})["traceEvents"] if e["ph"] == "X"]

    # -- the joined snapshot --
    fleetz = fleetview.fleetz()
    snap = fm.TENANT_GUARD.snapshot()

    criteria = {
        "series_bounded_k_plus_1": bool(snap["series_per_family"]) and all(
            n <= snap["k"] + 1 for n in snap["series_per_family"].values()),
        "fleetz_names_both_replicas": (
            set(REPLICAS) <= set(fleetz["replicas"])
            and all(fleetz["replicas"][r].get("healthy") for r in REPLICAS)
            and fleetz["pinning"].get(HOT) == homes[HOT]),
        "federated_trace_stitches_client_and_replica": (
            fed is not None
            and f"client:{fleetview.name}" in fed_lanes
            and homes[HOT] in fed_lanes
            and len(fed_spans) == 2),
        "per_tenant_slo_burn_fired": (
            bool(hot_res.get("burning"))
            and bool(burn_events)
            and (bool(hot_bundles) if bundle_dir else True)
            and len(healthy_peers) > 0),
    }
    artifact = {
        "tool": "karpenter-tpu-telemetry-drill",
        "schema": 1,
        "tenants": N_TENANTS,
        "replicas": list(REPLICAS),
        "hot_tenant": {"id": HOT, "home": homes[HOT],
                       "slo_instance": hot_iname,
                       "result": hot_res,
                       "burn_events": burn_events,
                       "bundles": hot_bundles,
                       "healthy_peer_instances": len(healthy_peers)},
        "tenant_guard": snap,
        "fleetz": fleetz,
        "federated_trace": {"trace_id": client_span.trace_id,
                            "lanes": fed_lanes,
                            "n_spans": len(fed_spans)},
        "criteria": criteria,
        "passed": all(criteria.values()),
    }
    if out_dir:
        os.makedirs(out_dir, exist_ok=True)
        path = os.path.join(out_dir, "telemetry_drill.json")
        with open(path, "w") as f:
            json.dump(artifact, f, indent=2, sort_keys=True, default=str)
            f.write("\n")
        artifact["artifact_path"] = path
    return artifact


def main() -> int:
    out_dir = os.environ.get(
        "KARPENTER_TPU_DRILL_DIR",
        os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))),
            "benchmarks", "results", "telemetry"))
    artifact = run_drill(out_dir)
    print(json.dumps({"passed": artifact["passed"],
                      "criteria": artifact["criteria"],
                      "artifact": artifact.get("artifact_path")},
                     indent=2))
    return 0 if artifact["passed"] else 1


if __name__ == "__main__":
    sys.exit(main())
