"""The profile drill: prove the gap ledger accounts for the headline.

ISSUE 13's acceptance instrument: a 10k-pod solve (the BASELINE configs[4]
shape at 10k pods — full 603-type fleet catalog, 8 overlapping
provisioners) is driven through BOTH routing paths

  - ``single``  — one-device dispatch (TPUSolver, no mesh), and
  - ``sharded`` — the routed mesh path (ShardedContext over the CPU_ENV's
    8 virtual devices, ShapeRouter forced with crossover_cells=0 — the
    multichip_wire idiom),

with the profiling plane ON, and the drill asserts three things per path:

  1. **attribution** — the gap ledger's named phases (encode / serialize /
     link / device_exec / decode) cover >= 95% of measured solve wall
     time: ``attributed_share >= 0.95``;
  2. **residue** — the explicit ``unaccounted`` share stays < 5%;
  3. **overhead** — min-of-repeats wall with profiling enabled is within
     5% of the profiling-disabled baseline (the always-on profiler is
     cheap enough to leave on).

The artifact lands at benchmarks/results/profiling/profile_drill.json
(deterministic path — re-running overwrites) and each path's shares are
recorded through benchmarks/ledger.py, so `make perf-regress` gates
attribution like any other perf metric. Run via `make profile-drill`;
bench.py --profile reuses run_path() at bench-sized workloads.
"""

from __future__ import annotations

import json
import os
import statistics
import sys
import time

OUT_DIR = os.path.join(os.path.dirname(__file__), "results", "profiling")
ARTIFACT = os.path.join(OUT_DIR, "profile_drill.json")

PODS = 10_000
REPEATS = 9
WARMUP = 2
MAX_UNACCOUNTED_SHARE = 0.05
MAX_OVERHEAD_SHARE = 0.05
N_DEVICES = 8


def _solvers(n_devices: int = N_DEVICES):
    """(catalog, provisioners, single solver, sharded solver). The sharded
    half is None when the mesh can't build (single-device host)."""
    from karpenter_tpu.utils.jaxenv import pin_cpu

    pin_cpu(n_devices)
    from benchmarks.baseline_configs import stress_problem_50k
    from karpenter_tpu.solver import buckets
    from karpenter_tpu.solver.core import TPUSolver

    catalog, provisioners, pods = stress_problem_50k(PODS)
    single = TPUSolver(catalog, provisioners)
    sharded = None
    try:
        from karpenter_tpu.parallel.sharded import ShardedContext

        ctx = ShardedContext()
        router = buckets.ShapeRouter(n_devices=ctx.device_count,
                                     crossover_cells=0)
        sharded = TPUSolver(catalog, provisioners,
                            mesh_ctx=ctx, router=router)
    except Exception as e:  # noqa: BLE001 — mesh is optional surface
        print(f"profile_drill: mesh unavailable ({e}); sharded path skipped",
              file=sys.stderr)
    return catalog, provisioners, pods, single, sharded


def run_path(name: str, solver, pods, repeats: int = REPEATS,
             warmup: int = WARMUP) -> dict:
    """Measure one routing path: warmup compiles, then `repeats` solves
    with profiling ON (gap-ledger rows + wall), then the same count with
    the plane OFF for the overhead baseline. min-of-repeats is the noise
    estimator on both sides (standard for runtime comparisons)."""
    from karpenter_tpu import profiling
    from karpenter_tpu.profiling import GAP_LEDGER

    for _ in range(warmup):
        solver.solve(pods)

    profiling.set_enabled(True)
    profiling.PROFILER.ensure_started()
    GAP_LEDGER.clear()
    walls_on: "list[float]" = []
    walls_off: "list[float]" = []
    for i in range(repeats):
        # interleave ON/OFF (alternating which goes first) so allocator /
        # jit-cache warm-drift across the loop cancels out instead of
        # billing whichever side happened to run last as "faster"
        for side in (("on", "off") if i % 2 == 0 else ("off", "on")):
            if side == "on":
                t0 = time.perf_counter()
                solver.solve(pods)
                walls_on.append(time.perf_counter() - t0)
            else:
                with profiling.disabled():
                    t0 = time.perf_counter()
                    solver.solve(pods)
                    walls_off.append(time.perf_counter() - t0)
    rows = GAP_LEDGER.rows()[-repeats:]

    on_min, off_min = min(walls_on), min(walls_off)
    # overhead from MIN-of-repeats over the interleaved samples: container
    # scheduler noise is additive-positive and ~10x the true profiler
    # cost, so min approaches each side's noise floor and the interleaving
    # (not the estimator) is what keeps warm-drift from biasing one side
    overhead = max(0.0, (on_min - off_min) / off_min) if off_min > 0 else 0.0
    phase_names = sorted({p for r in rows for p in r["phases_ms"]})
    phases_ms = {
        p: round(statistics.median(r["phases_ms"].get(p, 0.0) for r in rows),
                 4)
        for p in phase_names
    }
    attributed = statistics.median(r["attributed_share"] for r in rows)
    unaccounted = statistics.median(r["unaccounted_share"] for r in rows)
    last = rows[-1]
    out = {
        "path": name,
        "repeats": repeats,
        "wall_ms_min": round(on_min * 1e3, 3),
        "wall_ms_median": round(statistics.median(walls_on) * 1e3, 3),
        "baseline_wall_ms_min": round(off_min * 1e3, 3),
        "phases_ms": phases_ms,
        "unaccounted_ms": round(
            statistics.median(r["unaccounted_ms"] for r in rows), 4),
        "attributed_share": round(attributed, 6),
        "unaccounted_share": round(unaccounted, 6),
        "overhead_share": round(overhead, 6),
        "bucket": last.get("bucket", ""),
        "route": last.get("route", ""),
        "roofline": last.get("roofline"),
        "passed": (attributed >= 1.0 - MAX_UNACCOUNTED_SHARE
                   and unaccounted < MAX_UNACCOUNTED_SHARE
                   and overhead < MAX_OVERHEAD_SHARE),
    }
    return out


def gate_probe(pods: int = 400) -> dict:
    """Small single-path probe for `make perf-regress`: one warmed solve,
    returns its gap-ledger row (the gate reads unaccounted_share)."""
    from karpenter_tpu.utils.jaxenv import pin_cpu

    pin_cpu(N_DEVICES)
    from benchmarks.baseline_configs import stress_problem_50k
    from karpenter_tpu import profiling
    from karpenter_tpu.solver.core import TPUSolver

    catalog, provisioners, probe_pods = stress_problem_50k(pods)
    solver = TPUSolver(catalog, provisioners)
    profiling.set_enabled(True)
    solver.solve(probe_pods)  # compile
    solver.solve(probe_pods)
    return profiling.GAP_LEDGER.rows()[-1]


def run_drill(repeats: int = REPEATS) -> dict:
    from benchmarks import ledger

    _catalog, _provisioners, pods, single, sharded = _solvers()
    paths = {"single": run_path("single", single, pods, repeats)}
    if sharded is not None:
        paths["sharded"] = run_path("sharded", sharded, pods, repeats)
    record = {
        "tool": "karpenter_tpu.profile_drill",
        "schema": 1,
        "pods": PODS,
        "repeats": repeats,
        "thresholds": {
            "max_unaccounted_share": MAX_UNACCOUNTED_SHARE,
            "max_overhead_share": MAX_OVERHEAD_SHARE,
        },
        "paths": paths,
        "passed": bool(paths) and all(p["passed"] for p in paths.values()),
    }
    os.makedirs(OUT_DIR, exist_ok=True)
    with open(ARTIFACT, "w") as f:
        json.dump(record, f, indent=1, sort_keys=True)
        f.write("\n")
    for name, p in paths.items():
        workload = {"name": "profile_drill", "path": name, "pods": PODS}
        degraded = not p["passed"]
        for metric, value in (
                ("profile_unaccounted_share", p["unaccounted_share"]),
                ("profile_attributed_share", p["attributed_share"]),
                ("profile_overhead_share", p["overhead_share"])):
            ledger.record(metric, value, "ratio",
                          source="benchmarks.profile_drill", backend="cpu",
                          workload=workload, degraded=degraded,
                          artifact=ARTIFACT)
    return record


def main(argv=None) -> int:
    record = run_drill()
    print(json.dumps({
        "passed": record["passed"],
        "paths": {k: {"attributed_share": v["attributed_share"],
                      "unaccounted_share": v["unaccounted_share"],
                      "overhead_share": v["overhead_share"],
                      "wall_ms_min": v["wall_ms_min"]}
                  for k, v in record["paths"].items()},
        "artifact": ARTIFACT,
    }))
    return 0 if record["passed"] else 1


if __name__ == "__main__":
    sys.exit(main())
