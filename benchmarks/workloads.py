"""Shared benchmark workload generators.

One definition of the headline mixed workload, used by bench.py (the driver
entry), hack/tpu_capture.py (opportunistic on-chip capture), and the scale
ladder in benchmarks/baseline_configs.py, so every recorded number is over
the same pod population shape.

Reference analogue: the reference's benchmark fixtures are generated once
and shared across scales (/root/reference/pkg/controllers/interruption/
interruption_benchmark_test.go:61-76 reuses one message factory).
"""

from __future__ import annotations

# (name, share out of 10_000, cpu, memory, zone-pin, zone-spread?)
_DEPLOYMENTS = [
    ("web", 3000, "500m", "1Gi", None, True),
    ("api", 2000, "1", "2Gi", None, False),
    ("cache", 1000, "2", "8Gi", None, False),
    ("batch", 1500, "250m", "512Mi", None, False),
    ("etl", 800, "4", "8Gi", None, False),
    ("zone-a", 700, "1", "1Gi", "zone-1a", False),
    ("zone-b", 500, "1", "1Gi", "zone-1b", False),
    ("mem", 500, "500m", "4Gi", None, False),
]


def mixed_workload(n: int) -> list:
    """`n` pods in the headline 8-deployment mix (zone selectors + one
    zone-spread deployment), scaled proportionally from the 10k shape.
    mixed_workload(10_000) reproduces bench.py's original workload exactly."""
    from karpenter_tpu.apis import wellknown as wk
    from karpenter_tpu.models.pod import TopologySpreadConstraint, make_pod

    spread = (TopologySpreadConstraint(max_skew=1, topology_key=wk.LABEL_ZONE),)
    counts = [max(0, round(n * share / 10_000)) for _, share, *_ in _DEPLOYMENTS]
    counts[0] += n - sum(counts)  # rounding remainder lands on the largest

    pods = []
    for (name, _, cpu, mem, zone, has_spread), count in zip(_DEPLOYMENTS, counts):
        sel = {"topology.kubernetes.io/zone": zone} if zone else {}
        # re-key via wellknown to survive label constant changes
        if zone:
            sel = {wk.LABEL_ZONE: zone}
        topo = spread if has_spread else ()
        for i in range(count):
            pods.append(make_pod(f"{name}-{i}", cpu=cpu, memory=mem,
                                 node_selector=dict(sel), topology=topo))
    assert len(pods) == n, (len(pods), n)
    return pods
