"""The critical-path drill: prove the chain view explains the headline.

ISSUE 18's acceptance instrument: a 10k-pod solve (the BASELINE
stress_problem_50k shape) is driven through BOTH routing paths

  - ``single``  — one-device dispatch (TPUSolver, no mesh), and
  - ``sharded`` — the routed mesh path (ShardedContext over the CPU_ENV's
    8 virtual devices, ShapeRouter forced with crossover_cells=0),

plus a ``service`` leg — the same single solver behind an in-process
SolverService Sync/Solve round-trip, so the ``serialize`` phase (wire
lane) appears on a measured path — with the critical ledger ON. Per path
the drill asserts and records:

  1. **attribution** — the flat gap-ledger projection still covers
     >= 95% of the solve wall (``attributed_share >= 0.95``; the interval
     view must not have cost the flat view anything);
  2. **overlap baseline** — today's solve is serial, so the measured
     ``overlap_ratio`` must sit at ~0 (< 0.05): the ledger's headroom
     claim starts from an honest zero, and any future pipelining shows up
     as the ratio lifting off this recorded floor;
  3. **critical shares named** — the per-phase on-critical-path share,
     with ``serialize``/``encode`` called out per path (serialize is 0 by
     construction off the service leg);
  4. **measured vs modelled** — the warmup-captured XLA cost-analysis
     rungs (roofline.measured_snapshot()), with per-rung drift deltas
     against the hand model, ledgered so drift trends are gated.

Artifact: benchmarks/results/critical/critical_drill.json (deterministic
path, KARPENTER_TPU_CRITICAL_DIR redirects for presubmit). Each path's
shares are recorded through benchmarks/ledger.py; `make perf-regress`
gates critical_serialize_share via gate_probe(). Run via
`make critical-drill` (`--small` for the presubmit-sized variant).
"""

from __future__ import annotations

import json
import os
import statistics
import sys
import time

OUT_DIR = (os.environ.get("KARPENTER_TPU_CRITICAL_DIR")
           or os.path.join(os.path.dirname(__file__), "results", "critical"))
ARTIFACT = os.path.join(OUT_DIR, "critical_drill.json")

PODS = 10_000
SMALL_PODS = 400
REPEATS = 5
WARMUP = 2
MIN_ATTRIBUTED_SHARE = 0.95
# the serial baseline: a measured ratio above this means the ledger is
# claiming overlap a serial dispatch cannot have produced (a chain bug),
# not that the solver got faster
MAX_SERIAL_OVERLAP = 0.05
N_DEVICES = 8


def _solvers(pods_n: int = PODS, n_devices: int = N_DEVICES):
    """(catalog, provisioners, pods, single solver, sharded solver) — the
    profile_drill harness. The sharded half is None when the mesh can't
    build (single-device host)."""
    from karpenter_tpu.utils.jaxenv import pin_cpu

    pin_cpu(n_devices)
    from benchmarks.baseline_configs import stress_problem_50k
    from karpenter_tpu.solver import buckets
    from karpenter_tpu.solver.core import TPUSolver

    catalog, provisioners, pods = stress_problem_50k(pods_n)
    single = TPUSolver(catalog, provisioners)
    sharded = None
    try:
        from karpenter_tpu.parallel.sharded import ShardedContext

        ctx = ShardedContext()
        router = buckets.ShapeRouter(n_devices=ctx.device_count,
                                     crossover_cells=0)
        sharded = TPUSolver(catalog, provisioners,
                            mesh_ctx=ctx, router=router)
    except Exception as e:  # noqa: BLE001 — mesh is optional surface
        print(f"critical_drill: mesh unavailable ({e}); sharded path "
              f"skipped", file=sys.stderr)
    return catalog, provisioners, pods, single, sharded


def _service_solve(catalog, provisioners, pods):
    """An in-process SolverService Sync + a Solve callable: the one leg
    where ``serialize`` is a real measured phase (wire lane), not zero.
    In-process keeps the drill hermetic; the wire encode/decode work is
    identical to the remote path."""
    from karpenter_tpu.solver import wire
    from karpenter_tpu.solver.service import SolverService, pb

    svc = SolverService()
    svc.Sync(pb.SyncRequest(
        catalog=wire.catalog_to_wire(catalog),
        provisioners=[wire.provisioner_to_wire(p) for p in provisioners],
    ), None)
    req = pb.SolveRequest(
        catalog_seqnum=catalog.seqnum,
        catalog_hash=wire.catalog_hash(catalog),
        provisioner_hash=wire.provisioners_hash(provisioners),
        pods=[wire.pod_to_wire(p) for p in pods],
    )
    return lambda: svc.Solve(req, None)


def _critical_summary(name: str, rows: "list[dict]",
                      walls_ms: "list[float]") -> dict:
    """Fold one path's gap-ledger rows (each carrying its ``critical``
    section) into the drill's per-path record."""
    crits = [r["critical"] for r in rows if r.get("critical")]
    if not crits:
        return {"path": name, "error": "no critical rows", "passed": False}
    med = lambda key: statistics.median(c[key] for c in crits)  # noqa: E731
    phase_names = sorted({p for c in crits
                          for p in c["on_critical_path_ms"]})
    on_ms = {p: round(statistics.median(
        c["on_critical_path_ms"].get(p, 0.0) for c in crits), 4)
        for p in phase_names}
    share = {p: round(statistics.median(
        c["critical_share"].get(p, 0.0) for c in crits), 6)
        for p in phase_names}
    waits = {w: round(statistics.median(
        c["waits_ms"].get(w, 0.0) for c in crits), 4)
        for w in sorted({w for c in crits for w in c["waits_ms"]})}
    attributed = statistics.median(r["attributed_share"] for r in rows)
    overlap = med("overlap_ratio")
    return {
        "path": name,
        "repeats": len(rows),
        "wall_ms_min": round(min(walls_ms), 3),
        "wall_ms_median": round(statistics.median(walls_ms), 3),
        "critical_path_ms": round(med("critical_path_ms"), 4),
        "total_work_ms": round(med("total_work_ms"), 4),
        "overlap_ratio": round(overlap, 6),
        "attributed_share": round(attributed, 6),
        "on_critical_path_ms": on_ms,
        "critical_share": share,
        # the two shares the acceptance names per path: what fraction of
        # the chain is wire serialization vs host encode
        "critical_serialize_share": share.get("serialize", 0.0),
        "critical_encode_share": share.get("encode", 0.0),
        "waits_ms": waits,
        "passed": (attributed >= MIN_ATTRIBUTED_SHARE
                   and 0.0 <= overlap < MAX_SERIAL_OVERLAP),
    }


def run_path(name: str, solve, repeats: int = REPEATS,
             warmup: int = WARMUP) -> dict:
    """Measure one leg: warmup compiles, then `repeats` solves with the
    profiling + critical planes ON; the per-solve interval records land in
    the gap-ledger rows' ``critical`` sections."""
    from karpenter_tpu import profiling
    from karpenter_tpu.profiling import GAP_LEDGER, critical

    for _ in range(warmup):
        solve()
    profiling.set_enabled(True)
    critical.set_enabled(True)
    GAP_LEDGER.clear()
    walls_ms: "list[float]" = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        solve()
        walls_ms.append((time.perf_counter() - t0) * 1e3)
    rows = GAP_LEDGER.rows()[-repeats:]
    return _critical_summary(name, rows, walls_ms)


def gate_probe(pods: int = SMALL_PODS) -> dict:
    """Small service-routed probe for `make perf-regress`: one warmed
    Solve through the in-process service, returns the per-path summary
    (the gate reads critical_serialize_share — serialization creeping
    onto the critical path is a regression the wall clock alone hides)."""
    from karpenter_tpu.utils.jaxenv import pin_cpu

    pin_cpu(N_DEVICES)
    catalog, provisioners, probe_pods, _single, _sharded = \
        _solvers(pods, n_devices=N_DEVICES)
    solve = _service_solve(catalog, provisioners, probe_pods)
    return run_path("service", solve, repeats=3, warmup=1)


def _roofline_section() -> dict:
    """The measured-roofline evidence: warmup-captured XLA rungs with the
    per-rung measured-vs-modelled drift deltas the acceptance ledgers."""
    from karpenter_tpu.profiling import roofline

    snap = roofline.measured_snapshot()
    deltas = {}
    for bucket, rung in (snap.get("rungs") or {}).items():
        if "flops_drift" in rung:
            deltas[bucket] = {
                "flops_drift": rung["flops_drift"],
                "measured_flops": rung.get("flops"),
                "modelled_flops": rung.get("modelled_flops"),
                "flagged": rung.get("flagged", False),
            }
    snap["drift_deltas"] = deltas
    return snap


def run_drill(pods_n: int = PODS, repeats: int = REPEATS) -> dict:
    from benchmarks import ledger
    from karpenter_tpu import profiling
    from karpenter_tpu.profiling import critical, roofline

    # the planes must be on BEFORE the solvers warm: the measured-roofline
    # capture fires inside warm_shapes and gates on both flags
    profiling.set_enabled(True)
    critical.set_enabled(True)
    roofline.clear_measured()
    catalog, provisioners, pods, single, sharded = _solvers(pods_n)
    paths = {"single": run_path("single", lambda: single.solve(pods),
                                repeats)}
    if sharded is not None:
        paths["sharded"] = run_path("sharded",
                                    lambda: sharded.solve(pods), repeats)
    paths["service"] = run_path(
        "service", _service_solve(catalog, provisioners, pods), repeats)
    # warm the single solver's observed rung explicitly so the measured
    # roofline has at least one captured entry even on a cold run
    try:
        if single.last_shape_key is not None:
            single.warm_shapes([single.last_shape_key])
    except Exception as e:  # noqa: BLE001 — advisory capture
        print(f"critical_drill: roofline warm capture failed: {e}",
              file=sys.stderr)
    record = {
        "tool": "karpenter_tpu.critical_drill",
        "schema": 1,
        "pods": pods_n,
        "repeats": repeats,
        "thresholds": {
            "min_attributed_share": MIN_ATTRIBUTED_SHARE,
            "max_serial_overlap": MAX_SERIAL_OVERLAP,
        },
        "paths": paths,
        "roofline_measured": _roofline_section(),
        "passed": bool(paths) and all(p["passed"] for p in paths.values()),
    }
    os.makedirs(OUT_DIR, exist_ok=True)
    with open(ARTIFACT, "w") as f:
        json.dump(record, f, indent=1, sort_keys=True)
        f.write("\n")
    for name, p in paths.items():
        if "error" in p:
            continue
        workload = {"name": "critical_drill", "path": name, "pods": pods_n}
        degraded = not p["passed"]
        for metric, value, unit in (
                ("critical_overlap_ratio", p["overlap_ratio"], "ratio"),
                ("critical_attributed_share", p["attributed_share"],
                 "ratio"),
                ("critical_serialize_share", p["critical_serialize_share"],
                 "share"),
                ("critical_path_ms", p["critical_path_ms"], "ms")):
            ledger.record(metric, value, unit,
                          source="benchmarks.critical_drill", backend="cpu",
                          workload=workload, degraded=degraded,
                          artifact=ARTIFACT)
    for bucket, delta in record["roofline_measured"]["drift_deltas"].items():
        ledger.record("roofline_flops_drift", delta["flops_drift"], "ratio",
                      source="benchmarks.critical_drill", backend="cpu",
                      workload={"name": "critical_drill", "bucket": bucket},
                      degraded=bool(delta["flagged"]), artifact=ARTIFACT)
    return record


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    small = "--small" in argv
    record = run_drill(pods_n=SMALL_PODS if small else PODS,
                       repeats=3 if small else REPEATS)
    print(json.dumps({
        "passed": record["passed"],
        "paths": {k: {"overlap_ratio": v.get("overlap_ratio"),
                      "attributed_share": v.get("attributed_share"),
                      "critical_serialize_share":
                          v.get("critical_serialize_share"),
                      "critical_encode_share":
                          v.get("critical_encode_share"),
                      "wall_ms_min": v.get("wall_ms_min")}
                  for k, v in record["paths"].items()},
        "roofline_rungs": len(
            record["roofline_measured"].get("rungs") or {}),
        "drift_flagged": record["roofline_measured"].get("drift_flagged"),
        "artifact": ARTIFACT,
    }))
    return 0 if record["passed"] else 1


if __name__ == "__main__":
    sys.exit(main())
