"""The catalog-churn endurance drill: 1000 zipf tenants, 4 real replicas,
an HBM cap fitting ~1/4 of the hot set, and an in-artifact A/B proving
the admission filter halves eviction thrash.

The overload plane's whole story — graduated backpressure, anti-thrash
resident eviction, fairness under shedding — re-proven across REAL
process boundaries:

* each solver replica is its own OS process (fleet/replica.py) booted
  with `KARPENTER_TPU_HBM_CAPACITY_BYTES` sized (by an in-process grid
  calibration) so the residency cap fits roughly a quarter of the
  per-replica hot catalog set;
* traffic is a catalog-churn mix from ONE seeded RNG: every request
  Syncs a catalog — usually one of the replica's skew-popular hot
  variants, with probability `churn_prob` a never-seen-again one-shot —
  then solves through the fleet frontend's fairness queue;
* the SAME fixed-length schedule runs twice: once with the overload
  plane forced off (`KARPENTER_TPU_OVERLOAD=0`, plain LRU, unbounded
  backlog, no shedding) and once with it on. Both windows report the
  always-on thrash ledger (solver/service.py eviction_stats), so the
  halving claim is an in-artifact A/B, not a cross-run comparison;
* every audit reads federated scrape evidence (`/debug/statusz` over
  HTTP): resident bytes vs the cap each scrape cycle, per-tenant shed
  attribution citing SHED_REASONS, fairness (no tenant waits past the
  starvation bound), and the guard's transition ledger for monotone
  one-step brownout recovery.

`build_replay_plan()` reproduces the full (tenant, variant) sequence
bit-for-bit without spawning anything, so the committed artifact's
schedule digest is replayable in tier-1 time.

Run as `make churn-drill` (full: 4 replicas, 1000 tenants) or
`make churn-drill-small` (2 replicas, tier-1 sized). Artifact:
benchmarks/results/churn/churn_drill.json (or _small)."""

from __future__ import annotations

import argparse
import collections
import hashlib
import itertools
import json
import os
import random
import shutil
import sys
import tempfile
import threading
import time
import zlib
from dataclasses import dataclass, asdict
from typing import Optional

PODS_PER_SOLVE = 2
# zipf skew over a replica's hot variants: the head must dominate so the
# residency cap (≈ hot/4) can hold the working set ONLY when one-shots
# are kept out of the main LRU — exactly the property the A/B measures
HOT_SKEW = 2.0
ONE_SHOT_BASE = 1_000_000
# resident-bytes audit slack: grid builds run OUTSIDE the service lock
# (Health stays responsive during churn), so an async scrape can observe
# up to a couple of in-flight builds on top of the retained set — the
# RETAINED set (final scrape, post-drain) is held to the cap strictly
INFLIGHT_ALLOWANCE_SOLVERS = 2.0


@dataclass(frozen=True)
class DrillConfig:
    name: str
    replicas: int
    tenants: int
    tail_len: int                  # fixed-length zipf tail after the sweep
    workers: int
    max_wave: int
    seed: int = 0
    hot_variants: int = 6          # per-replica hot catalog set size
    churn_prob: float = 0.55       # P(a tail draw Syncs a one-shot catalog)
    # residency cap in calibrated solver-grid units. The geometry that
    # makes every audit non-vacuous (solved hot = 3116B, synced one-shot
    # probationer = 2x-heavier 3264B): the TYPICAL steady state — 3
    # solved hots plus a resident probationer, 12612B — sits in the
    # guard's [0.75, 0.9) shed band, so over-rate sheds flow whenever a
    # churned catalog is on probation; the PEAK state — a full 4-entry
    # LRU plus probationer, 15728B — crosses 0.9, so brownouts and
    # low-water drains happen but only at the peak, not on every
    # one-shot install (a cap where the TYPICAL state crosses 0.9 makes
    # each one-shot strip two warm hots and collapses the A/B margin in
    # both windows). The hot set (6 variants, 18696B solved) still
    # outweighs the cap, so its zipf tail is forced through eviction
    cap_solvers: float = 5.2
    tick_interval_s: float = 0.01
    starvation_bound: int = 16
    zipf_exponent: float = 1.1     # tenant-rank skew (fleet drill's value)
    solve_timeout_s: float = 60.0
    boot_timeout_s: float = 240.0
    scrape_interval_s: float = 0.1
    drain_timeout_s: float = 20.0
    # bound on sync->solve eviction races per request: each retry re-Syncs
    # (cheap — the catalog is known) and under 32-worker churn a hot
    # solver can lose this race several times in a row, so the bound is
    # generous; the OFF window (no probation side-car) races hardest
    sync_retries: int = 10
    warmup_rungs: "tuple[int, ...]" = (2, 4)
    # ON must divide the OFF thrash ratio by at least this factor
    thrash_improvement: float = 2.0
    # FULL requires the ON window to actually shed (falsifiability: an
    # A/B whose ON window never sheds proves nothing about attribution)
    require_sheds: bool = False


FULL = DrillConfig(name="full", replicas=4, tenants=1000, tail_len=2000,
                   workers=32, max_wave=8, require_sheds=True)
SMALL = DrillConfig(name="small", replicas=2, tenants=32, tail_len=144,
                    workers=6, max_wave=4)


# -- deterministic schedule (shared by the drill and its replay plan) -------


def _tenant_ids(cfg: DrillConfig) -> "list[str]":
    return [f"tenant-{i:04d}" for i in range(cfg.tenants)]


def _replica_names(cfg: DrillConfig) -> "list[str]":
    return [f"r{i}" for i in range(cfg.replicas)]


def _replica_of(cfg: DrillConfig, tid: str) -> int:
    # stable content hash, NOT salted builtin hash(): routing must agree
    # between the run that produced an artifact and the replay audit
    return zlib.crc32(tid.encode()) % cfg.replicas


def _zipf_cum(n: int, exponent: float) -> "list[float]":
    cum, total = [], 0.0
    for i in range(n):
        total += 1.0 / ((i + 1) ** exponent)
        cum.append(total)
    return cum


def _zipf_idx(cum: "list[float]", r: float) -> int:
    import bisect

    return bisect.bisect_left(cum, r * cum[-1])


def _hot_variant(cfg: DrillConfig, tid: str, hot_cum, r: float) -> int:
    """One hot-catalog draw for `tid`: its replica's hot set, zipf-skewed
    so the head variants carry most of the mass."""
    rep = _replica_of(cfg, tid)
    return rep * cfg.hot_variants + _zipf_idx(hot_cum, r)


def build_items(cfg: DrillConfig) -> "list[tuple[str, int, str]]":
    """The full deterministic (tenant, variant, kind) sequence: a
    shuffled sweep (every tenant once, hot draw — warms the hot set and
    pins down the within-weight population) followed by a FIXED-length
    zipf tail with the churn mix. Fixed length — not wall-clock bounded —
    so both A/B windows realize the identical schedule and the artifact
    digest covers exactly what ran."""
    tenants = _tenant_ids(cfg)
    rng = random.Random(cfg.seed)
    sweep = list(tenants)
    rng.shuffle(sweep)
    hot_cum = _zipf_cum(cfg.hot_variants, HOT_SKEW)
    tenant_cum = _zipf_cum(len(tenants), cfg.zipf_exponent)
    one_shot = itertools.count(ONE_SHOT_BASE)
    items: "list[tuple[str, int, str]]" = []
    for tid in sweep:
        items.append((tid, _hot_variant(cfg, tid, hot_cum, rng.random()),
                      "hot"))
    for _ in range(cfg.tail_len):
        tid = tenants[_zipf_idx(tenant_cum, rng.random())]
        if rng.random() < cfg.churn_prob:
            items.append((tid, next(one_shot), "one"))
        else:
            items.append((tid, _hot_variant(cfg, tid, hot_cum,
                                            rng.random()), "hot"))
    return items


def schedule_digest(items) -> str:
    h = hashlib.blake2b(digest_size=16)
    for tid, variant, kind in items:
        h.update(f"{tid}:{variant}:{kind}".encode())
        h.update(b"\x00")
    return h.hexdigest()


def build_replay_plan(cfg: DrillConfig) -> dict:
    """The drill's deterministic skeleton, computed WITHOUT spawning
    anything: the full churn schedule and a digest over it. A committed
    artifact replays bit-for-bit from (seed, config) alone."""
    items = build_items(cfg)
    counts = collections.Counter(tid for tid, _, _ in items)
    return {
        "schema": 1,
        "seed": cfg.seed,
        "tenants": cfg.tenants,
        "replicas": _replica_names(cfg),
        "requests": len(items),
        "one_shots": sum(1 for _, _, k in items if k == "one"),
        "hot_variants_per_replica": cfg.hot_variants,
        "within_weight_tenants": sum(1 for c in counts.values() if c == 1),
        "head": [f"{t}:{v}:{k}" for t, v, k in items[:8]],
        "schedule_digest": schedule_digest(items),
    }


# -- workload ---------------------------------------------------------------


N_TYPES = 24  # big enough that grid residency dominates a solver's weight
# one-shot (churned) catalogs are BIGGER than hot ones: a tenant mutating
# its catalog every submission is typically growing it, and the heavier
# synced-only grid is what lifts HBM pressure into the guard's shed band
# while a probationer is resident — WITHOUT crossing the 0.9 low-water
# trigger — so the drill exercises the whole ladder, not just defer
N_TYPES_ONE_SHOT = 48


def _variant_catalog(variant: int):
    """Catalog content for one variant id. Prices are perturbed — od by
    `variant % 9973`, spot by `variant // 9973` steps — so every variant
    id maps to a distinct content hash (the LRU identity) while shapes
    stay identical within each class (hot vs one-shot), keeping compile
    caches warm and grid builds cheap."""
    from karpenter_tpu.models.instancetype import Catalog, make_instance_type

    od = round(0.20 + (variant % 9973) * 1e-4, 6)
    spot = round(0.07 + (variant // 9973) * 1e-4, 6)
    n = N_TYPES_ONE_SHOT if variant >= ONE_SHOT_BASE else N_TYPES
    return Catalog(types=[
        make_instance_type(f"m{i}.large", cpu=4 * (1 + i % 4),
                           memory=f"{16 * (1 + i % 4)}Gi",
                           od_price=round(od + 0.01 * i, 6),
                           spot_price=round(spot + 0.01 * i, 6))
        for i in range(n)])


def _provisioners():
    from karpenter_tpu.apis import wellknown as wk
    from karpenter_tpu.apis.provisioner import Provisioner
    from karpenter_tpu.models.requirements import OP_IN, Requirements

    prov = Provisioner(name="default", requirements=Requirements.of(
        (wk.LABEL_CAPACITY_TYPE, OP_IN, ["spot", "on-demand"])))
    prov.set_defaults()
    return [prov]


def calibrate_solver_bytes() -> int:
    """Measure the SOLVED residency weight of one variant — static grid
    plus one bucket rung of delta tensors — by running a Sync + Solve
    through an in-process SolverService and reading the HBM ledger.
    Residency is a deterministic function of catalog/pod shapes
    (identical across variants AND across the parent/replica process
    boundary on the same platform), so the parent can size the replicas'
    cap without booting a calibration subprocess."""
    from karpenter_tpu.models.pod import make_pod
    from karpenter_tpu.solver import buckets, wire
    from karpenter_tpu.solver.service import SolverService, pb, hbm_key

    svc = SolverService()
    provs = _provisioners()
    wire_cat = wire.catalog_to_wire(_variant_catalog(0))
    svc.Sync(pb.SyncRequest(
        catalog=wire_cat,
        provisioners=[wire.provisioner_to_wire(p) for p in provs]), None)
    pods = [make_pod(f"churn-calib-p{j}", cpu="1", memory="2Gi")
            for j in range(PODS_PER_SOLVE)]
    svc.Solve(pb.SolveRequest(
        catalog_hash=wire.catalog_hash(wire_cat),
        provisioner_hash=wire.provisioners_hash(provs),
        pods=[wire.pod_to_wire(p) for p in pods]), None)
    nbytes = int(buckets.HBM.resident_bytes())
    with svc._lock:
        keys = list(svc._cache) + list(svc._probation)
    for key in keys:
        buckets.HBM.release(hbm_key(key))
    if nbytes <= 0:
        raise RuntimeError("HBM calibration tracked 0 bytes: the grid "
                           "build no longer files device puts under "
                           "hbm_scope — the cap audit would be vacuous")
    return nbytes


def classify_outcome(exc) -> "tuple[str, Optional[str]]":
    """Map a wire error back to (outcome, shed_reason): the frontend
    aborts FleetShed as DEADLINE_EXCEEDED with the shed message in the
    status details, so the client can attribute every shed to its
    SHED_REASONS row without a side channel."""
    msg = str(exc)
    if "browned out" in msg:
        return "shed", "overload-brownout"
    if "overload pressure" in msg:
        return "shed", "overload-pressure"
    if "backlog exceeded the bound" in msg:
        return "shed", "overload-queue-overflow"
    if "shedding at admission" in msg or "gave up waiting" in msg:
        return "shed", "deadline"
    return "error", None


# -- the drill --------------------------------------------------------------


def _set_env(key: str, value: "Optional[str]"):
    """Apply one env edit (None deletes); returns a restore thunk."""
    prev = os.environ.get(key)
    if value is None:
        os.environ.pop(key, None)
    else:
        os.environ[key] = value

    def restore():
        if prev is None:
            os.environ.pop(key, None)
        else:
            os.environ[key] = prev
    return restore


def _log_tail(path: str, n: int = 20) -> str:
    try:
        with open(path, errors="replace") as f:
            return "".join(f.readlines()[-n:])
    except OSError as e:
        return f"<no log: {e}>"


def _run_window(cfg: DrillConfig, label: str, overload_on: bool,
                cap_bytes: int, items, log_dir: str) -> dict:
    """Boot a fresh fleet, run the FULL schedule through it, scrape, and
    tear down. The overload gate and the HBM cap ride the environment —
    replicas inherit the parent's os.environ at spawn — restored before
    returning so windows cannot contaminate each other."""
    from karpenter_tpu.fleet.replica import (
        GrpcReplicaTransport, spawn_replica, wait_for_registrations)
    from karpenter_tpu.introspect.fleetview import HttpReplica
    from karpenter_tpu.overload.state import FLAG_ENV
    from karpenter_tpu.solver import solver_pb2 as pb
    from karpenter_tpu.solver import wire
    from karpenter_tpu.solver.buckets import HBM_CAPACITY_ENV
    from karpenter_tpu.models.pod import make_pod

    names = _replica_names(cfg)
    rendezvous = tempfile.mkdtemp(prefix=f"churn-{label}-", dir=log_dir)
    restores = [
        _set_env(FLAG_ENV, None if overload_on else "0"),
        _set_env(HBM_CAPACITY_ENV, str(cap_bytes)),
    ]
    procs: "dict[str, object]" = {}
    transports: "dict[str, GrpcReplicaTransport]" = {}
    stop_scrape = threading.Event()
    failed = True
    try:
        for name in names:
            procs[name] = spawn_replica(
                name, rendezvous, max_wave=cfg.max_wave,
                tick_interval_s=cfg.tick_interval_s,
                starvation_bound=cfg.starvation_bound)
        regs = wait_for_registrations(rendezvous, names,
                                      timeout_s=cfg.boot_timeout_s)
        debug: "dict[str, HttpReplica]" = {}
        for name in names:
            transports[name] = GrpcReplicaTransport(name, regs[name]["grpc"])
            debug[name] = HttpReplica(name, regs[name]["debug"])

        provs = _provisioners()
        prov_hash = wire.provisioners_hash(provs)
        catalogs: "dict[int, object]" = {}
        hashes: "dict[int, int]" = {}

        def catalog_of(variant: int):
            cat = catalogs.get(variant)
            if cat is None:
                cat = catalogs[variant] = _variant_catalog(variant)
                hashes[variant] = wire.catalog_hash(wire.catalog_to_wire(cat))
            return cat

        seq = itertools.count()

        def build_request(tid: str, variant: int):
            i = next(seq)
            pods = [make_pod(f"{tid}-q{i}-p{j}", cpu="1", memory="2Gi")
                    for j in range(PODS_PER_SOLVE)]
            catalog_of(variant)
            return pb.SolveRequest(
                catalog_hash=hashes[variant], provisioner_hash=prov_hash,
                pods=[wire.pod_to_wire(p) for p in pods])

        # -- warm: head catalog + batch rungs on every replica ----------
        for idx, name in enumerate(names):
            head = idx * cfg.hot_variants
            transports[name].sync(catalog_of(head), provs)
            transports[name](f"warm-{name}",
                             build_request(f"warm-{name}", head),
                             cfg.solve_timeout_s * 4)
            for k in cfg.warmup_rungs:
                burst = [threading.Thread(
                    target=transports[name],
                    args=(f"warm-{name}-{k}-{j}",
                          build_request(f"warm-{name}-{k}-{j}", head),
                          cfg.solve_timeout_s * 4))
                    for j in range(k)]
                for t in burst:
                    t.start()
                for t in burst:
                    t.join()

        # -- scraper: resident-vs-cap samples every cycle ----------------
        samples: "list[dict]" = []
        samples_lock = threading.Lock()

        def scraper():
            while not stop_scrape.is_set():
                for name in names:
                    try:
                        snap = debug[name].statusz()
                    except Exception as e:  # noqa: BLE001 — audited below
                        rec = {"replica": name, "error": str(e)}
                    else:
                        hbm = snap.get("hbm") or {}
                        fleet = (snap.get("fleet") or {}).get(
                            "frontends") or [{}]
                        rec = {"replica": name,
                               "resident_bytes":
                                   hbm.get("resident_bytes_total"),
                               "capacity_bytes": hbm.get("capacity_bytes"),
                               "pressure": hbm.get("pressure"),
                               "queued": fleet[0].get("queued")}
                    with samples_lock:
                        samples.append(rec)
                stop_scrape.wait(cfg.scrape_interval_s)

        # -- traffic: the full fixed schedule through the fairness queue --
        outcomes: "list[Optional[dict]]" = [None] * len(items)
        cursor = itertools.count()
        # a one-shot Sync→Solve pair holds this per-replica gate so a
        # concurrent one-shot cannot recycle the probation slot between
        # the Sync and the Solve it serves (hot traffic stays concurrent)
        oneshot_gate = {name: threading.Lock() for name in names}

        def solve_with_resync(tr, tid: str, variant: int) -> dict:
            t0 = time.perf_counter()
            for attempt in range(cfg.sync_retries + 1):
                try:
                    tr(tid, build_request(tid, variant), cfg.solve_timeout_s)
                    return {"tenant": tid, "outcome": "served",
                            "ms": (time.perf_counter() - t0) * 1e3}
                except Exception as e:  # noqa: BLE001 — classified below
                    msg = str(e)
                    if ("re-Sync required" in msg
                            and attempt < cfg.sync_retries):
                        # the target solver was evicted between our Sync
                        # and the queue drain: re-Sync (a repeat sighting
                        # — it earns residency) and retry
                        tr.sync(catalog_of(variant), provs)
                        continue
                    outcome, reason = classify_outcome(e)
                    rec = {"tenant": tid, "outcome": outcome,
                           "ms": (time.perf_counter() - t0) * 1e3}
                    if reason is not None:
                        rec["reason"] = reason
                    else:
                        rec["error"] = f"{type(e).__name__}: {e}"
                    return rec
            raise AssertionError("unreachable")

        def worker():
            while True:
                i = next(cursor)
                if i >= len(items):
                    return
                tid, variant, kind = items[i]
                name = names[_replica_of(cfg, tid)]
                tr = transports[name]
                try:
                    if kind == "one":
                        # churn: push the one-shot catalog, then serve the
                        # tenant from its replica's resident head — the
                        # Sync exercises the admission filter, the Solve
                        # exercises fairness under the pressure it causes
                        with oneshot_gate[name]:
                            tr.sync(catalog_of(variant), provs)
                        solve_v = _replica_of(cfg, tid) * cfg.hot_variants
                    else:
                        tr.sync(catalog_of(variant), provs)
                        solve_v = variant
                    outcomes[i] = {**solve_with_resync(tr, tid, solve_v),
                                   "variant": variant, "kind": kind}
                except Exception as e:  # noqa: BLE001 — audited as outcome
                    outcomes[i] = {"tenant": tid, "variant": variant,
                                   "kind": kind, "outcome": "error",
                                   "error": f"{type(e).__name__}: {e}"}

        scrape_thread = threading.Thread(target=scraper, daemon=True)
        workers = [threading.Thread(target=worker, daemon=True)
                   for _ in range(cfg.workers)]
        t0 = time.perf_counter()
        scrape_thread.start()
        for w in workers:
            w.start()
        for w in workers:
            w.join()
        wall = time.perf_counter() - t0

        # -- drain, then the final (retained-state) scrape ----------------
        deadline = time.monotonic() + cfg.drain_timeout_s
        while time.monotonic() < deadline:
            queued = 0
            for name in names:
                snap = debug[name].statusz()
                fronts = (snap.get("fleet") or {}).get("frontends") or []
                queued += sum(int(f.get("queued") or 0) for f in fronts)
            if queued == 0:
                break
            time.sleep(0.1)
        stop_scrape.set()
        scrape_thread.join(timeout=5.0)

        finals: "dict[str, dict]" = {}
        for name in names:
            snap = debug[name].statusz()
            fronts = (snap.get("fleet") or {}).get("frontends") or []
            ours = next((f for f in fronts if f.get("name") == name),
                        fronts[0] if fronts else {})
            over = snap.get("overload") or {}
            orow = next((f for f in (over.get("frontends") or [])
                         if f.get("name") == name), {})
            finals[name] = {
                "hbm": snap.get("hbm") or {},
                "fairness": {"starvation_bound":
                             ours.get("starvation_bound"),
                             "queued": ours.get("queued"),
                             "tenants": ours.get("tenants") or {}},
                "overload_enabled": over.get("enabled"),
                "overload_counters": over.get("counters") or {},
                "guard": orow.get("guard") or {},
                "guard_evidence": orow.get("evidence") or {},
                "eviction": orow.get("eviction") or {},
            }

        served = [o for o in outcomes if o and o["outcome"] == "served"]
        result = {
            "label": label,
            "overload_on": overload_on,
            "realized": sum(1 for o in outcomes if o is not None),
            "served": len(served),
            "sheds": sum(1 for o in outcomes
                         if o and o["outcome"] == "shed"),
            "errors": sum(1 for o in outcomes
                          if o and o["outcome"] == "error"),
            "error_head": [o["error"] for o in outcomes
                           if o and o.get("error")][:5],
            "wall_s": round(wall, 3),
            "solves_per_sec": (round(len(served) / wall, 3)
                               if wall > 0 else 0.0),
            "pids": {n: regs[n]["pid"] for n in names},
            "outcomes": [o for o in outcomes if o is not None],
            "samples": samples,
            "finals": finals,
        }
        failed = False
        return result
    finally:
        stop_scrape.set()
        for proc in procs.values():
            try:
                proc.terminate()
            except OSError:
                pass
        for proc in procs.values():
            try:
                proc.wait(timeout=10.0)
            except Exception:  # noqa: BLE001 — escalate, then move on
                proc.kill()
        for tr in transports.values():
            tr.close()
        for restore in reversed(restores):
            restore()
        if failed:
            for name in procs:
                tail = _log_tail(os.path.join(rendezvous, f"{name}.log"))
                print(f"--- {name} [{label}] log tail ({rendezvous}) ---\n"
                      f"{tail}", file=sys.stderr)


def _window_eviction_totals(window: dict) -> dict:
    installs = thrash = evictions = 0
    for rec in window["finals"].values():
        ev = rec.get("eviction") or {}
        installs += int(ev.get("installs") or 0)
        thrash += int(ev.get("thrash_events") or 0)
        evictions += int(ev.get("evictions") or 0)
    ratio = (thrash / installs) if installs else 0.0
    return {"installs": installs, "evictions": evictions,
            "thrash_events": thrash, "thrash_ratio": round(ratio, 4)}


def _scraped_shed_tenants(window: dict) -> "dict[str, dict]":
    """tenant -> {total, reasons{reason: count}} summed across replicas,
    schedule tenants only (warm traffic audited separately)."""
    out: "dict[str, dict]" = {}
    for rec in window["finals"].values():
        for tid, st in (rec["fairness"]["tenants"] or {}).items():
            if not tid.startswith("tenant-"):
                continue
            total = int(st.get("shed_admission") or 0) + \
                int(st.get("shed_queue") or 0)
            if total == 0:
                continue
            row = out.setdefault(tid, {"total": 0, "reasons": {}})
            row["total"] += total
            for per in (st.get("shed_reasons") or {}).values():
                for reason, n in per.items():
                    row["reasons"][reason] = \
                        row["reasons"].get(reason, 0) + int(n)
    return out


def audit(cfg: DrillConfig, plan: dict, items, per_solver: int,
          cap_bytes: int, off: dict, on: dict):
    """Every acceptance criterion, from scrape evidence + client
    outcomes; returns (criteria, violations, evidence)."""
    from karpenter_tpu.chaos import invariants as inv
    from karpenter_tpu.explain.reasons import SHED_REASONS
    from karpenter_tpu.overload.guard import OverloadGuard

    violations: "list[inv.Violation]" = []
    counts = collections.Counter(tid for tid, _, _ in items)

    # real subprocesses, full schedule realized in BOTH windows
    pids = set(off["pids"].values()) | set(on["pids"].values())
    real = (len(pids) == 2 * cfg.replicas and os.getpid() not in pids)
    realized = (off["realized"] == len(items)
                and on["realized"] == len(items))

    # resident bytes vs the cap: retained state (final scrape) strictly
    # under the cap; mid-run samples under cap + in-flight-build slack
    allowance = int(INFLIGHT_ALLOWANCE_SOLVERS * per_solver)
    max_sample, over_samples, n_samples = 0, 0, 0
    for window in (off, on):
        for s in window["samples"]:
            r = s.get("resident_bytes")
            if r is None:
                continue
            n_samples += 1
            max_sample = max(max_sample, int(r))
            if r > cap_bytes + allowance:
                over_samples += 1
    max_final = max(int((rec["hbm"].get("resident_bytes_total") or 0))
                    for w in (off, on) for rec in w["finals"].values())
    resident_capped = (n_samples > 0 and over_samples == 0
                       and max_final <= cap_bytes)
    if not resident_capped:
        violations.append(inv.Violation(
            "churn-resident-over-cap",
            f"{over_samples}/{n_samples} scrape samples over "
            f"cap+allowance ({cap_bytes}+{allowance}); max sample "
            f"{max_sample}, max retained {max_final}"))

    # the A/B: admission filter ON must divide the thrash ratio
    ev_off, ev_on = (_window_eviction_totals(w) for w in (off, on))
    thrash_halved = (
        ev_off["thrash_events"] > 0
        and ev_on["thrash_ratio"] * cfg.thrash_improvement
        <= ev_off["thrash_ratio"])
    if not thrash_halved:
        violations.append(inv.Violation(
            "churn-thrash-not-halved",
            f"off ratio {ev_off['thrash_ratio']} "
            f"({ev_off['thrash_events']}/{ev_off['installs']}) vs on "
            f"{ev_on['thrash_ratio']} ({ev_on['thrash_events']}/"
            f"{ev_on['installs']}); need >= {cfg.thrash_improvement}x cut"))

    # fairness: no tenant past the starvation bound, either window
    fair_v: "list[inv.Violation]" = []
    for window in (off, on):
        for name, rec in window["finals"].items():
            fair_v += inv.check_fairness_never_starves(rec["fairness"])
    violations += fair_v

    # every non-served outcome is a shed citing the vocabulary, and the
    # scraped per-tenant ledgers reconcile with the client's count
    outcome_v = inv.check_completes_or_sheds(
        off["outcomes"] + on["outcomes"])
    violations += outcome_v
    shed_map_on = _scraped_shed_tenants(on)
    scraped_total = sum(row["total"] for row in shed_map_on.values())
    bad_reasons = sorted(
        {r for row in shed_map_on.values() for r in row["reasons"]}
        - set(SHED_REASONS))
    sheds_cited = (not outcome_v and not bad_reasons
                   and scraped_total == on["sheds"])
    if bad_reasons or scraped_total != on["sheds"]:
        violations.append(inv.Violation(
            "churn-shed-attribution",
            f"scraped sheds {scraped_total} vs client {on['sheds']}; "
            f"off-vocabulary reasons {bad_reasons}"))

    # fairness contract under pressure: within-weight tenants (exactly
    # one appearance — they can never be over their weighted share at
    # decide time) are served and never shed; every overload-* shed
    # lands on a multi-appearance tenant
    within = {tid for tid, c in counts.items() if c == 1}
    starved = sorted(
        tid for tid in within
        if not all(o["outcome"] == "served"
                   for o in on["outcomes"] if o["tenant"] == tid))
    shed_within = sorted(tid for tid in shed_map_on if tid in within)
    misattributed = sorted(
        tid for tid, row in shed_map_on.items()
        if counts.get(tid, 0) < 2
        and any(r.startswith("overload-") for r in row["reasons"]))
    within_ok = not starved and not shed_within
    absorbed_ok = not misattributed
    if not within_ok:
        violations.append(inv.Violation(
            "churn-within-weight-starved",
            f"within-weight tenants shed or unserved: "
            f"{(starved + shed_within)[:5]}"))
    if not absorbed_ok:
        violations.append(inv.Violation(
            "churn-shed-misattributed",
            f"overload sheds on single-appearance tenants: "
            f"{misattributed[:5]}"))

    # brownout recovery: every downward guard transition steps exactly
    # one rung and only fires below the hysteresis mark
    enter, hyst = OverloadGuard.ENTER, OverloadGuard.HYSTERESIS
    mono_v = []
    for name, rec in on["finals"].items():
        for t in (rec["guard_evidence"].get("transitions") or []):
            frm, to = int(t["from"]), int(t["to"])
            if to < frm and (frm - to != 1
                             or t["pressure"] >= enter[frm] - hyst):
                mono_v.append(f"{name}: {t}")
    if mono_v:
        violations.append(inv.Violation(
            "churn-brownout-not-monotone",
            f"non-monotone or early down transitions: {mono_v[:3]}"))

    # strict noop: the OFF window's overload plane must be inert
    off_sheds = sum(
        int(st.get("shed_admission") or 0) + int(st.get("shed_queue") or 0)
        for rec in off["finals"].values()
        for st in (rec["fairness"]["tenants"] or {}).values())
    off_counters = {k: v for rec in off["finals"].values()
                    for k, v in (rec["overload_counters"] or {}).items()
                    if v}
    off_inert = (off["sheds"] == 0 and off_sheds == 0
                 and not any(rec["overload_enabled"]
                             for rec in off["finals"].values())
                 and not off_counters)
    if not off_inert:
        violations.append(inv.Violation(
            "churn-off-window-not-inert",
            f"disabled window shed {off['sheds']}/{off_sheds} "
            f"(client/scraped) or counted activity {off_counters}"))

    criteria = {
        "replicas_are_real_subprocesses": real,
        "schedule_fully_realized": realized,
        "resident_bytes_capped": resident_capped,
        "thrash_halved_by_admission_filter": thrash_halved,
        "fairness_never_starves": not fair_v,
        "sheds_cite_reason_vocabulary": sheds_cited,
        "within_weight_tenants_never_shed": within_ok,
        "overload_sheds_absorbed_by_over_rate_tenants": absorbed_ok,
        "brownout_recovery_monotone": not mono_v,
        "off_window_inert": off_inert,
        "invariants_hold": not violations,
    }
    if cfg.require_sheds:
        criteria["overload_sheds_observed"] = on["sheds"] > 0
        if on["sheds"] == 0:
            violations.append(inv.Violation(
                "churn-no-sheds",
                "the ON window never shed: the attribution audits were "
                "vacuous at this scale"))
            criteria["invariants_hold"] = False
    evidence = {
        "eviction_off": ev_off,
        "eviction_on": ev_on,
        "resident": {"cap_bytes": cap_bytes, "per_solver_bytes": per_solver,
                     "inflight_allowance_bytes": allowance,
                     "max_sample_bytes": max_sample,
                     "max_retained_bytes": max_final,
                     "samples": n_samples},
        "shed_tenants_on": shed_map_on,
        "within_weight_tenants": len(within),
    }
    return criteria, violations, evidence


def run_drill(cfg: DrillConfig, out_dir: "Optional[str]" = None) -> dict:
    plan = build_replay_plan(cfg)
    items = build_items(cfg)
    per_solver = calibrate_solver_bytes()
    cap_bytes = int(per_solver * cfg.cap_solvers)
    log_root = tempfile.mkdtemp(prefix="churn-drill-")
    try:
        off = _run_window(cfg, "off", False, cap_bytes, items, log_root)
        on = _run_window(cfg, "on", True, cap_bytes, items, log_root)
    except Exception:
        raise
    else:
        shutil.rmtree(log_root, ignore_errors=True)
    criteria, violations, evidence = audit(
        cfg, plan, items, per_solver, cap_bytes, off, on)

    def window_summary(w: dict) -> dict:
        shed_reasons = collections.Counter(
            o["reason"] for o in w["outcomes"]
            if o["outcome"] == "shed")
        return {k: w[k] for k in ("label", "overload_on", "realized",
                                  "served", "sheds", "errors",
                                  "error_head", "wall_s",
                                  "solves_per_sec")} | {
            "shed_reasons": dict(shed_reasons),
            "eviction": _window_eviction_totals(w),
            "guard": {n: rec["guard"] for n, rec in w["finals"].items()},
            "guard_transitions": {
                n: (rec["guard_evidence"].get("transitions") or [])
                for n, rec in w["finals"].items()},
        }

    artifact = {
        "tool": "karpenter-tpu-churn-drill",
        "schema": 1,
        "captured_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "config": asdict(cfg),
        "replay": plan,
        "calibration": {"per_solver_bytes": per_solver,
                        "cap_bytes": cap_bytes,
                        "cap_solvers": cfg.cap_solvers},
        "windows": {"off": window_summary(off), "on": window_summary(on)},
        "audit": evidence,
        "violations": [v.as_dict() for v in violations],
        "criteria": criteria,
        "passed": all(criteria.values()),
    }
    if out_dir:
        os.makedirs(out_dir, exist_ok=True)
        suffix = "" if cfg.name == "full" else f"_{cfg.name}"
        path = os.path.join(out_dir, f"churn_drill{suffix}.json")
        with open(path, "w") as f:
            json.dump(artifact, f, indent=2, sort_keys=True, default=str)
            f.write("\n")
        artifact["artifact_path"] = path
    return artifact


# -- presubmit perf gate ----------------------------------------------------


def gate_probe() -> dict:
    """Tier-1-sized thrash probe for hack/check_perf_regress: ONE
    in-process SolverService under a cap fitting ~1/4 of an 8-variant hot
    set, driven with the drill's churn mix (admission filter on). The
    gate trends the thrash ratio so filter rot — one-shots creeping back
    into the main LRU — fails presubmit like any perf regression."""
    from karpenter_tpu import overload
    from karpenter_tpu.solver import buckets, wire
    from karpenter_tpu.solver.buckets import HBM_CAPACITY_ENV
    from karpenter_tpu.solver.service import SolverService, pb, hbm_key

    provs = _provisioners()
    wire_provs = [wire.provisioner_to_wire(p) for p in provs]

    def sync(svc, variant: int):
        svc.Sync(pb.SyncRequest(
            catalog=wire.catalog_to_wire(_variant_catalog(variant)),
            provisioners=wire_provs), None)

    prev_enabled = overload.set_enabled(True)
    svc = SolverService()
    restore_cap = None
    try:
        sync(svc, 0)  # calibration install (also the probe's head)
        per_solver = max(1, int(buckets.HBM.resident_bytes()))
        restore_cap = _set_env(HBM_CAPACITY_ENV, str(int(per_solver * 2.5)))
        rng = random.Random(0)
        hot_cum = _zipf_cum(8, HOT_SKEW)
        one_shot = itertools.count(ONE_SHOT_BASE)
        for _ in range(60):
            if rng.random() < 0.55:
                sync(svc, next(one_shot))
            else:
                sync(svc, _zipf_idx(hot_cum, rng.random()))
        stats = svc.eviction_stats()
        return {"thrash_ratio": stats["thrash_ratio"],
                "installs": stats["installs"],
                "thrash_events": stats["thrash_events"]}
    finally:
        if restore_cap is not None:
            restore_cap()
        overload.set_enabled(prev_enabled)
        with svc._lock:
            keys = list(svc._cache) + list(svc._probation)
        for key in keys:
            buckets.HBM.release(hbm_key(key))


def _ledger_records(artifact: dict) -> None:
    """Record the drill's trend metrics through the SAME extractor the
    ledger's backfill uses, against the repo-relative artifact path — a
    later `backfill()` dedupes against what the live run wrote."""
    from benchmarks import ledger

    path = artifact.get("artifact_path")
    if not path:
        return
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    rel = os.path.relpath(path, root)
    for (metric, value, unit, backend, degraded,
         workload, ts) in ledger._churn_entries(artifact):
        ledger.append(ledger.make_entry(
            metric, value, unit, source="benchmarks.churn_drill",
            backend=backend, degraded=degraded, workload=workload,
            artifact=rel, recorded_at=ts))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--small", action="store_true",
                    help="tier-1-sized config (2 replicas, 32 tenants)")
    ap.add_argument("--out-dir", default=None)
    args = ap.parse_args(argv)
    cfg = SMALL if args.small else FULL
    out_dir = args.out_dir or os.environ.get(
        "KARPENTER_TPU_DRILL_DIR",
        os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))),
            "benchmarks", "results", "churn"))
    artifact = run_drill(cfg, out_dir)
    _ledger_records(artifact)
    print(json.dumps({"passed": artifact["passed"],
                      "criteria": artifact["criteria"],
                      "thrash_off":
                          artifact["audit"]["eviction_off"]["thrash_ratio"],
                      "thrash_on":
                          artifact["audit"]["eviction_on"]["thrash_ratio"],
                      "sheds_on": artifact["windows"]["on"]["sheds"],
                      "violations": artifact["violations"][:10],
                      "artifact": artifact.get("artifact_path")},
                     indent=2))
    return 0 if artifact["passed"] else 1


if __name__ == "__main__":
    sys.exit(main())
