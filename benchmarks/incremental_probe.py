"""Presubmit-sized probe for the incremental plane's headline ratio.

`gate_probe()` rebuilds the soak's measurement (bench.py --soak,
incremental section) at gate scale: a small churned fleet, a handful of
reconcile cycles, the incremental resident-patch cycle timed against the
legacy full-recompute sweeps it replaces. It returns the steady-state
encode share — incremental cycle p50 over legacy cycle p50 — which
hack/check_perf_regress.py trends through the ledger noise band: a
structural regression (resident patching drifting back toward
fleet-proportional work) moves this ratio long before any absolute
latency band would notice at probe scale.

Parity is asserted, not returned: a probe that got faster by diverging
from the legacy sweeps is a bug, so divergence raises instead of
reporting a flattering share.
"""
from __future__ import annotations

import dataclasses
import random
import statistics
import time


def gate_probe(n_nodes: int = 1500, cycles: int = 8, qps: int = 120) -> dict:
    import numpy as np

    from benchmarks.workloads import mixed_workload
    from karpenter_tpu.apis import wellknown as wk
    from karpenter_tpu.apis.provisioner import Provisioner
    from karpenter_tpu.controllers.deprovisioning import \
        DeprovisioningController
    from karpenter_tpu.incremental import (DeltaTracker, ResidentCandidates,
                                           ResidentMasks, empty_node_rows,
                                           expired_node_rows)
    from karpenter_tpu.models.cluster import ClusterState, StateNode
    from karpenter_tpu.models.encode import existing_fit_vector
    from karpenter_tpu.models.pod import group_pods, make_pod
    from karpenter_tpu.utils.clock import FakeClock

    rng = random.Random(20260806)
    now = 1_000_000.0
    clock = FakeClock(now)
    provs = [Provisioner(name="p-empty", ttl_seconds_after_empty=10**9),
             Provisioner(name="p-plain")]
    for p in provs:
        p.set_defaults()

    class _Kube:
        def provisioners(self):
            return provs

    class _Termination:
        def request_deletion(self, name):
            return False

    alloc = wk.capacity_vector({wk.RESOURCE_CPU: 16_000,
                                wk.RESOURCE_MEMORY: 64 * 2**30,
                                wk.RESOURCE_PODS: 110})
    templates = [make_pod(f"tmpl-{i}", cpu=f"{250 * (1 + i % 4)}m",
                          memory=f"{512 * (1 + i % 4)}Mi",
                          owner_kind="ReplicaSet") for i in range(4)]

    def fresh_node(name):
        i = rng.randrange(1 << 30)
        return StateNode(
            name=name,
            labels={wk.LABEL_ZONE: f"zone-1{'abc'[i % 3]}",
                    wk.LABEL_CAPACITY_TYPE: ("spot" if i % 4 == 0
                                             else "on-demand"),
                    wk.LABEL_INSTANCE_TYPE: f"m.size{i % 6}",
                    "team": f"t{i % 12}"},
            allocatable=list(alloc),
            provisioner_name=provs[i % len(provs)].name,
            price=0.05 + (i % 100) / 1000.0,
            created_ts=now - (i % 86_400),
            pods=[dataclasses.replace(templates[j % len(templates)],
                                      name=f"{name}-p{j}", node_name=name)
                  for j in range(8)])

    cluster = ClusterState()
    names = []
    for k in range(n_nodes):
        name = f"probe-{k:05d}"
        cluster.add_node(fresh_node(name))
        names.append(name)
    ctrl = DeprovisioningController(
        kube=_Kube(), cloudprovider=None, cluster=cluster,
        termination=_Termination(), clock=clock, use_tpu_solver=False)
    mask_specs = [g.spec for g in group_pods(mixed_workload(40))]

    rmasks = ResidentMasks(cluster)
    rcands = ResidentCandidates(cluster)
    tracker = DeltaTracker(cluster)
    tracker.advance()

    def churn(cycle):
        for j in range(qps):
            node = cluster.nodes[names[rng.randrange(len(names))]]
            op = rng.random()
            if op < 0.5:
                t = templates[rng.randrange(len(templates))]
                cluster.bind_pod(node.name, dataclasses.replace(
                    t, name=f"probe-churn-{cycle}-{j}", node_name=node.name))
            elif op < 0.8:
                if node.pods:
                    node.pods.pop(rng.randrange(len(node.pods)))
            else:
                node.labels["team"] = f"t{rng.randrange(12)}"

    def inc_cycle():
        t0 = time.perf_counter()
        tracker.dirty_names()
        tracker.advance()
        rmasks.sync(mask_specs)
        rcands.sync()
        rcands.eligible_rows()
        _, ttl_e = ctrl._prov_ttl_columns("ttl_seconds_after_empty")
        _, ttl_x = ctrl._prov_ttl_columns("ttl_seconds_until_expired")
        empty_node_rows(cluster, ttl_e)
        expired_node_rows(cluster, ttl_x, clock.now())
        return (time.perf_counter() - t0) * 1000

    inc_ms, legacy_ms = [], []
    for cycle in range(max(3, cycles)):
        churn(cycle)
        clock.step(1.0)
        # incremental first: the resident patch pays the dirty rows'
        # evictability recomputes itself (same ordering as the soak)
        ims = inc_cycle()
        t0 = time.perf_counter()
        ctrl.reconcile_emptiness()
        ctrl.reconcile_expiration()
        cands = cluster.consolidation_candidates()
        ex = cluster.existing_columns()
        legacy_vecs = [existing_fit_vector(ex, s) for s in mask_specs]
        lms = (time.perf_counter() - t0) * 1000
        if cycle == 0:  # cold full build / cache seeding — not steady state
            continue
        inc_ms.append(ims)
        legacy_ms.append(lms)
        if not all(np.array_equal(rmasks.mask_for(ex, s), lv)
                   for s, lv in zip(mask_specs, legacy_vecs)):
            raise AssertionError("incremental probe: resident mask diverged "
                                 "from fresh existing_fit_vector fold")
        if rcands.candidate_names() != sorted(n.name for n in cands):
            raise AssertionError("incremental probe: resident candidate set "
                                 "diverged from consolidation_candidates")

    share = statistics.median(inc_ms) / max(statistics.median(legacy_ms),
                                            1e-9)
    return {"encode_share": round(share, 4),
            "inc_cycle_p50_ms": round(statistics.median(inc_ms), 3),
            "legacy_cycle_p50_ms": round(statistics.median(legacy_ms), 3),
            "nodes": n_nodes, "cycles_measured": len(inc_ms), "qps": qps}


if __name__ == "__main__":
    import json

    print(json.dumps(gate_probe(), indent=2, sort_keys=True))
