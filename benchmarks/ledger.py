"""The unified perf ledger: one append-only JSONL trend file for every
benchmark this repo records (docs/designs/slo.md).

Bench numbers used to live in ~30 ad-hoc JSON artifacts with no shared
schema and no trend: a regression could only be found by a human diffing
BENCH_r{N} against r{N-1}. This module is the single write path — every
bench entrypoint (`bench.py` headline/steady/fleet/soak,
`benchmarks/wire_bench.py`, `benchmarks/interruption_bench.py`,
`benchmarks/multichip_wire.py`) records its headline numbers through
`record()` — and the single read path for trend consumers
(`hack/check_perf_regress.py` noise bands, `hack/check_round_claims.py`
ledger citations).

Each entry carries the full provenance a future reader needs to trust or
discard it: git sha, backend, the `degraded` flag, the workload shape,
the source entrypoint, and the artifact path the number came from.
Entries are one JSON object per line, append-only (history is never
rewritten; a corrected number is a NEW entry at a newer sha). The ledger
itself must never break a bench: `record()` swallows write failures after
logging them.

`backfill()` seeds the trend from history — BENCH_r01–r05 at the repo
root plus every artifact already under benchmarks/results/ — and is
idempotent: entries are deduped on (artifact, metric, workload), so
re-running it is a no-op.

CLI:
    python -m benchmarks.ledger backfill        # seed/refresh from history
    python -m benchmarks.ledger band METRIC     # print a noise band
    python -m benchmarks.ledger tail [N]        # last N entries
"""

from __future__ import annotations

import glob
import json
import logging
import os
import subprocess
import time

log = logging.getLogger("karpenter.ledger")

SCHEMA_VERSION = 1

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DEFAULT_PATH = os.path.join(_ROOT, "benchmarks", "results", "ledger.jsonl")


def ledger_path(path: "str | None" = None) -> str:
    """Resolution order: explicit arg > KARPENTER_TPU_LEDGER env (tests and
    ad-hoc runs must not pollute the committed trend) > the committed file."""
    return path or os.environ.get("KARPENTER_TPU_LEDGER") or DEFAULT_PATH


def _git_sha() -> str:
    try:
        out = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"], cwd=_ROOT,
            capture_output=True, text=True, timeout=10)
        if out.returncode == 0:
            return out.stdout.strip()
    except Exception:
        pass
    return ""


def _relpath(p: "str | None") -> "str | None":
    if not p:
        return p
    try:
        ap = os.path.abspath(p)
        if ap.startswith(_ROOT + os.sep):
            return os.path.relpath(ap, _ROOT)
    except Exception:
        pass
    return p


def make_entry(metric: str, value, unit: str, *, source: str,
               backend: "str | None" = None, degraded: bool = False,
               workload: "dict | None" = None,
               artifact: "str | None" = None,
               recorded_at: "str | None" = None,
               git_sha: "str | None" = None,
               detail: "dict | None" = None) -> dict:
    entry = {
        "schema": SCHEMA_VERSION,
        "recorded_at": recorded_at or time.strftime(
            "%Y%m%dT%H%M%SZ", time.gmtime()),
        "git_sha": _git_sha() if git_sha is None else git_sha,
        "source": source,
        "metric": metric,
        "value": value,
        "unit": unit,
        "backend": backend or "",
        "degraded": bool(degraded),
        "workload": dict(workload or {}),
        "artifact": _relpath(artifact),
    }
    if detail:
        entry["detail"] = detail
    return entry


def append(entry: dict, path: "str | None" = None) -> bool:
    """Append one prepared entry; one os.write of a full line (O_APPEND) so
    concurrent writers can't interleave partial lines. Never raises."""
    target = ledger_path(path)
    try:
        os.makedirs(os.path.dirname(target), exist_ok=True)
        line = json.dumps(entry, sort_keys=True) + "\n"
        fd = os.open(target, os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)
        try:
            os.write(fd, line.encode())
        finally:
            os.close(fd)
        return True
    except Exception as e:  # noqa: BLE001 — the ledger must not break a bench
        log.warning("perf-ledger append failed (%s): %s", target, e)
        return False


def record(metric: str, value, unit: str, *, source: str,
           backend: "str | None" = None, degraded: bool = False,
           workload: "dict | None" = None, artifact: "str | None" = None,
           detail: "dict | None" = None,
           path: "str | None" = None) -> dict:
    """The one write path every bench entrypoint records through. Returns
    the entry (written or not — a failed append is logged, not raised)."""
    entry = make_entry(metric, value, unit, source=source, backend=backend,
                       degraded=degraded, workload=workload,
                       artifact=artifact, detail=detail)
    append(entry, path=path)
    return entry


def entries(path: "str | None" = None) -> "list[dict]":
    """Every parseable entry, in file order. Malformed lines are skipped
    (append-only files survive crashes mid-write; a torn tail line must not
    poison the whole trend)."""
    target = ledger_path(path)
    out: "list[dict]" = []
    try:
        with open(target) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    e = json.loads(line)
                except ValueError:
                    continue
                if isinstance(e, dict) and "metric" in e:
                    out.append(e)
    except OSError:
        pass
    return out


def _median(xs: "list[float]") -> float:
    s = sorted(xs)
    n = len(s)
    mid = n // 2
    return s[mid] if n % 2 else (s[mid - 1] + s[mid]) / 2.0


def noise_band(metric: str, backend: "str | None" = None,
               path: "str | None" = None,
               ledger_entries: "list[dict] | None" = None,
               include_degraded: bool = False) -> "dict | None":
    """Median ± MAD over the ledger's history for one (metric, backend).
    Degraded entries are excluded by default — a relay-wedged CPU fallback
    must not widen the band the real numbers are judged against."""
    es = ledger_entries if ledger_entries is not None else entries(path)
    vals = [float(e["value"]) for e in es
            if e.get("metric") == metric
            and isinstance(e.get("value"), (int, float))
            and (backend is None or e.get("backend") == backend)
            and (include_degraded or not e.get("degraded"))]
    if not vals:
        return None
    med = _median(vals)
    mad = _median([abs(v - med) for v in vals])
    return {"metric": metric, "backend": backend, "n": len(vals),
            "median": med, "mad": mad}


# -- backfill -----------------------------------------------------------------
#
# One extractor per historical artifact family; each yields
# (metric, value, unit, backend, degraded, workload, recorded_at) tuples.
# The dedupe key is (artifact, metric, workload-json), so backfill is
# idempotent and can be re-run after new artifacts land.


def _bench_round_entries(doc: dict):
    """BENCH_r0N.json driver wrappers: {n, cmd, rc, tail, parsed} where
    `parsed` is bench.py's one emitted JSON line."""
    p = doc.get("parsed") or {}
    if not isinstance(p, dict) or p.get("value") is None:
        return
    detail = p.get("detail") or {}
    yield (p.get("metric", "scheduling_cycle_p50_ms_10k_pods_600_types"),
           p["value"], p.get("unit", "ms"), p.get("backend", ""),
           bool(p.get("degraded")),
           {"round": doc.get("n")},
           (detail.get("latest_tpu_capture") or {}).get("captured_at"))
    for extra in ("native_routed_ms", "onchip_ms", "wave_steady_per_solve_ms",
                  "callback_headline_ms", "io_escape_sync_after_ms",
                  "consolidation_500_streaming_ms"):
        v = p.get(extra)
        if isinstance(v, (int, float)):
            yield (extra, v, "ms", p.get("backend", ""),
                   bool(p.get("degraded")), {"round": doc.get("n")}, None)


def _ladder_entries(doc: dict):
    """benchmarks/record.py ladder artifacts (bench_*.json): interruption /
    wire_interruption msgs/s ladders, baseline-config ms sweep, and the
    wire provisioning cycle."""
    ts = doc.get("recorded_at")
    backend = doc.get("backend", "")
    for e in doc.get("entries") or []:
        kind = e.get("bench")
        if kind in ("interruption", "wire_interruption"):
            if isinstance(e.get("msgs_per_sec"), (int, float)):
                yield (f"{kind}_msgs_per_sec", e["msgs_per_sec"], "msgs/s",
                       backend, False, {"messages": e.get("messages")}, ts)
        elif kind == "baseline_config":
            if isinstance(e.get("ms"), (int, float)):
                yield ("baseline_config_ms", e["ms"], "ms", backend, False,
                       {"name": e.get("name")}, ts)
        elif kind == "wire_provisioning":
            for field, metric in (("cycle_seconds", "wire_cycle_seconds"),
                                  ("ingest_seconds", "wire_ingest_seconds")):
                if isinstance(e.get(field), (int, float)):
                    yield (metric, e[field], "s", backend, False,
                           {"pods": e.get("pods")}, ts)


def _tpu_capture_entries(doc: dict):
    ts = doc.get("captured_at")
    backend = doc.get("backend", "tpu")
    head = doc.get("headline") or {}
    if isinstance(head.get("p50_ms"), (int, float)):
        yield ("onchip_headline_p50_ms", head["p50_ms"], "ms", backend,
               bool(doc.get("partial")), {"device": doc.get("device")}, ts)
    for section, metric in (("exec_only_10k", "onchip_exec_only_10k_ms"),
                            ("consolidation_500", "consolidation_500_ms")):
        v = (doc.get(section) or {}).get("p50_ms")
        if isinstance(v, (int, float)):
            yield (metric, v, "ms", backend, bool(doc.get("partial")), {}, ts)


def _fleet_entries(doc: dict):
    ts = None
    backend = doc.get("backend", "")
    wl = {"tenants": doc.get("tenants"), "requests": doc.get("requests")}
    if isinstance(doc.get("value"), (int, float)):
        yield (doc.get("metric", "fleet_sustained_solves_per_sec"),
               doc["value"], doc.get("unit", "solves/s"), backend,
               not doc.get("passed", True), wl, ts)
    if isinstance(doc.get("p99_ms"), (int, float)):
        yield ("fleet_p99_ms", doc["p99_ms"], "ms", backend,
               not doc.get("passed", True), wl, ts)


def _soak_entries(doc: dict):
    wl = {"nodes": doc.get("nodes"), "pods": doc.get("pods")}
    if isinstance(doc.get("value"), (int, float)):
        yield (doc.get("metric", "soak_cycle_p99_ms"), doc["value"],
               doc.get("unit", "ms"), "cpu", not doc.get("passed", True),
               wl, None)
    if isinstance(doc.get("cycle_p50_ms"), (int, float)):
        yield ("soak_cycle_p50_ms", doc["cycle_p50_ms"], "ms", "cpu",
               not doc.get("passed", True), wl, None)


def _multichip_entries(doc: dict):
    wl = {"n_pods": doc.get("n_pods"), "devices": doc.get("devices"),
          "mesh": doc.get("mesh")}
    degraded = not (doc.get("bit_parity") and doc.get("decision_parity"))
    for field in ("wire_solve_ms", "service_solve_ms"):
        if isinstance(doc.get(field), (int, float)):
            yield (f"multichip_{field}", doc[field], "ms",
                   doc.get("backend", ""), degraded, wl,
                   doc.get("captured_at"))


def _trace_summary_entries(doc: dict):
    if isinstance(doc.get("device_exec_per_run_ms"), (int, float)):
        yield ("device_exec_per_run_ms", doc["device_exec_per_run_ms"], "ms",
               "tpu", False, {"workload": doc.get("workload")},
               doc.get("captured_at"))


def _profiling_entries(doc: dict):
    """benchmarks/profile_drill.py artifacts: per-path attribution shares
    (the perf-regress gate trends profile_unaccounted_share)."""
    if doc.get("tool") != "karpenter_tpu.profile_drill":
        return
    for name, p in (doc.get("paths") or {}).items():
        degraded = not p.get("passed", False)
        wl = {"name": "profile_drill", "path": name, "pods": doc.get("pods")}
        for field, metric in (
                ("unaccounted_share", "profile_unaccounted_share"),
                ("attributed_share", "profile_attributed_share"),
                ("overhead_share", "profile_overhead_share")):
            if isinstance(p.get(field), (int, float)):
                yield (metric, p[field], "ratio", "cpu", degraded, wl, None)


def _explain_entries(doc: dict):
    """benchmarks/explain_drill.py artifacts: attribution coverage, oracle
    parity, and the enabled-vs-disabled solve overhead (perf-regress
    trends explain_overhead_share)."""
    if doc.get("tool") != "karpenter_tpu.explain_drill":
        return
    degraded = not doc.get("passed", False)
    att = doc.get("attribution") or {}
    ovh = doc.get("overhead") or {}
    wl = {"name": "explain_drill", "pods": doc.get("pods"),
          "unassigned": att.get("pods_unassigned")}
    for section, field, metric in (
            (att, "attribution_coverage", "explain_attribution_coverage"),
            (att, "reason_parity", "explain_reason_parity"),
            (ovh, "overhead_share", "explain_overhead_share")):
        if isinstance(section.get(field), (int, float)):
            yield (metric, section[field], "ratio", "cpu", degraded, wl,
                   None)


def _fleet_drill_entries(doc: dict):
    """benchmarks/fleet_drill.py artifacts (full + _small): aggregate
    fleet throughput across REAL replica subprocesses, the slowest
    surviving replica's rate, and how many membership cycles the mid-run
    kill took to absorb. Degraded whenever the drill failed a criterion."""
    if doc.get("tool") != "karpenter-tpu-fleet-drill":
        return
    cfg = doc.get("config") or {}
    traffic = doc.get("traffic") or {}
    degraded = not doc.get("passed", False)
    ts = doc.get("captured_at")
    wl = {"name": "fleet_drill", "config": cfg.get("name"),
          "replicas": cfg.get("replicas"), "tenants": cfg.get("tenants")}
    if isinstance(traffic.get("aggregate_solves_per_sec"), (int, float)):
        yield ("fleet_drill_aggregate_solves_per_sec",
               traffic["aggregate_solves_per_sec"], "solves/s", "cpu",
               degraded, wl, ts)
    if isinstance(traffic.get("p99_ms"), (int, float)):
        yield ("fleet_drill_p99_ms", traffic["p99_ms"], "ms", "cpu",
               degraded, wl, ts)
    rc = (doc.get("kill") or {}).get("recovery_cycles")
    if isinstance(rc, (int, float)):
        yield ("fleet_drill_recovery_cycles", rc, "cycles", "cpu",
               degraded, wl, ts)
    rates = [r.get("solves_per_sec")
             for r in (doc.get("per_replica") or {}).values()
             if isinstance(r, dict)
             and isinstance(r.get("solves_per_sec"), (int, float))]
    if rates:
        yield ("fleet_drill_replica_min_solves_per_sec", min(rates),
               "solves/s", "cpu", degraded, wl, ts)


def _incremental_entries(doc: dict):
    """bench.py --soak incremental artifacts: steady-state incremental
    cycle p99, the share of the legacy full-recompute cycle it costs
    (the perf-regress gate trends incremental_steady_encode_share), and
    the per-cycle bit-parity verdict. Degraded whenever any cycle's
    parity audit failed."""
    if doc.get("tool") != "karpenter-tpu-incremental-soak":
        return
    degraded = not doc.get("parity_green_every_cycle", False)
    wl = {"nodes": doc.get("nodes"), "pods": doc.get("pods"),
          "qps": doc.get("churn_qps_equiv")}
    for field, metric, unit in (
            ("cycle_p99_incremental_ms", "cycle_p99_incremental_ms", "ms"),
            ("cycle_p50_incremental_ms", "cycle_p50_incremental_ms", "ms"),
            ("steady_encode_share_of_legacy_cycle",
             "incremental_steady_encode_share", "share"),
            ("dirty_rows_p50", "incremental_dirty_rows_p50", "rows")):
        if isinstance(doc.get(field), (int, float)):
            yield (metric, doc[field], unit, "cpu", degraded, wl, None)


def _critical_entries(doc: dict):
    """critical_drill artifacts: per-path overlap ratio (the serial
    baseline any future pipelining lifts off), flat-attribution share,
    and the serialize critical share the perf-regress gate trends; plus
    per-rung measured-vs-modelled FLOPs drift. Degraded whenever a path
    missed its acceptance or a rung's drift tripped the 2x flag."""
    if doc.get("tool") != "karpenter_tpu.critical_drill":
        return
    pods = doc.get("pods")
    for name, p in sorted((doc.get("paths") or {}).items()):
        if not isinstance(p, dict) or "error" in p:
            continue
        wl = {"name": "critical_drill", "path": name, "pods": pods}
        degraded = not p.get("passed", False)
        for field, metric, unit in (
                ("overlap_ratio", "critical_overlap_ratio", "ratio"),
                ("attributed_share", "critical_attributed_share", "ratio"),
                ("critical_serialize_share", "critical_serialize_share",
                 "share"),
                ("critical_path_ms", "critical_path_ms", "ms")):
            if isinstance(p.get(field), (int, float)):
                yield (metric, p[field], unit, "cpu", degraded, wl, None)
    roof = doc.get("roofline_measured") or {}
    for bucket, delta in sorted((roof.get("drift_deltas") or {}).items()):
        if isinstance(delta.get("flops_drift"), (int, float)):
            yield ("roofline_flops_drift", delta["flops_drift"], "ratio",
                   "cpu", bool(delta.get("flagged")),
                   {"name": "critical_drill", "bucket": bucket}, None)


def _spot_entries(doc: dict):
    """chaos --spot-storm artifacts: restore latency for the headline
    reclaim storm, the proactive-rebalance volume the rate limiter
    admitted, and the fleet's sticker cost either side of the storm.
    Degraded whenever the drill failed an invariant."""
    if doc.get("tool") != "karpenter_tpu.chaos" or \
            doc.get("mode") != "spot-storm":
        return
    degraded = not doc.get("passed", False)
    key = doc.get("key_numbers") or {}
    wl = {"name": "spot_storm", "nodes": doc.get("nodes"),
          "reclaims": doc.get("reclaims"), "seed": doc.get("seed")}
    for field, metric, unit in (
            ("restore_cycles", "spot_storm_restore_cycles", "cycles"),
            ("proactive_rebalances", "spot_storm_proactive_rebalances",
             "count"),
            ("hourly_cost_before", "spot_storm_hourly_cost_before",
             "usd_per_hour"),
            ("hourly_cost_after", "spot_storm_hourly_cost_after",
             "usd_per_hour"),
            ("wrong_forecast_post_clear_launches",
             "spot_storm_wrong_forecast_post_clear_launches", "count")):
        if isinstance(key.get(field), (int, float)):
            yield (metric, key[field], unit, "cpu", degraded, wl, None)


def _churn_entries(doc: dict):
    """benchmarks/churn_drill.py artifacts (full + _small): the A/B
    eviction-thrash ratios (filter on vs off over the SAME schedule),
    the ON window's shed volume, and each window's sustained solve rate.
    Degraded whenever the drill failed a criterion."""
    if doc.get("tool") != "karpenter-tpu-churn-drill":
        return
    cfg = doc.get("config") or {}
    audit = doc.get("audit") or {}
    windows = doc.get("windows") or {}
    degraded = not doc.get("passed", False)
    ts = doc.get("captured_at")
    wl = {"name": "churn_drill", "config": cfg.get("name"),
          "replicas": cfg.get("replicas"), "tenants": cfg.get("tenants"),
          "seed": cfg.get("seed")}
    for side in ("on", "off"):
        ev = audit.get(f"eviction_{side}") or {}
        if isinstance(ev.get("thrash_ratio"), (int, float)):
            yield (f"churn_thrash_ratio_{side}", ev["thrash_ratio"],
                   "ratio", "cpu", degraded, wl, ts)
        w = windows.get(side) or {}
        if isinstance(w.get("solves_per_sec"), (int, float)):
            yield (f"churn_solves_per_sec_{side}", w["solves_per_sec"],
                   "solves/s", "cpu", degraded, wl, ts)
    sheds = (windows.get("on") or {}).get("sheds")
    if isinstance(sheds, (int, float)):
        yield ("churn_sheds_on", sheds, "count", "cpu", degraded, wl, ts)


_BACKFILL_SOURCES = (
    ("BENCH_r0*.json", "bench.py", _bench_round_entries),
    ("benchmarks/results/bench_*.json", "benchmarks.record",
     _ladder_entries),
    ("benchmarks/results/interruption_*.json", "benchmarks.interruption_bench",
     _ladder_entries),
    ("benchmarks/results/wire_bench_*.json", "benchmarks.wire_bench",
     _ladder_entries),
    ("benchmarks/results/tpu_*.json", "bench.py", _tpu_capture_entries),
    ("benchmarks/results/fleet/fleet_bench.json", "bench.py --fleet",
     _fleet_entries),
    ("benchmarks/results/fleet/fleet_drill*.json", "benchmarks.fleet_drill",
     _fleet_drill_entries),
    ("benchmarks/results/churn/churn_drill*.json", "benchmarks.churn_drill",
     _churn_entries),
    ("benchmarks/results/soak/soak_*.json", "bench.py --soak",
     _soak_entries),
    ("benchmarks/results/incremental/incremental_*.json", "bench.py --soak",
     _incremental_entries),
    ("benchmarks/results/critical/critical_*.json",
     "benchmarks.critical_drill", _critical_entries),
    ("benchmarks/results/multichip_wire_*.json", "benchmarks.multichip_wire",
     _multichip_entries),
    ("benchmarks/results/trace_summary_*.json", "hack/summarize_trace",
     _trace_summary_entries),
    ("benchmarks/results/profiling/*.json", "benchmarks.profile_drill",
     _profiling_entries),
    ("benchmarks/results/explain/*.json", "benchmarks.explain_drill",
     _explain_entries),
    ("benchmarks/results/spot/spotstorm_*.json",
     "python -m karpenter_tpu chaos --spot-storm", _spot_entries),
)


def _dedupe_key(e: dict) -> tuple:
    return (e.get("artifact"), e.get("metric"),
            json.dumps(e.get("workload") or {}, sort_keys=True))


def backfill(root: "str | None" = None,
             path: "str | None" = None) -> int:
    """Seed the ledger from historical artifacts; returns the number of
    entries added. Idempotent: existing (artifact, metric, workload) keys
    are skipped, so `backfill(); backfill()` adds zero the second time."""
    base = root or _ROOT
    seen = {_dedupe_key(e) for e in entries(path)}
    added = 0
    for pattern, source, extract in _BACKFILL_SOURCES:
        for ap in sorted(glob.glob(os.path.join(base, pattern))):
            try:
                with open(ap) as f:
                    doc = json.load(f)
            except (OSError, ValueError) as e:
                log.warning("backfill skipping %s: %s", ap, e)
                continue
            if not isinstance(doc, dict):
                continue
            rel = os.path.relpath(ap, base)
            for (metric, value, unit, backend, degraded,
                 workload, ts) in extract(doc):
                entry = make_entry(
                    metric, value, unit, source=source, backend=backend,
                    degraded=degraded, workload=workload, artifact=rel,
                    recorded_at=ts or "backfill", git_sha="")
                key = _dedupe_key(entry)
                if key in seen:
                    continue
                seen.add(key)
                if append(entry, path=path):
                    added += 1
    return added


def record_artifact_entries(doc: dict, artifact: str, source: str,
                            path: "str | None" = None) -> int:
    """Ledger entries for one freshly written ladder-shaped artifact,
    via the SAME extractor backfill uses — so a later `backfill()` dedupes
    against what the live run already recorded."""
    added = 0
    for (metric, value, unit, backend, degraded,
         workload, ts) in _ladder_entries(doc):
        entry = make_entry(metric, value, unit, source=source,
                           backend=backend, degraded=degraded,
                           workload=workload, artifact=artifact,
                           recorded_at=ts)
        if append(entry, path=path):
            added += 1
    return added


def write_ladder_artifact(results: "list[dict]", prefix: str,
                          source: str) -> "str | None":
    """Standalone bench mains call this: write one dated
    benchmarks/results/<prefix>_<ts>.json and record its entries. Returns
    the artifact path, or None when KARPENTER_TPU_BENCH_ARTIFACT=0 —
    benchmarks.record sets that for its subprocesses because it archives
    and records the same lines itself (one artifact, not two)."""
    if os.environ.get("KARPENTER_TPU_BENCH_ARTIFACT") == "0":
        return None
    ts = time.strftime("%Y%m%dT%H%M%SZ", time.gmtime())
    doc = {"recorded_at": ts, "backend": "cpu", "entries": results}
    out_dir = os.path.join(_ROOT, "benchmarks", "results")
    os.makedirs(out_dir, exist_ok=True)
    ap = os.path.join(out_dir, f"{prefix}_{ts}.json")
    with open(ap, "w") as f:
        json.dump(doc, f, indent=1)
    record_artifact_entries(doc, os.path.relpath(ap, _ROOT), source)
    return ap


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    sub = ap.add_subparsers(dest="cmd", required=True)
    sub.add_parser("backfill")
    band = sub.add_parser("band")
    band.add_argument("metric")
    band.add_argument("--backend", default=None)
    tail = sub.add_parser("tail")
    tail.add_argument("n", nargs="?", type=int, default=10)
    args = ap.parse_args(argv)
    if args.cmd == "backfill":
        n = backfill()
        print(f"ledger backfill: {n} entries added "
              f"({len(entries())} total in {ledger_path()})")
    elif args.cmd == "band":
        b = noise_band(args.metric, backend=args.backend)
        print(json.dumps(b, indent=1) if b else
              f"no entries for metric {args.metric!r}")
        return 0 if b else 1
    elif args.cmd == "tail":
        for e in entries()[-args.n:]:
            print(json.dumps(e, sort_keys=True))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
