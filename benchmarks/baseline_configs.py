"""BASELINE.json configs[0..4] benchmark scenarios.

One JSON line per config:
  {"bench": "baseline_config", "config": i, "name": ..., "ms": ...,
   "nodes": ..., "detail": {...}}

Configs (BASELINE.json):
  0  inflate: 100 homogeneous CPU pods, 1 provisioner, ~20 types
     (+ decision parity with the scalar oracle — the north-star check)
  1  5k mixed cpu/mem pods, anti-affinity + topology spread across 3 AZs,
     full catalog
  2  GPU pods with taints/tolerations + extended resources, spot+OD weighting
  3  consolidation: 500 under-utilized nodes, replacement search over the
     full catalog
  4  stress: 50k pods, 8 provisioners with overlapping requirements, full
     offering set — sharded over every visible device via parallel/sharded
  5  pair sweep: multi-node consolidation over 64-node pair grids
  6  config 1's workload on the PRODUCTION routed backend (C++ scan)
  7  4x stress: 200k pods, same shape as 4 — beyond-reference scale point
  8  ICE storm: p50 first-solve-after-an-ICE-mark at config-1 shape — the
     static-grid fast path (docs/designs/bin-packing-kernel.md)
  9  20x stress: 1M pods x the full real fleet in one sharded dispatch

Usage: python -m benchmarks.baseline_configs [--configs 0,1,...,9]
"""

from __future__ import annotations

import argparse
import json
import statistics
import time

from karpenter_tpu.apis import wellknown as wk
from karpenter_tpu.apis.provisioner import Provisioner
from karpenter_tpu.models.instancetype import Catalog
from karpenter_tpu.models.pod import (Taint, Toleration,
                                      TopologySpreadConstraint, make_pod)
from karpenter_tpu.models.requirements import OP_IN, Requirements
from karpenter_tpu.providers.instancetypes import generate_fleet_catalog
from karpenter_tpu.solver.core import NativeSolver, TPUSolver

REPEATS = 5


def _provisioner(name="default", **kw):
    p = Provisioner(name=name, **kw)
    p.set_defaults()
    return p


def _timed_solve(solver, pods, repeats=REPEATS):
    result = solver.solve(pods)  # warmup: compile + grid build
    times = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        result = solver.solve(pods)
        times.append((time.perf_counter() - t0) * 1000)
    return result, statistics.median(times)


def config_0_inflate() -> dict:
    catalog = generate_fleet_catalog(max_types=20)
    prov = _provisioner()
    pods = [make_pod(f"inflate-{i}", cpu="1", memory="1536Mi")
            for i in range(100)]
    solver = TPUSolver(catalog, [prov])
    result, ms = _timed_solve(solver, pods)

    # north star: identical node decisions to the sequential oracle
    from karpenter_tpu.oracle.scheduler import Scheduler
    oracle = Scheduler(catalog, [prov])
    oracle_result = oracle.schedule(pods)
    oracle_decisions = oracle_result.node_decisions(oracle.options)
    assert result.decisions() == oracle_decisions, "decision parity violated"

    return {"bench": "baseline_config", "config": 0, "name": "inflate-100",
            "ms": round(ms, 3), "nodes": len(result.nodes),
            "detail": {"n_types": len(catalog.types),
                       "oracle_parity": True,
                       "unschedulable": result.unschedulable_count()}}


def _mixed_5k_pods():
    spread = (TopologySpreadConstraint(max_skew=1, topology_key=wk.LABEL_ZONE),)
    pods = []
    for name, count, cpu, mem, topo, anti in (
            ("web", 1500, "500m", "1Gi", spread, False),
            ("api", 1200, "1", "2Gi", spread, False),
            ("singleton", 100, "250m", "512Mi", (), True),
            ("cache", 700, "2", "8Gi", (), False),
            ("batch", 1000, "250m", "512Mi", (), False),
            ("mem", 500, "500m", "4Gi", (), False)):
        for i in range(count):
            pods.append(make_pod(f"{name}-{i}", cpu=cpu, memory=mem,
                                 topology=topo, anti_affinity_hostname=anti))
    assert len(pods) == 5000
    return pods


def config_1_mixed_5k() -> dict:
    catalog = generate_fleet_catalog()
    prov = _provisioner(requirements=Requirements.of(
        (wk.LABEL_CAPACITY_TYPE, OP_IN, ["spot", "on-demand"])))
    solver = TPUSolver(catalog, [prov])
    result, ms = _timed_solve(solver, _mixed_5k_pods())
    assert result.unschedulable_count() == 0
    return {"bench": "baseline_config", "config": 1, "name": "mixed-5k-3az",
            "ms": round(ms, 3), "nodes": len(result.nodes),
            "detail": {"n_types": len(catalog.types)}}


def config_6_mixed_5k_routed() -> dict:
    """config 1's workload on the PRODUCTION routed backend (the C++ scan
    the controller prefers behind a high-RTT tunnel) — records the number
    a real cycle pays next to the device-kernel-on-virtual-CPU number so
    the two are never conflated round-over-round."""
    catalog = generate_fleet_catalog()
    prov = _provisioner(requirements=Requirements.of(
        (wk.LABEL_CAPACITY_TYPE, OP_IN, ["spot", "on-demand"])))
    solver = NativeSolver(catalog, [prov])
    result, ms = _timed_solve(solver, _mixed_5k_pods())
    assert result.unschedulable_count() == 0
    return {"bench": "baseline_config", "config": 6,
            "name": "mixed-5k-3az-routed",
            "ms": round(ms, 3), "nodes": len(result.nodes),
            "detail": {"n_types": len(catalog.types), "backend": "native"}}


def config_2_gpu() -> dict:
    catalog = generate_fleet_catalog()
    gpu_prov = _provisioner(
        name="gpu", weight=10,  # preferred for pods that tolerate its taint
        taints=(Taint(key="nvidia.com/gpu", value="true", effect="NoSchedule"),),
        requirements=Requirements.of(
            (wk.LABEL_INSTANCE_GPU_NAME, OP_IN, ["a100"]),
            (wk.LABEL_CAPACITY_TYPE, OP_IN, ["spot", "on-demand"])))
    cpu_prov = _provisioner(name="default")
    tol = (Toleration(key="nvidia.com/gpu", operator="Exists"),)
    pods = [make_pod(f"train-{i}", cpu="4", memory="16Gi",
                     extended={wk.RESOURCE_NVIDIA_GPU: 1}, tolerations=tol)
            for i in range(200)]
    pods += [make_pod(f"cpu-{i}", cpu="1", memory="2Gi") for i in range(300)]
    solver = TPUSolver(catalog, [gpu_prov, cpu_prov])
    result, ms = _timed_solve(solver, pods)
    assert result.unschedulable_count() == 0
    gpu_nodes = [n for n in result.nodes if n.provisioner.name == "gpu"]
    assert gpu_nodes and all(
        dict(n.option.itype.labels).get(wk.LABEL_INSTANCE_GPU_NAME) == "a100"
        for n in gpu_nodes)
    # spot+OD weighting: every gpu node decision picked the cheaper offering
    assert all(n.option.capacity_type == "spot" for n in gpu_nodes)
    return {"bench": "baseline_config", "config": 2, "name": "gpu-taints-spot",
            "ms": round(ms, 3), "nodes": len(result.nodes),
            "detail": {"gpu_nodes": len(gpu_nodes)}}


def config_3_consolidation() -> dict:
    from karpenter_tpu.models.cluster import ClusterState, StateNode
    from karpenter_tpu.ops.consolidate import run_consolidation

    catalog = generate_fleet_catalog()
    prov = _provisioner(consolidation_enabled=True)
    cluster = ClusterState()
    # 500 m5.2xlarge-ish nodes each holding one small pod: all candidates
    big = catalog.by_name["m5.2xlarge"]
    for i in range(500):
        node = StateNode(
            name=f"n-{i}",
            labels={**big.labels_dict(), wk.LABEL_ZONE: "zone-1a",
                    wk.LABEL_CAPACITY_TYPE: "on-demand",
                    wk.LABEL_PROVISIONER: "default"},
            allocatable=big.allocatable_vector(),
            instance_type=big.name, zone="zone-1a", capacity_type="on-demand",
            price=big.offerings[0].price, provisioner_name="default",
            pods=[make_pod(f"p-{i}", cpu="500m", memory="1Gi",
                           node_name=f"n-{i}")],
        )
        cluster.add_node(node)
    run_consolidation(cluster, catalog, [prov])  # warmup
    times = []
    action = None
    for _ in range(REPEATS):
        t0 = time.perf_counter()
        action = run_consolidation(cluster, catalog, [prov])
        times.append((time.perf_counter() - t0) * 1000)
    assert action is not None
    return {"bench": "baseline_config", "config": 3, "name": "consolidation-500",
            "ms": round(statistics.median(times), 3), "nodes": 500,
            "detail": {"action": action.kind, "node": action.node,
                       "savings_per_hour": round(action.savings, 4)}}


def stress_problem_50k(n_pods: int = 50_000):
    """BASELINE.json configs[4] shape, the ONE definition shared by the
    recorded benchmark (config_4_stress_50k) and the driver's multichip
    dryrun (__graft_entry__.dryrun_multichip) so the CI parity check can
    never desynchronize from the benchmarked shape: full 603-type real fleet
    catalog, 8 provisioners with overlapping requirements, 25 deployments.
    Returns (catalog, provisioners, pods)."""
    catalog = generate_fleet_catalog()
    provisioners = []
    for i, (ct, archs) in enumerate((
            (["on-demand"], ["amd64"]),
            (["spot", "on-demand"], ["amd64"]),
            (["spot"], ["amd64"]),
            (["on-demand"], ["arm64"]),
            (["spot", "on-demand"], ["arm64"]),
            (["spot", "on-demand"], ["amd64", "arm64"]),
            (["on-demand"], ["amd64", "arm64"]),
            (["spot"], ["amd64", "arm64"]))):
        p = Provisioner(name=f"prov-{i}", weight=i,
                        requirements=Requirements.of(
                            (wk.LABEL_CAPACITY_TYPE, OP_IN, ct),
                            (wk.LABEL_ARCH, OP_IN, archs)))
        p.set_defaults()
        provisioners.append(p)
    n_dep = 25
    per = n_pods // n_dep
    pods = [make_pod(f"d{d}-p{i}", cpu=f"{250 * (d % 4 + 1)}m",
                     memory=f"{512 * (d % 8 + 1)}Mi")
            for d in range(n_dep) for i in range(per)]
    return catalog, provisioners, pods


def config_4_stress_50k() -> dict:
    return _stress_config(4, "stress-50k-sharded", 50_000, REPEATS)


def config_9_stress_1m() -> dict:
    """20x the 50k stress shape: one MILLION pending pods x the full 603-type real fleet in a
    single sharded dispatch — far beyond any scale the sequential
    reference's per-pod loop entertains (its own E2E ceiling is ~100-pod
    utilization suites). Repeats kept low: the point is that the shape
    fits and solves, the ladder's per-cycle numbers live in configs 1-7."""
    return _stress_config(9, "stress-1m-sharded", 1_000_000, 2)


def config_7_stress_200k() -> dict:
    """4x the reference-scale stress shape — beyond-reference scale point:
    200k pending pods solved in one sharded dispatch (the reference
    schedules incrementally and has no single-cycle analogue)."""
    return _stress_config(7, "stress-200k-sharded", 200_000, max(2, REPEATS // 2))


def _stress_config(idx: int, name: str, n_pods: int, repeats: int) -> dict:
    import jax
    import numpy as np

    from karpenter_tpu.models.encode import encode_problem
    from karpenter_tpu.ops.packer import PackInputs
    from karpenter_tpu.parallel.sharded import make_mesh, sharded_pack
    from karpenter_tpu.solver.core import _bucket

    catalog, provisioners, pods = stress_problem_50k(n_pods)
    assert len(pods) == n_pods

    from karpenter_tpu.models.encode import build_grid

    grid = build_grid(catalog)
    grid.get_cols()  # catalog-side arrays are cached per seqnum in production
    # encode timed the same way the solve is: warm median (steady-state
    # controllers re-encode persistent pod objects every cycle; the cold
    # first-contact cost is reported separately)
    group_cache: dict = {}
    t_enc = time.perf_counter()
    enc = encode_problem(catalog, provisioners, pods, grid=grid,
                         group_cache=group_cache)
    encode_cold_ms = (time.perf_counter() - t_enc) * 1000
    enc_times = []
    for _ in range(repeats):
        t_enc = time.perf_counter()
        enc = encode_problem(catalog, provisioners, pods, grid=grid,
                             group_cache=group_cache)
        enc_times.append((time.perf_counter() - t_enc) * 1000)
    encode_ms = statistics.median(enc_times)

    Gb = _bucket(enc.group_vec.shape[0])

    def pad(a, n, axis=0, fill=0):
        if a.shape[axis] == n:
            return a
        w = [(0, 0)] * a.ndim
        w[axis] = (0, n - a.shape[axis])
        return np.pad(a, w, constant_values=fill)

    inputs = PackInputs(
        alloc_t=enc.alloc_t, tiebreak=enc.tiebreak,
        group_vec=pad(enc.group_vec, Gb), group_count=pad(enc.group_count, Gb),
        group_cap=pad(enc.group_cap, Gb), group_feas=pad(enc.group_feas, Gb),
        group_newprov=pad(enc.group_newprov, Gb, fill=-1), overhead=enc.overhead,
        ex_alloc=enc.ex_alloc, ex_used=enc.ex_used, ex_feas=pad(enc.ex_feas, Gb),
    )
    n_slots = _bucket(enc.n_slots)
    mesh = make_mesh(len(jax.devices()))
    result = sharded_pack(inputs, n_slots, mesh)  # warmup (compile)
    jax.block_until_ready(result.assign)
    times = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        result = sharded_pack(inputs, n_slots, mesh)
        jax.block_until_ready(result.assign)
        times.append((time.perf_counter() - t0) * 1000)
    n_open = int(np.asarray(result.active).sum())
    n_unsched = int(np.asarray(result.unsched).sum())
    assert n_unsched == 0, f"{n_unsched} pods unschedulable"
    return {"bench": "baseline_config", "config": idx, "name": name,
            "ms": round(statistics.median(times), 3), "nodes": n_open,
            "detail": {"n_pods": len(pods), "n_types": len(catalog.types),
                       "n_devices": mesh.devices.size,
                       "encode_ms": round(encode_ms, 3),
                       "encode_cold_ms": round(encode_cold_ms, 3),
                       "mesh": dict(zip(mesh.axis_names, mesh.devices.shape))}}


def config_5_pair_sweep() -> dict:
    """Multi-node (pair) consolidation sweep — beyond-reference capability:
    64 full nodes, no single-node action exists, the batched pair dispatch
    evaluates 496 two-node lanes."""
    from karpenter_tpu.models.cluster import ClusterState, StateNode
    from karpenter_tpu.models.instancetype import make_instance_type
    from karpenter_tpu.ops.consolidate import run_consolidation

    catalog = generate_fleet_catalog()
    # the globally cheapest >=8-vCPU type: nothing cheaper can host a full
    # node's pods, so no single-node action exists
    big = min((t for t in catalog.types
               if dict(t.capacity)[wk.RESOURCE_CPU] >= 8000),
              key=lambda t: t.offerings[0].price)
    # a bulk-discounted big type (sub-linear pricing): the shape where pair
    # consolidation wins but single-node search cannot — priced so one bulk
    # node undercuts TWO `big` nodes but not one
    bulk_price = round(big.offerings[0].price * 1.7, 4)
    catalog.types.append(make_instance_type(
        "bulk.32xlarge", cpu=32, memory="128Gi", od_price=bulk_price))
    catalog.bump()  # rebuilds by_name too
    prov = _provisioner(consolidation_enabled=True)
    cluster = ClusterState()
    alloc = big.allocatable_vector()
    cpu_free = alloc[wk.RESOURCE_INDEX[wk.RESOURCE_CPU]]
    # FULL nodes: no cheaper single type fits a node's pods, but two nodes'
    # pods collapse onto one bulk.32xlarge (1.7x < 2x big's price)
    for i in range(64):
        n_pods = max(1, cpu_free // 1000)
        node = StateNode(
            name=f"n-{i:03d}",
            labels={**big.labels_dict(), wk.LABEL_ZONE: "zone-1a",
                    wk.LABEL_CAPACITY_TYPE: "on-demand",
                    wk.LABEL_PROVISIONER: "default"},
            allocatable=list(alloc),
            instance_type=big.name, zone="zone-1a", capacity_type="on-demand",
            price=big.offerings[0].price, provisioner_name="default",
            pods=[make_pod(f"p{i}-{j}", cpu="1", memory="1Gi",
                           node_name=f"n-{i:03d}") for j in range(n_pods)],
        )
        cluster.add_node(node)
    run_consolidation(cluster, catalog, [prov])  # warmup
    times = []
    action = None
    for _ in range(REPEATS):
        t0 = time.perf_counter()
        action = run_consolidation(cluster, catalog, [prov])
        times.append((time.perf_counter() - t0) * 1000)
    return {"bench": "baseline_config", "config": 5, "name": "pair-sweep-64",
            "ms": round(statistics.median(times), 3), "nodes": 64,
            "detail": {"action": None if action is None else
                       {"kind": action.kind, "nodes": list(action.nodes),
                        "replacement": action.replacement,
                        "savings_per_hour": round(action.savings, 4)}}}


def config_8_ice_storm() -> dict:
    """Spot-interruption storm: every message marks a pool unavailable,
    bumping catalog content — the next cycle used to pay a full grid +
    group-encode rebuild. Measures the p50 FIRST solve after each of a
    series of ICE marks (fresh catalog object + donated solver per mark,
    exactly the controller's solver-cache path), beside the same solver's
    warm number. Reference analogue: the ICE cache is designed for
    millisecond retries (website concepts _index.md:143,
    unavailableofferings.go:31-80)."""
    from karpenter_tpu.cache import UnavailableOfferings
    from karpenter_tpu.providers.instancetypes import InstanceTypeProvider

    src = generate_fleet_catalog()
    ice = UnavailableOfferings()
    provider = InstanceTypeProvider(src, ice, None)
    prov = _provisioner(requirements=Requirements.of(
        (wk.LABEL_CAPACITY_TYPE, OP_IN, ["spot", "on-demand"])))
    pods = _mixed_5k_pods()
    catalog = provider.list(None)
    solver = TPUSolver(catalog, [prov])
    solver.solve(pods)
    _, warm_ms = _timed_solve(solver, pods, repeats=3)
    # storm: distinct spot pools marked one per cycle
    spot_pools = [(t.name, o.zone) for t in catalog.types[:8]
                  for o in t.offerings
                  if o.capacity_type == "spot" and o.available][:6]
    post_ice = []
    for itype, zone in spot_pools:
        ice.mark_unavailable("ICE", itype, zone, "spot")
        cat2 = provider.list(None)
        nxt = TPUSolver(cat2, [prov])
        nxt.adopt_static(solver)
        t0 = time.perf_counter()
        result = nxt.solve(pods)
        post_ice.append((time.perf_counter() - t0) * 1000)
        assert result.unschedulable_count() == 0
        solver = nxt
    ms = statistics.median(post_ice)
    return {"bench": "baseline_config", "config": 8, "name": "ice-storm-5k",
            "ms": round(ms, 3), "nodes": len(result.nodes),
            "detail": {"n_types": len(catalog.types),
                       "marks": len(spot_pools),
                       "warm_ms": round(warm_ms, 3),
                       "post_ice_ms": [round(x, 2) for x in post_ice]}}


CONFIGS = {
    0: config_0_inflate,
    1: config_1_mixed_5k,
    2: config_2_gpu,
    3: config_3_consolidation,
    4: config_4_stress_50k,
    5: config_5_pair_sweep,
    6: config_6_mixed_5k_routed,
    7: config_7_stress_200k,
    8: config_8_ice_storm,
    9: config_9_stress_1m,
}


def main(argv=None) -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--configs", default="0,1,2,3,4,5")
    args = parser.parse_args(argv)
    for idx in (int(c) for c in args.configs.split(",")):
        print(json.dumps(CONFIGS[idx]()), flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
