"""Interruption-controller throughput benchmark.

Parity target: /root/reference/pkg/controllers/interruption/
interruption_benchmark_test.go:61-120 — queue 100 / 1,000 / 5,000 / 15,000
interruption messages against provisioned (fake) nodes and measure drain
throughput of the receive -> parse -> act -> delete pipeline.

Usage: python -m benchmarks.interruption_bench [--scales 100,1000,5000,15000]
Prints one JSON line per scale:
  {"bench": "interruption", "messages": N, "seconds": S, "msgs_per_sec": R}
"""

from __future__ import annotations

import argparse
import json
import time

from karpenter_tpu.apis import wellknown as wk
from karpenter_tpu.apis.nodetemplate import NodeTemplate
from karpenter_tpu.apis.settings import Settings
from karpenter_tpu.fake.cloud import FakeCloud
from karpenter_tpu.models.cluster import StateNode
from karpenter_tpu.models.instancetype import Catalog, make_instance_type
from karpenter_tpu.models.machine import make_provider_id
from karpenter_tpu.operator import Operator


def _catalog() -> Catalog:
    return Catalog(types=[
        make_instance_type("m.large", cpu=4, memory="16Gi",
                           od_price=0.20, spot_price=0.07),
    ])


def build_operator(n_nodes: int) -> Operator:
    settings = Settings(cluster_name="bench",
                        cluster_endpoint="https://bench.example",
                        interruption_queue_name="bench-queue",
                        batch_idle_duration=0.0, batch_max_duration=0.0)
    op = Operator(FakeCloud(catalog=_catalog()), settings, _catalog())
    op.kube.create("nodetemplates", "default", NodeTemplate(
        name="default", subnet_selector={"id": "subnet-zone-1a"},
        security_group_selector={"id": "sg-default"}))
    # seed nodes directly, as the reference benchmark provisions fake nodes
    # (interruption_benchmark_test.go:87-120) — provisioning isn't under test
    for i in range(n_nodes):
        node = StateNode(
            name=f"node-{i}",
            provider_id=make_provider_id("zone-1a", f"i-{i:08d}"),
            labels={wk.LABEL_INSTANCE_TYPE: "m.large",
                    wk.LABEL_ZONE: "zone-1a",
                    wk.LABEL_CAPACITY_TYPE: wk.CAPACITY_TYPE_SPOT,
                    wk.LABEL_PROVISIONER: "default"},
            instance_type="m.large", zone="zone-1a",
            capacity_type=wk.CAPACITY_TYPE_SPOT,
            allocatable=wk.capacity_vector({wk.RESOURCE_CPU: 4000,
                                            wk.RESOURCE_MEMORY: 16 * 2**30,
                                            wk.RESOURCE_PODS: 110}),
            provisioner_name="default",
        )
        op.cluster.add_node(node)
        op.kube.create("nodes", node.name, node)
    return op


def spot_message(instance_id: str) -> str:
    return json.dumps({
        "source": "cloud.spot",
        "detail-type": "Spot Instance Interruption Warning",
        "detail": {"instance-id": instance_id},
    })


# the drain pipeline's phases, as instrumented by the controller's
# karpenter_interruption_phase_seconds histogram
PHASES = ("parse", "index_lookup", "store_write", "ack")


def phase_deltas(hist, before: "dict[str, float]", n: int) -> dict:
    """Per-message microseconds each phase spent since `before` — the
    registry is process-global, so ladder rungs must diff, not read."""
    return {p: round((hist.sum(phase=p) - before[p]) / n * 1e6, 2)
            for p in PHASES}


def run_scale(n: int) -> dict:
    op = build_operator(n)
    try:
        for i in range(n):
            op.queue.send(spot_message(f"i-{i:08d}"))
        hist = op.interruption.phase_seconds
        before = {p: hist.sum(phase=p) for p in PHASES}
        t0 = time.perf_counter()
        drained = 0
        while drained < n:
            got = op.interruption.reconcile_once()
            if got == 0:
                break
            drained += got
        seconds = time.perf_counter() - t0
        assert drained == n, f"drained {drained}/{n}"
        acted = op.interruption.actions.value(action="CordonAndDrain")
        assert acted >= n, f"only {acted}/{n} cordon actions"
        return {"bench": "interruption", "messages": n,
                "seconds": round(seconds, 4),
                "msgs_per_sec": round(n / seconds, 1),
                "phase_us_per_msg": phase_deltas(hist, before, n)}
    finally:
        op.stop()


def droop_attribution(results: "list[dict]") -> "dict | None":
    """Which phase carries the ladder's msgs/s droop: per-message growth
    of each phase from the smallest scale to the largest."""
    ladder = [r for r in results if r.get("phase_us_per_msg")]
    if len(ladder) < 2:
        return None
    lo, hi = ladder[0], ladder[-1]
    growth = {p: round(hi["phase_us_per_msg"][p] - lo["phase_us_per_msg"][p],
                       2) for p in PHASES}
    return {"bench": "interruption_phase_droop",
            "from_messages": lo["messages"], "to_messages": hi["messages"],
            "msgs_per_sec": [lo["msgs_per_sec"], hi["msgs_per_sec"]],
            "phase_growth_us_per_msg": growth,
            "dominant_phase": max(growth, key=lambda p: growth[p])}


def main(argv=None) -> int:
    from benchmarks import ledger

    parser = argparse.ArgumentParser()
    parser.add_argument("--scales", default="100,1000,5000,15000")
    args = parser.parse_args(argv)
    results = []
    for scale in (int(s) for s in args.scales.split(",")):
        results.append(run_scale(scale))
        print(json.dumps(results[-1]), flush=True)
    droop = droop_attribution(results)
    if droop:
        results.append(droop)
        print(json.dumps(droop), flush=True)
    ledger.write_ladder_artifact(results, "interruption",
                                 "benchmarks.interruption_bench")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
