"""The real-replica fleet drill: N subprocesses, 1000 tenants, one kill.

Every fleet-layer claim this repo has proven in-process — rendezvous
routing, health-gated membership, client-side failover, federated
observability — re-proven across REAL process boundaries:

* each solver replica is its own OS process (fleet/replica.py), booted
  on ephemeral ports and discovered through the filesystem rendezvous;
* FleetView scrapes live `/debug/statusz` + `/debug/traces` over HTTP
  (HttpReplica), so every row carries genuine scrape evidence:
  scrape_ms, staleness_s, and the serving process's real pid;
* MembershipManager heartbeats measure real HTTP round-trips;
  FailoverClient speaks the real gRPC solver wire;
* mid-run, one replica is SIGKILLed. The drill then audits blast
  radius, kill absorption, survivor progress, fairness, epoch
  monotonicity and quarantine bounds PURELY from federated scrape
  evidence — the instrument panel is the witness, not the harness.

The traffic schedule (sweep-first + zipf tail) is derived from one
seeded RNG; `build_replay_plan()` reproduces it bit-for-bit without
spawning anything, so the committed artifact's schedule digest is
replayable and testable in tier-1 time.

Run as `make fleet-drill` (full: 4 replicas, 1000 tenants, throughput
floor 2x the single-process fleet baseline) or `make fleet-drill-small`
(2 replicas, tier-1 sized — also exercised by tests/test_fleet_drill.py).
Artifact: benchmarks/results/fleet/fleet_drill.json (or _small)."""

from __future__ import annotations

import argparse
import bisect
import collections
import hashlib
import itertools
import json
import os
import random
import shutil
import sys
import tempfile
import threading
import time
from dataclasses import dataclass, asdict
from typing import Optional

# single-process fleet baseline (ledger: fleet_sustained_solves_per_sec,
# benchmarks/results/fleet/fleet_bench.json): the full drill must sustain
# at least 2x this across the replica fleet to prove the processes add
# capacity instead of just overhead
SINGLE_PROCESS_BASELINE = 79.944
PODS_PER_SOLVE = 4
CLIENT_SPAN = "fleet.drill.federation"


@dataclass(frozen=True)
class DrillConfig:
    name: str
    replicas: int
    tenants: int
    duration_s: float
    workers: int
    max_wave: int
    seed: int = 0
    tick_interval_s: float = 0.01
    membership_tick_s: float = 0.25
    kill_frac: float = 0.45          # kill at this fraction of the window
    recovery_limit: int = 3          # membership cycles to absorb the kill
    # the fairness contract each replica declares (and the drill audits):
    # closed-loop zipf traffic plus the post-kill remap flood queues a hot
    # tenant several rotations deep, so the bound is sized for the drill's
    # offered depth rather than the open-loop default of 4
    starvation_bound: int = 16
    zipf_exponent: float = 1.1
    solve_timeout_s: float = 30.0
    hedge_horizon_s: float = 10.0    # >> queue waits on a loaded host
    gray_factor: float = 50.0        # CPU-contended probes must not gray-eject
    throughput_floor: "Optional[float]" = None
    boot_timeout_s: float = 240.0
    warmup_rungs: "tuple[int, ...]" = (2, 4, 8)


FULL = DrillConfig(name="full", replicas=4, tenants=1000, duration_s=10.0,
                   workers=48, max_wave=32,
                   throughput_floor=round(2 * SINGLE_PROCESS_BASELINE, 3))
SMALL = DrillConfig(name="small", replicas=2, tenants=48, duration_s=4.0,
                    workers=8, max_wave=4, warmup_rungs=(2, 4))


# -- deterministic schedule (shared by the drill and its replay plan) -------


def _tenant_ids(cfg: DrillConfig) -> "list[str]":
    return [f"tenant-{i:04d}" for i in range(cfg.tenants)]


def _replica_names(cfg: DrillConfig) -> "list[str]":
    return [f"r{i}" for i in range(cfg.replicas)]


def _zipf_cum(n: int, exponent: float) -> "list[float]":
    """Cumulative zipf weights over tenant ranks (tenant-0000 heaviest)."""
    cum, total = [], 0.0
    for i in range(n):
        total += 1.0 / ((i + 1) ** exponent)
        cum.append(total)
    return cum


def _zipf_pick(tenants, cum, r: float) -> str:
    return tenants[bisect.bisect_left(cum, r * cum[-1])]


def schedule_digest(sweep: "list[str]", tail: "list[str]") -> str:
    h = hashlib.blake2b(digest_size=16)
    for tid in sweep:
        h.update(tid.encode())
        h.update(b"\x00")
    h.update(b"--tail--")
    for tid in tail:
        h.update(tid.encode())
        h.update(b"\x00")
    return h.hexdigest()


def build_replay_plan(cfg: DrillConfig) -> dict:
    """The drill's deterministic skeleton, computed WITHOUT spawning
    anything: the shuffled sweep order, the zipf tail preview, and a
    digest over both. `_Schedule` consumes the identical RNG stream, so
    the digest in a committed artifact replays bit-for-bit from (seed,
    config) alone — no wall time, no pids, no ports."""
    tenants = _tenant_ids(cfg)
    rng = random.Random(cfg.seed)
    sweep = list(tenants)
    rng.shuffle(sweep)
    cum = _zipf_cum(len(tenants), cfg.zipf_exponent)
    tail = [_zipf_pick(tenants, cum, rng.random())
            for _ in range(2 * cfg.tenants)]
    names = _replica_names(cfg)
    return {
        "schema": 1,
        "seed": cfg.seed,
        "tenants": cfg.tenants,
        "replicas": names,
        "kill_victim": names[1 % len(names)],
        "zipf_exponent": cfg.zipf_exponent,
        "sweep_head": sweep[:8],
        "tail_head": tail[:8],
        "schedule_digest": schedule_digest(sweep, tail),
    }


class _Schedule:
    """Thread-safe tenant-id source: the shuffled sweep FIRST (every
    tenant exactly once, completed even past the deadline — the 1000
    tenants are the point), then the zipf tail until the deadline. The
    RNG stream is consumed in exactly the order `build_replay_plan`
    previews, so the plan's digest covers this sequence."""

    def __init__(self, cfg: DrillConfig):
        tenants = _tenant_ids(cfg)
        rng = random.Random(cfg.seed)
        sweep = list(tenants)
        rng.shuffle(sweep)
        self._sweep = collections.deque(sweep)
        self._rng = rng
        self._tenants = tenants
        self._cum = _zipf_cum(len(tenants), cfg.zipf_exponent)
        self._lock = threading.Lock()
        self.deadline: "Optional[float]" = None

    def next(self) -> "Optional[str]":
        with self._lock:
            if self._sweep:
                return self._sweep.popleft()
            if self.deadline is not None \
                    and time.perf_counter() < self.deadline:
                return _zipf_pick(self._tenants, self._cum,
                                  self._rng.random())
            return None


# -- the drill --------------------------------------------------------------


def _workload():
    """The fleet bench workload (bench.py --fleet): identical content for
    every tenant, so the whole fleet dedupes onto one resident solver per
    replica and batches across tenants."""
    from karpenter_tpu.apis import wellknown as wk
    from karpenter_tpu.apis.provisioner import Provisioner
    from karpenter_tpu.models.instancetype import Catalog, make_instance_type
    from karpenter_tpu.models.requirements import OP_IN, Requirements

    catalog = Catalog(types=[
        make_instance_type("m.large", cpu=4, memory="16Gi",
                           od_price=0.20, spot_price=0.07),
        make_instance_type("m.xlarge", cpu=16, memory="64Gi",
                           od_price=0.80, spot_price=0.28),
    ])
    prov = Provisioner(name="default", requirements=Requirements.of(
        (wk.LABEL_CAPACITY_TYPE, OP_IN, ["spot", "on-demand"])))
    prov.set_defaults()
    return catalog, [prov]


def _percentile(sorted_ms: "list[float]", q: float) -> "Optional[float]":
    if not sorted_ms:
        return None
    idx = min(len(sorted_ms) - 1, int(len(sorted_ms) * q))
    return round(sorted_ms[idx], 3)


def _log_tail(path: str, n: int = 20) -> str:
    try:
        with open(path, errors="replace") as f:
            return "".join(f.readlines()[-n:])
    except OSError as e:
        return f"<no log: {e}>"


def run_drill(cfg: DrillConfig, out_dir: "Optional[str]" = None) -> dict:
    """Run the drill against real subprocesses; returns the artifact
    dict (written to `out_dir/fleet_drill[_small].json` when given)."""
    from karpenter_tpu.chaos import invariants as inv
    from karpenter_tpu.fleet.failover import FailoverClient
    from karpenter_tpu.fleet.membership import MembershipManager
    from karpenter_tpu.fleet.replica import (
        GrpcReplicaTransport, http_probe, spawn_replica,
        wait_for_registrations)
    from karpenter_tpu.fleet.router import FleetRouter
    from karpenter_tpu.introspect.fleetview import FleetView, HttpReplica
    from karpenter_tpu.resilience.policy import RetryBudget
    from karpenter_tpu.solver import solver_pb2 as pb
    from karpenter_tpu.solver import wire
    from karpenter_tpu.models.pod import make_pod
    from karpenter_tpu.tracing import TRACER
    from karpenter_tpu.utils.clock import WallClock

    plan = build_replay_plan(cfg)
    names = _replica_names(cfg)
    victim = plan["kill_victim"]
    survivors = [n for n in names if n != victim]
    tenants = _tenant_ids(cfg)
    rendezvous = tempfile.mkdtemp(prefix="fleet-drill-")
    procs: "dict[str, object]" = {}
    transports: "dict[str, GrpcReplicaTransport]" = {}
    threads: "list[threading.Thread]" = []
    stop_tick = threading.Event()
    failed = True
    try:
        # -- boot the fleet: real subprocesses on ephemeral ports -----------
        for name in names:
            procs[name] = spawn_replica(
                name, rendezvous, max_wave=cfg.max_wave,
                tick_interval_s=cfg.tick_interval_s,
                starvation_bound=cfg.starvation_bound)
        regs = wait_for_registrations(rendezvous, names,
                                      timeout_s=cfg.boot_timeout_s)

        # -- sync content + warm the wave rungs on every replica ------------
        catalog, provs = _workload()
        prov_hash = wire.provisioners_hash(provs)
        cat_hash = None
        for name in names:
            transports[name] = GrpcReplicaTransport(name, regs[name]["grpc"])
            resp = transports[name].sync(catalog, provs)
            cat_hash = resp.catalog_hash
        seq = itertools.count()

        def build_request(tid: str, trace_ctx=None):
            i = next(seq)
            pods = [make_pod(f"{tid}-q{i}-p{j}", cpu="1", memory="2Gi")
                    for j in range(PODS_PER_SOLVE)]
            req = pb.SolveRequest(
                catalog_hash=cat_hash, provisioner_hash=prov_hash,
                pods=[wire.pod_to_wire(p) for p in pods])
            if trace_ctx is not None:
                req.trace_context.CopyFrom(
                    wire.trace_context_to_wire(trace_ctx))
            return req

        def warm(name: str):
            # solo first (compile the K=1..pad rung), then concurrent
            # bursts so every batch rung the window will see is jitted
            # before the clock starts
            transports[name]("warm-solo", build_request("warm-solo"),
                             cfg.solve_timeout_s * 4)
            for k in cfg.warmup_rungs:
                burst = [threading.Thread(
                    target=transports[name],
                    args=(f"warm-{k}-{j}", build_request(f"warm-{k}-{j}"),
                          cfg.solve_timeout_s * 4))
                    for j in range(k)]
                for t in burst:
                    t.start()
                for t in burst:
                    t.join()

        for name in names:
            warm(name)

        # -- wire the observability + membership + failover planes ----------
        # WallClock: statusz timestamps cross process boundaries, so the
        # view's staleness arithmetic must share the replicas' clock domain
        router = FleetRouter()
        view = FleetView(router=router, name="fleet-drill",
                         clock=WallClock())
        membership = MembershipManager(router, view=view,
                                       gray_factor=cfg.gray_factor)
        # the audit view scrapes EVERY replica (including the corpse,
        # post-kill) independently of membership, so partial-scrape
        # degradation itself is auditable evidence
        audit_view = FleetView(name="fleet-drill-audit", clock=WallClock())
        audit_eps: "dict[str, HttpReplica]" = {}
        for name in names:
            membership.register(
                name, http_probe(regs[name]["health"]),
                endpoint=HttpReplica(name, regs[name]["debug"]))
            audit_eps[name] = HttpReplica(name, regs[name]["debug"])
            audit_view.add_replica(audit_eps[name])
        for _ in range(20):
            membership.tick()
            if set(membership.members()) == set(names):
                break
        else:
            raise RuntimeError(
                f"fleet never converged: members={membership.members()}")

        cycles: "list[dict]" = []
        cycles_lock = threading.Lock()

        def ticker():
            while not stop_tick.is_set():
                events = membership.tick()
                rec = {"ts": time.time(), "epoch": membership.epoch(),
                       "members": sorted(membership.members()),
                       "events": events,
                       "ejected": [e["replica"] for e in events
                                   if e.get("event") == "ReplicaEjected"]}
                with cycles_lock:
                    cycles.append(rec)
                stop_tick.wait(cfg.membership_tick_s)

        remaps: "collections.Counter" = collections.Counter()
        failover = FailoverClient(
            router, transports, seed=cfg.seed,
            hedge_horizon_s=cfg.hedge_horizon_s,
            budget=RetryBudget(capacity=128.0, refill_per_success=0.5),
            on_remap=lambda tid, new: remaps.update([new]))

        # -- federation probe: one trace across client + 2 real replicas ----
        fed_targets = names[:2]
        with TRACER.start_span(CLIENT_SPAN, targets=len(fed_targets)) as sp:
            for name in fed_targets:
                transports[name]("tenant-0000",
                                 build_request("tenant-0000", sp.context()),
                                 cfg.solve_timeout_s)
        fed = view.federated_trace(sp.trace_id)
        fed_lanes = {e["args"]["name"]: e["pid"]
                     for e in (fed or {}).get("traceEvents", ())
                     if e["ph"] == "M"}
        fed_spans = [e for e in (fed or {}).get("traceEvents", ())
                     if e["ph"] == "X"]
        federation = {
            "trace_id": sp.trace_id,
            "lanes": fed_lanes,
            "n_spans": len(fed_spans),
            "client_pid": os.getpid(),
            "replica_pids": {n: regs[n]["pid"] for n in fed_targets},
        }
        federation_ok = (
            fed is not None
            and fed_lanes.get("client:fleet-drill") == os.getpid()
            and all(fed_lanes.get(n) == regs[n]["pid"] for n in fed_targets)
            and len(set(fed_lanes.values())) >= 3)

        # -- baseline brackets ----------------------------------------------
        pinning_before = router.assignment(tenants)
        rows0 = audit_view.fleetz()["replicas"]
        served_start = {n: r["served"] for n, r in rows0.items()
                        if isinstance(r.get("served"), int)}

        # -- traffic + kill -------------------------------------------------
        sched = _Schedule(cfg)
        outcomes: "list[dict]" = []
        kill_state: "dict[str, object]" = {}

        def worker():
            while True:
                tid = sched.next()
                if tid is None:
                    return
                t0 = time.perf_counter()
                try:
                    failover.solve(tid, build_request(tid),
                                   timeout_s=cfg.solve_timeout_s)
                    outcomes.append({
                        "tenant": tid, "outcome": "served",
                        "ms": (time.perf_counter() - t0) * 1e3})
                except Exception as e:  # noqa: BLE001 — audited as an outcome
                    outcomes.append({
                        "tenant": tid, "outcome": "error",
                        "error": f"{type(e).__name__}: {e}"})

        def killer():
            stop_tick.wait(cfg.duration_s * cfg.kill_frac)
            if stop_tick.is_set():
                return
            kill_state["kill_wall"] = time.time()
            procs[victim].kill()  # SIGKILL: no goodbye, no deregistration
            # wait for membership to eject the corpse, then bracket the
            # survivors' served counters for the progress invariant
            deadline = time.monotonic() + max(10.0, cfg.duration_s)
            while time.monotonic() < deadline and not stop_tick.is_set():
                with cycles_lock:
                    post = [c for c in cycles
                            if c["ts"] >= kill_state["kill_wall"]]
                if any(victim in c["ejected"] for c in post):
                    break
                time.sleep(0.05)
            rows = audit_view.fleetz()["replicas"]
            kill_state["served_mid"] = {
                n: rows[n]["served"] for n in survivors
                if isinstance(rows.get(n, {}).get("served"), int)}

        tick_thread = threading.Thread(target=ticker, daemon=True)
        kill_thread = threading.Thread(target=killer, daemon=True)
        workers = [threading.Thread(target=worker, daemon=True)
                   for _ in range(cfg.workers)]
        t_start = time.perf_counter()
        sched.deadline = t_start + cfg.duration_s
        tick_thread.start()
        kill_thread.start()
        for w in workers:
            w.start()
        for w in workers:
            w.join()
        wall = time.perf_counter() - t_start
        kill_thread.join(timeout=15.0)
        stop_tick.set()
        tick_thread.join(timeout=5.0)

        # -- the audit: every invariant from federated scrape evidence ------
        pinning_after = router.assignment(tenants)
        fleetz_after = audit_view.fleetz()
        rows_after = fleetz_after["replicas"]
        served_end = {n: r["served"] for n, r in rows_after.items()
                      if isinstance(r.get("served"), int)}
        with cycles_lock:
            all_cycles = list(cycles)
        kill_wall = kill_state.get("kill_wall")
        post_kill = [c for c in all_cycles
                     if kill_wall is not None and c["ts"] >= kill_wall]
        recovery_cycles = next(
            (i + 1 for i, c in enumerate(post_kill)
             if victim in c["ejected"]), None)

        fairness_rows: "dict[str, dict]" = {}
        violations = []
        violations += inv.check_scrape_evidence(
            rows_after,
            expect_pids={n: regs[n]["pid"] for n in survivors})
        if not rows_after.get(victim, {}).get("healthy", True):
            pass  # the corpse degraded to a named error row — as designed
        else:
            violations += [inv.Violation(
                "scrape-evidence-complete",
                f"killed replica {victim} still scrapes healthy")]
        violations += inv.check_remap_blast_radius(
            pinning_before, pinning_after, {victim})
        violations += inv.check_kill_absorbed(
            post_kill, victim, limit=cfg.recovery_limit)
        violations += inv.check_survivors_progress(
            kill_state.get("served_mid") or {}, served_end, {victim})
        violations += inv.check_epoch_monotone(
            [c["epoch"] for c in all_cycles])
        violations += inv.check_quarantine_cascade(
            failover.evidence()["quarantine"]["victims"])
        violations += inv.check_completes_or_sheds(outcomes)
        for name in survivors:
            snap = audit_eps[name].statusz()  # full scrape
            fronts = (snap.get("fleet") or {}).get("frontends") or []
            ours = next((f for f in fronts if f.get("name") == name), None)
            if ours is None:
                violations += [inv.Violation(
                    "fairness-never-starves",
                    f"replica {name}: scraped statusz carries no frontend "
                    f"row to audit")]
                continue
            fairness_rows[name] = {"starvation_bound":
                                   ours.get("starvation_bound"),
                                   "queued": ours.get("queued"),
                                   "tenants": ours.get("tenants") or {}}
            violations += inv.check_fairness_never_starves(
                fairness_rows[name])

        # -- throughput -----------------------------------------------------
        served = [o for o in outcomes if o["outcome"] == "served"]
        errors = [o for o in outcomes if o["outcome"] != "served"]
        lats = sorted(o["ms"] for o in served)
        aggregate = round(len(served) / wall, 3) if wall > 0 else 0.0
        per_replica = {}
        for name in names:
            start = served_start.get(name)
            end = served_end.get(name)
            mid = (kill_state.get("served_mid") or {}).get(name)
            per_replica[name] = {
                "served_start": start, "served_mid": mid,
                "served_end": end,
                "solves_per_sec": (round((end - start) / wall, 3)
                                   if name != victim
                                   and isinstance(start, int)
                                   and isinstance(end, int) else None),
            }

        floor = cfg.throughput_floor
        criteria = {
            "replicas_are_real_subprocesses": (
                len({regs[n]["pid"] for n in names}) == len(names)
                and os.getpid() not in {regs[n]["pid"] for n in names}),
            "every_tenant_served": (
                {o["tenant"] for o in served} >= set(tenants)),
            "aggregate_throughput_over_floor": (
                floor is None or aggregate >= floor),
            "kill_absorbed_within_limit": (
                recovery_cycles is not None
                and recovery_cycles <= cfg.recovery_limit),
            "federated_trace_spans_real_processes": federation_ok,
            "invariants_hold": not violations,
        }
        artifact = {
            "tool": "karpenter-tpu-fleet-drill",
            "schema": 1,
            "captured_at": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                         time.gmtime()),
            "config": asdict(cfg),
            "replay": plan,
            "registrations": {n: {"pid": regs[n]["pid"],
                                  "grpc": regs[n]["grpc"],
                                  "debug": regs[n]["debug"]}
                              for n in names},
            "baseline": {"single_process_solves_per_sec":
                         SINGLE_PROCESS_BASELINE,
                         "floor_solves_per_sec": floor},
            "traffic": {
                "requests": len(outcomes),
                "served": len(served),
                "errors": len(errors),
                "error_head": [o["error"] for o in errors[:5]],
                "distinct_tenants": len({o["tenant"] for o in outcomes}),
                "wall_s": round(wall, 3),
                "aggregate_solves_per_sec": aggregate,
                "p50_ms": _percentile(lats, 0.50),
                "p99_ms": _percentile(lats, 0.99),
            },
            "kill": {
                "victim": victim,
                "kill_wall": kill_wall,
                "recovery_cycles": recovery_cycles,
                "recovery_limit": cfg.recovery_limit,
                "post_kill_cycles": [
                    {k: c[k] for k in ("epoch", "members", "ejected")}
                    for c in post_kill[:8]],
                "remaps": dict(remaps),
                "warm_state_losses":
                    failover.evidence()["warm_state_losses"],
            },
            "per_replica": per_replica,
            "federation": federation,
            "scrape": {
                "membership_epoch": membership.epoch(),
                # rows minus the per-tenant tables (full evidence is huge;
                # the invariants already consumed it above)
                "rows": {n: {k: v for k, v in r.items() if k != "tenants"}
                         for n, r in rows_after.items()},
            },
            "violations": [v.as_dict() for v in violations],
            "criteria": criteria,
            "passed": all(criteria.values()),
        }
        if out_dir:
            os.makedirs(out_dir, exist_ok=True)
            suffix = "" if cfg.name == "full" else f"_{cfg.name}"
            path = os.path.join(out_dir, f"fleet_drill{suffix}.json")
            with open(path, "w") as f:
                json.dump(artifact, f, indent=2, sort_keys=True,
                          default=str)
                f.write("\n")
            artifact["artifact_path"] = path
        failed = not artifact["passed"]
        return artifact
    finally:
        stop_tick.set()
        for name, proc in procs.items():
            try:
                proc.terminate()
            except OSError:
                pass
        for name, proc in procs.items():
            try:
                proc.wait(timeout=10.0)
            except Exception:  # noqa: BLE001 — escalate, then move on
                proc.kill()
        for tr in transports.values():
            tr.close()
        if failed:
            for name in procs:
                tail = _log_tail(os.path.join(rendezvous, f"{name}.log"))
                print(f"--- {name} log tail ({rendezvous}) ---\n{tail}",
                      file=sys.stderr)
        else:
            shutil.rmtree(rendezvous, ignore_errors=True)


def _ledger_records(artifact: dict) -> None:
    """Record the drill's trend metrics through the SAME extractor the
    ledger's backfill uses, against the repo-relative artifact path — a
    later `backfill()` dedupes against what the live run wrote."""
    from benchmarks import ledger

    path = artifact.get("artifact_path")
    if not path:
        return
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    rel = os.path.relpath(path, root)
    for (metric, value, unit, backend, degraded,
         workload, ts) in ledger._fleet_drill_entries(artifact):
        ledger.append(ledger.make_entry(
            metric, value, unit, source="benchmarks.fleet_drill",
            backend=backend, degraded=degraded, workload=workload,
            artifact=rel, recorded_at=ts))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--small", action="store_true",
                    help="tier-1-sized config (2 replicas, no floor)")
    ap.add_argument("--out-dir", default=None)
    args = ap.parse_args(argv)
    cfg = SMALL if args.small else FULL
    out_dir = args.out_dir or os.environ.get(
        "KARPENTER_TPU_DRILL_DIR",
        os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))),
            "benchmarks", "results", "fleet"))
    artifact = run_drill(cfg, out_dir)
    _ledger_records(artifact)
    print(json.dumps({"passed": artifact["passed"],
                      "criteria": artifact["criteria"],
                      "aggregate_solves_per_sec":
                          artifact["traffic"]["aggregate_solves_per_sec"],
                      "recovery_cycles":
                          artifact["kill"]["recovery_cycles"],
                      "violations": artifact["violations"][:10],
                      "artifact": artifact.get("artifact_path")},
                     indent=2))
    return 0 if artifact["passed"] else 1


if __name__ == "__main__":
    sys.exit(main())
