"""End-to-end WIRE benchmark: the deployed topology under load
(VERDICT r4 ask #7).

Everything crosses real sockets: the coordination plane is HttpKubeStore
against the mini apiserver over HTTP (watches included), and scheduling
solves go through the gRPC solver sidecar (solver/service.py) — the
topology `python -m karpenter_tpu controller --solver HOST:PORT
--kubeconfig ...` deploys. Recorded alongside the in-process ladder
(benchmarks/record.py) so the wire tax is always attributable.

Scenarios:
  * interruption ladder 100/1k/5k/15k — the reference benchmark's scales
    (/root/reference/pkg/controllers/interruption/
    interruption_benchmark_test.go:61-76), with node state living in the
    HTTP store;
  * a 10k-pod provisioning cycle: pods ingested through the apiserver,
    one watch-driven reconcile that solves via gRPC, launches machines,
    and binds every pod back through the store. Reported split: ingest /
    solve / full cycle, plus the routed solver kind.

Usage: python -m benchmarks.wire_bench [--scales ...] [--pods 10000]
One JSON line per scenario.
"""

from __future__ import annotations

import argparse
import json
import time

from karpenter_tpu.apis.nodetemplate import NodeTemplate
from karpenter_tpu.apis.provisioner import Provisioner
from karpenter_tpu.apis.settings import Settings
from karpenter_tpu.coordination.httpkube import HttpKubeStore
from karpenter_tpu.fake.apiserver import serve as serve_apiserver
from karpenter_tpu.fake.cloud import FakeCloud
from karpenter_tpu.operator import Operator
from karpenter_tpu.providers.instancetypes import generate_fleet_catalog


def boot_wire_operator(catalog, grpc_solver: bool = True, **settings_kw):
    """(operator, teardown_fn): HttpKubeStore coordination plane + gRPC
    solver sidecar, both on real localhost sockets."""
    from karpenter_tpu.solver.service import serve as serve_solver

    srv, port, _state = serve_apiserver()
    kube = HttpKubeStore(f"http://127.0.0.1:{port}")
    kube.start()  # LIST seed + live watch streams: the benchmark must pay
    # the full informer/watch-echo traffic a deployed controller pays
    solver_server = None
    solver_factory = None
    solver_target = ""
    if grpc_solver:
        solver_server, sport, _svc = serve_solver()
        solver_target = f"127.0.0.1:{sport}"
        from karpenter_tpu.solver.client import RemoteSolver

        solver_factory = (lambda cat, provs:
                          RemoteSolver(cat, provs, target=solver_target))
    settings = Settings(cluster_name="wire",
                        cluster_endpoint="https://wire.example",
                        batch_idle_duration=0.0, batch_max_duration=0.0,
                        **settings_kw)
    op = Operator(FakeCloud(catalog=catalog), settings, catalog, kube=kube,
                  solver_factory=solver_factory, solver_target=solver_target)
    op.kube.create("nodetemplates", "default", NodeTemplate(
        name="default",
        subnet_selector={"id": "subnet-zone-1a,subnet-zone-1b,subnet-zone-1c"},
        security_group_selector={"id": "sg-default"}))
    op.cloudprovider.register_nodetemplate(
        op.kube.get("nodetemplates", "default"))

    def teardown():
        op.stop()
        try:
            kube.stop()
        except Exception:
            pass
        if solver_server is not None:
            solver_server.stop(0)
        srv.shutdown()
        srv.server_close()

    return op, teardown


def wire_provisioning(n_pods: int = 10_000) -> dict:
    import os

    from benchmarks.workloads import mixed_workload

    # the wire benchmark must PAY the gRPC solve leg: the measured routing
    # policy would otherwise prefer the in-process native scan and the
    # recorded "deployed topology" would exclude the sidecar entirely
    os.environ["KARPENTER_TPU_ROUTE_CROSSOVER"] = "0"
    catalog = generate_fleet_catalog()
    op, teardown = boot_wire_operator(catalog)
    try:
        prov = Provisioner(name="default", provider_ref="default")
        prov.set_defaults()
        op.kube.create("provisioners", "default", prov)

        from karpenter_tpu.tracing import TRACER

        # full-cycle phase attribution: diff the global phase histogram
        # around the run (watch-ingest decode/apply spans flush in batches
        # of HttpKubeStore.INGEST_SPAN_BATCH, so the tail batch of a
        # 10k-pod ingest may land after the read — attribution, not audit)
        phases = ("ingest.decode", "ingest.apply", "provisioning.solve",
                  "provisioning.create", "provisioning.bind.existing",
                  "provisioning.bind.pods")
        before = {p: TRACER.phase_sum(p) for p in phases}

        pods = mixed_workload(n_pods)
        t0 = time.perf_counter()
        for p in pods:
            op.kube.create("pods", p.name, p)
        ingest_s = time.perf_counter() - t0

        t1 = time.perf_counter()
        op.provisioning.reconcile_once()
        cycle_s = time.perf_counter() - t1

        pending = len(op.kube.pending_pods())
        machines = len(op.kube.list("machines"))
        assert pending == 0, f"{pending} pods still pending after the cycle"
        assert machines > 0
        assert op.provisioning.last_solver_kind == "tpu", (
            f"solve did not cross the gRPC boundary "
            f"(kind={op.provisioning.last_solver_kind})")
        phase_s = {p: round(TRACER.phase_sum(p) - before[p], 4)
                   for p in phases}
        return {"bench": "wire_provisioning", "pods": n_pods,
                "ingest_seconds": round(ingest_s, 3),
                "cycle_seconds": round(cycle_s, 3),
                "machines": machines,
                "solver": op.provisioning.last_solver_kind,
                "phase_seconds": phase_s,
                "detail": {"n_types": len(catalog.types),
                           "topology": "HttpKubeStore + gRPC solver"}}
    finally:
        teardown()


def wire_interruption(n: int) -> dict:
    """The interruption drain pipeline with node state in the HTTP store."""
    from karpenter_tpu.apis import wellknown as wk
    from karpenter_tpu.models.cluster import StateNode
    from karpenter_tpu.models.machine import make_provider_id

    catalog = generate_fleet_catalog(max_types=10)
    op, teardown = boot_wire_operator(
        catalog, grpc_solver=False, interruption_queue_name="wire-queue")
    try:
        big = catalog.types[0]
        for i in range(n):
            node = StateNode(
                name=f"node-{i}",
                provider_id=make_provider_id("zone-1a", f"i-{i:08d}"),
                labels={wk.LABEL_INSTANCE_TYPE: big.name,
                        wk.LABEL_ZONE: "zone-1a",
                        wk.LABEL_CAPACITY_TYPE: wk.CAPACITY_TYPE_SPOT,
                        wk.LABEL_PROVISIONER: "default"},
                instance_type=big.name, zone="zone-1a",
                capacity_type=wk.CAPACITY_TYPE_SPOT,
                allocatable=big.allocatable_vector(),
                provisioner_name="default")
            op.cluster.add_node(node)
            op.kube.create("nodes", node.name, node)
        for i in range(n):
            op.queue.send(json.dumps({
                "source": "cloud.spot",
                "detail-type": "Spot Instance Interruption Warning",
                "detail": {"instance-id": f"i-{i:08d}"}}))
        from benchmarks.interruption_bench import PHASES, phase_deltas

        hist = op.interruption.phase_seconds
        before = {p: hist.sum(phase=p) for p in PHASES}
        t0 = time.perf_counter()
        drained = 0
        while drained < n:
            got = op.interruption.reconcile_once()
            if got == 0:
                break
            drained += got
        seconds = time.perf_counter() - t0
        assert drained == n, f"drained {drained}/{n}"
        return {"bench": "wire_interruption", "messages": n,
                "seconds": round(seconds, 4),
                "msgs_per_sec": round(n / seconds, 1),
                "phase_us_per_msg": phase_deltas(hist, before, n),
                "detail": {"topology": "HttpKubeStore"}}
    finally:
        teardown()


def main(argv=None) -> int:
    from benchmarks import ledger
    from benchmarks.interruption_bench import droop_attribution

    ap = argparse.ArgumentParser()
    ap.add_argument("--scales", default="100,1000,5000,15000")
    ap.add_argument("--pods", type=int, default=10_000)
    args = ap.parse_args(argv)
    results = []
    for scale in (int(s) for s in args.scales.split(",") if s):
        results.append(wire_interruption(scale))
        print(json.dumps(results[-1]), flush=True)
    droop = droop_attribution(results)
    if droop:
        droop["bench"] = "wire_interruption_phase_droop"
        results.append(droop)
        print(json.dumps(droop), flush=True)
    results.append(wire_provisioning(args.pods))
    print(json.dumps(results[-1]), flush=True)
    ledger.write_ladder_artifact(results, "wire_bench",
                                 "benchmarks.wire_bench")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
